"""The epoch-loop trainer — one engine for every dataset/model.

Wires together mesh, dataset, prefetcher, pjit step, LR schedule,
checkpointing, NaN guard, and evaluation; dataset-agnostic where the
reference duplicates a session loop per dataset (`flyingChairsTrain.py`,
`sintelTrain.py`, `ucf101train.py` — SURVEY.md §2.2).

NaN handling upgrades the reference's crash-on-NaN assert
(`flyingChairsTrain.py:203`) to restore-from-last-checkpoint
(SURVEY.md §5.3).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..core.config import ExperimentConfig
from ..data import InputPipeline, Prefetcher, build_dataset, derive_batch_rng
from ..models.registry import build_model
from ..obs import incident as obs_incident
from ..obs import trace as obs_trace
from ..obs.heartbeat import Heartbeat
from ..obs.ledger import ExecutableLedger
from ..obs.telemetry import (
    NOMINAL_BF16_TFLOPS,
    device_memory_summary,
    lowered_flops,
    process_rss_bytes,
)
from ..parallel.mesh import batch_sharding, build_mesh
from ..resilience.faults import build_injector
from ..resilience.healing import HealingSampler
from ..resilience.verify import config_digest
from .checkpoint import CheckpointManager
from .evaluate import evaluate_aee, evaluate_ucf101
from .metrics_log import (
    AsyncFetcher,
    MetricsLogger,
    ProfilerSession,
    StepTimer,
    SyncFetcher,
)
from .elastic import maybe_host_fault, pace_to_world
from .schedule import step_decay_schedule
from .state import create_train_state, make_optimizer
from .step import make_eval_fn, make_train_step
from .warmup import cache_delta, enable_for_config


# Early-preemption latch (ADVICE r03): model build + the first TPU
# compile can take minutes, and a SIGTERM landing before fit() installs
# its graceful handler would hit the default action and kill the process
# with no checkpoint. The CLI installs this minimal latch at entry; fit()
# takes over and converts a latched signal into an immediate
# save-and-stop (the loop exits before its first step, and the normal
# finalize path writes the checkpoint). Same escalation contract as the
# fit() handler: a SECOND signal restores the default action and
# re-raises, so a run wedged in compile stays killable.
_EARLY_SIGTERM: dict = {"sig": None, "handler": None}

# A prefetch.get() wait above this is counted as a `starved` step (the
# device had no staged batch to eat); below it is queue-handoff noise.
STARVED_WAIT_S = 1e-3

#: Per-pyramid-scale loss decomposition: record field -> the step
#: metrics key it reads (train/step.py stacks these per scale, finest
#: first). "Models Matter, So Does Training" (PAPERS.md): the per-scale
#: photometric-vs-smoothness trajectories are what predicts EPE — and
#: the signal ROADMAP item 3's EPE-driven curriculum switch points will
#: consume. Written into every periodic train record by _on_metrics.
SCALE_RECORD_FIELDS: tuple[tuple[str, str], ...] = (
    ("loss_total_by_scale", "scale_total"),
    ("loss_photo_by_scale", "scale_Charbonnier_reconstruct"),
    ("loss_smooth_by_scale", "scale_smooth"),
)


def per_scale_last(v) -> list[float]:
    """Last inner step's per-scale vector (finest first) as a JSON-ready
    list — the loss_*_by_scale record fields. Arrays carry a leading K
    axis when steps_per_call > 1; 6 significant figures keep the record
    compact without rounding a 1e-5-scale term to zero."""
    a = np.asarray(v)
    if a.ndim == 2:  # [K, S] under steps_per_call stacking
        a = a[-1]
    return [float(f"{float(x):.6g}") for x in np.atleast_1d(a)]


def _poison_batch(batch: dict) -> dict:
    """Dispatch-site fault action: one NaN in the first float input
    tensor. The batch may already be device-resident and sharded (the
    prefetcher staged it); the functional `.at[].set` keeps it there."""
    out = dict(batch)
    for key in ("volume", "source", *batch):
        if key in out and jnp.issubdtype(
                jnp.asarray(out[key]).dtype, jnp.floating):
            arr = jnp.asarray(out[key])
            out[key] = arr.at[(0,) * arr.ndim].set(jnp.nan)
            return out
    return out


def install_preemption_latch() -> None:
    def _latch(signum, frame):
        if _EARLY_SIGTERM["sig"] is not None:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)
            return
        _EARLY_SIGTERM["sig"] = signum

    # remembered so fit()'s handler restore can recognize the latch and
    # NOT re-install it after training: post-fit (checkpoint already
    # committed) a SIGTERM must kill the process, not be swallowed into
    # a flag nobody reads anymore
    _EARLY_SIGTERM["handler"] = _latch
    try:
        signal.signal(signal.SIGTERM, _latch)
    except ValueError:  # non-main thread: host runtime owns signals
        pass


def data_stream_seed(mesh, seed: int, start_step: int) -> np.ndarray:
    """Base seed of the host data-sampling stream for a fit() beginning
    at start_step.

    (process_seed, start_step): process_seed decorrelates data shards
    while keeping replica peers identical (parallel/mesh.py); start_step
    gives each RESUME a fresh stream — a fixed seed would replay the
    draws already trained on, since the numpy data rng is not part of
    the checkpoint. The loop derives one rng PER BATCH INDEX from this
    base (`data/pipeline.py::derive_batch_rng`), so the sample/augment
    stream is bit-identical for any `data.num_workers`.
    """
    from ..parallel.mesh import process_seed

    return np.array([process_seed(mesh, seed), start_step], dtype=np.uint32)


def data_stream_rng(mesh, seed: int, start_step: int) -> np.random.RandomState:
    """Sequential-stream view of `data_stream_seed` (tools that sample
    without the batch-indexed pipeline, e.g. tools/synthetic_fit.py).
    Array seeding is exact and order-sensitive."""
    return np.random.RandomState(data_stream_seed(mesh, seed, start_step))


def _example_input(cfg: ExperimentConfig) -> jnp.ndarray:
    h, w = cfg.data.crop_size or cfg.data.image_size
    t = cfg.data.time_step
    channels = 3 if cfg.model == "ucf101_spatial" else 3 * t
    return jnp.zeros((cfg.data.batch_size, h, w, channels), jnp.float32)


class Trainer:
    def __init__(self, cfg: ExperimentConfig, dataset=None, mesh=None,
                 profile: bool = False,
                 profile_steps: tuple[int, int] | None = None,
                 ckpt_dir: str | None = None,
                 train_step=None, eval_fn=None, tx=None,
                 manifest_extra: dict | None = None,
                 extra_stats=None, on_eval=None):
        # The recipe engine (train/recipe.py) drives one Trainer per
        # stage through these hooks: ckpt_dir isolates each stage's
        # checkpoint lineage, train_step/eval_fn inject the stage's
        # PRE-COMPILED executables (so a stage switch is a
        # zero-recompile event provable from the ledger) — with tx the
        # EXACT optimizer object those executables were lowered against
        # (a Compiled's input pytree pins the TrainState's static tx
        # metadata by identity, so a freshly built twin would not
        # match), manifest_extra rides the active stage index on every
        # checkpoint manifest, extra_stats() merges recipe counters
        # into heartbeat/train records/fit summary, and on_eval(step,
        # metrics) -> bool ends fit() early when the stage's advance
        # trigger fires (eval_trend plateau).
        self.cfg = cfg
        self._extra_stats = extra_stats
        self._on_eval = on_eval
        # Persistent compile cache BEFORE any compile (init, train, eval):
        # a process whose config was warmed (`deepof_tpu warmup`) or simply
        # run before loads executables instead of recompiling — the
        # execution layer's "start hot" half (train/warmup.py).
        enable_for_config(cfg)
        # An elastic trainer child (train/elastic.py) in virtual-host
        # mode owns exactly elastic.virtual_devices of the forced CPU
        # platform — each member of the pool gets its own private mesh.
        el = cfg.elastic
        self._elastic_child = el.host_index >= 0 and el.num_hosts > 0
        if mesh is not None:
            self.mesh = mesh
        elif self._elastic_child and el.virtual_devices > 0:
            from ..parallel.mesh import local_mesh

            self.mesh = local_mesh(el.virtual_devices)
        else:
            self.mesh = build_mesh(cfg.mesh)
        self.dataset = dataset if dataset is not None else build_dataset(cfg.data)
        t = cfg.data.time_step
        flow_channels = 2 * (t - 1)
        dtype = (jnp.bfloat16 if cfg.train.compute_dtype == "bfloat16"
                 else jnp.float32)
        self.model = build_model(cfg.model, flow_channels=flow_channels,
                                 dtype=dtype, width_mult=cfg.width_mult,
                                 corr_max_disp=cfg.corr_max_disp,
                                 corr_stride=cfg.corr_stride)

        self.logger = MetricsLogger(cfg.train.log_dir)
        self.profiler = ProfilerSession(cfg.train.log_dir, enabled=profile,
                                        steps=profile_steps)
        # XLA cost-analysis FLOPs per optimizer step, computed once at
        # the first dispatch (obs/telemetry.py) — None until then, and on
        # backends without a cost model.
        self._flops_per_step: float | None = None
        self.steps_per_epoch = max(self.dataset.num_train // cfg.data.batch_size, 1)
        schedule = step_decay_schedule(cfg.optim, self.steps_per_epoch)
        self.schedule = schedule
        tx = tx if tx is not None else make_optimizer(cfg.optim, schedule)
        self.state = create_train_state(
            self.model, _example_input(cfg), tx, seed=cfg.train.seed,
            log=lambda m: self.logger.log("info", 0, message=m))

        # Deterministic fault injector (resilience/faults.py): None when
        # disabled — every site below guards on one `is not None`, the
        # zero-overhead contract. One injector is shared by the data
        # path, the fetchers, and the checkpoint manager so per-site
        # attempt counting is globally consistent.
        self._inj = build_injector(cfg.resilience.faults)
        if self._inj is not None:
            self.logger.log("warn", 0,
                            message="fault injection ENABLED "
                                    f"({cfg.resilience.faults})")
        # Elastic children share one verified-checkpoint directory: the
        # generation's PRIMARY host writes it, every host restores from
        # it on (re)spawn — so a re-formed world resumes from one
        # consistent state and a lost primary's torn last write falls
        # back to the previous valid step (train/elastic.py).
        ckpt_dir = (ckpt_dir if ckpt_dir
                    else el.ckpt_dir if self._elastic_child and el.ckpt_dir
                    else cfg.train.log_dir + "/ckpt")
        ckpt_writer = (not self._elastic_child
                       or el.host_index == el.primary_host)
        # the advisory config digest must be identical across hosts and
        # generations of ONE elastic run (only per-host identity and the
        # host-local log_dir differ), or every re-form would warn about
        # a cross-config restore
        digest_src = cfg if not self._elastic_child else cfg.replace(
            train=dataclasses.replace(cfg.train, log_dir=""),
            elastic=type(el)())
        self.ckpt = CheckpointManager(
            ckpt_dir, keep=cfg.train.keep_ckpts,
            verify=cfg.resilience.verify_checkpoints,
            log=lambda s, m: self.logger.log("warn", s, message=m),
            info_log=lambda s, m: self.logger.log("info", s, message=m),
            injector=self._inj,
            config_digest=config_digest(dataclasses.asdict(digest_src)),
            writer=ckpt_writer, manifest_extra=manifest_extra)
        # VGG16 pretrained conv-trunk init (`flyingChairsTrain.py:60-76`);
        # fresh starts only — a checkpoint to resume from takes precedence.
        _vgg_trunks = {"vgg16": ("encoder",), "st_single": ("encoder",),
                       "ucf101_spatial": ("encoder",),
                       "st_baseline": ("spatial",)}
        if (cfg.train.vgg16_npz and cfg.model in _vgg_trunks
                and self.ckpt.latest_step() is None):
            from ..models.common import load_vgg16_npz

            self.state = self.state.replace(params=load_vgg16_npz(
                self.state.params, cfg.train.vgg16_npz,
                trunk_path=_vgg_trunks[cfg.model]))
            self.logger.log("info", 0,
                            message=f"VGG16 trunk init from {cfg.train.vgg16_npz}")

        # Cross-config transfer init (Chairs -> Sintel fine-tune recipe):
        # graft matching-shape params from another run; fresh starts only.
        if cfg.train.init_from and self.ckpt.latest_step() is None:
            from .checkpoint import transfer_params

            src_params = CheckpointManager(
                cfg.train.init_from + "/ckpt", create=False,
                async_save=False).restore_raw(subtree="params")
            if src_params is None:
                raise FileNotFoundError(
                    f"train.init_from: no checkpoint under "
                    f"{cfg.train.init_from}/ckpt")
            params, n_copied, n_skipped = transfer_params(
                self.state.params, src_params)
            self.state = self.state.replace(params=params)
            self.logger.log(
                "info", 0,
                message=f"transfer init from {cfg.train.init_from}: "
                        f"{n_copied} tensors copied, {n_skipped} re-init")

        restored = self.ckpt.restore(self.state)
        if restored is not None:
            self.state = restored
            self.logger.log("info", int(self.state.step),
                            message=f"resumed from step {int(self.state.step)}")
        elif self.ckpt.latest_step() is not None:
            # checkpoints EXIST but none is restorable (every candidate
            # failed verification/restore): silently starting from step 0
            # would clobber/prune a damaged run's directory and hide the
            # corruption — refuse, with the diagnosis command
            raise RuntimeError(
                f"auto-resume: checkpoints exist under {self.ckpt.directory} "
                "but none is restorable (all candidates failed "
                "verification/restore); refusing to silently restart from "
                "scratch — run `deepof_tpu verify-ckpt "
                f"{cfg.train.log_dir}` for per-checkpoint status, then move "
                "the ckpt directory aside to intentionally start fresh")

        # Sharded eval requires eval_batch_size % data-axis size == 0; adjust
        # to the nearest multiple (minimum one sample per shard) rather than
        # erroring mid-training.
        data_shards = self.mesh.shape["data"]
        eval_bs = max(cfg.train.eval_batch_size // data_shards, 1) * data_shards
        if eval_bs != cfg.train.eval_batch_size:
            self.logger.log(
                "warn", 0,
                message=f"eval_batch_size {cfg.train.eval_batch_size} not "
                        f"divisible by data axis ({data_shards}); adjusted "
                        f"to {eval_bs}")
            import dataclasses as _dc

            cfg = cfg.replace(train=_dc.replace(cfg.train,
                                                eval_batch_size=eval_bs))
            self.cfg = cfg

        spatial = self.mesh.shape.get("spatial", 1)
        if spatial > 1:
            from ..parallel.spatial import min_spatial_height, spatial_cp_active

            h = (cfg.data.crop_size or cfg.data.image_size)[0]
            down = getattr(self.model, "max_downsample", 64)
            if not spatial_cp_active(h, down, spatial):
                self.logger.log(
                    "warn", 0,
                    message=f"spatial CP inactive: H={h} fails the "
                            f"gradient-safety gate for {cfg.model} at "
                            f"spatial={spatial} (need H >= "
                            f"{min_spatial_height(down, spatial)}, H % "
                            f"{spatial} == 0, and no empty deepest-level "
                            "shard — parallel/spatial.py); those devices "
                            "only replicate work")

        smooth_border = cfg.model in ("st_single", "st_baseline")
        self._injected_step = train_step is not None
        self.train_step = (train_step if train_step is not None else
                           make_train_step(self.model, cfg,
                                           self.dataset.mean,
                                           self.mesh, smooth_border))
        self.eval_fn = (eval_fn if eval_fn is not None else
                        make_eval_fn(self.model, cfg, self.dataset.mean,
                                     mesh=self.mesh,
                                     smooth_border_mask=smooth_border))
        if jax.process_count() > 1:
            # Multi-host eval: every host loads the same full val batch
            # (deterministic), contributes its rows to the global array,
            # and allgathers outputs so host-side AEE sees the full batch.
            from jax.experimental import multihost_utils

            from ..parallel.mesh import put_global_from_full

            raw_eval, mesh_ = self.eval_fn, self.mesh

            def eval_fn_mh(params, batch):
                batch = put_global_from_full(batch, mesh_,
                                             batch_sharding(mesh_))
                return {k: multihost_utils.process_allgather(v, tiled=True)
                        for k, v in raw_eval(params, batch).items()}

            self.eval_fn = eval_fn_mh
        self._augment = None  # set by enable_augmentation()

    def enable_augmentation(self) -> None:
        if self.cfg.data.augment_geo or self.cfg.data.augment_photo:
            from ..data.augmentation import make_augment_fn

            self._augment = make_augment_fn(self.cfg.data)

    def _local_train_batch_size(self) -> int:
        """Rows this host loads per step. Single-process: the full batch.
        Multi-host: only the rows of this process's data-axis shards — each
        host loads 1/num_hosts of the data (SURVEY.md §5.8); hosts draw
        from decorrelated rng streams (see fit())."""
        if jax.process_count() == 1:
            return self.cfg.data.batch_size
        from ..parallel.mesh import local_batch_rows

        n, _ = local_batch_rows(self.mesh, self.cfg.data.batch_size)
        return n

    def _next_train_batch(self, it: int, rng: np.random.RandomState) -> dict:
        batch = self.dataset.sample_train(self._local_train_batch_size(), rng=rng)
        if self._augment is not None:
            batch = self._augment(batch, np.int64(rng.randint(0, 2**31)))
        return batch

    def evaluate(self, dump: bool = False) -> dict[str, float]:
        # visuals are identical on every host (replicated state): one writer
        dump = dump and jax.process_index() == 0
        dump_dir = (self.cfg.train.log_dir + "/visuals") if dump else None
        if self.cfg.model in ("st_single", "st_baseline", "ucf101_spatial"):
            return evaluate_ucf101(self.eval_fn, self.state.params,
                                   self.dataset, self.cfg)
        return evaluate_aee(self.eval_fn, self.state.params, self.dataset,
                            self.cfg, dump_dir)

    def fit(self, num_epochs: int | None = None,
            max_steps: int | None = None) -> dict[str, float]:
        cfg = self.cfg
        self.enable_augmentation()
        start_step = int(self.state.step)
        el = cfg.elastic
        if self._elastic_child:
            # Elastic determinism contract (train/elastic.py): the host
            # index, the CURRENT world size, and the generation are all
            # folded into the base seed — each re-form re-shards every
            # survivor onto a stream decorrelated from everything any
            # previous generation drew, and the whole run reproduces
            # from (seed, fault schedule) alone.
            from ..parallel.mesh import elastic_stream_seed

            seed_arr = elastic_stream_seed(cfg.train.seed, el.host_index,
                                           el.num_hosts, el.generation,
                                           start_step)
        else:
            seed_arr = data_stream_seed(self.mesh, cfg.train.seed,
                                        start_step)
        inj = self._inj
        # Self-healing data path (resilience/healing.py): per micro-batch
        # index, bounded retries with backoff — the rng is RE-DERIVED per
        # attempt, so a recovered transient fault yields the bit-identical
        # batch — then quarantine + a deterministic substitute drawn from
        # the same derive_batch_rng stream (salt = redraw round). Runs
        # inside the pipeline workers, so healing parallelizes with
        # assembly for any `num_workers`.
        # warn records from the healer (worker threads, possibly a few
        # batches ahead of the loop) stamp the loop's CURRENT step — an
        # approximate but live timeline, not the fit's start step
        cur_step = {"s": start_step}
        healer = HealingSampler(
            make_rng=lambda i, rnd: derive_batch_rng(seed_arr, i, salt=rnd),
            sample=self._next_train_batch,
            retries=cfg.resilience.data_retries,
            backoff_s=cfg.resilience.data_backoff_s,
            substitutes=cfg.resilience.data_substitutes,
            injector=inj,
            log=lambda m: self.logger.log("warn", cur_step["s"], message=m))
        k = max(cfg.train.steps_per_call, 1)
        if k == 1:
            sharding = batch_sharding(self.mesh)
        else:
            from ..parallel.mesh import stacked_batch_sharding

            sharding = stacked_batch_sharding(self.mesh)

        def _stack(xs):
            # On-device augmentation output stays on device (D2D stack);
            # np.stack would silently read full image batches back to host.
            # Multi-process must take the host path: put_global's
            # device-array assembly treats axis 0 as the data-sharded batch
            # axis, which the stacked [K, B, ...] layout violates.
            if isinstance(xs[0], jax.Array) and jax.process_count() == 1:
                return jnp.stack(xs)
            return np.stack([np.asarray(x) for x in xs])

        def assemble(call_idx: int) -> dict:
            """One dispatch's input, a pure function of its index: each
            micro-batch i draws from derive_batch_rng(seed_arr, i), so
            the stream is identical for any num_workers AND any
            steps_per_call regrouping. Runs on pipeline workers (or
            inline on the prefetch thread at num_workers=0) — decode,
            augmentation, and the K-stack all happen off the main
            thread. A NaN rollback resumes dispatching from the next
            unconsumed index (the stream continues forward, exactly like
            the pre-pipeline sequential rng did). Sample draws go
            through the HealingSampler (retry/quarantine/substitute);
            the `assemble` injection site sits above it so an injected
            assembly fault exercises the pipeline-worker retry path."""
            if inj is not None:
                inj.check("assemble", call_idx)
            if k == 1:
                return healer(call_idx)
            # steps_per_call: K batches stacked on a leading scan axis
            bs = [healer(i) for i in range(call_idx * k, call_idx * k + k)]
            return {key: _stack([b[key] for b in bs]) for key in bs[0]}

        # --- Observability (DESIGN.md "Observability") ---
        # Span tracer installed BEFORE the pipeline: its workers start
        # assembling eagerly at construction, and those spans belong on
        # the timeline. Single-writer (primary process only), same
        # rationale as MetricsLogger; uninstalled + flushed in finally.
        primary = jax.process_index() == 0
        tracer = None
        if cfg.obs.trace and primary:
            # (role, index) stamp the trace so obs/aggregate.py can
            # merge an elastic pool's per-host timelines; host_index < 0
            # (plain single-process training) stamps trainer-0
            tracer = obs_trace.install(obs_trace.Tracer(
                path=os.path.join(cfg.train.log_dir, "trace.json"),
                ring_size=cfg.obs.trace_ring, role="trainer",
                index=max(cfg.elastic.host_index, 0)))

        def _obs_teardown() -> None:
            # construction-failure path: the process-global tracer must
            # not outlive this fit (a later fit/eval would silently
            # record into the dead run's ring); flush what was collected
            if tracer is not None:
                obs_trace.uninstall()
                try:
                    tracer.flush()
                except OSError:
                    pass
        timer = StepTimer(cfg.data.batch_size, len(self.mesh.devices.flat))
        # Multi-worker host assembly (data/pipeline.py): N threads
        # decode/augment/stack out-of-order, delivery stays in index
        # order through the bounded reorder buffer.
        pipeline = InputPipeline(assemble, num_workers=cfg.data.num_workers,
                                 reorder_depth=cfg.data.reorder_depth,
                                 retries=cfg.resilience.pipeline_retries,
                                 backoff_s=cfg.resilience.data_backoff_s)
        # stage=True: the next (super-)batch is transferred AND resident
        # on device while the current call's scan executes, its wait spent
        # on the prefetch thread and accounted as the `put` phase. The
        # pipeline's workers start assembling eagerly at construction, so
        # a failure before the main try/finally takes ownership must not
        # leak the live pool.
        try:
            prefetch = Prefetcher(pipeline.get, depth=cfg.data.prefetch,
                                  sharding=sharding, stage=True,
                                  phase_cb=timer.phase)
        except BaseException:
            pipeline.close()
            _obs_teardown()
            raise
        # In-flight metrics pipelining (DESIGN.md "Execution layer"):
        # depth > 0 drains value fetches on a background consumer so the
        # next dispatch never waits on the previous fetch's RTT; the
        # bounded queue blocks dispatch at `depth` in-flight calls,
        # keeping host progress honest. depth 0 = serial fetch inline.
        depth = max(cfg.train.pipeline_depth, 0)
        fetch_kw = dict(timer=timer, retries=cfg.resilience.fetch_retries,
                        backoff_s=cfg.resilience.data_backoff_s, injector=inj)
        try:
            fetcher = (AsyncFetcher(depth=depth, **fetch_kw) if depth > 0
                       else SyncFetcher(**fetch_kw))
        except BaseException:  # same leak guard as the Prefetcher above
            pipeline.close()
            prefetch.close()
            _obs_teardown()
            raise

        def resilience_stats() -> dict:
            """ONE source for the prefixed data-path/fetcher/ckpt/fault
            counter merge — the heartbeat sample, every periodic train
            record, and the fit summary all call this, so the three
            surfaces can never drift apart."""
            return {**{f"data_{sk}": sv
                       for sk, sv in pipeline.stats().items()},
                    **{f"data_{sk}": sv
                       for sk, sv in prefetch.stats().items()},
                    **{f"data_{sk}": sv
                       for sk, sv in healer.stats().items()},
                    **{f"pipeline_{sk}": sv
                       for sk, sv in fetcher.stats().items()},
                    **{f"ckpt_{sk}": sv
                       for sk, sv in self.ckpt.stats().items()},
                    **({f"fault_{sk}": sv
                        for sk, sv in inj.stats().items()}
                       if inj is not None else {}),
                    **(self._extra_stats()
                       if self._extra_stats is not None else {})}
        # Liveness heartbeat + wedge watchdog (obs/heartbeat.py): a
        # background thread atomically rewrites heartbeat.json with
        # step/rates/depths/device-memory/RSS, and dumps every thread's
        # stack to the log (+ flushes the trace ring) when no step
        # completes within watchdog_factor x the median recent step time
        # — the historical "hung fetch on a dead tunnel" becomes a
        # diagnosable artifact instead of a silent stall.
        # Executable ledger (obs/ledger.py): the live run's train-step
        # provenance row — StableHLO fingerprint, first-step compile
        # wall, persistent-cache hit/miss, cost analysis, donation map —
        # appended to <log_dir>/ledger.jsonl at the first step, from the
        # same lower-only retrace the FLOPs telemetry already pays.
        # Memory-analysis fields stay None here (the jit-dispatch path
        # has no AOT Compiled object; `warmup` rows carry them).
        ledger = (ExecutableLedger(cfg.train.log_dir,
                                   backend=jax.default_backend())
                  if cfg.obs.ledger and primary else None)
        # Incident flight recorder (obs/incident.py): NaN rollbacks,
        # quarantine exhaustion, and watchdog wedges snapshot a bounded
        # diagnostic bundle; off (and structurally absent) by default.
        incidents = (obs_incident.install(cfg, cfg.train.log_dir,
                                          "trainer")
                     if primary else None)
        heartbeat = None
        if cfg.obs.heartbeat and primary:

            def _hb_sample() -> dict:
                # resilience counters ride along (skipped_updates /
                # rollbacks via timer.counters(), quarantine/retry/
                # fallback via resilience_stats) so `deepof_tpu tail`
                # sees recovery activity even between train records;
                # the exec_* ledger block does too once the first step
                # has recorded the lowering
                return {**timer.rates(), **timer.counters(),
                        **resilience_stats(),
                        **(ledger.stats() if ledger is not None else {})}

            sample_fn = (_hb_sample if incidents is None
                         else incidents.wrap_sample(_hb_sample))
            try:
                heartbeat = Heartbeat(
                    os.path.join(cfg.train.log_dir, "heartbeat.json"),
                    period_s=cfg.obs.heartbeat_period_s,
                    watchdog_factor=cfg.obs.watchdog_factor,
                    watchdog_min_s=cfg.obs.watchdog_min_s,
                    sample=sample_fn,
                    log=lambda s, m: self.logger.log("warn", s, message=m),
                    tracer=tracer,
                    on_wedge=(None if incidents is None else
                              lambda dump: incidents.record(
                                  "watchdog_wedge", "critical",
                                  text_files={"stacks.txt": dump})))
            except BaseException:  # same leak guard as above
                fetcher.close()
                pipeline.close()
                prefetch.close()
                _obs_teardown()
                raise
        # Set by the fetch callback when a fetched step is non-finite;
        # the main loop converts it into a rollback at the next boundary
        # (at most `depth` extra dispatched calls late — all discarded by
        # the checkpoint restore, so divergence handling is unchanged).
        nan_event: dict = {"m": None}
        streak = {"ok": False}  # a fetched finite step resets the NaN streak
        # Divergence-ladder rung-1 state (DESIGN.md "Resilience"): the
        # step function skips non-finite updates in place; the observed
        # skip streak escalates to a rollback only at
        # resilience.max_consecutive_skips. Counted at fetch granularity
        # (metrics are only host-visible at log/eval/ckpt boundaries).
        skip_state = {"streak": 0}
        last_eval: dict[str, float] = {}
        # Preemption-graceful stop (SURVEY.md §5.3): TPU pods get SIGTERM
        # before eviction; the reference dies losing everything since its
        # last Saver call. Here the FIRST signal just ends the step loop,
        # so the normal end-of-fit path runs: NaN-guard-checked final
        # checkpoint + async-save commit — auto-resume then continues the
        # schedule exactly. A SECOND signal escalates to the default
        # action (a run hung in prefetch.get()/compile must stay killable
        # by SIGTERM, not force an operator SIGKILL that would skip
        # finalize()). Registered only in the main thread (signal.signal
        # raises ValueError elsewhere — e.g. a trainer driven from a
        # worker thread — where the host runtime owns signal handling).
        stop_sig: dict[str, int | None] = {"sig": None}

        def _on_sigterm(signum, frame):
            if stop_sig["sig"] is not None:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)
                return
            stop_sig["sig"] = signum

        # explicit installed flag: signal.signal() returns None for a
        # previous NON-Python (C-level) handler, so None cannot double as
        # the "not installed" sentinel
        handler_installed = False
        prev_handler = None
        try:
            prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
            handler_installed = True
        except ValueError:
            pass
        # A SIGTERM latched by install_preemption_latch() before this
        # point (during model build / first compile) becomes an immediate
        # save-and-stop: the loop below exits before its first step and
        # the finalize path writes the checkpoint.
        if _EARLY_SIGTERM["sig"] is not None:
            stop_sig["sig"] = _EARLY_SIGTERM["sig"]
            _EARLY_SIGTERM["sig"] = None
        try:
            total_steps = (num_epochs or cfg.train.num_epochs) * self.steps_per_epoch
            if max_steps is not None:
                total_steps = min(total_steps, start_step + max_steps)
            if self._elastic_child and el.target_step > 0:
                # elastic runs train to an ABSOLUTE step: a respawned
                # trainer resumes from the re-form checkpoint and stops
                # where the run ends, not target-more-steps later
                total_steps = int(el.target_step)
            if cfg.train.nan_guard and self.ckpt.latest_step() is None:
                self.ckpt.save(self.state)  # rollback target before step 1
            ckpt_mark = timer.mark()
            self.profiler.maybe_start()
            first_step = True

            def _crossed(prev: int, new: int, every: int) -> bool:
                return every > 0 and prev // every != new // every

            def _scalar_last(v) -> float:
                """Last inner step's value (arrays carry a leading K axis
                when steps_per_call > 1); v is already host-side."""
                a = np.asarray(v)
                return float(a) if a.ndim == 0 else float(a[-1])

            def _on_metrics(tag, m_host):
                """Fetch-completion consumer: divergence triage + the
                train log record. Runs on the fetcher thread (or inline
                at depth 0) once the device values for `tag`'s step have
                ARRIVED — the honest value-fetch clock (DESIGN.md).

                The graduated ladder: updates the step fn already
                skipped in place (`update_skipped`) cost nothing beyond
                a counter until the skip streak hits
                resilience.max_consecutive_skips — then escalate to the
                checkpoint rollback. A non-finite loss whose update was
                NOT skipped means divergence reached the state: roll
                back immediately (the pre-ladder behavior)."""
                gs, ep, log_due_ = tag
                skipped = 0
                if "update_skipped" in m_host:
                    skipped = int(round(float(
                        np.asarray(m_host["update_skipped"]).sum())))
                if skipped:
                    timer.count("skipped_updates", skipped)
                    skip_state["streak"] += skipped
                    self.logger.log(
                        "warn", gs,
                        message=f"non-finite grads at step {gs}: "
                                f"{skipped} update(s) skipped in place "
                                f"(state unchanged; streak "
                                f"{skip_state['streak']}/"
                                f"{cfg.resilience.max_consecutive_skips})")
                nonfinite = cfg.train.nan_guard and not np.isfinite(
                    np.asarray(m_host["total"])).all()
                if nonfinite and not skipped:
                    nan_event["m"] = (gs, m_host)
                    return  # never log a diverged record
                if (skipped and cfg.train.nan_guard
                        and skip_state["streak"] >= max(
                            cfg.resilience.max_consecutive_skips, 1)):
                    # escalate skip->rollback — rollback is nan_guard
                    # machinery, so nan_guard=false keeps its pre-ladder
                    # meaning: count skips, never roll back or abort
                    nan_event["m"] = (gs, m_host)
                    return
                if not skipped:
                    skip_state["streak"] = 0
                if nonfinite:
                    return  # skipped in place: state clean, record isn't
                streak["ok"] = True
                if log_due_:
                    # input-side observability travels with every train
                    # record: pipeline queue/assemble/utilization stats,
                    # the loop's starved counter, and the decoded-image
                    # cache's hit/miss/eviction counters (alongside the
                    # compile-cache counters in the first-step record)
                    cache_s = getattr(self.dataset, "cache_stats", None)
                    cache_kw = ({f"decode_cache_{ck}": cv
                                 for ck, cv in cache_s().items()
                                 if ck in ("hits", "misses", "evictions")}
                                if cache_s is not None else {})
                    self.logger.log(
                        "train", gs, epoch=ep,
                        loss=_scalar_last(m_host["total"]),
                        lr=float(self.schedule(gs - 1)),
                        grad_norm=_scalar_last(m_host["grad_norm"]),
                        **{key: _scalar_last(v) for key, v in m_host.items()
                           if key in ("action_loss", "accuracy")},
                        # per-pyramid-scale loss decomposition (finest
                        # first): photometric vs smoothness trajectories
                        # in every periodic record, not just the total
                        **{field: per_scale_last(m_host[src])
                           for field, src in SCALE_RECORD_FIELDS
                           if src in m_host},
                        **timer.rates(), **timer.phases(),
                        **timer.counters(), **resilience_stats(),
                        **cache_kw, **self._telemetry(timer))

            gstep = start_step
            consecutive_nans = 0
            metrics = None
            # Pacing floor cache: the floor only advances within a
            # generation, so while gstep stays within sync_ahead of the
            # last observed floor no file read is needed at all.
            world_floor = start_step
            while gstep < total_steps and stop_sig["sig"] is None:
                if (self._elastic_child and el.sync_ahead > 0
                        and el.world_file
                        and gstep - world_floor > el.sync_ahead):
                    # step-skew limiter (train/elastic.py): wait while
                    # this host is more than sync_ahead steps past the
                    # slowest live host — a re-form can then discard at
                    # most ckpt-cadence + sync_ahead steps. The wait
                    # touches the heartbeat: a pacing leader is healthy.
                    floor = pace_to_world(
                        el.world_file, el.generation, gstep,
                        el.sync_ahead,
                        should_stop=lambda: stop_sig["sig"] is not None,
                        touch=(heartbeat.touch if heartbeat is not None
                               else None),
                        # a coordinator dead long enough to look stale
                        # by its own verdict horizon has stopped
                        # publishing: finish as an orphan, don't block
                        stale_s=max(3 * el.poll_s, el.stale_after_s))
                    # inapplicable pacing (no file / stale generation)
                    # re-checks only after another sync_ahead steps
                    world_floor = floor if floor is not None else gstep
                    if stop_sig["sig"] is not None:
                        break
                self.profiler.observe(gstep, k)  # --profile-steps window
                t0 = time.perf_counter()
                with obs_trace.span("input_wait"):
                    batch = prefetch.get()
                wait = time.perf_counter() - t0
                timer.phase("assemble", wait)
                if wait > STARVED_WAIT_S:
                    # the device-facing starvation signal: the main
                    # thread (and so the next dispatch) measurably
                    # waited on the host input side
                    timer.count("starved")
                if inj is not None:
                    # dispatch-site fault: poison the staged batch with
                    # one NaN — the deterministic stand-in for "the
                    # device produced non-finite grads at this step",
                    # exercising the skip-in-place rung end to end. The
                    # whole dispatched window [gstep, gstep+k) is checked
                    # so a scheduled step inside a steps_per_call stride
                    # still fires (the poison lands in the first
                    # micro-batch — the skip ladder doesn't care which).
                    hits = [s for s in range(gstep, gstep + k)
                            if inj.hit("dispatch", s)]
                    if hits:
                        batch = _poison_batch(batch)
                        self.logger.log(
                            "warn", gstep,
                            message=f"fault injection: dispatch batch at "
                                    f"step(s) {hits} poisoned with NaN")
                t0 = time.perf_counter()
                if first_step:  # XLA compile-time report (SURVEY.md §5.1)
                    cache_watch = cache_delta()
                    with obs_trace.span("dispatch", step=gstep + k,
                                        compile=True):
                        self.state, metrics = self.train_step(self.state,
                                                              batch)
                        jax.block_until_ready(metrics["total"])
                    dc = cache_watch.stats()
                    first_wall = time.perf_counter() - t0
                    lowered = None
                    if cfg.obs.flops or ledger is not None:
                        # ONE lower-only retrace (no second backend
                        # compile) serves both the FLOPs telemetry and
                        # the ledger's provenance row
                        try:
                            lowered = self.train_step.lower(self.state,
                                                            batch)
                        except Exception:  # noqa: BLE001 - telemetry only
                            lowered = None
                    if cfg.obs.flops and lowered is not None:
                        # every periodic record then carries model_tflops
                        self._flops_per_step = lowered_flops(lowered)
                    if ledger is not None and not self._injected_step:
                        # compile_kind="first_step": first_wall includes
                        # one EXECUTED step stride, a different unit
                        # from warmup's pure lower+compile "aot" rows —
                        # diff_ledgers only bounds like against like.
                        # An INJECTED pre-compiled step (recipe engine)
                        # records nothing: its compile already owns an
                        # "aot" row (train_step_stage<i>) and its first
                        # dispatch is execution, not compile — keeping
                        # the ledger a pure compile record is what
                        # makes "a stage switch added zero rows"
                        # provable from it
                        ledger.record("train_step", lowered=lowered,
                                      compile_s=first_wall,
                                      compile_kind="first_step", cache=dc)
                    # hit/miss counters surfaced in metrics: a warmed
                    # process shows compile_cache_misses == 0 here
                    self.logger.log(
                        "info", gstep + k,
                        message=f"first step (compile + run): "
                                f"{time.perf_counter() - t0:.1f}s",
                        compile_cache_requests=dc["requests"],
                        compile_cache_hits=dc["hits"],
                        compile_cache_misses=dc["misses"],
                        flops_per_step=self._flops_per_step)
                    first_step = False
                else:
                    with obs_trace.span("dispatch", step=gstep + k):
                        self.state, metrics = self.train_step(self.state,
                                                              batch)
                timer.phase("dispatch", time.perf_counter() - t0)
                timer.tick(k)
                prev, gstep = gstep, gstep + k
                cur_step["s"] = gstep  # live step for healer warn records
                if heartbeat is not None:
                    heartbeat.beat(gstep)
                if inj is not None and self._elastic_child:
                    # host-level chaos (train/elastic.py): SIGKILL /
                    # wedge / preemption-SIGTERM of THIS host once its
                    # step reaches faults.host_fault_step — after the
                    # beat, so the coordinator's last observation of a
                    # killed host is the step it actually completed
                    maybe_host_fault(
                        inj, el.host_index, gstep,
                        cfg.resilience.faults.host_fault_step,
                        log=lambda m: self.logger.log(
                            "warn", gstep, message=m))
                epoch = gstep // self.steps_per_epoch
                end_of_epoch = _crossed(prev, gstep, self.steps_per_epoch)
                log_due = _crossed(prev, gstep, cfg.train.log_every) or end_of_epoch
                eval_due = end_of_epoch or _crossed(prev, gstep,
                                                    cfg.train.eval_every)

                ckpt_due = (end_of_epoch
                            and epoch % cfg.train.ckpt_every_epochs == 0)
                ckpt_due = ckpt_due or _crossed(prev, gstep,
                                                cfg.train.ckpt_every_steps)

                # One host fetch serves the NaN guard, logging, and the
                # pre-checkpoint health check (per-metric fetches would
                # each pay a transport round trip — DESIGN.md). The fetch
                # drains in the background: the next iteration's dispatch
                # proceeds while these values are still in transit.
                if log_due or eval_due or ckpt_due:
                    fetcher.submit((gstep, epoch, log_due), metrics,
                                   _on_metrics)

                # Sync points: eval and checkpoint decisions must see every
                # host-visible metric first, so divergence never reaches an
                # eval record and a NaN state is never saved as a rollback
                # target; at most log_every-1 + depth*K steps of NaN
                # training are lost (all rewound by the restore).
                if eval_due or ckpt_due or nan_event["m"] is not None:
                    fetcher.drain()

                if nan_event["m"] is not None:
                    # a NaN callback may land between the drain trigger
                    # above and this read; drain again (no-op when already
                    # drained) so every in-flight fetch — possibly from a
                    # step dispatched off the diverged state — lands
                    # before the rewind, never after it
                    fetcher.drain()
                    nan_step, _ = nan_event["m"]
                    nan_event["m"] = None
                    streak["ok"] = False
                    skip_state["streak"] = 0  # the rollback rewinds the run
                    timer.count("rollbacks")
                    if incidents is not None:
                        incidents.record(
                            "nan_rollback",
                            trigger={"nan_step": nan_step,
                                     "consecutive": consecutive_nans + 1})
                    self._rollback(nan_step)
                    gstep = int(self.state.step)
                    # discarded steps must not count toward throughput
                    # (rewind to the restored checkpoint's snapshot);
                    # log/eval/ckpt boundaries between the rollback
                    # target and the NaN step will re-fire as gstep
                    # re-crosses them (duplicate step records downstream)
                    timer.rewind(ckpt_mark)
                    if heartbeat is not None:
                        heartbeat.touch()  # restore device_puts took time
                    consecutive_nans += 1
                    if consecutive_nans >= 3:
                        if incidents is not None:
                            incidents.record(
                                "nan_quarantine_exhausted", "critical",
                                trigger={"step": gstep,
                                         "consecutive": consecutive_nans})
                        raise FloatingPointError(
                            f"loss diverged to NaN {consecutive_nans} "
                            f"consecutive times around step {gstep}; "
                            "rollback is not recovering — aborting")
                    continue
                if streak["ok"]:
                    streak["ok"] = False
                    consecutive_nans = 0

                if eval_due:
                    if heartbeat is not None:
                        # flush BEFORE the sweep: the first eval's XLA
                        # trace/lowering is GIL-bound Python — on a
                        # contended host it starves the heartbeat writer
                        # thread for its whole duration, and the elastic
                        # coordinator would judge the (fresh-but-frozen)
                        # file stale and evict a healthy host mid-eval.
                        # A synchronous write re-bases the supervisor's
                        # staleness clock to the eval's start (CHANGES
                        # PR 9 known-benign, fixed here; pinned in
                        # tests/test_elastic.py host_verdict timing)
                        heartbeat.touch(flush=True)
                    with obs_trace.span("eval", step=gstep):
                        last_eval = self.evaluate(dump=cfg.train.dump_visuals)
                    self.logger.log("eval", gstep, epoch=epoch, **last_eval)
                    timer.pause()  # eval time is not training throughput
                    if heartbeat is not None:
                        heartbeat.touch()  # a long sweep is not a wedge
                    if (self._on_eval is not None
                            and self._on_eval(gstep, dict(last_eval))):
                        # recipe advance trigger (train/recipe.py): end
                        # this stage's fit at the eval boundary; the
                        # normal finalize path below writes the clean
                        # final checkpoint the next stage resumes from
                        self.logger.log(
                            "info", gstep,
                            message="on_eval hook requested stop at step "
                                    f"{gstep} (stage advance trigger)")
                        break
                if ckpt_due:
                    with obs_trace.span("ckpt", step=gstep):
                        saved = self.ckpt.save(self.state)
                    if saved is not None:
                        # a DEGRADED save (disk full, injected) keeps the
                        # previous mark: a later rollback restores the
                        # last checkpoint actually written, and rewind
                        # must discard exactly the steps that restore
                        # discards — not just those since the failed save
                        ckpt_mark = timer.mark()
                    timer.pause()
                    if heartbeat is not None:
                        heartbeat.touch()
            self.profiler.maybe_stop()
            if healer.quarantine_log:
                # the run summary's quarantine listing: one info record
                # naming every quarantined draw (index, round, error) —
                # the per-event warn records carry the live timeline,
                # this is the roll-up an operator greps for
                self.logger.log(
                    "info", gstep,
                    message=f"{len(healer.quarantine_log)} sample draw(s) "
                            "quarantined and substituted this run: "
                            + "; ".join(
                                f"batch {ev['index']} round {ev['round']} "
                                f"({ev['error']})"
                                for ev in healer.quarantine_log[:20]))
            # all in-flight NaN checks land before finalize — but bounded:
            # a consumer wedged in a dead-tunnel device_get must not hang
            # this path away from the finally's close()/ckpt.finalize()
            drained = fetcher.drain(timeout=120.0)
            if not drained:
                self.logger.log(
                    "warn", gstep,
                    message="metrics fetch still in flight after 120s at "
                            "finalize (hung device?); final state cannot "
                            "be NaN-checked — skipping the final save")
            if stop_sig["sig"] is not None:
                self.logger.log(
                    "warn", gstep,
                    message=f"signal {stop_sig['sig']} received; stopping "
                            "after a clean final checkpoint (auto-resume "
                            "continues from here)")
            # The final state may include up to log_every-1 steps that no
            # host-visible NaN check has seen; saving it unchecked would
            # make a diverged state the newest checkpoint and defeat both
            # auto-resume and _rollback.
            final_ok = drained and nan_event["m"] is None
            if final_ok and cfg.train.nan_guard and metrics is not None:
                total = np.asarray(jax.device_get(metrics["total"]))
                final_ok = bool(np.isfinite(total).all())
                if not final_ok and "update_skipped" in metrics:
                    # a non-finite final loss whose update(s) the step fn
                    # skipped IN PLACE never reached the state — the
                    # state is clean and saving it is correct (rolling
                    # back would discard good steps for nothing)
                    sk = np.atleast_1d(np.asarray(
                        jax.device_get(metrics["update_skipped"])))
                    bad = ~np.isfinite(np.atleast_1d(total))
                    final_ok = bool(np.all(sk[bad] >= 0.5))
            if final_ok:
                self.ckpt.save(self.state)
            elif not drained:
                # hung device: the rollback below would also touch the
                # device (restore device_puts params); leave state as-is —
                # the newest committed checkpoint stays the resume point
                pass
            else:
                # don't just suppress the save: leave self.state consistent
                # with the newest (healthy) checkpoint so callers that keep
                # using the trainer don't run on diverged params
                self._rollback(gstep)
                timer.rewind(ckpt_mark)
                self.logger.log(
                    "warn", gstep,
                    message="non-finite loss at final step; state rolled "
                            "back to the last good checkpoint instead of "
                            "saving the diverged state")
        finally:
            if heartbeat is not None:
                heartbeat.close()  # writes the final heartbeat.json state
            fetcher.close()
            # pipeline BEFORE prefetch: the prefetch thread may be
            # blocked inside pipeline.get() waiting on workers, which
            # the Prefetcher's own stop event cannot interrupt —
            # closing the pipeline first releases it (its get() raise
            # is swallowed into the dying prefetch thread), so
            # prefetch.close()'s join returns promptly
            pipeline.close()
            prefetch.close()
            self.ckpt.finalize()  # commit any in-flight async save
            if tracer is not None:
                # uninstall first: this fit's tracer must not keep
                # collecting from a later fit()/eval; flush is
                # best-effort (a read-only tree must not mask a body
                # exception)
                obs_trace.uninstall()
                try:
                    tracer.flush()
                except OSError:
                    pass
            # restore only AFTER finalize(): the final async-save commit
            # must stay protected by the graceful handler. A C-level
            # previous handler cannot be re-installed from Python
            # (signal.signal returned None for it) — fall back to SIG_DFL
            # so the process at least stays killable. The early preemption
            # latch is likewise NOT restored: its only job was protecting
            # the pre-fit window, and re-arming it would silently swallow
            # the first SIGTERM after training completes.
            if handler_installed:
                restore = prev_handler
                if restore is None or restore is _EARLY_SIGTERM.get("handler"):
                    restore = signal.SIG_DFL
                signal.signal(signal.SIGTERM, restore)
        # phases + fetcher + input-pipeline stats travel with the rates:
        # bench logs show where host time went (assemble/put/dispatch/
        # fetch), how much overlap the pipelined drain achieved
        # (max_in_flight), and whether the device ever starved on host
        # batch assembly (starved / data_* worker stats).
        return {**last_eval, **timer.rates(), **timer.phases(),
                **timer.counters(),
                # resilience roll-up rides along (quarantine/retry/
                # substitute, checkpoint recovery events, fault_* when
                # injection is on) — every recovery event is visible in
                # the one-line run summary, from the same merge the
                # heartbeat and train records use
                **resilience_stats(),
                # telemetry (model_tflops/mfu_nominal/dev mem/rss);
                # None-valued fields dropped — the summary stays
                # float()-able for CLI printing
                **{k: v for k, v in self._telemetry(timer).items()
                   if v is not None}}

    def _telemetry(self, timer: StepTimer) -> dict:
        """Device-memory / RSS / model-FLOP fields for a train record
        (obs/telemetry.py — the bench-only instrumentation, promoted).
        Keys are schema-stable across backends: values the backend
        cannot report serialize as null in metrics.jsonl."""
        out = dict(device_memory_summary())
        out["rss_bytes"] = process_rss_bytes()
        if self._flops_per_step:
            sps = timer.rates()["steps_per_sec"]
            if sps > 0:
                tfs = self._flops_per_step * sps / timer.n_chips / 1e12
                # significant figures, not decimals: a cpu smoke's 1e-5
                # TFLOP/s must not round to a meaningless 0.0
                out["model_tflops"] = float(f"{tfs:.4g}")
                out["mfu_nominal"] = float(f"{tfs / NOMINAL_BF16_TFLOPS:.4g}")
        return out

    def _rollback(self, step: int) -> None:
        with obs_trace.span("rollback", step=step):
            restored = self.ckpt.restore(self.state)
            if restored is None:
                # no RESTORABLE checkpoint: either none was ever written
                # or every candidate failed verification/restore.
                # Proceeding would keep training on the diverged state —
                # fail with the one fact the operator needs (where the
                # checkpoints should be / what's in that dir).
                raise FloatingPointError(
                    f"divergence at step {step} and no restorable "
                    f"checkpoint under {self.ckpt.directory} to roll back "
                    "to (none written yet, or every candidate failed "
                    "verification — run `deepof_tpu verify-ckpt "
                    f"{os.path.dirname(self.ckpt.directory)}` to see "
                    "per-checkpoint status)")
            self.state = restored
        self.logger.log("warn", step,
                        message=f"divergence at step {step}; rolled back "
                                f"to step {int(restored.step)}")
