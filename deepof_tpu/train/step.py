"""pjit train/eval step functions.

One step builder serves every model family (the reference re-implements the
session loop per dataset, SURVEY.md §2.2):

  - 2-frame flow models (FlowNet-S/C, VGG16, Inception-v3): unsupervised
    pyramid loss over (source, target);
  - multi-frame volume models (Sintel T-volume): `pyramid_loss_multi`;
  - two-stream action models (STsingle/STbaseline): pyramid loss + action
    cross-entropy weighted by the finest flow weight, matching
    `ucf101wrapFlow.py:186-188`;
  - spatial-only classifier: cross-entropy.

Data parallelism: the step is `jax.jit`-ed with the batch sharded over the
mesh "data" axis and the state replicated; XLA inserts the gradient
all-reduce over ICI from the sharding annotations (no hand-written psum
needed — SURVEY.md §2.7).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

from ..core.config import ExperimentConfig, LossConfig
from ..losses.pyramid import (
    lrn_normalize,
    preprocess,
    pyramid_loss,
    pyramid_loss_multi,
)
from ..parallel.mesh import batch_sharding, replicated_sharding
from ..parallel.spatial import constrain_batch, mesh_context
from .state import TrainState

Mean = tuple[float, float, float]


def _tiled_mean(mean: Mean, channels: int) -> jnp.ndarray:
    reps = channels // len(mean)
    return jnp.tile(jnp.asarray(mean), reps)


def model_losses(
    model,
    params,
    batch: dict[str, jnp.ndarray],
    mean: Mean,
    loss_cfg: LossConfig,
    train: bool = False,
    dropout_rng: jax.Array | None = None,
    smooth_border_mask: bool = False,
    compute_dtype: Any = jnp.float32,
    remat: bool = False,
) -> tuple[jnp.ndarray, dict[str, Any]]:
    """Forward + objective. Returns (total_loss, aux dict with per-scale
    loss dicts, finest flow, reconstruction, and optional action logits)."""
    rngs = {"dropout": dropout_rng} if (train and dropout_rng is not None) else None
    # Spatial context parallelism: shard H over the "spatial" mesh axis (if
    # populated) so GSPMD partitions the convs with compiler-inserted halo
    # exchanges (SURVEY.md §5.7). Reads the mesh from the enclosing
    # `mesh_context` set by the step builders. The model's downsample
    # factor derives the gradient-safety fence (parallel/spatial.py).
    batch = constrain_batch(
        batch, max_downsample=getattr(model, "max_downsample", 64))

    def fwd(x, **kw):
        def inner(xx):
            out = model.apply({"params": params}, xx.astype(compute_dtype),
                              rngs=rngs, **kw)
            return jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), out)

        # rematerialize the encoder-decoder in backward instead of storing
        # its activations (TrainConfig.remat; params are closure-captured,
        # which jax.checkpoint differentiates through)
        return jax.checkpoint(inner)(x) if remat else inner(x)

    aux: dict[str, Any] = {}

    if "volume" in batch:  # multi-frame Sintel volume
        vol = batch["volume"]
        scaled = preprocess(vol, _tiled_mean(mean, vol.shape[-1]))
        flows = fwd(scaled)
        pyramid = list(zip(flows, model.flow_scales))
        total, losses, recon = pyramid_loss_multi(pyramid, lrn_normalize(scaled), loss_cfg)
        aux.update(losses=losses, flow=flows[0] * model.flow_scales[0], recon=recon)
        return total, aux

    # Dual-stream augmentation (reference `flyingChairsTrain_vgg.py:186-195`):
    # the photo-augmented pair (net_*) feeds the network; the geo-only pair
    # (source/target) feeds the photometric loss.
    src = preprocess(batch["source"], mean)
    tgt = preprocess(batch["target"], mean)
    net_src = preprocess(batch["net_source"], mean) if "net_source" in batch else src
    net_tgt = preprocess(batch["net_target"], mean) if "net_target" in batch else tgt
    pair = jnp.concatenate([net_src, net_tgt], axis=-1)

    if getattr(model, "classifier_only", False):
        logits = fwd(src, train=train)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["label"])
        total = jnp.mean(ce)
        aux.update(logits=logits, action_loss=total)
        return total, aux

    is_two_stream = getattr(model, "has_action_head", False)
    if is_two_stream:
        flows, logits = fwd(pair, train=train)
    else:
        flows = fwd(pair)

    flows_bw = None
    if loss_cfg.occlusion and not is_two_stream:
        # fw/bw occlusion masking: second forward on the swapped pair
        # (LossConfig.occlusion; costs one extra model evaluation)
        flows_bw = fwd(jnp.concatenate([net_tgt, net_src], axis=-1))

    pyramid = list(zip(flows, model.flow_scales))
    total, losses, recon = pyramid_loss(
        pyramid, lrn_normalize(src), lrn_normalize(tgt), loss_cfg,
        smooth_border_mask=smooth_border_mask, flow_pyramid_bw=flows_bw)
    aux.update(losses=losses, flow=flows[0] * model.flow_scales[0], recon=recon)

    if is_two_stream:
        ce = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, batch["label"]))
        # action loss enters with the finest flow weight (`ucf101wrapFlow.py:186-188`)
        total = total + loss_cfg.weights[0] * ce
        acc = jnp.mean((jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
        aux.update(logits=logits, action_loss=ce, accuracy=acc)
    return total, aux


def make_train_step(model, cfg: ExperimentConfig, mean: Mean, mesh,
                    smooth_border_mask: bool = False):
    """Build the jitted, sharded train step: (state, batch) -> (state, metrics).

    With `cfg.train.steps_per_call = K > 1` the returned fn instead takes K
    stacked batches ([K, B, ...] leaves) and runs K optimizer steps in one
    call via `lax.scan`, returning metrics with a leading K axis. One
    dispatch + one value fetch then serves K steps — amortizing per-step
    host/transport overhead (DESIGN.md "Benchmark honesty").
    """
    compute_dtype = jnp.bfloat16 if cfg.train.compute_dtype == "bfloat16" else jnp.float32

    if cfg.loss.occlusion and (
            getattr(model, "has_action_head", False)
            or getattr(model, "classifier_only", False)
            or cfg.data.time_step > 2):
        raise ValueError(
            "loss.occlusion=true supports only flow-only 2-frame models; "
            f"model={cfg.model!r} time_step={cfg.data.time_step} would "
            "silently skip the masking")

    def step(state: TrainState, batch):
        rng, dropout_rng = jax.random.split(state.rng)

        def loss_fn(params):
            with mesh_context(mesh):
                total, aux = model_losses(
                    model, params, batch, mean, cfg.loss, train=True,
                    dropout_rng=dropout_rng,
                    smooth_border_mask=smooth_border_mask,
                    compute_dtype=compute_dtype, remat=cfg.train.remat)
            return total, aux

        (total, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        grad_norm = optax.global_norm(grads)
        if cfg.resilience.skip_nonfinite:
            # Divergence-ladder rung 1 (DESIGN.md "Resilience"): detect
            # non-finite loss/grads BEFORE the update and skip it in
            # place — params, opt_state, and step stay exactly the
            # previous state's (rng still advances so a retried batch
            # doesn't replay the same dropout draw), and the host sees
            # `update_skipped` per inner step. One bad batch then costs
            # one skipped update, not a checkpoint rollback. The select
            # is a no-op bitwise when finite: jnp.where(True, new, old)
            # returns `new` exactly.
            finite = jnp.isfinite(total) & jnp.isfinite(grad_norm)
            applied = state.apply_gradients(grads).replace(rng=rng)
            kept = state.replace(rng=rng)
            new_state = jax.tree_util.tree_map(
                lambda n, o: jnp.where(finite, n, o), applied, kept)
            skipped = 1.0 - finite.astype(jnp.float32)
        else:
            new_state = state.apply_gradients(grads).replace(rng=rng)
            skipped = jnp.float32(0.0)
        metrics = {"total": total, "grad_norm": grad_norm,
                   "update_skipped": skipped}
        if "losses" in aux:
            # per-pyramid-scale decomposition (finest first): photometric
            # ("Charbonnier_reconstruct") and smoothness ("smooth" = U+V)
            # components ride every metrics fetch — the loop folds them
            # into each periodic train record as loss_*_by_scale lists
            for key in ("total", "Charbonnier_reconstruct", "U_loss",
                        "V_loss", "smooth"):
                metrics[f"scale_{key}"] = jnp.stack([d[key] for d in aux["losses"]])
        for key in ("action_loss", "accuracy"):
            if key in aux:
                metrics[key] = aux[key]
        return new_state, metrics

    repl, data = replicated_sharding(mesh), batch_sharding(mesh)
    k = max(cfg.train.steps_per_call, 1)
    if k == 1:
        return jax.jit(
            step,
            in_shardings=(repl, data),
            out_shardings=(repl, repl),
            donate_argnums=(0,),
        )

    from ..parallel.mesh import stacked_batch_sharding

    def multi_step(state: TrainState, batches):
        return jax.lax.scan(step, state, batches)

    return jax.jit(
        multi_step,
        in_shardings=(repl, stacked_batch_sharding(mesh)),
        out_shardings=(repl, repl),
        donate_argnums=(0,),
    )


def make_eval_fn(model, cfg: ExperimentConfig, mean: Mean, mesh=None,
                 smooth_border_mask: bool = False):
    """Jitted eval forward: (params, batch) -> metrics + finest flow (already
    multiplied by flow_scale, before the eval amplifier/clip protocol which
    is host-side in `evaluate.py`). Reuses the training graph — the gen-1
    `testOF.py` design, not gen-2's graph-rebuilding evaluateNet
    (SURVEY.md §3.2)."""

    def fwd(params, batch):
        with mesh_context(mesh):
            total, aux = model_losses(
                model, params, batch, mean, cfg.loss, train=False,
                smooth_border_mask=smooth_border_mask)
        out = {"total": total}
        for key in ("flow", "recon", "logits"):
            if key in aux:
                out[key] = aux[key]
        return out

    if mesh is None:
        return jax.jit(fwd)
    return jax.jit(fwd, in_shardings=(replicated_sharding(mesh), batch_sharding(mesh)))
