"""Scrapeable metrics: fixed latency histograms, Prometheus text
rendering, and the SLO layer (DESIGN.md "Fleet observability").

Every stats surface in this repo (`serve_*` engine counters, `fleet_*`
router/supervisor counters, `elastic_*` coordinator counters, resilience
counters) is a flat dict of numbers that previously lived only in
heartbeat.json and metrics.jsonl. This module makes those same blocks
scrapeable:

  LatencyHistogram — FIXED log-spaced buckets (`LATENCY_BUCKETS_MS`,
      powers of two from 0.5 ms to ~16 s). Fixed by contract: two
      processes' histograms merge EXACTLY (bucket-wise integer sum), so
      the router's fleet-wide histogram equals the sum of its replicas'
      and a percentile read upstream never disagrees with one taken
      downstream. Thread-safe, O(1) observe.

  render_prometheus / parse_prometheus — the Prometheus text exposition
      format (text/plain; version=0.0.4) over any stats dict: numbers
      become gauges, nested numeric maps become labeled gauges, nested
      string maps become `{key=...,value=...} 1` state samples, and
      histogram snapshots become `_bucket{le=...}` cumulative series +
      `_sum`/`_count`. The parser is the test suite's and the bench
      recorder's read-back path, so render/parse round-trip is pinned.

  slo_state — latency/error-budget arithmetic from a histogram snapshot:
      the SLO threshold rounds UP to the nearest histogram bound (the
      bucket contract again — burn computed at any aggregation level is
      identical), breaches + server-side failures burn the error budget,
      and `exhausted` is the bit `tail` turns into its distinct exit
      code.

  start_metrics_server — a minimal stdlib HTTP server (GET /metrics +
      GET /healthz) for processes that have no HTTP frontend of their
      own (the elastic coordinator); the serve server and the fleet
      router mount /metrics on their existing handlers instead.

Stdlib-only at import (obs/__init__ discipline): analyze/tail and the
jax-free supervisors all use this module.
"""

from __future__ import annotations

import json
import re
import threading
from bisect import bisect_left
from typing import Callable

#: Fixed log-spaced latency bucket upper bounds, in milliseconds
#: (powers of two, 0.5 ms .. 16.4 s; one implicit +Inf bucket past the
#: end). FIXED means: never derived from config or observed data — two
#: histograms anywhere in the fleet always share these bounds, so
#: merging is an exact bucket-wise sum.
LATENCY_BUCKETS_MS: tuple[float, ...] = tuple(0.5 * 2 ** i
                                              for i in range(16))

#: Fixed log-spaced bounds for the label-free flow-QUALITY proxies
#: (obs/quality.py): dimensionless Charbonnier/census/smoothness values,
#: powers of two from ~0.001 to 1024. Same contract as the latency
#: bounds: never config-derived, so replica quality histograms merge
#: EXACTLY at the router. NOTE: quality snapshots reuse the histogram
#: snapshot schema ("buckets_ms"/"sum_ms" keys) for merge/percentile
#: machinery compatibility — the bounds are raw proxy units, not
#: milliseconds (the Prometheus renderer drops the _ms suffix for any
#: non-latency bounds).
QUALITY_BUCKETS: tuple[float, ...] = tuple(2.0 ** i for i in range(-10, 11))


class ValueHistogram:
    """Thread-safe fixed-bucket histogram over arbitrary nonnegative
    values. The bounds are fixed BY THE CALLER'S CONTRACT (a shared
    module constant, never config/data-derived), which is what makes two
    processes' snapshots merge exactly. O(1) observe."""

    def __init__(self, bounds: tuple[float, ...] = LATENCY_BUCKETS_MS,
                 sum_digits: int = 6):
        self._bounds = tuple(float(b) for b in bounds)
        self._sum_digits = int(sum_digits)
        self._lock = threading.Lock()
        self._counts = [0] * (len(self._bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = max(float(value), 0.0)
        idx = bisect_left(self._bounds, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        """JSON-ready state: {"buckets_ms", "counts", "sum_ms",
        "count"}. `counts` are per-bucket (NOT cumulative) so snapshots
        merge by element-wise addition; the Prometheus renderer
        cumulates at render time. Key names carry "_ms" for schema
        stability across every consumer — for non-latency bounds the
        values are raw units (see QUALITY_BUCKETS note)."""
        with self._lock:
            return {"buckets_ms": list(self._bounds),
                    "counts": list(self._counts),
                    "sum_ms": round(self._sum, self._sum_digits),
                    "count": self._count}


class LatencyHistogram(ValueHistogram):
    """Thread-safe fixed-bucket latency histogram (see module docstring).

    `observe` takes seconds (every latency in this repo is monotonic
    seconds); the snapshot reports milliseconds (the unit the serve
    percentiles already use)."""

    def __init__(self):
        super().__init__(LATENCY_BUCKETS_MS, sum_digits=3)

    def observe(self, seconds: float) -> None:
        super().observe(max(float(seconds), 0.0) * 1e3)


def percentile_ms(hist: dict | None, frac: float) -> float | None:
    """Approximate percentile from a fixed-bucket snapshot: the upper
    bound of the bucket holding the quantile rank (the same answer at
    every aggregation level, because the buckets are fixed by contract —
    unlike a deque-based percentile, this one survives an exact merge).
    None on an empty/absent histogram; observations in the +Inf bucket
    report the largest finite bound (the histogram cannot say more)."""
    if not is_hist_snapshot(hist):
        return None
    total = sum(int(c) for c in hist["counts"])
    if total <= 0:
        return None
    rank = max(min(float(frac), 1.0), 0.0) * (total - 1)
    cum = 0
    for i, c in enumerate(hist["counts"]):
        cum += int(c)
        if cum > rank:
            bounds = hist["buckets_ms"]
            return float(bounds[min(i, len(bounds) - 1)])
    return float(hist["buckets_ms"][-1])


def is_hist_snapshot(value) -> bool:
    return (isinstance(value, dict) and "counts" in value
            and "buckets_ms" in value)


def merge_hists(snapshots: list[dict]) -> dict:
    """Element-wise EXACT merge of histogram snapshots — the fleet
    aggregation primitive. Every snapshot in the set must share one
    internally consistent bound layout (the latency buckets, the quality
    buckets — any fixed-by-contract set); a mismatch within the set, or
    a bounds/counts length mismatch, raises ValueError — a foreign
    histogram must fail loudly, not merge approximately."""
    if not snapshots:
        raise ValueError("merge_hists: empty snapshot list")
    first = snapshots[0]
    if not is_hist_snapshot(first):
        raise ValueError(f"not a histogram snapshot: {first!r}")
    buckets = list(first["buckets_ms"])
    counts = [0] * (len(buckets) + 1)
    sum_ms = 0.0
    count = 0
    for s in snapshots:
        if not is_hist_snapshot(s):
            raise ValueError(f"not a histogram snapshot: {s!r}")
        if list(s["buckets_ms"]) != buckets or len(s["counts"]) != len(counts):
            raise ValueError(
                "histogram bucket bounds differ — cannot merge exactly "
                f"(got {s['buckets_ms']!r})")
        for i, c in enumerate(s["counts"]):
            counts[i] += int(c)
        sum_ms += float(s["sum_ms"])
        count += int(s["count"])
    return {"buckets_ms": buckets, "counts": counts,
            # 6 digits, not 3: quality-proxy sums are dimensionless and
            # can sit at 1e-4 scale per sample (ValueHistogram's
            # sum_digits=6) — a 3-digit merge would zero them fleet-wide
            "sum_ms": round(sum_ms, 6), "count": count}


# ------------------------------------------------------------------ SLO


def validate_slo(obs_cfg) -> None:
    """Loud config validation (the config_from_dict philosophy: a knob
    that cannot work must fail at construction, not silently no-op).
    A latency target past the largest histogram bound could never count
    a breach — the fixed buckets cannot distinguish 17 s from 60 s —
    so the serve engine and the fleet router reject it up front."""
    target = float(obs_cfg.slo_latency_ms)
    if target > LATENCY_BUCKETS_MS[-1]:
        raise ValueError(
            f"obs.slo_latency_ms={target:g} exceeds the largest fixed "
            f"histogram bound ({LATENCY_BUCKETS_MS[-1]:g} ms) — breaches "
            "past it are indistinguishable in the bucket layout and the "
            "SLO would silently never burn; pick a target <= the bound "
            "(or 0 to disable the SLO layer)")
    if float(obs_cfg.slo_error_budget) <= 0:
        raise ValueError(
            f"obs.slo_error_budget={obs_cfg.slo_error_budget!r} must be "
            "> 0 (the fraction of requests allowed to breach)")


def slo_state(hist: dict | None, requests: int, failures: int,
              latency_ms: float, error_budget: float) -> dict:
    """Latency/error-budget state from one histogram snapshot.

    hist: a LatencyHistogram snapshot (None = no latency data yet).
    requests: total admitted requests (the budget's denominator).
    failures: server-side failures (shed/unavailable/dispatch — CLIENT
        errors deliberately excluded: a caller's bad input must not burn
        the operator's budget).
    latency_ms: the SLO latency target; rounded UP to the nearest
        histogram bucket bound ("bucket_ms" reports the effective
        threshold) so burn computed from merged histograms at any
        aggregation level is identical.
    error_budget: allowed bad fraction (breaches + failures over
        requests); burn = bad_fraction / budget, exhausted at >= 1.
    """
    latency_ms = float(latency_ms)
    idx = bisect_left(LATENCY_BUCKETS_MS, latency_ms)
    bucket_ms = (LATENCY_BUCKETS_MS[idx] if idx < len(LATENCY_BUCKETS_MS)
                 else None)  # None: the target exceeds every bound (+Inf)
    breaches = 0
    if is_hist_snapshot(hist):
        # observations STRICTLY above the effective bound: everything in
        # buckets past idx (bucket idx holds obs <= its bound)
        breaches = sum(int(c) for c in hist["counts"][idx + 1:])
    requests = max(int(requests), 0)
    failures = max(int(failures), 0)
    bad = breaches + failures
    budget = max(float(error_budget), 1e-9)
    bad_fraction = (bad / requests) if requests else 0.0
    burn = bad_fraction / budget
    return {
        "latency_ms": latency_ms,
        "bucket_ms": bucket_ms,
        "error_budget": round(budget, 6),
        "requests": requests,
        "breaches": breaches,
        "failures": failures,
        "bad_fraction": round(bad_fraction, 6),
        "burn": round(burn, 4),
        "exhausted": bool(requests and bad_fraction >= budget),
    }


# ----------------------------------------------------------- prometheus

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
#: /metrics Content-Type (the exposition-format version Prometheus pins)
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _sanitize(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    return f"_{name}" if name[:1].isdigit() else name


def _escape_label(value) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt(value) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    f = float(value)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(stats: dict, namespace: str = "deepof") -> str:
    """Render a flat stats dict (the serve_*/fleet_*/elastic_* blocks)
    as Prometheus text exposition format. Rules:

      number/bool          -> gauge `ns_key value`
      dict of numbers      -> labeled gauge `ns_key{key="sub"} value`
      dict of strings      -> state sample `ns_key{key="sub",value="s"} 1`
      histogram snapshot   -> `ns_base_bucket{le=...}` CUMULATIVE counts
                              (+Inf last) + `ns_base_sum` + `ns_base_count`,
                              where base strips a trailing `_hist` and
                              appends `_ms` for latency-bounded
                              histograms (quality histograms keep raw
                              dimensionless names)
      None / other         -> skipped

    Deterministic output ordering (sorted keys) so scrapes diff cleanly.
    """
    lines: list[str] = []
    for key in sorted(stats):
        value = stats[key]
        if value is None or isinstance(value, str):
            continue
        name = f"{_sanitize(namespace)}_{_sanitize(key)}"
        if is_hist_snapshot(value):
            base = key[:-len("_hist")] if key.endswith("_hist") else key
            # the "_ms" unit suffix belongs only to latency histograms;
            # quality histograms (QUALITY_BUCKETS bounds) carry raw
            # dimensionless proxy values despite the snapshot's schema
            # key names (see QUALITY_BUCKETS note)
            unit = ("_ms" if list(value["buckets_ms"])
                    == list(LATENCY_BUCKETS_MS) else "")
            base = f"{_sanitize(namespace)}_{_sanitize(base)}{unit}"
            lines.append(f"# TYPE {base} histogram")
            cum = 0
            for bound, c in zip(value["buckets_ms"], value["counts"]):
                cum += int(c)
                lines.append(f'{base}_bucket{{le="{_fmt(bound)}"}} {cum}')
            cum += int(value["counts"][len(value["buckets_ms"])])
            lines.append(f'{base}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{base}_sum {_fmt(value['sum_ms'])}")
            lines.append(f"{base}_count {_fmt(value['count'])}")
        elif isinstance(value, dict):
            numeric = {k: v for k, v in value.items()
                       if isinstance(v, (int, float)) and v is not None}
            stringy = {k: v for k, v in value.items() if isinstance(v, str)}
            if numeric:
                lines.append(f"# TYPE {name} gauge")
                for sub in sorted(numeric):
                    lines.append(
                        f'{name}{{key="{_escape_label(sub)}"}} '
                        f"{_fmt(numeric[sub])}")
            if stringy:
                lines.append(f"# TYPE {name} gauge")
                for sub in sorted(stringy):
                    lines.append(
                        f'{name}{{key="{_escape_label(sub)}",'
                        f'value="{_escape_label(stringy[sub])}"}} 1')
        elif isinstance(value, (int, float)):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_fmt(value)}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> dict[str, float]:
    """Inverse of render_prometheus for the test suite and the bench
    scrape path: {"name" or 'name{a="b",...}' (labels sorted): value}.
    Unparseable lines are skipped (a scrape must not crash the reader)."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labels, raw = m.groups()
        try:
            value = float(raw)
        except ValueError:
            continue
        if labels:
            pairs = sorted(
                (k, v.encode().decode("unicode_escape"))
                for k, v in _LABEL_RE.findall(labels))
            name += "{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}"
        out[name] = value
    return out


# -------------------------------------------------------- metrics server


def start_metrics_server(stats_fn: Callable[[], dict],
                         host: str = "127.0.0.1", port: int = 0):
    """A minimal daemon-threaded HTTP server exposing GET /metrics
    (Prometheus text over `stats_fn()`) and GET /healthz (the same dict
    as JSON) — for processes with no frontend of their own (the elastic
    coordinator). Returns the already-serving HTTPServer; callers read
    `server_address` for the bound port and call shutdown()/
    server_close() on exit. `stats_fn` failures become a 500, never a
    crashed serving thread."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Server(ThreadingHTTPServer):
        daemon_threads = True

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # obs owns visibility
            pass

        def _reply(self, status: int, body: bytes, ctype: str) -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
            if self.path not in ("/metrics", "/healthz", "/stats"):
                self._reply(404, b'{"error": "not_found"}',
                            "application/json")
                return
            try:
                stats = stats_fn() or {}
            except Exception as e:  # noqa: BLE001 - scrape must not kill
                self._reply(500, json.dumps(
                    {"error": "stats_failed",
                     "message": f"{type(e).__name__}: {e}"}).encode(),
                    "application/json")
                return
            if self.path == "/metrics":
                self._reply(200, render_prometheus(stats).encode(),
                            PROM_CONTENT_TYPE)
            else:
                self._reply(200, json.dumps(stats).encode(),
                            "application/json")

    httpd = Server((host, port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True,
                     name="obs-metrics").start()
    return httpd
