"""Label-free flow-quality observability (DESIGN.md "Quality
observability").

The paper's core insight is that flow quality is measurable WITHOUT
ground truth: warp the second frame backward by the predicted flow and
score the photometric error against the first frame (PAPER.md §0 — the
training objective itself is this proxy). The serve stack observes
latency, throughput, and SLO burn fleet-wide, but has been blind to
whether the *flows* are degrading in production — a quantized tier's
drift, a stale warm-start prior, a corrupted replica's weights. This
module closes that axis with three per-request proxies, computed on a
sampled fraction of served requests, OFF the hot path:

  photo    mean generalized-Charbonnier photometric error of
           warp(frame2, flow) vs frame1 over the border-mask interior —
           the paper's objective as a serving metric (lower = better
           reconstruction = better flow, modulo occlusion).
  census   mean soft census-transform distance (ops/census.py) between
           the warped frame and frame1 — the illumination-robust twin of
           `photo`: a brightness change moves `photo` but not `census`,
           so the PAIR distinguishes "flows degraded" from "the video
           got darker".
  smooth   mean first-difference magnitude of the flow field
           (ops/smoothness.py semantics) — a collapsing or exploding
           flow head moves this even when photometric error looks fine
           (e.g. zero flow on a static scene).

Architecture (the hot-path contract):

  - **Sampling is deterministic.** `QualitySampler` decides per
    ACCEPTED-request index via a seeded hash, so the sampled set is a
    pure function of (seed, rate, submission order) — identical at any
    worker count, reproducible across replicas given the same stream.
  - **Scoring never blocks a response.** Sampled rows are copied onto a
    BOUNDED queue consumed by one scorer thread; a full queue DROPS the
    sample and counts it (`serve_quality_dropped`) — a wedged scorer
    costs samples, never latency.
  - **One jitted scorer executable per bucket**, lowered from the same
    `make_score_fn` + `quality_avals` pair `warmup --serve` pre-lowers,
    so sampling never compiles on a live endpoint. Engines running a
    custom/fake executor (jax-free fleet replicas) score through the
    numpy reference implementation instead — same math, no jax import.
  - **Fixed-bound histograms** (obs/export.py QUALITY_BUCKETS) make the
    per-replica quality distributions merge EXACTLY at the router, like
    the latency histograms. Per-(tier, mode) sum/count maps make int8-
    vs-f32 and warm-vs-cold quality drift visible in production, not
    just in bench.
  - **Drift verdict with a budget.** The first `quality_ref_samples`
    scored requests freeze a reference median; after that, every sample
    whose `photo` exceeds `ref_p50 * quality_drift_factor` burns the
    `obs.quality_budget` (breach fraction / budget, the SLO pattern).
    Exhaustion is the bit `deepof_tpu tail` turns into exit code 7.

Import discipline: stdlib + numpy at module level (this module is
imported by the serve engine, never by analyze/tail); jax enters only
inside `make_score_fn` / the engine's lowering path.
"""

from __future__ import annotations

import queue
import threading
import zlib
from collections import deque
from typing import Callable

import numpy as np

from .export import QUALITY_BUCKETS, ValueHistogram, percentile_ms

#: Charbonnier parameters of the photometric proxy — the reference
#: loss's (epsilon, alpha_c) pair (core/config.py LossConfig defaults),
#: fixed here so the proxy is comparable across configs and replicas.
PHOTO_EPS = 1e-4
PHOTO_ALPHA = 0.25
#: Border-mask ratio excluded from the photometric/census means (warp
#: border clamping pollutes the edge band — losses/photometric.py).
BORDER_RATIO = 0.1
#: Census window of the quality proxy (ops/census.py default).
CENSUS_WINDOW = 7

#: TF grayscale weights on BGR channels — ops/smoothness._GRAY_WEIGHTS,
#: repeated here so the numpy path needs no jax-importing module.
_GRAY = np.array([0.2989, 0.587, 0.114], np.float32)


# ------------------------------------------------------------- sampling


class QualitySampler:
    """Deterministic seeded Bernoulli sampler over request indices.

    `sample(i)` is a pure function of (seed, i): a crc32 hash mapped to
    [0, 1) compared against the rate — the same contract the fault
    injector uses for its probability schedules, so the sampled SET is
    identical for any pipeline worker count or scorer backlog, and two
    replicas given the same request stream sample the same requests."""

    def __init__(self, rate: float, seed: int = 0):
        self.rate = min(max(float(rate), 0.0), 1.0)
        self.seed = int(seed)

    def sample(self, index: int) -> bool:
        if self.rate <= 0.0:
            return False
        if self.rate >= 1.0:
            return True
        h = zlib.crc32(f"q:{self.seed}:{int(index)}".encode())
        return h / 2**32 < self.rate


# ------------------------------------------- the proxy (numpy reference)


def _border_interior(h: int, w: int, extra: int = 0) -> tuple[slice, slice]:
    """Interior slice pair of the border mask (losses border_mask
    semantics: width = ceil(BORDER_RATIO * h), widened by `extra`)."""
    bw = int(np.ceil(h * BORDER_RATIO)) + max(int(extra), 0)
    bw = min(bw, max((min(h, w) - 1) // 2, 0))
    return slice(bw, h - bw or None), slice(bw, w - bw or None)


def _resize_np(img: np.ndarray, hw: tuple[int, int]) -> np.ndarray:
    """Half-pixel-centered bilinear resize (cv2, matching
    jax.image.resize 'bilinear' within float tolerance)."""
    if img.shape[:2] == tuple(hw):
        return img
    import cv2

    return cv2.resize(img, (hw[1], hw[0]), interpolation=cv2.INTER_LINEAR)


def warp_bilinear_np(image: np.ndarray, flow: np.ndarray) -> np.ndarray:
    """Backward warp (H, W, C) by (H, W, 2), clip-at-border bilinear —
    the numpy twin of ops/warp.backward_warp (same u/v convention, same
    independent neighbor clipping)."""
    h, w = image.shape[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    fx = xs + flow[..., 0]
    fy = ys + flow[..., 1]
    x0 = np.floor(fx).astype(np.int64)
    y0 = np.floor(fy).astype(np.int64)
    wx = (fx - x0)[..., None]
    wy = (fy - y0)[..., None]
    x0c = np.clip(x0, 0, w - 1)
    x1c = np.clip(x0 + 1, 0, w - 1)
    y0c = np.clip(y0, 0, h - 1)
    y1c = np.clip(y0 + 1, 0, h - 1)
    ia = image[y0c, x0c]
    ib = image[y1c, x0c]
    ic = image[y0c, x1c]
    id_ = image[y1c, x1c]
    return (ia * (1 - wx) * (1 - wy) + ib * (1 - wx) * wy
            + ic * wx * (1 - wy) + id_ * wx * wy)


def census_descriptors_np(gray255: np.ndarray, window: int = CENSUS_WINDOW,
                          eps: float = 0.81) -> np.ndarray:
    """(H, W, 1) grayscale intensities -> (H, W, window**2) soft census
    descriptors — the numpy twin of ops/census.census_transform (edge
    padding, normalized differences)."""
    h, w = gray255.shape[:2]
    r = window // 2
    padded = np.pad(gray255, ((r, r), (r, r), (0, 0)), mode="edge")
    shifted = [padded[dy:dy + h, dx:dx + w, :]
               for dy in range(window) for dx in range(window)]
    d = np.concatenate(shifted, axis=-1) - gray255
    return d / np.sqrt(eps + np.square(d))


def census_distance_np(a: np.ndarray, b: np.ndarray,
                       thresh: float = 0.1) -> np.ndarray:
    d2 = np.square(a - b)
    return np.sum(d2 / (thresh + d2), axis=-1, keepdims=True)


def score_pair_np(x: np.ndarray, flow: np.ndarray,
                  census_window: int = CENSUS_WINDOW) -> tuple[float, float, float]:
    """The (photo, smooth, census) proxy triple for ONE served request —
    numpy reference implementation (and the scorer used by jax-free
    custom/fake-executor engines).

    x: (H, W, 6) the preprocessed network-input row ((img - mean)/255
       BGR, serve/buckets.prepare_pair) — frame1 in channels 0:3,
       frame2 in 3:6.
    flow: (fh, fw, 2) the raw dispatch output — the finest scaled flow
       at the head grid; displacement units are head-grid pixels (the
       loss's convention at that level), so frames are resized DOWN to
       the flow grid before warping, exactly as loss_interp resizes.
    """
    fh, fw = flow.shape[:2]
    f1 = _resize_np(np.ascontiguousarray(x[..., :3], np.float32), (fh, fw))
    f2 = _resize_np(np.ascontiguousarray(x[..., 3:6], np.float32), (fh, fw))
    recon = warp_bilinear_np(f2, flow.astype(np.float32))
    ys, xs = _border_interior(fh, fw)
    diff = 255.0 * (recon - f1)
    photo = float(np.mean(
        np.power(np.square(diff[ys, xs]) + PHOTO_EPS ** 2, PHOTO_ALPHA)))
    # smoothness: mean first-difference magnitude of the flow field
    # (forward_diff semantics; last row/col invalid, excluded)
    du = flow[:, :-1, :] - flow[:, 1:, :]
    dv = flow[:-1, :, :] - flow[1:, :, :]
    smooth = float((np.mean(np.abs(du)) + np.mean(np.abs(dv))) / 2.0)
    g1 = np.tensordot(f1 * 255.0, _GRAY, axes=[[-1], [0]])[..., None]
    gr = np.tensordot(recon * 255.0, _GRAY, axes=[[-1], [0]])[..., None]
    cys, cxs = _border_interior(fh, fw, extra=census_window // 2)
    dist = census_distance_np(
        census_descriptors_np(gr, census_window),
        census_descriptors_np(g1, census_window))
    census = float(np.mean(dist[cys, cxs]))
    return photo, smooth, census


# --------------------------------------------------- the proxy (jitted)


def make_score_fn(census_window: int = CENSUS_WINDOW) -> Callable:
    """(x[B,H,W,6], flow[B,fh,fw,2]) -> [3] float32 (photo, smooth,
    census means over the batch) — the jitted scorer the engine lowers
    once per bucket and `warmup --serve` pre-lowers identically (shared
    definition = shared persistent-cache key). Same math as
    score_pair_np, over the repo's jnp ops (ops/warp, ops/census)."""
    import jax
    import jax.numpy as jnp

    from ..ops.census import census_distance, census_transform
    from ..ops.warp import backward_warp

    def score(x, flow):
        b, fh, fw = flow.shape[0], flow.shape[1], flow.shape[2]
        # antialias=False = plain half-pixel bilinear, the same samples
        # cv2.INTER_LINEAR takes — the numpy reference path and this one
        # agree to float precision at any grid (parity-pinned in tests)
        f1 = jax.image.resize(x[..., :3], (b, fh, fw, 3), "bilinear",
                              antialias=False)
        f2 = jax.image.resize(x[..., 3:6], (b, fh, fw, 3), "bilinear",
                              antialias=False)
        recon = backward_warp(f2, flow, impl="xla")
        ys, xs = _border_interior(fh, fw)
        diff = 255.0 * (recon - f1)
        photo = jnp.mean(jnp.power(
            jnp.square(diff[:, ys, xs, :]) + PHOTO_EPS ** 2, PHOTO_ALPHA))
        du = flow[:, :, :-1, :] - flow[:, :, 1:, :]
        dv = flow[:, :-1, :, :] - flow[:, 1:, :, :]
        smooth = (jnp.mean(jnp.abs(du)) + jnp.mean(jnp.abs(dv))) / 2.0
        dist = census_distance(census_transform(recon, census_window),
                               census_transform(f1, census_window))
        cys, cxs = _border_interior(fh, fw, extra=census_window // 2)
        census = jnp.mean(dist[:, cys, cxs, :])
        return jnp.stack([photo, smooth, census]).astype(jnp.float32)

    return score


def quality_avals(bucket: tuple[int, int], flow_hw: tuple[int, int]):
    """(x_sds, flow_sds) for one bucket's scorer executable — shared by
    the engine's lowering and `warmup --serve` so their persistent-cache
    keys match. Batch 1: scoring is per sampled request, off-path."""
    import jax

    x_sds = jax.ShapeDtypeStruct((1, bucket[0], bucket[1], 6), np.float32)
    flow_sds = jax.ShapeDtypeStruct((1, flow_hw[0], flow_hw[1], 2),
                                    np.float32)
    return x_sds, flow_sds


# -------------------------------------------------------------- scorer


class QualityScorer:
    """Sampled off-hot-path quality scoring for one engine (see module
    docstring).

    score_fn: (bucket, x[1,H,W,6], flow[1,fh,fw,2]) -> (photo, smooth,
        census) floats. The engine provides either the jitted per-bucket
        executable path or the numpy reference (custom/fake executors).
    All configuration comes from ObsConfig's quality_* knobs.
    """

    def __init__(self, score_fn: Callable, sample_rate: float,
                 seed: int = 0, queue_depth: int = 128,
                 ref_samples: int = 64, window: int = 256,
                 drift_factor: float = 2.0, budget: float = 0.1):
        self.sampler = QualitySampler(sample_rate, seed)
        self._score_fn = score_fn
        self._ref_samples = max(int(ref_samples), 1)
        self._drift_factor = max(float(drift_factor), 1.0)
        self._budget = max(float(budget), 1e-9)
        self._q: queue.Queue = queue.Queue(maxsize=max(int(queue_depth), 1))
        self._lock = threading.Lock()
        self._sampled = 0   # accepted onto the queue
        self._dropped = 0   # queue full: sample lost, response unaffected
        self._scored = 0    # scorer completed
        self._errors = 0    # scorer raised (counted, thread survives)
        self._breaches = 0  # post-reference photo > ref_p50 * factor
        self._post_ref = 0  # scored samples after the reference froze
        self._ref: list[float] = []     # photo values building the reference
        self._ref_p50: float | None = None
        self._window: deque = deque(maxlen=max(int(window), 8))
        self._hists = {"photo": ValueHistogram(QUALITY_BUCKETS),
                       "smooth": ValueHistogram(QUALITY_BUCKETS),
                       "census": ValueHistogram(QUALITY_BUCKETS)}
        # per-(tier/mode) sum/count maps: the axis that makes int8 and
        # warm-start drift visible per operating point (maps merge
        # key-wise at the router, so the fleet view stays exact; a mean
        # per key re-derives as sum / scored at any aggregation level)
        self._scored_by_key: dict[str, int] = {}
        self._photo_sum_by_key: dict[str, float] = {}
        self._smooth_sum_by_key: dict[str, float] = {}
        self._census_sum_by_key: dict[str, float] = {}
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-quality-scorer")
        self._thread.start()

    # ------------------------------------------------------------ intake
    def should_sample(self, index: int) -> bool:
        return self.sampler.sample(index)

    def submit(self, x_row: np.ndarray, flow_row: np.ndarray,
               bucket: tuple[int, int], tier: str, mode: str) -> bool:
        """Hand one sampled request's (input row, raw flow output) to
        the scorer thread. NEVER blocks: a full queue drops the sample
        and counts it. Rows are copied by the caller (they must not
        alias the batcher's reusable buffers)."""
        try:
            self._q.put_nowait((x_row, flow_row, tuple(bucket), str(tier),
                                str(mode)))
        except queue.Full:
            with self._lock:
                self._dropped += 1
            return False
        with self._lock:
            self._sampled += 1
        return True

    # ------------------------------------------------------------ scorer
    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                break
            x_row, flow_row, bucket, tier, mode = item
            try:
                photo, smooth, census = self._score_fn(
                    bucket, x_row[None], flow_row[None])
            except Exception:  # noqa: BLE001 - scoring must not die; counted
                with self._lock:
                    self._errors += 1
                continue
            self._observe(float(photo), float(smooth), float(census),
                          f"{tier}/{mode}")

    def _observe(self, photo: float, smooth: float, census: float,
                 key: str) -> None:
        self._hists["photo"].observe(photo)
        self._hists["smooth"].observe(smooth)
        self._hists["census"].observe(census)
        with self._lock:
            self._scored += 1
            self._scored_by_key[key] = self._scored_by_key.get(key, 0) + 1
            for sums, v in ((self._photo_sum_by_key, photo),
                            (self._smooth_sum_by_key, smooth),
                            (self._census_sum_by_key, census)):
                sums[key] = round(sums.get(key, 0.0) + v, 6)
            if self._ref_p50 is None:
                self._ref.append(photo)
                if len(self._ref) >= self._ref_samples:
                    self._ref_p50 = float(np.median(self._ref))
                return
            self._post_ref += 1
            self._window.append(photo)
            if photo > self._ref_p50 * self._drift_factor:
                self._breaches += 1

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        """The serve_quality_* block (engine.stats() merges it; only
        present when sampling is configured on, so sample_rate=0 keeps
        the serve schema byte-identical)."""
        with self._lock:
            scored = self._scored
            post = self._post_ref
            breaches = self._breaches
            ref_p50 = self._ref_p50
            cur = list(self._window)
            out = {
                "serve_quality_sample_rate": self.sampler.rate,
                "serve_quality_sampled": self._sampled,
                "serve_quality_dropped": self._dropped,
                "serve_quality_scored": scored,
                "serve_quality_errors": self._errors,
                "serve_quality_breaches": breaches,
                "serve_quality_scored_by_key": dict(self._scored_by_key),
                "serve_quality_photo_sum_by_key":
                    dict(self._photo_sum_by_key),
                "serve_quality_smooth_sum_by_key":
                    dict(self._smooth_sum_by_key),
                "serve_quality_census_sum_by_key":
                    dict(self._census_sum_by_key),
            }
        hists = {k: h.snapshot() for k, h in self._hists.items()}
        out["serve_quality_photo_hist"] = hists["photo"]
        out["serve_quality_smooth_hist"] = hists["smooth"]
        out["serve_quality_census_hist"] = hists["census"]
        out["serve_quality_photo_p50"] = percentile_ms(hists["photo"], 0.50)
        out["serve_quality_smooth_p50"] = percentile_ms(hists["smooth"], 0.50)
        out["serve_quality_census_p50"] = percentile_ms(hists["census"], 0.50)
        # the drift verdict (derived — per-replica; the fleet re-derives
        # from the merged breach/scored counters if it wants one number)
        bad_fraction = (breaches / post) if post else 0.0
        cur_p50 = float(np.median(cur)) if cur else None
        out["serve_quality"] = {
            "sample_rate": self.sampler.rate,
            "scored": scored,
            "ref_samples": min(scored, self._ref_samples),
            "ref_p50": round(ref_p50, 6) if ref_p50 is not None else None,
            "current_p50": (round(cur_p50, 6) if cur_p50 is not None
                            else None),
            "drift_ratio": (round(cur_p50 / ref_p50, 4)
                            if cur_p50 is not None and ref_p50 else None),
            "drift_factor": self._drift_factor,
            "breaches": breaches,
            "bad_fraction": round(bad_fraction, 6),
            "budget": round(self._budget, 6),
            "burn": round(bad_fraction / self._budget, 4),
            "exhausted": bool(post and bad_fraction >= self._budget),
        }
        return out

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait (bounded) until every accepted sample has been scored —
        test/bench quiescence, never called on the serve path."""
        import time

        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        while time.monotonic() < deadline:
            with self._lock:
                if self._scored + self._errors >= self._sampled:
                    return True
            time.sleep(0.01)
        return False

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            # behind any queued samples: the scorer drains, then exits.
            # A WEDGED scorer's full queue must not block close (the
            # drop-not-block contract applies to shutdown too): the
            # thread is a daemon, skipping the sentinel just abandons it.
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=10.0)
