"""The observability schema: ONE declaration per counter key.

Every stats surface in this repo (the engine's ``serve_*`` block, the
router/supervisor ``fleet_*`` block, the coordinator's ``elastic_*``
block, the train loop's ``data_*``/``fault_*``/resilience merge) used to
be an ad-hoc flat dict whose MERGE behavior lived in per-consumer lists:
`analyze.aggregate_processes` enumerated which serve counters sum,
`Router.scrape_replicas` carried skip/max frozensets plus suffix
heuristics, `analyze._RESILIENCE_KEYS` enumerated the recovery counters.
PRs 4, 6, 7, 9, 10 and 11 each hand-patched one of those lists after a
new counter silently missed it — the exact mechanical defect class this
module retires.

This registry is the single schema owner:

  - every key declares its **merge kind** (how N processes' values
    combine into one fleet-wide value) and its **owner** (which
    subsystem writes it);
  - the merge paths (`analyze.aggregate_processes`,
    `Router.scrape_replicas` -> Prometheus `/metrics`, the
    resilience-counter surface) are DRIVEN from it — a registered
    counter joins every aggregate automatically;
  - `deepof_tpu lint`'s ``counter-registry`` rule cross-checks that
    every ``serve_*``/``fleet_*``/``elastic_*``/``data_*``/``fault_*``
    key written into a stats dict anywhere in the package is declared
    here — an unregistered counter is a CI failure, not a silent gap
    in the fleet scrape.

Merge kinds:

  sum      additive event counter — fleet value = sum of processes'
  max      high-water mark — fleet value = max of processes'
  gauge    per-process configuration or instantaneous reading (replica
           count, queue depth ceiling, generation) — never merged; a
           2-replica fleet does not have max_batch 16
  bool     flag — never merged (summing booleans exports nonsense)
  hist     fixed-bucket LatencyHistogram snapshot (obs/export.py) —
           merged EXACTLY bucket-wise via merge_hists, per key
  map      dict of numeric sub-counters (per-tier, per-replica) —
           merged key-wise by sum
  state    dict of string states (replica/host state machines) — never
           merged (states are per-process identity)
  derived  computed from other keys (percentiles, rates, means, SLO
           blocks) — never merged; the honest fleet figure is
           re-derived from the merged histogram/counters

Stdlib-only at import (the obs/__init__ discipline): analyze/tail, the
jax-free supervisors, and the linter all import this module.
"""

from __future__ import annotations

from dataclasses import dataclass

#: The key prefixes the `counter-registry` lint rule polices: a literal
#: dict key with one of these prefixes written anywhere in the package
#: must resolve in this registry.
LINTED_PREFIXES: tuple[str, ...] = (
    "serve_", "fleet_", "elastic_", "data_", "fault_", "exec_",
    "incident_", "alert_", "degrade_", "deadline_", "recipe_")

MERGE_KINDS: frozenset[str] = frozenset((
    "sum", "max", "gauge", "bool", "hist", "map", "state", "derived"))


@dataclass(frozen=True)
class Key:
    """One observability key's schema entry.

    name: the full key as written into stats dicts ("serve_requests");
        for a prefix family, the shared prefix ("fault_").
    kind: merge kind (see module docstring).
    owner: the subsystem that writes it — engine | session | quality |
        server | router | fleet | elastic | data | resilience | ckpt |
        faults | train | degrade.
    prefix: True = family entry: every key starting with `name`
        resolves here (dynamically named counters — per-site fault
        counts). Exact entries always win over families.
    resilience: True = part of the resilience-counter surface analyze/
        tail show as the `resilience` block (nonzero values only).
    """

    name: str
    kind: str
    owner: str
    prefix: bool = False
    resilience: bool = False


def _keys(owner: str, kind: str, *names: str, **kw) -> list[Key]:
    return [Key(n, kind, owner, **kw) for n in names]


_ENTRIES: list[Key] = [
    # ------------------------------------------ serve_* (engine core)
    *_keys("engine", "sum",
           "serve_requests", "serve_responses", "serve_errors",
           "serve_server_errors", "serve_batches",
           "serve_dispatch_failures", "serve_bucket_splits",
           "serve_tier_splits", "serve_warm_splits",
           "serve_timeout_flushes",
           # instantaneous per-replica depth, but the sum IS the honest
           # fleet figure: total requests queued across the pool
           "serve_queue_depth"),
    Key("serve_max_queue_depth", "max", "engine"),
    *_keys("engine", "gauge",
           "serve_max_batch", "serve_buckets", "serve_tiers",
           "serve_last_occupancy"),
    *_keys("engine", "map",
           "serve_requests_by_tier", "serve_responses_by_tier"),
    *_keys("engine", "derived",
           "serve_occupancy_mean", "serve_latency_p50_ms",
           "serve_latency_p99_ms", "serve_requests_per_s", "serve_slo"),
    Key("serve_latency_hist", "hist", "engine"),
    # ------------------------------- serve_sessions_* (session store)
    *_keys("session", "sum",
           "serve_sessions_active", "serve_sessions_created",
           "serve_sessions_resumed", "serve_sessions_expired",
           "serve_sessions_evicted", "serve_sessions_deleted",
           "serve_sessions_rebucketed", "serve_sessions_frames",
           "serve_sessions_steps", "serve_sessions_decode_saved",
           "serve_sessions_warm_steps", "serve_sessions_cold_fallbacks"),
    Key("serve_sessions_warm_start", "bool", "session"),
    Key("serve_session_latency_hist", "hist", "session"),
    *_keys("session", "derived",
           "serve_session_latency_p50_ms", "serve_session_latency_p99_ms"),
    # --------------------- serve_quality_* (label-free flow quality,
    # obs/quality.py: sampled photometric/census/smoothness proxies)
    *_keys("quality", "sum",
           "serve_quality_sampled", "serve_quality_dropped",
           "serve_quality_scored", "serve_quality_errors",
           "serve_quality_breaches"),
    Key("serve_quality_sample_rate", "gauge", "quality"),
    *_keys("quality", "map",
           "serve_quality_scored_by_key", "serve_quality_photo_sum_by_key",
           "serve_quality_smooth_sum_by_key",
           "serve_quality_census_sum_by_key"),
    *_keys("quality", "hist",
           "serve_quality_photo_hist", "serve_quality_smooth_hist",
           "serve_quality_census_hist"),
    *_keys("quality", "derived",
           "serve_quality", "serve_quality_photo_p50",
           "serve_quality_smooth_p50", "serve_quality_census_p50"),
    # ------------------------------ serve_* written by the fleet scrape
    *_keys("router", "sum",
           "serve_replicas_scraped", "serve_replicas_scrape_failed"),
    # --------------------------------------- fleet_* (router half)
    *_keys("router", "sum",
           "fleet_requests", "fleet_responses", "fleet_errors",
           "fleet_server_errors", "fleet_failovers", "fleet_retries",
           "fleet_shed", "fleet_unavailable",
           "fleet_session_primes", "fleet_session_steps",
           "fleet_session_lost", "fleet_session_evicted",
           "fleet_session_expired"),
    *_keys("router", "gauge", "fleet_in_flight", "fleet_sessions_sticky"),
    # routed counts folded out of the per-index map when a slot retires
    # (autoscale scale-down): keeps fleet_routed bounded by the active
    # pool while the total stays monotonic
    Key("fleet_routed_retired", "sum", "router"),
    Key("fleet_routed", "map", "router"),
    Key("fleet_draining", "bool", "router"),
    Key("fleet_latency_hist", "hist", "router"),
    Key("fleet_slo", "derived", "router"),
    # load trend from the router's per-second completion buckets
    # (ISSUE 16 predictive autoscaling): recent requests/s and its
    # least-squares slope (req/s per second) — instantaneous, per-router
    *_keys("router", "gauge", "fleet_load_rps", "fleet_load_slope"),
    # ----------------------------------- fleet_* (supervisor half)
    *_keys("fleet", "gauge", "fleet_replicas", "fleet_ready"),
    Key("fleet_states", "state", "fleet"),
    *_keys("fleet", "sum",
           "fleet_evictions", "fleet_crashes", "fleet_clean_exits",
           "fleet_wedge_evictions", "fleet_stale_evictions",
           "fleet_spawn_failures", "fleet_respawns", "fleet_broken",
           "fleet_kill_escalations",
           # graceful scale-down departures (autoscaler): deliberately
           # NOT an eviction — `tail`'s rc-4 contract stays about
           # sickness, retirement is the pool doing its job
           "fleet_retired"),
    # ---------------------- fleet_autoscale_* (serve/autoscale.py):
    # the SLO-driven load-follower's own block — scale events, streak
    # ticks, and the pool bounds it scales between
    Key("fleet_autoscale_enabled", "bool", "fleet"),
    *_keys("fleet", "gauge",
           "fleet_autoscale_min", "fleet_autoscale_max",
           "fleet_autoscale_last_event_s"),
    *_keys("fleet", "sum",
           "fleet_autoscale_up", "fleet_autoscale_down",
           "fleet_autoscale_blocked_max",
           "fleet_autoscale_pressure_ticks", "fleet_autoscale_idle_ticks",
           # ticks where the PREDICTIVE load-slope signal (ISSUE 16,
           # fleet.autoscale_up_slope) was the pressure source before
           # any shed/breach landed — how often the pool scaled ahead
           # of the load instead of behind it
           "fleet_autoscale_slope_ticks"),
    # -------------- deadline_* / degrade_* (the brownout plane, PR 19:
    # serve/degrade.py + the deadline gates in engine/server/router).
    # Names are DISJOINT by owner on purpose: the /metrics surface
    # dict-merges router.stats() with the replica scrape, so a name two
    # owners both wrote would silently clobber.
    # engine-owned (per-replica, summed by the fleet scrape): budgeted
    # arrivals, where expired budgets died, and requests actually served
    # on a downgraded operating point
    *_keys("engine", "sum",
           "deadline_requests", "deadline_enqueue_expired",
           "deadline_flush_expired", "deadline_wait_expired",
           "degrade_tier_downgrades", "degrade_bucket_downgrades"),
    # router-owned: admission/failover expiries + L3 low-priority sheds
    *_keys("router", "sum",
           "deadline_admission_expired", "degrade_shed_low"),
    # controller-owned (serve/degrade.py stats block)
    Key("degrade_enabled", "bool", "degrade"),
    *_keys("degrade", "gauge", "degrade_level", "degrade_l3_age_s"),
    Key("degrade_level_name", "state", "degrade"),
    *_keys("degrade", "sum",
           "degrade_transitions", "degrade_escalations",
           "degrade_recoveries", "degrade_l3_entries"),
    # sustained-L3 verdict: `tail`'s rc 10 (cli.py) reads this
    Key("degrade_l3_sustained", "bool", "degrade"),
    Key("degrade_last_reason", "state", "degrade"),
    # ------------------- exec_* (obs/ledger.py, the executable ledger:
    # compile/HLO/memory provenance per lowering — DESIGN.md
    # "Executable ledger"). Counters ride every stats surface that
    # carries the engine block (heartbeat, /metrics, the fleet scrape,
    # analyze/tail); the fingerprint map is per-process identity and the
    # MFU is re-derived, never merged.
    *_keys("ledger", "sum",
           "exec_lowerings", "exec_recompiles", "exec_compile_s",
           "exec_cache_hits", "exec_cache_misses", "exec_dispatches",
           "exec_dispatch_s",
           # artifact plane (serve/artifacts.py): executables
           # deserialized from the store instead of compiled (hits) vs
           # compiled because no entry matched the local fingerprint
           # (misses) vs compiled because an entry failed an integrity
           # gate (rejects — always loud)
           "exec_artifact_hits", "exec_artifact_misses",
           "exec_artifact_rejects",
           # executable index (trace-free boot): executables resolved
           # by jax-free key with zero trace/lower calls (hits) vs no
           # index entry for the key (misses — lowering path taken) vs
           # entry failed a trust gate: forged, cross-wired, stale
           # target, version skew (rejects — always loud)
           "exec_index_hits", "exec_index_misses", "exec_index_rejects",
           # deferred deep-verify plane: background re-lowerings that
           # confirmed an index-resolved executable (ok) vs loudly
           # demoted it — fingerprint mismatch, fresh compile swapped
           # in (demoted). Summing across a fleet stays honest: each
           # replica verifies its own boots exactly once.
           "exec_deep_verify_ok", "exec_deep_verify_demoted"),
    # index-resolved executables still awaiting their background
    # re-lowering; a fleet sum is the pool's total unverified count
    Key("exec_deep_verify_pending", "sum", "ledger"),
    Key("exec_executables", "gauge", "ledger"),
    Key("exec_fingerprints", "state", "ledger"),
    Key("exec_mfu_nominal", "derived", "ledger"),
    # ------------------------------------- elastic_* (coordinator)
    *_keys("elastic", "gauge",
           "elastic_hosts", "elastic_live", "elastic_done",
           "elastic_generation", "elastic_resumed_step",
           "elastic_target_step", "elastic_last_reform_s"),
    *_keys("elastic", "sum",
           "elastic_reforms", "elastic_lost_hosts", "elastic_preemptions",
           "elastic_steps_lost", "elastic_spawns", "elastic_respawns",
           "elastic_kill_escalations"),
    Key("elastic_max_step", "max", "elastic"),
    Key("elastic_states", "state", "elastic"),
    # --------------------- data_* (pipeline / prefetch / healer merge,
    # train/loop.py resilience_stats prefixes their stats() dicts)
    Key("data_num_workers", "gauge", "data"),
    *_keys("data", "sum",
           "data_batches", "data_assemble_s", "data_waits", "data_wait_s"),
    *_keys("data", "derived", "data_assemble_s_mean", "data_worker_util"),
    *_keys("data", "gauge", "data_queue_depth", "data_staged_depth"),
    *_keys("data", "max", "data_max_queue_depth", "data_max_staged_depth"),
    # decoded-image LRU counters (data/datasets.py _DecodedCache, merged
    # into train records under the same data_ prefix)
    Key("data_decode_cache_", "sum", "data", prefix=True),
    # ------------------------------ fault_* (resilience/faults.py):
    # per-site injection counts are dynamically named — one family
    Key("fault_", "sum", "faults", prefix=True, resilience=True),
    # ------------------- the resilience surface (train records +
    # heartbeat; analyze/tail's `resilience` block). Declaration order
    # here IS the block's key order (resilience_keys() preserves it —
    # pinned byte-identical to the pre-registry _RESILIENCE_KEYS tuple),
    # so new entries append at the end.
    *_keys("train", "sum", "skipped_updates", "rollbacks",
           resilience=True),
    *_keys("data", "sum",
           "data_sample_retries", "data_quarantined", "data_substituted",
           "data_retries", resilience=True),
    Key("pipeline_fetch_retries", "sum", "data", resilience=True),
    *_keys("ckpt", "sum",
           "ckpt_save_failures", "ckpt_restore_failures",
           "ckpt_restore_fallbacks", "ckpt_verify_failures",
           resilience=True),
    # non-resilience ckpt counter (rides the same ckpt_ stats prefix)
    Key("ckpt_saves", "sum", "ckpt"),
    # ------------------- recipe_* (train/recipe.py, the staged-recipe
    # engine): active stage (per-process identity, never merged),
    # stage-advance events, the per-member mixture draw counts the
    # deterministic sampler accumulates, and the cause of the newest
    # advance trigger ("steps" | "plateau"). Ride every train-side
    # stats surface (heartbeat, train records, fit summary) via the
    # Trainer's extra_stats hook.
    *_keys("recipe", "gauge", "recipe_stage", "recipe_stages"),
    Key("recipe_advances", "sum", "recipe"),
    Key("recipe_draws_by_dataset", "map", "recipe"),
    Key("recipe_last_trigger", "state", "recipe"),
    Key("recipe_stage_name", "state", "recipe"),
    # --------------- incident_*/alert_* (obs/incident.py, the flight
    # recorder): capture/dedup/rate-limit accounting plus the alert-
    # rule engine. Deliberately NOT resilience-surfaced — the legacy
    # resilience tuple's key order is byte-pinned output; bundles
    # surface through the dedicated `incidents` analyze/tail block.
    *_keys("incident", "sum",
           "incident_captured", "incident_collected",
           "incident_deduped", "incident_rate_limited",
           "incident_capture_errors"),
    Key("incident_by_kind", "map", "incident"),
    Key("incident_last_kind", "state", "incident"),
    Key("alert_rules", "gauge", "incident"),
    Key("alert_firings", "sum", "incident"),
    Key("alert_errors", "sum", "incident"),
]

#: name -> Key for exact entries (validated no-duplicate below).
REGISTRY: dict[str, Key] = {}
#: prefix families, longest prefix first (most specific wins).
FAMILIES: list[Key] = []

for _k in _ENTRIES:
    if _k.kind not in MERGE_KINDS:
        raise ValueError(f"registry: bad kind {_k.kind!r} for {_k.name!r}")
    if _k.prefix:
        FAMILIES.append(_k)
    else:
        if _k.name in REGISTRY:
            raise ValueError(f"registry: duplicate key {_k.name!r}")
        REGISTRY[_k.name] = _k
FAMILIES.sort(key=lambda k: -len(k.name))


def lookup(name: str) -> Key | None:
    """The schema entry for a stats key: exact match first, then the
    longest matching prefix family. None = unregistered."""
    hit = REGISTRY.get(name)
    if hit is not None:
        return hit
    for fam in FAMILIES:
        if name.startswith(fam.name):
            return fam
    return None


def merge_kind(name: str) -> str | None:
    """The key's merge kind, or None when unregistered."""
    hit = lookup(name)
    return hit.kind if hit is not None else None


def resilience_keys() -> tuple[str, ...]:
    """The exact-named resilience-surface counters — drives
    `analyze._RESILIENCE_KEYS` (prefix families like fault_* are
    surfaced by their prefix in analyze, not enumerated here)."""
    return tuple(k.name for k in _ENTRIES
                 if k.resilience and not k.prefix)


def keys_for_owner(owner: str) -> tuple[str, ...]:
    return tuple(k.name for k in _ENTRIES if k.owner == owner)


# ------------------------------------------------- generic dict merging


def merge_stats_blocks(blocks: list[dict], prefix: str = "") -> dict:
    """Registry-driven merge of N processes' flat stats dicts into one
    fleet-wide dict — THE aggregation primitive behind
    `Router.scrape_replicas` and `analyze.aggregate_processes`.

    prefix: keys in `blocks` may be stored stripped of their registry
    prefix (analyze's serve block drops "serve_"); lookups prepend it.

    Per key, by registry kind: sum adds, max takes the maximum, map
    merges key-wise by sum, hist merges exactly (foreign-bucket
    snapshots are skipped, never a crash), gauge/bool/state/derived are
    dropped (their fleet-wide value is meaningless or re-derived).
    UNREGISTERED keys fall back to the historical suffix heuristic —
    numeric values sum unless they look derived (_p50_ms/_p99_ms/
    _per_s/_mean) — so scraping a newer replica that exports a key this
    process's registry predates degrades to the old behavior instead of
    dropping data silently.
    """
    from .export import is_hist_snapshot, merge_hists

    sums: dict = {}
    maxima: dict = {}
    maps: dict[str, dict] = {}
    hists: dict[str, list] = {}
    for block in blocks:
        if not block:
            continue
        for k, v in block.items():
            kind = merge_kind(prefix + k)
            if kind is None:  # unregistered: the historical heuristic
                if is_hist_snapshot(v):
                    kind = "hist"
                elif isinstance(v, bool):
                    kind = "bool"
                elif isinstance(v, (int, float)):
                    kind = ("derived" if k.endswith(
                        ("_p50_ms", "_p99_ms", "_per_s", "_mean"))
                        else "sum")
                elif isinstance(v, dict):
                    kind = "map"
                else:
                    continue
            if kind == "sum" and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                sums[k] = sums.get(k, 0) + v
            elif kind == "max" and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                maxima[k] = max(maxima.get(k, 0), v)
            elif kind == "map" and isinstance(v, dict):
                tgt = maps.setdefault(k, {})
                for sub, n in v.items():
                    if isinstance(n, (int, float)) \
                            and not isinstance(n, bool):
                        tgt[sub] = tgt.get(sub, 0) + n
            elif kind == "hist" and is_hist_snapshot(v):
                hists.setdefault(k, []).append(v)
            # gauge / bool / state / derived: deliberately dropped
    out = {**sums, **maxima}
    # a map with no numeric sub-values merged (e.g. an unregistered
    # state-style dict from a newer replica) is dropped, not exported
    # as a meaningless empty {} — matching the retired implementation
    out.update({k: dict(v) for k, v in maps.items() if v})
    for k, hs in hists.items():
        try:
            out[k] = merge_hists(hs)
        except ValueError:
            pass  # foreign/old-format snapshot: skip, never crash
    return out
