"""Executable ledger + drift math: compile/HLO/memory provenance for
every lowering the framework performs (DESIGN.md "Executable ledger").

The serving and training planes can see latency, SLO burn, and
label-free flow quality, but nothing recorded what each compiled
executable *costs*: an HLO drift (a config edit that silently changed
the lowering), an unexpected recompile (a cache miss where yesterday's
run had a hit), a compile-time blowup, or a memory-footprint jump were
invisible until a bench run happened to catch them. This module makes
each lowering a ledger row — written to ``<log_dir>/ledger.jsonl`` next
to ``metrics.jsonl`` — and makes "did the executables change?" a diff
against a committed baseline ledger instead of a hope.

Per lowering, a row records:

  - a **stable StableHLO fingerprint**: sha256 over the normalized
    ``lowered.as_text()`` (location metadata stripped — the only
    nondeterministic part of the text; the module body, including the
    donation-encoding ``tf.aliasing_output`` attributes, is a pure
    function of (jax version, config, avals, backend)). Same config +
    same jax ⇒ same fingerprint across processes and hosts; any change
    to the computation changes it.
  - **compile wall seconds** and the persistent-cache provenance of the
    compile (requests/hits/misses from train/warmup.py's counters) —
    "this process compiled nothing" stays a checkable fact per
    executable, not per process.
  - **XLA cost analysis**: FLOPs and bytes accessed, their ratio
    (arithmetic intensity), and the nominal roofline seconds one call
    would take at obs/telemetry.py's ``NOMINAL_BF16_TFLOPS`` — the
    drift signal is the COST MODEL, not wall time, because cost
    analysis is deterministic in the lowering while wall time is host
    noise (DESIGN.md has the rationale).
  - **memory_analysis footprint**: argument/output/temp/alias bytes and
    generated code size of the compiled executable (None where the
    backend does not report).
  - the **donation map**: how many of the executable's input leaves are
    donated (buffer reuse) — a lost donation is a silent memory-
    footprint regression even when the HLO is otherwise unchanged.

On top of the rows sits the regression sentinel: :func:`diff_ledgers`
compares a live run's ledger against a committed baseline and yields a
verdict (`fingerprint_drift`, `unexpected_recompiles`,
`compile_blowups`, `memory_growth`) that ``tools/ledger_diff.py`` and
``deepof_tpu tail`` turn into exit code **8** — the same CI-shaped
contract as rc 3–7.

Import discipline: stdlib-only at module import (analyze/tail and the
jax-free diff tool import this); jax is touched only inside the
recording helpers, which always run next to an actual lowering.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Any, Callable

from .telemetry import NOMINAL_BF16_TFLOPS

#: Ledger schema version (rows carry it; diff refuses nothing on
#: mismatch but reports it — older baselines stay comparable on the
#: fields both sides have).
LEDGER_SCHEMA = 1

#: Every lowering row carries exactly these keys (None where a backend
#: does not report a value) — the schema the fixture test pins.
ROW_KEYS = (
    "kind", "schema", "name", "time", "backend", "fingerprint",
    "hlo_chars", "compile_s", "resolve_s", "compile_kind", "cache_requests",
    "cache_hits", "cache_misses", "cache_verdict", "flops",
    "bytes_accessed", "arith_intensity", "roofline_s", "argument_bytes",
    "output_bytes", "temp_bytes", "alias_bytes", "code_bytes",
    "donated_args", "num_args",
)

# MLIR location metadata is the one part of the printed module that is
# not a pure function of the computation (file paths, line numbers,
# enable-debug-info settings). jax 0.4.x prints without it by default,
# but the fingerprint must not silently change if a caller or a future
# jax turns it on — strip every `loc(...)` attribute (including
# `loc(unknown)` and nested `loc(callsite(...))`/`loc(fused<...>[...])`
# forms, which need balanced-paren scanning, not a regex) and `#loc...`
# definition lines before hashing.
_LOC_LINE = re.compile(r"^#loc\d*\s*=.*$", re.MULTILINE)


def _strip_loc_attrs(text: str) -> str:
    """Remove every `loc(...)` attribute — balanced parens, quote-aware
    (a quoted file name inside a location may itself contain parens),
    token-boundary checked (an identifier merely ending in "loc" is
    kept)."""
    out = []
    i, n = 0, len(text)
    while i < n:
        j = text.find("loc(", i)
        if j == -1:
            out.append(text[i:])
            break
        if j > 0 and (text[j - 1].isalnum() or text[j - 1] in "_$."):
            out.append(text[i:j + 4])
            i = j + 4
            continue
        # drop the attribute plus the whitespace that separated it
        out.append(text[i:j].rstrip(" \t"))
        k = j + 3  # at the opening paren
        depth = 0
        in_str = False
        while k < n:
            c = text[k]
            if in_str:
                if c == "\\":
                    k += 1
                elif c == '"':
                    in_str = False
            elif c == '"':
                in_str = True
            elif c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
                if depth == 0:
                    break
            k += 1
        i = k + 1
    return "".join(out)


def exec_name(bucket: tuple[int, int], tier: str, mode: str) -> str:
    """The canonical ledger name of a serve-lattice executable — shared
    by `warmup --serve` and the engine so a warmup baseline and a live
    run's rows diff by name: ``serve:<H>x<W>:<tier>:<mode>``."""
    return f"serve:{bucket[0]}x{bucket[1]}:{tier}:{mode}"


def quality_exec_name(bucket: tuple[int, int]) -> str:
    """Ledger name of a bucket's quality-scorer executable (tiers and
    modes share it): ``quality:<H>x<W>``."""
    return f"quality:{bucket[0]}x{bucket[1]}"


def normalize_hlo(text: str) -> str:
    """The fingerprint's input: the StableHLO module text with location
    metadata stripped and line endings normalized. Deliberately keeps
    the module/function names and every attribute that changes the
    compiled artifact (shapes, dtypes, donation aliasing, precision)."""
    text = _strip_loc_attrs(text)
    text = _LOC_LINE.sub("", text)
    return "\n".join(line.rstrip() for line in text.splitlines()).strip()


def fingerprint_text(text: str) -> str:
    """sha256 over the normalized module text, truncated to 16 hex chars
    (64 bits — collision-safe for the dozens of executables a run
    lowers, short enough to eyeball in a report)."""
    norm = normalize_hlo(text)
    return hashlib.sha256(norm.encode()).hexdigest()[:16]


def _cost_analysis(obj) -> dict | None:
    """Flatten `.cost_analysis()` from a Lowered or Compiled object —
    jax returns a dict, a one-element list of dicts, or raises on
    backends without a cost model."""
    try:
        ca = obj.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        return dict(ca) if ca else None
    except Exception:  # noqa: BLE001 - cost model is best-effort
        return None


def _donation(lowered) -> tuple[int | None, int | None]:
    """(donated leaves, total input leaves) from the lowering's
    args_info pytree — the executable's buffer-donation map."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(
            lowered.args_info, is_leaf=lambda a: hasattr(a, "donated"))
        flags = [bool(a.donated) for a in leaves if hasattr(a, "donated")]
        if not flags:
            return None, None
        return sum(flags), len(flags)
    except Exception:  # noqa: BLE001 - provenance is best-effort
        return None, None


def lowering_row(name: str, lowered=None, compiled=None,
                 compile_s: float | None = None,
                 resolve_s: float | None = None,
                 compile_kind: str | None = None,
                 cache: dict | None = None,
                 cache_verdict: str | None = None,
                 backend: str | None = None,
                 fingerprint: str | None = None) -> dict:
    """One ledger row for a lowering. `lowered` (jax.stages.Lowered)
    supplies the fingerprint, cost analysis, and donation map;
    `compiled` (jax.stages.Compiled) supplies memory_analysis — pass
    None where a site has no AOT-compiled object (the train loop's
    jit-dispatch compile) and the fields stay None rather than paying a
    second XLA compile just to fill them. `compile_kind` says what
    compile_s MEASURES — "aot" (pure lower+compile, record_aot),
    "first_step" (the train loop's first-step wall: compile + one
    executed step), "artifact" (fetch/deserialize from the artifact
    store, NO compile at all), or "deep_verify" (the background
    verifier's post-serve re-lowering) — so diff_ledgers never compares
    the units. `cache_verdict` names where the executable came from:
    explicit "artifact_hit" / "index_hit" from the artifact plane, else
    derived from the persistent-cache delta ("hit"/"miss"), else None.
    `fingerprint` sets the row's fingerprint when there is no local
    Lowered to hash (an index-resolved row carries the INDEX's claimed
    fingerprint — what the deep-verify plane later re-checks); it is
    ignored when `lowered` is passed."""
    row: dict[str, Any] = {k: None for k in ROW_KEYS}
    row.update({"kind": "exec", "schema": LEDGER_SCHEMA, "name": name,
                "time": round(time.time(), 3), "backend": backend,
                "fingerprint": fingerprint})
    if compile_s is not None:
        row["compile_s"] = round(float(compile_s), 4)
        row["compile_kind"] = compile_kind
    if resolve_s is not None:
        # the resolution step alone — XLA compile ("aot") or artifact
        # fetch+deserialize ("artifact") — with the shared trace/lower
        # wall excluded; compile_s keeps the historical lower+resolve
        # total so existing baselines stay comparable
        row["resolve_s"] = round(float(resolve_s), 4)
    if cache:
        for k in ("requests", "hits", "misses"):
            if isinstance(cache.get(k), int):
                row[f"cache_{k}"] = cache[k]
    if cache_verdict is not None:
        row["cache_verdict"] = cache_verdict
    elif (row.get("cache_hits") or 0) >= 1:
        row["cache_verdict"] = "hit"
    elif (row.get("cache_misses") or 0) >= 1:
        row["cache_verdict"] = "miss"
    ca = None
    if lowered is not None:
        try:
            text = lowered.as_text()
            row["fingerprint"] = fingerprint_text(text)
            row["hlo_chars"] = len(normalize_hlo(text))
        except Exception:  # noqa: BLE001 - provenance is best-effort
            pass
        ca = _cost_analysis(lowered)
        row["donated_args"], row["num_args"] = _donation(lowered)
    if ca is None and compiled is not None:
        ca = _cost_analysis(compiled)
    if ca:
        flops = float(ca.get("flops", 0.0))
        byt = float(ca.get("bytes accessed", 0.0))
        if flops > 0:
            row["flops"] = flops
            # the time a perfectly-utilized nominal chip would take per
            # call: measured wall / roofline_s = per-executable MFU
            row["roofline_s"] = flops / (NOMINAL_BF16_TFLOPS * 1e12)
        if byt > 0:
            row["bytes_accessed"] = byt
        if flops > 0 and byt > 0:
            row["arith_intensity"] = round(flops / byt, 3)
    if compiled is not None:
        try:
            ma = compiled.memory_analysis()
            for field, key in (("argument_size_in_bytes", "argument_bytes"),
                               ("output_size_in_bytes", "output_bytes"),
                               ("temp_size_in_bytes", "temp_bytes"),
                               ("alias_size_in_bytes", "alias_bytes"),
                               ("generated_code_size_in_bytes",
                                "code_bytes")):
                v = getattr(ma, field, None)
                if isinstance(v, int):
                    row[key] = v
        except Exception:  # noqa: BLE001 - cpu reports it, others may not
            pass
    return row


class ExecutableLedger:
    """Per-run executable ledger: appends one row per lowering to
    ``<log_dir>/ledger.jsonl`` and keeps the ``exec_*`` counter block
    every stats surface exports (heartbeat, /metrics, analyze/tail,
    the fleet scrape — obs/registry.py declares the merge kinds).

    Thread-safe; all hot-path work is `note_exec` (one dict update under
    a lock per already-timed dispatch — the serve bench bounds the whole
    ledger at ≤ 2% of serve p99). File I/O happens only at lowering
    time (compiles dominate it by orders of magnitude) and at flush().
    """

    def __init__(self, log_dir: str | None, enabled: bool = True,
                 backend: str | None = None):
        self.path = (os.path.join(log_dir, "ledger.jsonl")
                     if log_dir and enabled else None)
        self.backend = backend
        self._lock = threading.Lock()
        self._fingerprints: dict[str, str] = {}
        self._lowerings = 0
        self._recompiles = 0
        self._compile_s = 0.0
        self._cache_hits = 0
        self._cache_misses = 0
        # artifact-plane fetch accounting (serve/artifacts.py):
        # hits = executables deserialized instead of compiled,
        # misses = no entry for the local fingerprint (compiled),
        # rejects = entry present but failed an integrity gate (compiled)
        self._artifact_hits = 0
        self._artifact_misses = 0
        self._artifact_rejects = 0
        # executable-index accounting (trace-free resolution):
        # hits = executables resolved with zero trace/lower calls,
        # misses = no index entry for the key (lowering path taken),
        # rejects = entry present but failed a trust gate (forged,
        # cross-wired, stale target, version skew) — loud fallback
        self._index_hits = 0
        self._index_misses = 0
        self._index_rejects = 0
        # deferred deep-verify plane: pending = index-resolved entries
        # awaiting background re-lowering, ok = fingerprint confirmed,
        # demoted = mismatch -> executable swapped for a fresh compile
        self._deep_verify_pending = 0
        self._deep_verify_ok = 0
        self._deep_verify_demoted = 0
        # per-executable measured execution time: name -> [count, total_s,
        # roofline_s] — MFU = roofline / mean measured, re-derived at
        # stats() time, never merged (registry kind: derived)
        self._exec: dict[str, list] = {}

    @property
    def enabled(self) -> bool:
        return self.path is not None

    # ------------------------------------------------------------ record
    def _append(self, row: dict) -> None:
        if self.path is None:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def record(self, name: str, lowered=None, compiled=None,
               compile_s: float | None = None,
               resolve_s: float | None = None,
               compile_kind: str | None = None,
               cache: dict | None = None,
               cache_verdict: str | None = None,
               fingerprint: str | None = None) -> dict:
        """Build, count, and append one lowering row (see lowering_row).
        Returns the row so call sites can fold the fingerprint into
        their own reports (the warmup CLI report does)."""
        row = lowering_row(name, lowered=lowered, compiled=compiled,
                           compile_s=compile_s, resolve_s=resolve_s,
                           compile_kind=compile_kind,
                           cache=cache, cache_verdict=cache_verdict,
                           backend=self.backend, fingerprint=fingerprint)
        with self._lock:
            self._lowerings += 1
            if compile_s is not None:
                self._compile_s += float(compile_s)
            if isinstance(row.get("cache_hits"), int):
                self._cache_hits += row["cache_hits"]
            if isinstance(row.get("cache_misses"), int):
                self._cache_misses += row["cache_misses"]
            fp = row.get("fingerprint")
            if fp is not None:
                prev = self._fingerprints.get(name)
                if prev is not None and prev != fp:
                    # the live recompile signal: the SAME executable name
                    # lowered to a DIFFERENT module within one run
                    self._recompiles += 1
                self._fingerprints[name] = fp
            if row.get("roofline_s") is not None:
                self._exec.setdefault(name, [0, 0.0, 0.0])[2] = \
                    row["roofline_s"]
        if self.enabled:
            self._append(row)
        return row

    def record_aot(self, name: str, lower_fn: Callable[[], Any],
                   artifacts=None) -> Any:
        """The shared AOT helper: time lower_fn() -> Lowered, then
        resolve the executable — from the artifact store when one is
        passed (serve/artifacts.py ArtifactStore, keyed by THIS
        lowering's StableHLO fingerprint, so drifted code always
        misses) and only otherwise by compiling — measure the
        persistent-cache delta of exactly this resolution, and record
        the row: compile_kind "artifact" + cache_verdict "artifact_hit"
        on a fetch, the ordinary "aot" row on a compile (miss, reject,
        or no store). Returns (compiled, row)."""
        from ..train.warmup import cache_delta

        verdict = None
        with cache_delta() as d:
            t0 = time.perf_counter()
            lowered = lower_fn()
            t_res = time.perf_counter()
            compiled = None
            if artifacts is not None:
                try:
                    fp = fingerprint_text(lowered.as_text())
                    compiled, verdict = artifacts.fetch(fp)
                except Exception:  # noqa: BLE001 - store is best-effort
                    compiled, verdict = None, "reject:fetch_failed"
            if compiled is None:
                t_res = time.perf_counter()  # a reject's failed fetch
                #   is not compile wall: resolve_s stays the step that
                #   actually produced the executable
                compiled = lowered.compile()
            dt = time.perf_counter() - t0
            resolve_s = time.perf_counter() - t_res
        hit = verdict == "hit"
        if artifacts is not None:
            with self._lock:
                if hit:
                    self._artifact_hits += 1
                elif verdict == "miss":
                    self._artifact_misses += 1
                else:
                    self._artifact_rejects += 1
        row = self.record(name, lowered=lowered, compiled=compiled,
                          compile_s=dt, resolve_s=resolve_s,
                          compile_kind="artifact" if hit else "aot",
                          cache=d.stats(),
                          cache_verdict="artifact_hit" if hit else None)
        return compiled, row

    def record_index(self, name: str, artifacts, key: str) -> Any:
        """The trace-free resolution helper: resolve `key` through the
        store's executable index (serve/artifacts.py ``resolve`` —
        zero trace/lower calls on every path) and, on a hit, record the
        ``cache_verdict="index_hit"`` row: compile_kind "artifact"
        (resolve_s = compile_s = pure fetch+deserialize wall, and
        diff_ledgers already treats "artifact" rows as non-recompiles),
        fingerprint = the INDEX's claimed fingerprint (there is no
        local Lowered to hash — the deep-verify plane re-checks it
        after serving starts), cost/memory provenance read off the
        deserialized executable. A miss or reject records nothing and
        returns (None, None, verdict): the caller falls back to the
        lowering path, which writes its own row. Returns
        (compiled | None, row | None, verdict)."""
        t0 = time.perf_counter()
        try:
            compiled, fp, verdict = artifacts.resolve(key)
        except Exception:  # noqa: BLE001 - index is best-effort
            compiled, fp, verdict = None, None, "index_reject:resolve_failed"
        dt = time.perf_counter() - t0
        with self._lock:
            if verdict == "index_hit":
                self._index_hits += 1
                self._deep_verify_pending += 1
            elif verdict == "index_miss":
                self._index_misses += 1
            else:
                self._index_rejects += 1
        if compiled is None:
            return None, None, verdict
        row = self.record(name, lowered=None, compiled=compiled,
                          compile_s=dt, resolve_s=dt,
                          compile_kind="artifact",
                          cache_verdict="index_hit",
                          fingerprint=fp)
        return compiled, row, verdict

    def note_deep_verify(self, ok: bool) -> None:
        """One background deep-verify outcome: confirmed (ok) or
        demoted (the index's fingerprint does not match what local code
        lowers to — the executable was swapped for a fresh compile).
        Either way one pending slot drains."""
        with self._lock:
            self._deep_verify_pending = max(
                0, self._deep_verify_pending - 1)
            if ok:
                self._deep_verify_ok += 1
            else:
                self._deep_verify_demoted += 1

    def note_exec(self, name: str, seconds: float) -> None:
        """Accumulate one measured execution of `name` (the serve
        engine's flush timer feeds this; training MFU rides the
        per-record telemetry instead — DESIGN.md) — the denominator of
        the per-executable MFU the stats block derives."""
        with self._lock:
            e = self._exec.setdefault(name, [0, 0.0, 0.0])
            e[0] += 1
            e[1] += float(seconds)

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        """The registry-declared ``exec_*`` block (obs/registry.py owner
        `ledger`): lowering/compile/cache counters, the per-executable
        fingerprint map, and the nominal-roofline MFU over every
        executable with measured executions."""
        with self._lock:
            out: dict[str, Any] = {
                "exec_lowerings": self._lowerings,
                "exec_recompiles": self._recompiles,
                "exec_compile_s": round(self._compile_s, 3),
                "exec_cache_hits": self._cache_hits,
                "exec_cache_misses": self._cache_misses,
                "exec_artifact_hits": self._artifact_hits,
                "exec_artifact_misses": self._artifact_misses,
                "exec_artifact_rejects": self._artifact_rejects,
                "exec_index_hits": self._index_hits,
                "exec_index_misses": self._index_misses,
                "exec_index_rejects": self._index_rejects,
                "exec_deep_verify_pending": self._deep_verify_pending,
                "exec_deep_verify_ok": self._deep_verify_ok,
                "exec_deep_verify_demoted": self._deep_verify_demoted,
                "exec_executables": len(self._fingerprints),
                "exec_fingerprints": dict(self._fingerprints),
                "exec_dispatches": sum(e[0] for e in self._exec.values()),
                "exec_dispatch_s": round(
                    sum(e[1] for e in self._exec.values()), 4),
            }
            # per-executable MFU vs the nominal roofline: how much of
            # the chip's nominal peak the measured dispatches achieved;
            # the max across executables answers "is ANY path near
            # roofline", which survives idle executables at 0
            mfus = [e[2] * e[0] / e[1]
                    for e in self._exec.values()
                    if e[0] > 0 and e[1] > 0 and e[2] > 0]
        out["exec_mfu_nominal"] = (round(max(mfus), 6) if mfus else None)
        return out

    def flush(self) -> None:
        """Append one kind="exec_timing" row per executable with
        measured executions (run end / engine close): the measured
        mean next to the roofline, so offline analysis can re-derive
        MFU without the live process."""
        if not self.enabled:
            return
        with self._lock:
            items = [(n, list(e)) for n, e in self._exec.items()
                     if e[0] > 0]
        for name, (count, total_s, roofline_s) in items:
            mean_s = total_s / count
            self._append({
                "kind": "exec_timing", "schema": LEDGER_SCHEMA,
                "name": name, "time": round(time.time(), 3),
                "count": count, "total_s": round(total_s, 4),
                "mean_s": round(mean_s, 6),
                "mfu_nominal": (round(roofline_s / mean_s, 6)
                                if roofline_s > 0 and mean_s > 0
                                else None)})


# ------------------------------------------------- reading and diffing
# (stdlib-only: analyze/tail and tools/ledger_diff.py run jax-free)


def resolve_ledger_path(path: str) -> str:
    """A ledger argument may be the ledger.jsonl itself or a run dir
    holding one — ONE resolution rule, shared by load_ledger and the
    CLI pre-checks (tail's --ledger-baseline existence check), so the
    gates can never diverge on what counts as a valid ledger path."""
    if os.path.isdir(path):
        return os.path.join(path, "ledger.jsonl")
    return path


def load_ledger(path: str) -> list[dict]:
    """Rows from a ledger.jsonl (or a run dir containing one). Torn
    trailing writes from a killed run are tolerated like metrics.jsonl."""
    path = resolve_ledger_path(path)
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return rows


def latest_by_name(rows: list[dict]) -> dict[str, dict]:
    """{executable name -> newest lowering row}. The newest row wins:
    a re-lowering within a run supersedes the first (the recompile
    itself is visible via exec_recompiles and the diff)."""
    out: dict[str, dict] = {}
    for r in rows:
        if r.get("kind") == "exec" and isinstance(r.get("name"), str):
            out[r["name"]] = r
    return out


def summarize_ledger(rows: list[dict]) -> dict | None:
    """The condensed `exec` block analyze/tail print for a run dir with
    a ledger: totals plus the slowest compiles (the entries worth
    staring at when a cold start got slower). compile_s_total is the
    raw wall the dir's recorded compiles paid, split per compile_kind
    (a dir that held both a `warmup` baseline and a live run mixes
    "aot" and "first_step" units — the split keeps them readable apart,
    exactly as diff_ledgers refuses to compare them); `slowest` is
    newest-row-per-name so a re-lowered executable appears once, with
    its kind."""
    execs = [r for r in rows if r.get("kind") == "exec"]
    if not execs:
        return None
    by_name = latest_by_name(rows)
    recompiles = 0
    seen: dict[str, str] = {}
    for r in execs:
        fp = r.get("fingerprint")
        name = r.get("name")
        if fp and name:
            if name in seen and seen[name] != fp:
                recompiles += 1
            seen[name] = fp
    timings = {r["name"]: r for r in rows
               if r.get("kind") == "exec_timing"
               and isinstance(r.get("name"), str)}
    compile_s = [r["compile_s"] for r in execs
                 if isinstance(r.get("compile_s"), (int, float))]
    by_kind: dict[str, float] = {}
    for r in execs:
        if isinstance(r.get("compile_s"), (int, float)):
            k = r.get("compile_kind") or "unknown"
            by_kind[k] = by_kind.get(k, 0.0) + r["compile_s"]
    out: dict[str, Any] = {
        "lowerings": len(execs),
        "executables": len(by_name),
        "recompiles": recompiles,
        "compile_s_total": round(sum(compile_s), 3) if compile_s else None,
        "compile_s_by_kind": ({k: round(v, 3)
                               for k, v in sorted(by_kind.items())}
                              if by_kind else None),
        "cache_hits": sum(r.get("cache_hits") or 0 for r in execs),
        "cache_misses": sum(r.get("cache_misses") or 0 for r in execs),
        "slowest": [
            {"name": r["name"], "compile_s": r["compile_s"],
             "compile_kind": r.get("compile_kind"),
             "fingerprint": r.get("fingerprint")}
            for r in sorted(
                (r for r in by_name.values()
                 if isinstance(r.get("compile_s"), (int, float))),
                key=lambda r: -r["compile_s"])[:3]],
    }
    mfus = [t["mfu_nominal"] for t in timings.values()
            if isinstance(t.get("mfu_nominal"), (int, float))]
    if mfus:
        out["mfu_nominal_max"] = round(max(mfus), 6)
    return out


#: diff_ledgers' default bounds — overridable from tools/ledger_diff.py
#: and `tail --ledger-*` flags.
DEFAULT_COMPILE_FACTOR = 2.0
DEFAULT_COMPILE_FLOOR_S = 1.0
DEFAULT_MEMORY_FACTOR = 1.2


def _footprint(row: dict) -> int | None:
    vals = [row.get(k) for k in ("argument_bytes", "output_bytes",
                                 "temp_bytes")]
    vals = [v for v in vals if isinstance(v, int)]
    return sum(vals) if vals else None


def diff_ledgers(baseline: list[dict], run: list[dict],
                 compile_factor: float = DEFAULT_COMPILE_FACTOR,
                 compile_floor_s: float = DEFAULT_COMPILE_FLOOR_S,
                 memory_factor: float = DEFAULT_MEMORY_FACTOR) -> dict:
    """The regression sentinel: a live run's ledger vs a committed
    baseline, per executable name (newest row per name on both sides).

    Four failure classes, each a list of {name, baseline, run} entries:

      fingerprint_drift     the HLO changed — the computation is not
                            the one the baseline measured
      unexpected_recompiles the baseline's compile was a persistent-
                            cache hit but this run's missed — a silent
                            cold-start regression (cache key drift,
                            evicted cache, version skew). Rows whose
                            compile_kind is "artifact" (fingerprint- or
                            index-resolved fetches, including
                            cache_verdict="index_hit" rows) or
                            "deep_verify" (the background verifier's
                            post-serve re-lowering) never enter this
                            check on either side: a fetch is not a
                            compile and a deep verify is not a boot, so
                            their cache activity is healthy, not a
                            miss — no spurious rc 8 from booting off
                            the artifact plane
      compile_blowups       compile_s exceeded
                            max(compile_floor_s, baseline * factor) —
                            compared ONLY between rows whose
                            compile_kind matches: a warmup baseline's
                            pure lower+compile ("aot") never bounds a
                            live train run's first-step wall
                            ("first_step" = compile + one executed
                            step), which would fire a false rc 8 on a
                            healthy run
      memory_growth         argument+output+temp bytes exceeded
                            baseline * memory_factor

    `new` / `missing` names are reported but never fail the diff: a
    config can legitimately grow or shrink its lattice, and the warmup
    report covers per-entry coverage. `failed` = any failure-class list
    nonempty — tools/ledger_diff.py and `tail` map it to rc 8.
    """
    base = latest_by_name(baseline)
    live = latest_by_name(run)
    drift, recompiles, blowups, growth = [], [], [], []
    for name in sorted(set(base) & set(live)):
        b, r = base[name], live[name]
        bf, rf = b.get("fingerprint"), r.get("fingerprint")
        if bf and rf and bf != rf:
            drift.append({"name": name, "baseline": bf, "run": rf})
        if (b.get("compile_kind") not in ("artifact", "deep_verify")
                and r.get("compile_kind") not in ("artifact", "deep_verify")
                and (b.get("cache_hits") or 0) >= 1
                and (b.get("cache_misses") or 0) == 0
                and (r.get("cache_misses") or 0) >= 1):
            recompiles.append({
                "name": name,
                "baseline": {"hits": b.get("cache_hits"),
                             "misses": b.get("cache_misses")},
                "run": {"hits": r.get("cache_hits"),
                        "misses": r.get("cache_misses")}})
        bc, rc = b.get("compile_s"), r.get("compile_s")
        if (isinstance(bc, (int, float)) and isinstance(rc, (int, float))
                and b.get("compile_kind") == r.get("compile_kind")
                and rc > max(float(compile_floor_s),
                             bc * float(compile_factor))):
            blowups.append({"name": name, "baseline": bc, "run": rc})
        bm, rm = _footprint(b), _footprint(r)
        if (bm is not None and rm is not None and bm > 0
                and rm > bm * float(memory_factor)):
            growth.append({"name": name, "baseline": bm, "run": rm})
    out = {
        "executables": len(set(base) | set(live)),
        "compared": len(set(base) & set(live)),
        "new": sorted(set(live) - set(base)),
        "missing": sorted(set(base) - set(live)),
        "fingerprint_drift": drift,
        "unexpected_recompiles": recompiles,
        "compile_blowups": blowups,
        "memory_growth": growth,
        "bounds": {"compile_factor": float(compile_factor),
                   "compile_floor_s": float(compile_floor_s),
                   "memory_factor": float(memory_factor)},
    }
    out["failed"] = bool(drift or recompiles or blowups or growth)
    return out


def find_baseline(log_dir: str, explicit: str | None = None) -> str | None:
    """The baseline ledger path for a run dir: an explicit path wins;
    otherwise the committed-by-convention ``<log_dir>/
    ledger_baseline.jsonl`` when present; else None (no verdict)."""
    if explicit:
        return explicit
    cand = os.path.join(log_dir, "ledger_baseline.jsonl")
    return cand if os.path.isfile(cand) else None


def ledger_verdict(log_dir: str, baseline: str | None = None,
                   compile_factor: float = DEFAULT_COMPILE_FACTOR,
                   compile_floor_s: float = DEFAULT_COMPILE_FLOOR_S,
                   memory_factor: float = DEFAULT_MEMORY_FACTOR,
                   run_rows: list[dict] | None = None,
                   base_rows: list[dict] | None = None) -> dict | None:
    """tail/analyze's one-call entry: diff the run dir's ledger.jsonl
    against its baseline (find_baseline), or None when either side is
    absent/unreadable — no ledger, no verdict, never a crash in tail.
    Pass `run_rows`/`base_rows` when the caller already loaded a side
    (tail_summary loads the run's for the condensed block; ledger_drift
    loads the shared baseline once for a whole fleet) so a
    `tail --follow` tick parses each file once, not once per process."""
    path = find_baseline(log_dir, baseline)
    if path is None:
        return None
    try:
        if base_rows is None:
            base_rows = load_ledger(path)
        if run_rows is None:
            run_rows = load_ledger(log_dir)
    except OSError:
        return None
    if not base_rows or not run_rows:
        return None
    return diff_ledgers(base_rows, run_rows,
                        compile_factor=compile_factor,
                        compile_floor_s=compile_floor_s,
                        memory_factor=memory_factor)
