"""Incident plane: anomaly-triggered flight recorder (DESIGN.md
"Incident plane").

Every detector the stack already has — watchdog wedge (tail rc 3),
fleet eviction/broken/stall (rc 4), elastic re-form/abort (rc 5), SLO
budget exhaustion (rc 6), quality drift (rc 7), ledger drift (rc 8),
deep-verify demote, train NaN rollback — leaves only a counter and a
log line; the evidence (trace ring, heartbeats, metrics tail, thread
stacks, ledger rows) is gone or scattered by the time an operator runs
`tail`. The IncidentRecorder is the black-box flight recorder: at the
moment a verdict fires it snapshots a bounded, self-contained bundle
into `<log_dir>/incidents/<ts>-<kind>-<pid>-<seq>/`:

    manifest.json       schema, kind/severity/role, trigger payload,
                        counter snapshot, config + registry digests,
                        file inventory — written LAST (commit marker)
    stacks.txt          every live thread's stack at capture time
    heartbeats.jsonl    the last-K observed heartbeat samples
    heartbeat.json      the live heartbeat file, verbatim
    metrics_tail.jsonl  the newest N lines of metrics.jsonl
    ledger_tail.jsonl   the newest N executable-ledger rows (if any)
    trace.json          the flushed span ring (if a tracer is installed)

Capture discipline — a trigger can fire on a hot-ish path (stats(),
the supervisor poll), so capture must be rare, bounded, and unable to
hurt the process it is diagnosing:

  - atomic-rename commit: the bundle stages under a `.tmp-` name and
    renames into place only after manifest.json lands — a reader never
    mistakes a torn bundle for a committed one, and `incidents gc`
    removes orphaned staging dirs (a capture killed mid-write).
  - per-kind dedup: a kind (or explicit dedup key) that already
    captured within `obs.incident_dedup_window_s` is counted
    (`incident_deduped`), not re-captured — a flapping trigger cannot
    fill the disk.
  - token bucket: `obs.incident_burst` capacity refilled at
    `obs.incident_rate_per_min` — a storm of DISTINCT kinds is bounded
    too (`incident_rate_limited`).
  - keep bound: only the newest `obs.incident_keep` committed bundles
    are retained; older ones are pruned at capture time.
  - never raises: any capture failure increments
    `incident_capture_errors` and returns None.

Declarative alert rules (`obs.alerts`) evaluate on the heartbeat
cadence over registry-declared counters, so operators define new
triggers from config without code:

    "[name:] [rate(]counter[)] OP value [warn|critical]"

e.g. ``"err_burst: rate(serve_errors) > 5 critical"`` or
``"serve_queue_depth >= 64"`` or — the brownout plane's counters
(serve/degrade.py) are registry-declared like any other —
``"browned: degrade_level >= 2 warn"``. `rate()` is per-second between
consecutive heartbeat samples; the counter must resolve in
obs/registry.py (validated loudly at install time). A firing rule
records an incident of kind ``alert_<name>`` — the dedup window is the
re-fire policy while the condition holds. (Entering brownout L3 also
records a built-in critical ``brownout_l3`` bundle directly from the
controller — no rule needed for the terminal level.)

`obs.incidents=false` (the default) is a structural no-op: `install`
returns None, no recorder exists, no `incident_*` key enters any
stats block, and every trigger site guards on `is not None`.

Stdlib-only at import (the obs/__init__ discipline): the `incidents`
CLI, analyze/tail, and the jax-free supervisors all import this
module.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import shutil
import threading
import time
from collections import deque

SCHEMA_VERSION = 1
INCIDENTS_DIRNAME = "incidents"
STAGING_PREFIX = ".tmp-"
MANIFEST_NAME = "manifest.json"
ACK_FILENAME = "ACK"
SEVERITIES = ("warn", "critical")

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

_ALERT_RE = re.compile(
    r"^\s*(?:(?P<name>[A-Za-z0-9_.-]+)\s*:)?\s*"
    r"(?:(?P<rate>rate)\s*\(\s*(?P<rcounter>[A-Za-z0-9_]+)\s*\)"
    r"|(?P<counter>[A-Za-z0-9_]+))\s*"
    r"(?P<op>>=|<=|>|<)\s*"
    r"(?P<value>-?[0-9]+(?:\.[0-9]+)?(?:[eE][-+]?[0-9]+)?)"
    r"(?:\s+(?P<sev>warn|critical))?\s*$")


class AlertRule:
    """One parsed `obs.alerts` rule (see module docstring grammar)."""

    __slots__ = ("spec", "name", "counter", "rate", "op", "threshold",
                 "severity")

    def __init__(self, spec: str, name: str, counter: str, rate: bool,
                 op: str, threshold: float, severity: str):
        self.spec = spec
        self.name = name
        self.counter = counter
        self.rate = rate
        self.op = op
        self.threshold = threshold
        self.severity = severity

    def evaluate(self, sample: dict, prev, now_m: float):
        """(fired, observed value) against one heartbeat sample. `prev`
        is (monotonic time, sample) of the previous observation —
        rate() rules need it and never fire on the first sample."""
        cur = sample.get(self.counter)
        if not isinstance(cur, (int, float)) or isinstance(cur, bool):
            return False, None
        if self.rate:
            if prev is None:
                return False, None
            pt, psample = prev
            pv = psample.get(self.counter)
            dt = now_m - pt
            if (not isinstance(pv, (int, float)) or isinstance(pv, bool)
                    or dt <= 0):
                return False, None
            value = (float(cur) - float(pv)) / dt
        else:
            value = float(cur)
        return _OPS[self.op](value, self.threshold), round(value, 6)


def parse_alert_rules(specs) -> list[AlertRule]:
    """Parse + validate `obs.alerts` rule strings. Loud ValueError on a
    malformed rule or a counter the registry does not declare — a typo'd
    alert that silently never fires is worse than no alert."""
    from .registry import lookup

    rules: list[AlertRule] = []
    seen: set[str] = set()
    for spec in specs or ():
        m = _ALERT_RE.match(str(spec))
        if m is None:
            raise ValueError(
                f"bad obs.alerts rule {spec!r}: expected "
                f"'[name:] [rate(]counter[)] OP value [warn|critical]' "
                f"with OP one of > >= < <=")
        counter = m.group("counter") or m.group("rcounter")
        if lookup(counter) is None:
            raise ValueError(
                f"obs.alerts rule {spec!r}: counter {counter!r} is not "
                f"declared in obs/registry.py — alert rules may only "
                f"watch registered keys")
        name = m.group("name") or counter
        if name in seen:
            raise ValueError(f"obs.alerts: duplicate rule name {name!r}")
        seen.add(name)
        rules.append(AlertRule(
            spec=str(spec), name=name, counter=counter,
            rate=bool(m.group("rate")), op=m.group("op"),
            threshold=float(m.group("value")),
            severity=m.group("sev") or "warn"))
    return rules


# -------------------------------------------------------------- helpers


def _tail_lines(path: str, n: int, max_bytes: int = 1 << 18) -> str | None:
    """The newest n lines of a (possibly large) text file, reading at
    most max_bytes from the end — the bundle stays bounded no matter
    how long the run's metrics log has grown."""
    if n <= 0:
        return None
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(0, size - max_bytes))
            data = f.read(max_bytes)
    except OSError:
        return None
    lines = data.decode("utf-8", errors="replace").splitlines()
    if size > max_bytes and lines:
        lines = lines[1:]  # the first line may be torn by the seek
    tail = lines[-n:]
    if not tail:
        return None
    return "\n".join(tail) + "\n"


def config_digest(cfg) -> str | None:
    """Stable short digest of a (dataclass) config tree — the manifest
    records which config the incident happened under without embedding
    the whole tree in every bundle."""
    try:
        blob = json.dumps(dataclasses.asdict(cfg), sort_keys=True,
                          default=str)
    except Exception:  # noqa: BLE001 - digesting is best-effort
        return None
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def registry_digest() -> str:
    """Short digest of the observability schema (registered key names):
    two bundles with the same digest were captured under the same
    counter vocabulary."""
    from .registry import REGISTRY

    return hashlib.sha256(
        ",".join(sorted(REGISTRY)).encode()).hexdigest()[:16]


def _safe_kind(kind: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "-", str(kind))[:64] or "incident"


# Bundle names are <ts>-<kind>-<pid>-<seq>: the sequence must be unique
# per PROCESS, not per recorder — two recorder instances capturing the
# same kind within the same second (record_offline constructs one per
# call) would otherwise collide on the final rename.
_seq_lock = threading.Lock()
_seq_counter = 0


def _next_seq() -> int:
    global _seq_counter
    with _seq_lock:
        _seq_counter += 1
        return _seq_counter


# ------------------------------------------------------------- recorder


class IncidentRecorder:
    """See module docstring. One per process; spawns no threads —
    capture runs on whichever thread hit the trigger (rare + bounded
    by construction)."""

    def __init__(self, log_dir: str, role: str, *,
                 rate_per_min: float = 6.0, burst: int = 3,
                 dedup_window_s: float = 300.0, metrics_tail: int = 200,
                 heartbeats: int = 8, keep: int = 32, alerts=(),
                 config_digest: str | None = None):
        self.log_dir = log_dir
        self.role = role
        self._rate = max(float(rate_per_min), 0.0) / 60.0
        self._burst = max(int(burst), 1)
        self._tokens = float(self._burst)
        self._refilled = time.monotonic()
        self._dedup_s = max(float(dedup_window_s), 0.0)
        self._tail = max(int(metrics_tail), 0)
        self._keep = max(int(keep), 1)
        self._config_digest = config_digest
        self._rules = parse_alert_rules(alerts)
        self._hb_ring: deque = deque(maxlen=max(int(heartbeats), 1))
        self._prev_sample: tuple[float, dict] | None = None
        self._lock = threading.Lock()
        self._seq = 0
        self._last_capture: dict[str, float] = {}
        self._captured = 0
        self._deduped = 0
        self._rate_limited = 0
        self._errors = 0
        self._collected = 0
        self._by_kind: dict[str, int] = {}
        self._last_kind: str | None = None
        self._alert_firings = 0
        self._alert_errors = 0

    # ------------------------------------------------------------ record
    def record(self, kind: str, severity: str = "warn",
               trigger: dict | None = None,
               text_files: dict[str, str] | None = None,
               dedup_key: str | None = None) -> str | None:
        """Capture one incident bundle; returns its committed path, or
        None when deduped / rate-limited / capture failed. Never raises
        — the trigger site must not die of its own flight recorder."""
        try:
            return self._record(kind, severity, trigger, text_files,
                                dedup_key)
        except Exception:  # noqa: BLE001 - capture must never propagate
            with self._lock:
                self._errors += 1
            return None

    def _record(self, kind, severity, trigger, text_files, dedup_key):
        key = dedup_key or str(kind)
        now = time.monotonic()
        with self._lock:
            last = self._last_capture.get(key)
            if (last is not None and self._dedup_s > 0
                    and now - last < self._dedup_s):
                self._deduped += 1
                return None
            # token bucket, refilled lazily: a storm of distinct kinds
            # is bounded even when each passes its own dedup window
            self._tokens = min(
                float(self._burst),
                self._tokens + (now - self._refilled) * self._rate)
            self._refilled = now
            if self._tokens < 1.0:
                self._rate_limited += 1
                return None
            self._tokens -= 1.0
            self._last_capture[key] = now
            seq = _next_seq()
            self._seq = seq
            hb_ring = [dict(h) for h in self._hb_ring]
            counters = self._stats_locked()
        path = self._capture(kind, severity, dict(trigger or {}),
                             dict(text_files or {}), key, seq, counters,
                             hb_ring)
        with self._lock:
            self._captured += 1
            self._by_kind[kind] = self._by_kind.get(kind, 0) + 1
            self._last_kind = str(kind)
        return path

    def _capture(self, kind, severity, trigger, text_files, key, seq,
                 counters, hb_ring) -> str:
        inc_root = os.path.join(self.log_dir, INCIDENTS_DIRNAME)
        os.makedirs(inc_root, exist_ok=True)
        ts = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        name = f"{ts}-{_safe_kind(kind)}-{os.getpid()}-{seq}"
        staging = os.path.join(inc_root,
                               f"{STAGING_PREFIX}{os.getpid()}-{seq}")
        os.makedirs(staging)
        files: dict[str, int] = {}

        def put(fname: str, text: str) -> None:
            p = os.path.join(staging, fname)
            with open(p, "w") as f:
                f.write(text)
            files[fname] = os.path.getsize(p)

        if "stacks.txt" not in text_files:
            from .heartbeat import dump_all_stacks

            text_files["stacks.txt"] = dump_all_stacks()
        for fname, text in text_files.items():
            put(fname, str(text))
        if hb_ring:
            put("heartbeats.jsonl",
                "\n".join(json.dumps(h, default=str) for h in hb_ring)
                + "\n")
        hb_path = os.path.join(self.log_dir, "heartbeat.json")
        if os.path.isfile(hb_path):
            try:
                shutil.copyfile(hb_path,
                                os.path.join(staging, "heartbeat.json"))
                files["heartbeat.json"] = os.path.getsize(
                    os.path.join(staging, "heartbeat.json"))
            except OSError:
                pass
        for src, dst in (("metrics.jsonl", "metrics_tail.jsonl"),
                         ("ledger.jsonl", "ledger_tail.jsonl")):
            tail = _tail_lines(os.path.join(self.log_dir, src),
                               self._tail)
            if tail:
                put(dst, tail)
        try:  # flushed span ring: the timeline leading into the anomaly
            from . import trace as obs_trace

            tr = os.path.join(staging, "trace.json")
            obs_trace.flush_current(tr)
            if os.path.isfile(tr):
                files["trace.json"] = os.path.getsize(tr)
        except Exception:  # noqa: BLE001 - trace capture is best-effort
            pass
        t = time.time()
        manifest = {
            "schema": SCHEMA_VERSION,
            "id": name,
            "kind": str(kind),
            "severity": severity if severity in SEVERITIES else "warn",
            "role": self.role,
            "pid": os.getpid(),
            "seq": seq,
            "time": t,
            "iso_time": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime(t)),
            "trigger": trigger,
            "counters": counters,
            "dedup_key": key,
            "config_digest": self._config_digest,
            "registry_digest": registry_digest(),
            "files": files,
            "origin": None,
        }
        # manifest LAST, then the atomic rename: a bundle without a
        # manifest is torn by definition; a renamed bundle is complete
        with open(os.path.join(staging, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f, indent=2, default=str)
        final = os.path.join(inc_root, name)
        os.rename(staging, final)
        self._prune(inc_root)
        return final

    def _prune(self, inc_root: str) -> None:
        """Bounded disk: beyond `keep` committed bundles, the oldest
        are removed (dedup + the token bucket bound the rate; this
        bounds the total)."""
        try:
            names = sorted(n for n in os.listdir(inc_root)
                           if not n.startswith(STAGING_PREFIX))
        except OSError:
            return
        for name in names[:max(0, len(names) - self._keep)]:
            shutil.rmtree(os.path.join(inc_root, name),
                          ignore_errors=True)

    # ----------------------------------------------------- alert engine
    def observe(self, sample: dict) -> None:
        """Feed one heartbeat sample: ring-buffer it (the bundle's
        `heartbeats.jsonl`) and evaluate the alert rules against it.
        Called on the heartbeat cadence; never raises."""
        try:
            now_m = time.monotonic()
            rec = dict(sample or {})
            rec.setdefault("time", time.time())
            with self._lock:
                prev = self._prev_sample
                self._hb_ring.append(rec)
                self._prev_sample = (now_m, rec)
            for rule in self._rules:
                try:
                    fired, value = rule.evaluate(rec, prev, now_m)
                except Exception:  # noqa: BLE001 - one bad rule != all
                    with self._lock:
                        self._alert_errors += 1
                    continue
                if not fired:
                    continue
                with self._lock:
                    self._alert_firings += 1
                self.record(
                    f"alert_{rule.name}", rule.severity,
                    trigger={"rule": rule.spec, "counter": rule.counter,
                             "op": rule.op, "threshold": rule.threshold,
                             "value": value, "rate": rule.rate})
        except Exception:  # noqa: BLE001
            with self._lock:
                self._errors += 1

    def wrap_sample(self, fn):
        """Wrap a heartbeat `sample` callback: observe each sample for
        the ring + alert rules, and merge the incident_*/alert_*
        counter block into it (registry -> heartbeat -> /metrics)."""
        def wrapped() -> dict:
            out = dict(fn() or {})
            self.observe(out)
            out.update(self.stats())
            return out
        return wrapped

    # ------------------------------------------------------------- stats
    def note_collected(self, n: int) -> None:
        """Supervisor-side sweep accounting (collect_from_children)."""
        if n:
            with self._lock:
                self._collected += int(n)

    def stats(self) -> dict:
        with self._lock:
            return self._stats_locked()

    def _stats_locked(self) -> dict:
        out = {
            "incident_captured": self._captured,
            "incident_deduped": self._deduped,
            "incident_rate_limited": self._rate_limited,
            "incident_capture_errors": self._errors,
            "incident_collected": self._collected,
            "incident_by_kind": dict(self._by_kind),
            "alert_rules": len(self._rules),
            "alert_firings": self._alert_firings,
            "alert_errors": self._alert_errors,
        }
        if self._last_kind is not None:
            out["incident_last_kind"] = self._last_kind
        return out


def install(cfg, log_dir: str | None, role: str) -> IncidentRecorder | None:
    """The one construction path every process kind uses. None when
    `obs.incidents` is off or there is no log dir — the structural
    no-op: callers guard every trigger on `is not None`."""
    obs = cfg.obs
    if not getattr(obs, "incidents", False) or not log_dir:
        return None
    return IncidentRecorder(
        log_dir, role,
        rate_per_min=obs.incident_rate_per_min,
        burst=obs.incident_burst,
        dedup_window_s=obs.incident_dedup_window_s,
        metrics_tail=obs.incident_metrics_tail,
        heartbeats=obs.incident_heartbeats,
        keep=obs.incident_keep,
        alerts=obs.alerts,
        config_digest=config_digest(cfg))


# ------------------------------------------- offline one-shot recording


def record_offline(log_dir: str, kind: str, severity: str,
                   trigger: dict | None = None,
                   dedup_key: str | None = None,
                   role: str = "offline") -> str | None:
    """One-shot bundle writer for verdicts computed OUTSIDE the live
    process — the `tail` rc-8 ledger-drift gate is the consumer (no
    live process ever sees that verdict). Dedup is structural: an
    existing committed bundle with the same kind + dedup key suppresses
    the capture, so a `tail --follow` loop writes one bundle per
    distinct regression, not one per tick. Best-effort and silent: a
    read-only run dir must not break the (stdout-pure) tail."""
    key = dedup_key or str(kind)
    try:
        for man in list_incidents(log_dir):
            if man.get("kind") == kind and man.get("dedup_key") == key:
                return None
        rec = IncidentRecorder(log_dir, role, dedup_window_s=0.0)
        return rec._record(kind, severity, trigger, None, key)
    except Exception:  # noqa: BLE001
        return None


# -------------------------------------------------- triage (jax-free)


def incidents_dir(log_dir: str) -> str:
    return os.path.join(log_dir, INCIDENTS_DIRNAME)


def list_incidents(log_dir: str) -> list[dict]:
    """Every COMMITTED bundle's manifest under <log_dir>/incidents/,
    oldest first, each annotated with `id` and the live `acked` state
    (an ACK file in the bundle dir). Staging dirs and manifest-less
    dirs are torn, not incidents."""
    root = incidents_dir(log_dir)
    out: list[dict] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    for name in names:
        if name.startswith(STAGING_PREFIX):
            continue
        d = os.path.join(root, name)
        try:
            with open(os.path.join(d, MANIFEST_NAME)) as f:
                man = json.load(f)
        except (OSError, ValueError):
            continue
        man["id"] = name
        man["acked"] = os.path.exists(os.path.join(d, ACK_FILENAME))
        out.append(man)
    out.sort(key=lambda m: (m.get("time") or 0, m["id"]))
    return out


def _staging_dirs(log_dir: str) -> list[str]:
    root = incidents_dir(log_dir)
    try:
        return sorted(os.path.join(root, n) for n in os.listdir(root)
                      if n.startswith(STAGING_PREFIX))
    except OSError:
        return []


def incident_summary(log_dir: str) -> dict | None:
    """The condensed `incidents` block analyze/tail embed; None when
    the run recorded none (schema-stable with the pre-incident stack).
    `unacked_critical` is the figure `tail` maps to exit code 9."""
    mans = list_incidents(log_dir)
    torn = len(_staging_dirs(log_dir))
    if not mans and not torn:
        return None
    by_kind: dict[str, int] = {}
    critical = unacked = 0
    for m in mans:
        by_kind[m.get("kind", "?")] = by_kind.get(m.get("kind", "?"), 0) + 1
        if m.get("severity") == "critical":
            critical += 1
            if not m.get("acked"):
                unacked += 1
    out = {"total": len(mans), "critical": critical,
           "unacked_critical": unacked, "torn": torn,
           "by_kind": by_kind}
    if mans:
        last = mans[-1]
        out["last"] = {k: last.get(k) for k in
                       ("id", "kind", "severity", "time", "acked",
                        "origin")}
    return out


def show_incident(log_dir: str, incident_id: str) -> dict:
    """One bundle's manifest + on-disk file inventory. Raises
    FileNotFoundError for an unknown or torn id."""
    d = os.path.join(incidents_dir(log_dir), incident_id)
    mpath = os.path.join(d, MANIFEST_NAME)
    if (incident_id.startswith(STAGING_PREFIX)
            or not os.path.isfile(mpath)):
        raise FileNotFoundError(
            f"no committed incident {incident_id!r} under {log_dir!r}")
    with open(mpath) as f:
        man = json.load(f)
    man["id"] = incident_id
    man["acked"] = os.path.exists(os.path.join(d, ACK_FILENAME))
    man["dir"] = d
    man["files_on_disk"] = {
        n: os.path.getsize(os.path.join(d, n))
        for n in sorted(os.listdir(d)) if n != MANIFEST_NAME}
    return man


def ack_incidents(log_dir: str, incident_id: str | None = None) -> list[str]:
    """Acknowledge one bundle (or all, id=None) by dropping an ACK
    file — the reviewed-by-an-operator marker that clears rc 9.
    Returns the ids newly acknowledged."""
    if incident_id is not None:
        targets = [show_incident(log_dir, incident_id)]
    else:
        targets = list_incidents(log_dir)
    acked = []
    for man in targets:
        if man.get("acked"):
            continue
        p = os.path.join(incidents_dir(log_dir), man["id"], ACK_FILENAME)
        with open(p, "w") as f:
            f.write(time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
                    + "\n")
        acked.append(man["id"])
    return acked


def gc_incidents(log_dir: str, older_than_days: float | None = None,
                 acked: bool = False, keep: int | None = None) -> dict:
    """Remove torn staging dirs (always), plus — opt-in — acknowledged
    bundles, bundles older than `older_than_days`, and everything
    beyond the newest `keep`."""
    removed: list[str] = []
    staging_removed = 0
    for d in _staging_dirs(log_dir):
        shutil.rmtree(d, ignore_errors=True)
        staging_removed += 1
    mans = list_incidents(log_dir)
    now = time.time()
    survivors = []
    for m in mans:
        drop = False
        if acked and m.get("acked"):
            drop = True
        if (older_than_days is not None
                and isinstance(m.get("time"), (int, float))
                and now - m["time"] > float(older_than_days) * 86400.0):
            drop = True
        if drop:
            shutil.rmtree(os.path.join(incidents_dir(log_dir), m["id"]),
                          ignore_errors=True)
            removed.append(m["id"])
        else:
            survivors.append(m)
    if keep is not None and len(survivors) > max(int(keep), 0):
        for m in survivors[:len(survivors) - max(int(keep), 0)]:
            shutil.rmtree(os.path.join(incidents_dir(log_dir), m["id"]),
                          ignore_errors=True)
            removed.append(m["id"])
    return {"dir": incidents_dir(log_dir), "removed": removed,
            "staging_removed": staging_removed,
            "kept": len(list_incidents(log_dir))}


# -------------------------------------------- supervisor-side collection


def collect_from_children(run_dir: str) -> int:
    """Sweep committed incident bundles out of depth-1 child process
    dirs (fleet `replica-N/`, elastic `host-N/`) into the run root's
    incidents/, renamed `<child>--<id>` and annotated with their
    origin — one `tail --fleet` / `incidents list` at the run root sees
    the whole drill, including bundles a SIGKILLed replica left behind.
    Move (atomic same-fs rename), not copy: a bundle is counted once.
    Returns the number collected; best-effort (a vanishing child dir is
    a race, not an error)."""
    moved = 0
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        return 0
    dest_root = os.path.join(run_dir, INCIDENTS_DIRNAME)
    for name in names:
        if name == INCIDENTS_DIRNAME:
            continue
        src_root = os.path.join(run_dir, name, INCIDENTS_DIRNAME)
        if not os.path.isdir(src_root):
            continue
        try:
            bids = sorted(os.listdir(src_root))
        except OSError:
            continue
        for bid in bids:
            if bid.startswith(STAGING_PREFIX):
                continue  # torn or mid-capture: never collect those
            src = os.path.join(src_root, bid)
            if not os.path.isfile(os.path.join(src, MANIFEST_NAME)):
                continue
            dst = os.path.join(dest_root, f"{name}--{bid}")
            if os.path.exists(dst):
                continue
            os.makedirs(dest_root, exist_ok=True)
            try:
                os.rename(src, dst)
            except OSError:
                continue
            moved += 1
            _annotate_origin(dst, name)
    return moved


def _annotate_origin(bundle_dir: str, origin: str) -> None:
    """Best-effort `origin` stamp after collection (atomic replace, so
    a concurrent reader still sees valid JSON)."""
    mpath = os.path.join(bundle_dir, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            man = json.load(f)
        man["origin"] = origin
        tmp = mpath + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(man, f, indent=2, default=str)
        os.replace(tmp, mpath)
    except (OSError, ValueError):
        pass
