"""Unified observability layer (L0 — stdlib-only at import).

The stack is genuinely concurrent (prefetch thread, N pipeline workers,
AsyncFetcher consumer, staged device puts), and scalar counters cannot
show *when* those threads overlapped, stalled, or wedged. This package
holds the instruments that can:

  trace.py      lock-cheap ring-buffered span tracer emitting Chrome
                trace-event JSON (load artifacts' trace.json in Perfetto
                / chrome://tracing) — the cross-thread timeline that
                makes dispatch/put/fetch/assemble overlap visible
                instead of inferred from phase totals.
  heartbeat.py  background thread atomically rewriting heartbeat.json
                (step, rates, queue depths, device memory, RSS) plus a
                wedge watchdog: no step within k x a robust recent
                step-time estimate => all thread stacks dumped to the
                log and the trace ring flushed.
  telemetry.py  process/device sampling shared by training and bench:
                XLA cost-analysis FLOPs (model TFLOP/s + nominal MFU),
                per-device memory_stats, process RSS.
  export.py     the scrapeable face (DESIGN.md "Fleet observability"):
                fixed log-spaced latency histograms that merge EXACTLY
                across processes, Prometheus text rendering/parsing for
                every stats block (GET /metrics on the serve server,
                the fleet router, the elastic coordinator), and the
                latency/error-budget SLO layer (`tail` rc 6).
  aggregate.py  multi-process trace merge: every per-process
                trace.json/heartbeat.json/metrics.jsonl under a run dir
                becomes ONE Perfetto timeline with per-process tracks
                and request-id flow arrows chaining each request across
                router and replica (`tools/trace_summary.py --merge`).

Import discipline: this __init__, trace.py, export.py, and aggregate.py
import only the stdlib (`bench.py`'s orchestrating parent and
`analyze.py` may import them without initializing an accelerator
backend); telemetry.py defers its jax imports into the sampling
functions for the same reason.
"""

from . import trace

__all__ = ["trace"]
