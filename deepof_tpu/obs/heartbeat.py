"""Liveness heartbeat + wedge watchdog.

The historical failure mode this instrument exists for: a dead relay
tunnel wedges a `device_get`/`device_put` inside one of the loop's
threads and the run goes silent — no log line, no crash, nothing to
diagnose (CHANGES.md PR 1 notes; the rc=139 host flakes were likewise
reconstructed by hand). Two halves:

  Heartbeat file: a background thread atomically rewrites
  `heartbeat.json` every `period_s` with the last completed step, rates,
  queue/staged depths (caller-provided sample callback), per-device
  memory, process RSS, and the age of the last step. "Is it making
  progress?" becomes one `cat` (or `deepof_tpu tail`), even from outside
  the process, and the atomic tmp+rename rewrite means a reader never
  sees a torn file.

  Wedge watchdog: the loop calls `beat(step)` at each completed
  dispatch; the watchdog keeps a robust (median) estimate of recent
  step times and declares a wedge when no step completes within
  `watchdog_factor x` that estimate (floored by `watchdog_min_s` so
  normal jitter and short stalls never fire). On a wedge it dumps EVERY
  thread's stack to the metrics log — naming which thread is stuck
  where — flushes the trace ring (the timeline leading into the stall
  survives), and marks `wedged: true` in the heartbeat file. One firing
  per stall: the state re-arms when steps resume.

The watchdog only observes and reports — it never kills the process
(policy belongs to the operator / the SIGTERM paths in train/loop.py);
`on_wedge` is the hook for anything stronger. Long legitimate pauses
(eval sweeps, checkpoint saves, compiles) are handled by `touch()`,
which resets the activity clock without polluting the step-time
estimate, plus the arm threshold of `MIN_BEATS_TO_ARM` completed steps.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable

from . import trace as obs_trace
from .telemetry import device_memory_summary, process_rss_bytes

#: Completed steps before the watchdog arms: the first dispatches include
#: the XLA compile, whose duration must neither trip the watchdog nor
#: enter the step-time estimate as a "recent step".
MIN_BEATS_TO_ARM = 3


def dump_all_stacks() -> str:
    """Every live thread's stack, name first — the wedge diagnosis.
    `sys._current_frames` is CPython-specific but this repo already
    depends on CPython threading semantics throughout."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for tid, frame in sorted(sys._current_frames().items()):
        header = f"--- thread {names.get(tid, '<unknown>')} (tid={tid}) ---"
        parts.append(header + "\n" + "".join(traceback.format_stack(frame)))
    return "\n".join(parts)


def _median(xs) -> float:
    return statistics.median(xs) if xs else 0.0


class Heartbeat:
    """See module docstring.

    path: heartbeat.json destination (atomically rewritten).
    period_s: rewrite cadence; also the watchdog poll cadence.
    watchdog_factor: k in "wedged when no step for k x median step time".
    watchdog_min_s: wedge-age floor — below this, never declare (keeps
        sub-second-step runs from flagging scheduler hiccups).
    sample: optional () -> dict merged into each heartbeat record
        (rates, queue depths, ...); exceptions are contained.
    log: optional (step, message) sink for the wedge report
        (MetricsLogger-shaped: lands in metrics.jsonl as a warn record).
    tracer: optional obs.trace.Tracer flushed when a wedge fires.
    on_wedge: optional (stack_dump_str) hook after the dump is logged.
    devmem: sample per-device memory on the background thread. False
        keeps the process jax-free (device_memory_summary imports jax
        and touches the backend) — fleet supervisors and fake-executor
        replicas beat without ever initializing an accelerator; the
        dev_mem_* keys stay present as nulls so the schema is stable.
    """

    def __init__(self, path: str, period_s: float = 5.0,
                 watchdog_factor: float = 20.0, watchdog_min_s: float = 60.0,
                 sample: Callable[[], dict] | None = None,
                 log: Callable[[int, str], None] | None = None,
                 tracer=None, on_wedge: Callable[[str], None] | None = None,
                 window: int = 64, devmem: bool = True):
        self.path = path
        self._period = max(float(period_s), 0.05)
        self._factor = max(float(watchdog_factor), 1.0)
        self._min_s = max(float(watchdog_min_s), 0.0)
        self._sample = sample
        self._log = log
        self._tracer = tracer
        self._on_wedge = on_wedge
        self._lock = threading.Lock()
        # serializes file writes: touch(flush=True) writes from the
        # CALLING thread, racing the background writer — both use the
        # same pid-derived tmp path, and an interleaved truncate/write
        # could promote torn JSON into heartbeat.json via os.replace
        self._write_lock = threading.Lock()
        self._durs: deque = deque(maxlen=max(int(window), 4))
        self._last_activity = time.monotonic()
        self._beats = 0
        self._last_step = 0
        self._wedge_active = False
        self._wedges = 0
        self._stop = threading.Event()
        # Device-memory sampling runs on its OWN thread, feeding a cached
        # snapshot: memory_stats() crosses into the backend, and a hung
        # backend (the dead-tunnel case this watchdog exists for) would
        # otherwise wedge the heartbeat/watchdog thread itself — the
        # instrument must outlive the failure it diagnoses. A hang there
        # only stales the cached values; the watchdog keeps polling.
        self._devmem: dict = {"dev_mem_bytes_in_use": None,
                              "dev_mem_peak_bytes": None}
        self._sampler = None
        if devmem:
            self._sampler = threading.Thread(target=self._sample_devices,
                                             daemon=True, name="obs-devmem")
            self._sampler.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="obs-heartbeat")
        self._thread.start()

    # ------------------------------------------------------------ inputs
    def beat(self, step: int) -> None:
        """A step completed: record its duration, re-arm the watchdog."""
        now = time.monotonic()
        with self._lock:
            self._durs.append(now - self._last_activity)
            self._last_activity = now
            self._beats += 1
            self._last_step = int(step)
            self._wedge_active = False

    def touch(self, flush: bool = False) -> None:
        """Activity that is not a step (eval, checkpoint, rollback):
        resets the wedge clock without entering the step-time estimate.

        flush=True additionally rewrites heartbeat.json NOW, from the
        calling thread. Use it when entering a long GIL-bound phase (the
        eval-executable XLA lowering/trace is pure Python): on a
        contended host the background writer thread can starve for the
        whole phase, so an external supervisor reading the file
        (fleet/elastic `host_verdict`) would see a stale timestamp and
        evict a healthy host. A synchronous write on entry hands the
        supervisor the full `stale_after_s` window measured FROM the
        phase start — the in-loop cadence cannot guarantee that."""
        with self._lock:
            self._last_activity = time.monotonic()
            self._wedge_active = False
        if flush:
            self._write()

    # ----------------------------------------------------------- sampling
    def _snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            durs = list(self._durs)
            rec = {
                "time": time.time(),
                "pid": os.getpid(),
                "step": self._last_step,
                "beats": self._beats,
                "last_step_age_s": round(now - self._last_activity, 3),
                "step_time_median_s": round(_median(durs), 4) if durs else None,
                "heartbeat_period_s": self._period,
                "wedged": self._wedge_active,
                "wedges": self._wedges,
            }
        rec["rss_bytes"] = process_rss_bytes()
        rec.update(self._devmem)  # cached by the obs-devmem thread
        if self._sample is not None:
            try:
                rec.update(self._sample() or {})
            except Exception as e:  # noqa: BLE001 - sampling is best-effort
                rec["sample_error"] = f"{type(e).__name__}: {e}"
        return rec

    def _write(self) -> None:
        with self._write_lock:  # flush-from-caller vs writer thread
            rec = self._snapshot()
            try:
                d = os.path.dirname(os.path.abspath(self.path))
                os.makedirs(d, exist_ok=True)
                tmp = os.path.join(
                    d, f".{os.path.basename(self.path)}.tmp.{os.getpid()}")
                with open(tmp, "w") as f:
                    json.dump(rec, f)
                os.replace(tmp, self.path)  # readers never see a torn file
            except OSError:
                pass  # read-only tree must not crash the heartbeat thread

    # ----------------------------------------------------------- watchdog
    def _check_wedge(self) -> None:
        now = time.monotonic()
        with self._lock:
            if self._beats < MIN_BEATS_TO_ARM or self._wedge_active:
                return
            est = _median(self._durs)
            age = now - self._last_activity
            threshold = max(self._factor * est, self._min_s)
            if age <= threshold:
                return
            # declare INSIDE the lock so a concurrent beat() can't race a
            # half-fired wedge; the heavy reporting happens outside it
            self._wedge_active = True
            self._wedges += 1
            step = self._last_step
        dump = dump_all_stacks()
        msg = (f"WATCHDOG: no step completed for {age:.1f}s "
               f"(> max({self._factor:g} x median {est:.3f}s, "
               f"{self._min_s:g}s)) — wedged? All thread stacks:\n{dump}")
        if self._log is not None:
            try:
                self._log(step, msg)
            except Exception:  # noqa: BLE001 - reporting must not raise here
                pass
        tracer = self._tracer if self._tracer is not None \
            else obs_trace.current()
        try:
            tracer.instant("watchdog_wedge", age_s=round(age, 1))
            tracer.flush()  # the timeline leading into the stall survives
        except Exception:  # noqa: BLE001
            pass
        if self._on_wedge is not None:
            try:
                self._on_wedge(dump)
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------- threads
    def _sample_devices(self) -> None:
        while True:
            try:
                self._devmem = device_memory_summary()  # atomic rebind
            except Exception:  # noqa: BLE001 - sampling must never raise
                pass
            if self._stop.wait(self._period):
                return

    def _run(self) -> None:
        while not self._stop.wait(self._period):
            self._check_wedge()
            self._write()
        self._write()  # final state on close: fresh file at exit

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=self._period + 5.0)
        if self._sampler is not None:
            # a sampler wedged inside a hung backend call is abandoned
            # (daemon)
            self._sampler.join(timeout=1.0)
