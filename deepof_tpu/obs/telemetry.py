"""Process + device telemetry sampling, shared by training and bench.

Promotes what used to be bench-only instrumentation into the training
loop: XLA's own cost-analysis FLOPs (so every fit() can log model
TFLOP/s and a nominal MFU, not just bench.py), per-device
`memory_stats()` (HBM bytes-in-use / peak), and process RSS.

Import discipline: jax is imported lazily inside the functions —
importing this module must stay side-effect free (bench.py's
orchestrating parent and the heartbeat thread both import it without
wanting a backend initialized; see obs/__init__).
"""

from __future__ import annotations

import os

#: Nominal dense bf16 peak of the chip this container tunnels to (v5e:
#: 197 TFLOP/s). Single source of truth — bench.py and the train loop
#: both compute `mfu_nominal` against it.
NOMINAL_BF16_TFLOPS = 197.0


def lowered_flops(lowered) -> float | None:
    """FLOPs from an already-lowered module's cost analysis — the
    shared extraction behind step_flops, split out so a caller that
    holds a `jax.stages.Lowered` (the train loop reuses one lowering
    for FLOPs AND the executable ledger's provenance row) never pays a
    second trace. None when the backend does not report it."""
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:  # noqa: BLE001 - cost model is best-effort
        return None


def step_flops(step, *example_args) -> float | None:
    """XLA's FLOPs estimate for one call of a jitted `step`, from the
    LOWERED module (`jit(...).lower(...).cost_analysis()`) — traces but
    never compiles on the backend (matters on a tunnel whose compile
    latency swings). Lowered cost analysis reports GLOBAL
    (pre-partition) FLOPs, and a lax.scan body is counted ONCE, so the
    value is per-optimizer-step for any steps_per_call (bench.py has the
    verification notes). None when the backend does not report it."""
    try:
        return lowered_flops(step.lower(*example_args))
    except Exception:  # noqa: BLE001 - cost model is best-effort
        return None


def process_rss_bytes() -> int | None:
    """Resident set size of this process (host RAM actually mapped) —
    the input pipeline's decoded-image cache, reorder buffers, and any
    leak all show up here. Linux /proc; None elsewhere."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None


def device_memory_stats() -> list[dict]:
    """Per-device `memory_stats()` snapshot. Fields are None where the
    backend does not report (the cpu PJRT client returns no stats);
    callers decide whether to surface or drop the nulls."""
    import jax

    out = []
    for d in jax.devices():
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001 - never let sampling kill a run
            ms = None
        out.append({
            "device": str(d),
            "bytes_in_use": ms.get("bytes_in_use") if ms else None,
            "peak_bytes_in_use": ms.get("peak_bytes_in_use") if ms else None,
        })
    return out


def device_memory_summary() -> dict:
    """Max bytes-in-use / peak across devices, log-record keyed.

    Max (not sum): with replicated params + sharded batches the hottest
    chip is the one that OOMs, so the headroom question is per-device.
    Keys are always present (None on backends without stats) so a
    record's schema does not depend on the backend — `MetricsLogger`
    serializes None as null.
    """
    stats = device_memory_stats()
    in_use = [s["bytes_in_use"] for s in stats
              if s["bytes_in_use"] is not None]
    peak = [s["peak_bytes_in_use"] for s in stats
            if s["peak_bytes_in_use"] is not None]
    return {
        "dev_mem_bytes_in_use": max(in_use) if in_use else None,
        "dev_mem_peak_bytes": max(peak) if peak else None,
    }
