"""Lock-cheap, ring-buffered span tracer -> Chrome trace-event JSON.

Every instrumented site (`train/loop.py` dispatch/eval/ckpt/rollback,
`train/metrics_log.py` fetch, `data/prefetch.py` put, `data/pipeline.py`
worker assemble) calls the module-level `span(name, **args)`; with no
tracer installed that is one global read + a shared no-op context
manager, so instrumentation costs nothing when tracing is off and the
instrumented modules never need a tracer threaded through their
constructors.

Design constraints, in order:

  - The hot path takes NO lock: completed spans are appended to a
    `collections.deque(maxlen=ring_size)` — append and the implicit
    oldest-eviction are single C-level ops, atomic under the GIL, so
    pipeline workers / prefetch / fetcher / main all record concurrently
    without contending. Memory is bounded by construction: the ring
    keeps the newest `ring_size` spans (the window that matters when a
    watchdog fires).
  - Timestamps come from `time.perf_counter()` (CLOCK_MONOTONIC —
    comparable across threads of one process), rebased to the tracer's
    construction so `ts` starts near zero.
  - `flush()` writes the Chrome trace-event format (JSON object with a
    `traceEvents` list of "X" complete events + "M" thread-name
    metadata) atomically (tmp + rename), so a viewer — or the watchdog,
    which flushes mid-run — never reads a torn file. Perfetto and
    chrome://tracing both load it directly.

Stdlib-only at import (see obs/__init__ docstring).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque


class _NullSpan:
    """Shared, stateless no-op context manager (safe to re-enter from
    any number of threads at once)."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """The uninstalled state: every operation is a no-op."""

    path: str | None = None

    def span(self, name: str, **args) -> _NullSpan:
        return _NULL_SPAN

    def instant(self, name: str, **args) -> None:
        pass

    def flush(self, path: str | None = None) -> str | None:
        return None


class _Span:
    """One live span: created by Tracer.span, records on __exit__."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict | None):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer._record(self._name, self._t0, time.perf_counter(),
                             self._args)
        return False


class Tracer:
    """Ring-buffered span recorder; see module docstring.

    path: default flush destination (conventionally
        `<log_dir>/trace.json`).
    ring_size: max retained events — spans beyond it evict the oldest
        (bounded memory; a full training run keeps its newest window).
    """

    def __init__(self, path: str | None = None, ring_size: int = 16384):
        self.path = path
        self.ring_size = max(int(ring_size), 16)
        self._events: deque = deque(maxlen=self.ring_size)
        self._epoch = time.perf_counter()
        self._epoch_unix = time.time()
        # tid -> thread name, captured at first event from that thread.
        # Plain dict: item assignment is GIL-atomic, and a benign
        # double-write of the same name is harmless.
        self._threads: dict[int, str] = {}
        self._dropped = 0  # informational; deque eviction is implicit

    # ------------------------------------------------------------ record
    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (ph='i') — e.g. the watchdog's wedge."""
        now = time.perf_counter()
        self._note_thread()
        self._events.append(("i", name, threading.get_ident(),
                             (now - self._epoch) * 1e6, 0.0, args or None))

    def _note_thread(self) -> None:
        # unconditional (last-writer-wins) setitem: one GIL-atomic dict
        # op, and an ident REUSED by a later thread maps to the name of
        # the thread that most recently emitted under it (the OS may
        # recycle idents of finished threads; Chrome's tid-keyed format
        # cannot distinguish them anyway)
        self._threads[threading.get_ident()] = threading.current_thread().name

    def _record(self, name: str, t0: float, t1: float,
                args: dict | None) -> None:
        self._note_thread()
        if len(self._events) == self.ring_size:
            self._dropped += 1  # append below evicts the oldest
        self._events.append(("X", name, threading.get_ident(),
                             (t0 - self._epoch) * 1e6, (t1 - t0) * 1e6,
                             args))

    # ------------------------------------------------------------- flush
    def events(self) -> list[dict]:
        """Chrome trace-event dicts for the current ring contents."""
        pid = os.getpid()
        out: list[dict] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": "deepof_tpu"},
        }]
        # snapshot first (C-level copies are GIL-atomic; iterating the
        # live deque while writers append is not)
        threads = dict(self._threads)
        events = list(self._events)
        for tid in sorted(threads):
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "args": {"name": threads[tid]}})
        for ph, name, tid, ts, dur, args in events:
            ev: dict = {"ph": ph, "name": name, "cat": "obs", "pid": pid,
                        "tid": tid, "ts": round(ts, 1)}
            if ph == "X":
                ev["dur"] = round(dur, 1)
            else:
                ev["s"] = "g"  # instants render process-wide
            if args:
                ev["args"] = args
            out.append(ev)
        return out

    def flush(self, path: str | None = None) -> str | None:
        """Atomically write the trace file; safe to call repeatedly and
        from any thread (the watchdog flushes mid-run, fit() at close —
        later flushes simply rewrite with more events)."""
        path = path or self.path
        if path is None:
            return None
        payload = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "trace_epoch_unix": self._epoch_unix,
                "ring_size": self.ring_size,
                "dropped_spans": self._dropped,
            },
        }
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path


# --------------------------------------------------------------- current
# Module-level current tracer: instrumented code calls obs.trace.span()
# unconditionally; fit() installs a real Tracer for its lifetime when
# ObsConfig.trace is on and uninstalls (back to the no-op) in its finally.
_NULL = NullTracer()
_current: Tracer | NullTracer = _NULL
_install_lock = threading.Lock()


def install(tracer: Tracer) -> Tracer:
    """Make `tracer` the process-current tracer (returns it)."""
    global _current
    with _install_lock:
        _current = tracer
    return tracer


def uninstall() -> None:
    """Back to the no-op tracer."""
    global _current
    with _install_lock:
        _current = _NULL


def current() -> Tracer | NullTracer:
    return _current


def span(name: str, **args):
    """Record a span on the current tracer (no-op when none installed)."""
    return _current.span(name, **args)


def instant(name: str, **args) -> None:
    _current.instant(name, **args)


def flush_current(path: str | None = None) -> str | None:
    """Flush the installed tracer (the watchdog's entry point)."""
    return _current.flush(path)
