"""Lock-cheap, ring-buffered span tracer -> Chrome trace-event JSON.

Every instrumented site (`train/loop.py` dispatch/eval/ckpt/rollback,
`train/metrics_log.py` fetch, `data/prefetch.py` put, `data/pipeline.py`
worker assemble) calls the module-level `span(name, **args)`; with no
tracer installed that is one global read + a shared no-op context
manager, so instrumentation costs nothing when tracing is off and the
instrumented modules never need a tracer threaded through their
constructors.

Design constraints, in order:

  - The hot path takes NO lock: completed spans are appended to a
    `collections.deque(maxlen=ring_size)` — append and the implicit
    oldest-eviction are single C-level ops, atomic under the GIL, so
    pipeline workers / prefetch / fetcher / main all record concurrently
    without contending. Memory is bounded by construction: the ring
    keeps the newest `ring_size` spans (the window that matters when a
    watchdog fires).
  - Timestamps come from `time.perf_counter()` (CLOCK_MONOTONIC —
    comparable across threads of one process), rebased to the tracer's
    construction so `ts` starts near zero.
  - `flush()` writes the Chrome trace-event format (JSON object with a
    `traceEvents` list of "X" complete events + "M" thread-name
    metadata) atomically (tmp + rename), so a viewer — or the watchdog,
    which flushes mid-run — never reads a torn file. Perfetto and
    chrome://tracing both load it directly.

Stdlib-only at import (see obs/__init__ docstring).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque

#: CPython's auto-generated thread names ("Thread-12 (handler_func)"):
#: ThreadingHTTPServer spawns one uniquely-auto-named thread per HTTP
#: request, and keying tracks by (tid, emit-time name) would otherwise
#: mint one single-span track per REQUEST once idents recycle. The
#: serial number carries no identity — collapse it so every
#: auto-named thread running the same function shares one track name,
#: while explicitly-named threads (prefetch, serve-batcher,
#: pipeline-worker-N, ...) keep the full recycle-split fix.
_AUTO_THREAD_NAME = re.compile(r"^Thread-\d+( \(.*\))?$")


class _NullSpan:
    """Shared, stateless no-op context manager (safe to re-enter from
    any number of threads at once)."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **args) -> None:
        """No-op counterpart of _Span.set."""


_NULL_SPAN = _NullSpan()


class NullTracer:
    """The uninstalled state: every operation is a no-op."""

    path: str | None = None

    def span(self, name: str, **args) -> _NullSpan:
        return _NULL_SPAN

    def instant(self, name: str, **args) -> None:
        pass

    def flush(self, path: str | None = None) -> str | None:
        return None


class _Span:
    """One live span: created by Tracer.span, records on __exit__."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict | None):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def set(self, **args) -> None:
        """Attach args discovered DURING the span (e.g. the batcher
        learns its request ids only while accumulating the batch)."""
        if self._args is None:
            self._args = {}
        self._args.update(args)

    def __exit__(self, *exc) -> bool:
        self._tracer._record(self._name, self._t0, time.perf_counter(),
                             self._args)
        return False


class Tracer:
    """Ring-buffered span recorder; see module docstring.

    path: default flush destination (conventionally
        `<log_dir>/trace.json`).
    ring_size: max retained events — spans beyond it evict the oldest
        (bounded memory; a full training run keeps its newest window).
    role / index: process identity stamped into the trace (process_name
        metadata + otherData) so obs/aggregate.py can merge many
        processes' traces into one fleet timeline — "trainer-1",
        "replica-0", "router", "coordinator".
    """

    def __init__(self, path: str | None = None, ring_size: int = 16384,
                 role: str | None = None, index: int | None = None):
        self.path = path
        self.ring_size = max(int(ring_size), 16)
        self.role = role
        self.index = index
        self._events: deque = deque(maxlen=self.ring_size)
        self._epoch = time.perf_counter()
        self._epoch_unix = time.time()
        # tid -> thread name registry (historical record: a thread whose
        # every event was evicted from the ring is still named in the
        # metadata). NOT the source of truth for event->name binding —
        # each event records its thread's name at EMIT time, so a tid
        # the OS recycled onto a later, differently-named thread cannot
        # retroactively rename earlier spans (the PR 3 last-writer-wins
        # hazard); events() splits such a tid into per-name tracks.
        self._threads: dict[int, str] = {}
        self._dropped = 0  # informational; deque eviction is implicit

    # ------------------------------------------------------------ record
    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (ph='i') — e.g. the watchdog's wedge."""
        now = time.perf_counter()
        tname = self._note_thread()
        self._events.append(("i", name, threading.get_ident(), tname,
                             (now - self._epoch) * 1e6, 0.0, args or None))

    def _note_thread(self) -> str:
        # the registry write is one GIL-atomic dict op; the RETURNED
        # name is what binds the event (emit-time capture — see __init__)
        name = threading.current_thread().name
        m = _AUTO_THREAD_NAME.match(name)
        if m:  # auto-named ephemeral: drop the per-thread serial
            name = "Thread" + (m.group(1) or "")
        self._threads[threading.get_ident()] = name
        return name

    def _record(self, name: str, t0: float, t1: float,
                args: dict | None) -> None:
        tname = self._note_thread()
        if len(self._events) == self.ring_size:
            self._dropped += 1  # append below evicts the oldest
        self._events.append(("X", name, threading.get_ident(), tname,
                             (t0 - self._epoch) * 1e6, (t1 - t0) * 1e6,
                             args))

    # ------------------------------------------------------------- flush
    def process_name(self) -> str:
        """The track label for this process in a merged fleet trace."""
        if self.role is None:
            return "deepof_tpu"
        return (self.role if self.index is None
                else f"{self.role}-{self.index}")

    def events(self) -> list[dict]:
        """Chrome trace-event dicts for the current ring contents.

        Thread tracks are keyed by (tid, emit-time name): a tid the OS
        recycled across differently-named threads splits into one track
        per name (the first name keeps the real tid; later names get
        synthetic tids), so every span renders under the thread that
        actually emitted it."""
        pid = os.getpid()
        out: list[dict] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": self.process_name()},
        }]
        # snapshot first (C-level copies are GIL-atomic; iterating the
        # live deque while writers append is not)
        threads = dict(self._threads)
        events = list(self._events)
        track: dict[tuple[int, str], int] = {}
        used: set[int] = set()
        next_synthetic = max([e[2] for e in events] + list(threads)
                             + [0]) + 1

        def tid_for(tid: int, tname: str) -> int:
            nonlocal next_synthetic
            key = (tid, tname)
            mapped = track.get(key)
            if mapped is None:
                if tid not in used:
                    mapped = tid
                else:  # recycled ident: a fresh synthetic track
                    mapped = next_synthetic
                    next_synthetic += 1
                used.add(mapped)
                track[key] = mapped
            return mapped

        body: list[dict] = []
        for ph, name, tid, tname, ts, dur, args in events:
            ev: dict = {"ph": ph, "name": name, "cat": "obs", "pid": pid,
                        "tid": tid_for(tid, tname), "ts": round(ts, 1)}
            if ph == "X":
                ev["dur"] = round(dur, 1)
            else:
                ev["s"] = "g"  # instants render process-wide
            if args:
                ev["args"] = args
            body.append(ev)
        # registry-only threads (all their events evicted) still get a
        # track name; an entry contradicting an emit-time binding maps
        # to its own synthetic track instead of renaming the real one
        for tid in sorted(threads):
            tid_for(tid, threads[tid])
        for (tid, tname), mapped in sorted(track.items(),
                                           key=lambda kv: kv[1]):
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": mapped, "args": {"name": tname}})
        out.extend(body)
        return out

    def flush(self, path: str | None = None) -> str | None:
        """Atomically write the trace file; safe to call repeatedly and
        from any thread (the watchdog flushes mid-run, fit() at close —
        later flushes simply rewrite with more events)."""
        path = path or self.path
        if path is None:
            return None
        payload = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "trace_epoch_unix": self._epoch_unix,
                "ring_size": self.ring_size,
                "dropped_spans": self._dropped,
                # process identity for obs/aggregate.py's fleet merge
                "role": self.role,
                "index": self.index,
                "pid": os.getpid(),
            },
        }
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path


# --------------------------------------------------------------- current
# Module-level current tracer: instrumented code calls obs.trace.span()
# unconditionally; fit() installs a real Tracer for its lifetime when
# ObsConfig.trace is on and uninstalls (back to the no-op) in its finally.
_NULL = NullTracer()
_current: Tracer | NullTracer = _NULL
_install_lock = threading.Lock()


def install(tracer: Tracer) -> Tracer:
    """Make `tracer` the process-current tracer (returns it)."""
    global _current
    with _install_lock:
        _current = tracer
    return tracer


class _Installed:
    """Scope guard returned by installed(); see its docstring."""

    def __init__(self, tracer: Tracer | None):
        self.tracer = tracer

    def __enter__(self) -> Tracer | None:
        return self.tracer

    def __exit__(self, *exc) -> bool:
        if self.tracer is not None:
            uninstall()
            try:
                self.tracer.flush()
            except OSError:
                pass
        return False


def installed(tracer: Tracer | None) -> _Installed:
    """Install `tracer` for the duration of a with-block and make the
    teardown STRUCTURAL: uninstall + best-effort flush on ANY exit —
    clean return, SIGTERM-driven drain, or a failure anywhere in the
    body (a bind error, a failed restore/compile). The spans leading
    into a startup failure are exactly what an early-installed tracer
    exists to capture, and the process-global current tracer must never
    outlive its run (a later run would silently record into the dead
    ring). `tracer=None` (tracing off) makes the whole block a no-op,
    so call sites need no conditional."""
    if tracer is not None:
        install(tracer)
    return _Installed(tracer)


def uninstall() -> None:
    """Back to the no-op tracer."""
    global _current
    with _install_lock:
        _current = _NULL


def current() -> Tracer | NullTracer:
    return _current


def span(name: str, **args):
    """Record a span on the current tracer (no-op when none installed)."""
    return _current.span(name, **args)


def instant(name: str, **args) -> None:
    _current.instant(name, **args)


def flush_current(path: str | None = None) -> str | None:
    """Flush the installed tracer (the watchdog's entry point)."""
    return _current.flush(path)
