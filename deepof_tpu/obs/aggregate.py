"""Cross-process trace aggregation: one fleet timeline from a run dir.

A fleet drill (`serve --replicas N`), an elastic pool (`train --elastic
N`), or any supervised run leaves a TREE of per-process observability
artifacts under its log dir:

    <run>/trace.json  heartbeat.json  metrics.jsonl          (supervisor)
    <run>/replica-0/trace.json  heartbeat.json  metrics.jsonl
    <run>/replica-1/...
    <run>/host-2/...

Each artifact is single-process by construction (PR 3): no tool could
answer "where did request X spend its time" across the router hop and
the replica's batcher, or see a failover replay as one timeline. This
module merges the whole tree into ONE Perfetto/chrome://tracing-loadable
trace:

  Process tracks — every process dir becomes its own pid track, named
      from the tracer's (role, index) stamp (obs/trace.py otherData)
      with the original pid and relative path preserved; tids stay
      process-local (pids are remapped to small distinct values, so
      collisions between a recycled OS pid in two dirs are impossible).

  One clock — each tracer's timestamps are relative to its OWN
      monotonic construction epoch; the stamp records that epoch's wall
      time (`trace_epoch_unix`), so every event rebases onto a shared
      zero (the earliest epoch in the tree). Wall-clock skew between
      processes ON ONE HOST is bounded by the time.time() resolution —
      good enough to see a router span enclose its replica's spans.

  Flow arrows — spans carrying a `request_id` (or a batched
      `request_ids` list) in their args are chained per request id with
      Chrome flow events (ph s/t/f, id = the request id): the router's
      `route` span connects to the replica's `serve_enqueue ->
      serve_batch -> serve_dispatch -> serve_postprocess`, so one
      request's journey across processes renders as one arrowed path —
      failover replays show as a fan-out from the same id.

  Context events — each process's heartbeat.json becomes an instant
      event (final counters at its wall time), and its metrics.jsonl
      non-train records (warn / serve / elastic / eval) become instant
      markers, so "replica-1 evicted" sits ON the timeline next to the
      spans it explains.

Stdlib-only (obs/__init__ discipline): aggregation runs next to a live
fleet without initializing any backend. `tools/trace_summary.py
--merge` is the headless CLI face.
"""

from __future__ import annotations

import json
import os
from collections import defaultdict

#: metrics.jsonl kinds rendered as instant markers (train records are
#: periodic bulk data, not timeline landmarks).
_MARKER_KINDS = ("warn", "serve", "elastic", "eval", "info")
#: cap on instant markers per process (a long run's metrics.jsonl must
#: not dwarf the span timeline; the newest markers win)
_MAX_MARKERS = 512

_ARTIFACTS = ("trace.json", "heartbeat.json", "metrics.jsonl")


def discover_processes(run_dir: str) -> list[dict]:
    """Process dirs of a supervised run: the run dir itself (the
    supervisor) plus its IMMEDIATE subdirectories holding at least one
    observability artifact. Returns [{"dir", "rel", "role", "index"}]
    supervisor-first, then by name. Depth is deliberately bounded at 1:
    supervised children (fleet replicas, elastic trainer hosts) are
    only ever direct subdirs, and an unbounded walk would enumerate a
    co-located checkpoint tree on every `tail --follow` tick — and
    adopt any unrelated nested dir that happens to hold a metrics file
    as a phantom "child". Role/index prefer the tracer's own stamp
    (read later, from trace.json); this infers a fallback from the
    directory naming conventions (replica-N = fleet replica, host-N =
    elastic trainer, the root = the supervisor/router/coordinator)."""
    run_dir = os.path.abspath(run_dir)

    def has_artifact(d: str) -> bool:
        return any(os.path.isfile(os.path.join(d, a)) for a in _ARTIFACTS)

    out = []
    if has_artifact(run_dir):
        out.append({"dir": run_dir, "rel": "", "role": "supervisor",
                    "index": None})
    try:
        children = sorted(e.name for e in os.scandir(run_dir)
                          if e.is_dir(follow_symlinks=False))
    except OSError:
        children = []
    for base in children:
        d = os.path.join(run_dir, base)
        if not has_artifact(d):
            continue
        role, index = "process", None
        if base.startswith("replica-"):
            role, index = "replica", _int_suffix(base)
        elif base.startswith("host-"):
            role, index = "trainer", _int_suffix(base)
        out.append({"dir": d, "rel": base, "role": role, "index": index})
    return out


def _int_suffix(name: str):
    try:
        return int(name.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return None


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _load_jsonl(path: str) -> list[dict]:
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail from a killed process
    except OSError:
        pass
    return records


def _request_ids(args: dict | None) -> list:
    """Every request id a span's args name (single or batched)."""
    if not args:
        return []
    out = []
    rid = args.get("request_id")
    if rid is not None:
        out.append(rid)
    rids = args.get("request_ids")
    if isinstance(rids, (list, tuple)):
        out.extend(r for r in rids if r is not None)
    return out


def aggregate_run(run_dir: str, out_path: str | None = None) -> dict:
    """Merge every process's trace/heartbeat/metrics under `run_dir`
    into one Chrome trace (written to `out_path`, default
    `<run_dir>/trace_merged.json`) and return the summary dict:

        {"path", "processes": [{"name", "rel", "pid", "orig_pid",
          "spans", "markers"}], "spans", "flows",
         "request_ids", "requests_correlated"}

    `requests_correlated` counts request ids whose spans appear in >= 2
    distinct processes — the cross-process correlation the plane exists
    for."""
    procs = discover_processes(run_dir)
    if not procs:
        raise FileNotFoundError(
            f"no trace.json/heartbeat.json/metrics.jsonl anywhere under "
            f"{run_dir!r} — is this a run's --log-dir?")

    # pass 1: load + establish the shared clock zero
    epochs = []
    for p in procs:
        p["trace"] = _load_json(os.path.join(p["dir"], "trace.json"))
        p["heartbeat"] = _load_json(os.path.join(p["dir"],
                                                 "heartbeat.json"))
        p["records"] = _load_jsonl(os.path.join(p["dir"], "metrics.jsonl"))
        other = (p["trace"] or {}).get("otherData", {})
        if other.get("role"):
            p["role"] = other["role"]
            if other.get("index") is not None:
                p["index"] = other["index"]
        p["orig_pid"] = other.get("pid")
        epoch = other.get("trace_epoch_unix")
        p["epoch"] = epoch if isinstance(epoch, (int, float)) else None
        if p["epoch"] is not None:
            epochs.append(p["epoch"])
        for r in p["records"]:
            t = r.get("time")
            if isinstance(t, (int, float)):
                epochs.append(t)
    zero = min(epochs) if epochs else 0.0

    merged: list[dict] = []
    spans_by_rid: dict = defaultdict(list)
    summary: dict = {"path": None, "processes": [], "spans": 0,
                     "flows": 0, "request_ids": 0,
                     "requests_correlated": 0}

    for i, p in enumerate(procs):
        pid = i + 1  # small distinct pids: OS pid reuse across dirs is
        #              irrelevant, and Perfetto sorts tracks stably
        name = (p["role"] if p["index"] is None
                else f"{p['role']}-{p['index']}")
        label = name
        if p["orig_pid"] is not None:
            label += f" (pid {p['orig_pid']})"
        if p["rel"]:
            label += f" [{p['rel']}]"
        merged.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        merged.append({"ph": "M", "name": "process_sort_index",
                       "pid": pid, "tid": 0, "args": {"sort_index": i}})
        # rebase: event ts are relative to the tracer's own epoch
        offset_us = ((p["epoch"] - zero) * 1e6
                     if p["epoch"] is not None else 0.0)
        n_spans = 0
        for ev in (p["trace"] or {}).get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    continue  # replaced by the labeled track above
                merged.append(ev)
                continue
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = round(ev["ts"] + offset_us, 1)
            merged.append(ev)
            if ev.get("ph") == "X":
                n_spans += 1
                for rid in _request_ids(ev.get("args")):
                    # only STRING ids correlate across processes: the
                    # router's X-Request-Id embeds its pid + a sequence,
                    # so it is fleet-unique by construction. Integer ids
                    # are each engine's process-LOCAL itertools counter —
                    # two replicas both have a request 1 — so they are
                    # namespaced per process (intra-process chains only,
                    # never a false cross-process arrow).
                    key = rid if isinstance(rid, str) else f"p{pid}#{rid}"
                    spans_by_rid[key].append(ev)
        # heartbeat: one instant with the final counters at its wall time
        n_markers = 0
        hb = p["heartbeat"]
        if hb is not None and isinstance(hb.get("time"), (int, float)):
            merged.append({"ph": "i", "name": "heartbeat", "cat": "obs",
                           "pid": pid, "tid": 0, "s": "p",
                           "ts": round((hb["time"] - zero) * 1e6, 1),
                           "args": hb})
            n_markers += 1
        # metrics.jsonl landmarks (newest first under the cap)
        markers = [r for r in p["records"]
                   if r.get("kind") in _MARKER_KINDS
                   and isinstance(r.get("time"), (int, float))]
        for r in markers[-_MAX_MARKERS:]:
            args = {k: v for k, v in r.items() if k != "time"}
            msg = args.get("message")
            if isinstance(msg, str) and len(msg) > 300:
                args["message"] = msg[:300] + "..."
            merged.append({"ph": "i", "name": f"metrics_{r['kind']}",
                           "cat": "obs", "pid": pid, "tid": 0, "s": "p",
                           "ts": round((r["time"] - zero) * 1e6, 1),
                           "args": args})
            n_markers += 1
        summary["processes"].append({
            "name": name, "rel": p["rel"], "pid": pid,
            "orig_pid": p["orig_pid"], "spans": n_spans,
            "markers": n_markers})
        summary["spans"] += n_spans

    # flow arrows: chain each request id's spans in time order
    n_flows = 0
    n_corr = 0
    for rid, evs in sorted(spans_by_rid.items()):
        if len(evs) < 2:
            continue
        evs.sort(key=lambda e: e.get("ts", 0.0))
        if len({e["pid"] for e in evs}) >= 2:
            n_corr += 1
        last = len(evs) - 1
        for j, ev in enumerate(evs):
            ph = "s" if j == 0 else ("f" if j == last else "t")
            flow = {"ph": ph, "cat": "request", "name": "request",
                    "id": rid, "pid": ev["pid"], "tid": ev["tid"],
                    "ts": ev["ts"]}
            if ph == "f":
                flow["bp"] = "e"  # bind to the enclosing slice
            merged.append(flow)
            n_flows += 1
    summary["flows"] = n_flows
    summary["request_ids"] = len(spans_by_rid)
    summary["requests_correlated"] = n_corr

    out_path = out_path or os.path.join(os.path.abspath(run_dir),
                                        "trace_merged.json")
    payload = {"traceEvents": merged, "displayTimeUnit": "ms",
               "otherData": {"merged_from": [p["rel"] or "." for p in
                                             procs],
                             "clock_zero_unix": zero}}
    d = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{os.path.basename(out_path)}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, out_path)
    summary["path"] = out_path
    return summary


# ------------------------------------------------------- headless views


def per_process_table(merged_path: str) -> dict[str, dict[str, dict]]:
    """{process -> {span name -> {"count", "total_ms", "max_ms"}}} from
    a merged trace — trace_summary --merge's first block."""
    payload = _load_json(merged_path) or {}
    events = payload.get("traceEvents", [])
    names = {e["pid"]: e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    table: dict[str, dict[str, dict]] = defaultdict(dict)
    for e in events:
        if e.get("ph") != "X" or not isinstance(e.get("dur"),
                                                (int, float)):
            continue
        proc = names.get(e.get("pid"), str(e.get("pid")))
        row = table[proc].setdefault(e.get("name", "?"),
                                     {"count": 0, "total_ms": 0.0,
                                      "max_ms": 0.0})
        ms = float(e["dur"]) / 1e3
        row["count"] += 1
        row["total_ms"] += ms
        row["max_ms"] = max(row["max_ms"], ms)
    for proc in table.values():
        for row in proc.values():
            row["total_ms"] = round(row["total_ms"], 3)
            row["max_ms"] = round(row["max_ms"], 3)
    return dict(table)


def per_request_table(merged_path: str, limit: int = 20) -> list[dict]:
    """Per-request-id journeys from a merged trace, slowest first:
    [{"request_id", "processes", "spans": [{"process", "name",
    "dur_ms"}], "total_ms"}] — trace_summary --merge's second block."""
    payload = _load_json(merged_path) or {}
    events = payload.get("traceEvents", [])
    names = {e["pid"]: e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    by_rid: dict = defaultdict(list)
    for e in events:
        if e.get("ph") != "X":
            continue
        for rid in _request_ids(e.get("args")):
            # same namespacing rule as aggregate_run: integer ids are
            # process-local counters, never cross-process identities
            key = rid if isinstance(rid, str) else f"p{e.get('pid')}#{rid}"
            by_rid[key].append(e)
    rows = []
    for rid, evs in by_rid.items():
        evs.sort(key=lambda e: e.get("ts", 0.0))
        spans = [{"process": names.get(e.get("pid"), str(e.get("pid"))),
                  "name": e.get("name", "?"),
                  "dur_ms": round(float(e.get("dur", 0.0)) / 1e3, 3)}
                 for e in evs]
        rows.append({"request_id": rid,
                     "processes": len({e["pid"] for e in evs}),
                     "spans": spans,
                     "total_ms": round(sum(s["dur_ms"] for s in spans),
                                       3)})
    rows.sort(key=lambda r: -r["total_ms"])
    return rows[:max(int(limit), 1)]
