"""Minimal repro: GSPMD spatial-sharding gradient mis-scaling.

Evidence behind `parallel/spatial.py`'s MIN_ROWS_PER_SHARD=2 fence. A
stride-2 SAME-padded conv chain is differentiated twice — input batch
replicated vs. H sharded over the "spatial" mesh axis — and per-layer
kernel-gradient ratios are printed.

Findings on the 8-device CPU mesh (jax 0.9 era; mechanism is the SPMD
partitioner, not the backend):

  - If every level keeps >= 2 rows per spatial shard, sharded and
    replicated gradients agree to float tolerance in every configuration
    tested (spatial 2 and 4, depths 2-5).
  - Uneven deep levels are safe when the >=2-rows bound holds: probed
    deepest levels of 5 rows over 2 shards and 10 over 4 (including a
    1-real-row last shard from ceil-partitioning) are all exact.
  - Once the chain reaches a level with exactly 1 row per shard
    (H_level == spatial), the backward halo exchange of that level's conv
    mis-scales the input cotangent: EVERY upstream conv's gradient comes
    back x4 (spatial=2) while all downstream layers stay exact. With
    spatial=4 the same 1-row/shard collapse happens to come back clean,
    but a deeper sub-row collapse (H_level < spatial) shows x2 — the
    factor depends on GSPMD's per-level partitioning choices, so the only
    robust contract is the 2-rows-per-shard floor.
  - Very small inputs (e.g. H=8, 2 levels) escape the bug because GSPMD
    replicates the tiny levels instead of partitioning them.

Run: python tools/halo_grad_repro.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepof_tpu.core.hostmesh import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402
from flax import linen as nn  # noqa: E402


def make_stack(n_down: int):
    class Stack(nn.Module):
        @nn.compact
        def __call__(self, x):
            for i in range(n_down):
                x = nn.elu(nn.Conv(4, (3, 3), strides=(2, 2), padding="SAME",
                                   name=f"c{i}")(x))
            return nn.Conv(2, (3, 3), padding="SAME", name="head")(x)

    return Stack()


def probe(spatial: int, h: int, n_down: int, w: int = 32) -> None:
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8 // spatial, spatial),
                ("data", "spatial"))
    model = make_stack(n_down)
    x = jnp.asarray(np.random.RandomState(0).rand(8 // spatial, h, w, 3),
                    jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]

    def loss(p, xx, shard):
        if shard:
            xx = jax.lax.with_sharding_constraint(
                xx, NamedSharding(mesh, P(("data",), "spatial")))
        return (model.apply({"params": p}, xx) ** 2).sum()

    gr = jax.device_get(
        jax.jit(jax.grad(lambda p, xx: loss(p, xx, False)))(params, x))
    gs = jax.device_get(
        jax.jit(jax.grad(lambda p, xx: loss(p, xx, True)))(params, x))
    coarsest = h >> n_down
    print(f"spatial={spatial} H={h} depth={n_down} coarsestH={coarsest} "
          f"({coarsest / spatial:.1f} rows/shard):")
    for name in sorted(gr):
        r = np.asarray(gr[name]["kernel"]).ravel()
        s = np.asarray(gs[name]["kernel"]).ravel()
        m = np.abs(r) > 1e-6 * np.abs(r).max()
        ratio = float(np.median(np.abs(s[m] / r[m])))
        err = float(np.abs(s - r).max() / np.abs(r).max())
        flag = "  <-- MISMATCH" if err > 1e-3 else ""
        print(f"  {name:6s} median|g_sharded/g_repl|={ratio:8.4f} "
              f"relerr={err:.2e}{flag}")


if __name__ == "__main__":
    # broken: a 1-row/shard level at spatial=2 -> every upstream grad x4
    probe(2, 64, 5)
    probe(2, 32, 4)
    # clean: 2 rows/shard at the coarsest level
    probe(2, 128, 5)
    probe(4, 64, 3)
    # partitioner-choice-dependent: 1-row/shard clean at spatial=4, but a
    # sub-row collapse shows x2
    probe(4, 32, 3)
    probe(4, 32, 4)
    # uneven deepest levels at >= 2 average rows/shard: exact
    probe(2, 160, 5)
    probe(2, 80, 4)
    probe(4, 160, 4)
