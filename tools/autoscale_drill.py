#!/usr/bin/env python
"""Headless fleet-autoscaling chaos drill (DESIGN.md "Supervision
plane"; tools/elastic_drill.py lineage).

Runs a live autoscaling serving fleet (`deepof_tpu serve --autoscale`,
jax-free fake-executor replicas) through the ISSUE 14 acceptance
scenario, end to end through the real CLI, HTTP, router, supervisor and
control loop:

  1. burst a min_replicas pool with closed-loop clients — the router
     SHEDS (sheds_before), the autoscaler scales up;
  2. the same burst against the scaled pool — sheds_after must
     collapse to ~0;
  3. sustained idle walks the pool back down via graceful drain
     (retired counts, ZERO evictions in the control run);
  4. with --fault kill (default), a ready replica is SIGKILLed while
     the pool is mid-scale-down: every probe request must still
     resolve to a 200 via failover/respawn (bounded client retries,
     zero silent drops), and `deepof_tpu tail` exits 4 surfacing the
     crash — while the fault-free control exits 0, pinning that
     RETIREMENT is not sickness.

Emits one pinned-schema JSON verdict; exit code 0 iff the drill
completed. `--fault none` runs the control.

    python tools/autoscale_drill.py --max-replicas 3 --clients 8
"""

import argparse
import http.client
import itertools
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from serve_bench import _drive_timed  # noqa: E402 - single owner of the
#   closed-loop timed client pool (its "drops" semantics are the
#   zero-silent-drops ledger both tools pin; one copy, not two)

#: Pinned output schema — downstream tooling (BENCH recorders, CI
#: gates) may rely on exactly these keys existing.
REQUIRED_KEYS = (
    "max_replicas", "fault", "requests", "errors", "drops",
    "sheds_before", "sheds_after", "scale_ups", "scale_downs",
    "retired", "evictions", "peak_replicas", "final_replicas",
    "kill_requests", "resolved_after_kill", "completed", "rc",
    "tail_rc", "wall_s",
)


def _body() -> bytes:
    import base64

    import cv2
    import numpy as np

    rng = np.random.RandomState(0)
    imgs = []
    for _ in range(2):
        ok, buf = cv2.imencode(
            ".png", rng.randint(1, 255, (30, 60, 3), dtype=np.uint8))
        assert ok
        imgs.append(base64.b64encode(buf.tobytes()).decode())
    return json.dumps({"prev": imgs[0], "next": imgs[1]}).encode()


def _post(port: int, body: bytes, timeout: float = 30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/flow", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _healthz(port: int) -> dict:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", "/healthz")
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def _sheds(hz: dict) -> int:
    return int(hz.get("fleet_shed") or 0) + int(hz.get("fleet_unavailable")
                                                or 0)


def run_drill(max_replicas: int = 3, clients: int = 8,
              burst_s: float = 6.0, idle_s: float = 25.0,
              fault: str = "kill", log_dir: str | None = None,
              timeout_s: float = 300.0) -> dict:
    """One drill run; returns the REQUIRED_KEYS dict."""
    if log_dir is None:
        log_dir = tempfile.mkdtemp(prefix="autoscale_drill_")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    max_in_flight = 4
    cmd = [sys.executable, "-m", "deepof_tpu", "serve", "--preset",
           "flyingchairs", "--autoscale", "--max-replicas",
           str(max_replicas), "--log-dir", log_dir,
           "--set", "data.image_size=(64,64)",
           "--set", "data.gt_size=(64,64)",
           "--set", "serve.fake_exec_ms=30", "--set", "serve.max_batch=2",
           "--set", "serve.host=127.0.0.1", "--set", "serve.port=0",
           "--set", f"serve.fleet.max_in_flight={max_in_flight}",
           "--set", "serve.fleet.poll_s=0.1",
           "--set", "serve.fleet.stale_after_s=10",
           "--set", "serve.fleet.term_grace_s=3",
           "--set", "serve.fleet.drain_timeout_s=3",
           "--set", "serve.fleet.backoff_s=0.1",
           "--set", "serve.fleet.autoscale_period_s=0.25",
           "--set", "serve.fleet.autoscale_up_after_s=0.5",
           "--set", "serve.fleet.autoscale_down_after_s=2.0",
           "--set", "serve.fleet.autoscale_up_cooldown_s=1.0",
           "--set", "serve.fleet.autoscale_down_cooldown_s=2.0",
           "--set", "obs.heartbeat_period_s=0.25"]
    t0 = time.monotonic()
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env,
                            cwd=REPO)
    out: dict = {"max_replicas": max_replicas, "fault": fault,
                 "log_dir": log_dir}
    killed_pid = None
    # --timeout backstop: every phase below is individually bounded
    # EXCEPT the announce readline — and a wedged fleet could stretch
    # the bounded ones past any CI budget. Killing the serve process
    # unblocks whatever is waiting (readline EOFs, probes refuse) and
    # the drill falls through to the completed=false verdict.
    expired = threading.Event()

    def _expire() -> None:
        expired.set()
        try:
            proc.kill()
        except OSError:
            pass

    watchdog = threading.Timer(max(float(timeout_s), 1.0), _expire)
    watchdog.daemon = True
    watchdog.start()
    try:
        line = proc.stdout.readline()
        try:
            port = int(json.loads(line)["serving"].rsplit(":", 1)[1]
                       .rstrip("/"))
        except (ValueError, KeyError, json.JSONDecodeError):
            raise RuntimeError(f"no serving announce line: {line!r}")
        body = _body()

        # phase 1: burst the floor pool — sheds + scale-up
        shed0 = _sheds(_healthz(port))
        burst1 = _drive_timed(port, body, clients, burst_s)
        sheds_before = _sheds(_healthz(port)) - shed0

        # hold trickle until scaled capacity can absorb the burst
        deadline = time.monotonic() + 60
        hold = {"ok": 0, "errors": 0, "drops": 0}
        while time.monotonic() < deadline:
            hz = _healthz(port)
            ready = int(hz.get("fleet_ready") or 0)
            if (ready >= max_replicas
                    or ready * max_in_flight > clients):
                break
            chunk = _drive_timed(port, body, 2, 0.5)
            for k in hold:
                hold[k] += chunk[k]
        peak = int(hz.get("fleet_replicas") or 0)

        # phase 2: the same burst against the scaled pool
        shed1 = _sheds(_healthz(port))
        burst2 = _drive_timed(port, body, clients, burst_s)
        sheds_after = _sheds(_healthz(port)) - shed1
        peak = max(peak, int(_healthz(port).get("fleet_replicas") or 0))

        # phase 3: sustained idle -> graceful scale-down
        deadline = time.monotonic() + idle_s
        hz = _healthz(port)
        while time.monotonic() < deadline:
            hz = _healthz(port)
            if int(hz.get("fleet_autoscale_down") or 0) >= 1:
                break
            time.sleep(0.25)

        # phase 4 (--fault kill): SIGKILL a ready replica while the
        # pool is mid-scale-down; every probe must still resolve
        kill_requests = 0
        resolved = 0
        if fault == "kill":
            # the pool is actively scaling down: a victim picked from a
            # snapshot can finish its graceful retirement before the
            # signal lands — re-pick from a FRESH /healthz read until a
            # kill sticks (bounded; the probes below pin failover even
            # when the window closes with no victim left)
            for _ in range(10):
                victim = next((r for r in _healthz(port).get("replicas", [])
                               if r.get("state") == "ready"
                               and r.get("pid")), None)
                if victim is None:
                    break
                try:
                    os.kill(victim["pid"], signal.SIGKILL)
                    killed_pid = victim["pid"]
                    break
                except (ProcessLookupError, PermissionError):
                    continue
            kill_requests = 30
            for _ in range(kill_requests):
                for attempt in range(40):  # bounded client retry
                    try:
                        status, _payload = _post(port, body, timeout=15)
                    except Exception:  # noqa: BLE001 - retried
                        status = -1
                    if status == 200:
                        resolved += 1
                        break
                    time.sleep(0.25)

        # let the pool settle back toward the floor, then read final
        # counters and stop the fleet gracefully
        deadline = time.monotonic() + idle_s
        while time.monotonic() < deadline:
            hz = _healthz(port)
            if int(hz.get("fleet_replicas") or 0) <= 1:
                break
            time.sleep(0.25)
        hz = _healthz(port)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)

        requests = (burst1["ok"] + burst1["errors"] + burst2["ok"]
                    + burst2["errors"] + hold["ok"] + hold["errors"]
                    + kill_requests)
        drops = burst1["drops"] + burst2["drops"] + hold["drops"]
        tail = subprocess.run(
            [sys.executable, "-m", "deepof_tpu", "tail", "--log-dir",
             log_dir],
            capture_output=True, text=True, timeout=60, env=env, cwd=REPO)
        expected_tail = 4 if fault == "kill" else 0
        out.update({
            "requests": requests,
            "errors": burst1["errors"] + burst2["errors"] + hold["errors"],
            "drops": drops,
            "sheds_before": sheds_before,
            "sheds_after": sheds_after,
            "scale_ups": int(hz.get("fleet_autoscale_up") or 0),
            "scale_downs": int(hz.get("fleet_autoscale_down") or 0),
            "retired": int(hz.get("fleet_retired") or 0),
            "evictions": int(hz.get("fleet_evictions") or 0),
            "peak_replicas": peak,
            "final_replicas": int(hz.get("fleet_replicas") or 0),
            "kill_requests": kill_requests,
            "resolved_after_kill": resolved,
            "rc": rc,
            "tail_rc": tail.returncode,
            "wall_s": round(time.monotonic() - t0, 2),
        })
        out["completed"] = bool(
            rc == 0
            and out["scale_ups"] >= 1 and out["scale_downs"] >= 1
            and out["retired"] >= 1
            and sheds_before > 0 and sheds_after < sheds_before
            and drops == 0
            and resolved == kill_requests
            and out["tail_rc"] == expected_tail
            and (fault == "kill" or out["evictions"] == 0))
        return out
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        if "completed" not in out:
            out.setdefault("rc", proc.returncode)
            out["completed"] = False
            try:
                out["stderr_tail"] = proc.stderr.read()[-1500:]
            except (OSError, ValueError):
                pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--max-replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=8,
                    help="burst width (closed-loop clients)")
    ap.add_argument("--burst-s", type=float, default=6.0)
    ap.add_argument("--idle-s", type=float, default=25.0,
                    help="idle window for the scale-down legs")
    ap.add_argument("--fault", default="kill", choices=("kill", "none"),
                    help="kill = SIGKILL a ready replica mid-scale-down "
                         "(tail must exit 4); none = fault-free control "
                         "(zero evictions, tail must exit 0)")
    ap.add_argument("--log-dir", default=None,
                    help="run directory (default: a fresh temp dir)")
    ap.add_argument("--timeout", type=float, default=300.0)
    args = ap.parse_args(argv)

    out = run_drill(max_replicas=args.max_replicas, clients=args.clients,
                    burst_s=args.burst_s, idle_s=args.idle_s,
                    fault=args.fault, log_dir=args.log_dir,
                    timeout_s=args.timeout)
    missing = [k for k in REQUIRED_KEYS if k not in out]
    assert not missing, f"drill output missing pinned keys: {missing}"
    print(json.dumps(out, indent=2))
    return 0 if out["completed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
