#!/bin/sh
# Tunnel liveness watcher. Probes backend init in throwaway subprocesses
# (an in-process wedged init can never be retried — see DESIGN.md
# "Benchmark honesty") and appends a timestamped record per attempt, so a
# round with the tunnel down all session leaves checked-in evidence of
# continuous outage (VERDICT r02 item 1). On success it touches
# /tmp/tunnel_up and keeps probing at a slower cadence so the log also
# records when a live window closes. /tmp/tunnel_up is a session-local
# signal for the OPERATOR (poll it between CPU tasks to know when the
# TPU-gated queue — perf_probe, synthetic_fit — can run); no repo code
# reads it, and it is only meaningful while this watcher is running.
LOG="${1:-/root/repo/artifacts/tunnel_probe_r03.log}"
INTERVAL="${2:-300}"
mkdir -p "$(dirname "$LOG")"
while :; do
    t0=$(date -u +%Y-%m-%dT%H:%M:%SZ)
    out=$(timeout 120 python -c "import jax; print(jax.devices())" 2>&1)
    rc=$?
    if [ "$rc" -eq 0 ]; then
        echo "$t0 rc=0 UP $(echo "$out" | tail -1)" >> "$LOG"
        touch /tmp/tunnel_up
        sleep 600
    else
        echo "$t0 rc=$rc DOWN" >> "$LOG"
        rm -f /tmp/tunnel_up
        sleep "$INTERVAL"
    fi
done
