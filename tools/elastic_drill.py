#!/usr/bin/env python
"""Headless elastic-training chaos drill (DESIGN.md "Elastic training").

Runs a small N-virtual-host elastic training run (`deepof_tpu train
--elastic N`) with a seeded `host_loss` SIGKILL of one host mid-run —
the production preemption scenario, end to end, on one machine — and
emits a pinned-schema JSON verdict: did the run complete to the target
step with zero operator action, how many re-forms it took, how much
work was lost, and how long recovery took (loss detection -> every
survivor training again).

This is the CI-shaped face of the acceptance drill in
tests/test_elastic.py (slow tier) and the source of the elastic rows in
the BENCH_r0x.json cpu proxies:

    python tools/elastic_drill.py --hosts 3 --target 10 \
        --kill-host 1 --kill-step 4

Exit code 0 iff the drill completed (target reached, checkpoints
verify); 1 otherwise. `--fault none` runs the fault-free control (the
supervision layer must never misjudge a healthy host: reforms == 0).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: Pinned output schema — downstream tooling (BENCH recorders, CI
#: gates) may rely on exactly these keys existing.
REQUIRED_KEYS = (
    "hosts", "target_step", "fault", "completed", "rc",
    "generation", "reforms", "lost_hosts", "steps_lost", "resumed_step",
    "max_step", "recovery_wall_s", "wall_s", "ckpt_ok", "tail_rc",
)


def run_drill(hosts: int = 3, target: int = 10, kill_host: int = 1,
              kill_step: int = 4, ckpt_every: int = 3,
              fault: str = "host_loss", log_dir: str | None = None,
              timeout_s: float = 900.0) -> dict:
    """One drill run; returns the REQUIRED_KEYS dict."""
    own_dir = log_dir is None
    if own_dir:
        log_dir = tempfile.mkdtemp(prefix="elastic_drill_")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    cmd = [sys.executable, "-m", "deepof_tpu", "train", "--preset",
           "flyingchairs", "--synthetic", "--elastic", str(hosts),
           "--max-steps", str(target), "--log-dir", log_dir,
           "--set", "model=flownet_s", "--set", "width_mult=0.25",
           "--set", "data.batch_size=4", "--set", "train.eval_batch_size=4",
           "--set", "train.log_every=1", "--set", "train.eval_every=0",
           "--set", "train.ckpt_every_epochs=1000000",
           "--set", f"train.ckpt_every_steps={ckpt_every}",
           "--set", "obs.heartbeat_period_s=0.25",
           "--set", "elastic.poll_s=0.2",
           "--set", "elastic.stale_after_s=10",
           "--set", "elastic.wedge_after_s=30",
           # skew limiter <= ckpt cadence so the re-form's discarded
           # tail stays within the checkpoint period by construction
           "--set", f"elastic.sync_ahead={max(min(ckpt_every - 1, 4), 1)}"]
    if fault != "none":
        cmd += ["--set", "resilience.faults.enabled=true",
                "--set", f"resilience.faults.{fault}_at=({kill_host},)",
                "--set", f"resilience.faults.host_fault_step={kill_step}"]
    t0 = time.monotonic()
    res = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=timeout_s, env=env, cwd=REPO)
    wall = time.monotonic() - t0
    lines = [ln for ln in res.stdout.strip().splitlines() if ln]
    try:
        summary = json.loads(lines[-1]) if lines else {}
    except json.JSONDecodeError:
        summary = {}

    from deepof_tpu.resilience import verify as ckpt_verify

    rep = ckpt_verify.verify_run(log_dir)
    # success demands a manifest-VERIFIED checkpoint at/past the target
    # (a torn, manifest-less final save must not pass the drill)
    ckpt_ok = bool(rep["ok"]) and (max(rep["valid_steps"],
                                       default=0) >= target)
    tail = subprocess.run(
        [sys.executable, "-m", "deepof_tpu", "tail", "--log-dir", log_dir],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO)
    out = {
        "hosts": hosts,
        "target_step": target,
        "fault": fault,
        "completed": bool(summary.get("completed"))
        and res.returncode == 0 and ckpt_ok,
        "rc": res.returncode,
        "generation": summary.get("elastic_generation"),
        "reforms": summary.get("elastic_reforms"),
        "lost_hosts": summary.get("elastic_lost_hosts"),
        "steps_lost": summary.get("elastic_steps_lost"),
        "resumed_step": summary.get("elastic_resumed_step"),
        "max_step": summary.get("elastic_max_step"),
        # loss detection -> every survivor running again (the
        # coordinator stamps it when the re-formed world is back)
        "recovery_wall_s": summary.get("elastic_last_reform_s"),
        "wall_s": round(wall, 2),
        "ckpt_ok": ckpt_ok,
        "tail_rc": tail.returncode,
        "log_dir": log_dir,
    }
    if res.returncode != 0:
        out["stderr_tail"] = res.stderr[-1500:]
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--hosts", type=int, default=3)
    ap.add_argument("--target", type=int, default=10,
                    help="absolute target step")
    ap.add_argument("--kill-host", type=int, default=1)
    ap.add_argument("--kill-step", type=int, default=4,
                    help="arm the fault at this global step")
    ap.add_argument("--ckpt-every", type=int, default=3,
                    help="checkpoint cadence (bounds lost work)")
    ap.add_argument("--fault", default="host_loss",
                    choices=("host_loss", "host_wedge", "preempt_notice",
                             "none"),
                    help="which host chaos site to arm (none = "
                         "fault-free control: reforms must be 0)")
    ap.add_argument("--log-dir", default=None,
                    help="run directory (default: a fresh temp dir)")
    ap.add_argument("--timeout", type=float, default=900.0)
    args = ap.parse_args(argv)

    out = run_drill(hosts=args.hosts, target=args.target,
                    kill_host=args.kill_host, kill_step=args.kill_step,
                    ckpt_every=args.ckpt_every, fault=args.fault,
                    log_dir=args.log_dir, timeout_s=args.timeout)
    missing = [k for k in REQUIRED_KEYS if k not in out]
    assert not missing, f"drill output missing pinned keys: {missing}"
    print(json.dumps(out, indent=2))
    return 0 if out["completed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
