"""Input-dependence diagnostic for a synthetic_fit checkpoint lineage.

The r04/r05 fitting studies needed a sharper signal than the AEE curve:
a run parked at the zero-flow level can be (a) collapsed to constant
near-zero output (no input-dependence — the S-trunk failure mode,
DESIGN.md "Learning evidence" items 6-7), or (b) predicting real but
misaligned structure. This tool separates them: it restores the newest
checkpoint of a `tools/synthetic_fit.py` lineage and reports

  - spatial-pattern correlation  corr(pred - mean, gt - mean) within
    samples (does the net predict the FIELD's shape?),
  - per-sample-mean correlation  (does it predict the global motion?),
  - magnitude stats (|pred| vs |gt| — collapse shows as |pred| ~ 0).

Run with the SAME model/data flags as the fit it inspects, e.g.:
    python tools/fit_corr.py --model flownet_s --width-mult 0.5 \
        --style affine --blobs 40 --max-shift 4 \
        --out artifacts/synthetic_fit_cpu_s_affine.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepof_tpu.core.hostmesh import force_cpu_devices  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=1)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--feature-scale", type=int, default=16)
    ap.add_argument("--max-shift", type=float, default=4.0)
    ap.add_argument("--style", default="blobs",
                    choices=("noise", "blobs", "affine"))
    ap.add_argument("--blobs", type=int, default=8)
    ap.add_argument("--width-mult", type=float, default=1.0)
    ap.add_argument("--model", default="flownet_s",
                    choices=("flownet_s", "flownet_c", "inception_v3",
                             "vgg16"))
    ap.add_argument("--max-disp", type=int, default=4)
    ap.add_argument("--corr-stride", type=int, default=2)
    ap.add_argument("--num-train", type=int, default=8192)
    ap.add_argument("--out", required=True,
                    help="the fit's --out jsonl; the checkpoint lineage "
                         "lives at <out>.ckpt")
    args = ap.parse_args()

    force_cpu_devices(args.devices)
    import jax.numpy as jnp
    import numpy as np

    from deepof_tpu.core.config import (
        DataConfig,
        ExperimentConfig,
        LossConfig,
        MeshConfig,
        OptimConfig,
        TrainConfig,
    )
    from deepof_tpu.data.datasets import SyntheticData
    from deepof_tpu.models.registry import build_model
    from deepof_tpu.parallel.mesh import build_mesh
    from deepof_tpu.train.checkpoint import CheckpointManager
    from deepof_tpu.train.evaluate import postprocess_flow
    from deepof_tpu.train.state import create_train_state, make_optimizer
    from deepof_tpu.train.step import make_eval_fn

    h = w = 64
    cfg = ExperimentConfig(
        name="fit_corr", model=args.model,
        loss=LossConfig(weights=(16, 8, 4, 2, 1, 1)),
        optim=OptimConfig(learning_rate=args.lr),
        data=DataConfig(dataset="synthetic", image_size=(h, w),
                        gt_size=(h, w), batch_size=args.batch),
        mesh=MeshConfig(),
        train=TrainConfig(seed=0, eval_amplifier=2.0, eval_clip=(-300, 250),
                          eval_batch_size=8))
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data, num_train=args.num_train,
                       feature_scale=args.feature_scale,
                       max_shift=args.max_shift, style=args.style,
                       n_blobs=args.blobs)
    model_kw = ({"max_disp": args.max_disp, "corr_stride": args.corr_stride}
                if args.model == "flownet_c" else {})
    model = build_model(args.model, width_mult=args.width_mult, **model_kw)
    tx = make_optimizer(cfg.optim, lambda s: args.lr)
    state = create_train_state(model, jnp.zeros((args.batch, h, w, 6)), tx,
                               seed=0)
    ck = CheckpointManager(args.out + ".ckpt", keep=1, async_save=False)
    st = ck.restore(state)
    if st is None:
        raise SystemExit(f"no checkpoint under {args.out}.ckpt")
    eval_fn = make_eval_fn(model, cfg, ds.mean, mesh=mesh)
    preds, gts = [], []
    for bid in range(2):
        b = ds.sample_val(8, bid)
        out = eval_fn(st.params, b)
        preds.append(postprocess_flow(np.asarray(out["flow"]), cfg,
                                      b["flow"].shape[1:3]))
        gts.append(b["flow"])
    p, g = np.concatenate(preds), np.concatenate(gts)
    pc = p - p.mean(axis=(1, 2), keepdims=True)
    gc = g - g.mean(axis=(1, 2), keepdims=True)
    spat = float((pc * gc).sum()
                 / max(np.sqrt((pc ** 2).sum() * (gc ** 2).sum()), 1e-12))
    pm, gm = p.mean(axis=(1, 2)), g.mean(axis=(1, 2))
    pmc, gmc = pm - pm.mean(0), gm - gm.mean(0)
    mean_corr = float((pmc * gmc).sum()
                      / max(np.sqrt((pmc ** 2).sum() * (gmc ** 2).sum()),
                            1e-12))
    print(json.dumps({
        "step": int(st.step),
        "spatial_pattern_corr": round(spat, 4),
        "per_sample_mean_corr": round(mean_corr, 4),
        "pred_abs_mean": round(float(np.abs(pm).mean()), 4),
        "gt_abs_mean": round(float(np.abs(gm).mean()), 4),
        "pred_std": round(float(p.std()), 4),
    }))


if __name__ == "__main__":
    main()
