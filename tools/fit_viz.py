"""Visual artifacts from a synthetic_fit checkpoint (the reference dumps
flow-color/warp images during eval — `flyingChairsTrain.py:272-291`; this
is the equivalent for the learning-evidence runs).

For N held-out samples, writes side-by-side panels to --out:
source | target | GT flow color | predicted flow color | warped recon.

Run after a fit whose checkpoint survived (budget-exhausted lineages):
    python tools/fit_viz.py --ckpt artifacts/synthetic_fit_cpu_viz.jsonl \
        --out artifacts/viz_r04
(--ckpt takes the fit's --out path; the tool derives <out>.ckpt and reads
the lineage's config fingerprint so the model/data are rebuilt exactly.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepof_tpu.core.hostmesh import force_cpu_devices  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt", required=True,
                    help="the fit's --out jsonl path (ckpt dir is derived)")
    ap.add_argument("--out", required=True)
    ap.add_argument("--samples", type=int, default=4)
    ap.add_argument("--devices", type=int, default=1)
    args = ap.parse_args()

    if args.devices > 0:
        force_cpu_devices(args.devices)
    import cv2
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepof_tpu.core.config import (
        DataConfig, ExperimentConfig, LossConfig, OptimConfig, TrainConfig)
    from deepof_tpu.data.datasets import SyntheticData
    from deepof_tpu.models.registry import build_model
    from deepof_tpu.ops.warp import backward_warp
    from deepof_tpu.parallel.mesh import batch_sharding, build_mesh
    from deepof_tpu.train.checkpoint import CheckpointManager
    from deepof_tpu.train.state import create_train_state, make_optimizer
    from deepof_tpu.train.evaluate import postprocess_flow
    from deepof_tpu.train.step import make_eval_fn
    from deepof_tpu.utils.flowviz import flow_to_color

    ckpt_dir = args.ckpt + ".ckpt"
    if not os.path.isdir(ckpt_dir):
        raise SystemExit(
            f"no checkpoint under {ckpt_dir} (a fit that reached its "
            "target removes its lineage; rerun with a smaller --steps so "
            "the budget-exhausted path keeps one)")
    with open(os.path.join(ckpt_dir, "config_fingerprint.json")) as f:
        fp = json.load(f)

    h = w = 64  # the fit tool's fixed resolution
    cfg = ExperimentConfig(
        name="fit_viz", model=fp.get("model", "flownet_s"),
        width_mult=fp.get("width_mult", 1.0),
        corr_max_disp=fp.get("max_disp", 20),
        corr_stride=fp.get("corr_stride", 2),
        loss=LossConfig(weights=(16, 8, 4, 2, 1, 1)),
        optim=OptimConfig(learning_rate=fp["lr"]),
        data=DataConfig(dataset="synthetic", image_size=(h, w),
                        gt_size=(h, w), batch_size=8),
        train=TrainConfig(seed=0, eval_amplifier=2.0, eval_clip=(-300, 250),
                          eval_batch_size=8, log_dir=args.out),
    )
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data, num_train=fp.get("num_train", 64),
                       feature_scale=fp.get("feature_scale", 8),
                       max_shift=fp.get("max_shift", 4.0),
                       style=fp.get("style", "blobs"),
                       n_blobs=fp.get("blobs", 8))
    # corr knobs only for the corr family (synthetic_fit writes max_disp
    # into every fingerprint, including flownet_s lineages)
    corr_kw = ({"corr_max_disp": cfg.corr_max_disp,
                "corr_stride": cfg.corr_stride}
               if cfg.model == "flownet_c" else {})
    model = build_model(cfg.model, width_mult=cfg.width_mult, **corr_kw)
    tx = make_optimizer(cfg.optim, lambda s: fp["lr"])
    state = create_train_state(model, jnp.zeros((8, h, w, 6)), tx, seed=0)
    state = CheckpointManager(ckpt_dir, async_save=False).restore(state)
    if state is None:
        raise SystemExit(f"no checkpoint under {ckpt_dir}")
    print("restored step", int(state.step))

    eval_fn = make_eval_fn(model, cfg, ds.mean, mesh=mesh)
    b = ds.sample_val(8, 0)
    out = eval_fn(state.params, jax.device_put(b, batch_sharding(mesh)))
    flow_half = np.asarray(out["flow"])  # finest flow x scale, half res
    # the exact eval protocol: amplify -> clip(eval_clip) -> resize to GT
    pred_full = postprocess_flow(flow_half, cfg, (h, w))

    os.makedirs(args.out, exist_ok=True)
    for i in range(min(args.samples, 8)):
        src = np.asarray(b["source"][i])
        tgt = np.asarray(b["target"][i])
        gt = np.asarray(b["flow"][i])
        pred = pred_full[i]
        recon = np.asarray(backward_warp(
            jnp.asarray(tgt)[None], jnp.asarray(pred)[None]))[0]
        # shared normalization so GT and prediction colors are comparable
        rad = max(float(np.hypot(gt[..., 0], gt[..., 1]).max()), 1e-3)
        panel = np.concatenate([
            src, tgt,
            flow_to_color(gt, max_flow=rad),
            flow_to_color(pred, max_flow=rad),
            recon,
        ], axis=1)
        path = os.path.join(args.out, f"val{i}_src-tgt-gtflow-pred-warp.png")
        cv2.imwrite(path, np.clip(panel, 0, 255).astype(np.uint8))
        epe = float(np.hypot(*(pred - gt).transpose(2, 0, 1)).mean())
        print(f"{path}  EPE {epe:.3f}")


if __name__ == "__main__":
    main()
