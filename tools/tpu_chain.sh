#!/bin/sh
# TPU-gated measurement chain. Left running in the background, it waits
# for a live tunnel window (perf_probe's own subprocess-probe wait loop)
# and then spends it in priority order (VERDICT r03 items 1/2/3/4/7):
#   1. perf_probe ALL sections — headline (+ last_good_bench.json for
#      the orchestrator fallback) FIRST, then calib, decomp, warpscan,
#      spc, corr, batch, multiframe, warp
#   2. synthetic_fit on the real chip to < 1 px held-out EPE
#      (dense-canvas config — the sparse default provably stalls in an
#      aperture basin, DESIGN.md)
# Each stage re-execs on failure (a wedge between the subprocess probe
# and main-process init aborts that attempt; only that process is lost).
# All output lands under artifacts/ with timestamps.
cd "$(dirname "$0")/.." || exit 1
PLOG=artifacts/perf_probe_r05.log
FLOG=artifacts/synthetic_fit_tpu_run_r05.log

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

# Single-instance guard: two chains would race the same artifact paths
# (the fit stage rewrites per-rung jsonl + ckpt lineages) and
# double-book the one TPU chip. mkdir is the atomic primitive (the old
# check-then-write pidfile raced two simultaneous starts and a dead
# chain's pidfile could block forever via PID reuse — ADVICE r04); the
# pid inside lets a stale lock from a SIGKILL'd chain be reclaimed, and
# the EXIT trap removes the lock on every normal/signalled exit.
LOCK=artifacts/.tpu_chain.lock
if ! mkdir "$LOCK" 2>/dev/null; then
    holder=$(cat "$LOCK/pid" 2>/dev/null)
    if [ -n "$holder" ] && kill -0 "$holder" 2>/dev/null; then
        echo "$(stamp) another chain (pid $holder) is running; exiting" >> "$PLOG"
        exit 0
    fi
    # stale lock: holder is dead. Reclaim (rmdir+mkdir is not atomic,
    # but both racers got here via a dead holder — worst case one loses
    # the mkdir and exits via the liveness check next line).
    rm -rf "$LOCK"
    if ! mkdir "$LOCK" 2>/dev/null; then
        echo "$(stamp) lost stale-lock race; exiting" >> "$PLOG"
        exit 0
    fi
fi
echo $$ > "$LOCK/pid"
trap 'rm -rf "$LOCK"' EXIT INT TERM

echo "$(stamp) chain start" >> "$PLOG"
i=0
while [ $i -lt 60 ]; do
    i=$((i + 1))
    echo "$(stamp) perf_probe attempt $i" >> "$PLOG"
    timeout 3600 python tools/perf_probe.py --wait-s 600 >> "$PLOG" 2>&1
    rc=$?  # capture IMMEDIATELY: both `if cmd` and $(stamp) clobber $?
    if [ "$rc" -eq 0 ]; then
        echo "$(stamp) perf_probe SUCCESS" >> "$PLOG"
        break
    fi
    echo "$(stamp) perf_probe attempt $i failed (rc=$rc)" >> "$PLOG"
    sleep 120
done

# Fit ladder, reordered by the r04 CPU findings (DESIGN.md): rung 1 is
# the configuration that MEASURABLY learns — FlowNet-C with the task's
# displacement scale matched to the cost volume's bins (max_shift 8 px
# at 64 px = ~1 feature px at the 1/8-res corr grid, stride 1). The
# CPU run crossed half the zero-flow baseline within 500 steps. Later
# rungs document the contrast: FlowNet-S (must discover correlation
# from scratch — the r04 supervised control shows it cannot within any
# in-round budget) with the curriculum and census levers, at full
# width/30k TPU steps where the extra budget might still move it.
FIT_ARGS_COMMON="--devices 0 --steps 30000 --eval-every 250 \
    --lr-decay-every 4000 --batch 16 --blobs 40"
i=0
rung=1
while [ $i -lt 20 ]; do
    i=$((i + 1))
    case $rung in
        1) extra="--model flownet_c --max-disp 3 --corr-stride 1 --max-shift 8"
           tag=corr8 ;;
        2) extra=""; tag=default ;;
        3) extra="--curriculum-steps 8000"; tag=curriculum ;;
        *) extra="--curriculum-steps 8000 --photometric census"
           tag=curr_census ;;
    esac
    echo "$(stamp) synthetic_fit TPU attempt $i rung=$tag" >> "$FLOG"
    # probe first in a throwaway subprocess; the fit itself has no wait loop
    if ! timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(stamp) tunnel down, retry in 300s" >> "$FLOG"
        sleep 300
        continue
    fi
    # Do NOT delete stale per-tag output: synthetic_fit reads it for
    # prior_best bookkeeping and appends on resume, so the jsonl + ckpt
    # lineage must survive across attempts (ADVICE r04 — the old rm -f
    # orphaned the ckpt's history). Staleness is handled below by
    # gating escalation on the FINAL record of the file only.
    timeout 3600 python tools/synthetic_fit.py $FIT_ARGS_COMMON $extra \
        --out "artifacts/synthetic_fit_tpu_$tag.jsonl" >> "$FLOG" 2>&1
    rc=$?  # capture IMMEDIATELY: both `if cmd` and $(stamp) clobber $?
    if [ "$rc" -eq 0 ]; then
        echo "$(stamp) synthetic_fit TPU SUCCESS rung=$tag" >> "$FLOG"
        fit_ok=1
        fit_extra=$extra  # the affine stretch reuses the winning recipe
        break
    fi
    echo "$(stamp) synthetic_fit attempt $i rung=$tag failed (rc=$rc)" >> "$FLOG"
    # A "budget exhausted" outcome means the rung genuinely ran out of
    # steps short of 1 px: escalate. Anything else (tunnel drop mid-run
    # writes an "interrupted" outcome; timeout/wedge writes none): retry
    # the same rung. Only the LAST record counts — an earlier session's
    # exhausted outcome deeper in the lineage must not trigger
    # escalation for an attempt that died mid-run (ADVICE r04).
    if tail -1 "artifacts/synthetic_fit_tpu_$tag.jsonl" 2>/dev/null \
        | grep -q 'budget exhausted' \
        && [ "$rc" -eq 1 ] && [ "$rung" -lt 4 ]; then
        rung=$((rung + 1))
    fi
    sleep 120
done

# Stretch goal once the blobs fit SUCCEEDED (fit_ok set only on rc=0;
# the jsonl alone is no proxy — synthetic_fit writes its meta record
# before training starts): the affine style's spatially varying GT
# field (datasets.py SyntheticData style="affine") — stronger learning
# evidence than a global shift. One attempt per window pass.
if [ "${fit_ok:-0}" -eq 1 ]; then
    echo "$(stamp) affine fit attempt" >> "$FLOG"
    if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        timeout 3600 python tools/synthetic_fit.py $FIT_ARGS_COMMON \
            --style affine $fit_extra \
            --out artifacts/synthetic_fit_tpu_affine.jsonl >> "$FLOG" 2>&1
        rc=$?
        echo "$(stamp) affine fit rc=$rc" >> "$FLOG"
    else
        echo "$(stamp) affine fit skipped: tunnel down" >> "$FLOG"
    fi
fi
echo "$(stamp) chain done" >> "$PLOG"
