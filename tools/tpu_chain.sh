#!/bin/sh
# TPU-gated measurement chain. Left running in the background, it waits
# for a live tunnel window (perf_probe's own subprocess-probe wait loop)
# and then spends it in priority order (VERDICT r03 items 1/2/3/4/7):
#   1. perf_probe ALL sections — headline (+ last_good_bench.json for
#      the orchestrator fallback) FIRST, then calib, decomp, warpscan,
#      spc, corr, batch, multiframe, warp
#   2. synthetic_fit on the real chip to < 1 px held-out EPE
#      (dense-canvas config — the sparse default provably stalls in an
#      aperture basin, DESIGN.md)
# Each stage re-execs on failure (a wedge between the subprocess probe
# and main-process init aborts that attempt; only that process is lost).
# All output lands under artifacts/ with timestamps.
cd "$(dirname "$0")/.." || exit 1
PLOG=artifacts/perf_probe_r04.log
FLOG=artifacts/synthetic_fit_tpu_run_r04.log

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

echo "$(stamp) chain start" >> "$PLOG"
i=0
while [ $i -lt 60 ]; do
    i=$((i + 1))
    echo "$(stamp) perf_probe attempt $i" >> "$PLOG"
    timeout 3600 python tools/perf_probe.py --wait-s 600 >> "$PLOG" 2>&1
    rc=$?  # capture IMMEDIATELY: both `if cmd` and $(stamp) clobber $?
    if [ "$rc" -eq 0 ]; then
        echo "$(stamp) perf_probe SUCCESS" >> "$PLOG"
        break
    fi
    echo "$(stamp) perf_probe attempt $i failed (rc=$rc)" >> "$PLOG"
    sleep 120
done

i=0
while [ $i -lt 20 ]; do
    i=$((i + 1))
    echo "$(stamp) synthetic_fit TPU attempt $i" >> "$FLOG"
    # probe first in a throwaway subprocess; the fit itself has no wait loop
    if ! timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(stamp) tunnel down, retry in 300s" >> "$FLOG"
        sleep 300
        continue
    fi
    # dense canvas + bigger batch: the sparse default provably stalls in
    # an aperture basin at ~3.9 px regardless of steps or LR (12k-step
    # CPU run, artifacts/synthetic_fit_long.jsonl); the 40-blob probe
    # shows the better trajectory (synthetic_fit_dense_probe.jsonl)
    timeout 3600 python tools/synthetic_fit.py --devices 0 \
        --steps 30000 --eval-every 250 --lr-decay-every 4000 \
        --batch 16 --blobs 40 \
        --out artifacts/synthetic_fit_tpu.jsonl >> "$FLOG" 2>&1
    rc=$?  # capture IMMEDIATELY: both `if cmd` and $(stamp) clobber $?
    if [ "$rc" -eq 0 ]; then
        echo "$(stamp) synthetic_fit TPU SUCCESS" >> "$FLOG"
        fit_ok=1
        break
    fi
    echo "$(stamp) synthetic_fit attempt $i failed (rc=$rc)" >> "$FLOG"
    sleep 120
done

# Stretch goal once the blobs fit SUCCEEDED (fit_ok set only on rc=0;
# the jsonl alone is no proxy — synthetic_fit writes its meta record
# before training starts): the affine style's spatially varying GT
# field (datasets.py SyntheticData style="affine") — stronger learning
# evidence than a global shift. One attempt per window pass.
if [ "${fit_ok:-0}" -eq 1 ]; then
    echo "$(stamp) affine fit attempt" >> "$FLOG"
    if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        timeout 3600 python tools/synthetic_fit.py --devices 0 --style affine \
            --steps 30000 --eval-every 250 --lr-decay-every 4000 \
            --batch 16 --blobs 40 \
            --out artifacts/synthetic_fit_tpu_affine.jsonl >> "$FLOG" 2>&1
        rc=$?
        echo "$(stamp) affine fit rc=$rc" >> "$FLOG"
    else
        echo "$(stamp) affine fit skipped: tunnel down" >> "$FLOG"
    fi
fi
echo "$(stamp) chain done" >> "$PLOG"
