#!/bin/sh
# TPU-gated measurement chain. Left running in the background, it waits
# for a live tunnel window (perf_probe's own subprocess-probe wait loop)
# and then spends it in priority order (VERDICT r03 items 1/2/3/4/7):
#   1. perf_probe ALL sections — headline (+ last_good_bench.json for
#      the orchestrator fallback) FIRST, then calib, decomp, warpscan,
#      spc, corr, batch, multiframe, warp
#   2. synthetic_fit on the real chip to < 1 px held-out EPE
#      (dense-canvas config — the sparse default provably stalls in an
#      aperture basin, DESIGN.md)
# Each stage re-execs on failure (a wedge between the subprocess probe
# and main-process init aborts that attempt; only that process is lost).
# All output lands under artifacts/ with timestamps.
cd "$(dirname "$0")/.." || exit 1
PLOG=artifacts/perf_probe_r05.log
FLOG=artifacts/synthetic_fit_tpu_run_r05.log

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

# Single-instance guard: two chains would race the same artifact paths
# (the fit stage rewrites per-rung jsonl + ckpt lineages) and
# double-book the one TPU chip. mkdir is the atomic primitive (the old
# check-then-write pidfile raced two simultaneous starts and a dead
# chain's pidfile could block forever via PID reuse — ADVICE r04); the
# pid inside lets a stale lock from a SIGKILL'd chain be reclaimed, and
# the EXIT trap removes the lock on every normal/signalled exit.
LOCK=artifacts/.tpu_chain.lock
if ! mkdir "$LOCK" 2>/dev/null; then
    holder=$(cat "$LOCK/pid" 2>/dev/null)
    if [ -n "$holder" ] && kill -0 "$holder" 2>/dev/null; then
        echo "$(stamp) another chain (pid $holder) is running; exiting" >> "$PLOG"
        exit 0
    fi
    # stale lock: holder is dead. Reclaim (rmdir+mkdir is not atomic,
    # but both racers got here via a dead holder — worst case one loses
    # the mkdir and exits via the liveness check next line).
    rm -rf "$LOCK"
    if ! mkdir "$LOCK" 2>/dev/null; then
        echo "$(stamp) lost stale-lock race; exiting" >> "$PLOG"
        exit 0
    fi
fi
echo $$ > "$LOCK/pid"
# INT/TERM must EXIT after cleanup — a bare cleanup trap swallows the
# signal and the script keeps running lockless (observed r05: a TERM'd
# chain survived and deleted its successor's lock)
trap 'rm -rf "$LOCK"; trap - EXIT; exit 143' INT TERM
trap 'rm -rf "$LOCK"' EXIT

echo "$(stamp) chain start" >> "$PLOG"
i=0
while [ $i -lt 60 ]; do
    i=$((i + 1))
    echo "$(stamp) perf_probe attempt $i" >> "$PLOG"
    timeout 3600 python tools/perf_probe.py --wait-s 600 >> "$PLOG" 2>&1
    rc=$?  # capture IMMEDIATELY: both `if cmd` and $(stamp) clobber $?
    if [ "$rc" -eq 0 ]; then
        echo "$(stamp) perf_probe SUCCESS" >> "$PLOG"
        break
    fi
    echo "$(stamp) perf_probe attempt $i failed (rc=$rc)" >> "$PLOG"
    sleep 120
done

# Fit ladder, r05 revision. Rung 1 is the configuration that MEASURABLY
# learns — FlowNet-C with the task's displacement scale matched to the
# cost volume's bins (<1 px on CPU in 57 min, r04); on-chip it converts
# VERDICT r04 item 3 in minutes. Rung 2 is the parity-backbone answer
# the CPU could never give (VERDICT r04 item 2): the r05 CPU study
# pinned the S-trunk failure as input-INDEPENDENCE (tools/fit_corr.py:
# corr(pred, gt) ~ 0 after thousands of steps under every loss shaping
# tried — lambda sweep, sub-pixel curriculum, in-basin 2 px shifts),
# and the reference's own recipe for this family is ~600k steps
# (flyingChairsTrain.py LR schedule) — a budget that is ~an hour on
# chip and a multi-WEEK item on this host's CPU. So rung 2 runs
# FlowNet-S half-width at 300k steps with the measured-best task
# (dense multi-octave blobs) and decay schedule; checkpoint+resume
# carries it across window drops. Rungs 3/4 keep the r04 escalation
# levers at the long budget.
i=0
rung=1
while [ $i -lt 20 ]; do
    i=$((i + 1))
    common="--devices 0 --eval-every 250 --batch 16 --blobs 40"
    case $rung in
        1) extra="--steps 30000 --lr-decay-every 4000 \
            --model flownet_c --max-disp 3 --corr-stride 1 --max-shift 8"
           tag=corr8 ;;
        2) extra="--steps 30000 --lr-decay-every 4000 --batch 8 \
            --model inception_v3 --style affine --max-shift 4 \
            --curriculum-start 0.25 --curriculum-steps 3000"
           tag=inc_affine ;;
        3) extra="--steps 300000 --lr-decay-every 40000 \
            --model flownet_s --width-mult 0.5"
           tag=s_long ;;
        *) extra="--steps 300000 --lr-decay-every 40000 \
            --model flownet_s --width-mult 0.5 --curriculum-steps 80000"
           tag=s_long_curr ;;
    esac
    echo "$(stamp) synthetic_fit TPU attempt $i rung=$tag" >> "$FLOG"
    # probe first in a throwaway subprocess; the fit itself has no wait loop
    if ! timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(stamp) tunnel down, retry in 300s" >> "$FLOG"
        sleep 300
        continue
    fi
    # Do NOT delete stale per-tag output: synthetic_fit reads it for
    # prior_best bookkeeping and appends on resume, so the jsonl + ckpt
    # lineage must survive across attempts (ADVICE r04 — the old rm -f
    # orphaned the ckpt's history). Staleness is handled below by
    # gating escalation on the FINAL record of the file only.
    timeout 5400 python tools/synthetic_fit.py $common $extra \
        --out "artifacts/synthetic_fit_tpu_$tag.jsonl" >> "$FLOG" 2>&1
    rc=$?  # capture IMMEDIATELY: both `if cmd` and $(stamp) clobber $?
    if [ "$rc" -eq 0 ]; then
        echo "$(stamp) synthetic_fit TPU SUCCESS rung=$tag" >> "$FLOG"
        if [ "$rung" -lt 3 ]; then
            # rung 1 (<1 px on-chip, corr path) and rung 2 (Inception
            # parity backbone — the recipe PROVEN on CPU at r05:
            # AEE 1.03 in 2.4k steps) each convert in minutes on chip;
            # continue up the ladder to the S-trunk long run after
            echo "$(stamp) rung $rung converted; next rung" >> "$FLOG"
            fit_ok=1
            fit_extra="--model flownet_c --max-disp 3 --corr-stride 1 --max-shift 8"
            rung=$((rung + 1))
            continue
        fi
        echo "$(stamp) parity rung converged rung=$tag" >> "$FLOG"
        fit_ok=1
        break
    fi
    echo "$(stamp) synthetic_fit attempt $i rung=$tag failed (rc=$rc)" >> "$FLOG"
    # A "budget exhausted" outcome means the rung genuinely ran out of
    # steps short of 1 px: escalate. Anything else (tunnel drop mid-run
    # writes an "interrupted" outcome; timeout/wedge writes none): retry
    # the same rung. Only the LAST record counts — an earlier session's
    # exhausted outcome deeper in the lineage must not trigger
    # escalation for an attempt that died mid-run (ADVICE r04).
    if tail -1 "artifacts/synthetic_fit_tpu_$tag.jsonl" 2>/dev/null \
        | grep -q 'budget exhausted' \
        && [ "$rc" -eq 1 ] && [ "$rung" -lt 4 ]; then
        rung=$((rung + 1))
    fi
    sleep 120
done

# Stretch goal once the blobs fit SUCCEEDED (fit_ok set only on rc=0;
# the jsonl alone is no proxy — synthetic_fit writes its meta record
# before training starts): the affine style's spatially varying GT
# field (datasets.py SyntheticData style="affine") — stronger learning
# evidence than a global shift. One attempt per window pass.
if [ "${fit_ok:-0}" -eq 1 ]; then
    echo "$(stamp) affine fit attempt" >> "$FLOG"
    if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        timeout 3600 python tools/synthetic_fit.py $FIT_ARGS_COMMON \
            --style affine $fit_extra \
            --out artifacts/synthetic_fit_tpu_affine.jsonl >> "$FLOG" 2>&1
        rc=$?
        echo "$(stamp) affine fit rc=$rc" >> "$FLOG"
    else
        echo "$(stamp) affine fit skipped: tunnel down" >> "$FLOG"
    fi
fi
echo "$(stamp) chain done" >> "$PLOG"
