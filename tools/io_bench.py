"""Host IO-path benchmark: native C++ batch decode vs cv2 python loop.

Quantifies the input-pipeline claim in DESIGN.md ("per-step host decode
starves the chip") with numbers from THIS host: synthetic FlyingChairs-
shaped PPMs (384x512 -> 320x448) and Sintel-shaped PNGs (436x1024 native)
are generated in /tmp, then both decode paths are timed end-to-end on
identical batches (native includes its thread-pool parallelism — that is
the point: one call decodes the batch off the GIL).

Run: python tools/io_bench.py [--batch 16] [--reps 5]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import cv2  # noqa: E402

from deepof_tpu import native  # noqa: E402
from deepof_tpu.data.datasets import _imread_bgr, _resize  # noqa: E402


def _stage(root: str, kind: str, n: int) -> list[str]:
    rng = np.random.RandomState(0)
    paths = []
    for i in range(n):
        if kind == "chairs_ppm":
            img = rng.randint(0, 255, (384, 512, 3), dtype=np.uint8)
            p = os.path.join(root, f"c{i:03d}.ppm")
            with open(p, "wb") as f:
                f.write(b"P6\n512 384\n255\n")
                f.write(img[..., ::-1].tobytes())
        else:  # sintel_png
            img = rng.randint(0, 255, (436, 1024, 3), dtype=np.uint8)
            p = os.path.join(root, f"s{i:03d}.png")
            cv2.imwrite(p, img)
        paths.append(p)
    return paths


def _time(fn, reps: int) -> float:
    fn()  # warm (page cache, pool spin-up)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()
    if not native.available():
        raise SystemExit("native IO unavailable (no toolchain)")

    with tempfile.TemporaryDirectory() as root:
        for kind, size in [("chairs_ppm", (320, 448)),
                           ("sintel_png", (436, 1024))]:
            paths = _stage(root, kind, args.batch)

            def run_native():
                return native.decode_image_batch(paths, size)

            def run_cv2():
                return np.stack(
                    [_resize(_imread_bgr(p), size) for p in paths]
                ).astype(np.float32)

            tn = _time(run_native, args.reps)
            tp = _time(run_cv2, args.reps)
            # parity guard: same tensors (1 LSB for codec rounding)
            np.testing.assert_allclose(run_native(), run_cv2(), atol=1.0)
            print(f"{kind}: batch={args.batch} native={args.batch / tn:7.1f} "
                  f"img/s  cv2={args.batch / tp:7.1f} img/s  "
                  f"speedup={tp / tn:4.2f}x", flush=True)


if __name__ == "__main__":
    main()
