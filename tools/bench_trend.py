"""Perf-trajectory trend report over every BENCH_r*.json in the repo.

The ROADMAP's never-go-dark rule has every PR since r06 recording the
cpu proxies (data_bench, serve_bench, fleet, precision, stream/warm,
lint wall-time, quality) into one BENCH_r<NN>.json per round — but
reading the trajectory meant opening 13 files by hand. This tool folds
them into ONE report: per-proxy series over rounds, the best-so-far
value per proxy, and a regression flag when the newest round sits more
than ``--tolerance`` below the best — the "did this PR cost us a proxy"
question as one JSON line.

Proxy extraction is a declarative spec table (name, JSON path, higher-
or-lower-is-better); rounds that predate a proxy simply lack points in
its series (r01–r04 used the old bench-orchestrate schema and carry no
extractable proxies — they still count as rounds). All host-noise
caveats from the per-round notes apply: these are CONTENDED-HOST cpu
proxies, so the regression flag is a prompt to read the round's note,
not a verdict by itself.

Run: python tools/bench_trend.py [--dir /root/repo] [--tolerance 0.3]
     [--json-indent 2]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
#: keys every bench_trend report carries (schema smoke test)
REQUIRED_KEYS = (
    "rounds", "latest_round", "files", "series", "best", "latest",
    "regressions", "trend", "tolerance",
)

#: (series name, path through the BENCH json, "higher"|"lower" = better).
#: Series names deliberately avoid the registry-linted counter prefixes:
#: these are report fields, not stats-block keys.
PROXY_SPEC: tuple[tuple[str, tuple[str, ...], str], ...] = (
    ("bench_data_w0_batches_per_s", ("data_bench", "workers0", "value"),
     "higher"),
    ("bench_serve_requests_per_s", ("serve_bench", "value"), "higher"),
    ("bench_serve_speedup_vs_serial", ("serve_bench", "speedup_vs_serial"),
     "higher"),
    ("bench_fleet_requests_per_s", ("serve_bench_fleet", "value"),
     "higher"),
    ("bench_fleet_speedup_vs_single",
     ("serve_bench_fleet", "speedup_vs_single"), "higher"),
    ("bench_precision_int8_requests_per_s",
     ("serve_bench_precision", "tiers", "int8", "requests_per_s"),
     "higher"),
    ("bench_precision_int8_epe_vs_f32",
     ("serve_bench_precision", "tiers", "int8", "epe_vs_f32"), "lower"),
    ("bench_stream_speedup", ("serve_bench_stream", "value"), "higher"),
    ("bench_warm_speedup", ("serve_bench_stream", "warm", "value"),
     "higher"),
    ("bench_warm_epe_vs_cold_px",
     ("serve_bench_stream", "warm", "epe_vs_cold_px"), "lower"),
    # r14 autoscaler ramp (serve_bench --ramp): scaled-burst throughput,
    # how fast capacity arrived after the burst started, and the two
    # hard invariants — sheds once scaled and silent drops — which
    # should pin at/near 0 every round
    ("bench_ramp_requests_per_s", ("serve_bench_ramp", "requests_per_s"),
     "higher"),
    ("bench_ramp_scale_up_latency_s",
     ("serve_bench_ramp", "scale_up_latency_s"), "lower"),
    ("bench_ramp_sheds_after_scale",
     ("serve_bench_ramp", "sheds_after_scale"), "lower"),
    ("bench_ramp_drops", ("serve_bench_ramp", "drops"), "lower"),
    # r16 zero-cold-start serving: how long a scaled-up replica takes
    # to serve (spawn -> first admitted request), the sheds the
    # predictive load-slope signal pre-empted vs reactive-only on the
    # same ramped drive, and the artifact plane's two cold-start
    # figures — end-to-end warm wall (bounded by the trace/lower floor
    # on a cpu host) and the isolated compile-vs-fetch acquisition step
    ("bench_ramp_scale_up_first_response_ms",
     ("serve_bench_ramp", "scale_up_to_first_response_ms"), "lower"),
    ("bench_ramp_predictive_shed_delta",
     ("serve_bench_ramp", "predictive_shed_delta"), "higher"),
    ("bench_artifact_cold_start_speedup",
     ("serve_bench_artifact", "cold_start_speedup"), "higher"),
    ("bench_artifact_acquire_speedup",
     ("serve_bench_artifact", "acquire_speedup"), "higher"),
    # r17 trace-free replica boot: the index leg's wall (fetch +
    # deserialize only — zero trace/lower), the r16 fingerprint boot
    # kept for continuity, and what moving integrity off the boot path
    # bought (fingerprint wall / index wall)
    ("bench_artifact_index_wall_s",
     ("serve_bench_artifact", "warm_wall_index_s"), "lower"),
    ("bench_artifact_fingerprint_boot_speedup",
     ("serve_bench_artifact", "fingerprint_boot_speedup"), "higher"),
    ("bench_artifact_index_vs_fingerprint_speedup",
     ("serve_bench_artifact", "index_vs_artifact_speedup"), "higher"),
    # r15 executable ledger (obs/ledger.py + serve_bench
    # --ledger-overhead): hot-path cost of ledgering (bounded <= 2%),
    # total lattice compile seconds, and the measured-vs-nominal-
    # roofline MFU of the bench engine's serve executable — the compile/
    # perf provenance trajectory, per round
    ("bench_ledger_overhead_pct", ("ledger", "p99_overhead_pct"),
     "lower"),  # noise-centered: flagged via ABS_BOUNDS, not vs best
    ("bench_ledger_compile_s", ("ledger", "compile_s_total"), "lower"),
    ("bench_ledger_mfu", ("ledger", "mfu_nominal"), "higher"),
    # r18 incident plane (obs/incident.py + serve_bench --incidents):
    # the flight recorder's hot-path p99 cost with an idle recorder
    # (bounded <= 1%), and the round's committed-bundle count on the
    # healthy bench workload — should pin at 0 every round (a nonzero
    # count means a bench run tripped an anomaly trigger)
    ("bench_incident_overhead_pct", ("incidents", "p99_overhead_pct"),
     "lower"),  # noise-centered: flagged via ABS_BOUNDS, not vs best
    ("bench_incident_captured", ("incidents", "captured"), "lower"),
    ("bench_lint_wall_s", ("lint", "value"), "lower"),
    ("bench_elastic_recovery_s",
     ("elastic_drill", "host_loss", "recovery_wall_s"), "lower"),
    ("bench_quality_scorer_overhead_pct",
     ("serve_bench_quality", "scorer_overhead_pct"), "lower"),
    ("bench_quality_p99_overhead_pct",
     ("serve_bench_quality", "p99_overhead_pct"), "lower"),
    ("bench_quality_photo_f32", ("serve_bench_quality", "tiers", "f32",
                                 "photo"), "lower"),
    # r19 brownout plane (serve/degrade.py + serve_bench --brownout):
    # the overload A/B's protection invariant — default-priority sheds
    # on the controller-ON leg must pin at 0 (any nonzero flags against
    # a best of 0 immediately) — and the headline absorbed-shed delta
    # (OFF-leg default sheds minus ON-leg, the sheds the brownout plane
    # redirected onto low-priority work; load-shape dependent, so the
    # wide relative tolerance applies, like predictive_shed_delta)
    ("bench_brownout_default_sheds_on",
     ("serve_bench_brownout", "default_sheds_on"), "lower"),
    ("bench_brownout_shed_delta",
     ("serve_bench_brownout", "default_shed_delta"), "higher"),
)

#: noise-centered signed proxies: the overhead percentages hover around
#: zero and go NEGATIVE on a contended host (the r14/r15 BENCH notes),
#: so "worse than best-so-far by a fraction" is meaningless — a best of
#: -0.5% would flag a later +0.6% that sits well inside the acceptance
#: bound. These series regress ONLY when the newest value exceeds the
#: ABSOLUTE bound their ISSUE acceptance set; None = no ISSUE set an
#: absolute acceptance for this series (recorded, never auto-flagged).
ABS_BOUNDS: dict[str, float | None] = {
    "bench_ledger_overhead_pct": 2.0,       # ISSUE 15: <= 2% of p99
    "bench_incident_overhead_pct": 1.0,     # ISSUE 18: <= 1% of p99
    "bench_quality_p99_overhead_pct": 5.0,  # ISSUE 13: p99 < 5% at 0.1
    # rps-based companion figure; ISSUE 13's 5% acceptance bounds the
    # P99 overhead, not this one
    "bench_quality_scorer_overhead_pct": None,
}

#: compile-seconds series are cache-BIMODAL: a round whose persistent
#: compile cache is warm records ~0.05 s per executable, a cold round
#: seconds-to-minutes — both healthy, so relative-to-best would flag
#: every cold round as a phantom blowup against a cache-hit best. They
#: flag with obs/ledger.py's own compile-blowup rule applied against
#: the WORST prior round: latest > max(floor, prior_max * factor)
#: (see _beyond for why best-so-far collapses the bound to the floor).
COMPILE_FLOOR_S = 1.0
COMPILE_FACTOR = 2.0


def _is_compile_series(name: str) -> bool:
    return (name == "bench_ledger_compile_s"
            or name.startswith("ledger_compile_s:"))


def _is_mfu_series(name: str) -> bool:
    """Measured-MFU series are roofline_s / measured dispatch WALL, so
    they scale inversely with host contention (the BENCH notes record
    ~2x round-to-round host swings) — by the ledger's own rationale
    ("wall time is host noise") they are recorded and sloped but never
    auto-flagged."""
    return (name == "bench_ledger_mfu"
            or name.startswith("ledger_mfu_nominal:"))

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _lookup(d, path: tuple[str, ...]):
    for key in path:
        if not isinstance(d, dict) or key not in d:
            return None
        d = d[key]
    return d if isinstance(d, (int, float)) and not isinstance(d, bool) \
        else None


def bench_trend(bench_dir: str, tolerance: float = 0.3) -> dict:
    """The trend report (see module docstring). tolerance: relative
    slack before the latest point of a series flags as a regression
    against its best-so-far (0.3 = flag when >30% worse — wide on
    purpose: these proxies run on contended hosts)."""
    # filter by the round regex, not just the glob: a stray
    # BENCH_rerun.json / BENCH_r13-old.json in the repo root is skipped,
    # not a crash in the sort key
    files = sorted((p for p in glob.glob(os.path.join(bench_dir,
                                                      "BENCH_r*.json"))
                    if _ROUND_RE.search(p)),
                   key=lambda p: int(_ROUND_RE.search(p).group(1)))
    rounds: list[int] = []
    series: dict[str, list[dict]] = {name: [] for name, _, _ in PROXY_SPEC}
    for path in files:
        m = _ROUND_RE.search(path)
        rnd = int(m.group(1))
        rounds.append(rnd)
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue  # a torn/absent round stays a round, with no points
        for name, spec, _ in PROXY_SPEC:
            value = _lookup(data, spec)
            if value is not None:
                series[name].append({"round": rnd, "value": value})
        # per-executable ledger series (dynamic names: the BENCH ledger
        # block's "executables" map carries compile seconds and MFU per
        # lattice entry — a single executable's compile-time trajectory
        # is visible without opening the rounds by hand). Sense: compile
        # seconds lower-is-better, MFU higher.
        execs = (data.get("ledger") or {}).get("executables")
        if isinstance(execs, dict):
            for ename, entry in sorted(execs.items()):
                if not isinstance(entry, dict):
                    continue
                for field, sense in (("compile_s", "lower"),
                                     ("mfu_nominal", "higher")):
                    v = entry.get(field)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        key = f"ledger_{field}:{ename}"
                        series.setdefault(key, []).append(
                            {"round": rnd, "value": v,
                             "sense": sense})

    best: dict[str, dict] = {}
    latest: dict[str, dict] = {}
    regressions: dict[str, dict] = {}
    trend: dict[str, dict] = {}
    senses = {name: sense for name, _, sense in PROXY_SPEC}
    for name, pts in series.items():
        if not pts:
            continue
        # static proxies carry their sense in PROXY_SPEC; dynamic
        # per-executable ledger series carry it per point
        sense = senses.get(name) or pts[-1].get("sense", "lower")
        pick = max if sense == "higher" else min
        b = pick(pts, key=lambda p: p["value"])
        last = pts[-1]
        best[name] = {"round": b["round"], "value": b["value"],
                      "sense": sense}
        latest[name] = {"round": last["round"], "value": last["value"]}
        t = _series_trend(name, pts, sense, tolerance)
        if t is not None:
            trend[name] = t
        flagged, detail = _beyond(name, pts, sense, tolerance)
        if flagged:
            regressions[name] = {
                "best_round": b["round"], "best": b["value"],
                "latest_round": last["round"], "latest": last["value"],
                **detail,
            }
    return {
        "rounds": rounds,
        "latest_round": rounds[-1] if rounds else None,
        "files": [os.path.basename(p) for p in files],
        "series": {k: v for k, v in series.items() if v},
        "best": best,
        "latest": latest,
        "regressions": regressions,
        "trend": trend,
        "tolerance": float(tolerance),
    }


def _beyond(name: str, pts: list[dict], sense: str,
            tolerance: float) -> tuple[bool, dict]:
    """The ONE regression rule, shared by bench_trend()'s regressions
    map and _series_trend()'s `regressing` flag so the two can never
    disagree about the same series. Four branches:

      ABS_BOUNDS series   noise-centered signed overheads — flag only
                          past the absolute acceptance bound (never,
                          when the bound is None)
      MFU series          wall-derived host noise — never auto-flag
      compile series      cache-bimodal — the ledger's own blowup rule,
                          but against the WORST prior round, not the
                          best (best is a cache-hit round, whose 2x
                          bound would collapse to the 1 s floor and
                          phantom-flag every healthy >1 s cold compile;
                          a genuine blowup is slower than any compile
                          this series has ever recorded, by the factor
                          and above the floor)
      everything else     relative to best-so-far with `tolerance`

    Returns (flagged, detail) — detail carries the branch's bound
    fields for the regressions entry."""
    pick = max if sense == "higher" else min
    bv = float(pick(p["value"] for p in pts))
    lv = float(pts[-1]["value"])
    if name in ABS_BOUNDS:
        bound = ABS_BOUNDS[name]
        if bound is not None and lv > bound:
            return True, {"abs_bound": bound}
        return False, {}
    if _is_mfu_series(name):
        return False, {}
    if _is_compile_series(name):
        prior = [float(p["value"]) for p in pts[:-1]]
        ref = max(prior) if prior else lv
        if lv > max(COMPILE_FLOOR_S, ref * COMPILE_FACTOR):
            return True, {"compile_floor_s": COMPILE_FLOOR_S,
                          "compile_factor": COMPILE_FACTOR,
                          "prior_max": ref}
        return False, {}
    if bv == 0:
        return False, {}
    worse = ((bv - lv) / abs(bv) if sense == "higher"
             else (lv - bv) / abs(bv))
    if worse > float(tolerance):
        return True, {"worse_frac": round(worse, 4)}
    return False, {}


def _series_trend(name: str, pts: list[dict], sense: str,
                  tolerance: float, window: int = 8) -> dict | None:
    """Per-series slope + sustained-regression flag, the analyze.py
    eval_trend shape ported to bench rounds: the least-squares slope of
    value vs round over the newest `window` points, and `regressing` =
    the slope moves the WRONG way for the series' sense AND the newest
    point is beyond the series' regression rule (`_beyond` — the same
    classifier the regressions map uses) — one noisy round never flags,
    a sustained slide does. None below 3 points (a slope over 2 rounds
    is just their difference)."""
    recent = pts[-max(int(window), 3):]
    if len(recent) < 3:
        return None
    xs = [float(p["round"]) for p in recent]
    ys = [float(p["value"]) for p in recent]
    n = len(xs)
    mx, my = sum(xs) / n, sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    if denom <= 0:
        return None
    slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
    adverse = slope < 0 if sense == "higher" else slope > 0
    beyond, _ = _beyond(name, pts, sense, tolerance)
    return {
        "window": n,
        "slope_per_round": round(slope, 6),
        "regressing": bool(adverse and beyond),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_trend")
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the BENCH_r*.json files (default: repo "
             "root)")
    ap.add_argument("--tolerance", type=float, default=0.3,
                    help="relative slack vs best-so-far before the "
                         "latest round flags as a regression (default "
                         "0.3 — wide: contended-host proxies)")
    ap.add_argument("--json-indent", type=int, default=None)
    args = ap.parse_args(argv)
    report = bench_trend(args.dir, tolerance=args.tolerance)
    print(json.dumps(report, indent=args.json_indent))
    # regressions are a prompt to read the round note, not a failure:
    # rc stays 0 so CI trend collection never blocks on host noise
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
