"""Perf-trajectory trend report over every BENCH_r*.json in the repo.

The ROADMAP's never-go-dark rule has every PR since r06 recording the
cpu proxies (data_bench, serve_bench, fleet, precision, stream/warm,
lint wall-time, quality) into one BENCH_r<NN>.json per round — but
reading the trajectory meant opening 13 files by hand. This tool folds
them into ONE report: per-proxy series over rounds, the best-so-far
value per proxy, and a regression flag when the newest round sits more
than ``--tolerance`` below the best — the "did this PR cost us a proxy"
question as one JSON line.

Proxy extraction is a declarative spec table (name, JSON path, higher-
or-lower-is-better); rounds that predate a proxy simply lack points in
its series (r01–r04 used the old bench-orchestrate schema and carry no
extractable proxies — they still count as rounds). All host-noise
caveats from the per-round notes apply: these are CONTENDED-HOST cpu
proxies, so the regression flag is a prompt to read the round's note,
not a verdict by itself.

Run: python tools/bench_trend.py [--dir /root/repo] [--tolerance 0.3]
     [--json-indent 2]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
#: keys every bench_trend report carries (schema smoke test)
REQUIRED_KEYS = (
    "rounds", "latest_round", "files", "series", "best", "latest",
    "regressions", "tolerance",
)

#: (series name, path through the BENCH json, "higher"|"lower" = better).
#: Series names deliberately avoid the registry-linted counter prefixes:
#: these are report fields, not stats-block keys.
PROXY_SPEC: tuple[tuple[str, tuple[str, ...], str], ...] = (
    ("bench_data_w0_batches_per_s", ("data_bench", "workers0", "value"),
     "higher"),
    ("bench_serve_requests_per_s", ("serve_bench", "value"), "higher"),
    ("bench_serve_speedup_vs_serial", ("serve_bench", "speedup_vs_serial"),
     "higher"),
    ("bench_fleet_requests_per_s", ("serve_bench_fleet", "value"),
     "higher"),
    ("bench_fleet_speedup_vs_single",
     ("serve_bench_fleet", "speedup_vs_single"), "higher"),
    ("bench_precision_int8_requests_per_s",
     ("serve_bench_precision", "tiers", "int8", "requests_per_s"),
     "higher"),
    ("bench_precision_int8_epe_vs_f32",
     ("serve_bench_precision", "tiers", "int8", "epe_vs_f32"), "lower"),
    ("bench_stream_speedup", ("serve_bench_stream", "value"), "higher"),
    ("bench_warm_speedup", ("serve_bench_stream", "warm", "value"),
     "higher"),
    ("bench_warm_epe_vs_cold_px",
     ("serve_bench_stream", "warm", "epe_vs_cold_px"), "lower"),
    # r14 autoscaler ramp (serve_bench --ramp): scaled-burst throughput,
    # how fast capacity arrived after the burst started, and the two
    # hard invariants — sheds once scaled and silent drops — which
    # should pin at/near 0 every round
    ("bench_ramp_requests_per_s", ("serve_bench_ramp", "requests_per_s"),
     "higher"),
    ("bench_ramp_scale_up_latency_s",
     ("serve_bench_ramp", "scale_up_latency_s"), "lower"),
    ("bench_ramp_sheds_after_scale",
     ("serve_bench_ramp", "sheds_after_scale"), "lower"),
    ("bench_ramp_drops", ("serve_bench_ramp", "drops"), "lower"),
    ("bench_lint_wall_s", ("lint", "value"), "lower"),
    ("bench_elastic_recovery_s",
     ("elastic_drill", "host_loss", "recovery_wall_s"), "lower"),
    ("bench_quality_scorer_overhead_pct",
     ("serve_bench_quality", "scorer_overhead_pct"), "lower"),
    ("bench_quality_photo_f32", ("serve_bench_quality", "tiers", "f32",
                                 "photo"), "lower"),
)

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _lookup(d, path: tuple[str, ...]):
    for key in path:
        if not isinstance(d, dict) or key not in d:
            return None
        d = d[key]
    return d if isinstance(d, (int, float)) and not isinstance(d, bool) \
        else None


def bench_trend(bench_dir: str, tolerance: float = 0.3) -> dict:
    """The trend report (see module docstring). tolerance: relative
    slack before the latest point of a series flags as a regression
    against its best-so-far (0.3 = flag when >30% worse — wide on
    purpose: these proxies run on contended hosts)."""
    # filter by the round regex, not just the glob: a stray
    # BENCH_rerun.json / BENCH_r13-old.json in the repo root is skipped,
    # not a crash in the sort key
    files = sorted((p for p in glob.glob(os.path.join(bench_dir,
                                                      "BENCH_r*.json"))
                    if _ROUND_RE.search(p)),
                   key=lambda p: int(_ROUND_RE.search(p).group(1)))
    rounds: list[int] = []
    series: dict[str, list[dict]] = {name: [] for name, _, _ in PROXY_SPEC}
    for path in files:
        m = _ROUND_RE.search(path)
        rnd = int(m.group(1))
        rounds.append(rnd)
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue  # a torn/absent round stays a round, with no points
        for name, spec, _ in PROXY_SPEC:
            value = _lookup(data, spec)
            if value is not None:
                series[name].append({"round": rnd, "value": value})

    best: dict[str, dict] = {}
    latest: dict[str, dict] = {}
    regressions: dict[str, dict] = {}
    for name, _, sense in PROXY_SPEC:
        pts = series[name]
        if not pts:
            continue
        pick = max if sense == "higher" else min
        b = pick(pts, key=lambda p: p["value"])
        last = pts[-1]
        best[name] = {"round": b["round"], "value": b["value"],
                      "sense": sense}
        latest[name] = {"round": last["round"], "value": last["value"]}
        bv, lv = float(b["value"]), float(last["value"])
        if bv == 0:
            continue
        worse = ((bv - lv) / abs(bv) if sense == "higher"
                 else (lv - bv) / abs(bv))
        if worse > float(tolerance):
            regressions[name] = {
                "best_round": b["round"], "best": b["value"],
                "latest_round": last["round"], "latest": last["value"],
                "worse_frac": round(worse, 4),
            }
    return {
        "rounds": rounds,
        "latest_round": rounds[-1] if rounds else None,
        "files": [os.path.basename(p) for p in files],
        "series": {k: v for k, v in series.items() if v},
        "best": best,
        "latest": latest,
        "regressions": regressions,
        "tolerance": float(tolerance),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_trend")
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the BENCH_r*.json files (default: repo "
             "root)")
    ap.add_argument("--tolerance", type=float, default=0.3,
                    help="relative slack vs best-so-far before the "
                         "latest round flags as a regression (default "
                         "0.3 — wide: contended-host proxies)")
    ap.add_argument("--json-indent", type=int, default=None)
    args = ap.parse_args(argv)
    report = bench_trend(args.dir, tolerance=args.tolerance)
    print(json.dumps(report, indent=args.json_indent))
    # regressions are a prompt to read the round note, not a failure:
    # rc stays 0 so CI trend collection never blocks on host noise
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
