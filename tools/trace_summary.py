#!/usr/bin/env python
"""Top-N longest spans (and per-name aggregates) from a trace.json —
and, with --merge, the whole fleet's timeline from a run directory.

Companion to the obs/trace.py tracer: when there is no Perfetto at hand
(headless host, mid-run triage over ssh), this prints the spans that
dominated the timeline straight from the Chrome trace-event file.

    python tools/trace_summary.py /tmp/run/trace.json --top 15
    python tools/trace_summary.py trace.json --name dispatch

--merge drives obs/aggregate.py headlessly over a multi-process run
dir (fleet replicas / elastic hosts): writes <run>/trace_merged.json
(Perfetto-loadable, per-process tracks + request-id flow arrows) and
prints per-process span aggregates plus the slowest request journeys —
merged traces are inspectable with no viewer at all.

    python tools/trace_summary.py --merge /tmp/fleet_run

Stdlib-only (like the tracer itself): usable next to a live trainer
without initializing any backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_events(path: str) -> tuple[list[dict], dict[int, str]]:
    """(complete 'X' span events, tid -> thread name)."""
    with open(path) as f:
        payload = json.load(f)
    events = payload.get("traceEvents", payload if isinstance(payload, list)
                         else [])
    threads = {e.get("tid"): e.get("args", {}).get("name", "?")
               for e in events
               if e.get("ph") == "M" and e.get("name") == "thread_name"}
    spans = [e for e in events
             if e.get("ph") == "X" and isinstance(e.get("dur"), (int, float))]
    return spans, threads


def summarize(spans: list[dict], threads: dict[int, str], top: int,
              name: str | None = None) -> str:
    if name:
        spans = [s for s in spans if s.get("name") == name]
    lines = []
    by_name: dict[str, list[float]] = defaultdict(list)
    for s in spans:
        by_name[s.get("name", "?")].append(float(s["dur"]))

    lines.append(f"{len(spans)} spans, {len(by_name)} names, "
                 f"{len(threads)} named threads")
    lines.append("")
    lines.append(f"{'name':<16} {'count':>6} {'total_ms':>10} "
                 f"{'mean_ms':>9} {'max_ms':>9}")
    for nm, durs in sorted(by_name.items(), key=lambda kv: -sum(kv[1])):
        lines.append(f"{nm:<16} {len(durs):>6} {sum(durs) / 1e3:>10.1f} "
                     f"{sum(durs) / len(durs) / 1e3:>9.2f} "
                     f"{max(durs) / 1e3:>9.2f}")
    lines.append("")
    lines.append(f"top {top} longest spans:")
    lines.append(f"{'dur_ms':>9} {'ts_ms':>10} {'thread':<18} "
                 f"{'name':<16} args")
    for s in sorted(spans, key=lambda s: -float(s["dur"]))[:top]:
        thread = threads.get(s.get("tid"), str(s.get("tid")))
        args = s.get("args") or {}
        lines.append(f"{float(s['dur']) / 1e3:>9.2f} "
                     f"{float(s.get('ts', 0)) / 1e3:>10.1f} "
                     f"{thread:<18} {s.get('name', '?'):<16} "
                     f"{json.dumps(args) if args else ''}")
    return "\n".join(lines)


def merge_report(run_dir: str, top: int) -> tuple[str, int]:
    """(report text, exit code) for --merge: aggregate the run dir's
    per-process artifacts into one trace and summarize it headlessly."""
    # imported lazily: plain single-trace mode stays stdlib-only-at-work
    from deepof_tpu.obs import aggregate

    try:
        summary = aggregate.aggregate_run(run_dir)
    except FileNotFoundError as e:
        return str(e), 1
    lines = [
        f"merged {len(summary['processes'])} process(es) -> "
        f"{summary['path']}",
        f"{summary['spans']} spans, {summary['flows']} flow events, "
        f"{summary['request_ids']} request id(s), "
        f"{summary['requests_correlated']} correlated across processes",
        "",
        f"{'process':<28} {'spans':>6} {'markers':>8}",
    ]
    for p in summary["processes"]:
        name = p["name"] + (f" [{p['rel']}]" if p["rel"] else "")
        lines.append(f"{name:<28} {p['spans']:>6} {p['markers']:>8}")

    table = aggregate.per_process_table(summary["path"])
    for proc in sorted(table):
        lines.append("")
        lines.append(f"-- {proc}")
        lines.append(f"{'name':<20} {'count':>6} {'total_ms':>10} "
                     f"{'max_ms':>9}")
        rows = sorted(table[proc].items(),
                      key=lambda kv: -kv[1]["total_ms"])
        for name, row in rows:
            lines.append(f"{name:<20} {row['count']:>6} "
                         f"{row['total_ms']:>10.1f} {row['max_ms']:>9.2f}")

    requests = aggregate.per_request_table(summary["path"], limit=top)
    if requests:
        lines.append("")
        lines.append(f"slowest {len(requests)} request journey(s):")
        for r in requests:
            hops = " -> ".join(f"{s['process']}:{s['name']}"
                               f"({s['dur_ms']:.2f}ms)"
                               for s in r["spans"])
            lines.append(f"  {r['request_id']} "
                         f"[{r['processes']} process(es), "
                         f"{r['total_ms']:.2f}ms] {hops}")
    return "\n".join(lines), 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="print top-N longest spans from a Chrome trace-event "
                    "trace.json (obs/trace.py output), or --merge a "
                    "multi-process run dir into one fleet trace")
    p.add_argument("path", nargs="?", default=None,
                   help="trace.json written by the span tracer")
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--name", default=None,
                   help="restrict the top-N listing to one span name")
    p.add_argument("--merge", default=None, metavar="RUN_DIR",
                   help="aggregate every per-process trace/heartbeat/"
                        "metrics under a run dir into "
                        "<run_dir>/trace_merged.json and print "
                        "per-process + per-request-id aggregates")
    args = p.parse_args(argv)
    if args.merge is not None:
        report, rc = merge_report(args.merge, args.top)
        print(report, file=sys.stderr if rc else sys.stdout)
        return rc
    if args.path is None:
        p.error("need a trace.json path (or --merge RUN_DIR)")
    try:
        spans, threads = load_events(args.path)
    except (OSError, ValueError) as e:
        print(f"cannot read {args.path}: {e}", file=sys.stderr)
        return 1
    if not spans:
        print("no complete ('X') span events in this trace")
        return 0
    print(summarize(spans, threads, args.top, args.name))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
