#!/usr/bin/env python
"""Top-N longest spans (and per-name aggregates) from a trace.json.

Companion to the obs/trace.py tracer: when there is no Perfetto at hand
(headless host, mid-run triage over ssh), this prints the spans that
dominated the timeline straight from the Chrome trace-event file.

    python tools/trace_summary.py /tmp/run/trace.json --top 15
    python tools/trace_summary.py trace.json --name dispatch

Stdlib-only (like the tracer itself): usable next to a live trainer
without initializing any backend.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict


def load_events(path: str) -> tuple[list[dict], dict[int, str]]:
    """(complete 'X' span events, tid -> thread name)."""
    with open(path) as f:
        payload = json.load(f)
    events = payload.get("traceEvents", payload if isinstance(payload, list)
                         else [])
    threads = {e.get("tid"): e.get("args", {}).get("name", "?")
               for e in events
               if e.get("ph") == "M" and e.get("name") == "thread_name"}
    spans = [e for e in events
             if e.get("ph") == "X" and isinstance(e.get("dur"), (int, float))]
    return spans, threads


def summarize(spans: list[dict], threads: dict[int, str], top: int,
              name: str | None = None) -> str:
    if name:
        spans = [s for s in spans if s.get("name") == name]
    lines = []
    by_name: dict[str, list[float]] = defaultdict(list)
    for s in spans:
        by_name[s.get("name", "?")].append(float(s["dur"]))

    lines.append(f"{len(spans)} spans, {len(by_name)} names, "
                 f"{len(threads)} named threads")
    lines.append("")
    lines.append(f"{'name':<16} {'count':>6} {'total_ms':>10} "
                 f"{'mean_ms':>9} {'max_ms':>9}")
    for nm, durs in sorted(by_name.items(), key=lambda kv: -sum(kv[1])):
        lines.append(f"{nm:<16} {len(durs):>6} {sum(durs) / 1e3:>10.1f} "
                     f"{sum(durs) / len(durs) / 1e3:>9.2f} "
                     f"{max(durs) / 1e3:>9.2f}")
    lines.append("")
    lines.append(f"top {top} longest spans:")
    lines.append(f"{'dur_ms':>9} {'ts_ms':>10} {'thread':<18} "
                 f"{'name':<16} args")
    for s in sorted(spans, key=lambda s: -float(s["dur"]))[:top]:
        thread = threads.get(s.get("tid"), str(s.get("tid")))
        args = s.get("args") or {}
        lines.append(f"{float(s['dur']) / 1e3:>9.2f} "
                     f"{float(s.get('ts', 0)) / 1e3:>10.1f} "
                     f"{thread:<18} {s.get('name', '?'):<16} "
                     f"{json.dumps(args) if args else ''}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="print top-N longest spans from a Chrome trace-event "
                    "trace.json (obs/trace.py output)")
    p.add_argument("path", help="trace.json written by the span tracer")
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--name", default=None,
                   help="restrict the top-N listing to one span name")
    args = p.parse_args(argv)
    try:
        spans, threads = load_events(args.path)
    except (OSError, ValueError) as e:
        print(f"cannot read {args.path}: {e}", file=sys.stderr)
        return 1
    if not spans:
        print("no complete ('X') span events in this trace")
        return 0
    print(summarize(spans, threads, args.top, args.name))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
