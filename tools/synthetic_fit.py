"""Learning-evidence artifact: drive synthetic val EPE below 1 px.

Real FlyingChairs/Sintel data cannot be staged in this zero-egress
container (DESIGN.md "Learning evidence"), so the quality proxy is the
procedural dataset with exact ground truth (`data/datasets.py
SyntheticData`): uniform-shift pairs, where the unsupervised objective's
minimizer IS the true flow. The tool trains a flow model (--model:
flownet_s, or flownet_c whose correlation cost volume makes matching
learnable within small step budgets — DESIGN.md r04) with the DEFAULT
FlyingChairs loss configuration (Charbonnier, canonical smoothness,
lambda=1, weights 16/8/4/2/1/1; escalation levers opt-in) and the
FlyingChairs eval protocol (pr1 x 2, resize to GT resolution, AEE vs
exact GT), recording EPE-vs-steps to the --out jsonl until EPE < 1 px.
Checkpointed + auto-resuming; config-fingerprinted per lineage.

Run: python tools/synthetic_fit.py [--steps N] [--out PATH]
(CPU: defaults to a 1-device mesh — this container has a single core, so
an 8-device virtual mesh would only thrash it; pass --devices 8 to run
the sharded path.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepof_tpu.core.hostmesh import force_cpu_devices  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4000)
    ap.add_argument("--eval-every", type=int, default=50)
    ap.add_argument("--devices", type=int, default=1,
                    help="virtual CPU devices; 0 = do NOT force CPU, use "
                         "the default backend (the real chip) — minutes "
                         "instead of days for the <1px run")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--lr-decay-every", type=int, default=1500,
                    help="halve lr every N steps (0 = constant)")
    ap.add_argument("--feature-scale", type=int, default=16)
    ap.add_argument("--max-shift", type=float, default=4.0)
    ap.add_argument("--style", default="blobs",
                    choices=("noise", "blobs", "affine"))
    ap.add_argument("--blobs", type=int, default=8,
                    help="blob count for the blobs/affine canvases; denser "
                         "= photometric signal on more pixels (the sparse "
                         "default leaves most pixels aperture-ambiguous)")
    ap.add_argument("--target-epe", type=float, default=1.0)
    ap.add_argument("--fresh", action="store_true",
                    help="ignore and remove any existing checkpoint for "
                         "this --out instead of auto-resuming")
    ap.add_argument("--width-mult", type=float, default=1.0,
                    help="flownet_s thin-variant channel multiplier; the "
                         "CPU hedge runs 0.25 (~16x cheaper steps), the "
                         "TPU rungs keep the full reference widths")
    ap.add_argument("--model", default="flownet_s",
                    choices=("flownet_s", "flownet_c", "inception_v3",
                             "vgg16"),
                    help="flownet_c's explicit correlation cost volume "
                         "builds matching into the architecture — the r04 "
                         "supervised control showed FlowNet-S must DISCOVER "
                         "correlation from scratch (the original needed "
                         "~1M iterations), far beyond any in-round step "
                         "budget, regardless of loss recipe (DESIGN.md). "
                         "The parity backbones (flownet_s, and the "
                         "reference's actual training model inception_v3, "
                         "`flyingChairsTrain.py:103`) learn in-budget only "
                         "in the small-displacement regime (--max-shift "
                         "<= ~2: photometric refinement inside the fine "
                         "levels' basin, no correspondence discovery "
                         "needed — the regime of the reference's UCF-101 "
                         "video task). inception_v3/vgg16 ignore "
                         "--width-mult (reference widths only).")
    ap.add_argument("--max-disp", type=int, default=4,
                    help="flownet_c correlation search radius in feature "
                         "pixels x stride. The class default (20, sized "
                         "for 320x448) would build 441 displacement maps "
                         "on this tool's 8x8 conv3 grid with most offsets "
                         "pure padding; 4 -> 25 maps covering +-32 image "
                         "px, ample for --max-shift 4.")
    ap.add_argument("--corr-stride", type=int, default=2,
                    help="flownet_c correlation displacement stride in "
                         "feature pixels; 1 gives the finest displacement "
                         "bins (8 image px at the 1/8-res conv3 grid) — "
                         "required for the cost volume to resolve shifts "
                         "of ~1 feature pixel")
    ap.add_argument("--num-train", type=int, default=8192,
                    help="unique procedural training samples. The dataset "
                         "class default (64, sized for tests) lets the "
                         "model MEMORIZE per-canvas flow constants instead "
                         "of learning matching — train loss descends while "
                         "held-out AEE stays at the zero-flow level "
                         "(DESIGN.md r04). Generation is procedural, so "
                         "large values cost nothing.")
    ap.add_argument("--curriculum-start", type=float, default=1.0,
                    help="TRAIN displacement bound at step 0 of the "
                         "curriculum ramp. Sub-pixel values (continuous "
                         "styles only — blobs quantizes to whole pixels) "
                         "put EVERY pixel's zero-flow init inside the "
                         "warp's linear (Lucas-Kanade) regime, the "
                         "coherent-gradient condition for a plain conv "
                         "stack to lock onto input-dependence before the "
                         "ramp grows the task")
    ap.add_argument("--curriculum-steps", type=int, default=0,
                    help="ramp the TRAIN max_shift from 1 px to --max-shift "
                         "over this many steps (0 = off). Diagnosis (r04, "
                         "DESIGN.md): the loss valley to GT exists and is "
                         "monotone, but a shift beyond ~the blob sigma is "
                         "outside the finest levels' photometric basin "
                         "(weighted 16x), so training parks at zero-flow "
                         "regardless of photometric variant; starting "
                         "in-basin and ramping keeps the network locked on "
                         "— the classical coarse-to-fine trick, applied to "
                         "the data instead of the pyramid. Eval always "
                         "runs at the full --max-shift.")
    # Escalation levers (VERDICT r03 item 3): if the default recipe stalls
    # in a photometric basin, the chain's ladder ADDS these built quality
    # upgrades cumulatively so the artifacts record which added lever
    # cracked it.
    ap.add_argument("--photometric", default="charbonnier",
                    choices=("charbonnier", "census"))
    ap.add_argument("--smoothness-order", type=int, default=1,
                    choices=(1, 2))
    ap.add_argument("--occlusion", action="store_true")
    ap.add_argument("--lambda-smooth", type=float, default=1.0)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "synthetic_fit.jsonl"))
    args = ap.parse_args()

    # SIGTERM (the chain's `timeout`, the CPU guard's window kill) must
    # run the finally-block outcome write just like SIGINT does — without
    # this, a killed run leaves no terminal record (observed r05: the
    # blobs-2px run's outcome had to be reconstructed by hand)
    import signal

    signal.signal(signal.SIGTERM,
                  lambda *_: (_ for _ in ()).throw(SystemExit(143)))

    if args.devices > 0:
        force_cpu_devices(args.devices)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepof_tpu.core.config import (
        DataConfig,
        ExperimentConfig,
        LossConfig,
        MeshConfig,
        OptimConfig,
        TrainConfig,
    )
    from deepof_tpu.data.datasets import SyntheticData
    from deepof_tpu.models.registry import build_model
    from deepof_tpu.parallel.mesh import batch_sharding, build_mesh
    from deepof_tpu.train.evaluate import evaluate_aee
    from deepof_tpu.train.state import create_train_state, make_optimizer
    from deepof_tpu.train.step import make_eval_fn, make_train_step

    h = w = 64
    batch = args.batch
    cfg = ExperimentConfig(
        name="synthetic_fit",
        model=args.model,
        # the DEFAULT FlyingChairs loss config (`flyingChairsWrapFlow.py:
        # 43-49,120-123`): Charbonnier eps=1e-4 alpha_c=.25 alpha_s=.37,
        # lambda_smooth=1, weights 16/8/4/2/1/1 — unless an escalation
        # lever is set
        loss=LossConfig(weights=(16, 8, 4, 2, 1, 1),
                        photometric=args.photometric,
                        smoothness_order=args.smoothness_order,
                        occlusion=args.occlusion,
                        lambda_smooth=args.lambda_smooth),
        optim=OptimConfig(learning_rate=args.lr),
        data=DataConfig(dataset="synthetic", image_size=(h, w),
                        gt_size=(h, w), batch_size=batch),
        mesh=MeshConfig(),
        # FlyingChairs eval protocol: pr1 x 2, clip, AEE at GT resolution
        # (`flyingChairsTrain.py:264-296`)
        train=TrainConfig(seed=0, eval_amplifier=2.0, eval_clip=(-300, 250),
                          eval_batch_size=8,
                          log_dir=os.path.dirname(args.out) or "."),
    )
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data, num_train=args.num_train,
                       feature_scale=args.feature_scale,
                       max_shift=args.max_shift, style=args.style,
                       n_blobs=args.blobs)

    def curriculum_shift(s: int) -> float:
        """TRAIN displacement bound at step s: ramps 1 -> max_shift over
        curriculum_steps (integer-shift styles quantize it to whole
        pixels, rounded — so the ramp is a staircase, reaching the full
        bound at ~5/6 of the ramp). Eval and the zero-flow baseline
        always use the full max_shift (sample_val ignores the override)."""
        if not args.curriculum_steps:
            return args.max_shift
        frac = min(s / args.curriculum_steps, 1.0)
        start = args.curriculum_start
        return min(start + (args.max_shift - start) * frac, args.max_shift)
    model_kw = ({"max_disp": args.max_disp, "corr_stride": args.corr_stride}
                if args.model == "flownet_c" else {})
    model = build_model(args.model, width_mult=args.width_mult, **model_kw)

    def schedule(s):
        if not args.lr_decay_every:
            return args.lr
        return args.lr * 0.5 ** (s // args.lr_decay_every)

    tx = make_optimizer(cfg.optim, schedule)
    state = create_train_state(model, jnp.zeros((batch, h, w, 6)), tx, seed=0)
    # Resumable: a tunnel drop (or the chain's window guard) killing a fit
    # at step 29k must not cost the whole run — the chain's retry resumes
    # from the newest checkpoint. The ckpt dir is derived from --out so
    # every rung/backend combination keeps its own lineage. A config
    # fingerprint guards against silently resuming a checkpoint trained
    # under DIFFERENT hyper-parameters (same --out, new flags): mismatch
    # wipes the stale lineage and starts fresh.
    import shutil

    from deepof_tpu.train.checkpoint import CheckpointManager

    ckpt_dir = args.out + ".ckpt"
    fp_keys = (
        "model", "max_disp", "corr_stride",
        "lr", "lr_decay_every", "feature_scale", "max_shift", "style",
        "blobs", "batch", "photometric", "smoothness_order", "occlusion",
        "lambda_smooth", "width_mult", "curriculum_steps",
        "curriculum_start", "num_train")
    fingerprint = {k: getattr(args, k) for k in fp_keys}
    fingerprint["canvas_version"] = SyntheticData.CANVAS_VERSION
    # a lineage written before a knob existed has no key for it: the old
    # run used that knob's EFFECTIVE value at the time, so compare
    # missing keys against that — resuming is only valid when the current
    # value matches it (e.g. adding --curriculum-steps to an old lineage
    # must start fresh: the curriculum's whole point is easing lock-on
    # from init). For most knobs the historical value IS the argparse
    # default; knobs whose argparse default intentionally moved (and the
    # canvas generator version) carry explicit legacy values.
    fp_defaults = {k: ap.get_default(k) for k in fp_keys}
    fp_defaults["num_train"] = 64   # pre-knob runs used the class default
    fp_defaults["canvas_version"] = 1  # pre-r04 single-octave canvases
    fp_path = os.path.join(ckpt_dir, "config_fingerprint.json")
    if os.path.isdir(ckpt_dir):
        stale = args.fresh
        try:
            with open(fp_path) as fpf:
                loaded = json.load(fpf)
            stale = stale or {**fp_defaults, **loaded} != fingerprint
        except (OSError, ValueError):
            stale = True
        if stale:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
    ckpt = CheckpointManager(ckpt_dir, keep=1, async_save=False)
    restored = ckpt.restore(state)
    start_step = 0
    if restored is not None:
        state = restored
        start_step = int(state.step)
    os.makedirs(ckpt_dir, exist_ok=True)
    with open(fp_path, "w") as fpf:
        json.dump(fingerprint, fpf)
    step = make_train_step(model, cfg, ds.mean, mesh)
    eval_fn = make_eval_fn(model, cfg, ds.mean, mesh=mesh)

    # the zero-flow-collapse baseline this artifact is judged against,
    # computed on the actual held-out val split (it depends on the rng
    # draw order, hence on feature_scale)
    vflows = np.concatenate([ds.sample_val(8, i)["flow"] for i in range(2)])
    zero_epe = float(np.sqrt((vflows ** 2).sum(-1)).mean())

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    t0 = time.time()
    # Resume bookkeeping from the existing artifact: (a) the outcome
    # record must report the best AEE of the WHOLE lineage, not just this
    # process; (b) a predecessor killed mid-write can leave a truncated
    # final line — terminate it so the appended records stay one-JSON-
    # per-line parseable.
    prior_best, prior_best_step = float("inf"), 0
    needs_newline = False
    if start_step and os.path.exists(args.out):
        with open(args.out, "rb") as prev:
            raw = prev.read()
        needs_newline = bool(raw) and not raw.endswith(b"\n")
        for line in raw.decode("utf-8", errors="replace").splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # the truncated fragment
            if rec.get("kind") == "eval" and rec.get("aee") is not None:
                if rec["aee"] < prior_best:
                    prior_best, prior_best_step = rec["aee"], rec["step"]
    # append on resume: the artifact keeps the whole lineage, with a fresh
    # meta record marking where this process picked up
    with open(args.out, "a" if start_step else "w") as f:
        if needs_newline:
            f.write("\n")
        f.write(json.dumps({
            "kind": "meta", "model": cfg.model, "dataset": "synthetic",
            "resumed_from": start_step,
            "image_size": [h, w], "batch": batch, "lr": args.lr,
            "lr_decay_every": args.lr_decay_every,
            "feature_scale": args.feature_scale,
            "max_shift": args.max_shift,
            "style": args.style,
            "blobs": args.blobs,
            "width_mult": args.width_mult,
            "curriculum_steps": args.curriculum_steps,
            "curriculum_start": args.curriculum_start,
            "num_train": args.num_train,
            "zero_flow_epe": round(zero_epe, 4),
            "loss": (f"{args.photometric}, canonical order="
                     f"{args.smoothness_order}, lambda="
                     f"{args.lambda_smooth}, occlusion={args.occlusion}, "
                     "weights 16/8/4/2/1/1"),
            "eval": "pr1 x2, AEE at GT res, held-out synthetic val",
        }) + "\n")
        # seeded by start_step so a resume draws a fresh data stream
        # instead of replaying the batches already trained on (same
        # rationale as train/loop.py::data_stream_rng)
        rng = np.random.RandomState(start_step)
        best_aee, best_step = prior_best, prior_best_step
        done = {"written": False}

        def outcome(stopped_at: int, note: str) -> None:
            # the artifact's terminal record, emitted by THIS tool on
            # every exit path so the file is regenerable (ADVICE r02);
            # best_aee is null if no finite eval ever landed (divergence)
            done["written"] = True
            f.write(json.dumps({
                "kind": "outcome",
                "best_aee": round(best_aee, 4) if np.isfinite(best_aee)
                else None,
                "best_step": best_step, "stopped_at_step": stopped_at,
                "zero_flow_epe": round(zero_epe, 4), "note": note,
                "wall_s": round(time.time() - t0, 1)}) + "\n")
            f.flush()

        s = start_step
        completed = False
        try:
            for s in range(start_step, args.steps + 1):
                if s % args.eval_every == 0:
                    res = evaluate_aee(eval_fn, state.params, ds, cfg)
                    rec = {"kind": "eval", "step": s,
                           "aee": round(res["aee"], 4),
                           "aae": round(res["aae"], 4),
                           "val_loss": round(res["val_loss"], 4),
                           "lr": schedule(s),
                           "wall_s": round(time.time() - t0, 1)}
                    if res["aee"] < best_aee:
                        best_aee, best_step = res["aee"], s
                    f.write(json.dumps(rec) + "\n")
                    f.flush()
                    print(rec, flush=True)
                    if res["aee"] < args.target_epe:
                        print(f"target EPE {args.target_epe} reached at "
                              f"step {s}", flush=True)
                        outcome(s, f"target {args.target_epe} px reached")
                        # the lineage is complete — a later rerun with the
                        # same --out should start fresh, not resume past
                        # the finished run's final step
                        shutil.rmtree(ckpt_dir, ignore_errors=True)
                        return
                    if s > start_step:  # resume point for a killed run
                        ckpt.save(state)
                b = jax.device_put(
                    ds.sample_train(batch, rng=rng,
                                    max_shift=curriculum_shift(s)),
                    batch_sharding(mesh))
                state, _ = step(state, b)
            completed = True
        finally:
            if not done["written"]:
                # interrupted (Ctrl-C / error) or budget exhausted:
                # terminate the artifact either way, labeled truthfully
                note = ("step budget exhausted before target" if completed
                        else f"interrupted at step {s}")
                outcome(s, note)
        print("step budget exhausted before target EPE", flush=True)
        raise SystemExit(1)


if __name__ == "__main__":
    main()
