"""Headless serving benchmark: requests/s + latency percentiles against
a local InferenceEngine (no HTTP, no checkpoint needed).

Drives the dynamic micro-batcher with a configurable open-loop arrival
process (one request every --gap-ms) and reports one JSON line (same
convention as bench.py): throughput, p50/p99 latency, dispatch count,
mean batch occupancy. Two executors:

  --fake (default): a deterministic timed executor — sleeps --exec-ms
      per DISPATCH (batch-size independent, like a device whose forward
      is latency-bound) and computes flow as a cheap function of the
      input. Measures the batcher itself; runs anywhere in
      milliseconds; the fast-tier schema smoke test uses this.
  --real: builds the config's model with randomly initialized params
      (or restores --log-dir's newest verified checkpoint when given)
      and measures true end-to-end engine throughput.

--serial additionally runs the identical workload through a max_batch=1
engine (the serial per-pair dispatch pattern) and reports the speedup —
the dynamic-batching win as one number.

--fleet N instead benchmarks the self-healing serving FLEET end to end
(serve/fleet.py + serve/router.py): N fake-executor replica
subprocesses behind the health-gated router, driven closed-loop by
--clients concurrent HTTP clients, then the identical workload against
a 1-replica fleet — `speedup_vs_single` is the fleet scale-out win
through the full HTTP + routing + supervision path.

--stream instead benchmarks the streaming video-session API
(serve/session.py): a closed-loop client walks the SAME frame sequence
twice — once as a session (`engine.submit_next`, one decode per frame)
and once as the equivalent pairwise `/v1/flow` walk (two decodes per
pair) — against an engine whose decode is instrumented with an injected
per-decode delay (`--decode-ms`), the honest stand-in for real
jpeg/png decode + preprocess cost on a decode-bound workload. Reports
`stream_speedup` (the ISSUE 10 acceptance: >= 1.5x on a decode-bound
walk), the measured decode-count delta, and `flow_bitwise_equal` — the
streamed flows must be bit-identical to the pairwise walk's. Every
--stream result ALSO carries the temporal warm-start block
(`warm_stream_bench`): a REAL flownet_s warm-vs-cold session walk over
identical seeded coherent frames reporting `warm_speedup` (ISSUE 11
acceptance: >= 1.3 — the refinement-only executable vs the full cold
network) and `epe_vs_cold` (quality gate: <= 0.5 px).

--precision [f32,bf16,int8] sweeps the mixed-precision serving tiers
(serve/quant.py) through ONE real-model engine: per tier it reports
requests/s, p50/p99 latency, the weight bytes each dispatch moves, and
`epe_vs_f32` — mean endpoint error against the f32 tier's flows on the
identical seeded synthetic pairs (the tier's accuracy cost as one
number). Runs the real flownet_s forward (random init, or --log-dir's
checkpoint), so expect seconds of compile per (bucket, tier) on a cold
cache; honest note: on cpu proxies int8 rarely wins wall-clock — the
tier exists for device windows where weight bandwidth is the limiter.

Run: python tools/serve_bench.py [--requests 64] [--gap-ms 1]
     [--max-batch 8] [--timeout-ms 10] [--exec-ms 10] [--serial]
     python tools/serve_bench.py --fleet 2 [--clients 8]
     python tools/serve_bench.py --precision f32,bf16,int8 \
         [--requests 24] [--bucket 32x64]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from deepof_tpu.core.config import get_config  # noqa: E402
from deepof_tpu.serve.engine import (InferenceEngine,  # noqa: E402
                                     make_fake_forward)

#: keys every serve_bench JSON result carries (schema smoke test)
REQUIRED_KEYS = (
    "mode", "requests", "errors", "wall_s", "requests_per_s",
    "latency_p50_ms", "latency_p99_ms", "dispatches", "occupancy_mean",
    "max_batch", "timeout_ms", "gap_ms",
)

#: keys every --fleet result carries
FLEET_REQUIRED_KEYS = (
    "mode", "replicas", "clients", "requests", "errors", "wall_s",
    "requests_per_s", "single_wall_s", "single_requests_per_s",
    "speedup_vs_single", "failovers", "shed", "max_batch", "fake_exec_ms",
)

#: keys every --ramp result carries (schema smoke test): the bursty-load
#: autoscaler exercise — staged warm/burst/scaled-burst/idle phases of
#: closed-loop clients against a live autoscaling fleet. The ISSUE 14
#: shape: sheds_burst > 0 at the min-replicas pool, scale_ups >= 1,
#: sheds_after_scale ~ 0 once capacity arrived, scale_downs >= 1 after
#: sustained idle (graceful drain: retired == scale_downs, evictions
#: 0), drops == 0 (every admitted request resolved to a response).
RAMP_REQUIRED_KEYS = (
    "mode", "min_replicas", "max_replicas", "burst_clients", "phases",
    "requests", "requests_per_s", "errors", "drops", "sheds_burst",
    "sheds_after_scale", "scale_ups", "scale_downs", "retired",
    "evictions", "peak_replicas", "final_replicas", "scale_up_latency_s",
    "scale_up_to_first_response_ms", "predictive_sheds", "reactive_sheds",
    "predictive_shed_delta", "autoscale_up_slope",
    "wall_s", "max_batch", "fake_exec_ms", "max_in_flight",
)

#: keys every --artifact-cold result carries (schema smoke test): the
#: r16/r17 zero-cold-start acceptance A/B/C — one `warmup --serve`
#: publish into the executable artifact store (which also writes the
#: executable index), then the SAME cold engine warm three times (jax
#: caches cleared between legs): compile-bound (store off), fingerprint
#: boot (store on, index off — the r16 path that still traces+lowers
#: to compute the integrity fingerprint), and index boot (store + index
#: on — zero trace/lower on the resolve path). `cold_start_speedup` is
#: now compile wall / INDEX wall (the r17 headline);
#: `fingerprint_boot_speedup` keeps the r16 figure's continuity and
#: `index_vs_artifact_speedup` isolates what the index alone bought.
#: The index leg must show ladder-many `index_hits` and zero
#: misses/rejects or the index is not actually serving the boot.
ARTIFACT_COLD_REQUIRED_KEYS = (
    "mode", "model", "width_mult", "bucket", "tiers", "ladder",
    "publish_wall_s", "publish_compile_s", "warm_wall_compile_s",
    "warm_wall_artifact_s", "warm_wall_index_s", "cold_start_speedup",
    "fingerprint_boot_speedup", "index_vs_artifact_speedup",
    "acquire_compile_s", "acquire_fetch_s", "acquire_speedup",
    "artifact_hits", "artifact_misses", "artifact_rejects",
    "index_hits", "index_misses", "index_rejects",
    "store_entries", "store_bytes",
)

#: keys every --stream result carries (schema smoke test). The warm_*
#: block is the r11 temporal warm-start axis: a REAL-model warm-vs-cold
#: walk over identical seeded frames — `warm_speedup` (ISSUE 11
#: acceptance: >= 1.3 on the cpu proxy) and `epe_vs_cold` (quality
#: gate: <= 0.5 px) ride every --stream result, pinned here.
STREAM_REQUIRED_KEYS = (
    "mode", "frames", "flows", "errors", "wall_s", "frames_per_s",
    "pairwise_wall_s", "pairwise_frames_per_s", "stream_speedup",
    "stream_decodes", "pairwise_decodes", "decode_delta", "decode_saved",
    "flow_bitwise_equal", "latency_p50_ms", "latency_p99_ms",
    "max_batch", "timeout_ms", "decode_ms", "fake_exec_ms", "bucket",
    "warm_speedup", "epe_vs_cold", "warm_frames", "warm_steps",
    "warm_cold_fallbacks", "warm_width", "warm_bucket",
    "warm_latency_p50_ms", "warm_cold_latency_p50_ms",
)

#: keys every --precision result carries at the top level ...
PRECISION_REQUIRED_KEYS = (
    "mode", "requests", "max_batch", "timeout_ms", "gap_ms", "bucket",
    "precisions", "tiers",
)
#: ... and per tier inside result["tiers"][<tier>]
TIER_REQUIRED_KEYS = (
    "requests_per_s", "latency_p50_ms", "latency_p99_ms", "epe_vs_f32",
    "errors", "wall_s", "weight_bytes",
)

#: keys every --ledger result carries (schema smoke test): the
#: executable-ledger block (obs/ledger.py) over a real-model engine —
#: lattice compile seconds + fingerprints + nominal-roofline MFU from
#: the recorded ledger.jsonl, and the ledger's hot-path cost as a p99
#: pair (ledger on vs off on the identical seeded workload; the ISSUE
#: 15 acceptance bounds p99_overhead_pct <= 2)
LEDGER_REQUIRED_KEYS = (
    "mode", "requests", "max_batch", "timeout_ms", "gap_ms", "bucket",
    "lowerings", "compile_s_total", "mfu_nominal", "recompiles",
    "cache_hits", "cache_misses", "executables",
    "rps_ledger_off", "rps_ledger_on",
    "p99_ledger_off_ms", "p99_ledger_on_ms", "p99_overhead_pct",
)

#: keys every --incidents result carries (schema smoke test): the
#: incident flight recorder's hot-path cost as a p99 pair — identical
#: seeded workloads with obs.incidents off vs ON with an idle recorder
#: (alert rules installed, no trigger ever fires; the ISSUE 18
#: acceptance bounds p99_overhead_pct <= 1)
INCIDENT_REQUIRED_KEYS = (
    "mode", "requests", "max_batch", "timeout_ms", "gap_ms", "bucket",
    "alert_rules", "captured", "rps_incidents_off", "rps_incidents_on",
    "p99_incidents_off_ms", "p99_incidents_on_ms", "p99_overhead_pct",
)

#: keys every --quality result carries at the top level (schema smoke
#: test): per-tier label-free proxy scores on the standard seeded pairs
#: plus the scorer-overhead pair the ISSUE 13 acceptance reads
#: (sample_rate 0.1 p99 must degrade < 5% vs off)
QUALITY_REQUIRED_KEYS = (
    "mode", "requests", "max_batch", "timeout_ms", "gap_ms", "bucket",
    "precisions", "tiers", "quality", "sample_rate",
    "rps_quality_off", "rps_quality_on", "scorer_overhead_pct",
    "p99_quality_off_ms", "p99_quality_on_ms", "p99_overhead_pct",
)
#: ... and per tier inside result["tiers"][<tier>]
QUALITY_TIER_REQUIRED_KEYS = ("photo", "smooth", "census", "scored")

#: keys every --brownout result carries (schema smoke test): the r19
#: overload-ramp A/B — the identical mixed-priority overload (default
#: clients inside fleet capacity + low-priority clients pushing past
#: it) against two fresh 2-replica fleets, brownout controller ON vs
#: OFF. The headline is default_shed_delta: with the controller off,
#: saturation 503s land on default-priority traffic too
#: (default_sheds_off >= 1); with it on, the ladder walks to L3 and
#: sheds ONLY low-priority work at admission (default_sheds_on == 0 in
#: the counted window), with the tier/bucket downgrade counters proving
#: the intermediate rungs actually served cheaper.
BROWNOUT_REQUIRED_KEYS = (
    "mode", "replicas", "default_clients", "low_clients", "window_s",
    "max_batch", "fake_exec_ms", "max_in_flight",
    "default_sheds_off", "default_sheds_on", "default_shed_delta",
    "shed_low_on", "max_level_on", "transitions_on",
    "tier_downgrades_on", "bucket_downgrades_on",
    "p99_default_off_ms", "p99_default_on_ms",
    "low_ok_off", "low_ok_on", "drops", "wall_s",
)


def _bench_cfg(bucket: tuple[int, int], max_batch: int, timeout_ms: float,
               log_dir: str | None):
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=bucket, gt_size=bucket),
        serve=dataclasses.replace(cfg.serve, max_batch=max_batch,
                                  batch_timeout_ms=timeout_ms),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e4, 1e4)))
    if log_dir:
        cfg = cfg.replace(train=dataclasses.replace(cfg.train,
                                                    log_dir=log_dir))
    return cfg


def _real_model_params(cfg):
    import jax
    import jax.numpy as jnp

    from deepof_tpu.serve.engine import build_serve_model

    model = build_serve_model(cfg)
    h, w = cfg.data.image_size
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, h, w, 3 * cfg.data.time_step)))
    return model, variables["params"]


def run_workload(engine: InferenceEngine, requests: list, gap_ms: float,
                 precision: str | None = None):
    """Open-loop arrival: submit with a fixed inter-arrival gap, then
    wait for every future. Returns (wall_s, errors, results)."""
    t0 = time.perf_counter()
    futures = []
    for prev, nxt in requests:
        futures.append(engine.submit(prev, nxt, precision=precision))
        if gap_ms > 0:
            time.sleep(gap_ms / 1e3)
    results, errors = [], 0
    for fut in futures:
        try:
            results.append(fut.result(timeout=120.0))
        except Exception:  # noqa: BLE001 - counted, benchmark continues
            errors += 1
            results.append(None)
    return time.perf_counter() - t0, errors, results


def serve_bench(requests: int = 64, gap_ms: float = 1.0, max_batch: int = 8,
                timeout_ms: float = 10.0, exec_ms: float = 10.0,
                bucket: tuple[int, int] = (64, 64),
                native_hw: tuple[int, int] = (48, 96), fake: bool = True,
                log_dir: str | None = None, serial: bool = False) -> dict:
    cfg = _bench_cfg(bucket, max_batch, timeout_ms, log_dir)
    rng = np.random.RandomState(0)
    pairs = [(rng.randint(0, 255, (*native_hw, 3), dtype=np.uint8),
              rng.randint(0, 255, (*native_hw, 3), dtype=np.uint8))
             for _ in range(max(int(requests), 1))]

    if fake:
        make_engine = lambda c: InferenceEngine(  # noqa: E731
            c, forward_fn=make_fake_forward(exec_ms))
        mode = "fake"
    else:
        model_params = (_real_model_params(cfg) if not log_dir else None)
        make_engine = lambda c: InferenceEngine(  # noqa: E731
            c, model_params=model_params)
        mode = "real"

    with make_engine(cfg) as engine:
        engine.warm()
        wall, errors, _ = run_workload(engine, pairs, gap_ms)
        stats = engine.stats()

    out = {
        "mode": mode, "requests": len(pairs), "errors": errors,
        "wall_s": round(wall, 4),
        "requests_per_s": round((len(pairs) - errors) / wall, 2),
        "latency_p50_ms": stats["serve_latency_p50_ms"],
        "latency_p99_ms": stats["serve_latency_p99_ms"],
        "dispatches": stats["serve_batches"],
        "occupancy_mean": stats["serve_occupancy_mean"],
        "max_batch": max_batch, "timeout_ms": timeout_ms, "gap_ms": gap_ms,
        "fake_exec_ms": exec_ms if fake else None,
        "bucket": list(bucket),
    }
    if serial:
        scfg = cfg.replace(serve=dataclasses.replace(cfg.serve, max_batch=1))
        with make_engine(scfg) as eng1:
            eng1.warm()
            swall, serr, _ = run_workload(eng1, pairs, gap_ms)
        out["serial_wall_s"] = round(swall, 4)
        out["serial_requests_per_s"] = round((len(pairs) - serr) / swall, 2)
        out["speedup_vs_serial"] = round(swall / wall, 2) if wall > 0 else None
    return out


# ------------------------------------------------------------ stream


def _instrument_decode(engine, decode_ms: float, counter: dict) -> None:
    """Wrap the engine's decode with a per-decode delay + call counter:
    the injected stand-in for real image decode + preprocess cost (the
    synthetic arrays the bench feeds decode in microseconds, which would
    hide exactly the work the session cache exists to halve)."""
    orig = engine._decode

    def decode(img):
        counter["n"] += 1
        if decode_ms > 0:
            time.sleep(decode_ms / 1e3)
        return orig(img)

    engine._decode = decode


def stream_bench(frames: int = 32, decode_ms: float = 20.0,
                 exec_ms: float = 2.0, max_batch: int = 4,
                 timeout_ms: float = 2.0, bucket: tuple[int, int] = (32, 64),
                 native_hw: tuple[int, int] = (30, 60),
                 warm_frames: int = 16, warm_width: float = 0.5,
                 warm_bucket: tuple[int, int] = (64, 128),
                 warm_native: tuple[int, int] = (60, 120),
                 log_dir: str | None = None) -> dict:
    """Closed-loop video walk, streamed vs pairwise (see module
    docstring). Both walks drive the identical frame sequence through
    identically configured engines with the same injected decode delay;
    the only variable is the session cache — so `stream_speedup` is the
    one-decode-per-frame win and nothing else. The result additionally
    carries the `warm_*` block from `warm_stream_bench` (real-model
    temporal warm-start vs cold full network — its own engines, its own
    bucket), so one `--stream` run reports both streaming axes."""
    from deepof_tpu.serve.engine import ServeError  # noqa: F401 (doc)

    cfg = _bench_cfg(bucket, max_batch, timeout_ms, log_dir)
    rng = np.random.RandomState(0)
    frames = max(int(frames), 2)
    imgs = [rng.randint(1, 255, (*native_hw, 3), dtype=np.uint8)
            for _ in range(frames)]

    def walk_pairwise():
        counter = {"n": 0}
        flows, errors = [], 0
        with InferenceEngine(cfg, forward_fn=make_fake_forward(
                exec_ms)) as engine:
            engine.warm()
            _instrument_decode(engine, decode_ms, counter)
            t0 = time.perf_counter()
            for prev, nxt in zip(imgs, imgs[1:]):
                try:
                    flows.append(engine.submit(prev, nxt).result(
                        timeout=120.0)["flow"])
                except Exception:  # noqa: BLE001 - counted
                    errors += 1
                    flows.append(None)
            wall = time.perf_counter() - t0
        return wall, errors, flows, counter["n"], None

    def walk_stream():
        counter = {"n": 0}
        flows, errors = [], 0
        with InferenceEngine(cfg, forward_fn=make_fake_forward(
                exec_ms)) as engine:
            engine.warm()
            _instrument_decode(engine, decode_ms, counter)
            t0 = time.perf_counter()
            primed = engine.submit_next("bench", imgs[0]).result(
                timeout=120.0)
            assert primed.get("primed"), primed
            for frame in imgs[1:]:
                try:
                    flows.append(engine.submit_next("bench", frame).result(
                        timeout=120.0)["flow"])
                except Exception:  # noqa: BLE001 - counted
                    errors += 1
                    flows.append(None)
            wall = time.perf_counter() - t0
            stats = engine.stats()
        return wall, errors, flows, counter["n"], stats

    pw_wall, pw_err, pw_flows, pw_decodes, _ = walk_pairwise()
    st_wall, st_err, st_flows, st_decodes, st_stats = walk_stream()
    if warm_frames > 0:
        warm = warm_stream_bench(frames=warm_frames, warm_width=warm_width,
                                 bucket=warm_bucket, native_hw=warm_native,
                                 log_dir=log_dir)
    else:
        # --warm-frames 0: skip the real-model warm walk (keeps the
        # decode-bound fake-executor bench jax-free); the pinned keys
        # stay present, as nulls
        warm = {k: None for k in STREAM_REQUIRED_KEYS
                if k.startswith(("warm_", "epe_"))}

    n_flows = frames - 1
    equal = bool(pw_flows and len(pw_flows) == len(st_flows) and all(
        a is not None and b is not None and np.array_equal(a, b)
        for a, b in zip(pw_flows, st_flows)))
    st_rate = ((n_flows - st_err) / st_wall) if st_wall > 0 else None
    pw_rate = ((n_flows - pw_err) / pw_wall) if pw_wall > 0 else None
    return {
        "mode": "stream", "frames": frames, "flows": n_flows,
        "errors": st_err, "wall_s": round(st_wall, 4),
        "frames_per_s": round(st_rate, 2) if st_rate else None,
        "pairwise_errors": pw_err,
        "pairwise_wall_s": round(pw_wall, 4),
        "pairwise_frames_per_s": round(pw_rate, 2) if pw_rate else None,
        "stream_speedup": (round(st_rate / pw_rate, 2)
                           if st_rate and pw_rate else None),
        # measured decode ledger: N for the stream, 2(N-1) pairwise —
        # the one-decode-per-frame contract as raw counts
        "stream_decodes": st_decodes,
        "pairwise_decodes": pw_decodes,
        "decode_delta": pw_decodes - st_decodes,
        "decode_saved": st_stats["serve_sessions_decode_saved"],
        "flow_bitwise_equal": equal,
        "latency_p50_ms": st_stats["serve_session_latency_p50_ms"],
        "latency_p99_ms": st_stats["serve_session_latency_p99_ms"],
        "session_frames": st_stats["serve_sessions_frames"],
        "max_batch": max_batch, "timeout_ms": timeout_ms,
        "decode_ms": decode_ms, "fake_exec_ms": exec_ms,
        "bucket": list(bucket),
        **warm,
    }


# ------------------------------------------------------ warm-start


def _coherent_walk(rng, native_hw: tuple[int, int], frames: int,
                   noise: int = 6) -> list:
    """A temporally coherent seeded frame walk: every frame is the same
    base image under small independent pixel noise — the synthetic
    stand-in for consecutive video frames. Temporal coherence is the
    premise temporal warm-start exploits; iid random frames (the
    decode-bound walk's workload) would make `epe_vs_cold` measure
    noise, not the warm path."""
    base = rng.randint(1, 255, (*native_hw, 3)).astype(np.int16)
    return [np.clip(base + rng.randint(-noise, noise + 1, base.shape),
                    0, 255).astype(np.uint8) for _ in range(frames)]


def warm_stream_bench(frames: int = 16, warm_width: float = 0.5,
                      max_batch: int = 1, model_width: float = 0.5,
                      bucket: tuple[int, int] = (64, 128),
                      native_hw: tuple[int, int] = (60, 120),
                      log_dir: str | None = None) -> dict:
    """Temporal warm-start vs cold, REAL model (flownet_s, random init
    or --log-dir's checkpoint): the identical seeded coherent frame walk
    runs twice through session engines differing ONLY in
    `serve.session.warm_start` — cold dispatches the full network every
    step, warm dispatches the refinement-only executable once a prior
    flow exists. The walks are INTERLEAVED step by step (alternating
    order) so host-load noise hits both paths equally, and
    `warm_speedup` is the ratio of median per-step latencies — the
    executables' story, not the scheduler's. `epe_vs_cold` is the mean
    endpoint error of the warm walk's flows against the cold walk's on
    the same steps — the quality gate that makes the cheaper path
    provably not a quality regression.

    model_width: the COLD network's width multiplier — 0.5 here, not
    the suite's usual 0.25 thin variant, because `scaled_width`'s
    8-channel floor clips the refinement stage's width cut at
    0.25 x warm_width and would understate a ratio that is
    architecture-real at production widths (the floor artifact)."""
    frames = max(int(frames), 3)
    cfg = _bench_cfg(bucket, max_batch, 0.0, log_dir)
    cfg = cfg.replace(width_mult=model_width)

    def _session_cfg(warm: bool):
        return cfg.replace(serve=dataclasses.replace(
            cfg.serve, session=dataclasses.replace(
                cfg.serve.session, warm_start=warm,
                warm_width=warm_width)))

    model_params = (_real_model_params(_session_cfg(True))
                    if not log_dir else None)
    rng = np.random.RandomState(0)
    imgs = _coherent_walk(rng, native_hw, frames)

    # INTERLEAVED measurement: both engines live at once, and every
    # frame steps the cold walk and the warm walk back to back (order
    # alternating per frame). On a small contended host, sequential
    # walks seconds apart see different machines — interleaving makes
    # host-load noise hit both paths equally, so the median-latency
    # ratio measures the executables, not the scheduler.
    def step(engine, frame, flows, lats, errs):
        try:
            r = engine.submit_next("warm-bench", frame).result(120.0)
            flows.append(r["flow"])
            lats.append(r["latency_s"])
        except Exception:  # noqa: BLE001 - counted
            errs.append(1)
            flows.append(None)

    cold_flows, cold_lats, cold_errs = [], [], []
    warm_flows, warm_lats, warm_errs = [], [], []
    with InferenceEngine(_session_cfg(False),
                         model_params=model_params) as cold_eng, \
            InferenceEngine(_session_cfg(True),
                            model_params=model_params) as warm_eng:
        cold_eng.warm()
        warm_eng.warm()  # both lattices AOT-compiled before timing
        assert cold_eng.submit_next("warm-bench",
                                    imgs[0]).result(120.0).get("primed")
        assert warm_eng.submit_next("warm-bench",
                                    imgs[0]).result(120.0).get("primed")
        t0 = time.perf_counter()
        for i, frame in enumerate(imgs[1:]):
            order = ((cold_eng, cold_flows, cold_lats, cold_errs),
                     (warm_eng, warm_flows, warm_lats, warm_errs))
            for eng, flows, lats, errs in (order if i % 2 == 0
                                           else order[::-1]):
                step(eng, frame, flows, lats, errs)
        wall = time.perf_counter() - t0
        warm_stats = warm_eng.stats()
    cold_err, warm_err = len(cold_errs), len(warm_errs)

    deltas = [float(np.mean(np.sqrt(np.sum((a - b) ** 2, -1))))
              for a, b in zip(warm_flows, cold_flows)
              if a is not None and b is not None]
    med_warm = float(np.median(warm_lats)) if warm_lats else None
    med_cold = float(np.median(cold_lats)) if cold_lats else None
    return {
        "warm_frames": frames,
        "warm_errors": warm_err,
        "warm_cold_errors": cold_err,  # the cold REFERENCE walk's errors
        # one shared wall: the walks interleave in a single window
        "warm_wall_s": round(wall, 4),
        "warm_latency_p50_ms": (round(1e3 * med_warm, 3)
                                if med_warm else None),
        "warm_cold_latency_p50_ms": (round(1e3 * med_cold, 3)
                                     if med_cold else None),
        "warm_speedup": (round(med_cold / med_warm, 2)
                         if med_warm and med_cold else None),
        "epe_vs_cold": (round(float(np.mean(deltas)), 6)
                        if deltas else None),
        "warm_steps": warm_stats["serve_sessions_warm_steps"],
        "warm_cold_fallbacks": warm_stats["serve_sessions_cold_fallbacks"],
        "warm_width": warm_width,
        "warm_model_width": model_width,
        "warm_bucket": list(bucket),
    }


# --------------------------------------------------------- precision


def _percentile_ms(latencies_s: list, frac: float):
    if not latencies_s:
        return None
    lat = sorted(latencies_s)
    return round(1e3 * lat[int(frac * (len(lat) - 1))], 3)


def precision_bench(requests: int = 24, gap_ms: float = 0.5,
                    max_batch: int = 4, timeout_ms: float = 5.0,
                    bucket: tuple[int, int] = (32, 64),
                    native_hw: tuple[int, int] = (30, 60),
                    tiers: tuple[str, ...] = ("f32", "bf16", "int8"),
                    log_dir: str | None = None) -> dict:
    """Sweep the precision tiers through ONE engine on the REAL model
    forward: per tier, requests/s + p50/p99 over the identical seeded
    workload, the tier's params-tree bytes, and mean-EPE of its flows
    against the f32 tier's (the accuracy cost of the operating point).
    f32 always runs (it is the EPE reference), first."""
    from deepof_tpu.serve.quant import params_nbytes, resolve_precisions

    tiers = tuple(t for t in tiers if t != "f32")
    tiers = ("f32",) + tiers  # reference tier first, exactly once
    cfg = _bench_cfg(bucket, max_batch, timeout_ms, log_dir)
    cfg = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                precisions=tiers))
    resolve_precisions(cfg)  # fail fast on an unknown tier name
    model_params = (_real_model_params(cfg) if not log_dir else None)

    rng = np.random.RandomState(0)
    pairs = [(rng.randint(0, 255, (*native_hw, 3), dtype=np.uint8),
              rng.randint(0, 255, (*native_hw, 3), dtype=np.uint8))
             for _ in range(max(int(requests), 1))]

    out = {"mode": "precision", "requests": len(pairs),
           "max_batch": max_batch, "timeout_ms": timeout_ms,
           "gap_ms": gap_ms, "bucket": list(bucket),
           "precisions": list(tiers), "tiers": {}}
    f32_flows = None
    with InferenceEngine(cfg, model_params=model_params) as engine:
        engine.warm()
        for tier in tiers:
            wall, errors, results = run_workload(engine, pairs, gap_ms,
                                                 precision=tier)
            flows = [r["flow"] if r is not None else None for r in results]
            if tier == "f32":
                f32_flows = flows
            epe = None
            if f32_flows is not None:
                deltas = [float(np.mean(np.sqrt(np.sum((a - b) ** 2, -1))))
                          for a, b in zip(flows, f32_flows)
                          if a is not None and b is not None]
                epe = round(float(np.mean(deltas)), 6) if deltas else None
            lats = [r["latency_s"] for r in results if r is not None]
            out["tiers"][tier] = {
                "wall_s": round(wall, 4),
                "requests_per_s": round((len(pairs) - errors) / wall, 2),
                "latency_p50_ms": _percentile_ms(lats, 0.50),
                "latency_p99_ms": _percentile_ms(lats, 0.99),
                "epe_vs_f32": epe,
                "errors": errors,
                "weight_bytes": params_nbytes(
                    engine._params_by_tier[tier]),
            }
    return out


# ----------------------------------------------------------- quality


def quality_bench(requests: int = 24, gap_ms: float = 0.5,
                  max_batch: int = 4, timeout_ms: float = 5.0,
                  bucket: tuple[int, int] = (32, 64),
                  native_hw: tuple[int, int] = (30, 60),
                  tiers: tuple[str, ...] = ("f32", "bf16", "int8"),
                  sample_rate: float = 0.1,
                  log_dir: str | None = None) -> dict:
    """Label-free quality-proxy block (obs/quality.py) on the standard
    seeded pairs, two phases through the REAL model forward:

      scores  one engine at sample_rate 1.0 runs the identical seeded
              workload per precision tier and reports the mean photo /
              smooth / census proxies per tier (from the per-key sum
              maps — the same numbers a fleet merge would re-derive),
              plus the drift-verdict block after the whole sweep.
      overhead  two fresh engines (quality off vs sample_rate
              `sample_rate`) run the f32 workload; the requests/s and
              p99 deltas are the scorer's hot-path cost — the ISSUE 13
              acceptance wants p99 degradation < 5% at 0.1.
    """
    import dataclasses as dc

    tiers = ("f32",) + tuple(t for t in tiers if t != "f32")
    cfg = _bench_cfg(bucket, max_batch, timeout_ms, log_dir)
    cfg = cfg.replace(serve=dc.replace(cfg.serve, precisions=tiers))
    model_params = (_real_model_params(cfg) if not log_dir else None)

    rng = np.random.RandomState(0)
    pairs = [(rng.randint(0, 255, (*native_hw, 3), dtype=np.uint8),
              rng.randint(0, 255, (*native_hw, 3), dtype=np.uint8))
             for _ in range(max(int(requests), 1))]

    def q_cfg(rate: float):
        return cfg.replace(obs=dc.replace(cfg.obs,
                                          quality_sample_rate=rate))

    out = {"mode": "quality", "requests": len(pairs),
           "max_batch": max_batch, "timeout_ms": timeout_ms,
           "gap_ms": gap_ms, "bucket": list(bucket),
           "precisions": list(tiers), "sample_rate": sample_rate,
           "tiers": {}}
    # phase 1: per-tier proxy scores at sample_rate 1.0
    with InferenceEngine(q_cfg(1.0), model_params=model_params) as engine:
        engine.warm()
        for tier in tiers:
            run_workload(engine, pairs, gap_ms, precision=tier)
        engine._quality.drain(120.0)
        stats = engine.stats()
        scored = stats["serve_quality_scored_by_key"]
        sums = {"photo": stats["serve_quality_photo_sum_by_key"],
                "smooth": stats["serve_quality_smooth_sum_by_key"],
                "census": stats["serve_quality_census_sum_by_key"]}
        for tier in tiers:
            key = f"{tier}/cold"
            n = scored.get(key, 0)
            out["tiers"][tier] = {
                "scored": n,
                **{proxy: (round(sums[proxy].get(key, 0.0) / n, 6)
                           if n else None)
                   for proxy in ("photo", "smooth", "census")},
            }
        out["quality"] = stats["serve_quality"]
        out["dropped"] = stats["serve_quality_dropped"]
    # phase 2: scorer overhead — identical f32 workload, quality off vs
    # sampled at `sample_rate` (fresh engines: no warm-cache crosstalk)
    def timed(rate: float):
        with InferenceEngine(q_cfg(rate),
                             model_params=model_params) as eng:
            eng.warm()
            wall, errors, results = run_workload(eng, pairs, gap_ms)
            lats = [r["latency_s"] for r in results if r is not None]
            if eng._quality is not None:
                eng._quality.drain(120.0)
        rps = (len(pairs) - errors) / wall if wall > 0 else None
        return rps, _percentile_ms(lats, 0.99)

    rps_off, p99_off = timed(0.0)
    rps_on, p99_on = timed(float(sample_rate))
    out["rps_quality_off"] = round(rps_off, 2) if rps_off else None
    out["rps_quality_on"] = round(rps_on, 2) if rps_on else None
    out["scorer_overhead_pct"] = (
        round(100.0 * (rps_off - rps_on) / rps_off, 2)
        if rps_off and rps_on else None)
    out["p99_quality_off_ms"] = p99_off
    out["p99_quality_on_ms"] = p99_on
    out["p99_overhead_pct"] = (round(100.0 * (p99_on - p99_off) / p99_off, 2)
                               if p99_off and p99_on else None)
    return out


# ------------------------------------------------------------ ledger


def ledger_bench(requests: int = 24, gap_ms: float = 0.5,
                 max_batch: int = 4, timeout_ms: float = 5.0,
                 bucket: tuple[int, int] = (32, 64),
                 native_hw: tuple[int, int] = (30, 60),
                 log_dir: str | None = None) -> dict:
    """Executable-ledger block (obs/ledger.py) on the REAL model
    forward, two phases:

      provenance  one engine with obs.ledger on runs the seeded
                  workload; the recorded ledger.jsonl yields the
                  lattice's compile seconds, fingerprints, cache
                  provenance, and per-executable nominal-roofline MFU
                  (exec_timing rows written at engine close) — the
                  BENCH "ledger" block tools/bench_trend.py trends.
      overhead    a fresh engine with obs.ledger OFF runs the identical
                  workload; the p99 delta is the ledger's whole
                  hot-path cost (one perf_counter + dict update per
                  flush). The ISSUE 15 acceptance bounds it <= 2% of
                  serve p99.
    """
    import dataclasses as dc
    import tempfile

    from deepof_tpu.obs.ledger import load_ledger

    cfg0 = _bench_cfg(bucket, max_batch, timeout_ms, log_dir)
    model_params = (_real_model_params(cfg0) if not log_dir else None)
    # ledger rows need a run dir; without --log-dir use a fresh temp.
    # Either way the reported provenance is floored at this bench's own
    # start time below: a reused --log-dir appends to an existing
    # ledger.jsonl, and stale rows from an earlier run/config must not
    # pollute the executables map or compile_s_total (the PR 14 ramp
    # stale-record class).
    run_dir = log_dir or tempfile.mkdtemp(prefix="ledger_bench_")

    rng = np.random.RandomState(0)
    pairs = [(rng.randint(0, 255, (*native_hw, 3), dtype=np.uint8),
              rng.randint(0, 255, (*native_hw, 3), dtype=np.uint8))
             for _ in range(max(int(requests), 1))]

    def timed(ledger_on: bool):
        cfg = cfg0.replace(
            obs=dc.replace(cfg0.obs, ledger=ledger_on),
            train=dc.replace(cfg0.train, log_dir=run_dir))
        with InferenceEngine(cfg, model_params=model_params) as eng:
            eng.warm()
            # a discarded pre-workload: the first flushes of a fresh
            # engine pay one-time costs (executable resolution, lazy
            # imports) that would otherwise dominate the measured p99
            # on this small sample — the overhead pair must compare
            # steady-state hot paths
            run_workload(eng, pairs[:max(int(max_batch), 2)], gap_ms)
            wall, errors, results = run_workload(eng, pairs, gap_ms)
            lats = [r["latency_s"] for r in results if r is not None]
            stats = eng.stats()
        rps = (len(pairs) - errors) / wall if wall > 0 else None
        return rps, _percentile_ms(lats, 0.99), stats

    rps_off, p99_off, _ = timed(False)
    # rows carry time rounded to 1 ms; the tiny slack only covers that
    # rounding — every reported row must be from the ledger-on run below
    t_ledger_run = time.time() - 0.05
    rps_on, p99_on, stats_on = timed(True)

    rows = [r for r in load_ledger(run_dir)
            if (r.get("time") or 0) >= t_ledger_run]
    execs = {r["name"]: r for r in rows if r.get("kind") == "exec"}
    timings = {r["name"]: r for r in rows if r.get("kind") == "exec_timing"}
    executables = {
        name: {"compile_s": r.get("compile_s"),
               "fingerprint": r.get("fingerprint"),
               "mfu_nominal": (timings.get(name) or {}).get("mfu_nominal")}
        for name, r in sorted(execs.items())}
    mfus = [e["mfu_nominal"] for e in executables.values()
            if isinstance(e["mfu_nominal"], (int, float))]
    compile_s = [r.get("compile_s") for r in execs.values()
                 if isinstance(r.get("compile_s"), (int, float))]

    return {
        "mode": "ledger", "requests": len(pairs),
        "max_batch": max_batch, "timeout_ms": timeout_ms,
        "gap_ms": gap_ms, "bucket": list(bucket),
        "lowerings": stats_on.get("exec_lowerings"),
        "recompiles": stats_on.get("exec_recompiles"),
        "cache_hits": stats_on.get("exec_cache_hits"),
        "cache_misses": stats_on.get("exec_cache_misses"),
        "compile_s_total": (round(sum(compile_s), 3)
                            if compile_s else None),
        "mfu_nominal": round(max(mfus), 6) if mfus else None,
        "executables": executables,
        # 0.0 is a real (worst-possible) figure bench_trend must see —
        # only an incomputable rate records null (the PR 14 ramp
        # requests_per_s falsy-zero class)
        "rps_ledger_off": (round(rps_off, 2) if rps_off is not None
                           else None),
        "rps_ledger_on": (round(rps_on, 2) if rps_on is not None
                          else None),
        "p99_ledger_off_ms": p99_off,
        "p99_ledger_on_ms": p99_on,
        # p99_off must be truthy (the denominator); a collapsed-to-zero
        # p99_on still yields a computable -100% overhead
        "p99_overhead_pct": (round(100.0 * (p99_on - p99_off) / p99_off, 2)
                             if p99_off and p99_on is not None else None),
    }


# ---------------------------------------------------------- incidents


def incident_bench(requests: int = 24, gap_ms: float = 0.5,
                   max_batch: int = 4, timeout_ms: float = 5.0,
                   bucket: tuple[int, int] = (32, 64),
                   native_hw: tuple[int, int] = (30, 60),
                   log_dir: str | None = None) -> dict:
    """Incident-plane hot-path cost (obs/incident.py): the identical
    seeded REAL-model workload with obs.incidents off vs ON with an
    idle recorder — alert rules installed and evaluated on the stats
    cadence, but no trigger ever fires. The recorder touches nothing
    per-request (its only hot-path surface is the engine stats pass),
    so the p99 delta is the plane's whole serving cost; the ISSUE 18
    acceptance bounds it <= 1% of serve p99."""
    import dataclasses as dc
    import tempfile

    from deepof_tpu.obs import incident as obs_incident

    cfg0 = _bench_cfg(bucket, max_batch, timeout_ms, log_dir)
    model_params = (_real_model_params(cfg0) if not log_dir else None)
    run_dir = log_dir or tempfile.mkdtemp(prefix="incident_bench_")

    rng = np.random.RandomState(0)
    pairs = [(rng.randint(0, 255, (*native_hw, 3), dtype=np.uint8),
              rng.randint(0, 255, (*native_hw, 3), dtype=np.uint8))
             for _ in range(max(int(requests), 1))]

    def timed(on: bool):
        cfg = cfg0.replace(
            obs=dc.replace(cfg0.obs, incidents=on,
                           # a registered, never-satisfiable rule rides
                           # along so the installed recorder has
                           # production shape (rules parse at install;
                           # they evaluate on the heartbeat cadence,
                           # never per request)
                           alerts=(("serve_errors > 1e12",) if on
                                   else ())),
            train=dc.replace(cfg0.train, log_dir=run_dir))
        with InferenceEngine(cfg, model_params=model_params) as eng:
            eng.incidents = obs_incident.install(cfg, run_dir, "serve")
            eng.warm()
            # discarded pre-workload: steady-state hot paths only
            # (same rationale as ledger_bench)
            run_workload(eng, pairs[:max(int(max_batch), 2)], gap_ms)
            wall, errors, results = run_workload(eng, pairs, gap_ms)
            lats = [r["latency_s"] for r in results if r is not None]
            stats = eng.stats()
        rps = (len(pairs) - errors) / wall if wall > 0 else None
        return rps, _percentile_ms(lats, 0.99), stats

    rps_off, p99_off, _ = timed(False)
    rps_on, p99_on, stats_on = timed(True)
    return {
        "mode": "incidents", "requests": len(pairs),
        "max_batch": max_batch, "timeout_ms": timeout_ms,
        "gap_ms": gap_ms, "bucket": list(bucket),
        "alert_rules": stats_on.get("alert_rules"),
        # no trigger fires on this healthy workload: stays 0, and the
        # series in bench_trend.py pins the round's bundle count
        "captured": stats_on.get("incident_captured"),
        "rps_incidents_off": (round(rps_off, 2) if rps_off is not None
                              else None),
        "rps_incidents_on": (round(rps_on, 2) if rps_on is not None
                             else None),
        "p99_incidents_off_ms": p99_off,
        "p99_incidents_on_ms": p99_on,
        "p99_overhead_pct": (round(100.0 * (p99_on - p99_off) / p99_off, 2)
                             if p99_off and p99_on is not None else None),
    }


# ------------------------------------------------------------- fleet


def _fleet_cfg(log_dir: str, max_batch: int, timeout_ms: float,
               exec_ms: float, bucket: tuple[int, int]):
    import dataclasses as dc

    cfg = _bench_cfg(bucket, max_batch, timeout_ms, log_dir)
    return cfg.replace(
        serve=dc.replace(
            cfg.serve, fake_exec_ms=exec_ms, host="127.0.0.1", port=0,
            fleet=dc.replace(cfg.serve.fleet, poll_s=0.2, stale_after_s=10.0,
                             spawn_timeout_s=90.0, proxy_timeout_s=30.0,
                             max_in_flight=256, drain_timeout_s=5.0)),
        obs=dc.replace(cfg.obs, heartbeat_period_s=0.5))


def _flow_body(native_hw: tuple[int, int]) -> bytes:
    import base64

    import cv2

    rng = np.random.RandomState(0)
    imgs = []
    for _ in range(2):
        ok, buf = cv2.imencode(
            ".png", rng.randint(1, 255, (*native_hw, 3), dtype=np.uint8))
        assert ok
        imgs.append(base64.b64encode(buf.tobytes()).decode())
    return json.dumps({"prev": imgs[0], "next": imgs[1]}).encode()


def _drive_closed_loop(port: int, body: bytes, requests: int,
                       clients: int) -> tuple[float, int, int]:
    """`clients` threads each run a keep-alive connection and pull
    request slots from a shared counter until `requests` are done.
    Returns (wall_s, completed_200, errors)."""
    import http.client
    import itertools

    counter = itertools.count()
    ok_count = [0] * clients
    err_count = [0] * clients

    def worker(slot: int) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            while next(counter) < requests:
                try:
                    conn.request("POST", "/v1/flow", body,
                                 {"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    resp.read()
                    if resp.status == 200:
                        ok_count[slot] += 1
                    else:
                        err_count[slot] += 1
                except Exception:  # noqa: BLE001 - counted, keep driving
                    err_count[slot] += 1
                    conn.close()
                    conn = http.client.HTTPConnection("127.0.0.1", port,
                                                      timeout=60)
        finally:
            conn.close()

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.perf_counter() - t0, sum(ok_count), sum(err_count)


def _scrape_metrics(port: int) -> dict:
    """GET /metrics on the router and read the fleet-aggregated samples
    back through the Prometheus parser — the bench figures come off the
    SAME scrape path an operator's collector uses, so the bench and the
    live counters cannot drift apart silently."""
    import http.client

    from deepof_tpu.obs.export import parse_prometheus

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", "/metrics")
        samples = parse_prometheus(conn.getresponse().read().decode())
    finally:
        conn.close()
    return {
        "fleet_requests": samples.get("deepof_fleet_requests"),
        "fleet_responses": samples.get("deepof_fleet_responses"),
        "serve_responses": samples.get("deepof_serve_responses"),
        # bench report fields READ BACK from the /metrics scrape (histogram
        # series names, not new stats counters) — hence the waivers:
        # lint: counter-registry-ok(bench report field read back from /metrics)
        "serve_latency_count": samples.get("deepof_serve_latency_ms_count"),
        # lint: counter-registry-ok(bench report field read back from /metrics)
        "serve_latency_sum_ms": samples.get("deepof_serve_latency_ms_sum"),
        # lint: counter-registry-ok(bench report field read back from /metrics)
        "fleet_latency_count": samples.get("deepof_fleet_latency_ms_count"),
        # autoscale counters ride the same operator scrape path (None
        # for a fixed, non-autoscaling fleet)
        "fleet_autoscale_up": samples.get("deepof_fleet_autoscale_up"),
        "fleet_autoscale_down": samples.get("deepof_fleet_autoscale_down"),
    }


def _run_fleet_once(cfg, replicas: int, body: bytes, requests: int,
                    clients: int) -> dict:
    from deepof_tpu.serve.fleet import Fleet
    from deepof_tpu.serve.router import Router, build_router_server

    with Fleet(cfg, replicas) as fleet:
        fleet.start()
        fleet.wait_ready(min_ready=replicas,
                         timeout_s=cfg.serve.fleet.spawn_timeout_s)
        router = Router(cfg, fleet)
        httpd = build_router_server(cfg, router)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        scrape = None
        try:
            port = httpd.server_address[1]
            wall, ok, err = _drive_closed_loop(port, body, requests, clients)
            try:
                scrape = _scrape_metrics(port)
            except Exception:  # noqa: BLE001 - the scrape must not fail the bench
                scrape = None
        finally:
            router.draining = True
            httpd.shutdown()
            httpd.server_close()
        stats = {**fleet.stats(), **router.stats()}
    return {"wall_s": wall, "ok": ok, "errors": err, "stats": stats,
            "scrape": scrape}


def fleet_bench(replicas: int = 2, requests: int = 96, clients: int = 8,
                max_batch: int = 4, timeout_ms: float = 5.0,
                exec_ms: float = 20.0, bucket: tuple[int, int] = (32, 64),
                native_hw: tuple[int, int] = (30, 60),
                log_dir: str | None = None) -> dict:
    """End-to-end fleet benchmark (closed loop): N replicas behind the
    router vs the identical workload against 1 replica. The fake
    executor sleeps per dispatch, so the fleet win is real dispatch
    parallelism, not GIL luck."""
    import tempfile

    base = log_dir or tempfile.mkdtemp(prefix="serve_bench_fleet_")
    body = _flow_body(native_hw)
    replicas = max(int(replicas), 2)

    multi = _run_fleet_once(
        _fleet_cfg(os.path.join(base, f"fleet{replicas}"), max_batch,
                   timeout_ms, exec_ms, bucket),
        replicas, body, requests, clients)
    single = _run_fleet_once(
        _fleet_cfg(os.path.join(base, "fleet1"), max_batch, timeout_ms,
                   exec_ms, bucket),
        1, body, requests, clients)

    rps = ((requests - multi["errors"]) / multi["wall_s"]
           if multi["wall_s"] > 0 else None)
    srps = ((requests - single["errors"]) / single["wall_s"]
            if single["wall_s"] > 0 else None)
    return {
        "mode": "fleet", "replicas": replicas, "clients": clients,
        "requests": requests, "errors": multi["errors"],
        "wall_s": round(multi["wall_s"], 4),
        "requests_per_s": round(rps, 2) if rps else None,
        "single_errors": single["errors"],
        "single_wall_s": round(single["wall_s"], 4),
        "single_requests_per_s": round(srps, 2) if srps else None,
        "speedup_vs_single": (round(rps / srps, 2)
                              if rps and srps else None),
        "failovers": multi["stats"]["fleet_failovers"],
        "shed": multi["stats"]["fleet_shed"],
        "routed": multi["stats"]["fleet_routed"],
        "max_batch": max_batch, "timeout_ms": timeout_ms,
        "fake_exec_ms": exec_ms, "bucket": list(bucket), "log_dir": base,
        # the router's live /metrics scrape at the end of the window —
        # the bench's request counts, re-read through Prometheus
        "metrics_scrape": multi["scrape"],
    }


# -------------------------------------------------------------- ramp


def _drive_timed(port: int, body: bytes, clients: int,
                 duration_s: float, headers: dict | None = None,
                 collect_latency: bool = False) -> dict:
    """Closed-loop client pool for a fixed WINDOW (the ramp phases are
    time-staged, not count-staged): every worker hammers until the
    deadline. Returns {"ok", "errors", "drops"} — errors are structured
    non-200 replies (shed 503s land here), drops are transport-level
    failures where the client got NO response at all (the
    zero-silent-drops ledger; the router must make this 0). `headers`
    ride every request on top of Content-Type (the brownout A/B sends
    X-Priority/X-Deadline-Ms through here); `collect_latency` adds
    client-observed latency_p50_ms/latency_p99_ms over the 200s."""
    import http.client

    deadline = time.perf_counter() + max(float(duration_s), 0.0)
    ok = [0] * clients
    err = [0] * clients
    drops = [0] * clients
    lats: list[list[float]] = [[] for _ in range(clients)]
    hdrs = {"Content-Type": "application/json", **(headers or {})}

    def worker(slot: int) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            while time.perf_counter() < deadline:
                try:
                    t_req = time.perf_counter()
                    conn.request("POST", "/v1/flow", body, hdrs)
                    resp = conn.getresponse()
                    resp.read()
                    if resp.status == 200:
                        ok[slot] += 1
                        if collect_latency:
                            lats[slot].append(
                                (time.perf_counter() - t_req) * 1e3)
                    else:
                        err[slot] += 1
                except Exception:  # noqa: BLE001 - a silent drop, counted
                    drops[slot] += 1
                    conn.close()
                    conn = http.client.HTTPConnection("127.0.0.1", port,
                                                      timeout=60)
        finally:
            conn.close()

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out = {"ok": sum(ok), "errors": sum(err), "drops": sum(drops),
           "t0": round(t0, 2), "t1": round(time.time(), 2)}
    if collect_latency:
        flat = sorted(x for slot in lats for x in slot)
        out["latency_p50_ms"] = (
            round(flat[len(flat) // 2], 2) if flat else None)
        out["latency_p99_ms"] = (
            round(flat[min(int(len(flat) * 0.99), len(flat) - 1)], 2)
            if flat else None)
    return out


def _ramp_cfg(log_dir: str, max_replicas: int, max_batch: int,
              timeout_ms: float, exec_ms: float, max_in_flight: int,
              bucket: tuple[int, int], slope: float = 0.0):
    """Fast-cadence autoscaling fleet config: sub-second control loop,
    short sustain windows/cooldowns — the same policy shape as
    production, compressed so a bench run finishes in tens of seconds.
    `slope` > 0 arms the predictive load-slope scale-up signal."""
    import dataclasses as dc

    cfg = _fleet_cfg(log_dir, max_batch, timeout_ms, exec_ms, bucket)
    return cfg.replace(serve=dc.replace(
        cfg.serve,
        fleet=dc.replace(cfg.serve.fleet, autoscale=True, min_replicas=1,
                         max_replicas=max_replicas,
                         max_in_flight=max_in_flight,
                         autoscale_period_s=0.25,
                         autoscale_up_after_s=0.5,
                         autoscale_down_after_s=2.0,
                         autoscale_up_cooldown_s=1.0,
                         autoscale_down_cooldown_s=2.0,
                         autoscale_up_slope=slope)))


def _slope_leg(base: str, slope: float, max_replicas: int, max_batch: int,
               timeout_ms: float, exec_ms: float, max_in_flight: int,
               bucket: tuple[int, int], body: bytes,
               burst_clients: int, step_s: float = 1.0) -> dict:
    """One predictive-vs-reactive compare leg: a FRESH 1-replica
    autoscaling fleet under an incrementally ramped closed-loop drive
    (1 -> burst_clients clients, one more per `step_s`) — the load shape
    where a positive completions/s slope is visible BEFORE occupancy or
    sheds are. With `slope` armed the pool scales on the trend; with
    slope 0 it scales only after the reactive pressure sustains. The
    leg's shed count is the figure the delta is built from."""
    from deepof_tpu.serve.autoscale import Autoscaler
    from deepof_tpu.serve.fleet import Fleet
    from deepof_tpu.serve.router import Router, build_router_server

    cfg = _ramp_cfg(base, max_replicas, max_batch, timeout_ms, exec_ms,
                    max_in_flight, bucket, slope=slope)
    fc = cfg.serve.fleet
    out = {"ok": 0, "errors": 0, "drops": 0}
    with Fleet(cfg) as fleet:
        fleet.start()
        fleet.wait_ready(min_ready=1, timeout_s=fc.spawn_timeout_s)
        router = Router(cfg, fleet)
        fleet.on_retired = router.retire_slot
        httpd = build_router_server(cfg, router)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        port = httpd.server_address[1]
        scaler = Autoscaler(cfg, fleet, router)
        router.autoscale_stats = scaler.stats
        scaler.start()
        try:
            for clients in range(1, burst_clients + 1):
                chunk = _drive_timed(port, body, clients, step_s)
                for k in ("ok", "errors", "drops"):
                    out[k] += chunk[k]
            rs = router.stats()
            ss = scaler.stats()
            out.update({
                "slope": slope,
                "sheds": rs["fleet_shed"] + rs["fleet_unavailable"],
                "scale_ups": ss["fleet_autoscale_up"],
                "slope_ticks": ss.get("fleet_autoscale_slope_ticks", 0),
                "final_replicas": fleet.size,
            })
        finally:
            scaler.close()
            router.draining = True
            httpd.shutdown()
            httpd.server_close()
    return out


def ramp_bench(max_replicas: int = 3, burst_clients: int = 8,
               warm_s: float = 2.0, burst_s: float = 8.0,
               idle_s: float = 20.0, max_batch: int = 2,
               timeout_ms: float = 2.0, exec_ms: float = 30.0,
               max_in_flight: int = 4, bucket: tuple[int, int] = (32, 64),
               native_hw: tuple[int, int] = (30, 60),
               slope_threshold: float = 2.0,
               log_dir: str | None = None) -> dict:
    """Bursty-load autoscaler exercise, end to end and in-process
    (Fleet + Router + Autoscaler, fake-executor replica subprocesses):

      warm    1 closed-loop client against the min_replicas pool —
              the steady trickle a right-sized pool absorbs.
      burst   `burst_clients` clients against the same 1-replica pool:
              with max_in_flight * 1 slots the router SHEDS
              (sheds_burst), occupancy pins at 1.0, and the autoscaler
              scales up (scale_up_latency_s = burst start -> first
              scale-up event).
      scaled burst  once every scaled-up replica is ready (capacity
              max_replicas * max_in_flight > burst_clients), the same
              burst again: sheds_after_scale must collapse to ~0 —
              the load-follower absorbed the burst.
      idle    no load: sustained idle walks the pool back down via
              graceful drain (retired == scale_downs, evictions == 0),
              then one probe request proves the shrunken pool serves.

    drops counts transport-level no-response failures across ALL
    phases — the zero-silent-drops ledger; scale events ride the
    router's /metrics scrape (`metrics_scrape`) exactly as an
    operator's collector would see them.

    Two r16 figures ride the result: `scale_up_to_first_response_ms`
    (first scale-up event -> first request ADMITTED to the scaled-up
    replica, watched at 20 ms off the router's per-replica routed
    counters; the fake executor completes within one exec quantum of
    admission) and the predictive-vs-reactive compare — two extra
    fresh-fleet legs under an incrementally ramped drive, one with the
    load-slope signal armed at `slope_threshold`, one reactive-only;
    `predictive_shed_delta` = reactive sheds - predictive sheds, the
    sheds the trend signal pre-empted."""
    import tempfile

    from deepof_tpu.serve.autoscale import Autoscaler
    from deepof_tpu.serve.fleet import Fleet
    from deepof_tpu.serve.router import Router, build_router_server

    base = log_dir or tempfile.mkdtemp(prefix="serve_bench_ramp_")
    body = _flow_body(native_hw)
    max_replicas = max(int(max_replicas), 2)
    cfg = _ramp_cfg(base, max_replicas, max_batch, timeout_ms, exec_ms,
                    max_in_flight, bucket)
    fc = cfg.serve.fleet
    phases: dict[str, dict] = {}
    t_start = time.perf_counter()
    t_run_wall = time.time()  # scale-record scan floor: a reused
    #   --log-dir appends to an existing metrics.jsonl, and a previous
    #   run's scale_up record would yield a bogus (negative) latency
    with Fleet(cfg) as fleet:
        fleet.start()
        fleet.wait_ready(min_ready=1, timeout_s=fc.spawn_timeout_s)
        router = Router(cfg, fleet)
        fleet.on_retired = router.retire_slot
        httpd = build_router_server(cfg, router)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        port = httpd.server_address[1]
        scaler = Autoscaler(cfg, fleet, router)
        router.autoscale_stats = scaler.stats  # scrape-visible
        scaler.start()
        scrape = None
        peak = fleet.size
        try:
            def shed_now() -> int:
                rs = router.stats()
                return rs["fleet_shed"] + rs["fleet_unavailable"]

            phases["warm"] = _drive_timed(port, body, 1, warm_s)

            # spawn -> first response: watch the router's per-replica
            # routed counters for the first request admitted to a
            # replica that did not exist before the burst (admission is
            # within one exec quantum of its 200 — the fake executor
            # never fails a routed request here)
            baseline_names = set(router.stats()["fleet_routed"])
            first_new_resp: list[float | None] = [None]
            watch_stop = threading.Event()

            def _watch_first_response() -> None:
                while not watch_stop.is_set():
                    try:
                        routed = router.stats()["fleet_routed"]
                    except Exception:  # noqa: BLE001 - watcher must not raise
                        return
                    for rname, n in routed.items():
                        if rname not in baseline_names and n > 0:
                            first_new_resp[0] = time.time()
                            return
                    time.sleep(0.02)

            watcher = threading.Thread(target=_watch_first_response,
                                       daemon=True)
            watcher.start()

            shed0 = shed_now()
            t_burst_wall = time.time()
            phases["burst"] = _drive_timed(port, body, burst_clients,
                                           burst_s)
            sheds_burst = shed_now() - shed0

            # hold: a light trickle while the scaled-up replicas finish
            # spawning — a zero-load gap would sustain "idle" and walk
            # the pool straight back down before the scaled burst could
            # measure it (real bursts decay to baseline, not silence);
            # 2 clients sit inside the hysteresis band at any pool size.
            # The scaled burst measures CAPACITY, not startup latency,
            # so wait until the pool can absorb the whole burst width.
            hold = {"ok": 0, "errors": 0, "drops": 0,
                    "t0": round(time.time(), 2)}
            deadline = time.monotonic() + float(fc.spawn_timeout_s)
            while time.monotonic() < deadline:
                ready = fleet.stats()["fleet_ready"]
                if (ready >= scaler.max
                        or ready * max_in_flight > burst_clients):
                    break
                chunk = _drive_timed(port, body, 2, 0.5)
                for k in ("ok", "errors", "drops"):
                    hold[k] += chunk[k]
            hold["t1"] = round(time.time(), 2)
            phases["hold"] = hold
            watch_stop.set()
            watcher.join(timeout=1.0)
            peak = max(peak, fleet.size)
            up_events = scaler.stats()["fleet_autoscale_up"]
            first_up = None
            if up_events:
                # first scale-up's latency from the burst start, read
                # from the kind="fleet" records the autoscaler appended
                try:
                    with open(os.path.join(base, "metrics.jsonl")) as f:
                        for line in f:
                            rec = json.loads(line)
                            if (rec.get("kind") == "fleet"
                                    and rec.get("event") == "scale_up"
                                    and rec.get("time", 0.0)
                                    >= t_run_wall):
                                first_up = rec["time"]
                                break
                except (OSError, ValueError):
                    pass

            shed1 = shed_now()
            phases["scaled_burst"] = _drive_timed(port, body,
                                                  burst_clients, burst_s)
            sheds_after = shed_now() - shed1
            peak = max(peak, fleet.size)

            # idle: sustained zero load walks the pool back down
            deadline = time.monotonic() + max(float(idle_s), 0.0)
            while time.monotonic() < deadline:
                if (scaler.stats()["fleet_autoscale_down"] > 0
                        and fleet.size <= scaler.min):
                    break
                time.sleep(0.25)
            probe = _drive_timed(port, body, 1, 1.0)  # shrunken pool serves
            phases["probe"] = probe
            try:
                scrape = _scrape_metrics(port)
            except Exception:  # noqa: BLE001 - scrape must not fail the bench
                scrape = None
            sstats = scaler.stats()
            fstats = fleet.stats()
        finally:
            scaler.close()
            router.draining = True
            httpd.shutdown()
            httpd.server_close()
    # predictive-vs-reactive: two fresh fleets under the SAME ramped
    # drive — slope armed vs reactive-only. Run after the main drill so
    # its fleet is fully torn down (ports, subprocesses) first.
    predictive = _slope_leg(os.path.join(base, "leg_predictive"),
                            slope_threshold, max_replicas, max_batch,
                            timeout_ms, exec_ms, max_in_flight, bucket,
                            body, burst_clients)
    reactive = _slope_leg(os.path.join(base, "leg_reactive"), 0.0,
                          max_replicas, max_batch, timeout_ms, exec_ms,
                          max_in_flight, bucket, body, burst_clients)
    wall = time.perf_counter() - t_start

    total = {k: sum(p[k] for p in phases.values())
             for k in ("ok", "errors", "drops")}
    burst_rate = (phases["scaled_burst"]["ok"] / burst_s
                  if burst_s > 0 else None)
    return {
        "mode": "ramp", "min_replicas": 1, "max_replicas": max_replicas,
        "burst_clients": burst_clients,
        "phases": {name: dict(p) for name, p in phases.items()},
        "requests": sum(total.values()),
        "requests_per_s": (round(burst_rate, 2)
                           if burst_rate is not None else None),
        "errors": total["errors"],
        "drops": total["drops"],
        "sheds_burst": sheds_burst,
        "sheds_after_scale": sheds_after,
        "scale_ups": sstats["fleet_autoscale_up"],
        "scale_downs": sstats["fleet_autoscale_down"],
        "retired": fstats["fleet_retired"],
        "evictions": fstats["fleet_evictions"],
        "peak_replicas": peak,
        "final_replicas": fstats["fleet_replicas"],
        "scale_up_latency_s": (round(first_up - t_burst_wall, 2)
                               if first_up else None),
        "scale_up_to_first_response_ms": (
            round((first_new_resp[0] - first_up) * 1000.0, 1)
            if first_up and first_new_resp[0] else None),
        "predictive_sheds": predictive["sheds"],
        "reactive_sheds": reactive["sheds"],
        "predictive_shed_delta": reactive["sheds"] - predictive["sheds"],
        "autoscale_up_slope": slope_threshold,
        "compare_legs": {"predictive": predictive, "reactive": reactive},
        "wall_s": round(wall, 2),
        "max_batch": max_batch, "fake_exec_ms": exec_ms,
        "max_in_flight": max_in_flight, "bucket": list(bucket),
        "log_dir": base,
        "metrics_scrape": scrape,
    }


# ---------------------------------------------------------- brownout


def _brownout_cfg(log_dir: str, max_batch: int, timeout_ms: float,
                  exec_ms: float, max_in_flight: int,
                  bucket: tuple[int, int], enabled: bool):
    """Fleet config for one brownout A/B leg: a 2-rung bucket ladder
    and a 2-tier precision ladder (so L1/L2 have somewhere cheaper to
    go), a small per-replica in-flight cap (so the overload actually
    saturates), and — on the ON leg — the degrade controller at a
    compressed cadence (the same policy shape as production, like
    `_ramp_cfg` compresses the autoscaler). recover_after_s is set
    LONGER than the counted window: the drill measures protection at
    L3, not the recovery walk (tests/test_degrade.py owns hysteresis)."""
    import dataclasses as dc

    cfg = _fleet_cfg(log_dir, max_batch, timeout_ms, exec_ms, bucket)
    small = (max(bucket[0] // 2, 8), max(bucket[1] // 2, 8))
    return cfg.replace(serve=dc.replace(
        cfg.serve,
        buckets=(small, tuple(bucket)),
        precisions=("f32", "bf16"),
        fleet=dc.replace(cfg.serve.fleet, max_in_flight=max_in_flight),
        degrade=dc.replace(cfg.serve.degrade, enabled=enabled,
                           period_s=0.1, escalate_after_s=0.2,
                           recover_after_s=5.0, escalate_cooldown_s=0.3,
                           recover_cooldown_s=1.0)))


def _brownout_leg(base: str, enabled: bool, replicas: int,
                  default_clients: int, low_clients: int, ramp_s: float,
                  window_s: float, max_batch: int, timeout_ms: float,
                  exec_ms: float, max_in_flight: int,
                  bucket: tuple[int, int], body: bytes) -> dict:
    """One brownout leg: a FRESH fleet under the identical
    mixed-priority overload — `default_clients` closed-loop clients
    inside fleet capacity (each carrying a generous X-Deadline-Ms, so
    the deadline plumbing is live end to end) plus `low_clients`
    X-Priority:low clients pushing the pool past saturation. Ramp
    phase drives until the ON leg's controller reaches L3 (bounded),
    then the counted window measures per-priority outcomes. Figures
    come off the router's live /metrics scrape — the same path an
    operator's collector reads."""
    from deepof_tpu.obs.export import parse_prometheus
    from deepof_tpu.serve.fleet import Fleet
    from deepof_tpu.serve.router import Router, build_router_server

    cfg = _brownout_cfg(base, max_batch, timeout_ms, exec_ms,
                        max_in_flight, bucket, enabled)
    out: dict = {"enabled": enabled}
    max_level = 0
    with Fleet(cfg, replicas) as fleet:
        fleet.start()
        fleet.wait_ready(min_ready=replicas,
                         timeout_s=cfg.serve.fleet.spawn_timeout_s)
        router = Router(cfg, fleet)
        httpd = build_router_server(cfg, router)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        port = httpd.server_address[1]
        degr = None
        if enabled:
            from deepof_tpu.serve.degrade import DegradeController

            degr = DegradeController(cfg, fleet, router)
            router.degrade_stats = degr.stats  # scrape-visible
            router.degrade_level = degr.level  # folded into routing
            degr.start()
        try:
            def drive_mix(duration: float) -> tuple[dict, dict]:
                res: list[dict | None] = [None, None]

                def run(i, clients, headers, lat):
                    res[i] = _drive_timed(port, body, clients, duration,
                                          headers=headers,
                                          collect_latency=lat)

                pools = [
                    threading.Thread(target=run, args=(
                        0, default_clients,
                        {"X-Deadline-Ms": "5000"}, True)),
                    threading.Thread(target=run, args=(
                        1, low_clients, {"X-Priority": "low"}, False)),
                ]
                for t in pools:
                    t.start()
                for t in pools:
                    t.join()
                return res[0], res[1]

            # ramp: overload until the ON leg's ladder reaches L3 (the
            # OFF leg gets the same minimum warm so the A/B windows see
            # comparable queue state); bounded so a wedged controller
            # fails the bench visibly instead of hanging it
            ramp = {"ok": 0, "errors": 0, "drops": 0}
            ramp_deadline = time.monotonic() + (
                max(ramp_s, 10.0) if enabled else ramp_s)
            min_until = time.monotonic() + ramp_s
            while time.monotonic() < ramp_deadline:
                d, lo = drive_mix(0.5)
                for k in ramp:
                    ramp[k] += d[k] + lo[k]
                if degr is not None:
                    max_level = max(max_level, degr.level())
                if time.monotonic() >= min_until and (
                        degr is None or max_level >= 3):
                    break
            out["ramp"] = ramp

            # counted window: per-priority outcomes under the sustained
            # overload — client-observed, so a shed is a shed whether it
            # was the router's saturation 503 or the L3 priority shed
            shed0 = router.stats()["fleet_shed"]
            d, lo = drive_mix(window_s)
            if degr is not None:
                max_level = max(max_level, degr.level())
            rs = router.stats()
            out.update({
                "default_ok": d["ok"], "default_sheds": d["errors"],
                "low_ok": lo["ok"], "low_errors": lo["errors"],
                "drops": ramp["drops"] + d["drops"] + lo["drops"],
                "latency_p50_ms": d["latency_p50_ms"],
                "latency_p99_ms": d["latency_p99_ms"],
                "saturation_sheds_window": rs["fleet_shed"] - shed0,
                "shed_low": rs.get("degrade_shed_low", 0),
                "max_level": max_level,
                "transitions": rs.get("degrade_transitions", 0),
                "escalations": rs.get("degrade_escalations", 0),
                "recoveries": rs.get("degrade_recoveries", 0),
            })

            # engine-side counters ride replica /healthz -> the fleet-
            # aggregated /metrics scrape (all registry-declared keys)
            import http.client

            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            try:
                conn.request("GET", "/metrics")
                samples = parse_prometheus(
                    conn.getresponse().read().decode())
            finally:
                conn.close()
            out.update({
                "tier_downgrades": samples.get(
                    "deepof_degrade_tier_downgrades", 0),
                "bucket_downgrades": samples.get(
                    "deepof_degrade_bucket_downgrades", 0),
                "requests_with_deadline": samples.get(
                    "deepof_deadline_requests", 0),
                "scrape_degrade_level": samples.get("deepof_degrade_level"),
            })
        finally:
            if degr is not None:
                degr.close()
            router.draining = True
            httpd.shutdown()
            httpd.server_close()
    return out


def brownout_bench(replicas: int = 2, default_clients: int = 3,
                   low_clients: int = 8, ramp_s: float = 2.0,
                   window_s: float = 3.0, max_batch: int = 2,
                   timeout_ms: float = 2.0, exec_ms: float = 30.0,
                   max_in_flight: int = 2,
                   bucket: tuple[int, int] = (32, 64),
                   native_hw: tuple[int, int] = (30, 60),
                   log_dir: str | None = None) -> dict:
    """The r19 brownout A/B (DESIGN.md "Brownout"): the identical
    mixed-priority overload against two fresh fleets — controller OFF
    (saturation sheds land indiscriminately, default-priority traffic
    included) then ON (the ladder walks L1 tier -> L2 bucket -> L3
    priority shed, recompile-free, and default-priority traffic rides
    out the overload unshedded). `default_shed_delta` is the headline:
    the default-priority sheds the brownout plane absorbed."""
    import tempfile

    base = log_dir or tempfile.mkdtemp(prefix="serve_bench_brownout_")
    body = _flow_body(native_hw)
    replicas = max(int(replicas), 2)
    t0 = time.perf_counter()

    off = _brownout_leg(os.path.join(base, "leg_off"), False, replicas,
                        default_clients, low_clients, ramp_s, window_s,
                        max_batch, timeout_ms, exec_ms, max_in_flight,
                        bucket, body)
    on = _brownout_leg(os.path.join(base, "leg_on"), True, replicas,
                       default_clients, low_clients, ramp_s, window_s,
                       max_batch, timeout_ms, exec_ms, max_in_flight,
                       bucket, body)

    return {
        "mode": "brownout", "replicas": replicas,
        "default_clients": default_clients, "low_clients": low_clients,
        "window_s": window_s,
        "default_sheds_off": off["default_sheds"],
        "default_sheds_on": on["default_sheds"],
        "default_shed_delta": (off["default_sheds"]
                               - on["default_sheds"]),
        "shed_low_on": on["shed_low"],
        "max_level_on": on["max_level"],
        "transitions_on": on["transitions"],
        "escalations_on": on["escalations"],
        "recoveries_on": on["recoveries"],
        "tier_downgrades_on": on["tier_downgrades"],
        "bucket_downgrades_on": on["bucket_downgrades"],
        "requests_with_deadline_on": on["requests_with_deadline"],
        "p99_default_off_ms": off["latency_p99_ms"],
        "p99_default_on_ms": on["latency_p99_ms"],
        "p50_default_off_ms": off["latency_p50_ms"],
        "p50_default_on_ms": on["latency_p50_ms"],
        "default_ok_off": off["default_ok"],
        "default_ok_on": on["default_ok"],
        "low_ok_off": off["low_ok"], "low_ok_on": on["low_ok"],
        "low_errors_off": off["low_errors"],
        "low_errors_on": on["low_errors"],
        "drops": off["drops"] + on["drops"],
        "wall_s": round(time.perf_counter() - t0, 2),
        "max_batch": max_batch, "fake_exec_ms": exec_ms,
        "max_in_flight": max_in_flight, "bucket": list(bucket),
        "log_dir": base,
        "legs": {"off": off, "on": on},
    }


# ---------------------------------------------------- artifact cold start


def artifact_cold_bench(model: str = "flownet_s", width_mult: float = 1.0,
                        bucket: tuple[int, int] = (64, 128),
                        tiers: tuple[str, ...] = ("f32",),
                        log_dir: str | None = None) -> dict:
    """The r16/r17 zero-cold-start acceptance A/B/C, in one process:

      publish  `warmup --serve` AOT-compiles the bucket x tier ladder,
               publishes each executable into the artifact store, and
               writes the executable index (the single-writer leg —
               this wall is paid ONCE, not per replica).
      leg A    jax caches cleared, engine with the store OFF: warm()
               is compile-bound — every ladder entry traces, lowers,
               and XLA-compiles. This is what every scaled-up replica
               paid before the artifact plane.
      leg B    jax caches cleared, store ON but the index OFF
               (serve.artifacts_index=false): the r16 fingerprint boot
               — warm() traces + lowers (the fingerprint integrity
               gate needs the local lowering) then fetches +
               deserializes. Zero compiles, but the trace/lower floor
               is still paid per entry.
      leg C    jax caches cleared, store + index ON (deep verify off —
               its background re-lowering would pollute the wall on a
               1-core host): warm() resolves every entry through the
               index — key hash + manifest gate + fetch + deserialize,
               ZERO trace/lower calls — asserted via the engine's
               exec_index_* counters.

    Figures, honestly separated: `cold_start_speedup` = leg A wall /
    leg C wall — the r17 headline, no longer bounded by the
    trace+lower floor; `fingerprint_boot_speedup` = leg A / leg B (the
    r16 figure, kept for trend continuity); `index_vs_artifact_speedup`
    = leg B / leg C — what moving integrity off the boot path bought;
    `acquire_speedup` = mean "aot" row compile_s / mean fetch-verdict
    row resolve_s from the legs' ledger provenance — the isolated
    executable-acquisition step, the figure that scales with device
    compile walls. Defaults to the flagship-width flownet_s; the tiny
    bench model would understate all of them."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from deepof_tpu.serve.artifacts import store_entries, verify_entry
    from deepof_tpu.serve.engine import build_serve_model
    from deepof_tpu.train import warmup

    base = log_dir or tempfile.mkdtemp(prefix="serve_bench_artifact_")
    store_dir = os.path.join(base, "exec")
    cfg = _bench_cfg(bucket, 2, 40.0, os.path.join(base, "run"))
    cfg = cfg.replace(
        model=model, width_mult=width_mult,
        serve=dataclasses.replace(cfg.serve, buckets=(bucket,),
                                  precisions=tuple(tiers),
                                  artifacts_dir=store_dir))

    t0 = time.perf_counter()
    rep = warmup.warmup_serve(cfg)
    publish_wall = time.perf_counter() - t0
    ladder = len(rep["buckets"])
    publish_compile = round(sum(b.get("compile_s") or 0.0
                                for b in rep["buckets"]), 3)

    model_obj = build_serve_model(cfg)
    params = model_obj.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, *bucket, 3 * cfg.data.time_step)))["params"]

    # leg A: compile-bound cold start (store off)
    jax.clear_caches()
    cfg_off = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                    artifacts_dir=""))
    t0 = time.perf_counter()
    with InferenceEngine(cfg_off, model_params=(model_obj, params)) as eng:
        eng.warm()
    t_compile = time.perf_counter() - t0

    # leg B: fingerprint boot (store on, index off — the r16 path)
    jax.clear_caches()
    cfg_fp = cfg.replace(serve=dataclasses.replace(
        cfg.serve, artifacts_index=False))
    t0 = time.perf_counter()
    with InferenceEngine(cfg_fp, model_params=(model_obj, params)) as eng:
        eng.warm()
        st = eng.stats()
    t_artifact = time.perf_counter() - t0

    # leg C: index boot (store + index on; deep verify off so the
    # background re-lowering doesn't share the 1-core wall under test)
    jax.clear_caches()
    cfg_idx = cfg.replace(serve=dataclasses.replace(
        cfg.serve, artifacts_deep_verify=False))
    t0 = time.perf_counter()
    with InferenceEngine(cfg_idx, model_params=(model_obj, params)) as eng:
        eng.warm()
        st_idx = eng.stats()
    t_index = time.perf_counter() - t0

    fps = store_entries(store_dir)
    store_bytes = sum(verify_entry(store_dir, fp).get("size") or 0
                      for fp in fps)

    # per-step acquisition split from the ledger provenance rows the
    # two legs just appended: resolve_s is the resolution step alone —
    # XLA compile on an "aot" row, fingerprint+fetch+deserialize on an
    # "artifact" row — with the trace/lower floor both legs pay (the
    # fingerprint integrity gate needs the local lowering either way)
    # excluded
    acquire_compile = []
    acquire_fetch = []
    try:
        with open(os.path.join(base, "run", "ledger.jsonl")) as f:
            for line in f:
                row = json.loads(line)
                if row.get("resolve_s") is None:
                    continue
                if row.get("compile_kind") == "artifact":
                    acquire_fetch.append(row["resolve_s"])
                elif row.get("compile_kind") == "aot":
                    acquire_compile.append(row["resolve_s"])
    except (OSError, ValueError):
        pass
    acq_c = (round(sum(acquire_compile) / len(acquire_compile), 4)
             if acquire_compile else None)
    acq_f = (round(sum(acquire_fetch) / len(acquire_fetch), 4)
             if acquire_fetch else None)
    return {
        "mode": "artifact_cold_start", "model": model,
        "width_mult": width_mult, "bucket": list(bucket),
        "tiers": list(tiers), "ladder": ladder,
        "publish_wall_s": round(publish_wall, 2),
        "publish_compile_s": publish_compile,
        "warm_wall_compile_s": round(t_compile, 2),
        "warm_wall_artifact_s": round(t_artifact, 2),
        "warm_wall_index_s": round(t_index, 2),
        "cold_start_speedup": round(t_compile / max(t_index, 1e-9), 2),
        "fingerprint_boot_speedup": round(
            t_compile / max(t_artifact, 1e-9), 2),
        "index_vs_artifact_speedup": round(
            t_artifact / max(t_index, 1e-9), 2),
        "acquire_compile_s": acq_c,
        "acquire_fetch_s": acq_f,
        "acquire_speedup": (round(acq_c / max(acq_f, 1e-9), 1)
                            if acq_c is not None and acq_f is not None
                            else None),
        "artifact_hits": st.get("exec_artifact_hits", 0),
        "artifact_misses": st.get("exec_artifact_misses", 0),
        "artifact_rejects": st.get("exec_artifact_rejects", 0),
        "index_hits": st_idx.get("exec_index_hits", 0),
        "index_misses": st_idx.get("exec_index_misses", 0),
        "index_rejects": st_idx.get("exec_index_rejects", 0),
        "store_entries": len(fps), "store_bytes": store_bytes,
        "store_dir": store_dir, "log_dir": base,
        "warmup_artifacts": rep.get("artifacts"),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="serve_bench")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--gap-ms", type=float, default=1.0)
    ap.add_argument("--max-batch", type=int, default=None,
                    help="batcher max coalesced pairs (default 8; "
                         "2 in --ramp mode)")
    ap.add_argument("--timeout-ms", type=float, default=None,
                    help="batcher flush timeout (default 10; 2 in "
                         "--stream mode, where a closed-loop walk never "
                         "coalesces and the timeout is pure overhead)")
    ap.add_argument("--exec-ms", type=float, default=None,
                    help="fake mode: per-dispatch executor latency "
                         "(default 10; 2 in --stream mode so the walk "
                         "stays decode-bound)")
    ap.add_argument("--bucket", default="64x64", metavar="HxW")
    ap.add_argument("--native", default="48x96", metavar="HxW",
                    help="native resolution of the synthetic requests")
    ap.add_argument("--real", action="store_true",
                    help="real model forward instead of the fake executor")
    ap.add_argument("--log-dir", default=None,
                    help="real mode: restore this run's newest verified "
                         "checkpoint instead of random init")
    ap.add_argument("--serial", action="store_true",
                    help="also run max_batch=1 and report the speedup")
    ap.add_argument("--fleet", type=int, default=None, metavar="N",
                    help="benchmark an N-replica serving fleet (router + "
                         "supervised subprocesses, closed-loop HTTP "
                         "clients) against a 1-replica fleet")
    ap.add_argument("--clients", type=int, default=8,
                    help="fleet/ramp mode: concurrent closed-loop HTTP "
                         "clients (the ramp's burst width)")
    ap.add_argument("--ramp", action="store_true",
                    help="bursty-load autoscaler exercise (DESIGN.md "
                         "\"Supervision plane\"): staged warm/burst/"
                         "scaled-burst/idle phases of closed-loop "
                         "clients against a live autoscaling fleet — "
                         "sheds collapse after scale-up, sustained idle "
                         "drains the pool back down, drops must be 0")
    ap.add_argument("--max-replicas", type=int, default=3,
                    help="ramp mode: autoscaler pool ceiling")
    ap.add_argument("--burst-s", type=float, default=8.0,
                    help="ramp mode: seconds per burst phase")
    ap.add_argument("--idle-s", type=float, default=20.0,
                    help="ramp mode: idle window for the scale-down leg")
    ap.add_argument("--slope", type=float, default=2.0,
                    help="ramp mode: autoscale_up_slope threshold armed "
                         "in the predictive compare leg (completions/s "
                         "trend per second)")
    ap.add_argument("--brownout", action="store_true",
                    help="r19 brownout A/B (DESIGN.md \"Brownout\"): the "
                         "identical mixed-priority overload against two "
                         "fresh fleets, degrade controller off vs on — "
                         "default-priority sheds must collapse to 0 on "
                         "the ON leg while low-priority work sheds at "
                         "L3 and the tier/bucket downgrade counters "
                         "prove the intermediate rungs served cheaper")
    ap.add_argument("--window-s", type=float, default=3.0,
                    help="brownout mode: counted overload window per leg")
    ap.add_argument("--artifact-cold", action="store_true",
                    help="r16 zero-cold-start A/B: publish the ladder "
                         "into the executable artifact store, then time "
                         "a cold engine warm compile-bound vs artifact-"
                         "fetching (cold_start_speedup, artifact_hits)")
    ap.add_argument("--width-mult", type=float, default=1.0,
                    help="artifact-cold mode: model width (default the "
                         "flagship 1.0 — compile-dominated, the shape "
                         "the artifact win is real on)")
    ap.add_argument("--stream", action="store_true",
                    help="benchmark the streaming video-session API: a "
                         "closed-loop session walk vs the equivalent "
                         "pairwise /v1/flow walk over the same frames "
                         "(injected --decode-ms per decode), reporting "
                         "stream_speedup, the decode-count delta, and "
                         "bitwise flow parity")
    ap.add_argument("--frames", type=int, default=32,
                    help="stream mode: frames in the walked video")
    ap.add_argument("--decode-ms", type=float, default=20.0,
                    help="stream mode: injected per-decode delay (the "
                         "decode-bound workload stand-in)")
    ap.add_argument("--warm-frames", type=int, default=16,
                    help="stream mode: frames in the real-model temporal "
                         "warm-start walk (warm_speedup / epe_vs_cold); "
                         "0 skips the warm block entirely (keeps --stream "
                         "jax-free, warm keys reported as null)")
    ap.add_argument("--warm-width", type=float, default=0.5,
                    help="stream mode: serve.session.warm_width for the "
                         "warm refinement stage")
    ap.add_argument("--precision", nargs="?", const="f32,bf16,int8",
                    default=None, metavar="TIERS",
                    help="sweep mixed-precision serving tiers (comma "
                         "list; bare flag = f32,bf16,int8) on the real "
                         "model: per-tier requests/s, p50/p99, weight "
                         "bytes, and epe_vs_f32 on seeded pairs")
    ap.add_argument("--quality", action="store_true",
                    help="label-free quality-proxy block (obs/quality.py)"
                         " on the real model: per-tier photo/smooth/"
                         "census proxy scores on the standard seeded "
                         "pairs, the drift-verdict block, and the "
                         "scorer's hot-path overhead (requests/s + p99, "
                         "quality off vs --quality-rate)")
    ap.add_argument("--quality-rate", type=float, default=0.1,
                    help="quality mode: sample rate of the overhead "
                         "measurement (the scores phase always samples "
                         "at 1.0)")
    ap.add_argument("--ledger", action="store_true",
                    help="executable-ledger block (obs/ledger.py) on "
                         "the real model: lattice compile seconds + "
                         "fingerprints + nominal-roofline MFU from the "
                         "recorded ledger.jsonl, and the ledger's "
                         "hot-path p99 overhead (on vs off — the ISSUE "
                         "15 bound is <= 2%%)")
    ap.add_argument("--incidents", action="store_true",
                    help="incident flight-recorder hot-path overhead "
                         "(obs/incident.py): identical real-model "
                         "workloads with obs.incidents off vs on with "
                         "an idle recorder (no trigger fires — the "
                         "ISSUE 18 bound is <= 1%% of serve p99)")
    args = ap.parse_args(argv)

    def hw(spec):
        h, w = spec.lower().split("x")
        return (int(h), int(w))

    # per-mode defaults: a closed-loop stream walk never coalesces, so
    # the batch timeout and executor sleep are pure per-flow overhead
    # there — the other modes keep the historical 10 ms figures
    user_exec, user_timeout, user_batch = \
        args.exec_ms, args.timeout_ms, args.max_batch
    fast = 2.0 if args.stream else 10.0
    exec_ms = args.exec_ms if args.exec_ms is not None else fast
    timeout_ms = args.timeout_ms if args.timeout_ms is not None else fast
    args.exec_ms, args.timeout_ms = exec_ms, timeout_ms
    args.max_batch = user_batch if user_batch is not None else 8

    if args.artifact_cold:
        res = artifact_cold_bench(
            width_mult=args.width_mult, bucket=hw(args.bucket),
            tiers=(tuple(t.strip() for t in args.precision.split(",")
                         if t.strip())
                   if args.precision is not None else ("f32",)),
            log_dir=args.log_dir)
    elif args.brownout:
        # like --ramp: absent flags keep the brownout's own tuned
        # defaults (exec 30 ms / flush 2 ms / batch 2 / in-flight 2 —
        # the saturate-then-shed dynamics the A/B is built on)
        res = brownout_bench(window_s=args.window_s,
                             max_batch=user_batch if user_batch is not None
                             else 2,
                             exec_ms=user_exec if user_exec is not None
                             else 30.0,
                             timeout_ms=user_timeout
                             if user_timeout is not None else 2.0,
                             bucket=hw(args.bucket),
                             native_hw=hw(args.native),
                             log_dir=args.log_dir)
    elif args.ramp:
        # explicit flags pass through; absent ones keep the ramp's own
        # tuned defaults (exec 30 ms / flush 2 ms / batch 2 — the shed-
        # then-absorb dynamics the drill and BENCH figures are built on)
        res = ramp_bench(max_replicas=args.max_replicas,
                         burst_clients=args.clients,
                         burst_s=args.burst_s, idle_s=args.idle_s,
                         max_batch=user_batch if user_batch is not None
                         else 2,
                         exec_ms=user_exec if user_exec is not None
                         else 30.0,
                         timeout_ms=user_timeout if user_timeout is not None
                         else 2.0,
                         bucket=hw(args.bucket), native_hw=hw(args.native),
                         slope_threshold=args.slope,
                         log_dir=args.log_dir)
    elif args.stream:
        res = stream_bench(frames=args.frames, decode_ms=args.decode_ms,
                           exec_ms=exec_ms, max_batch=args.max_batch,
                           timeout_ms=timeout_ms,
                           bucket=hw(args.bucket), native_hw=hw(args.native),
                           warm_frames=args.warm_frames,
                           warm_width=args.warm_width,
                           log_dir=args.log_dir)
    elif args.ledger:
        res = ledger_bench(
            requests=args.requests, gap_ms=args.gap_ms,
            max_batch=args.max_batch, timeout_ms=args.timeout_ms,
            bucket=hw(args.bucket), native_hw=hw(args.native),
            log_dir=args.log_dir)
    elif args.incidents:
        res = incident_bench(
            requests=args.requests, gap_ms=args.gap_ms,
            max_batch=args.max_batch, timeout_ms=args.timeout_ms,
            bucket=hw(args.bucket), native_hw=hw(args.native),
            log_dir=args.log_dir)
    elif args.quality:
        res = quality_bench(
            requests=args.requests, gap_ms=args.gap_ms,
            max_batch=args.max_batch, timeout_ms=args.timeout_ms,
            bucket=hw(args.bucket), native_hw=hw(args.native),
            sample_rate=args.quality_rate, log_dir=args.log_dir)
    elif args.precision is not None:
        res = precision_bench(
            requests=args.requests, gap_ms=args.gap_ms,
            max_batch=args.max_batch, timeout_ms=args.timeout_ms,
            bucket=hw(args.bucket), native_hw=hw(args.native),
            tiers=tuple(t.strip() for t in args.precision.split(",")
                        if t.strip()),
            log_dir=args.log_dir)
    elif args.fleet is not None:
        res = fleet_bench(replicas=args.fleet, requests=args.requests,
                          clients=args.clients, max_batch=args.max_batch,
                          timeout_ms=args.timeout_ms, exec_ms=args.exec_ms,
                          bucket=hw(args.bucket), native_hw=hw(args.native),
                          log_dir=args.log_dir)
    else:
        res = serve_bench(requests=args.requests, gap_ms=args.gap_ms,
                          max_batch=args.max_batch,
                          timeout_ms=args.timeout_ms,
                          exec_ms=args.exec_ms, bucket=hw(args.bucket),
                          native_hw=hw(args.native), fake=not args.real,
                          log_dir=args.log_dir, serial=args.serial)
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
