"""Perf-regression sentinel CLI: diff a run's executable ledger against
a committed baseline ledger (DESIGN.md "Executable ledger").

Every lowering the framework performs writes a provenance row (StableHLO
fingerprint, compile seconds, persistent-cache hit/miss, XLA cost
analysis, memory footprint, donation map) to ``<log_dir>/ledger.jsonl``
(deepof_tpu/obs/ledger.py). This tool compares a live run's rows to a
baseline's, per executable name, and fails — exit code **8**, the same
code ``deepof_tpu tail`` uses — on:

  - **HLO fingerprint drift**: the computation changed (a config edit,
    a jax upgrade, a silently different lowering);
  - **unexpected recompiles**: the baseline's compile was a persistent-
    cache hit but this run's missed (cache-key drift / evicted cache);
  - **compile-time blowups**: compile_s past
    max(--compile-floor-s, baseline * --compile-factor);
  - **memory growth**: argument+output+temp bytes past
    baseline * --memory-factor.

New/missing executable names are reported but never fail (a config may
legitimately grow or shrink its lattice; the `warmup --serve` report
owns per-entry coverage).

CI shape: rc 0 clean, rc 8 on drift, rc 1 usage error. Typical flow —
commit a known-good run's ledger.jsonl as the baseline, then gate every
run (or the first live device-tunnel window's measurement run) with::

    python tools/ledger_diff.py --baseline ledgers/BASELINE.jsonl \
        --run /tmp/deepof_tpu

jax-free by design: the diff must run from any machine, against a live
run, without touching an accelerator backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from deepof_tpu.obs.ledger import (  # noqa: E402 - path bootstrap above
    DEFAULT_COMPILE_FACTOR, DEFAULT_COMPILE_FLOOR_S, DEFAULT_MEMORY_FACTOR,
    diff_ledgers, load_ledger)

#: exit code on drift — deliberately the SAME code `deepof_tpu tail`
#: returns for a failed ledger verdict, so scripted gates treat the
#: standalone diff and the tail ladder interchangeably
RC_DRIFT = 8


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ledger_diff",
        description="diff a run's executable ledger against a baseline "
                    "(rc 0 clean, 8 on drift, 1 usage error)")
    ap.add_argument("--baseline", required=True,
                    help="baseline ledger.jsonl (or a run dir holding "
                         "one)")
    ap.add_argument("--run", required=True,
                    help="the run's ledger.jsonl (or its --log-dir)")
    ap.add_argument("--compile-factor", type=float,
                    default=DEFAULT_COMPILE_FACTOR,
                    help="compile-time blowup bound: fail when "
                         "compile_s > max(floor, baseline * FACTOR) "
                         "(default %(default)s)")
    ap.add_argument("--compile-floor-s", type=float,
                    default=DEFAULT_COMPILE_FLOOR_S,
                    help="compile-blowup floor in seconds — below it no "
                         "compile time fails (default %(default)s)")
    ap.add_argument("--memory-factor", type=float,
                    default=DEFAULT_MEMORY_FACTOR,
                    help="memory-growth bound: fail when arg+out+temp "
                         "bytes > baseline * FACTOR "
                         "(default %(default)s)")
    ap.add_argument("--json-indent", type=int, default=None)
    args = ap.parse_args(argv)

    try:
        baseline = load_ledger(args.baseline)
        run = load_ledger(args.run)
    except OSError as e:
        print(f"ledger_diff: {e}", file=sys.stderr)
        return 1
    if not baseline:
        print(f"ledger_diff: no lowering rows in {args.baseline!r}",
              file=sys.stderr)
        return 1
    if not run:
        print(f"ledger_diff: no lowering rows in {args.run!r}",
              file=sys.stderr)
        return 1

    verdict = diff_ledgers(baseline, run,
                           compile_factor=args.compile_factor,
                           compile_floor_s=args.compile_floor_s,
                           memory_factor=args.memory_factor)
    print(json.dumps(verdict, indent=args.json_indent))
    return RC_DRIFT if verdict["failed"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
