"""TPU perf probe — the DESIGN.md "Open measurements", runnable.

Honest (value-fetch) timings; see DESIGN.md "Benchmark honesty" for why
`block_until_ready` is not trusted on this transport. Usage:

    python tools/perf_probe.py                 # waits for tunnel, runs all
    python tools/perf_probe.py --no-wait       # fail fast if tunnel down
    python tools/perf_probe.py --only warp,decomp   # named sections

Sections (in the order a short tunnel window should spend them —
VERDICT r03 item 1: the driver-visible number FIRST, context after):
  headline bench.py headline (value + MFU fields; also persists
           artifacts/last_good_bench.json for the orchestrator's
           last-known-good fallback)
  calib    raw matmul TFLOP/s + RTT (tunnel-condition context)
  decomp   Inception-v3 train-step decomposition (fwd / fwd+loss /
           +bwd / full step, and the pyramid-loss/warp share)
  warpscan device-honest warp timing: 20 warps chained inside one jit
           (per-call dispatch floor amortized away), incl. the finest
           160x224 level — supersedes `warp` for decisions
  spc      steps_per_call sweep (1/4/8): dispatch+RTT amortization
  corr     XLA vs Pallas correlation kernel, fwd + grad, FlowNet-C
           shapes (VERDICT r03 item 4: time it or demote it)
  batch    batch-size throughput curve (16/96)
  multiframe Sintel-shaped T=10 volume train step (VERDICT r03 item 7)
  warp     per-call XLA vs Pallas warp table (dispatch-contaminated on
           a high-RTT tunnel; kept for cross-window comparability)
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench as bench_mod  # noqa: E402


def wait_for_tunnel(max_s: float) -> None:
    # Probe backend init in throwaway subprocesses (bench._tunnel_alive):
    # a wedged init leaves an uninterruptible stuck C++ thread, so each
    # retry re-execs a fresh interpreter; JAX is only initialized in the
    # main process once a subprocess has seen the tunnel up.
    deadline = time.time() + max_s
    while True:
        if bench_mod._tunnel_alive(timeout_s=120, fail_fast=True):
            try:
                # the tunnel can wedge between the subprocess probe and
                # this main-process init; treat that as "still down" (the
                # stuck init thread is abandoned — bench's _watchdog
                # contract — and only costs this one process slot)
                devs = bench_mod._init_devices(timeout_s=240)
            except TimeoutError as e:
                raise SystemExit(
                    f"tunnel wedged during main-process init: {e}; "
                    "re-exec the probe (in-process retry would block "
                    "behind the stuck init)")
            print("tunnel up:", devs, flush=True)
            return
        if time.time() > deadline:
            raise SystemExit("gave up waiting for tunnel")
        print("tunnel down, retrying in 300s", flush=True)
        time.sleep(300)


def timeit(name, fn, *args, steps=10, windows=3, items=None):
    """Honest window timing. Each call's input is perturbed by 0 * the
    previous call's output, so the final value fetch transitively depends
    on EVERY dispatch in the window — per DESIGN.md "Benchmark honesty",
    a fetch depending only on the last dispatch undermeasures when
    earlier dispatches are still in flight."""
    import jax
    import jax.numpy as jnp

    def chain(tree, prev_out):
        z = jnp.asarray(prev_out).ravel()[0] * 0
        return jax.tree_util.tree_map(lambda x: x + z.astype(x.dtype), tree)

    out = fn(*args)
    val = float(jax.device_get(jnp.asarray(out).ravel()[0]))
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*args[:-1], chain(args[-1], out))
        float(jax.device_get(jnp.asarray(out).ravel()[0]))
        best = min(best, time.perf_counter() - t0)
    per = best / steps
    rate = f"  {items / per:9.1f} items/s" if items else ""
    print(f"{name:44s} {per*1e3:8.2f} ms{rate}  ({val:.4f})", flush=True)
    return per


def _time_full_step(step, state, b, steps=10, windows=3):
    """Per-call train-step timing via the ONE shared honesty-critical
    idiom (bench.time_train_step)."""
    per, state, _ = bench_mod.time_train_step(step, state, b, steps=steps,
                                              windows=windows)
    return per, state


def sec_calib() -> None:
    print("calib:", bench_mod.calibrate(), flush=True)


def sec_warp() -> None:
    import jax

    from deepof_tpu.ops.warp import backward_warp

    key = jax.random.PRNGKey(0)
    for (h, w) in [(40, 56), (80, 112)]:
        img = jax.random.uniform(key, (16, h, w, 3))
        flow = jax.random.uniform(key, (16, h, w, 2)) * 8 - 4
        for impl in ("xla", "pallas"):
            f = jax.jit(lambda i, fl, impl=impl:
                        backward_warp(i, fl, impl=impl).sum())
            timeit(f"warp fwd {impl} {h}x{w}", f, img, flow)
            g = jax.jit(lambda i, fl, impl=impl: jax.grad(
                lambda q: backward_warp(i, q, impl=impl).sum())(fl).sum())
            timeit(f"warp grad {impl} {h}x{w}", g, img, flow)


def sec_warp_scan() -> None:
    """Device-honest warp timing: 20 warps chained inside ONE jit via
    lax.scan, so the per-call dispatch floor (~10 ms on a 67 ms-RTT
    tunnel, which contaminated the per-call warp table in window 1)
    amortizes to noise. Includes the finest pyramid level (160x224,
    XLA-only: W > 128) to decide whether a two-lane-tile W<=256 Pallas
    variant is worth building."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from deepof_tpu.ops.warp import backward_warp

    key = jax.random.PRNGKey(0)
    n_inner = 20
    for (h, w) in [(40, 56), (80, 112), (160, 224)]:
        img = jax.random.uniform(key, (16, h, w, 3))
        flow = jax.random.uniform(key, (16, h, w, 2)) * 8 - 4
        impls = ("xla",) if w > 128 else ("xla", "pallas")
        if w > 128:
            # byte-bound or index-bound? the loss.gather_dtype decision
            def fwd16(i, fl):
                def body(f, _):
                    out = backward_warp(i.astype(jnp.bfloat16), f,
                                        impl="xla")
                    return f + 1e-30 * out.astype(jnp.float32).mean(), None
                return lax.scan(body, fl, None, length=n_inner)[0].sum()

            per = timeit(f"warp scan fwd xla-bf16 {h}x{w}", jax.jit(fwd16),
                         img, flow)
            print(f"{'  -> per-warp':44s} {per/n_inner*1e3:8.3f} ms",
                  flush=True)
        for impl in impls:
            def scan_fwd(i, fl, impl=impl):
                def body(f, _):
                    out = backward_warp(i, f, impl=impl)
                    # chain: next flow depends on this warp's output
                    # (1e-30 scale, not *0: XLA may fold mul-by-zero
                    # and DCE the warp — the sec_decomp lesson)
                    return f + 1e-30 * out.mean(), None
                return lax.scan(body, fl, None, length=n_inner)[0].sum()

            f = jax.jit(scan_fwd)
            per = timeit(f"warp scan fwd {impl} {h}x{w}", f, img, flow)
            print(f"{'  -> per-warp':44s} {per/n_inner*1e3:8.3f} ms",
                  flush=True)

            def scan_grad(i, fl, impl=impl):
                def body(f, _):
                    g = jax.grad(lambda q: backward_warp(
                        i, q, impl=impl).sum())(f)
                    return f + 1e-30 * g, None
                return lax.scan(body, fl, None, length=n_inner)[0].sum()

            g = jax.jit(scan_grad)
            per = timeit(f"warp scan grad {impl} {h}x{w}", g, img, flow)
            print(f"{'  -> per-grad':44s} {per/n_inner*1e3:8.3f} ms",
                  flush=True)


def sec_decomp() -> None:
    import jax
    import jax.numpy as jnp

    from deepof_tpu.losses.pyramid import lrn_normalize, preprocess, pyramid_loss
    from deepof_tpu.train.step import model_losses

    cfg, mesh, ds, model, state, step, b = bench_mod.headline_setup()
    B = cfg.data.batch_size

    src = preprocess(b["source"], ds.mean)
    tgt = preprocess(b["target"], ds.mean)
    pair = jnp.concatenate([src, tgt], -1).astype(jnp.bfloat16)

    fwd_sum = jax.jit(lambda p, x: sum(
        f.astype(jnp.float32).sum() for f in model.apply({"params": p}, x)))
    timeit("inception fwd only", fwd_sum, state.params, pair, items=B)

    fwd_loss = jax.jit(lambda p, bb: model_losses(
        model, p, bb, ds.mean, cfg.loss, compute_dtype=jnp.bfloat16)[0])
    timeit("inception fwd+loss", fwd_loss, state.params, b, items=B)

    def _fwd_loss_grad(p, bb):
        val, grads = jax.value_and_grad(
            lambda q: model_losses(model, q, bb, ds.mean, cfg.loss,
                                   compute_dtype=jnp.bfloat16)[0])(p)
        # keep every grad leaf alive: returning only `val` lets XLA DCE
        # the entire backward (caught in r03 — this line then measured
        # identical to fwd+loss)
        # 1e-30 scale (not *0: XLA may fold mul-by-zero and DCE again)
        return val + 1e-30 * sum(jnp.sum(g)
                                 for g in jax.tree_util.tree_leaves(grads))

    fwd_loss_grad = jax.jit(_fwd_loss_grad)
    timeit("inception fwd+loss+bwd", fwd_loss_grad, state.params, b, items=B)

    per, state = _time_full_step(step, state, b)
    print(f"{'full train step':44s} {per*1e3:8.2f} ms  "
          f"{B/per:9.1f} items/s", flush=True)

    flows = jax.jit(lambda p, x: model.apply({"params": p}, x))(state.params, pair)
    flows = [f.astype(jnp.float32) for f in flows]
    li, lo = lrn_normalize(src), lrn_normalize(tgt)
    loss_alone = jax.jit(lambda fl, a, o: pyramid_loss(
        list(zip(fl, model.flow_scales)), a, o, cfg.loss)[0])
    timeit("pyramid loss fwd alone", loss_alone, flows, li, lo, items=B)

    loss_grad_alone = jax.jit(lambda fl, a, o: sum(
        x.sum() for x in jax.grad(lambda q: pyramid_loss(
            list(zip(q, model.flow_scales)), a, o, cfg.loss)[0])(fl)))
    timeit("pyramid loss grad (wrt flows)", loss_grad_alone, flows, li, lo,
           items=B)


def sec_batch() -> None:
    # throughput curve: same model, growing batch; is the chip compute-
    # bound (flat items/s => yes) or dispatch/HBM-bound (rising)?
    # Two points only: each batch size is a distinct ~5-min remote
    # compile, and the decision (does 96 beat 16?) needs just the ends;
    # window 1 died mid-sweep paying for the interior points.
    for batch in (16, 96):
        cfg, mesh, ds, model, state, step, b = bench_mod.headline_setup(
            batch=batch)
        per, _ = _time_full_step(step, state, b, windows=2)
        print(f"{'batch sweep b=%d' % batch:44s} {per*1e3:8.2f} ms  "
              f"{batch/per:9.1f} items/s", flush=True)


def sec_spc() -> None:
    # steps_per_call sweep: K optimizer steps per dispatch; the gap
    # between K=1 and K->8 per-step times IS the per-dispatch host/
    # transport overhead (DESIGN.md "Benchmark honesty"). K=2 dropped:
    # each K is a distinct large remote compile; 1/4/8 brackets the
    # amortization curve.
    for k in (1, 4, 8):
        cfg, mesh, ds, model, state, step, b = bench_mod.headline_setup(
            steps_per_call=k)
        per_call, _ = _time_full_step(step, state, b, steps=6, windows=2)
        B = cfg.data.batch_size
        print(f"{'steps_per_call K=%d' % k:44s} {per_call/k*1e3:8.2f} "
              f"ms/step  {k*B/per_call:9.1f} items/s", flush=True)


def sec_headline() -> None:
    res = bench_mod.bench()
    print("bench:", {k: round(v, 2) if isinstance(v, float) else v
                     for k, v in res.items()}, flush=True)


def sec_corr() -> None:
    """XLA sweep vs Pallas correlation kernel at the FlowNet-C shapes
    (320x448 input -> conv3 features 40x56x256, 441 displacement maps).
    Each impl is timed independently so a Pallas compile failure on the
    real backend still leaves the XLA row (a measured demotion verdict
    rather than a dead section)."""
    import jax

    from deepof_tpu.ops.corr import correlation

    key = jax.random.PRNGKey(0)
    f1 = jax.random.normal(key, (16, 40, 56, 256)) * 0.1
    f2 = jax.random.normal(jax.random.PRNGKey(1), (16, 40, 56, 256)) * 0.1
    ok = 0
    for impl in ("xla", "pallas"):
        try:
            f = jax.jit(lambda a, b, impl=impl:
                        correlation(a, b, impl=impl).sum())
            timeit(f"corr fwd {impl} 40x56x256", f, f1, f2)
            g = jax.jit(lambda a, b, impl=impl: sum(
                x.sum() for x in jax.grad(
                    lambda q: correlation(q[0], q[1], impl=impl).sum())((a, b))))
            timeit(f"corr grad {impl} 40x56x256", g, f1, f2)
            ok += 1
        except Exception:  # noqa: BLE001 - ONE impl failing is itself data
            import traceback
            traceback.print_exc()
            print(f"corr {impl} FAILED (see traceback)", flush=True)
    if ok == 0:
        # both impls down is a transport failure, not a kernel verdict —
        # propagate so main() marks the section failed and the chain
        # retries (corr is in the required set)
        raise RuntimeError("corr: no impl produced a timing this pass")


def sec_multiframe() -> None:
    """Sintel-shaped multi-frame step: Inception-v3, T=10 volume
    (B,224,480,30), 18 flow channels, batch 4 — the reference Sintel
    recipe (`deepOF.py:13-16`, crop 224x480, SURVEY §2.2). Closes the
    time-axis perf gap (VERDICT r03 item 7): the T-volume path is
    dryrun-validated but had zero on-chip timing. Built through
    bench.headline_setup so it shares every other headline setting."""
    t, batch = 10, 4
    cfg, mesh, ds, model, state, step, b = bench_mod.headline_setup(
        batch=batch, image_size=(224, 480), time_step=t,
        weights=(16, 8, 4, 4, 2, 1))
    per, _ = _time_full_step(step, state, b, steps=6, windows=2)
    pairs = batch * (t - 1)  # T-1 consecutive warped pairs per item
    print(f"{'sintel T=10 full step b=4 224x480':44s} {per*1e3:8.2f} ms  "
          f"{batch/per:9.1f} items/s  {pairs/per:9.1f} pairs/s", flush=True)


# Execution order = priority order for a short tunnel window (VERDICT
# r03 item 1b): the driver-visible headline + its MFU fields FIRST, then
# calibration context, then the decision sections (decomp/warpscan/spc/
# corr), then sweeps; the per-call warp table is superseded by warpscan
# and runs last.
SECTIONS = {
    "headline": sec_headline,
    "calib": sec_calib,
    "decomp": sec_decomp,
    "warpscan": sec_warp_scan,
    "spc": sec_spc,
    "corr": sec_corr,
    "batch": sec_batch,
    "multiframe": sec_multiframe,
    "warp": sec_warp,
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--no-wait", action="store_true")
    ap.add_argument("--wait-s", type=float, default=7200)
    ap.add_argument("--only", default=None,
                    help="comma-separated section names (default: all, in "
                         f"order {','.join(SECTIONS)})")
    args = ap.parse_args()
    names = list(SECTIONS) if not args.only else args.only.split(",")
    unknown = [n for n in names if n not in SECTIONS]
    if unknown:
        raise SystemExit(f"unknown sections {unknown}; have {list(SECTIONS)}")
    wait_for_tunnel(0 if args.no_wait else args.wait_s)
    failed = []
    for n in names:
        print(f"--- section {n}", flush=True)
        t0 = time.perf_counter()
        try:
            SECTIONS[n]()
        except Exception:  # noqa: BLE001 - one section must not eat the
            # window: print and move on (a failure in decomp must not
            # block warpscan/spc from even being attempted this pass)
            import traceback
            traceback.print_exc()
            failed.append(n)
            print(f"--- section {n} FAILED in "
                  f"{time.perf_counter() - t0:.1f}s", flush=True)
            continue
        print(f"--- section {n} done in {time.perf_counter() - t0:.1f}s",
              flush=True)
    if failed:
        print(f"sections failed: {failed}", flush=True)
        # rc=0 (chain moves on) only when every DECISION section got its
        # data this pass; a mid-run tunnel drop that kills them must keep
        # the chain retrying (re-timing already-passed sections is cheap
        # with the persistent compile cache). calib/batch/warp are
        # context, not decisions — their failure alone doesn't retry.
        required = {"decomp", "warpscan", "spc", "headline", "corr",
                    "multiframe"}
        if required.intersection(failed):
            raise SystemExit(1)


if __name__ == "__main__":
    main()
