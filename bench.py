"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline (BASELINE.json): FlyingChairs image-pairs/sec/chip on the full
training step (forward + unsupervised pyramid loss + backward + Adam) of
the flagship Inception-v3 flow model at the reference's 320x448 input
(`deepOF.py:22`), bfloat16 compute.

The reference publishes no throughput numbers (BASELINE.md); the baseline
anchor is a self-measured first run stored in `BENCH_BASELINE.json`. When
absent, vs_baseline = 1.0.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def bench(model_name: str = "inception_v3", batch: int = 16,
          image_size=(320, 448), steps: int = 20, warmup: int = 3) -> dict:
    from deepof_tpu.core.config import (
        DataConfig, ExperimentConfig, LossConfig, OptimConfig, TrainConfig)
    from deepof_tpu.data.datasets import SyntheticData
    from deepof_tpu.models.registry import build_model
    from deepof_tpu.parallel.mesh import batch_sharding, build_mesh
    from deepof_tpu.train.state import create_train_state, make_optimizer
    from deepof_tpu.train.step import make_train_step

    h, w = image_size
    n_chips = len(jax.devices())
    cfg = ExperimentConfig(
        name="bench",
        model=model_name,
        loss=LossConfig(weights=(16, 8, 4, 2, 1, 1)),
        optim=OptimConfig(learning_rate=1.6e-5),
        data=DataConfig(dataset="synthetic", image_size=(h, w), gt_size=(h, w),
                        batch_size=batch),
        train=TrainConfig(seed=0, compute_dtype="bfloat16"),
    )
    mesh = build_mesh(cfg.mesh)
    model = build_model(cfg.model, dtype=jnp.bfloat16)
    tx = make_optimizer(cfg.optim, lambda s: cfg.optim.learning_rate)
    state = create_train_state(model, jnp.zeros((batch, h, w, 6)), tx, seed=0)
    ds = SyntheticData(cfg.data)
    step = make_train_step(model, cfg, ds.mean, mesh)
    b = jax.device_put(ds.sample_train(batch, iteration=0), batch_sharding(mesh))

    for _ in range(warmup):
        state, metrics = step(state, b)
    jax.block_until_ready(metrics["total"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, b)
    jax.block_until_ready(metrics["total"])
    dt = time.perf_counter() - t0

    pairs_per_sec = steps * batch / dt
    per_chip = pairs_per_sec / n_chips
    assert np.isfinite(float(jax.device_get(metrics["total"])))
    return {"pairs_per_sec_per_chip": per_chip, "pairs_per_sec": pairs_per_sec,
            "n_chips": n_chips, "batch": batch, "steps_per_sec": steps / dt}


def main() -> None:
    res = bench()
    baseline_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    vs = 1.0
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f).get("pairs_per_sec_per_chip")
        if base:
            vs = res["pairs_per_sec_per_chip"] / base
    print(json.dumps({
        "metric": "flyingchairs_train_pairs_per_sec_per_chip",
        "value": round(res["pairs_per_sec_per_chip"], 2),
        "unit": "image-pairs/sec/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
