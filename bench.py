"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline (BASELINE.json): FlyingChairs image-pairs/sec/chip on the full
training step (forward + unsupervised pyramid loss + backward + Adam) of
the flagship Inception-v3 flow model at the reference's 320x448 input
(`deepOF.py:22`), bfloat16 compute.

The reference publishes no throughput numbers (BASELINE.md); the baseline
anchor is a self-measured first run stored in `BENCH_BASELINE.json`. When
absent, vs_baseline = 1.0.

Tunnel resilience: the accelerator is reached through a shared relay
tunnel that can wedge backend init indefinitely, and a wedged in-process
init can never be retried (the stuck C++ thread blocks every later
attempt). So the parent process NEVER initializes the backend itself:
it probes liveness in throwaway subprocesses, runs the measurement in a
re-exec'd child (`bench.py --run`), and on any child failure goes back
to waiting until the wall budget is spent. Every probe/child attempt is
appended to artifacts/bench_probes.log so a dead-tunnel session leaves
timestamped evidence of continuous outage.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time


METRIC = "flyingchairs_train_pairs_per_sec_per_chip"
UNIT = "image-pairs/sec/chip"

# --data mode: host input-pipeline throughput in isolation (no TPU).
DATA_METRIC = "host_pipeline_batches_per_sec"
DATA_UNIT = "batches/s"


def emit(value: float, vs_baseline: float, error: str | None = None,
         **extra) -> None:
    line = {"metric": METRIC, "value": round(value, 2), "unit": UNIT,
            "vs_baseline": round(vs_baseline, 3)}
    line.update(extra)
    if error:
        line["error"] = error
    # flush: os._exit in main() skips interpreter shutdown, so a buffered
    # line (stdout = pipe under the harness) would otherwise be lost
    print(json.dumps(line), flush=True)


def _watchdog(fn, timeout_s: float, what: str):
    """Run fn() on a daemon thread; raise TimeoutError on hang or error.

    A wedged relay can block the axon claim loop AND remote compiles
    indefinitely, and a stuck C++ thread cannot be interrupted — the
    caller must treat a timeout as fatal and exit via os._exit.

    Limitation: if the container's sitecustomize itself hangs at
    interpreter startup (its register() blocks reading a relay-helper
    child's pipe), no in-process code runs at all — that failure mode can
    only be handled by the harness invoking this script under a timeout.
    """
    out: dict = {}

    def work():
        try:
            out["value"] = fn()
        except BaseException as e:  # noqa: BLE001 - report, don't vanish
            out["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join(timeout_s)
    if "value" in out:
        return out["value"]
    raise TimeoutError(
        out.get("error", f"{what} exceeded {timeout_s:.0f}s (wedged tunnel?)"))


def _init_devices(timeout_s: float = 240.0):
    _import_compute()
    devs = _watchdog(lambda: jax.devices(), timeout_s, "backend init")
    # Persistent compilation cache for the TPU path (window-1 r03 spent
    # ~10 of 47 live-tunnel minutes recompiling the same graphs per
    # attempt). Enabled only off-cpu, and only after backend init so the
    # gate can ask which backend this is: cross-process cache reads on
    # this host's cpu jaxlib intermittently corrupt the heap (see
    # TrainConfig.compile_cache). Also installs the hit/miss counters
    # bench() surfaces, so a measurement line says whether its window
    # paid XLA or loaded executables. Best-effort.
    try:
        if jax.default_backend() != "cpu":
            from deepof_tpu.train.warmup import enable_compile_cache
            enable_compile_cache()
    except Exception:  # noqa: BLE001 - cache is an optimization only
        pass
    return devs


PROBE_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "artifacts", "bench_probes.log")
# Freshest successful measurement (written by bench() on every success,
# including runs driven by tools/perf_probe.py's headline section). The
# orchestrator's exhaustion path reports it — with its timestamp and
# calibration context — instead of a blind 0.0 (VERDICT r03 item 1c).
LAST_GOOD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "artifacts", "last_good_bench.json")

# os._exit indirection so tests can observe orchestrate()'s terminal
# paths without killing the pytest process.
_exit = os._exit

#: Exit code of the stale-fallback path: distinct from both success (0)
#: and hard failure (1) so a driver can recognize — and must explicitly
#: accept — a cached headline (ADVICE r04; BENCH_ALLOW_STALE=1 opts in).
STALE_EXIT_CODE = 3


def _plog(event: str) -> None:
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    try:
        # best-effort evidence file — never let it preempt the one JSON
        # line on stdout (read-only tree, artifacts-path collision, ...)
        os.makedirs(os.path.dirname(PROBE_LOG), exist_ok=True)
        with open(PROBE_LOG, "a") as f:
            f.write(f"{stamp} {event}\n")
    except OSError:
        pass
    print(f"# {stamp} {event}", file=sys.stderr, flush=True)


def _tunnel_alive(timeout_s: float = 120.0, fail_fast: bool = False) -> bool:
    """Backend-init probe in a throwaway subprocess: a hang only costs
    the child, never this process. rc != 0 is a *deterministic* backend
    failure, not a hang — with fail_fast it aborts immediately (the
    interactive perf_probe contract); otherwise it is logged and treated
    as down so the unattended orchestrator keeps waiting (the error may
    be tunnel-transient, and its budget is bounded anyway)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        _plog(f"probe rc=timeout({timeout_s:.0f}s) DOWN")
        return False
    if r.returncode != 0:
        _plog(f"probe rc={r.returncode} ERROR {r.stderr.strip()[-200:]}")
        if fail_fast:
            raise SystemExit(
                f"backend failed (not a hang): {r.stderr.strip()[-500:]}")
        return False
    _plog(f"probe rc=0 UP n_devices={r.stdout.strip()}")
    return True


def orchestrate(deadline_s: float | None = None) -> None:
    """Wait for a live tunnel window, then measure in a re-exec'd child;
    retry on any failure until the wall budget runs out. Emits exactly
    one JSON line either way (the child's on success, an error line from
    here on exhaustion)."""
    deadline_s = deadline_s or float(os.environ.get("BENCH_DEADLINE_S", 1500))
    t_start = time.time()
    min_child_budget = 300.0
    attempts, last_err = 0, "no live tunnel window"
    _plog(f"orchestrate start deadline_s={deadline_s:.0f}")
    while True:
        remaining = deadline_s - (time.time() - t_start)
        if remaining < min_child_budget:
            break
        if not _tunnel_alive(min(120.0, max(10.0, remaining - min_child_budget))):
            time.sleep(min(30.0, max(0.0, remaining - min_child_budget)))
            continue
        remaining = deadline_s - (time.time() - t_start)
        child_budget = max(min(remaining - 30.0, 900.0), min_child_budget)
        attempts += 1
        # De-risk ladder: attempt 1 runs the full measured-fastest config
        # (warp_impl=auto incl. Pallas kernels, steps_per_call=4 to
        # amortize the ~67 ms tunnel RTT); attempt 2 drops back to
        # steps_per_call=1 (in case the K-step scan is the compile
        # problem); attempt 3+ additionally forces the pure-XLA warp. An
        # operator-exported BENCH_WARP_IMPL / BENCH_SPC pins that knob for
        # every attempt instead — including BENCH_WARP_IMPL="" (present-
        # but-empty pins the config default; only truly-unset engages the
        # ladder).
        warp = (os.environ["BENCH_WARP_IMPL"]
                if "BENCH_WARP_IMPL" in os.environ
                else ("" if attempts <= 2 else "xla"))
        spc = (os.environ["BENCH_SPC"] if "BENCH_SPC" in os.environ
               else ("4" if attempts <= 1 else "1"))
        _plog(f"child attempt={attempts} budget={child_budget:.0f}s"
              + (f" warp_impl={warp}" if warp else "") + f" spc={spc}")
        env = dict(os.environ, BENCH_DEADLINE_S=str(child_budget - 20.0),
                   BENCH_WARP_IMPL=warp, BENCH_SPC=spc)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--run"],
                timeout=child_budget, capture_output=True, text=True, env=env)
        except subprocess.TimeoutExpired:
            last_err = f"child attempt {attempts} hit {child_budget:.0f}s"
            _plog(f"child attempt={attempts} TIMEOUT")
            continue
        lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
        res = None
        for ln in lines:
            try:
                cand = json.loads(ln)
            except ValueError:
                continue
            if cand.get("metric") == METRIC:
                res = (ln, cand)
        if res and r.returncode == 0 and res[1].get("value", 0) > 0:
            _plog(f"child attempt={attempts} OK value={res[1]['value']}")
            print(res[0], flush=True)
            _exit(0)
        last_err = ((res[1].get("error") or f"child rc={r.returncode} "
                     f"value={res[1].get('value')}") if res else
                    f"child rc={r.returncode}: {r.stderr.strip()[-200:]}")
        _plog(f"child attempt={attempts} FAIL {last_err}")
        # backoff: a deterministically fast-failing child would otherwise
        # hammer the shared relay with probe+re-exec cycles all budget
        time.sleep(min(20.0, max(0.0, deadline_s - (time.time() - t_start)
                                 - min_child_budget)))
    _plog(f"orchestrate exhausted attempts={attempts} last={last_err}")
    err = (f"{last_err} (after {attempts} measurement attempts in "
           f"{deadline_s:.0f}s; probe log: artifacts/bench_probes.log)")
    lg = _load_last_good()
    if lg is not None:
        # Honest-but-not-blind fallback: the freshest chain-captured
        # headline, clearly marked stale with its own timestamp and
        # calibration context. value=0.0 is reserved for "no measurement
        # exists at all". The exit code stays NONZERO (rc=3) so a driver
        # keying on exit status cannot mistake a cached number for a
        # fresh one (ADVICE r04); exporting BENCH_ALLOW_STALE=1 is the
        # explicit opt-in that turns the stale line into rc=0.
        allow_stale = (os.environ.get("BENCH_ALLOW_STALE", "").strip().lower()
                       not in ("", "0", "false", "no", "off"))
        _plog(f"orchestrate fallback last_good value="
              f"{lg['res'].get('pairs_per_sec_per_chip')} "
              f"measured_at={lg.get('measured_at')} "
              f"rc={0 if allow_stale else STALE_EXIT_CODE}")
        emit(lg["res"]["pairs_per_sec_per_chip"], _vs_baseline(lg["res"]),
             stale=True, measured_at=lg.get("measured_at"),
             **{k: lg["res"][k] for k in _EXTRA_KEYS if k in lg["res"]},
             error=err)
        _exit(0 if allow_stale else STALE_EXIT_CODE)
    emit(0.0, 0.0, error=err)
    _exit(1)


_EXTRA_KEYS = ("matmul_tflops", "rtt_ms", "batch", "warp_impl",
               "steps_per_call", "model_tflops", "mfu_nominal",
               "mfu_vs_matmul", "compile_cache_requests",
               "compile_cache_hits", "compile_cache_misses",
               "decode_cache_hits", "decode_cache_misses",
               "decode_cache_evictions", "dev_mem_bytes_in_use",
               "dev_mem_peak_bytes")


def _save_last_good(res: dict) -> None:
    try:  # best-effort: a read-only tree must not fail the measurement
        os.makedirs(os.path.dirname(LAST_GOOD), exist_ok=True)
        with open(LAST_GOOD, "w") as f:
            json.dump({"measured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()), "res": res}, f)
    except OSError:
        pass


#: Max age of a last-good record the fallback will serve (ADVICE r04: an
#: unbounded fallback lets a consumer keying on exit status treat an
#: arbitrarily old measurement as fresh). 48h covers "captured earlier
#: this session or the previous one"; older chips/configs have drifted
#: too far to stand in for today's tree.
LAST_GOOD_MAX_AGE_S = 48 * 3600.0


def _load_last_good() -> dict | None:
    try:
        with open(LAST_GOOD) as f:
            lg = json.load(f)
        age = time.time() - time.mktime(
            time.strptime(lg.get("measured_at", ""), "%Y-%m-%dT%H:%M:%SZ"))
        # measured_at is UTC; mktime is local — this container runs UTC,
        # and the bound is deliberately coarse (hours, not minutes)
        if age > LAST_GOOD_MAX_AGE_S:
            _plog(f"last_good too old ({age / 3600.0:.1f}h > 48h); ignoring")
            return None
        if lg.get("res", {}).get("pairs_per_sec_per_chip", 0) > 0:
            return lg
    except (OSError, ValueError, OverflowError):
        pass
    return None


def _vs_baseline(res: dict) -> float:
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_BASELINE.json")) as f:
            base = json.load(f).get("pairs_per_sec_per_chip")
        return res["pairs_per_sec_per_chip"] / base if base else 1.0
    except Exception:  # noqa: BLE001 - missing/corrupt baseline: neutral
        return 1.0


# Third-party imports are deferred so the orchestrating parent stays
# stdlib-only: even *importing* jax runs the container's sitecustomize
# relay probe, and a hang there would bypass the whole tunnel-defuse
# design (no probe log, no JSON line). Only the --run child imports jax.
jax = jnp = np = None


def _import_compute() -> None:
    global jax, jnp, np
    if jax is None:
        import jax as _jax
        import jax.numpy as _jnp
        import numpy as _np
        jax, jnp, np = _jax, _jnp, _np


def calibrate(n: int = 4096, reps: int = 10) -> dict:
    """Raw bf16 matmul rate + host<->device RTT, to contextualize the
    headline number: the chip is reached through a shared tunnel whose
    throughput and latency swing over minutes (observed 30-130 TFLOP/s
    and 0.1-66 ms RTT on the same binary)."""
    _import_compute()
    a = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def mm(x):
        return (x @ x).sum()

    out = mm(a)  # compile mm AND the chaining ops used in the timed loop
    out = mm(out * 0 + a)
    float(jax.device_get(out))
    # RTT must round-trip a FRESH array: device_get on an already-fetched
    # one returns jax's cached host copy without touching the tunnel.
    # (warm the scalar-add compile first so RTT is transfer, not compile)
    float(jax.device_get(jax.device_put(jnp.float32(1.0)) + 1.0))
    t1 = time.perf_counter()
    float(jax.device_get(jax.device_put(jnp.float32(2.0)) + 1.0))
    rtt = time.perf_counter() - t1
    t0 = time.perf_counter()
    for _ in range(reps):
        out = mm(out * 0 + a)  # chain to prevent overlap-free reordering
    float(jax.device_get(out))
    # subtract the one value-fetch round trip so a 66ms-RTT tunnel does
    # not masquerade as a slow chip (compute here is only ~reps*4ms)
    dt = max(time.perf_counter() - t0 - rtt, 1e-9) / reps
    return {"matmul_tflops": round(2 * n**3 / dt / 1e12, 1),
            "rtt_ms": round(rtt * 1e3, 2)}


def headline_setup(model_name: str = "inception_v3", batch: int = 16,
                   image_size=(320, 448), steps_per_call: int = 1,
                   warp_impl: str | None = None, time_step: int = 2,
                   weights: tuple = (16, 8, 4, 2, 1, 1)):
    """The headline workload, shared with tools/perf_probe.py so the
    decomposition there always measures the same config as the headline.

    With steps_per_call = K > 1 the returned step takes K stacked batches
    ([K, B, ...]) and the returned sharded batch is stacked accordingly
    (the perf_probe dispatch-amortization sweep). warp_impl overrides
    `LossConfig.warp_impl` (None = the config default). time_step > 2
    builds the multi-frame T-volume variant (2(T-1) flow channels, 3T
    input channels — the probe's Sintel-shaped section) on the same
    pipeline, so multiframe timings share every other headline setting.

    Returns (cfg, mesh, ds, model, state, step, sharded_batch)."""
    _import_compute()
    from deepof_tpu.core.config import (
        DataConfig, ExperimentConfig, LossConfig, OptimConfig, TrainConfig)
    from deepof_tpu.data.datasets import SyntheticData
    from deepof_tpu.models.registry import build_model
    from deepof_tpu.parallel.mesh import (
        batch_sharding, build_mesh, stacked_batch_sharding)
    from deepof_tpu.train.state import create_train_state, make_optimizer
    from deepof_tpu.train.step import make_train_step

    h, w = image_size
    loss_kw = {"warp_impl": warp_impl} if warp_impl else {}
    cfg = ExperimentConfig(
        name="bench",
        model=model_name,
        loss=LossConfig(weights=tuple(weights), **loss_kw),
        optim=OptimConfig(learning_rate=1.6e-5),
        data=DataConfig(dataset="synthetic", image_size=(h, w), gt_size=(h, w),
                        batch_size=batch, time_step=time_step),
        train=TrainConfig(seed=0, compute_dtype="bfloat16",
                          steps_per_call=steps_per_call),
    )
    mesh = build_mesh(cfg.mesh)
    model = build_model(cfg.model, flow_channels=2 * (time_step - 1),
                        dtype=jnp.bfloat16,
                        corr_max_disp=cfg.corr_max_disp,
                        corr_stride=cfg.corr_stride)
    tx = make_optimizer(cfg.optim, lambda s: cfg.optim.learning_rate)
    state = create_train_state(
        model, jnp.zeros((batch, h, w, 3 * time_step)), tx, seed=0)
    ds = SyntheticData(cfg.data)
    step = make_train_step(model, cfg, ds.mean, mesh)
    one = ds.sample_train(batch, iteration=0)
    if steps_per_call > 1:
        b = jax.device_put({k: np.stack([v] * steps_per_call)
                            for k, v in one.items()},
                           stacked_batch_sharding(mesh))
    else:
        b = jax.device_put(one, batch_sharding(mesh))
    return cfg, mesh, ds, model, state, step, b


# The nominal bf16 chip peak used for `mfu_nominal` lives in
# deepof_tpu/obs/telemetry.py (single source of truth, shared with the
# train loop's per-record telemetry); imported lazily inside bench() so
# the orchestrating parent stays stdlib-only at import.


def time_train_step(step, state, b, steps: int = 10, windows: int = 3,
                    warmup: int = 1, metrics_key: str = "total"):
    """Honest best-of-windows timing of a (state, batch) train step.

    Ends every window by FETCHING the loss value — it transitively
    depends on every dispatched step, so it cannot materialize early
    (unlike `block_until_ready`; DESIGN.md "Benchmark honesty"). The
    donated state threads the dependency chain across calls. Returns
    (seconds per CALL, final state, fetched metrics value). The single
    timing idiom shared by bench() and tools/perf_probe.py."""
    _import_compute()
    for _ in range(max(warmup, 1)):  # >=1: m must exist for the fetch
        state, m = step(state, b)
    val = jax.device_get(m[metrics_key])
    # fail fast BEFORE spending the timing windows: a NaN step (or tunnel
    # garbage) should cost warmup steps, not the whole accelerator window
    assert np.isfinite(val).all(), f"non-finite {metrics_key} after warmup: {val}"
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step(state, b)
        val = jax.device_get(m[metrics_key])
        best = min(best, time.perf_counter() - t0)
    return best / steps, state, val


def step_flops(step, state, b) -> float | None:
    """XLA's own FLOPs estimate for one train step, from the LOWERED
    module — no second backend compile, which matters on a tunnel whose
    compile latency swings; None if the backend does not report it.
    Implementation shared with the train loop's per-record telemetry
    (deepof_tpu/obs/telemetry.py); imported lazily for the stdlib-only
    parent."""
    from deepof_tpu.obs.telemetry import step_flops as _step_flops

    return _step_flops(step, state, b)


HEADLINE_CONFIG = ("inception_v3", 16, (320, 448))


def bench(model_name: str = "inception_v3", batch: int = 16,
          image_size=(320, 448), steps: int = 20, warmup: int = 3,
          windows: int = 4) -> dict:
    n_chips = len(_init_devices())  # watchdog covers every entrypoint
    # BENCH_WARP_IMPL: insurance the orchestrator uses to de-risk the
    # measured-fastest default — if a Pallas composition failed to compile
    # inside the full train step (untestable without a live tunnel), later
    # child attempts fall back to the pure-XLA warp instead of forfeiting
    # the round's number.
    warp_impl = os.environ.get("BENCH_WARP_IMPL") or None
    # BENCH_SPC: K optimizer steps per dispatch (the Trainer's own
    # steps_per_call lax.scan path). One dispatch + one value fetch then
    # serves K steps, amortizing the per-step host/transport overhead
    # that dominates on a ~67 ms-RTT tunnel. Throughput stays
    # per-optimizer-step either way. Default 4 = the headline config, so
    # EVERY bench() caller (orchestrator attempt 1, perf_probe's headline
    # section, the CLI) measures and persists last_good under the same
    # config; the orchestrator's retry ladder pins 1 to de-risk.
    spc = max(int(os.environ.get("BENCH_SPC") or 4), 1)
    # cache accounting around everything that can compile (setup + the
    # timed fn's first call): a warmed window shows misses == 0 and
    # reaches measurement without paying XLA (DESIGN.md "Execution layer")
    try:
        from deepof_tpu.train.warmup import cache_delta
        cache_watch = cache_delta()
    except Exception:  # noqa: BLE001 - counters are observability only
        cache_watch = None
    cfg, mesh, ds, model, state, step, b = headline_setup(
        model_name, batch, image_size, steps_per_call=spc,
        warp_impl=warp_impl)

    # keep the per-attempt optimizer-step work roughly constant across
    # spc values (each timed CALL runs K steps; without this, spc=4 would
    # execute ~4x the work and push the attempt toward its child timeout)
    calls = max(steps // spc, 5)
    per_call, state, total = time_train_step(
        step, state, b, steps=calls, windows=windows, warmup=warmup)
    cache_d = cache_watch.stats() if cache_watch is not None else None
    per_step = per_call / spc
    pairs_per_sec = batch / per_step
    per_chip = pairs_per_sec / n_chips
    assert np.isfinite(total).all(), total
    res = {"pairs_per_sec_per_chip": per_chip, "pairs_per_sec": pairs_per_sec,
           "n_chips": n_chips, "batch": batch, "steps_per_sec": 1.0 / per_step,
           "steps_per_call": spc,
           "warp_impl": cfg.loss.warp_impl, **calibrate()}
    if cache_d is not None:
        # requests disambiguates: misses == 0 with requests == 0 means
        # the counters never saw a compile (cache disabled / listener
        # dead), NOT that the window was warm — don't let a silent
        # enable_compile_cache failure read as "compiled nothing"
        res["compile_cache_requests"] = cache_d["requests"]
        res["compile_cache_hits"] = cache_d["hits"]
        res["compile_cache_misses"] = cache_d["misses"]
    # Decoded-image cache counters (alongside the compile-cache ones):
    # zeros for the synthetic headline workload, live for CLI benches of
    # disk datasets — the host-decode half of the observability story.
    dcache = getattr(ds, "cache_stats", None)
    if dcache is not None:
        dstats = dcache()
        res["decode_cache_hits"] = int(dstats["hits"])
        res["decode_cache_misses"] = int(dstats["misses"])
        res["decode_cache_evictions"] = int(dstats["evictions"])
    # Device-memory telemetry (obs/telemetry.py): the same
    # bytes-in-use/peak fields the train loop logs per record, so a
    # bench line also answers "how close to HBM is this config". Null
    # fields (cpu backend) are dropped from the one-line output.
    from deepof_tpu.obs.telemetry import (
        NOMINAL_BF16_TFLOPS, device_memory_summary)

    res.update({k: v for k, v in device_memory_summary().items()
                if v is not None})
    # MFU: XLA-counted FLOPs/step x measured steps/sec, vs both the
    # nominal chip peak and the concurrently measured matmul rate (the
    # latter cancels tunnel-condition swings — DESIGN.md).
    flops = step_flops(step, state, b)
    if flops:
        # LOWERED cost_analysis reports GLOBAL (pre-partition) FLOPs —
        # verified: an 8-way-sharded einsum reports the full count from
        # .lower().cost_analysis() and 1/8 of it from
        # .compile().cost_analysis(). Per-chip rate therefore divides by
        # n_chips. No spc normalization: XLA counts a lax.scan body ONCE
        # (verified on this jax: K=4 scan reports 528386 flops vs 528384
        # for the single step), so the K-step program already reports
        # per-step flops.
        model_tflops = flops * res["steps_per_sec"] / n_chips / 1e12
        res.update(
            flops_per_step=flops,
            model_tflops=round(model_tflops, 2),
            mfu_nominal=round(model_tflops / NOMINAL_BF16_TFLOPS, 4),
            mfu_vs_matmul=round(model_tflops / max(res["matmul_tflops"], 1e-9),
                                4),
        )
    # Only the real headline measurement may become the orchestrator's
    # stale-fallback value: a CLI bench of another model/batch, or a CPU
    # smoke run, must not be reported later as the FlyingChairs-headline
    # pairs/sec (the record carries no model/backend discriminator the
    # reader could filter on).
    if ((model_name, batch, tuple(image_size)) == HEADLINE_CONFIG
            and jax.default_backend() == "tpu"):
        _save_last_good(res)
    return res


def data_bench(num_workers: int = 0, batch: int = 8, image_size=(64, 64),
               batches: int = 32, dataset: str = "synthetic",
               data_path: str = "", seed: int = 0,
               recipe_path: str = "") -> dict:
    """Host input-pipeline throughput in ISOLATION (batches/s, MB/s):
    dataset decode/assembly through `data/pipeline.py`'s worker pool,
    no model, no train step — so host vs. device bottlenecks are
    attributable without a TPU. Forces the cpu backend (JAX_PLATFORMS)
    before any compute import: a data measurement must never wait on,
    or perturb, the shared accelerator tunnel.

    Returns one flat JSON-ready dict: the throughput numbers plus the
    pipeline's observability counters (assemble time, queue depth,
    waits, worker utilization) and the decoded-image cache's
    hit/miss/eviction counters — the schema the tier-1 smoke test pins.

    The cpu pin is unconditional (an inherited JAX_PLATFORMS=tpu must
    not defeat it) but scoped: the prior value is restored on return so
    a process that later re-execs the TPU bench (orchestrate()) does not
    leak cpu into its children. In-process caveat: if jax was already
    imported with another platform before this call, the env var is too
    late — the `bench.py --data` CLI path imports compute only after
    this line.
    """
    prev_platforms = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        return _data_bench(num_workers, batch, image_size, batches,
                           dataset, data_path, seed, recipe_path)
    finally:
        if prev_platforms is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prev_platforms


def _data_bench(num_workers, batch, image_size, batches, dataset,
                data_path, seed, recipe_path="") -> dict:
    import numpy as np  # noqa: F811 - the compute-import convention here

    from deepof_tpu.core.config import DataConfig
    from deepof_tpu.data.datasets import build_dataset
    from deepof_tpu.data.pipeline import InputPipeline, derive_batch_rng

    h, w = image_size
    if recipe_path:
        # mixed-stream proxy: the recipe's FIRST stage weighted mixture
        # assembled through the same pipeline — measures the mixture
        # layer's sampling/normalization overhead vs. a single dataset
        from deepof_tpu.core.config import recipe_from_dict
        from deepof_tpu.data.mixture import build_mixture

        with open(recipe_path) as f:
            recipe = recipe_from_dict(json.load(f))
        if not recipe.stages:
            raise SystemExit(f"--recipe {recipe_path!r}: no stages")
        stage = recipe.stages[0]
        sh, sw = stage.image_size or (h, w)
        h, w = sh, sw
        cfg = DataConfig(dataset=dataset, data_path=data_path,
                         image_size=(sh, sw),
                         gt_size=stage.gt_size or (sh, sw),
                         crop_size=stage.crop_size, batch_size=batch,
                         time_step=stage.time_step or 2,
                         num_workers=num_workers)
        ds = build_mixture(cfg, stage)
        dataset = "+".join(m.dataset for m in stage.mixture)
    else:
        cfg = DataConfig(dataset=dataset, data_path=data_path,
                         image_size=(h, w), gt_size=(h, w),
                         batch_size=batch, num_workers=num_workers)
        ds = build_dataset(cfg)

    def assemble(i: int) -> dict:
        return ds.sample_train(batch, rng=derive_batch_rng(seed, i))

    pipe = InputPipeline(assemble, num_workers=num_workers,
                         reorder_depth=cfg.reorder_depth)
    try:
        first = pipe.get()  # warm: worker spin-up, first-touch caches
        bytes_per_batch = sum(
            v.nbytes for v in first.values() if hasattr(v, "nbytes"))
        t0 = time.perf_counter()
        n_bytes = 0
        for _ in range(batches):
            b = pipe.get()
            n_bytes += sum(v.nbytes for v in b.values()
                           if hasattr(v, "nbytes"))
        dt = max(time.perf_counter() - t0, 1e-9)
        stats = pipe.stats()
    finally:
        pipe.close()
    cache = (ds.cache_stats() if hasattr(ds, "cache_stats")
             else {"hits": 0, "misses": 0, "evictions": 0})
    bps = batches / dt
    res = {
        "metric": DATA_METRIC,
        "value": round(bps, 2),
        "unit": DATA_UNIT,
        "mb_per_sec": round(n_bytes / dt / 2**20, 2),
        "bytes_per_batch": int(bytes_per_batch),
        "batches": batches,
        "batch": batch,
        "image_size": [int(h), int(w)],
        "dataset": dataset,
        "num_workers": stats["num_workers"],
        "assemble_s_mean": stats["assemble_s_mean"],
        "queue_depth": stats["queue_depth"],
        "max_queue_depth": stats["max_queue_depth"],
        "waits": stats["waits"],
        "wait_s": stats["wait_s"],
        "worker_util": stats["worker_util"],
        "decode_cache_hits": int(cache["hits"]),
        "decode_cache_misses": int(cache["misses"]),
        "decode_cache_evictions": int(cache["evictions"]),
    }
    if recipe_path and hasattr(ds, "mixture_stats"):
        # which member each timed batch actually drew — the weighted
        # split is part of the measurement's identity
        res["draws_by_dataset"] = dict(
            ds.mixture_stats()["recipe_draws_by_dataset"])
    assert np.isfinite(bps)
    return res


def parse_image_size(spec: str) -> tuple[int, int]:
    """'HxW' -> (H, W); the one parser shared by `bench.py --data` and
    the package CLI's `bench --data-only` so the two advertised forms of
    the measurement can't drift."""
    try:
        h, w = (int(x) for x in spec.lower().split("x"))
    except ValueError:
        raise SystemExit(f"bad --image-size {spec!r}: use HxW")
    return h, w


def data_main(argv: list[str]) -> int:
    """`bench.py --data [--workers N] [--batch B] [--batches N]
    [--image-size HxW] [--dataset NAME] [--data-path P]`: print the
    data-only measurement as one JSON line. Plain return codes (no
    os._exit): there is no tunnel to defuse on the cpu-only path."""
    import argparse

    p = argparse.ArgumentParser(prog="bench.py --data")
    p.add_argument("--workers", type=int, default=0)
    # batch default matches the headline config AND the package CLI's
    # `deepof_tpu bench --data-only`, so the two advertised forms of
    # this measurement are comparable out of the box
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--batches", type=int, default=32)
    p.add_argument("--image-size", default="64x64",
                   metavar="HxW")
    p.add_argument("--dataset", default="synthetic")
    p.add_argument("--data-path", default="")
    p.add_argument("--recipe", default="", metavar="FILE",
                   help="time the recipe's first-stage weighted mixture "
                        "stream (data/mixture.py) instead of --dataset")
    args = p.parse_args([a for a in argv if a != "--data"])
    h, w = parse_image_size(args.image_size)
    res = data_bench(num_workers=args.workers, batch=args.batch,
                     image_size=(h, w), batches=args.batches,
                     dataset=args.dataset, data_path=args.data_path,
                     recipe_path=args.recipe)
    print(json.dumps(res), flush=True)
    return 0


def main(deadline_s: float | None = None) -> None:
    """Child mode: run the bench under a wall-clock watchdog. The init
    watchdog alone is not enough: a wedged relay can also hang the
    *remote compile* (observed), and a stuck C++ compile thread cannot be
    interrupted — so the final line is printed from the main thread and
    the process exits with os._exit, skipping atexit hooks a dead tunnel
    would block. The orchestrating parent re-execs this mode per attempt,
    so even a wedge this watchdog cannot unwind only costs one attempt."""
    deadline_s = deadline_s or float(os.environ.get("BENCH_DEADLINE_S", 1500))
    try:
        res = _watchdog(bench, deadline_s, "bench")
    except TimeoutError as e:
        emit(0.0, 0.0, error=str(e))
        _exit(1)
    extra = {k: res[k] for k in _EXTRA_KEYS if k in res}
    emit(res["pairs_per_sec_per_chip"], _vs_baseline(res), **extra)
    _exit(0)


if __name__ == "__main__":
    if "--data" in sys.argv:
        sys.exit(data_main(sys.argv[1:]))
    elif "--run" in sys.argv:
        main()
    else:
        orchestrate()
