"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline (BASELINE.json): FlyingChairs image-pairs/sec/chip on the full
training step (forward + unsupervised pyramid loss + backward + Adam) of
the flagship Inception-v3 flow model at the reference's 320x448 input
(`deepOF.py:22`), bfloat16 compute.

The reference publishes no throughput numbers (BASELINE.md); the baseline
anchor is a self-measured first run stored in `BENCH_BASELINE.json`. When
absent, vs_baseline = 1.0.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


METRIC = "flyingchairs_train_pairs_per_sec_per_chip"
UNIT = "image-pairs/sec/chip"


def emit(value: float, vs_baseline: float, error: str | None = None) -> None:
    line = {"metric": METRIC, "value": round(value, 2), "unit": UNIT,
            "vs_baseline": round(vs_baseline, 3)}
    if error:
        line["error"] = error
    print(json.dumps(line))


def _init_devices(timeout_s: float = 240.0):
    """Backend init with a watchdog: raises TimeoutError instead of
    hanging forever when the device tunnel is wedged (the axon claim loop
    can block indefinitely if the relay is down).

    Limitation: if the container's sitecustomize itself hangs at
    interpreter startup (its register() blocks reading a relay-helper
    child's pipe), no in-process code runs at all — that failure mode can
    only be handled by the harness invoking this script under a timeout.
    """
    out: dict = {}

    def probe():
        try:
            out["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001
            out["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in out:
        return out["devices"]
    raise TimeoutError(
        out.get("error", f"backend init exceeded {timeout_s:.0f}s"))


import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def bench(model_name: str = "inception_v3", batch: int = 16,
          image_size=(320, 448), steps: int = 20, warmup: int = 3) -> dict:
    from deepof_tpu.core.config import (
        DataConfig, ExperimentConfig, LossConfig, OptimConfig, TrainConfig)
    from deepof_tpu.data.datasets import SyntheticData
    from deepof_tpu.models.registry import build_model
    from deepof_tpu.parallel.mesh import batch_sharding, build_mesh
    from deepof_tpu.train.state import create_train_state, make_optimizer
    from deepof_tpu.train.step import make_train_step

    h, w = image_size
    n_chips = len(_init_devices())  # watchdog covers every entrypoint
    cfg = ExperimentConfig(
        name="bench",
        model=model_name,
        loss=LossConfig(weights=(16, 8, 4, 2, 1, 1)),
        optim=OptimConfig(learning_rate=1.6e-5),
        data=DataConfig(dataset="synthetic", image_size=(h, w), gt_size=(h, w),
                        batch_size=batch),
        train=TrainConfig(seed=0, compute_dtype="bfloat16"),
    )
    mesh = build_mesh(cfg.mesh)
    model = build_model(cfg.model, dtype=jnp.bfloat16)
    tx = make_optimizer(cfg.optim, lambda s: cfg.optim.learning_rate)
    state = create_train_state(model, jnp.zeros((batch, h, w, 6)), tx, seed=0)
    ds = SyntheticData(cfg.data)
    step = make_train_step(model, cfg, ds.mean, mesh)
    b = jax.device_put(ds.sample_train(batch, iteration=0), batch_sharding(mesh))

    for _ in range(warmup):
        state, metrics = step(state, b)
    jax.block_until_ready(metrics["total"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, b)
    jax.block_until_ready(metrics["total"])
    dt = time.perf_counter() - t0

    pairs_per_sec = steps * batch / dt
    per_chip = pairs_per_sec / n_chips
    assert np.isfinite(float(jax.device_get(metrics["total"])))
    return {"pairs_per_sec_per_chip": per_chip, "pairs_per_sec": pairs_per_sec,
            "n_chips": n_chips, "batch": batch, "steps_per_sec": steps / dt}


def main() -> None:
    try:
        res = bench()
    except TimeoutError as e:
        # harness contract: always ONE JSON line; nonzero exit flags failure
        emit(0.0, 0.0, error=f"accelerator unavailable: {e}")
        sys.exit(1)
    baseline_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    vs = 1.0
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f).get("pairs_per_sec_per_chip")
        if base:
            vs = res["pairs_per_sec_per_chip"] / base
    emit(res["pairs_per_sec_per_chip"], vs)


if __name__ == "__main__":
    main()
