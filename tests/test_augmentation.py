"""Augmentation tests: determinism under fixed keys, identity/flip exactness
of the affine path, photometric range preservation, dual-stream batch keys."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepof_tpu.core.config import DataConfig
from deepof_tpu.data import (
    apply_geo,
    augment_batch,
    identity_geo_params,
    make_augment_fn,
    photometric_augment,
    sample_geo_params,
)


@pytest.fixture
def images(rng):
    return jnp.asarray(rng.rand(2, 16, 24, 3).astype(np.float32) * 255.0)


def test_apply_geo_identity(images):
    out = apply_geo(images, identity_geo_params(2))
    np.testing.assert_allclose(np.asarray(out), np.asarray(images), atol=1e-3)


def test_apply_geo_flip(images):
    params = identity_geo_params(2)
    params["flip"] = jnp.asarray([True, False])
    out = np.asarray(apply_geo(images, params))
    np.testing.assert_allclose(out[0], np.asarray(images)[0, :, ::-1], atol=1e-3)
    np.testing.assert_allclose(out[1], np.asarray(images)[1], atol=1e-3)


def test_apply_geo_translation(images):
    params = identity_geo_params(2)
    params["tx"] = jnp.asarray([0.25, 0.0])  # shift right by 6 of 24 cols
    out = np.asarray(apply_geo(images, params))
    np.testing.assert_allclose(out[0][:, 6:], np.asarray(images)[0][:, :-6],
                               atol=1e-3)


def test_geo_params_deterministic():
    p1 = sample_geo_params(jax.random.PRNGKey(7), 4)
    p2 = sample_geo_params(jax.random.PRNGKey(7), 4)
    for k in p1:
        np.testing.assert_array_equal(np.asarray(p1[k]), np.asarray(p2[k]))
    assert float(jnp.max(p1["scale"])) <= 2.0
    assert float(jnp.min(p1["scale"])) >= 0.9


def test_photometric_identical_params_both_frames(images):
    a, b = photometric_augment(jax.random.PRNGKey(0), images, images)
    # same input + same per-sample params -> near-identical outputs (only the
    # additive noise differs between frames)
    assert float(jnp.mean(jnp.abs(a - b))) < 255.0 * 0.05
    assert float(jnp.min(a)) >= 0.0 and float(jnp.max(a)) <= 255.0


def test_augment_batch_dual_stream(images):
    batch = {"source": images, "target": images,
             "flow": jnp.zeros((2, 16, 24, 2)), "label": jnp.zeros((2,), jnp.int32)}
    out = augment_batch(batch, jax.random.PRNGKey(3), geo=True, photo=True)
    assert {"source", "target", "net_source", "net_target", "flow", "label"} <= set(out)
    # geo pair differs from the photo pair; flow passes through untouched
    assert not np.allclose(np.asarray(out["source"]), np.asarray(out["net_source"]))
    np.testing.assert_array_equal(np.asarray(out["flow"]), np.asarray(batch["flow"]))
    # deterministic under the same key
    out2 = augment_batch(batch, jax.random.PRNGKey(3), geo=True, photo=True)
    np.testing.assert_allclose(np.asarray(out["net_source"]),
                               np.asarray(out2["net_source"]))


def test_make_augment_fn_stays_on_device(rng):
    """Augmented tensors stay as jax arrays (no host roundtrip; the
    prefetcher device_puts them straight to the mesh sharding)."""
    import jax

    cfg = DataConfig(augment_geo=True, augment_photo=True)
    fn = make_augment_fn(cfg)
    batch = {"source": rng.rand(2, 16, 16, 3).astype(np.float32) * 255,
             "target": rng.rand(2, 16, 16, 3).astype(np.float32) * 255}
    out = fn(batch, 123)
    assert isinstance(out["net_source"], jax.Array)
    assert out["net_source"].shape == (2, 16, 16, 3)
    assert np.isfinite(np.asarray(out["net_source"])).all()
