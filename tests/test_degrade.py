"""Brownout control-plane tests (DESIGN.md "Brownout").

Unit tier (no threads, no sleeps beyond the batcher's own): the
DegradeController decision core driven with fabricated clocks/signals
(the `Autoscaler.evaluate` idiom from test_supervise.py) — escalation,
symmetric recovery, hysteresis-band streak resets, cooldowns, level
bounds, and no flapping under an oscillating load; the engine's
deadline gates at every stage (enqueue backpressure, pre-dispatch
flush) and its L1/L2 operating-point folding; the router's admission
deadline gate, malformed-header rejection, and the L3 low-priority
shed ordering against stub replicas; and `tail` rc 10 on sustained L3.

Chaos tier (subprocess replicas, fake timed executor): the ISSUE 19
acceptance drill — the identical mixed-priority overload against two
live 2-replica fleets, brownout off vs on; the ON leg must shed ZERO
default-priority requests while the OFF leg sheds >= 1, with the
ladder walk visible in the degrade_* counters.
"""

import dataclasses
import importlib.util
import json
import os
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from deepof_tpu.core.config import get_config
from deepof_tpu.serve.buckets import next_smaller_bucket
from deepof_tpu.serve.degrade import LEVELS, DegradeController
from deepof_tpu.serve.engine import InferenceEngine, ServeError

# ----------------------------------------------------------- helpers


def _cfg(max_batch=4, timeout_ms=400.0, buckets=(), image_size=(32, 64),
         log_dir="/tmp/deepof_degrade_test", **serve_kw):
    cfg = get_config("flyingchairs")
    return cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=image_size, gt_size=image_size),
        serve=dataclasses.replace(cfg.serve, max_batch=max_batch,
                                  batch_timeout_ms=timeout_ms,
                                  buckets=buckets, **serve_kw),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6), log_dir=log_dir))


class _FakeForward:
    """Deterministic timed executor (test_serve.py's): per-dispatch
    sleep, flow = channel difference."""

    def __init__(self, exec_s=0.0):
        self.exec_s = exec_s
        self.dispatches = 0
        self.lock = threading.Lock()

    def __call__(self, bucket, x):
        with self.lock:
            self.dispatches += 1
        if self.exec_s > 0:
            time.sleep(self.exec_s)
        return np.stack([x[..., 0] - x[..., 3], x[..., 1] - x[..., 4]],
                        axis=-1).astype(np.float32)


def _img(rng, hw=(48, 96)):
    return rng.randint(1, 255, (*hw, 3), dtype=np.uint8)


def _ctrl(**degrade_kw):
    """A DegradeController with no live fleet/router: `evaluate` is a
    pure function of (clock, signals, accumulated streak state)."""
    defaults = dict(enabled=True, period_s=0.25, escalate_after_s=2.0,
                    recover_after_s=10.0, escalate_cooldown_s=5.0,
                    recover_cooldown_s=5.0, up_occupancy=0.85,
                    down_occupancy=0.5, up_slo_burn=0.7, max_level=3,
                    l3_sustained_s=30.0)
    defaults.update(degrade_kw)
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(serve=dataclasses.replace(
        cfg.serve, degrade=dataclasses.replace(cfg.serve.degrade,
                                               **defaults)))
    return DegradeController(cfg, fleet=None, router=None)


def _sig(**kw):
    base = dict(ready=2, bad_total=0, occupancy=0.6, slo_burn=0.0)
    base.update(kw)
    return base


# ------------------------------------------------- decision core (pure)


def test_degrade_shed_pressure_sustained_escalates():
    c = _ctrl()
    # new refused work each tick: pressure from t=0, sustained past the
    # 2 s window -> ONE escalation, reason shed
    assert c.evaluate(0.0, _sig(bad_total=5)) == (None, "holding")
    assert c.evaluate(1.0, _sig(bad_total=9))[0] is None
    assert c.evaluate(2.5, _sig(bad_total=14)) == ("escalate", "shed")


def test_degrade_hysteresis_band_resets_streaks():
    c = _ctrl()
    c.evaluate(0.0, _sig(occupancy=0.9))
    # one mid-band tick (between down 0.5 and up 0.85) resets the
    # pressure streak: the next decision re-earns its full window
    c.evaluate(1.5, _sig(occupancy=0.6))
    assert c.evaluate(3.0, _sig(occupancy=0.9))[0] is None
    assert c.evaluate(5.5, _sig(occupancy=0.9)) == ("escalate", "occupancy")


def test_degrade_no_flapping_under_oscillating_load():
    """A load oscillating faster than either window never transitions:
    each pressure tick kills the calm streak and vice versa — the
    controller holds instead of flapping the fleet's operating point."""
    c = _ctrl(escalate_after_s=2.0, recover_after_s=2.0)
    c._level = 1
    t = 0.0
    for _ in range(40):
        assert c.evaluate(t, _sig(occupancy=0.95))[0] is None
        t += 1.0
        assert c.evaluate(t, _sig(occupancy=0.2))[0] is None
        t += 1.0
    assert c.level() == 1


def test_degrade_escalate_cooldown_and_max_level():
    c = _ctrl()
    c._last_escalate_m = 2.0
    c.evaluate(3.0, _sig(occupancy=1.0))
    # window met at 5.5 but only 3.5 s since the last escalation
    assert c.evaluate(5.5, _sig(occupancy=1.0)) == (None,
                                                    "escalate cooldown")
    assert c.evaluate(7.5, _sig(occupancy=1.0))[0] == "escalate"
    # at the ladder's top: pressure is reported, never acted on
    c2 = _ctrl()
    c2._level = 3
    c2.evaluate(0.0, _sig(occupancy=1.0))
    action, reason = c2.evaluate(2.5, _sig(occupancy=1.0))
    assert action is None and "max_level" in reason


def test_degrade_recovery_symmetric_with_cooldown_and_floor():
    c = _ctrl()
    c._level = 2
    c.evaluate(0.0, _sig(occupancy=0.3))
    assert c.evaluate(5.0, _sig(occupancy=0.3))[0] is None
    assert c.evaluate(10.5, _sig(occupancy=0.3)) == ("recover",
                                                     "sustained calm")
    # a fresh transition blocks the next recovery for recover_cooldown_s
    c2 = _ctrl()
    c2._level = 2
    c2._last_event_m = 9.0
    c2.evaluate(2.0, _sig(occupancy=0.3))
    assert c2.evaluate(12.5, _sig(occupancy=0.3)) == (None,
                                                      "recover cooldown")
    # at L0 calm is steady state, not an event
    c3 = _ctrl()
    c3.evaluate(0.0, _sig(occupancy=0.3))
    action, reason = c3.evaluate(10.5, _sig(occupancy=0.3))
    assert action is None and "L0" in reason


def test_degrade_slo_burn_is_pressure():
    c = _ctrl()
    c.evaluate(0.0, _sig(slo_burn=0.8))
    assert c.evaluate(2.5, _sig(slo_burn=0.8)) == ("escalate", "slo_burn")


def test_degrade_stats_block_and_l3_sustained():
    c = _ctrl(l3_sustained_s=30.0)
    s = c.stats()
    assert s["degrade_enabled"] is True
    assert s["degrade_level"] == 0
    assert s["degrade_level_name"] == LEVELS[0] == "normal"
    assert s["degrade_l3_sustained"] is False
    # L3 held past the budget: the rc-10 verdict flips
    c._level = 3
    c._l3_since = time.monotonic() - 100.0
    s = c.stats()
    assert s["degrade_l3_sustained"] is True
    assert s["degrade_l3_age_s"] >= 99.0


# ------------------------------------------- engine deadline + folding


def test_engine_flush_expired_deadline_fails_fast(rng):
    """A request whose deadline lapses while it waits for the batch
    window dies at the flush gate with a structured deadline_exceeded —
    it never occupies a padded batch slot."""
    fake = _FakeForward()
    with InferenceEngine(_cfg(max_batch=4, timeout_ms=150.0),
                         forward_fn=fake) as eng:
        fut = eng.submit(_img(rng), _img(rng), deadline_s=0.02)
        with pytest.raises(ServeError) as ei:
            fut.result(timeout=10)
        assert ei.value.code == "deadline_exceeded"
        stats = eng.stats()
        assert stats["deadline_requests"] == 1
        assert stats["deadline_flush_expired"] == 1
        # the expired request was filtered OUT of the batch, and a
        # deadline failure is the CALLER's budget, not a server error
        assert fake.dispatches == 0
        assert stats["serve_server_errors"] == 0
        # a live sibling with budget still serves
        assert eng.submit(_img(rng), _img(rng),
                          deadline_s=30.0).result(timeout=10)["flow"].size


def test_engine_enqueue_expired_deadline_under_backpressure(rng):
    """queue_depth backpressure polls the deadline: a request that
    cannot enter the queue before its budget lapses fails structured
    instead of blocking the submitter past its own deadline."""
    fake = _FakeForward(exec_s=0.5)
    cfg = _cfg(max_batch=1, timeout_ms=1.0, queue_depth=1)
    with InferenceEngine(cfg, forward_fn=fake) as eng:
        f1 = eng.submit(_img(rng), _img(rng))  # dispatched, executor busy
        time.sleep(0.1)
        f2 = eng.submit(_img(rng), _img(rng))  # fills the queue
        f3 = eng.submit(_img(rng), _img(rng), deadline_s=0.05)
        with pytest.raises(ServeError) as ei:
            f3.result(timeout=10)
        assert ei.value.code == "deadline_exceeded"
        assert eng.stats()["deadline_enqueue_expired"] == 1
        f1.result(timeout=10)
        f2.result(timeout=10)


def test_engine_degrade_level_folds_tier_and_bucket(rng):
    """L1 serves default-precision requests at the cheapest configured
    tier; L2 additionally drops one bucket rung; an EXPLICIT precision
    is honored at any level. Every reached operating point is a
    (bucket, tier) pair the warmup lattice already owns — the fold is
    pure routing, no compile."""
    cfg = _cfg(max_batch=1, timeout_ms=5.0,
               buckets=((16, 32), (32, 64)), precisions=("f32", "bf16"))
    with InferenceEngine(cfg, forward_fn=_FakeForward()) as eng:
        r0 = eng.submit(_img(rng, (30, 60)), _img(rng, (30, 60)),
                        degrade_level=0).result(timeout=10)
        assert r0["precision"] == "f32" and r0["bucket"] == (32, 64)
        r1 = eng.submit(_img(rng, (30, 60)), _img(rng, (30, 60)),
                        degrade_level=1).result(timeout=10)
        assert r1["precision"] == "bf16" and r1["bucket"] == (32, 64)
        r2 = eng.submit(_img(rng, (30, 60)), _img(rng, (30, 60)),
                        degrade_level=2).result(timeout=10)
        assert r2["precision"] == "bf16" and r2["bucket"] == (16, 32)
        # explicit tier survives the brownout
        r3 = eng.submit(_img(rng, (30, 60)), _img(rng, (30, 60)),
                        precision="f32", degrade_level=2).result(timeout=10)
        assert r3["precision"] == "f32"
        stats = eng.stats()
        assert stats["degrade_tier_downgrades"] == 2
        assert stats["degrade_bucket_downgrades"] == 2
    # the ladder helper: one rung down, floor-clamped, off-ladder no-op
    ladder = ((16, 32), (32, 64))
    assert next_smaller_bucket((32, 64), ladder) == (16, 32)
    assert next_smaller_bucket((16, 32), ladder) == (16, 32)
    assert next_smaller_bucket((64, 64), ladder) == (64, 64)


# --------------------------------------------- router admission + shed

from conftest import free_port  # noqa: E402

from deepof_tpu.serve.router import Router  # noqa: E402


class _StubFleet:
    """test_fleet.py's duck-typed Fleet for router unit tests."""

    def __init__(self, ports, host="127.0.0.1"):
        self.host = host
        self.ports = list(ports)
        self.size = len(self.ports)
        self.failures = []

    def ready_replicas(self):
        return [SimpleNamespace(idx=i, port=p)
                for i, p in enumerate(self.ports) if p is not None]

    def note_failure(self, idx):
        self.failures.append(idx)

    def stats(self):
        return {"fleet_replicas": self.size,
                "fleet_ready": len(self.ready_replicas())}

    def describe(self):
        return []


def _stub_replica():
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            body = json.dumps({"served_by": self.server.server_address[1],
                               "deadline_ms_seen":
                               self.headers.get("X-Deadline-Ms"),
                               "level_seen":
                               self.headers.get("X-Degrade-Level")}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def _router_cfg(log_dir):
    cfg = get_config("flyingchairs")
    return cfg.replace(
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=(32, 64), gt_size=(32, 64)),
        train=dataclasses.replace(cfg.train, log_dir=str(log_dir)))


def _flow_body(rng, hw=(30, 60)) -> bytes:
    def b64(img):
        import base64
        ok, buf = cv2.imencode(".png", img)
        assert ok
        return base64.b64encode(buf.tobytes()).decode()

    return json.dumps({"prev": b64(_img(rng, hw)),
                       "next": b64(_img(rng, hw))}).encode()


def test_router_admission_rejects_expired_deadline(rng, tmp_path):
    """An already-expired deadline dies at the front door with 504
    deadline_exceeded — it never reaches a replica; a live deadline is
    re-stamped as REMAINING budget on the proxied hop."""
    stub = _stub_replica()
    try:
        fleet = _StubFleet([stub.server_address[1]])
        router = Router(_router_cfg(tmp_path), fleet)
        body = _flow_body(rng)
        status, payload, _ = router.handle_flow(
            "/v1/flow", body, "application/json",
            headers={"X-Deadline-Ms": "0"})
        assert status == 504
        assert json.loads(payload)["error"] == "deadline_exceeded"
        assert router.stats()["deadline_admission_expired"] == 1
        assert router.stats()["fleet_routed"] == {}  # never proxied
        # a live deadline rides through, restamped as remaining ms
        status, payload, _ = router.handle_flow(
            "/v1/flow", body, "application/json",
            headers={"X-Deadline-Ms": "30000"})
        assert status == 200
        seen = float(json.loads(payload)["deadline_ms_seen"])
        assert 0.0 < seen <= 30000.0
        # malformed budgets are the CLIENT's bug: structured 400
        status, payload, _ = router.handle_flow(
            "/v1/flow", body, "application/json",
            headers={"X-Deadline-Ms": "soon"})
        assert status == 400
        assert json.loads(payload)["error"] == "bad_request"
    finally:
        stub.shutdown()
        stub.server_close()


def test_router_l3_sheds_low_priority_first(rng, tmp_path):
    """Priority shed ordering: at L3 a low-priority request answers a
    structured 503 shed_low_priority at admission while default
    traffic keeps serving (on the degraded operating point, stamped in
    X-Degrade-Level); below L3 low-priority serves normally."""
    stub = _stub_replica()
    try:
        fleet = _StubFleet([stub.server_address[1]])
        router = Router(_router_cfg(tmp_path), fleet)
        router.degrade_level = lambda: 3
        body = _flow_body(rng)
        status, payload, _ = router.handle_flow(
            "/v1/flow", body, "application/json",
            headers={"X-Priority": "low"})
        assert status == 503
        assert json.loads(payload)["error"] == "shed_low_priority"
        assert router.stats()["degrade_shed_low"] == 1
        # default traffic rides through with the live level stamped
        status, payload, _ = router.handle_flow(
            "/v1/flow", body, "application/json")
        assert status == 200
        assert json.loads(payload)["level_seen"] == "3"
        # below L3 the same low-priority request serves
        router.degrade_level = lambda: 2
        status, payload, _ = router.handle_flow(
            "/v1/flow", body, "application/json",
            headers={"X-Priority": "low"})
        assert status == 200
        # an unknown priority class is a client bug, not a guess
        status, payload, _ = router.handle_flow(
            "/v1/flow", body, "application/json",
            headers={"X-Priority": "urgent"})
        assert status == 400
    finally:
        stub.shutdown()
        stub.server_close()


def test_router_relays_replica_deadline_504_without_failover(rng,
                                                             tmp_path):
    """A replica's own deadline_exceeded 504 is the CALLER's verdict:
    the router relays it — replaying the request on a sibling would
    burn a second slot on work whose budget is already gone."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Expired(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            body = json.dumps({"error": "deadline_exceeded",
                               "message": "deadline expired"}).encode()
            self.send_response(504)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    expired = ThreadingHTTPServer(("127.0.0.1", 0), Expired)
    expired.daemon_threads = True
    threading.Thread(target=expired.serve_forever, daemon=True).start()
    healthy = _stub_replica()
    try:
        fleet = _StubFleet([expired.server_address[1],
                            healthy.server_address[1]])
        router = Router(_router_cfg(tmp_path), fleet)
        status, payload, _ = router.handle_flow(
            "/v1/flow", _flow_body(rng), "application/json",
            headers={"X-Deadline-Ms": "5000"})
        assert status == 504
        assert json.loads(payload)["error"] == "deadline_exceeded"
        assert router.stats()["fleet_failovers"] == 0
        assert fleet.failures == []  # the replica is healthy, not sick
    finally:
        for s in (expired, healthy):
            s.shutdown()
            s.server_close()


# ------------------------------------------------------------ tail rc 10


def test_tail_exits_10_on_sustained_l3(tmp_path, capsys):
    from deepof_tpu.cli import main as cli_main

    def run_dir(name, sustained):
        d = tmp_path / name
        d.mkdir()
        (d / "metrics.jsonl").write_text("")
        (d / "heartbeat.json").write_text(json.dumps({
            "time": time.time(), "pid": os.getpid(), "step": 0,
            "serve_requests": 50, "serve_responses": 50,
            "degrade_enabled": True, "degrade_level": 3,
            "degrade_level_name": "shed_low_priority",
            "degrade_transitions": 3, "degrade_escalations": 3,
            "degrade_recoveries": 0, "degrade_l3_entries": 1,
            "degrade_l3_age_s": 45.0 if sustained else 1.0,
            "degrade_l3_sustained": sustained,
            "degrade_last_reason": "shed"}))
        return d

    rc = cli_main(["tail", "--log-dir", str(run_dir("browned", True))])
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["degrade"]["l3_sustained"] is True
    assert summary["degrade"]["level"] == 3
    assert rc == 10
    # L3 inside its budget is a brownout doing its job: rc 0
    assert cli_main(["tail", "--log-dir",
                     str(run_dir("bridging", False))]) == 0


# ------------------------------------------------------ chaos drill


def _load_serve_bench():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "serve_bench.py")
    spec = importlib.util.spec_from_file_location("serve_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.chaos
def test_brownout_drill_protects_default_priority(tmp_path):
    """The ISSUE 19 acceptance: the identical mixed-priority overload
    against two live 2-replica fleets — brownout OFF sheds
    default-priority work (>= 1), brownout ON sheds ZERO default
    requests in the counted window, redirects the overload onto
    low-priority sheds at L3, and the tier/bucket downgrade counters
    prove the intermediate rungs actually served cheaper. Zero silent
    drops on either leg."""
    sb = _load_serve_bench()
    res = sb.brownout_bench(replicas=2, default_clients=3, low_clients=8,
                            ramp_s=1.5, window_s=2.0,
                            log_dir=str(tmp_path))
    assert res["default_sheds_on"] == 0
    assert res["default_sheds_off"] >= 1
    assert res["max_level_on"] == 3
    assert res["shed_low_on"] >= 1
    assert res["tier_downgrades_on"] >= 1
    assert res["bucket_downgrades_on"] >= 1
    assert res["drops"] == 0
    # the schema the BENCH rounds pin
    missing = [k for k in sb.BROWNOUT_REQUIRED_KEYS if k not in res]
    assert not missing, missing
    # the transition timeline landed in the ON leg's metrics.jsonl as
    # kind="serve" records (the analyze/tail surface)
    recs = []
    with open(os.path.join(str(tmp_path), "leg_on", "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("kind") == "serve" and "level_after" in rec:
                recs.append(rec)
    assert [r["level_after"] for r in recs][:3] == [1, 2, 3]
    assert all(r["event"] == "degrade_escalate" for r in recs[:3])
