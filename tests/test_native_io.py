"""Native C++ IO library vs the Python/cv2 reference path."""

import os

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from deepof_tpu import native
from deepof_tpu.core.config import DataConfig
from deepof_tpu.data.datasets import FlyingChairsData
from deepof_tpu.io.flo import read_flo, write_flo

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="g++ toolchain unavailable")


def _write_ppm(path, img):
    h, w, _ = img.shape
    with open(path, "wb") as f:
        f.write(b"P6\n# comment line\n%d %d\n255\n" % (w, h))
        f.write(img[..., ::-1].tobytes())  # PPM stores RGB; img is BGR


@pytest.fixture
def chairs_dir(tmp_path, rng):
    for i in range(4):
        img1 = rng.randint(0, 255, (64, 96, 3), dtype=np.uint8)
        img2 = rng.randint(0, 255, (64, 96, 3), dtype=np.uint8)
        flow = rng.randn(64, 96, 2).astype(np.float32)
        sid = f"{i + 1:05d}"
        _write_ppm(tmp_path / f"{sid}_img1.ppm", img1)
        _write_ppm(tmp_path / f"{sid}_img2.ppm", img2)
        write_flo(str(tmp_path / f"{sid}_flow.flo"), flow)
    return tmp_path


def test_native_ppm_identity_decode(chairs_dir):
    got = native.decode_ppm_batch([str(chairs_dir / "00001_img1.ppm")],
                                  (64, 96))[0]
    want = cv2.imread(str(chairs_dir / "00001_img1.ppm"), cv2.IMREAD_COLOR)
    np.testing.assert_allclose(got, want.astype(np.float32), atol=0.01)


def test_native_ppm_resize_matches_cv2(chairs_dir):
    got = native.decode_ppm_batch([str(chairs_dir / "00002_img1.ppm")],
                                  (32, 48))[0]
    raw = cv2.imread(str(chairs_dir / "00002_img1.ppm"), cv2.IMREAD_COLOR)
    want = cv2.resize(raw, (48, 32), interpolation=cv2.INTER_LINEAR)
    # cv2 resizes in uint8 (rounds); native computes float — allow 1 LSB
    np.testing.assert_allclose(got, want.astype(np.float32), atol=1.0)


def test_native_flo_roundtrip(chairs_dir):
    path = str(chairs_dir / "00003_flow.flo")
    assert native.flo_dims(path) == (64, 96)
    got = native.read_flo_batch([path], (64, 96))[0]
    np.testing.assert_array_equal(got, read_flo(path))


def test_flyingchairs_native_batch_matches_python(chairs_dir):
    # streaming mode (cache_decoded=False) activates the native batch path
    cfg = DataConfig(dataset="flyingchairs", data_path=str(chairs_dir),
                     image_size=(64, 96), gt_size=(64, 96), batch_size=2,
                     cache_decoded=False)
    ds = FlyingChairsData(cfg)
    assert ds._native_batch(["00001"]) is not None  # native path active
    b_native = ds.sample_train(2, iteration=0)
    assert b_native["source"].shape == (2, 64, 96, 3)
    assert b_native["flow"].shape == (2, 64, 96, 2)
    # force the python path and compare
    ds2 = FlyingChairsData(cfg)
    ds2._native_batch = lambda sids: None
    b_py = ds2.sample_train(2, iteration=0)
    np.testing.assert_allclose(b_native["source"], b_py["source"], atol=0.01)
    np.testing.assert_allclose(b_native["target"], b_py["target"], atol=0.01)
    np.testing.assert_array_equal(b_native["flow"], b_py["flow"])


def test_native_parallel_large_batch(chairs_dir):
    paths = [str(chairs_dir / f"{i + 1:05d}_img1.ppm") for i in range(4)] * 16
    out = native.decode_ppm_batch(paths, (32, 48))
    assert out.shape == (64, 32, 48, 3)
    assert np.isfinite(out).all()


def test_native_missing_file_raises(chairs_dir):
    with pytest.raises(IOError):
        native.decode_ppm_batch([str(chairs_dir / "nope.ppm")], (32, 48))


def test_native_corrupt_ppm_header_fails_cleanly(tmp_path):
    bad = tmp_path / "bad.ppm"
    bad.write_bytes(b"P6\n99999999 99999999\n255\n")  # absurd dims
    with pytest.raises(IOError):
        native.decode_ppm_batch([str(bad)], (32, 48))
    neg = tmp_path / "neg.ppm"
    neg.write_bytes(b"P6\n-5 10\n255\n")
    with pytest.raises(IOError):
        native.decode_ppm_batch([str(neg)], (32, 48))


def test_native_flo_dim_mismatch_fails(chairs_dir):
    # batch API probes dims from the first file; a mixed-resolution file
    # must error, not silently fread with the wrong row stride
    small = chairs_dir / "small.flo"
    write_flo(str(small), np.zeros((8, 8, 2), np.float32))
    with pytest.raises(IOError):
        native.read_flo_batch([str(chairs_dir / "00001_flow.flo"),
                               str(small)], (64, 96))


def test_native_png_decode_matches_cv2(tmp_path, rng):
    img = rng.randint(0, 255, (40, 56, 3), dtype=np.uint8)
    p = str(tmp_path / "x.png")
    cv2.imwrite(p, img)
    if not native.image_supported(p):
        pytest.skip("library built without PNG codec")
    got = native.decode_image_batch([p], (40, 56))[0]
    np.testing.assert_allclose(got, img.astype(np.float32), atol=0.01)


def test_native_jpeg_decode_close_to_cv2(tmp_path, rng):
    # JPEG decode is not bit-exact across libjpeg builds; compare loosely
    img = rng.randint(0, 255, (40, 56, 3), dtype=np.uint8)
    p = str(tmp_path / "x.jpg")
    cv2.imwrite(p, img, [cv2.IMWRITE_JPEG_QUALITY, 95])
    if not native.image_supported(p):
        pytest.skip("library built without JPEG codec")
    got = native.decode_image_batch([p], (40, 56))[0]
    want = cv2.imread(p, cv2.IMREAD_COLOR).astype(np.float32)
    assert np.abs(got - want).mean() < 2.0


def test_sintel_native_batch_matches_python(tmp_path, rng):
    from deepof_tpu.data.datasets import SintelData
    from deepof_tpu.io.flo import write_flo as wf

    for clip in ("alley_1", "bamboo_2"):
        img_dir = tmp_path / "training" / "final" / clip
        flow_dir = tmp_path / "training" / "flow" / clip
        img_dir.mkdir(parents=True)
        flow_dir.mkdir(parents=True)
        for f in range(1, 5):
            cv2.imwrite(str(img_dir / f"frame_{f:04d}.png"),
                        rng.randint(0, 255, (32, 64, 3), np.uint8))
            if f < 4:
                wf(str(flow_dir / f"frame_{f:04d}.flo"),
                   rng.randn(32, 64, 2).astype(np.float32))
    cfg = DataConfig(dataset="sintel", data_path=str(tmp_path),
                     image_size=(32, 64), gt_size=(32, 64), time_step=3,
                     sintel_pass="final", crop_size=(16, 32),
                     cache_decoded=False)
    ds = SintelData(cfg)
    if not native.image_supported(ds.windows[0][0]):
        pytest.skip("library built without PNG codec")
    assert ds._native_batch([0, 1]) is not None  # native path active
    bn = ds.sample_train(2, rng=np.random.RandomState(7))
    ds2 = SintelData(cfg)
    ds2._native_batch = lambda idxs, crop_rng=None: None
    bp = ds2.sample_train(2, rng=np.random.RandomState(7))
    assert bn["volume"].shape == bp["volume"].shape == (2, 16, 32, 9)
    np.testing.assert_allclose(bn["volume"], bp["volume"], atol=0.01)
    np.testing.assert_array_equal(bn["flow"], bp["flow"])


def test_ucf101_native_batch_matches_python(tmp_path, rng):
    from deepof_tpu.data.datasets import UCF101Data

    for ci, cls in enumerate(("ApplyEyeMakeup", "Archery")):
        clip = tmp_path / "frames" / cls / f"v_{cls}_g09_c01"
        clip.mkdir(parents=True)
        for f in range(3):
            cv2.imwrite(str(clip / f"f{f}.jpg"),
                        rng.randint(0, 255, (24, 32, 3), np.uint8))
    cfg = DataConfig(dataset="ucf101", data_path=str(tmp_path),
                     image_size=(24, 32), cache_decoded=False)
    ds = UCF101Data(cfg)
    first = ds.train_clips[0][0][0]
    if not native.image_supported(first):
        pytest.skip("library built without JPEG codec")
    bn = ds.sample_train(2, rng=np.random.RandomState(3))
    cfg2 = DataConfig(dataset="ucf101", data_path=str(tmp_path),
                      image_size=(24, 32), cache_decoded=True)  # python path
    ds2 = UCF101Data(cfg2)
    bp = ds2.sample_train(2, rng=np.random.RandomState(3))
    np.testing.assert_array_equal(bn["label"], bp["label"])
    # same frames picked (shared rng order); JPEG decoders may differ by
    # a few LSBs between libjpeg variants
    assert np.abs(bn["source"] - bp["source"]).mean() < 2.0
    assert np.abs(bn["target"] - bp["target"]).mean() < 2.0


def test_corrupt_file_mid_dataset_falls_back_to_python(chairs_dir):
    # A file the native codecs cannot decode (BMP content behind a .ppm
    # name — cv2 sniffs content and reads it fine) must degrade the BATCH
    # to the cv2 path, not raise out of the loader (ADVICE r02): same
    # content as the pure python batch, one RuntimeWarning.
    img = cv2.imread(str(chairs_dir / "00002_img1.ppm"), cv2.IMREAD_COLOR)
    ok, buf = cv2.imencode(".bmp", img)
    assert ok
    (chairs_dir / "00002_img1.ppm").write_bytes(buf.tobytes())
    cfg = DataConfig(dataset="flyingchairs", data_path=str(chairs_dir),
                     image_size=(64, 96), gt_size=(64, 96), batch_size=2,
                     cache_decoded=False)
    import deepof_tpu.data.datasets as dsm
    dsm._warned_native_fallback = False
    ds = FlyingChairsData(cfg)
    with pytest.warns(RuntimeWarning, match="native IO batch failed"):
        b = ds.sample_train(2, iteration=0)  # batch = 00001, 00002
    ds2 = FlyingChairsData(cfg)
    ds2._native_batch = lambda sids: None
    b_py = ds2.sample_train(2, iteration=0)
    np.testing.assert_allclose(b["source"], b_py["source"], atol=0.01)
    np.testing.assert_array_equal(b["flow"], b_py["flow"])


def test_single_image_entrypoints_survive_hostile_header(tmp_path):
    # Exported single-image C functions are callable straight from ctypes;
    # a 64k x 64k header must fail the call (rc != 0), not unwind a
    # bad_alloc across the C ABI (ADVICE r02).
    import ctypes

    bad = tmp_path / "huge.ppm"
    bad.write_bytes(b"P6\n65536 65536\n255\n")
    lib = native._load()
    out = np.empty((8, 8, 3), np.float32)
    ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    assert lib.deepof_decode_ppm(str(bad).encode(), ptr, 8, 8) != 0
    assert lib.deepof_decode_image(str(bad).encode(), ptr, 8, 8) != 0
