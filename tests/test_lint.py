"""graftlint (deepof_tpu/lint/) + the observability registry — ISSUE 12.

Fast tier, jax-free by construction (the linter's contract):

  - fixture-snippet positive/negative unit tests for all five rules
    (counter-registry, config-key, determinism, jit-purity,
    lock-discipline), waiver honoring (reason REQUIRED), and the CLI
    rc contract (0 clean / 2 findings / 1 usage error);
  - THE TIER-1 GATE: the linter over deepof_tpu/ + tools/ must report
    zero non-waived findings in < 30 s — the CI teeth of the whole
    subsystem;
  - the single registry-driven config-typo test that replaces the
    per-PR hand-written ones (test_fleet/test_elastic/test_session/
    test_warm each carried one): a parametrized walk over EVERY node
    of the config dataclass tree, with the four old hand-written
    assertions kept as explicit parity pins;
  - registry-driven merge pins on the recorded fixture run dir
    (tests/fixtures/obs_run + goldens): `summarize` and the fleet
    scrape are byte-identical to pre-refactor; `tail --fleet` /
    `aggregate_processes` are pinned byte-identical to the recorded
    post-refactor goldens AND proven a value-preserving superset of
    the pre-refactor output (the newly wired counters are the ONLY
    difference — that is satellite 2's contract stated precisely).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from deepof_tpu.cli import main as cli_main
from deepof_tpu.core.config import (ExperimentConfig, config_from_dict,
                                    get_config)
from deepof_tpu.lint import RULES, Finding, lint_paths, lint_source
from deepof_tpu.obs import registry as obs_registry

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURE_RUN = os.path.join(HERE, "fixtures", "obs_run")
GOLDENS = os.path.join(HERE, "fixtures", "goldens")
#: the frozen `now` the fixture's goldens were recorded against
FIXED_NOW = 1700000123.0


def _findings(src: str, rule: str, path: str = "x.py") -> list[Finding]:
    return [f for f in lint_source(src, path=path, rules=[rule])
            if not f.waived]


# ------------------------------------------------- rule: counter-registry


def test_counter_registry_flags_unregistered_writes():
    src = ('def stats(self):\n'
           '    out = {"serve_requests": 1, "serve_bogus_counter": 2}\n'
           '    out["fleet_novel_thing"] = 3\n'
           '    return out\n')
    found = _findings(src, "counter-registry", "deepof_tpu/serve/x.py")
    assert [("serve_bogus_counter" in f.message, f.line) for f in found
            if "bogus" in f.message] == [(True, 2)]
    assert any("fleet_novel_thing" in f.message and f.line == 3
               for f in found)
    assert len(found) == 2  # the registered key is NOT flagged


def test_counter_registry_negative_registered_and_dynamic_keys():
    src = ('def stats(self):\n'
           '    return {"serve_responses": 1,\n'
           '            "fault_decode": 2,\n'  # prefix family
           '            f"data_{k}": 3,\n'     # dynamic: not checkable
           '            "unprefixed": 4}\n')
    assert _findings(src, "counter-registry") == []


def test_counter_registry_reads_are_not_flagged():
    src = 'x = stats.get("serve_totally_unknown", 0)\n'
    assert _findings(src, "counter-registry") == []


# ------------------------------------------------------ rule: config-key


def test_config_key_flags_typos_along_the_chain():
    src = ('def f(cfg):\n'
           '    return cfg.serve.sesion.ttl_s\n'
           'def g(cfg):\n'
           '    sc = cfg.serve.session\n'
           '    return sc.warm_stat\n')
    found = _findings(src, "config-key")
    assert len(found) == 2
    assert "'sesion'" in found[0].message
    assert "'warm_stat'" in found[1].message


def test_config_key_self_attr_aliases_and_annotations():
    src = ('class E:\n'
           '    def __init__(self, cfg):\n'
           '        self.cfg = cfg\n'
           '        self.fc = cfg.serve.fleet\n'
           '    def h(self):\n'
           '        return self.fc.stall_after_sz\n'
           'def v(obs_cfg):\n'
           '    return obs_cfg.slo_latency_msz\n'
           'def w(c: "ExperimentConfig"):\n'
           '    return c.trainz\n')
    found = _findings(src, "config-key")
    assert ["stall_after_sz" in f.message for f in found].count(True) == 1
    assert any("slo_latency_msz" in f.message for f in found)
    assert any("trainz" in f.message for f in found)


def test_config_key_negative_valid_chains_and_methods():
    src = ('def f(cfg):\n'
           '    x = cfg.serve.session.ttl_s\n'
           '    y = cfg.replace(model="flownet_s")\n'
           '    z = cfg.train.log_dir.upper()\n'  # attr on a leaf: fine
           '    unknown_thing.some.attr\n'        # untyped root: fine
           '    return x, y, z\n')
    assert _findings(src, "config-key") == []


# ----------------------------------------------------- rule: determinism


def test_determinism_flags_unseeded_sources_in_scope():
    src = ('import time, random\n'
           'import numpy as np\n'
           'def sample():\n'
           '    a = time.time()\n'
           '    b = np.random.rand(3)\n'
           '    c = random.random()\n')
    found = _findings(src, "determinism", "deepof_tpu/data/x.py")
    assert len(found) == 3
    # out of scope (obs/): the same source is clean
    assert _findings(src, "determinism", "deepof_tpu/obs/x.py") == []


def test_determinism_scope_anchors_on_the_package_segment():
    """Scope fragments match from the deepof_tpu/ segment on, never the
    checkout prefix: a repo cloned under /data/... must not put every
    file in determinism scope, and files outside the package are never
    in scope."""
    src = "import time\nt = time.time()\n"
    # a checkout under /data: obs/ stays OUT of scope...
    assert _findings(src, "determinism",
                     "/data/ml/repo/deepof_tpu/obs/heartbeat.py") == []
    # ...and the package's own data/ subtree stays IN scope
    assert len(_findings(
        src, "determinism",
        "/data/ml/repo/deepof_tpu/data/pipeline.py")) == 1
    # non-package files (tools/, scratch) are out of scope entirely
    assert _findings(src, "determinism", "/data/tools/bench.py") == []


def test_determinism_negative_seeded_and_monotonic():
    src = ('import time\n'
           'import numpy as np\n'
           'def sample(seed):\n'
           '    rng = np.random.RandomState(seed)\n'
           '    t0 = time.perf_counter()\n'
           '    t1 = time.monotonic()\n'
           '    return rng.rand(3)\n')
    assert _findings(src, "determinism", "deepof_tpu/data/x.py") == []
    # unseeded constructor IS flagged
    bad = 'import numpy as np\nr = np.random.RandomState()\n'
    assert len(_findings(bad, "determinism", "deepof_tpu/data/x.py")) == 1


# ------------------------------------------------------ rule: jit-purity


def test_jit_purity_flags_print_open_and_global_mutation():
    src = ('import jax\n'
           'G = 0\n'
           'def step(x):\n'
           '    print("tracing")\n'
           '    f = open("/tmp/x")\n'
           '    return x\n'
           'jitted = jax.jit(step)\n'
           'def bad(c, x):\n'
           '    global G\n'
           '    G = G + 1\n'
           '    return c, x\n'
           'ys = jax.lax.scan(bad, 0, None)\n')
    found = _findings(src, "jit-purity")
    whats = sorted(f.message for f in found)
    assert len(found) == 3
    assert any("calls print()" in w for w in whats)
    assert any("opens a file" in w for w in whats)
    assert any("mutates module global 'G'" in w for w in whats)


def test_jit_purity_covers_decorator_forms():
    """The repo's dominant jit idiom is the decorator (`@jax.jit`,
    `@functools.partial(jax.jit, static_argnames=...)`) — the rule
    must catch effects there, not only in the call form."""
    src = ('import functools\n'
           'import jax\n'
           '@jax.jit\n'
           'def a(x):\n'
           '    print("gone after trace")\n'
           '    return x\n'
           '@functools.partial(jax.jit, static_argnames=("n",))\n'
           'def b(x, n):\n'
           '    f = open("/tmp/x")\n'
           '    return x\n'
           '@jax.jit\n'
           'def pure(x):\n'
           '    return x + 1\n')
    found = _findings(src, "jit-purity")
    assert len(found) == 2
    assert any("'a'" in f.message and "print" in f.message for f in found)
    assert any("'b'" in f.message and "opens a file" in f.message
               for f in found)


def test_jit_purity_negative_pure_fn_and_untraced_effects():
    src = ('import jax\n'
           'def clean(x):\n'
           '    return x * 2\n'
           'c = jax.jit(clean)\n'
           'def helper():\n'
           '    print("not traced")\n'  # never passed to jit: fine
           'helper()\n')
    assert _findings(src, "jit-purity") == []


# -------------------------------------------------- rule: lock-discipline


_LOCK_SRC = ('import threading\n'
             'class W:\n'
             '    def __init__(self):\n'
             '        self._lock = threading.Lock()\n'
             '        self._n = 0\n'
             '        t = threading.Thread(target=self._run)\n'
             '    def _run(self):\n'
             '        with self._lock:\n'
             '            self._n += 1\n'
             '    def reset(self):\n'
             '        self._n = 0\n')


def test_lock_discipline_flags_unlocked_multi_method_write():
    found = _findings(_LOCK_SRC, "lock-discipline")
    assert len(found) == 1
    assert "W.reset writes self._n outside the class lock" in \
        found[0].message
    assert found[0].line == 11


def test_lock_discipline_negative_all_locked_or_single_method():
    src = _LOCK_SRC.replace(
        '    def reset(self):\n        self._n = 0\n',
        '    def reset(self):\n        with self._lock:\n'
        '            self._n = 0\n')
    assert _findings(src, "lock-discipline") == []
    # a class with no thread spawn is out of scope entirely
    src2 = _LOCK_SRC.replace(
        '        t = threading.Thread(target=self._run)\n', '')
    assert _findings(src2, "lock-discipline") == []


# ------------------------------------------------------------- waivers


def test_waiver_with_reason_suppresses_and_is_reported():
    src = ('def s(self):\n'
           '    return {"serve_bogus": 1}'
           '  # lint: counter-registry-ok(fixture key)\n')
    all_f = lint_source(src, rules=["counter-registry"])
    assert len(all_f) == 1 and all_f[0].waived
    assert all_f[0].waive_reason == "fixture key"


def test_waiver_without_reason_does_not_suppress():
    src = ('def s(self):\n'
           '    return {"serve_bogus": 1}  # lint: counter-registry-ok()\n')
    all_f = lint_source(src, rules=["counter-registry"])
    assert len(all_f) == 1 and not all_f[0].waived


def test_waiver_standalone_comment_covers_next_line():
    src = ('def s(self):\n'
           '    # lint: counter-registry-ok(fixture key, long line)\n'
           '    return {"serve_bogus": 1}\n')
    all_f = lint_source(src, rules=["counter-registry"])
    assert len(all_f) == 1 and all_f[0].waived


def test_waiver_inside_a_string_literal_does_not_suppress():
    """Only REAL comment tokens waive: a string literal that happens to
    contain the waiver syntax (docs, fixtures) must not silently
    suppress findings on its line."""
    src = ('d = {"serve_bogus_key":\n'
           '     ("# lint: counter-registry-ok(oops)", 1)}\n')
    all_f = lint_source(src, rules=["counter-registry"])
    assert len(all_f) == 1 and not all_f[0].waived


def test_waiver_reason_may_contain_parens():
    src = ('def s(self):\n'
           '    return {"serve_bogus": 1}'
           '  # lint: counter-registry-ok(fixture key (see DESIGN.md))\n')
    all_f = lint_source(src, rules=["counter-registry"])
    assert len(all_f) == 1 and all_f[0].waived
    assert all_f[0].waive_reason == "fixture key (see DESIGN.md)"


def test_unknown_rule_raises():
    with pytest.raises(ValueError, match="no-such-rule"):
        lint_source("x = 1", rules=["no-such-rule"])


def test_unknown_rule_fails_even_over_an_empty_path_set(tmp_path):
    """A typo'd --rule over a path set with zero .py files must still
    be a loud usage error (rc 1), never an rc-0 'clean' — the CI-job-
    passes-forever failure mode."""
    with pytest.raises(ValueError, match="no-such-rule"):
        lint_paths([str(tmp_path)], rules=["no-such-rule"])
    assert cli_main(["lint", "--rule", "no-such-rule",
                     str(tmp_path)]) == 1


def test_syntax_error_is_a_finding_not_a_crash():
    found = lint_source("def broken(:\n")
    assert len(found) == 1 and found[0].rule == "parse"


# ------------------------------------------------------- CLI rc contract


def test_cli_rc_contract(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text('d = {"serve_not_a_real_key": 1}\n')

    assert cli_main(["lint", str(clean)]) == 0
    assert cli_main(["lint", str(dirty)]) == 2
    out = json.loads(capsys.readouterr().out.splitlines()[-1]) \
        if cli_main(["lint", "--json", str(dirty)]) == 2 else None
    assert out is not None and len(out["findings"]) == 1
    assert out["findings"][0]["rule"] == "counter-registry"
    # usage errors are rc 1, distinct from findings
    assert cli_main(["lint", "--rule", "nope", str(clean)]) == 1
    assert cli_main(["lint", str(tmp_path / "missing.py")]) == 1


def test_cli_lint_runs_jax_free():
    """The linter's import chain must never pull jax (the CI gate runs
    on accelerator-free hosts; analyzing a tree must not initialize a
    backend a live trainer holds). ALL rules run — config-key's
    deferred schema imports (core.config, resilience.faults) are
    exactly the chain that must stay jax-free. Subprocess: this suite
    has jax loaded already."""
    code = ("import sys\n"
            "from deepof_tpu.cli import main\n"
            f"rc = main(['lint', {os.path.join(REPO, 'deepof_tpu', 'obs')!r}])\n"
            "bad = [m for m in sys.modules"
            " if m == 'jax' or m.startswith('jax.') or m == 'jaxlib']\n"
            "assert rc == 0, rc\n"
            "assert not bad, bad\n")
    subprocess.run([sys.executable, "-c", code], check=True, cwd=REPO,
                   timeout=120)


# ---------------------------------------------------- THE tier-1 gate


def test_tier1_gate_zero_findings_over_package_and_tools():
    """The shipped tree lints clean (every real finding fixed or waived
    with a reason) in < 30 s — the acceptance criterion that turns the
    five invariants from reviewer vigilance into CI."""
    t0 = time.perf_counter()
    findings = lint_paths([os.path.join(REPO, "deepof_tpu"),
                           os.path.join(REPO, "tools")])
    elapsed = time.perf_counter() - t0
    live = [f for f in findings if not f.waived]
    assert live == [], "\n".join(f.format() for f in live)
    # every waiver carries a reason (core.py refuses reasonless ones,
    # but pin the shipped tree's waivers are audited)
    for f in findings:
        assert f.waive_reason, f.format()
    assert elapsed < 30.0, f"lint took {elapsed:.1f}s (gate: 30s)"


# ------------------------------------- registry schema + merge semantics


def test_registry_lookup_exact_and_prefix_families():
    assert obs_registry.lookup("serve_requests").kind == "sum"
    assert obs_registry.lookup("serve_latency_hist").kind == "hist"
    assert obs_registry.lookup("serve_sessions_warm_start").kind == "bool"
    assert obs_registry.lookup("fleet_routed").kind == "map"
    assert obs_registry.lookup("elastic_max_step").kind == "max"
    # prefix families: dynamically named per-site fault counters
    assert obs_registry.lookup("fault_decode").kind == "sum"
    assert obs_registry.lookup("fault_ckpt_corrupt").owner == "faults"
    assert obs_registry.lookup("serve_never_heard_of_it") is None
    assert obs_registry.merge_kind("nope") is None


def test_registry_resilience_keys_match_legacy_tuple():
    """The pre-registry _RESILIENCE_KEYS tuple, byte for byte — the
    analyze/tail resilience block's key ORDER is part of the pinned
    output."""
    assert obs_registry.resilience_keys() == (
        "skipped_updates", "rollbacks",
        "data_sample_retries", "data_quarantined", "data_substituted",
        "data_retries", "pipeline_fetch_retries",
        "ckpt_save_failures", "ckpt_restore_failures",
        "ckpt_restore_fallbacks", "ckpt_verify_failures")


def test_merge_stats_blocks_kinds():
    from deepof_tpu.obs.export import LatencyHistogram

    h1, h2 = LatencyHistogram(), LatencyHistogram()
    h1.observe(0.004)
    h2.observe(0.004)
    blocks = [
        {"serve_requests": 3, "serve_max_queue_depth": 5,
         "serve_requests_by_tier": {"f32": 2, "bf16": 1},
         "serve_sessions_warm_start": True, "serve_max_batch": 8,
         "serve_latency_p50_ms": 3.0,
         "serve_latency_hist": h1.snapshot()},
        {"serve_requests": 4, "serve_max_queue_depth": 2,
         "serve_requests_by_tier": {"f32": 1},
         "serve_sessions_warm_start": True, "serve_max_batch": 8,
         "serve_latency_p50_ms": 9.0,
         "serve_latency_hist": h2.snapshot()},
    ]
    out = obs_registry.merge_stats_blocks(blocks)
    assert out["serve_requests"] == 7                      # sum
    assert out["serve_max_queue_depth"] == 5               # max
    assert out["serve_requests_by_tier"] == {"f32": 3, "bf16": 1}  # map
    assert "serve_sessions_warm_start" not in out          # bool dropped
    assert "serve_max_batch" not in out                    # gauge dropped
    assert "serve_latency_p50_ms" not in out               # derived dropped
    assert out["serve_latency_hist"]["count"] == 2         # exact merge
    # unregistered keys fall back to the legacy suffix heuristic
    out2 = obs_registry.merge_stats_blocks(
        [{"serve_new_counter": 1, "serve_new_rate_per_s": 5.0},
         {"serve_new_counter": 2, "serve_new_rate_per_s": 7.0}])
    assert out2["serve_new_counter"] == 3
    assert "serve_new_rate_per_s" not in out2
    # an unregistered state-style dict (no numeric sub-values) is
    # dropped, never exported as a meaningless empty {}
    out3 = obs_registry.merge_stats_blocks(
        [{"serve_new_states": {"r0": "ready"}}])
    assert "serve_new_states" not in out3


# --------------- the ONE registry-driven config-typo test (satellite 1)
#
# Replaces the four per-PR hand-written rejection tests (test_fleet /
# test_elastic / test_session / test_warm) with a parametrized walk of
# the WHOLE config tree: at every dataclass node, an unknown key must
# be rejected loudly, naming the bogus field. The four original
# hand-written assertions ride along below as parity pins.


def _config_tree_paths():
    """Every dataclass node in the config tree as a dotted path
    ("" = root), discovered from the real dataclasses — a new nested
    config block joins this test with no edit."""
    paths = []

    def walk(cls, prefix):
        paths.append(prefix)
        import typing

        hints = typing.get_type_hints(cls)
        for f in dataclasses.fields(cls):
            hint = hints.get(f.name)
            if isinstance(hint, type) and dataclasses.is_dataclass(hint):
                walk(hint, f"{prefix}.{f.name}" if prefix else f.name)

    walk(ExperimentConfig, "")
    return paths


@pytest.mark.parametrize("path", _config_tree_paths())
def test_config_from_dict_rejects_unknown_key_at_every_node(path):
    d: dict = {}
    node = d
    for part in path.split(".") if path else []:
        node = node.setdefault(part, {})
    node["definitely_not_a_field"] = 1
    with pytest.raises(ValueError, match="definitely_not_a_field"):
        config_from_dict(d)
    # control: the same node WITHOUT the bogus key loads fine
    if path:
        node.clear()
        config_from_dict(d)


def test_config_typo_parity_pins():
    """The four original hand-written assertions, verbatim (the swap's
    parity pins): fleet (PR 6), elastic (PR 8), session (PR 10), warm
    (PR 11)."""
    with pytest.raises(ValueError):
        config_from_dict({"not_a_field": 1})
    with pytest.raises(ValueError, match="serve"):
        config_from_dict({"serve": {"fake_exec_sm": 5.0}})
    with pytest.raises(ValueError, match="hostz"):
        bad = dataclasses.asdict(ExperimentConfig())
        bad["elastic"]["hostz"] = 3
        config_from_dict(bad)
    with pytest.raises(ValueError, match="session"):
        config_from_dict({"serve": {"session": {"ttl_sec": 5.0}}})
    with pytest.raises(ValueError, match="session"):
        config_from_dict({"serve": {"session": {"warm_stat": True}}})
    with pytest.raises(ValueError, match="serve"):
        config_from_dict({"serve": {"session_warm_start": True}})
    with pytest.raises(ValueError, match="warm_start"):
        config_from_dict({"warm_start": True})


# -------------------- registry-driven merge pins on the fixture run dir
#
# tests/fixtures/obs_run is a frozen 2-replica fleet drill
# (make_obs_fixture.py). The goldens were recorded in two stages:
# *_pre.json with the PRE-refactor code (hand-kept merge lists),
# *_post.json with the registry-driven code. The pins state satellite
# 2's contract precisely: summarize and the fleet scrape are
# byte-identical pre -> post; aggregate/tail gain EXACTLY the
# previously-missing counters, with every pre-refactor key's value
# unchanged — and are now pinned byte-identical against the recorded
# post goldens so future drift fails loudly.


def _golden(name: str):
    with open(os.path.join(GOLDENS, name)) as f:
        return json.load(f)


def test_summarize_byte_identical_to_pre_refactor():
    from deepof_tpu.analyze import load_records, summarize

    got = summarize(load_records(FIXTURE_RUN))
    assert json.dumps(got) == json.dumps(_golden("summarize_pre.json"))


def test_aggregate_and_tail_pinned_and_superset_of_pre_refactor():
    from deepof_tpu.analyze import aggregate_processes, tail_summary

    agg = aggregate_processes(FIXTURE_RUN, now=FIXED_NOW)
    assert json.dumps(agg) == json.dumps(_golden("aggregate_post.json"))

    tail = tail_summary(FIXTURE_RUN, now=FIXED_NOW, fleet=True)
    golden_tail = _golden("tail_post.json")
    golden_tail["log_dir"] = FIXTURE_RUN  # recorded relative to repo
    tail["log_dir"] = FIXTURE_RUN
    assert json.dumps(tail) == json.dumps(golden_tail)

    # parity: every PRE-refactor key survives with its exact value (the
    # new counters are additions, never changes)
    def assert_superset(new, old, where=""):
        for k, v in old.items():
            assert k in new, f"{where}{k} lost in refactor"
            if isinstance(v, dict):
                assert_superset(new[k], v, f"{where}{k}.")
            else:
                assert new[k] == v, f"{where}{k}: {new[k]!r} != {v!r}"

    assert_superset(agg, _golden("aggregate_pre.json"))
    pre_tail = _golden("tail_pre.json")
    pre_tail.pop("log_dir")
    assert_superset(tail, pre_tail)
    # and the wiring actually happened: the counters the hand-kept list
    # missed are IN the merged block now
    for key in ("server_errors", "dispatch_failures", "timeout_flushes",
                "requests_by_tier", "max_queue_depth",
                "sessions_resumed", "sessions_expired"):
        assert key in agg["merged"], key
    assert "sessions_warm_start" not in agg["merged"]  # bool: dropped


def test_scrape_replicas_byte_identical_to_pre_refactor():
    """The registry-driven scrape merge reproduces the retired
    skip/max-frozenset + suffix-heuristic implementation EXACTLY, over
    live stub replicas serving the recorded /healthz payloads."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from deepof_tpu.serve.router import Router

    def stub(payload):
        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps(payload).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
        s = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=s.serve_forever, daemon=True).start()
        return s

    class _Replica:
        def __init__(self, idx, port):
            self.idx, self.port = idx, port

    class _StubFleet:
        host = "127.0.0.1"

        def __init__(self, ports):
            self.ports, self.size = ports, len(ports)

        def ready_replicas(self):
            return [_Replica(i, p) for i, p in enumerate(self.ports)]

    payloads = [json.load(open(os.path.join(
        FIXTURE_RUN, f"healthz-replica-{i}.json"))) for i in range(2)]
    servers = [stub(p) for p in payloads]
    try:
        router = Router(get_config("flyingchairs"),
                        _StubFleet([s.server_address[1] for s in servers]))
        got = router.scrape_replicas()
    finally:
        for s in servers:
            s.shutdown()
            s.server_close()
    assert json.dumps(got) == json.dumps(_golden("scrape_pre.json"))
