"""Training-stack tests on the virtual 8-device CPU mesh: mesh construction,
LR schedule, sharded train step (flow / volume / two-stream), checkpoint
save-restore, and an end-to-end Trainer.fit on the synthetic dataset."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepof_tpu.core.config import (
    DataConfig,
    ExperimentConfig,
    LossConfig,
    MeshConfig,
    OptimConfig,
    TrainConfig,
)
from deepof_tpu.data import SyntheticData, build_dataset
from deepof_tpu.models.registry import build_model
from deepof_tpu.parallel.mesh import batch_sharding, build_mesh
from deepof_tpu.train import (
    CheckpointManager,
    Trainer,
    create_train_state,
    evaluate_aee,
    make_eval_fn,
    make_train_step,
    step_decay_schedule,
)
from deepof_tpu.train.state import make_optimizer
pytestmark = pytest.mark.slow  # full-model/train-step compiles; see pytest.ini

H, W = 64, 64


def _cfg(tmp_path, **data_kw) -> ExperimentConfig:
    data = dict(dataset="synthetic", image_size=(H, W), gt_size=(H, W),
                batch_size=8)
    data.update(data_kw)
    return ExperimentConfig(
        name="test",
        model="flownet_s",
        # thin trunk: these tests assert wiring/equivalence semantics that
        # are width-independent; full-width flownet_s costs ~30s/step of
        # pure compute on the single-core CPU mesh (VERDICT r03 item 8)
        width_mult=0.25,
        loss=LossConfig(weights=(16, 8, 4, 2, 1, 1)),
        optim=OptimConfig(learning_rate=1e-4, epochs_per_decay=2),
        data=DataConfig(**data),
        train=TrainConfig(num_epochs=1, log_every=1, eval_every=0,
                          ckpt_every_epochs=1, log_dir=str(tmp_path),
                          eval_amplifier=1.0, eval_clip=(-1e4, 1e4),
                          eval_batch_size=8, seed=0),
    )


def test_build_mesh_axes():
    mesh = build_mesh(MeshConfig())
    assert mesh.axis_names == ("data", "spatial", "time")
    assert mesh.devices.size == jax.device_count()
    mesh2 = build_mesh(MeshConfig(spatial=2))
    assert mesh2.shape["spatial"] == 2
    assert mesh2.shape["data"] == jax.device_count() // 2
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(spatial=3))  # 8 % 3 != 0


def test_step_decay_schedule():
    sched = step_decay_schedule(
        OptimConfig(learning_rate=1.0, decay_factor=0.5, epochs_per_decay=2),
        steps_per_epoch=10)
    assert sched(0) == 1.0
    assert sched(19) == 1.0  # epoch 1
    assert sched(20) == 0.5  # epoch 2
    assert sched(40) == 0.25


@pytest.fixture(scope="module")
def flow_setup(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("flow")
    cfg = _cfg(tmp)
    mesh = build_mesh(cfg.mesh)
    trainer = Trainer(cfg, profile=False)
    return cfg, mesh, trainer


def test_train_step_decreases_loss(flow_setup):
    cfg, mesh, trainer = flow_setup
    ds = trainer.dataset
    batch = jax.device_put(ds.sample_train(8, iteration=0), batch_sharding(mesh))
    state = trainer.state
    first = None
    for _ in range(5):
        state, metrics = trainer.train_step(state, batch)
        total = float(metrics["total"])
        assert np.isfinite(total)
        if first is None:
            first = total
    assert total < first  # same batch, loss must go down
    assert metrics["scale_total"].shape == (6,)
    trainer.state = state


def test_eval_protocol_and_fit(flow_setup, tmp_path):
    cfg, mesh, trainer = flow_setup
    res = trainer.evaluate()
    assert {"aee", "aae", "val_loss"} <= set(res)
    assert np.isfinite(res["aee"])
    out = trainer.fit(num_epochs=1, max_steps=2)
    assert "steps_per_sec" in out
    # checkpoint written and resumable
    assert trainer.ckpt.latest_step() is not None
    restored = trainer.ckpt.restore(trainer.state)
    assert int(restored.step) == int(trainer.state.step)


def test_checkpoint_roundtrip(tmp_path):
    model = build_model("flownet_s", width_mult=0.25)
    tx = make_optimizer(OptimConfig(), lambda s: 1e-4)
    state = create_train_state(model, jnp.zeros((1, H, W, 6)), tx, seed=1)
    state = state.replace(step=state.step + 7)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    mgr.save(state)
    template = create_train_state(model, jnp.zeros((1, H, W, 6)), tx, seed=2)
    restored = mgr.restore(template)
    assert int(restored.step) == 7
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b),
        state.params, restored.params)
    # keep=2 pruning
    for d in (8, 9, 10):
        mgr.save(state.replace(step=jnp.asarray(d, jnp.int32)))
    assert mgr.all_steps() == [9, 10]


def test_remat_train_step_matches(tmp_path):
    """jax.checkpoint'ed forward must give the same loss/grads (it only
    changes what is stored vs recomputed)."""
    import dataclasses

    cfg = _cfg(tmp_path)
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data)
    model = build_model("flownet_s", width_mult=0.25)
    tx = make_optimizer(cfg.optim, lambda s: 1e-4)
    batch = jax.device_put(ds.sample_train(8, iteration=0), batch_sharding(mesh))
    results = {}
    for remat in (False, True):
        c = cfg.replace(train=dataclasses.replace(cfg.train, remat=remat))
        state = create_train_state(model, jnp.zeros((8, H, W, 6)), tx, seed=0)
        step = make_train_step(model, c, ds.mean, mesh)
        _, metrics = step(state, batch)
        results[remat] = (float(metrics["total"]), float(metrics["grad_norm"]))
    assert np.isclose(results[False][0], results[True][0], rtol=1e-6)
    assert np.isclose(results[False][1], results[True][1], rtol=1e-5)


def test_steps_per_call_matches_single(tmp_path):
    """K scanned steps in one call == K single-step calls (same batches)."""
    import dataclasses

    cfg = _cfg(tmp_path)
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data)
    model = build_model("flownet_s", width_mult=0.25)
    tx = make_optimizer(cfg.optim, lambda s: 1e-4)
    b0 = ds.sample_train(8, iteration=0)
    b1 = ds.sample_train(8, iteration=1)

    state = create_train_state(model, jnp.zeros((8, H, W, 6)), tx, seed=0)
    step1 = make_train_step(model, cfg, ds.mean, mesh)
    for b in (b0, b1):
        state, m = step1(state, jax.device_put(b, batch_sharding(mesh)))
    single_params = jax.device_get(state.params)
    single_total = float(m["total"])

    from deepof_tpu.parallel.mesh import stacked_batch_sharding

    c2 = cfg.replace(train=dataclasses.replace(cfg.train, steps_per_call=2))
    state2 = create_train_state(model, jnp.zeros((8, H, W, 6)), tx, seed=0)
    step2 = make_train_step(model, c2, ds.mean, mesh)
    stacked = {k: np.stack([b0[k], b1[k]]) for k in b0}
    state2, m2 = step2(state2, jax.device_put(stacked,
                                              stacked_batch_sharding(mesh)))
    assert m2["total"].shape == (2,)
    assert int(state2.step) == 2
    np.testing.assert_allclose(float(m2["total"][-1]), single_total, rtol=1e-5)
    # scanned vs unrolled compiles reassociate float math, and the warp's
    # floor/clip indexing turns a rounding flip at an integer flow
    # boundary into a DISCRETE per-pixel gradient jump, which Adam's
    # 1/(sqrt(v)+eps) then amplifies at isolated near-zero-v elements
    # (seen: 1 of 36864 elements at 2.4e-3 relative after two steps).
    # The bound absorbs those isolated discontinuities; a wiring bug
    # (wrong batch order, missed optimizer update) is an O(1) error and
    # still fails loudly.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, jax.device_get(b),
                                                rtol=1e-2, atol=3e-4),
        single_params, state2.params)


def test_occlusion_rejected_for_unsupported_models(tmp_path):
    """loss.occlusion only masks flow-only 2-frame models; anything else
    must fail at step-build time, not silently skip."""
    import dataclasses

    cfg = _cfg(tmp_path).replace(model="st_single")
    cfg = cfg.replace(loss=dataclasses.replace(cfg.loss, occlusion=True))
    mesh = build_mesh(cfg.mesh)
    model = build_model("st_single")
    with pytest.raises(ValueError, match="occlusion"):
        make_train_step(model, cfg, (0.0, 0.0, 0.0), mesh)


def test_grad_accum_matches_large_batch(tmp_path):
    """Two accumulated micro-batches == one optimizer step on the
    concatenated batch (losses are batch means, so gradients average)."""
    import dataclasses

    cfg = _cfg(tmp_path)
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data)
    model = build_model("flownet_s", width_mult=0.25)
    b0 = ds.sample_train(8, iteration=0)
    b1 = ds.sample_train(8, iteration=1)

    # accumulation: 2 micro-steps of 8
    acfg = cfg.replace(optim=dataclasses.replace(cfg.optim, grad_accum=2))
    tx_a = make_optimizer(acfg.optim, lambda s: 1e-4)
    state_a = create_train_state(model, jnp.zeros((8, H, W, 6)), tx_a, seed=0)
    init_params = jax.device_get(state_a.params)
    step_a = make_train_step(model, acfg, ds.mean, mesh)
    state_a, _ = step_a(state_a, jax.device_put(b0, batch_sharding(mesh)))
    mid = jax.device_get(state_a.params)
    jax.tree_util.tree_map(np.testing.assert_array_equal, init_params, mid)
    state_a, _ = step_a(state_a, jax.device_put(b1, batch_sharding(mesh)))

    # ... and the deferred update did land after the 2nd micro-step
    moved = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(a)
                                  - np.asarray(jax.device_get(b))).max()),
        init_params, state_a.params))
    assert max(moved) > 0

    # Exact averaging equivalence needs a gradient-linear optimizer (Adam
    # normalizes, so any two runs differ by <= 2*lr and the comparison
    # proves nothing): SGD accum of 2x8 == SGD on the concatenated 16.
    import optax

    sgd_a = optax.MultiSteps(optax.sgd(1e-2), every_k_schedule=2)
    state_sa = create_train_state(model, jnp.zeros((8, H, W, 6)), sgd_a, seed=0)
    step_sa = make_train_step(model, acfg, ds.mean, mesh)
    for b in (b0, b1):
        state_sa, _ = step_sa(state_sa, jax.device_put(b, batch_sharding(mesh)))

    big = {k: np.concatenate([b0[k], b1[k]]) for k in b0}
    bcfg = cfg.replace(data=dataclasses.replace(cfg.data, batch_size=16))
    state_sb = create_train_state(model, jnp.zeros((16, H, W, 6)),
                                  optax.sgd(1e-2), seed=0)
    step_sb = make_train_step(model, bcfg, ds.mean, mesh)
    state_sb, _ = step_sb(state_sb, jax.device_put(big, batch_sharding(mesh)))

    # Tolerance note: the b=8-accum and b=16 runs are DIFFERENT XLA
    # programs whose f32 forward rounding differs, and the warp's
    # floor/clip indexing turns a rounding flip at an integer flow
    # boundary into a DISCRETE gradient jump at that pixel — observed as
    # isolated ~1e-2-relative param diffs (one SGD lr=1e-2 step). So the
    # MAX bound absorbs the few discontinuity-amplified elements, while
    # the 99.9th-percentile bound keeps the BULK of parameters tight
    # (ADVICE r04: a blanket 5e-2 rtol would also pass a sub-5%
    # systematic error like an off-by-one in the 1/K averaging; a
    # systematic bug shifts every element and trips the percentile).
    diffs, refs = [], []

    def _collect(a, b):
        diffs.append(np.abs(np.asarray(jax.device_get(a), np.float64)
                            - np.asarray(jax.device_get(b), np.float64)).ravel())
        refs.append(np.abs(np.asarray(jax.device_get(b), np.float64)).ravel())

    jax.tree_util.tree_map(_collect, state_sa.params, state_sb.params)
    d, r = np.concatenate(diffs), np.concatenate(refs)
    # loose envelope (the old allclose bound): holds EVERYWHERE
    loose = d > 5e-4 + 5e-2 * r
    assert not loose.any(), \
        f"{loose.sum()} elements beyond the warp-discontinuity envelope"
    # tight envelope: only the isolated warp-discontinuity pixels may
    # exceed it — a systematic error shifts every element and trips this
    tight_frac = float(np.mean(d > 5e-4 + 1e-3 * r))
    assert tight_frac < 1e-3, f"tight-envelope violations: {tight_frac:.2e}"


def test_ckpt_every_steps(tmp_path):
    """Step-granularity checkpoints: saves land mid-epoch, not just at
    epoch/ckpt_every_epochs boundaries (SURVEY.md §5.3)."""
    import dataclasses

    cfg = _cfg(tmp_path)
    cfg = cfg.replace(train=dataclasses.replace(
        cfg.train, ckpt_every_steps=2, ckpt_every_epochs=10**6,
        nan_guard=False))
    trainer = Trainer(cfg, profile=False)
    trainer.fit(num_epochs=1, max_steps=4)
    assert trainer.ckpt.latest_step() >= 4  # saved at step cadence (+final)


def test_nan_guard_rollback_aborts_after_retries(tmp_path):
    """Persistent divergence must abort (bounded rollbacks), not loop
    forever re-training the same region from the restored checkpoint."""
    cfg = _cfg(tmp_path)
    trainer = Trainer(cfg, profile=False)
    real_step = trainer.train_step

    def nan_step(state, batch):
        state, metrics = real_step(state, batch)
        metrics = dict(metrics)
        metrics["total"] = jnp.float32(np.nan)
        return state, metrics

    trainer.train_step = nan_step
    with pytest.raises(FloatingPointError, match="consecutive"):
        trainer.fit(num_epochs=1, max_steps=50)


def test_final_save_skipped_on_unchecked_nan(tmp_path):
    """Divergence in the trailing (never host-checked) steps must not be
    saved as the newest checkpoint — a poisoned final save would become
    the auto-resume AND rollback target, defeating both."""
    import dataclasses

    cfg = _cfg(tmp_path)
    # log_every larger than the run so no in-loop NaN check ever fires
    cfg = cfg.replace(train=dataclasses.replace(
        cfg.train, log_every=10**6, ckpt_every_epochs=10**6))
    trainer = Trainer(cfg, profile=False)
    real_step = trainer.train_step

    def nan_step(state, batch):
        state, metrics = real_step(state, batch)
        metrics = dict(metrics)
        metrics["total"] = jnp.float32(np.nan)
        return state, metrics

    trainer.train_step = nan_step
    trainer.fit(num_epochs=1, max_steps=3)
    # only the pre-step-1 rollback target exists; the poisoned final state
    # was refused and the in-memory state rolled back to match it
    assert trainer.ckpt.latest_step() == 0
    assert int(trainer.state.step) == 0


def test_trainer_fit_steps_per_call(tmp_path):
    """Trainer end-to-end with K=2: step accounting, logging, checkpointing."""
    import dataclasses

    cfg = _cfg(tmp_path)
    cfg = cfg.replace(train=dataclasses.replace(cfg.train, steps_per_call=2))
    trainer = Trainer(cfg, profile=False)
    out = trainer.fit(num_epochs=1, max_steps=4)
    assert "steps_per_sec" in out
    assert int(trainer.state.step) >= 4
    assert trainer.ckpt.latest_step() is not None


def test_flownet_c_learns_matching_below_zero_flow(tmp_path):
    """The r04 learning-evidence property, pinned: FlowNet-C with the
    task displacement scale matched to its correlation bins (max_shift
    8 px at 64 px = ~1 feature px at the 1/8-res corr grid, stride 1)
    descends WELL below the zero-flow AEE under the default unsupervised
    recipe within a few hundred steps — where FlowNet-S (which must
    discover correspondence from scratch) provably parks at the
    zero-flow level for any in-round budget (DESIGN.md r04; full run:
    artifacts/synthetic_fit_cpu_corr8.jsonl, 0.99 px at step 6500)."""
    import dataclasses

    cfg = _cfg(tmp_path)
    cfg = cfg.replace(
        model="flownet_c",
        train=dataclasses.replace(cfg.train, eval_amplifier=2.0,
                                  eval_clip=(-300.0, 250.0)))
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data, num_train=512, max_shift=8.0,
                       style="blobs", n_blobs=40)
    model = build_model("flownet_c", width_mult=0.25, max_disp=3,
                        corr_stride=1)
    tx = make_optimizer(cfg.optim, lambda s: 3e-4)
    state = create_train_state(model, jnp.zeros((8, H, W, 6)), tx, seed=0)
    step = make_train_step(model, cfg, ds.mean, mesh)
    eval_fn = make_eval_fn(model, cfg, ds.mean, mesh=mesh)

    vflows = np.concatenate([ds.sample_val(8, i)["flow"] for i in range(2)])
    zero_epe = float(np.sqrt((vflows ** 2).sum(-1)).mean())
    rng = np.random.RandomState(0)
    for _ in range(600):
        b = jax.device_put(ds.sample_train(8, rng=rng), batch_sharding(mesh))
        state, _ = step(state, b)
    res = evaluate_aee(eval_fn, state.params, ds, cfg)
    # the full-run curve's knee is between steps 250 and 500 (at batch
    # 16): baseline-level until ~250, 0.55x by 500. 600 steps at batch 8
    # sits past the knee; 0.85x still asserts genuine matching (a
    # zero-flow collapse sits at 1.0x) with slack for the smaller batch
    assert res["aee"] < 0.85 * zero_epe, (res["aee"], zero_epe)


def test_inception_learns_flow_below_zero_flow(tmp_path):
    """The r05 flagship learning-evidence property, pinned: Inception-v3
    flow (the model the reference actually trains,
    `flyingChairsTrain.py:103`) descends WELL below the zero-flow AEE
    under the default unsupervised recipe on the spatially varying
    affine field with a sub-pixel curriculum start — where the
    FlowNet-S trunk provably parks (corr(pred, gt) ~ 0, DESIGN.md
    "Learning evidence, r05"). Thin variant (width 0.25) for CI cost;
    both probe configs locked on by step ~2000 (full runs:
    artifacts/synthetic_fit_cpu_inc_{affine: 0.883 px full width,
    thin: 1.08 px, pin: 1.15 px}.jsonl). Early-exits at the bound, so
    the typical cost is ~the lock-on point, not the cap."""
    import dataclasses

    cfg = _cfg(tmp_path)
    cfg = cfg.replace(
        model="inception_v3", width_mult=1.0,  # model built thin below
        train=dataclasses.replace(cfg.train, eval_amplifier=2.0,
                                  eval_clip=(-300.0, 250.0)))
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data, num_train=8192, max_shift=4.0,
                       style="affine", n_blobs=40, feature_scale=16)
    model = build_model("inception_v3", width_mult=0.25)
    tx = make_optimizer(cfg.optim, lambda s: 5e-4)
    state = create_train_state(model, jnp.zeros((8, H, W, 6)), tx, seed=0)
    step = make_train_step(model, cfg, ds.mean, mesh)
    eval_fn = make_eval_fn(model, cfg, ds.mean, mesh=mesh)

    vflows = np.concatenate([ds.sample_val(8, i)["flow"] for i in range(2)])
    zero_epe = float(np.sqrt((vflows ** 2).sum(-1)).mean())
    bound = 0.9 * zero_epe
    rng = np.random.RandomState(0)
    best = float("inf")
    for s in range(2600):
        shift = min(0.25 + (4.0 - 0.25) * s / 1200.0, 4.0)
        b = jax.device_put(ds.sample_train(8, rng=rng, max_shift=shift),
                           batch_sharding(mesh))
        state, _ = step(state, b)
        # evals only once lock-on is possible; early-exit at the bound
        if s >= 1399 and (s + 1) % 200 == 0:
            best = min(best,
                       evaluate_aee(eval_fn, state.params, ds, cfg)["aee"])
            if best < bound:
                break
    assert best < bound, (best, zero_epe)


def test_volume_train_step(tmp_path):
    cfg = _cfg(tmp_path, time_step=3)
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data)
    model = build_model("flownet_s", flow_channels=4, width_mult=0.25)
    tx = make_optimizer(cfg.optim, lambda s: 1e-4)
    state = create_train_state(model, jnp.zeros((8, H, W, 9)), tx)
    step = make_train_step(model, cfg, ds.mean, mesh)
    batch = jax.device_put(ds.sample_train(8, iteration=0), batch_sharding(mesh))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["total"]))


def test_two_stream_train_step(tmp_path):
    cfg = _cfg(tmp_path).replace(model="st_single")
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data)
    model = build_model("st_single")
    tx = make_optimizer(cfg.optim, lambda s: 1e-4)
    state = create_train_state(model, jnp.zeros((8, H, W, 6)), tx)
    step = make_train_step(model, cfg, ds.mean, mesh, smooth_border_mask=True)
    batch = jax.device_put(ds.sample_train(8, iteration=0), batch_sharding(mesh))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["total"]))
    assert "accuracy" in metrics and "action_loss" in metrics


@pytest.mark.parametrize("model_name,weights,smoothness", [
    ("vgg16", (16, 8, 4, 2, 1), "depthwise"),
    ("inception_v3", (16, 8, 4, 2, 1, 1), "canonical"),
    ("st_baseline", (16, 8, 4, 2, 1, 1), "canonical"),
    ("ucf101_spatial", (16,), "canonical"),
])
def test_every_model_family_trains(tmp_path, model_name, weights, smoothness):
    """One sharded train step per remaining model family (flownet_s/c and
    st_single are covered elsewhere): finite loss, grads flow."""
    cfg = _cfg(tmp_path).replace(
        model=model_name,
        loss=LossConfig(weights=weights, smoothness=smoothness))
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data)
    model = build_model(model_name)
    tx = make_optimizer(cfg.optim, lambda s: 1e-4)
    channels = 3 if model_name == "ucf101_spatial" else 6
    state = create_train_state(model, jnp.zeros((8, H, W, channels)), tx)
    smooth_border = model_name in ("st_single", "st_baseline")
    step = make_train_step(model, cfg, ds.mean, mesh, smooth_border)
    batch = jax.device_put(ds.sample_train(8, iteration=0),
                           batch_sharding(mesh))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["total"]))
    assert float(metrics["grad_norm"]) > 0


def test_transfer_init_chairs_to_sintel_shapes(tmp_path):
    """Cross-config transfer: 2-frame FlowNet-S pretrain -> T=4 volume
    model. Trunk convs graft; first conv (3T in-ch) and pyramid heads
    (2(T-1) out-ch) re-initialize."""
    import dataclasses

    from deepof_tpu.core.config import get_config
    from deepof_tpu.train.loop import Trainer

    src_dir = str(tmp_path / "chairs")
    cfg = get_config("flyingchairs").replace(model="flownet_s")
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=(32, 64), gt_size=(32, 64),
                                 batch_size=4, crop_size=None),
        train=dataclasses.replace(cfg.train, log_dir=src_dir,
                                  eval_batch_size=4, eval_amplifier=1.0))
    src_tr = Trainer(cfg)
    src_tr.ckpt.save(src_tr.state)
    src_params = src_tr.state.params

    tgt_dir = str(tmp_path / "sintel")
    tcfg = cfg.replace(
        data=dataclasses.replace(cfg.data, time_step=4),
        train=dataclasses.replace(cfg.train, log_dir=tgt_dir,
                                  eval_batch_size=4, eval_amplifier=1.0,
                                  init_from=src_dir))
    tgt_tr = Trainer(tcfg)
    tp = tgt_tr.state.params

    # trunk conv2 transferred exactly
    np.testing.assert_array_equal(
        np.asarray(tp["conv2"]["Conv_0"]["kernel"]),
        np.asarray(src_params["conv2"]["Conv_0"]["kernel"]))
    # first conv re-initialized (in-ch 12 vs 6: shapes differ)
    assert tp["conv1"]["Conv_0"]["kernel"].shape[2] == 12
    # pyramid head re-initialized (6 flow channels vs 2)
    assert tp["decoder"]["pr1"]["Conv_0"]["kernel"].shape[-1] == 6


def test_early_sigterm_latch_stops_before_first_step(tmp_path):
    """ADVICE r03: a SIGTERM during the unprotected window (model build /
    first compile, before fit() installs its handler) must still end in a
    clean checkpoint. The CLI installs `install_preemption_latch()` at
    entry; a latched signal makes fit() exit before its first step and
    run the normal finalize path."""
    import os as _os
    import signal as _signal

    from deepof_tpu.train import loop as loop_mod

    prev = _signal.getsignal(_signal.SIGTERM)
    loop_mod.install_preemption_latch()
    try:
        _os.kill(_os.getpid(), _signal.SIGTERM)  # latched, not fatal
        assert loop_mod._EARLY_SIGTERM["sig"] == _signal.SIGTERM
        trainer = Trainer(_cfg(tmp_path), profile=False)
        trainer.fit(num_epochs=1, max_steps=10)
        # no step ran (the latch converted to an immediate stop) and the
        # finalize path still wrote a resumable checkpoint
        assert int(trainer.state.step) == 0
        assert trainer.ckpt.latest_step() is not None
        assert loop_mod._EARLY_SIGTERM["sig"] is None  # consumed
        # post-fit the latch must NOT be re-armed: a SIGTERM after the
        # final checkpoint is committed should kill, not be swallowed
        assert _signal.getsignal(_signal.SIGTERM) == _signal.SIG_DFL
    finally:
        _signal.signal(_signal.SIGTERM, prev)
        loop_mod._EARLY_SIGTERM["sig"] = None


@pytest.mark.slow
def test_sigterm_graceful_checkpoint(tmp_path):
    """Preemption handling (SURVEY.md §5.3): SIGTERM mid-training ends the
    step loop cleanly — final NaN-checked checkpoint saved, exit 0, and
    the run is auto-resumable. Driven end-to-end through the CLI in a
    subprocess (signal handlers only work in a main thread)."""
    import os
    import signal as _signal
    import subprocess
    import sys
    import time as _time

    logdir = tmp_path / "run"
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.Popen(
        [sys.executable, "-m", "deepof_tpu.cli", "train",
         "--preset", "flyingchairs", "--synthetic", "--steps", "5000",
         "--model", "flownet_s", "--set", "train.log_every=2",
         "--set", "width_mult=0.25",
         "--log-dir", str(logdir)],
        cwd=repo, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    try:
        # wait for the IN-LOOP "first step" record: it is logged after
        # fit() installs the SIGTERM handler (the construction-time
        # "model parameters" info line is too early — a signal sent then
        # still hits the default handler and kills the process)
        mlog = logdir / "metrics.jsonl"
        deadline = _time.time() + 300
        while _time.time() < deadline:
            if mlog.exists() and "first step" in mlog.read_text():
                break
            _time.sleep(2)
        else:
            raise AssertionError("training never reached its first step")
        p.send_signal(_signal.SIGTERM)
        rc = p.wait(timeout=240)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == 0, rc
    text = mlog.read_text()
    assert "signal 15 received" in text
    # a checkpoint was committed and the run is resumable
    from deepof_tpu.train.checkpoint import CheckpointManager as _CM
    assert _CM(str(logdir / "ckpt")).latest_step() is not None


def test_data_stream_rng_resume_no_replay():
    """Resume must NOT replay the data stream from the beginning (the
    numpy data rng is not checkpointed): distinct start steps give
    distinct streams; equal inputs are deterministic; the replica
    contract (same mesh/seed/step => identical stream) holds."""
    from deepof_tpu.train.loop import data_stream_rng

    mesh = build_mesh(MeshConfig())
    a = data_stream_rng(mesh, 7, 0).randint(0, 2**31, 8)
    a2 = data_stream_rng(mesh, 7, 0).randint(0, 2**31, 8)
    b = data_stream_rng(mesh, 7, 1000).randint(0, 2**31, 8)
    c = data_stream_rng(mesh, 8, 0).randint(0, 2**31, 8)
    np.testing.assert_array_equal(a, a2)
    assert not np.array_equal(a, b)
    assert not np.array_equal(a, c)
