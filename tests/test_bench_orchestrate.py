"""Orchestrator logic of bench.py: relay, retry, exhaustion.

The measurement itself is TPU-gated; these tests pin the tunnel-
resilience control flow (VERDICT r02 item 1) with stubbed probes,
children, and clock — no backend touched.
"""

from __future__ import annotations

import json
import types

import pytest

import bench


class _Clock:
    """Deterministic stand-in for bench.time (orchestrate calls
    time/sleep/strftime/gmtime; the last-good age bound also calls
    mktime/strptime — those delegate to the real module so wall-clock
    timestamps written by the tests compare sanely against self.t,
    which starts at the real current time)."""

    def __init__(self):
        import time as _real_time

        self._real = _real_time
        self.t0 = _real_time.time()
        self.t = self.t0

    def elapsed(self):
        return self.t - self.t0

    def time(self):
        return self.t

    def sleep(self, s):
        self.t += s

    def strftime(self, fmt, tm=None):
        return "T"

    def gmtime(self):
        return None

    def mktime(self, tm):
        return self._real.mktime(tm)

    def strptime(self, s, fmt):
        return self._real.strptime(s, fmt)


def _wire(monkeypatch, tmp_path, alive, run):
    clock = _Clock()
    monkeypatch.setattr(bench, "time", clock)
    monkeypatch.setattr(bench, "PROBE_LOG", str(tmp_path / "probes.log"))
    # isolate from any real artifacts/last_good_bench.json on this tree
    monkeypatch.setattr(bench, "LAST_GOOD", str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "_tunnel_alive",
                        lambda timeout_s=120.0: clock.sleep(5) or alive())
    monkeypatch.setattr(
        bench, "subprocess",
        types.SimpleNamespace(run=run,
                              TimeoutExpired=bench.subprocess.TimeoutExpired))
    monkeypatch.setattr(bench, "_exit",
                        lambda code: (_ for _ in ()).throw(SystemExit(code)))
    return clock


def _json_lines(out):
    return [json.loads(ln) for ln in out.splitlines() if ln.startswith("{")]


def test_relays_child_success_line_verbatim(monkeypatch, capsys, tmp_path):
    good = json.dumps({"metric": bench.METRIC, "value": 251.3,
                       "unit": bench.UNIT, "vs_baseline": 1.01,
                       "mfu_nominal": 0.11})

    def run(cmd, timeout, capture_output, text, env):
        return types.SimpleNamespace(
            returncode=0, stdout="noise\n" + good + "\n", stderr="")

    clock = _wire(monkeypatch, tmp_path, lambda: True, run)
    with pytest.raises(SystemExit) as e:
        bench.orchestrate(deadline_s=1500)
    assert e.value.code == 0
    lines = _json_lines(capsys.readouterr().out)
    assert lines == [json.loads(good)]
    assert clock.elapsed() < 1500


def test_retries_after_failed_child_until_success(monkeypatch, capsys,
                                                  tmp_path):
    calls = {"n": 0}
    good = json.dumps({"metric": bench.METRIC, "value": 300.0,
                       "unit": bench.UNIT, "vs_baseline": 1.2})

    def run(cmd, timeout, capture_output, text, env):
        calls["n"] += 1
        monkeypatch.setattr(bench.time, "t", bench.time.t + 60)
        if calls["n"] < 3:  # two wedged windows, then a clean one
            raise bench.subprocess.TimeoutExpired(cmd, timeout)
        return types.SimpleNamespace(returncode=0, stdout=good + "\n",
                                     stderr="")

    _wire(monkeypatch, tmp_path, lambda: True, run)
    with pytest.raises(SystemExit) as e:
        bench.orchestrate(deadline_s=1500)
    assert e.value.code == 0
    assert calls["n"] == 3
    assert _json_lines(capsys.readouterr().out) == [json.loads(good)]


def test_exhaustion_emits_single_error_line(monkeypatch, capsys, tmp_path):
    def run(cmd, timeout, capture_output, text, env):  # pragma: no cover
        raise AssertionError("child must not run when tunnel is down")

    _wire(monkeypatch, tmp_path, lambda: False, run)
    with pytest.raises(SystemExit) as e:
        bench.orchestrate(deadline_s=700)
    assert e.value.code == 1
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1
    assert lines[0]["value"] == 0.0 and "attempts" in lines[0]["error"]
    # timestamped outage evidence was written
    assert "exhausted" in open(tmp_path / "probes.log").read()


def test_child_error_line_is_not_relayed_as_success(monkeypatch, capsys,
                                                    tmp_path):
    bad = json.dumps({"metric": bench.METRIC, "value": 0.0,
                      "unit": bench.UNIT, "vs_baseline": 0.0,
                      "error": "backend init exceeded 240s"})

    def run(cmd, timeout, capture_output, text, env):
        monkeypatch.setattr(bench.time, "t", bench.time.t + 200)
        return types.SimpleNamespace(returncode=1, stdout=bad + "\n",
                                     stderr="")

    _wire(monkeypatch, tmp_path, lambda: True, run)
    with pytest.raises(SystemExit) as e:
        bench.orchestrate(deadline_s=900)
    assert e.value.code == 1
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1
    assert lines[0]["value"] == 0.0
    assert "backend init exceeded" in lines[0]["error"]


def test_warp_impl_derisk_ladder_env(monkeypatch, capsys, tmp_path):
    """Attempt 1 runs the full fast config (default warp, spc=4);
    attempt 2 drops to spc=1; attempts 3+ also force 'xla'. An operator-
    exported value pins that knob for every attempt — including an
    exported *empty* BENCH_WARP_IMPL (pins the config default)."""
    seen = []

    def run(cmd, timeout, capture_output, text, env):
        seen.append((env.get("BENCH_WARP_IMPL"), env.get("BENCH_SPC")))
        monkeypatch.setattr(bench.time, "t", bench.time.t + 250)
        return types.SimpleNamespace(returncode=1, stdout="", stderr="x")

    _wire(monkeypatch, tmp_path, lambda: True, run)
    with pytest.raises(SystemExit):
        bench.orchestrate(deadline_s=1600)
    assert len(seen) >= 3
    assert seen[0] == ("", "4") and seen[1] == ("", "1")
    assert set(seen[2:]) == {("xla", "1")}

    seen.clear()
    monkeypatch.setenv("BENCH_WARP_IMPL", "xla")
    monkeypatch.setenv("BENCH_SPC", "2")
    _wire(monkeypatch, tmp_path, lambda: True, run)
    with pytest.raises(SystemExit):
        bench.orchestrate(deadline_s=1600)
    capsys.readouterr()
    assert seen and set(seen) == {("xla", "2")}

    seen.clear()
    monkeypatch.setenv("BENCH_WARP_IMPL", "")  # present-but-empty: pinned
    monkeypatch.delenv("BENCH_SPC")
    _wire(monkeypatch, tmp_path, lambda: True, run)
    with pytest.raises(SystemExit):
        bench.orchestrate(deadline_s=1600)
    capsys.readouterr()
    assert len(seen) >= 3 and {w for w, _ in seen} == {""}
    assert [s for _, s in seen[:2]] == ["4", "1"]  # spc ladder still live


def test_exhaustion_falls_back_to_last_good(monkeypatch, capsys, tmp_path):
    """With no live window but a chain-captured measurement on disk, the
    orchestrator reports that number marked stale instead of a blind 0.0
    (VERDICT r03 item 1c) — but exits NONZERO (rc=3) so a driver keying
    on exit status must opt in to stale values (ADVICE r04)."""
    def run(cmd, timeout, capture_output, text, env):  # pragma: no cover
        raise AssertionError("child must not run when tunnel is down")

    import time as _time

    # an ambient opt-in (the workflow bench.py documents) must not leak
    # into the strict-mode assertion below
    monkeypatch.delenv("BENCH_ALLOW_STALE", raising=False)
    _wire(monkeypatch, tmp_path, lambda: False, run)
    fresh = _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                           _time.gmtime(_time.time() - 3600))
    (tmp_path / "last_good.json").write_text(json.dumps({
        "measured_at": fresh,
        "res": {"pairs_per_sec_per_chip": 241.7, "matmul_tflops": 63.4,
                "rtt_ms": 67.0, "batch": 16, "warp_impl": "auto",
                "mfu_nominal": 0.11, "mfu_vs_matmul": 0.33}}))
    with pytest.raises(SystemExit) as e:
        bench.orchestrate(deadline_s=700)
    assert e.value.code == bench.STALE_EXIT_CODE
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1
    assert lines[0]["value"] == 241.7
    assert lines[0]["stale"] is True
    assert lines[0]["measured_at"] == fresh
    assert lines[0]["mfu_nominal"] == 0.11
    assert "error" in lines[0]  # the outage story still travels


def test_stale_fallback_opt_in_env_restores_rc0(monkeypatch, capsys,
                                                tmp_path):
    """BENCH_ALLOW_STALE=1 is the driver's explicit opt-in: same stale
    line, exit 0."""
    def run(cmd, timeout, capture_output, text, env):  # pragma: no cover
        raise AssertionError("child must not run when tunnel is down")

    import time as _time

    monkeypatch.setenv("BENCH_ALLOW_STALE", "1")
    _wire(monkeypatch, tmp_path, lambda: False, run)
    fresh = _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                           _time.gmtime(_time.time() - 3600))
    (tmp_path / "last_good.json").write_text(json.dumps({
        "measured_at": fresh, "res": {"pairs_per_sec_per_chip": 199.9}}))
    with pytest.raises(SystemExit) as e:
        bench.orchestrate(deadline_s=700)
    assert e.value.code == 0
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1 and lines[0]["stale"] is True


def test_exhaustion_skips_aged_out_last_good(monkeypatch, capsys, tmp_path):
    """A last-good record older than LAST_GOOD_MAX_AGE_S must not be
    served as a stale success (ADVICE r04: unbounded fallback age)."""
    import time as _time

    def run(cmd, timeout, capture_output, text, env):  # pragma: no cover
        raise AssertionError("child must not run when tunnel is down")

    _wire(monkeypatch, tmp_path, lambda: False, run)
    old = _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                         _time.gmtime(_time.time() - 49 * 3600))
    (tmp_path / "last_good.json").write_text(json.dumps({
        "measured_at": old, "res": {"pairs_per_sec_per_chip": 241.7}}))
    with pytest.raises(SystemExit) as e:
        bench.orchestrate(deadline_s=700)
    assert e.value.code == 1
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1 and lines[0]["value"] == 0.0


def test_bench_spc_math_and_last_good_gate(monkeypatch, tmp_path):
    """bench() with steps_per_call=K: throughput normalizes per optimizer
    step (per_call / K), lowered FLOPs are NOT divided by K (XLA counts a
    scan body once), and a non-TPU backend never writes the last-known-
    good fallback record."""
    import os

    import numpy as np

    bench._import_compute()  # conftest forced the cpu backend already
    monkeypatch.setattr(bench, "LAST_GOOD", str(tmp_path / "lg.json"))
    monkeypatch.setattr(bench, "_init_devices", lambda timeout_s=240.0: [0])
    monkeypatch.setattr(bench, "calibrate",
                        lambda: {"matmul_tflops": 100.0, "rtt_ms": 1.0})
    fake_cfg = types.SimpleNamespace(loss=types.SimpleNamespace(
        warp_impl="auto"))
    monkeypatch.setattr(
        bench, "headline_setup",
        lambda *a, **k: (fake_cfg, None, None, None, "state", "step", "b"))
    monkeypatch.setattr(
        bench, "time_train_step",
        lambda step, state, b, steps, windows, warmup: (0.4, state,
                                                        np.array([1.0])))
    monkeypatch.setattr(bench, "step_flops", lambda *a: 8e9)
    monkeypatch.setenv("BENCH_SPC", "4")
    res = bench.bench()
    assert res["steps_per_call"] == 4
    assert abs(res["steps_per_sec"] - 10.0) < 1e-9   # 4 steps / 0.4 s call
    assert abs(res["pairs_per_sec"] - 160.0) < 1e-9  # batch 16 x 10
    assert res["flops_per_step"] == 8e9              # scan body counted once
    assert not os.path.exists(tmp_path / "lg.json")  # cpu backend: no save


def test_exhaustion_ignores_empty_or_zero_last_good(monkeypatch, capsys,
                                                    tmp_path):
    def run(cmd, timeout, capture_output, text, env):  # pragma: no cover
        raise AssertionError("child must not run when tunnel is down")

    _wire(monkeypatch, tmp_path, lambda: False, run)
    (tmp_path / "last_good.json").write_text(json.dumps({
        "measured_at": "T", "res": {"pairs_per_sec_per_chip": 0.0}}))
    with pytest.raises(SystemExit) as e:
        bench.orchestrate(deadline_s=700)
    assert e.value.code == 1
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1 and lines[0]["value"] == 0.0
