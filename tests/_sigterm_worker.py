"""Subprocess half of the second-SIGTERM escalation test
(tests/test_resilience.py::test_second_sigterm_falls_through).

Runs a real Trainer.fit() on the synthetic dataset with the train step
wrapped so the SECOND dispatch blocks on a long main-thread sleep —
a deterministic stand-in for a run wedged somewhere the stop flag is
never polled. The parent waits for the WEDGED line, then sends SIGTERM
twice: the first is absorbed by fit()'s graceful handler (stop flag
only — the wedged loop never reaches the next boundary), the second
must fall through to the default action and kill the process with
SIGTERM (rc == -15), proving a wedged run stays killable without an
operator SIGKILL.

Run in a SUBPROCESS (not in-suite) for two reasons: signal handlers
only install in a main thread, and an in-process fit under the suite's
process-wide warm compile cache hits the known cpu cache-read heap
corruption (hostmesh.py r07 addendum) — same rationale as
tests/test_obs.py's CLI fit test.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepof_tpu.core.config import (  # noqa: E402
    DataConfig,
    ExperimentConfig,
    TrainConfig,
)
from deepof_tpu.train.loop import Trainer  # noqa: E402


def main() -> None:
    log_dir = sys.argv[1]
    cfg = ExperimentConfig(
        model="flownet_s",
        width_mult=0.25,
        data=DataConfig(dataset="synthetic", image_size=(64, 64),
                        gt_size=(64, 64), batch_size=8),
        train=TrainConfig(num_epochs=10**6, log_every=1, eval_every=0,
                          ckpt_every_epochs=10**6, log_dir=log_dir,
                          eval_batch_size=8, eval_amplifier=1.0, seed=0))
    trainer = Trainer(cfg)
    real_step = trainer.train_step
    calls = {"n": 0}

    def wedged_step(state, batch):
        calls["n"] += 1
        if calls["n"] >= 2:
            # main-thread wedge: fit()'s handler still runs (signals are
            # delivered between bytecodes; CPython resumes the sleep),
            # but the loop never reaches its stop_sig check
            print("WEDGED", flush=True)
            time.sleep(600)
        return real_step(state, batch)

    trainer.train_step = wedged_step
    trainer.fit(num_epochs=1, max_steps=10**6)


if __name__ == "__main__":
    main()
