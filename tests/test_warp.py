"""Golden tests: vectorized jnp warp vs a slow numpy loop oracle.

The oracle independently transcribes the semantics surveyed from the
reference graph construction (floor+frac, per-corner clip, bilinear blend;
SURVEY.md §2.4) — the same validation pattern as the reference's
`check_loss.py`.
"""

import numpy as np
import jax.numpy as jnp

from deepof_tpu.ops import backward_warp, backward_warp_volume


def warp_oracle(image: np.ndarray, flow: np.ndarray) -> np.ndarray:
    b, h, w, c = image.shape
    out = np.zeros_like(image)
    for bi in range(b):
        for y in range(h):
            for x in range(w):
                u, v = flow[bi, y, x]
                fx, fy = int(np.floor(u)), int(np.floor(v))
                wx, wy = u - np.floor(u), v - np.floor(v)
                x0 = np.clip(x + fx, 0, w - 1)
                x1 = np.clip(x + fx + 1, 0, w - 1)
                y0 = np.clip(y + fy, 0, h - 1)
                y1 = np.clip(y + fy + 1, 0, h - 1)
                for ci in range(c):
                    ia = image[bi, y0, x0, ci]
                    ib = image[bi, y1, x0, ci]
                    ic = image[bi, y0, x1, ci]
                    id_ = image[bi, y1, x1, ci]
                    out[bi, y, x, ci] = (
                        ia * (1 - wx) * (1 - wy) + ib * (1 - wx) * wy
                        + ic * wx * (1 - wy) + id_ * wx * wy
                    )
    return out


def test_zero_flow_identity(rng):
    img = rng.rand(2, 8, 10, 3).astype(np.float32)
    out = np.asarray(backward_warp(jnp.asarray(img), jnp.zeros((2, 8, 10, 2))))
    np.testing.assert_allclose(out, img, rtol=1e-6)


def test_integer_shift(rng):
    """Flow u=+1 shifts content: recon(x) = img(x+1)."""
    img = rng.rand(1, 6, 6, 1).astype(np.float32)
    flow = np.zeros((1, 6, 6, 2), np.float32)
    flow[..., 0] = 1.0
    out = np.asarray(backward_warp(jnp.asarray(img), jnp.asarray(flow)))
    np.testing.assert_allclose(out[0, :, :-1, 0], img[0, :, 1:, 0], rtol=1e-6)
    # last column clips to border
    np.testing.assert_allclose(out[0, :, -1, 0], img[0, :, -1, 0], rtol=1e-6)


def test_matches_oracle(rng):
    img = rng.rand(2, 9, 12, 3).astype(np.float32)
    flow = (rng.rand(2, 9, 12, 2).astype(np.float32) - 0.5) * 8
    got = np.asarray(backward_warp(jnp.asarray(img), jnp.asarray(flow)))
    want = warp_oracle(img, flow)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_large_out_of_range_flow_clips(rng):
    img = rng.rand(1, 5, 7, 2).astype(np.float32)
    flow = rng.randn(1, 5, 7, 2).astype(np.float32) * 100
    got = np.asarray(backward_warp(jnp.asarray(img), jnp.asarray(flow)))
    want = warp_oracle(img, flow)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert np.isfinite(got).all()


def test_volume_warp_matches_pairwise(rng):
    """Volume warp == independent per-pair warps."""
    b, h, w, t = 2, 6, 8, 4
    vol = rng.rand(b, h, w, 3 * t).astype(np.float32)
    flows = (rng.rand(b, h, w, 2 * (t - 1)).astype(np.float32) - 0.5) * 4
    got = np.asarray(backward_warp_volume(jnp.asarray(vol), jnp.asarray(flows)))
    assert got.shape == (b, h, w, 3 * (t - 1))
    for p in range(t - 1):
        nxt = vol[..., 3 * (p + 1) : 3 * (p + 2)]
        fl = flows[..., 2 * p : 2 * p + 2]
        want = warp_oracle(nxt, fl)
        np.testing.assert_allclose(got[..., 3 * p : 3 * p + 3], want, rtol=1e-5, atol=1e-6)


def test_xla_warp_lowers_to_single_gather():
    """Regression guard for the patch-gather optimization (DESIGN.md
    'Measured step decomposition'): the XLA warp path must lower to
    exactly ONE gather op — the 2x2 neighborhood rides as channels. A
    second gather reappearing means the 4x index-count regression is
    back."""
    import jax

    img = jnp.zeros((2, 20, 150, 3))
    flow = jnp.zeros((2, 20, 150, 2))
    txt = jax.jit(
        lambda i, f: backward_warp(i, f, impl="xla")).lower(img, flow).as_text()
    assert txt.count('"stablehlo.gather"(') == 1, txt.count('"stablehlo.gather"(')
