"""Recipe-engine tests (ISSUE r20): deterministic multi-dataset mixing,
staged curricula, and the UCF-101 action workload.

Fast, jax-free pins first: the mixed stream's bit-identity across
worker counts and elastic generation bumps (the `derive_batch_rng`
contract extended to the member CHOICE), the strict `recipe_from_dict`
round-trip with indexed unknown-key rejection, the loud build-time
member-structure validation, the pure `plateau_reached` trigger, and
the jax-free stage-resume scan over fabricated manifests.

Slow tests (full XLA compiles, `pytest.ini` slow marker) then drive
`run_recipe` end to end: a two-stage Chairs-shaped curriculum whose
stage switch provably compiles nothing (the run ledger holds only
warmup 'aot' rows), stage-correct resume from a mid-stage checkpoint,
an injected-AEE plateau advance, and the st_single action head trained
through a recipe and queried via `predict_action`.
"""

import dataclasses
import hashlib
import json
import os

import numpy as np
import pytest

from deepof_tpu.core.config import (
    DataConfig,
    ExperimentConfig,
    LossConfig,
    MixtureMemberConfig,
    OptimConfig,
    RecipeConfig,
    StageConfig,
    TrainConfig,
    config_from_dict,
    recipe_from_dict,
)
from deepof_tpu.data.mixture import MixtureDataset, build_mixture
from deepof_tpu.data.pipeline import InputPipeline, derive_batch_rng
from deepof_tpu.parallel.mesh import elastic_stream_seed
from deepof_tpu.resilience import verify as ckpt_verify
from deepof_tpu.train import recipe as recipe_mod


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def _mix_data_cfg(**kw) -> DataConfig:
    base = dict(dataset="synthetic", image_size=(32, 32), gt_size=(32, 32),
                batch_size=4, time_step=2)
    base.update(kw)
    return DataConfig(**base)


def _mix_stage(weights=(0.8, 0.2), **member_kw) -> StageConfig:
    members = tuple(
        MixtureMemberConfig(dataset="synthetic", weight=w, **member_kw)
        for w in weights)
    return StageConfig(name="mixstage", mixture=members)


def _batch_digest(batch: dict) -> str:
    h = hashlib.sha256()
    for k in sorted(batch):
        v = np.asarray(batch[k])
        h.update(k.encode())
        h.update(str(v.dtype).encode())
        h.update(str(v.shape).encode())
        h.update(np.ascontiguousarray(v).tobytes())
    return h.hexdigest()


def _stream_digest(seed, num_workers: int, n_batches: int = 12) -> str:
    """sha256 over `n_batches` mixed batches delivered through the real
    worker pipeline — the exact path the Trainer consumes."""
    ds = build_mixture(_mix_data_cfg(), _mix_stage())
    pipe = InputPipeline(
        lambda i: ds.sample_train(4, rng=derive_batch_rng(seed, i)),
        num_workers=num_workers)
    try:
        h = hashlib.sha256()
        for _ in range(n_batches):
            h.update(_batch_digest(pipe.get()).encode())
        return h.hexdigest()
    finally:
        pipe.close()


# --------------------------------------------------------------------------
# mixed-stream determinism (tentpole contract)
# --------------------------------------------------------------------------

def test_mixed_stream_identical_across_worker_counts():
    """The mixed stream is bit-identical for num_workers in {0, 1, 4}:
    the member choice folds out of the per-batch rng, so assembly order
    and pool size are invisible in the delivered bytes."""
    digests = {w: _stream_digest(1234, num_workers=w) for w in (0, 1, 4)}
    assert digests[0] == digests[1] == digests[4]


def test_mixed_stream_identical_across_elastic_generation_bump():
    """Elastic seeding composes with the mixture unchanged: the same
    `elastic_stream_seed` word array replays the identical mixed stream
    at any worker count, and a bumped generation yields a decorrelated
    (but itself reproducible) stream."""
    g0 = elastic_stream_seed(7, host_index=0, num_hosts=2, generation=0,
                             start_step=0)
    g1 = elastic_stream_seed(7, host_index=0, num_hosts=2, generation=1,
                             start_step=0)
    assert _stream_digest(g0, 0) == _stream_digest(g0, 4)
    assert _stream_digest(g1, 0) == _stream_digest(g1, 4)
    # survivors must not replay draws the old generation trained on
    assert _stream_digest(g0, 0) != _stream_digest(g1, 0)


def test_mixture_draw_counters_split_by_weight():
    """Both members of an 0.8/0.2 mixture are actually drawn, roughly
    weight-proportionally, and the registry-declared counter block
    reports the split."""
    members = (MixtureMemberConfig(dataset="synthetic", weight=0.75),
               MixtureMemberConfig(dataset="synthetic", weight=0.25,
                                   time_step=0))
    ds = build_mixture(_mix_data_cfg(),
                       StageConfig(name="counts", mixture=members))
    picks = [ds._pick(derive_batch_rng(0, i)) for i in range(400)]
    frac = sum(1 for p in picks if p == 0) / len(picks)
    assert 0.6 < frac < 0.9  # weight-proportional, not degenerate
    for i in range(10):
        ds.sample_train(2, rng=derive_batch_rng(0, i))
    stats = ds.mixture_stats()["recipe_draws_by_dataset"]
    assert sum(stats.values()) == 10


def test_mixture_normalizes_t2_volume_to_pair_form():
    """A T=2 volume batch mixes structurally with Chairs-style pairs:
    normalize_batch splits (B, H, W, 6) into {source, target}."""
    from deepof_tpu.data.mixture import normalize_batch

    vol = np.arange(2 * 4 * 4 * 6, dtype=np.float32).reshape(2, 4, 4, 6)
    out = normalize_batch({"volume": vol,
                           "flow": np.zeros((2, 4, 4, 2), np.float32)})
    assert set(out) == {"source", "target", "flow"}
    np.testing.assert_array_equal(out["source"], vol[..., :3])
    np.testing.assert_array_equal(out["target"], vol[..., 3:])


def test_mixture_member_structure_mismatch_is_loud():
    """Members that disagree on implied time_step (T=2 pairs vs a T=3
    volume) must fail at BUILD time with the stage name in the message
    — never mid-run with a shape error from inside the compiled step."""
    members = (MixtureMemberConfig(dataset="synthetic", weight=0.5),
               MixtureMemberConfig(dataset="synthetic", weight=0.5,
                                   time_step=3))
    stage = StageConfig(name="badstage", mixture=members)
    with pytest.raises(ValueError) as ei:
        build_mixture(_mix_data_cfg(), stage)
    msg = str(ei.value)
    assert "badstage" in msg and "disagree" in msg


def test_mixture_rejects_empty_and_nonpositive_weights():
    with pytest.raises(ValueError, match="empty mixture"):
        build_mixture(_mix_data_cfg(), StageConfig(name="empty"))
    with pytest.raises(ValueError, match="positive"):
        MixtureDataset([object()], [0.0], ["x"], stage="zeroweight")


# --------------------------------------------------------------------------
# config round-trip (satellite 1)
# --------------------------------------------------------------------------

def _sample_recipe() -> RecipeConfig:
    return RecipeConfig(
        enabled=True,
        stages=(
            StageConfig(
                name="chairs",
                mixture=(MixtureMemberConfig("flyingchairs", 0.8),
                         MixtureMemberConfig("sintel", 0.2,
                                             sintel_pass="clean")),
                image_size=(64, 64), steps=4),
            StageConfig(name="sintel", advance="plateau",
                        plateau_window=4, plateau_slope=0.05,
                        learning_rate=1e-5),
        ))


def test_recipe_config_json_round_trip():
    """RecipeConfig survives asdict -> JSON -> recipe_from_dict exactly,
    tuples (stages, mixture, image_size) re-tupled at every level."""
    rc = _sample_recipe()
    back = recipe_from_dict(json.loads(json.dumps(dataclasses.asdict(rc))))
    assert back == rc


def test_experiment_config_round_trip_carries_recipe():
    """The full config tree round-trips through config_from_dict with the
    recipe block intact — the parent->replica config handoff contract."""
    cfg = ExperimentConfig(recipe=_sample_recipe())
    back = config_from_dict(json.loads(json.dumps(dataclasses.asdict(cfg))))
    assert back == cfg
    assert back.recipe.stages[0].mixture[1].sintel_pass == "clean"


def test_recipe_from_dict_rejects_unknown_keys_with_indexed_path():
    """A typo at ANY nesting level fails loudly with the exact indexed
    path — never a silently-defaulted field."""
    with pytest.raises(ValueError, match=r"recipe"):
        recipe_from_dict({"enabledd": True})
    with pytest.raises(ValueError, match=r"recipe\.stages\[1\]"):
        recipe_from_dict({"stages": [{"name": "ok"}, {"stepss": 4}]})
    with pytest.raises(ValueError,
                       match=r"recipe\.stages\[0\]\.mixture\[1\]"):
        recipe_from_dict({"stages": [
            {"mixture": [{"dataset": "sintel"},
                         {"dataset": "sintel", "wieght": 0.5}]}]})


# --------------------------------------------------------------------------
# stage resolution + advance trigger + resume scan (jax-free)
# --------------------------------------------------------------------------

def test_stage_config_overrides_apply_and_sentinels_inherit():
    base = ExperimentConfig(data=_mix_data_cfg(time_step=2))
    stage = StageConfig(name="s", image_size=(48, 48), time_step=3,
                        model="st_single", learning_rate=5e-5,
                        loss_weights=(1.0, 2.0),
                        mixture=(MixtureMemberConfig("sintel", 1.0),))
    scfg = recipe_mod.stage_config(base, stage)
    assert scfg.data.image_size == (48, 48)
    assert scfg.data.time_step == 3
    assert scfg.data.dataset == "sintel"  # first member is the face
    assert scfg.model == "st_single"
    assert scfg.optim.learning_rate == 5e-5
    assert scfg.loss.weights == (1.0, 2.0)
    # sentinels inherit the base untouched
    assert scfg.data.gt_size == base.data.gt_size
    assert scfg.data.batch_size == base.data.batch_size


def test_plateau_reached_drill():
    """The pure plateau trigger on injected AEE series: a steep descent
    is not a plateau; a flat tail is; too few evals never trigger."""
    stage = StageConfig(name="p", advance="plateau", plateau_window=4,
                        plateau_slope=0.01, min_evals=3)
    improving = [{"step": 1000 * i, "aee": 10.0 - 2.0 * i}
                 for i in range(5)]
    assert not recipe_mod.plateau_reached(stage, improving)
    flat = [{"step": 1000 * i, "aee": 2.0} for i in range(5)]
    assert recipe_mod.plateau_reached(stage, flat)
    assert not recipe_mod.plateau_reached(stage, flat[:2])  # < min_evals
    # slight regression also counts as plateaued (no longer improving)
    regress = [{"step": 1000 * i, "aee": 2.0 + 0.001 * i}
               for i in range(5)]
    assert recipe_mod.plateau_reached(stage, regress)


def _recipe_base_cfg(tmp_path, stages) -> ExperimentConfig:
    return ExperimentConfig(
        data=_mix_data_cfg(),
        train=TrainConfig(log_dir=str(tmp_path / "run"), seed=0),
        recipe=RecipeConfig(enabled=True, stages=tuple(stages)))


def _fabricate_stage_ckpt(cfg, stage_idx: int, step: int,
                          extra: dict | None) -> None:
    step_dir = os.path.join(recipe_mod.stage_ckpt_dir(cfg, stage_idx),
                            f"step_{step}")
    os.makedirs(step_dir, exist_ok=True)
    with open(os.path.join(step_dir, "payload.bin"), "wb") as f:
        f.write(b"x" * 8)
    manifest = ckpt_verify.build_manifest(step_dir, step, extra=extra)
    ckpt_verify.write_manifest(step_dir, manifest)


def test_find_resume_stage_scans_newest_stage_first(tmp_path):
    stages = [StageConfig(name="a", steps=4), StageConfig(name="b"),
              StageConfig(name="c")]
    cfg = _recipe_base_cfg(tmp_path, stages)
    assert recipe_mod.find_resume_stage(cfg) == (0, {})  # fresh run
    _fabricate_stage_ckpt(cfg, 0, 4,
                          {"recipe_stage": 0, "recipe_stage_name": "a",
                           "stage_start_step": 0})
    _fabricate_stage_ckpt(cfg, 1, 7,
                          {"recipe_stage": 1, "recipe_stage_name": "b",
                           "stage_start_step": 4})
    idx, extra = recipe_mod.find_resume_stage(cfg)
    assert idx == 1  # highest stage with a committed step wins
    assert extra["stage_start_step"] == 4
    assert extra["recipe_stage_name"] == "b"


def test_find_resume_stage_falls_back_to_directory_index(tmp_path):
    """A manifest without the recipe extra (or no manifest at all) still
    resumes into the stage its DIRECTORY names — the scan is usable on
    checkpoints written before the recipe plane existed."""
    stages = [StageConfig(name="a"), StageConfig(name="b")]
    cfg = _recipe_base_cfg(tmp_path, stages)
    _fabricate_stage_ckpt(cfg, 1, 9, extra=None)
    idx, extra = recipe_mod.find_resume_stage(cfg)
    assert idx == 1
    assert "recipe_stage" not in extra


# --------------------------------------------------------------------------
# run_recipe advance logic with an injected AEE series (fast: FakeTrainer)
# --------------------------------------------------------------------------

class _FakeState:
    def __init__(self, step=0, params=None):
        self.step = step
        self.params = params if params is not None else {}

    def replace(self, **kw):
        out = _FakeState(self.step, self.params)
        for k, v in kw.items():
            setattr(out, k, v)
        return out


class _FakeLogger:
    def __init__(self, sink):
        self._sink = sink

    def log(self, kind, step, **fields):
        self._sink.append({"kind": kind, "step": step, **fields})


class _FakeTrainer:
    """Trainer facade driving run_recipe's advance logic without XLA:
    fit() 'trains' one step at a time and feeds the on_eval hook an
    injected AEE series — steeply improving for the first 4 steps, flat
    after — so the plateau trigger has a real trend to flatten on."""

    logs: list = []

    def __init__(self, scfg, dataset=None, mesh=None, ckpt_dir=None,
                 train_step=None, eval_fn=None, tx=None,
                 manifest_extra=None, extra_stats=None, on_eval=None,
                 **_kw):
        self.cfg = scfg
        self.state = _FakeState()
        self.steps_per_epoch = 1000
        self.logger = _FakeLogger(_FakeTrainer.logs)
        self._on_eval = on_eval
        self._extra_stats = extra_stats

    @staticmethod
    def _aee(step: int) -> float:
        return max(6.0 - step, 1.0)  # improves to step 5, then flat

    def fit(self, num_epochs=1, max_steps=None):
        n = (max_steps if max_steps is not None
             else num_epochs * self.steps_per_epoch)
        aee = float("nan")
        for _ in range(int(n)):
            step = int(self.state.step) + 1
            self.state = self.state.replace(step=step)
            if self._extra_stats is not None:
                self._extra_stats()  # the loop merges this every record
            aee = self._aee(step)
            if self._on_eval is not None and self._on_eval(step,
                                                           {"aee": aee}):
                break
        return {"aee": aee}


def test_run_recipe_plateau_advance_with_injected_aee(tmp_path,
                                                      monkeypatch):
    """The eval_trend-driven advance drill: the injected AEE series
    improves steeply (no trigger at min_evals) and then flattens — the
    stage must advance on 'plateau' exactly when the windowed slope
    flattens, not on its step budget, and the tail stage then runs its
    own fixed-step budget from the handoff step."""
    monkeypatch.setattr("deepof_tpu.train.loop.Trainer", _FakeTrainer)
    _FakeTrainer.logs = []
    from deepof_tpu.train.recipe import run_recipe

    stages = (
        StageConfig(name="plat",
                    mixture=(MixtureMemberConfig("synthetic", 1.0),),
                    advance="plateau", plateau_window=3,
                    plateau_slope=0.01, min_evals=3, steps=0),
        StageConfig(name="tail",
                    mixture=(MixtureMemberConfig("synthetic", 1.0),),
                    steps=2),
    )
    cfg = ExperimentConfig(
        data=_mix_data_cfg(),
        train=TrainConfig(log_dir=str(tmp_path / "run"), seed=0),
        # warmup=False: no XLA — the FakeTrainer never compiles
        recipe=RecipeConfig(enabled=True, stages=stages, warmup=False))
    out = run_recipe(cfg)
    # AEE series: 5,4,3,2,1,1,1 — window-3 slope first flattens at the
    # 7th eval (steps 5..7 all 1.0), so stage 0 ends exactly there
    assert out["per_stage"][0]["advance"] == "plateau"
    assert out["per_stage"][0]["end_step"] == 7
    assert out["advances"] == 1
    assert out["last_trigger"] == "plateau"
    assert out["final_stage"] == 1
    assert out["global_step"] == 9  # tail's 2-step budget from step 7
    assert out["per_stage"][1]["start_step"] == 7
    advance_logs = [r for r in _FakeTrainer.logs
                    if "recipe advance" in str(r.get("message", ""))]
    assert advance_logs and "'plateau'" in advance_logs[0]["message"]


def test_run_recipe_budget_cap_intersects_stage_budget(tmp_path,
                                                       monkeypatch):
    """--max-steps bounds TOTAL steps across stages: a cap inside stage
    0's own budget ends the run with cause 'budget' and no advance."""
    monkeypatch.setattr("deepof_tpu.train.loop.Trainer", _FakeTrainer)
    _FakeTrainer.logs = []
    from deepof_tpu.train.recipe import run_recipe

    stages = (StageConfig(name="a",
                          mixture=(MixtureMemberConfig("synthetic", 1.0),),
                          steps=8),
              StageConfig(name="b",
                          mixture=(MixtureMemberConfig("synthetic", 1.0),),
                          steps=4))
    cfg = ExperimentConfig(
        data=_mix_data_cfg(),
        train=TrainConfig(log_dir=str(tmp_path / "run"), seed=0),
        recipe=RecipeConfig(enabled=True, stages=stages, warmup=False))
    out = run_recipe(cfg, max_steps=5)
    assert out["global_step"] == 5
    assert out["final_stage"] == 0
    assert out["advances"] == 0
    assert out["per_stage"] == [{"stage": 0, "name": "a", "start_step": 0,
                                 "end_step": 5, "advance": "budget"}]


# --------------------------------------------------------------------------
# end-to-end recipe runs (slow; CLI subprocess)
#
# Deliberately subprocess-shaped: the suite process has the persistent
# compile cache enabled (conftest/force_cpu_devices) and warm
# cross-process cache READS corrupt the heap on this host's cpu jaxlib
# (hostmesh.py's documented residual risk; reproduced here as rc=134 at
# steady-state dispatch inside an in-process run_recipe). The CLI's
# auto gate keeps the cache OFF on cpu, so the child pays a fresh
# compile instead of a coin-flip segfault — and the tests exercise the
# real `train --recipe` / `predict --action` entry paths.
# --------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TWO_STAGE_RECIPE = {
    "stages": [
        {"name": "warm",
         "mixture": [{"dataset": "synthetic", "weight": 0.8},
                     {"dataset": "synthetic", "weight": 0.2}],
         "steps": 4},
        {"name": "main",
         "mixture": [{"dataset": "synthetic", "weight": 1.0}],
         "steps": 4},
    ]
}


def _cli_train(tmp_path, recipe: dict, *extra, model="flownet_s",
               width="0.25"):
    """One `train --recipe` CLI run; returns the printed summary dict."""
    import subprocess
    import sys

    recipe_path = tmp_path / "recipe.json"
    recipe_path.write_text(json.dumps(recipe))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    res = subprocess.run(
        [sys.executable, "-m", "deepof_tpu", "train", "--preset",
         "flyingchairs", "--synthetic", "--recipe", str(recipe_path),
         "--log-dir", str(tmp_path / "run"),
         "--set", f"model={model}", "--set", f"width_mult={width}",
         "--set", "train.log_every=1", "--set", "train.eval_every=0",
         "--set", "train.steps_per_call=1", *extra],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO)
    assert res.returncode == 0, (res.stdout[-1000:], res.stderr[-2000:])
    return json.loads(res.stdout.strip().splitlines()[-1])


def _run_records(tmp_path) -> list[dict]:
    with open(tmp_path / "run" / "metrics.jsonl") as f:
        return [json.loads(ln) for ln in f if ln.strip()]


@pytest.mark.slow
def test_cli_recipe_two_stage_end_to_end(tmp_path):
    """The acceptance drill: a two-stage curriculum advances on 'steps',
    grafts params across the boundary, rides recipe counters in the
    train records, and — with warmup — its ledger holds ONLY 'aot' rows:
    the stage switch provably compiled nothing. A second invocation over
    the finished run resumes stage-correct and trains zero steps."""
    out = _cli_train(tmp_path, _TWO_STAGE_RECIPE)
    assert out["final_stage"] == 1
    assert out["global_step"] == 8
    assert out["advances"] == 1
    assert out["last_trigger"] == "steps"
    assert [s["advance"] for s in out["per_stage"]] == ["steps", "steps"]
    assert out["per_stage"][1]["start_step"] == 4

    # zero-recompile proof: every ledger row is a warmup AOT compile of
    # a stage executable — nothing compiled at the stage boundary
    with open(tmp_path / "run" / "ledger.jsonl") as f:
        rows = [json.loads(ln) for ln in f if ln.strip()]
    assert rows and all(r["compile_kind"] == "aot" for r in rows)
    names = {r["name"] for r in rows}
    assert {"train_step_stage0", "eval_step_stage0",
            "train_step_stage1", "eval_step_stage1"} <= names

    # recipe counters ride the train records (obs/registry.py keys)
    records = _run_records(tmp_path)
    trains = [r for r in records if r.get("kind") == "train"]
    assert any(r.get("recipe_stage") == 1 for r in trains)
    draws = [r["recipe_draws_by_dataset"] for r in trains
             if isinstance(r.get("recipe_draws_by_dataset"), dict)]
    assert draws and sum(draws[-1].values()) > 0
    # params grafted at the boundary, not re-initialized
    assert any(r.get("kind") == "info"
               and "grafted" in str(r.get("message", "")) for r in records)

    out2 = _cli_train(tmp_path, _TWO_STAGE_RECIPE)
    assert out2["final_stage"] == 1
    assert out2["global_step"] == 8
    assert out2["advances"] == 0


@pytest.mark.slow
def test_cli_recipe_resumes_mid_stage(tmp_path):
    """A budget-truncated run stops inside stage 1; the next invocation
    lands in stage 1 (manifest extra), restores the mid-stage step, and
    completes the stage — never restarts it."""
    out1 = _cli_train(tmp_path, _TWO_STAGE_RECIPE, "--max-steps", "6")
    assert out1["global_step"] == 6
    assert out1["per_stage"][-1]["stage"] == 1
    assert out1["per_stage"][-1]["advance"] == "budget"

    out2 = _cli_train(tmp_path, _TWO_STAGE_RECIPE)
    assert out2["final_stage"] == 1
    assert out2["global_step"] == 8
    assert out2["per_stage"][-1]["advance"] == "steps"
    assert out2["per_stage"][-1]["start_step"] == 4  # stage 1's own base


@pytest.mark.slow
def test_cli_recipe_action_workload_trains_and_predicts(tmp_path):
    """The UCF-101 action workload end to end on the synthetic path: an
    st_single recipe stage trains the two-stream head, and
    `predict --action` classifies a frame pair from the stage
    checkpoint, attaching labels from the labels file."""
    import subprocess
    import sys

    import cv2

    recipe = {"stages": [
        {"name": "action",
         "mixture": [{"dataset": "synthetic", "weight": 1.0}],
         "steps": 2}]}
    out = _cli_train(tmp_path, recipe, model="st_single", width="1.0")
    assert out["global_step"] == 2

    rng = np.random.RandomState(0)
    a, b = str(tmp_path / "a.png"), str(tmp_path / "b.png")
    cv2.imwrite(a, rng.randint(0, 255, (80, 96, 3), np.uint8))
    cv2.imwrite(b, rng.randint(0, 255, (80, 96, 3), np.uint8))
    labels = tmp_path / "labels.txt"
    labels.write_text("".join(f"class{i}\n" for i in range(101)))

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    res = subprocess.run(
        [sys.executable, "-m", "deepof_tpu", "predict", "--preset",
         "flyingchairs", "--synthetic", "--set", "model=st_single",
         "--action", "--labels", str(labels),
         "--ckpt-dir", str(tmp_path / "run" / "ckpt-stage0"),
         "--pairs", f"{a}:{b}", "--out", str(tmp_path / "out")],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO)
    assert res.returncode == 0, (res.stdout[-1000:], res.stderr[-2000:])

    rows = json.load(open(tmp_path / "out" / "actions.json"))
    assert len(rows) == 1 and len(rows[0]["top"]) >= 1
    probs = [t["prob"] for t in rows[0]["top"]]
    assert all(0.0 <= p <= 1.0 for p in probs)
    assert probs == sorted(probs, reverse=True)  # ranked descending
    assert rows[0]["class"] == rows[0]["top"][0]["class"]
    assert rows[0]["label"].startswith("class")
