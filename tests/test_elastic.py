"""Elastic multi-host training tests (DESIGN.md "Elastic training").

Fast tier: the pure decision functions — generation/re-shard stream-seed
math, heartbeat-verdict gating, the host-level chaos hook, checkpoint
writer gating + restore provenance (ISSUE 8 satellites), config
round-trip, and the analyze/tail surfacing of the elastic_* block.

Slow tier (chaos): the acceptance drills — a 3-virtual-host run with a
seeded SIGKILL of host 1 mid-run completes to the target step with zero
operator action (generation bumped, steps_lost bounded by the checkpoint
cadence, final params verifiable via verify-ckpt, `tail` exits 5); a
fault-free elastic run at the same seed completes with reforms == 0; and
the plain (non-elastic) preemption-grace path: one SIGTERM to a running
fit() yields a verified checkpoint, flushed metrics, and exit 0.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax.numpy as jnp
import optax

from deepof_tpu.analyze import summarize, tail_summary
from deepof_tpu.core.config import ExperimentConfig, config_from_dict
from deepof_tpu.data.pipeline import derive_batch_rng
from deepof_tpu.parallel.mesh import elastic_stream_seed
from deepof_tpu.resilience import verify as ckpt_verify
from deepof_tpu.resilience.faults import FaultConfig, build_injector
from deepof_tpu.train.checkpoint import CheckpointManager
from deepof_tpu.train.elastic import host_verdict, maybe_host_fault
from deepof_tpu.train.state import TrainState

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------- stream-seed re-shard


def test_elastic_stream_seed_deterministic_and_decorrelated():
    """The re-form determinism contract: the base seed is a pure
    function of (seed, host, world, generation, start step), and any
    differing component yields a decorrelated stream — no survivor
    replays draws a previous generation already trained on."""
    a = elastic_stream_seed(7, 0, 3, 0, 0)
    np.testing.assert_array_equal(a, elastic_stream_seed(7, 0, 3, 0, 0))

    seeds = set()
    for hosts in (2, 3):
        for host in range(hosts):
            for gen in (0, 1, 2):
                seeds.add(tuple(elastic_stream_seed(7, host, hosts, gen, 4)))
    assert len(seeds) == 2 * 3 + 3 * 3  # every (host, world, gen) distinct

    # the derived per-batch rng streams actually differ (MT19937
    # init_by_array over the full word vector, data/pipeline.py)
    draws = {
        key: tuple(derive_batch_rng(np.array(key, np.uint32), 0)
                   .randint(0, 2**31, 4))
        for key in list(seeds)[:6]
    }
    assert len(set(draws.values())) == len(draws)

    # 64-bit seeds fold losslessly
    assert tuple(elastic_stream_seed(2**40 + 5, 0, 2, 0, 0)) != \
        tuple(elastic_stream_seed(5, 0, 2, 0, 0))
    # survivors keep their ORIGINAL identity: host 2 in a shrunken
    # 2-host world is legitimate and distinct from every 3-host stream
    survivor = tuple(elastic_stream_seed(7, 2, 2, 1, 4))
    assert survivor not in seeds
    assert survivor == tuple(elastic_stream_seed(7, 2, 2, 1, 4))
    with pytest.raises(ValueError):
        elastic_stream_seed(0, -1, 3, 0, 0)


# ------------------------------------------------ heartbeat verdicts


def test_host_verdict_gating():
    """The coordinator's lost-host decision, from heartbeat CONTENT:
    pid-gated (a dead incarnation's file can neither vouch nor condemn),
    wedged:true honored, stale file time caught, and the content stall
    (fresh file, >= 1 step, no progress) — while beats == 0 (first
    dispatch compiling) is never judged a stall."""
    hb = {"pid": 7, "time": 1000.0, "wedged": False, "beats": 3,
          "last_step_age_s": 2.0}
    assert host_verdict(hb, 7, 1001.0, 15.0, 45.0) == "ok"
    assert host_verdict(None, 7, 1001.0, 15.0, 45.0) == "no_heartbeat"
    assert host_verdict(hb, 8, 1001.0, 15.0, 45.0) == "foreign_pid"
    assert host_verdict(dict(hb, wedged=True), 7, 1001.0, 15.0,
                        45.0) == "wedged"
    assert host_verdict(hb, 7, 1020.0, 15.0, 45.0) == "stale"
    assert host_verdict(dict(hb, last_step_age_s=60.0), 7, 1001.0, 15.0,
                        45.0) == "stalled"
    # compile window: zero completed steps is never a stall verdict
    assert host_verdict(dict(hb, beats=0, last_step_age_s=600.0), 7,
                        1001.0, 15.0, 45.0) == "ok"
    # wedge_after_s = 0 disables the content-stall verdict
    assert host_verdict(dict(hb, last_step_age_s=600.0), 7, 1001.0, 15.0,
                        0.0) == "ok"


def test_host_verdict_eval_compile_window_with_pre_eval_flush(tmp_path):
    """The PR 9 known-benign false-stale, pinned on host_verdict's
    timing inputs: a GIL-bound eval compile starves the heartbeat
    WRITER thread, so the file's `time` freezes for the compile's whole
    duration. Without the pre-eval flush the frozen timestamp can
    already be up to a heartbeat period old (plus accrued step age) —
    the verdict goes "stale" mid-compile on a healthy host. With the
    loop's touch(flush=True) at eval entry (train/loop.py), the frozen
    file is stamped AT the compile's start, so the coordinator's full
    stale_after_s window measures the compile itself."""
    import json
    import os

    from deepof_tpu.obs.heartbeat import Heartbeat

    stale_after, wedge_after = 15.0, 45.0
    t_eval = 1000.0  # wall time the eval compile begins

    # WITHOUT the flush: last write landed a period before the compile
    # and the age clock carried the pre-eval accrual — 15 s into a 20 s
    # compile the file looks dead even though the host is healthy.
    unflushed = {"pid": 7, "time": t_eval - 5.0, "wedged": False,
                 "beats": 3, "last_step_age_s": 12.0}
    assert host_verdict(unflushed, 7, t_eval + 10.1, stale_after,
                        wedge_after) == "stale"

    # WITH the flush: the file is stamped at t_eval with age reset, so
    # the same 10 s of frozen writer reads healthy...
    flushed = {"pid": 7, "time": t_eval, "wedged": False, "beats": 3,
               "last_step_age_s": 0.0}
    assert host_verdict(flushed, 7, t_eval + 10.1, stale_after,
                        wedge_after) == "ok"
    # ... for the entire stale_after_s window measured from eval entry
    assert host_verdict(flushed, 7, t_eval + stale_after - 0.1,
                        stale_after, wedge_after) == "ok"
    # a compile genuinely longer than the window is still caught — the
    # fix re-bases the clock, it does not blind the supervisor
    assert host_verdict(flushed, 7, t_eval + stale_after + 0.1,
                        stale_after, wedge_after) == "stale"

    # and the Heartbeat side of the contract: touch(flush=True) rewrites
    # the file synchronously from the CALLING thread — no dependence on
    # the background writer that the compile is about to starve
    path = tmp_path / "heartbeat.json"
    hb = Heartbeat(str(path), period_s=3600.0, devmem=False)
    try:
        assert not os.path.exists(path)  # writer parked for an hour
        hb.beat(4)
        hb.touch(flush=True)
        rec = json.loads(path.read_text())
        assert rec["step"] == 4 and rec["beats"] == 1
        assert rec["last_step_age_s"] < 1.0  # age re-based at the flush
    finally:
        hb.close()


# ------------------------------------------------- host chaos hook


@pytest.mark.chaos
def test_maybe_host_fault_arms_at_step_and_fires_once():
    """Host sites are keyed by host index, armed at host_fault_step,
    and consume-once per incarnation: host_loss SIGKILLs, preempt_notice
    SIGTERMs (and stops — a preempted host must not also be killed),
    host_wedge blocks."""
    inj = build_injector(FaultConfig(enabled=True, host_loss_at=(1,),
                                     host_fault_step=5))
    kills, blocks = [], []
    act = dict(_kill=lambda pid, sig: kills.append(sig),
               _block=lambda: blocks.append(True))
    maybe_host_fault(inj, 1, 4, 5, **act)  # below arm step
    assert kills == []
    maybe_host_fault(inj, 0, 9, 5, **act)  # unscheduled host
    assert kills == []
    maybe_host_fault(inj, 1, 5, 5, **act)
    assert kills == [signal.SIGKILL]
    maybe_host_fault(inj, 1, 6, 5, **act)  # consume-once
    assert kills == [signal.SIGKILL]
    assert blocks == []

    msgs = []
    inj2 = build_injector(FaultConfig(enabled=True, preempt_notice_at=(0,),
                                      host_wedge_at=(2,)))
    maybe_host_fault(inj2, 0, 1, 0, log=msgs.append, **act)
    assert kills[-1] == signal.SIGTERM and not blocks
    maybe_host_fault(inj2, 2, 1, 0, log=msgs.append, **act)
    assert blocks == [True]
    assert any("preemption notice" in m for m in msgs)
    assert any("wedging" in m for m in msgs)
    # disabled injector / non-elastic host: zero-overhead no-ops
    maybe_host_fault(None, 1, 9, 0, **act)
    maybe_host_fault(inj2, -1, 9, 0, **act)
    assert kills == [signal.SIGKILL, signal.SIGTERM]


def test_pace_to_world_step_skew_limiter(tmp_path):
    """The pacing gate blocks only when (same generation) AND (this
    host > floor + sync_ahead); a missing/stale file or a raised stop
    flag releases it immediately, and every wait tick touches the
    heartbeat so a paced leader never reads as a stall."""
    from deepof_tpu.train.elastic import pace_to_world

    wf = str(tmp_path / "elastic_world.json")
    touches = []
    sleeps = []

    def run(gstep, gen=0, stop=lambda: False):
        sleeps.clear()
        pace_to_world(wf, gen, gstep, 2, should_stop=stop,
                      touch=lambda: touches.append(True),
                      _sleep=sleeps.append)
        return len(sleeps)

    assert run(10) == 0  # no file: pacing disabled, never a dependency

    with open(wf, "w") as f:
        json.dump({"generation": 0, "floor": 5, "target": 100}, f)
    assert run(7) == 0  # at floor + sync_ahead: proceed
    assert run(8, gen=1) == 0  # stale generation: the barrier owns us

    # ahead of the floor: waits (and touches) until the floor advances
    state = {"n": 0}

    def stop_after_advancing():
        state["n"] += 1
        if state["n"] == 3:
            with open(wf, "w") as f:
                json.dump({"generation": 0, "floor": 9, "target": 100}, f)
        return False

    assert run(8, stop=stop_after_advancing) >= 1
    assert touches  # the wait kept the heartbeat fresh

    # a raised stop flag releases a blocked host (the SIGTERM barrier)
    with open(wf, "w") as f:
        json.dump({"generation": 0, "floor": 0, "target": 100}, f)
    assert run(50, stop=lambda: True) == 0


# ----------------------------------------------------- config handoff


def test_elastic_config_round_trips_to_children():
    """The coordinator->child config.json handoff must carry the elastic
    identity exactly (config_from_dict, same contract the fleet pins),
    and reject typo'd fields at the elastic level too."""
    cfg = ExperimentConfig().replace(
        elastic=dataclasses.replace(
            ExperimentConfig().elastic, hosts=0, host_index=2, num_hosts=3,
            generation=4, primary_host=1, target_step=100,
            ckpt_dir="/tmp/x/ckpt", virtual_devices=2, wedge_after_s=7.5),
        resilience=dataclasses.replace(
            ExperimentConfig().resilience,
            faults=FaultConfig(enabled=True, host_loss_at=(1,),
                               host_fault_step=5)))
    back = config_from_dict(json.loads(json.dumps(dataclasses.asdict(cfg))))
    assert back == cfg
    assert back.elastic.host_index == 2 and back.elastic.generation == 4
    assert back.resilience.faults.host_loss_at == (1,)

    # typo rejection ("hostz") moved to the registry-driven whole-tree
    # walk in test_lint.py, which keeps this assertion as a parity pin


# ------------------------------- ckpt writer gating + restore provenance


def _mk_state(step: int, val: float) -> TrainState:
    tx = optax.sgd(0.1)
    params = {"w": jnp.full((4,), float(val))}
    return TrainState(step=jnp.asarray(step, jnp.int32), params=params,
                      opt_state=tx.init(params),
                      rng=jnp.zeros((2,), jnp.uint32), tx=tx)


def test_ckpt_writer_gating_shared_directory(tmp_path):
    """Elastic non-primary hosts open the shared checkpoint directory
    restore-only: save() is a no-op returning None (no directory
    surgery races), while restore sees the primary's commits."""
    primary = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    reader = CheckpointManager(str(tmp_path / "ckpt"), async_save=False,
                               writer=False)
    assert reader.save(_mk_state(1, 1.0)) is None
    assert primary.all_steps() == []
    assert primary.save(_mk_state(2, 2.0)) is not None
    assert int(reader.restore(_mk_state(0, 0.0)).step) == 2
    assert reader.stats()["saves"] == 0


def test_restore_logs_provenance(tmp_path):
    """ISSUE 8 satellite: every successful restore states WHICH step it
    restored and WHY (requested vs newest vs fallback-after-corruption)
    through the metrics-log sink, so a post-reform run's provenance is
    auditable from metrics.jsonl alone."""
    msgs = []
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=5,
                            async_save=False,
                            log=lambda s, m: msgs.append((s, m)))
    mgr.save(_mk_state(1, 1.0))
    mgr.save(_mk_state(2, 2.0))

    mgr.restore(_mk_state(0, 0.0))
    assert msgs[-1] == (2, "checkpoint restore: step 2 (newest checkpoint)")

    mgr.restore(_mk_state(0, 0.0), step=1)
    assert msgs[-1] == (1, "checkpoint restore: step 1 "
                           "(explicitly requested)")

    # corrupt the newest: the fallback restore names the corruption
    d2 = str(tmp_path / "ckpt" / "step_0000000002")
    victim = max((os.path.getsize(os.path.join(r, f)),
                  os.path.join(r, f))
                 for r, _, fs in os.walk(d2) for f in fs)[1]
    with open(victim, "r+b") as f:
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))
    mgr.restore(_mk_state(0, 0.0))
    step, m = msgs[-1]
    assert step == 1
    assert m == ("checkpoint restore: step 1 (fallback after corruption: "
                 "1 newer candidate(s) failed verification/restore)")


# ----------------------------------------------- analyze/tail surfacing


def test_tail_exits_5_surfacing_elastic_reforms(tmp_path, capsys):
    """`tail` must fail scripted health checks when the elastic block
    shows the world shrank (reforms / lost hosts) — rc 5, distinct from
    wedged rc 3 and fleet rc 4 — and surface the block from both the
    heartbeat and kind="elastic" records."""
    from deepof_tpu.cli import main

    block = {"elastic_hosts": 3, "elastic_live": 3, "elastic_generation": 0,
             "elastic_reforms": 0, "elastic_lost_hosts": 0,
             "elastic_steps_lost": 0, "elastic_resumed_step": 0}
    (tmp_path / "metrics.jsonl").write_text(json.dumps(
        {"kind": "elastic", "step": 5, "time": time.time(), **block}) + "\n")
    (tmp_path / "heartbeat.json").write_text(json.dumps(
        {"time": time.time(), "step": 5, "wedged": False, **block}))
    assert main(["tail", "--log-dir", str(tmp_path)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["elastic"]["hosts"] == 3

    hurt = dict(block, elastic_generation=1, elastic_reforms=1,
                elastic_lost_hosts=1, elastic_steps_lost=2,
                elastic_resumed_step=4)
    (tmp_path / "heartbeat.json").write_text(json.dumps(
        {"time": time.time(), "step": 9, "wedged": False, **hurt}))
    assert main(["tail", "--log-dir", str(tmp_path)]) == 5
    out = json.loads(capsys.readouterr().out)
    assert out["elastic"]["reforms"] == 1
    assert out["elastic"]["lost_hosts"] == 1

    # no heartbeat: the newest kind="elastic" record still surfaces
    (tmp_path / "heartbeat.json").unlink()
    (tmp_path / "metrics.jsonl").write_text(json.dumps(
        {"kind": "elastic", "step": 9, "time": time.time(), **hurt}) + "\n")
    assert main(["tail", "--log-dir", str(tmp_path)]) == 5
    capsys.readouterr()

    summary = summarize([{"kind": "elastic", "step": 9, **hurt}])
    assert summary["elastic"]["reforms"] == 1


# ------------------------------------------------ acceptance (slow)


def _run_drill(log_dir, args, timeout=900):
    """Drive tools/elastic_drill.py — the CI-shaped drill IS the
    acceptance test, so the drill config (model, cadences, supervision
    knobs, the sync_ahead <= ckpt-cadence coupling) is maintained in
    exactly one place."""
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "elastic_drill.py"),
         "--log-dir", str(log_dir), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    out = json.loads(res.stdout) if res.stdout.strip() else {}
    return res.returncode, out, res


@pytest.mark.slow
@pytest.mark.chaos
def test_elastic_drill_survives_host_loss(tmp_path):
    """ISSUE 8 acceptance: 3 virtual hosts, seeded SIGKILL of host 1
    once its step reaches 4. The coordinator detects the loss, barriers
    the survivors (verified checkpoint + exit 0), bumps the generation,
    re-forms on 2 hosts with re-sharded streams, resumes from the
    newest valid checkpoint, and the run completes to the target step
    with zero operator action. Lost work is bounded by the checkpoint
    cadence, the elastic_* block lands in heartbeat + metrics, `tail`
    exits 5, and the final checkpoint verifies."""
    d = tmp_path / "drill"
    rc, out, res = _run_drill(
        d, ["--hosts", "3", "--target", "10", "--kill-host", "1",
            "--kill-step", "4", "--ckpt-every", "3",
            "--fault", "host_loss"])
    assert rc == 0, (res.stdout[-1500:], res.stderr[-3000:])
    assert out["completed"] is True
    assert out["generation"] >= 1
    assert out["reforms"] == 1
    assert out["lost_hosts"] == 1
    assert out["max_step"] == 10
    assert out["ckpt_ok"] is True
    assert out["tail_rc"] == 5  # tail surfaces the re-form, rc 5
    # bounded lost work: <= the checkpoint cadence (the barrier save
    # pins the survivors; only the killed host's uncommitted tail is
    # discarded)
    assert 0 <= out["steps_lost"] <= 3, out
    assert out["resumed_step"] >= 1
    # per-host terminal states from the coordinator heartbeat
    states = json.loads(
        (d / "heartbeat.json").read_text())["elastic_states"]
    assert states == {"host-0": "done", "host-1": "lost",
                      "host-2": "done"}

    # the reform timeline is auditable from metrics.jsonl alone
    text = (d / "metrics.jsonl").read_text()
    assert "LOST (crashed" in text
    assert "re-forming" in text
    elastic_recs = [json.loads(ln) for ln in text.splitlines()
                    if '"kind": "elastic"' in ln]
    assert len(elastic_recs) >= 2  # one per re-form + shutdown

    # survivors resumed from the shared checkpoint with logged
    # provenance (satellite: auditable from metrics.jsonl alone)
    host_logs = "".join(
        (d / f"host-{i}" / "metrics.jsonl").read_text() for i in (0, 2))
    assert "checkpoint restore: step" in host_logs

    # elastic_* block in the coordinator heartbeat (tail's rc-5 read)
    hb = json.loads((d / "heartbeat.json").read_text())
    for key in ("elastic_generation", "elastic_reforms",
                "elastic_lost_hosts", "elastic_resumed_step",
                "elastic_steps_lost"):
        assert key in hb, key

    # final params restorable and verifiable via verify-ckpt
    rep = ckpt_verify.verify_run(str(d))
    assert rep["ok"], rep
    assert 10 in rep["valid_steps"], rep


@pytest.mark.slow
@pytest.mark.chaos
def test_elastic_fault_free_run_never_reforms(tmp_path):
    """ISSUE 8 acceptance, control half: the same seed without faults
    reaches the target step with reforms == 0 (the supervision layer
    must never misjudge a healthy slow host on this machine)."""
    d = tmp_path / "clean"
    rc, out, res = _run_drill(
        d, ["--hosts", "2", "--target", "6", "--fault", "none"])
    assert rc == 0, (res.stdout[-1500:], res.stderr[-3000:])
    assert out["completed"] is True
    assert out["reforms"] == 0
    assert out["lost_hosts"] == 0
    assert out["generation"] == 0
    assert out["max_step"] == 6
    assert out["tail_rc"] == 0  # nothing to surface: healthy run
    states = json.loads(
        (d / "heartbeat.json").read_text())["elastic_states"]
    assert all(s == "done" for s in states.values())
    rep = ckpt_verify.verify_run(str(d))
    assert rep["ok"] and 6 in rep["valid_steps"], rep


@pytest.mark.slow
def test_plain_fit_sigterm_saves_verified_ckpt_and_exits_0(tmp_path):
    """ISSUE 8 satellite: preemption grace for PLAIN (non-elastic)
    training — the first SIGTERM to a running fit() stops at the next
    step boundary, saves a VERIFIED checkpoint, flushes metrics, and
    exits 0 (the second-SIGTERM escalation is pinned separately by
    tests/_sigterm_worker.py)."""
    d = tmp_path / "preempt"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    p = subprocess.Popen(
        [sys.executable, "-m", "deepof_tpu", "train", "--preset",
         "flyingchairs", "--synthetic", "--max-steps", "100000",
         "--log-dir", str(d),
         "--set", "model=flownet_s", "--set", "width_mult=0.25",
         "--set", "train.log_every=1", "--set", "train.eval_every=0",
         "--set", "train.ckpt_every_epochs=1000000"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO)
    try:
        # wait for real training progress (a train record on disk)
        deadline = time.monotonic() + 420
        metrics = d / "metrics.jsonl"
        while time.monotonic() < deadline:
            if metrics.exists() and '"kind": "train"' in metrics.read_text():
                break
            if p.poll() is not None:
                raise AssertionError(p.communicate()[1][-3000:])
            time.sleep(0.5)
        else:
            raise AssertionError("no train record within 420s")
        p.send_signal(signal.SIGTERM)
        stdout, stderr = p.communicate(timeout=180)
    finally:
        if p.poll() is None:
            p.kill()
            p.communicate()
    assert p.returncode == 0, (p.returncode, stderr[-3000:])
    # the graceful stop is logged and the summary still printed
    text = metrics.read_text()
    assert "stopping after a clean final checkpoint" in text
    summary = json.loads(stdout.strip().splitlines()[-1])
    assert summary["steps_per_sec"] >= 0
    # the checkpoint it saved on the way out verifies
    rep = ckpt_verify.verify_run(str(d))
    assert rep["ok"], rep
    assert rep["valid_steps"], rep
    train_steps = [json.loads(ln)["step"] for ln in text.splitlines()
                   if '"kind": "train"' in ln]
    assert max(rep["valid_steps"]) >= max(train_steps) - 1
