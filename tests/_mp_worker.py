"""Worker process for test_multiprocess.py: one of N JAX CPU processes.

Launched with PYTHONPATH cleared (skips the container's sitecustomize);
forces 2 virtual CPU devices, joins the distributed runtime, and runs the
multi-host data-path plumbing (SURVEY.md §5.8): `local_batch_rows` row
slicing -> `put_global` assembly -> sharded train step, the stacked
[K, B, ...] `steps_per_call` layout, and the allgathered eval. Writes its
metrics as JSON for the parent test to compare against a single-process
run of the identical batches.

`make_setup()` is imported by test_multiprocess.py for its single-process
reference run — the equality asserts are only meaningful if both sides
build the identical config/model/optimizer/initial state.
"""

import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

H, W, BATCH = 16, 32, 8


def make_setup():
    """(cfg, ds, model, new_state_fn) shared by worker and reference."""
    import jax.numpy as jnp
    import optax

    from deepof_tpu.core.config import (
        DataConfig,
        ExperimentConfig,
        LossConfig,
        MeshConfig,
        OptimConfig,
        TrainConfig,
    )
    from deepof_tpu.data.datasets import SyntheticData
    from deepof_tpu.models.registry import build_model
    from deepof_tpu.train.state import create_train_state

    cfg = ExperimentConfig(
        name="mp",
        model="flownet_s",
        width_mult=0.25,  # thin trunk: DCN-equality semantics are width-free
        loss=LossConfig(weights=(16, 8, 4, 2, 1, 1)),
        optim=OptimConfig(learning_rate=1e-4),
        data=DataConfig(dataset="synthetic", image_size=(H, W),
                        gt_size=(H, W), batch_size=BATCH),
        mesh=MeshConfig(),  # pure data-parallel: data axis spans all hosts
        train=TrainConfig(seed=0),
    )
    ds = SyntheticData(cfg.data)
    model = build_model("flownet_s", width_mult=0.25)
    # SGD, not Adam: the test asserts cross-runtime loss EQUALITY, and
    # Adam's eps-scaled normalization amplifies the tiny collective
    # reassociation differences between the distributed and single-
    # process runtimes into O(lr) param drift; SGD is linear in grad
    tx = optax.sgd(cfg.optim.learning_rate)

    def new_state():
        return create_train_state(model, jnp.zeros((BATCH, H, W, 6)), tx,
                                  seed=0)

    return cfg, ds, model, new_state


def main() -> None:
    addr, nproc, pid, outdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])

    from deepof_tpu.core.hostmesh import force_cpu_devices

    # 2 virtual devices per worker (4 global): the DCN-path claims (row
    # slicing, put_global, cross-process collectives, allgathered eval)
    # are device-count-free, and halving the SPMD partitions on this
    # single-core host roughly halves compile+execute wall-clock — the
    # r04 suite-load flake margin (VERDICT r04 weak #6)
    force_cpu_devices(2)
    import jax

    jax.distributed.initialize(
        coordinator_address=addr, num_processes=nproc, process_id=pid,
        initialization_timeout=600)
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.local_devices()) == 2
    assert len(jax.devices()) == 2 * nproc

    import numpy as np
    import jax.numpy as jnp
    from jax import flatten_util

    from deepof_tpu.parallel.mesh import (
        batch_sharding,
        build_mesh,
        local_batch_rows,
        process_seed,
        put_global,
        put_global_from_full,
        stacked_batch_sharding,
    )
    from deepof_tpu.train.step import make_eval_fn, make_train_step

    cfg, ds, model, new_state = make_setup()
    mesh = build_mesh(cfg.mesh)
    state = new_state()
    step = make_train_step(model, cfg, ds.mean, mesh)

    n_local, rows = local_batch_rows(mesh, BATCH)
    results = {
        "rows": rows,
        "n_local": n_local,
        "process_seed": process_seed(mesh, 123),
    }

    def local_global(iteration):
        gb = ds.sample_train(BATCH, iteration=iteration)
        lb = {key: np.asarray(v)[rows] for key, v in gb.items()}
        return put_global(lb, batch_sharding(mesh))

    # --- AOT-compile EVERY collective program, then rendezvous, then
    # execute. gloo's context init has a hard 30s kv-store deadline that
    # fires at the FIRST collective *execution*; per-worker compile-time
    # skew (AOT-cache hit vs miss, scheduler contention) routinely
    # exceeds it (the r05 full-suite flake). Compiling all three legs
    # first and crossing a coordination-service barrier (10 min budget,
    # no gloo involved) brings both workers to the gloo key exchange
    # within milliseconds of each other.
    b = local_global(0)
    step_exec = step.lower(state, b).compile()

    kcfg = cfg.replace(train=dataclasses.replace(cfg.train, steps_per_call=2))
    kstate = new_state()
    kstep = make_train_step(model, kcfg, ds.mean, mesh)
    g0 = ds.sample_train(BATCH, iteration=0)
    g1 = ds.sample_train(BATCH, iteration=1)
    stacked = {key: np.stack([np.asarray(g0[key])[rows],
                              np.asarray(g1[key])[rows]]) for key in g0}
    kb = put_global(stacked, stacked_batch_sharding(mesh))
    kstep_exec = kstep.lower(kstate, kb).compile()

    from jax.experimental import multihost_utils

    eval_fn = make_eval_fn(model, cfg, ds.mean, mesh=mesh)
    vb = ds.sample_val(BATCH, 0)
    gvb = put_global_from_full(vb, mesh, batch_sharding(mesh))
    eval_exec = eval_fn.lower(state.params, gvb).compile()

    from jax._src import distributed

    distributed.global_state.client.wait_at_barrier(
        "mp_precollective", timeout_in_ms=600_000)

    # 2 train steps: each process loads ONLY its own rows of the
    # (deterministic) global batch; put_global assembles without any host
    # holding the full batch.
    for k in range(2):
        if k > 0:
            b = local_global(k)
        state, m = step_exec(state, b)
        results[f"step{k}_total"] = float(jax.device_get(m["total"]))
        results[f"step{k}_gradnorm"] = float(jax.device_get(m["grad_norm"]))
        flat, _ = flatten_util.ravel_pytree(state.params)
        results[f"step{k}_param_checksum"] = float(
            jax.device_get(jnp.abs(flat).sum()))

    # steps_per_call=2: stacked [K, local_B, ...] leaves under
    # P(None, "data") via make_array_from_process_local_data (the
    # non-leading sharded axis layout).
    kstate, km = kstep_exec(kstate, kb)
    results["scan_totals"] = np.asarray(jax.device_get(km["total"])).tolist()
    # assembly diagnostics: the global array each host sees must be the
    # full val batch, byte-identical to the host-local copy
    gsrc = np.asarray(multihost_utils.process_allgather(gvb["source"],
                                                        tiled=True))
    results["val_src_assembled_ok"] = bool(
        np.array_equal(gsrc, np.asarray(vb["source"])))
    # eval with the UNTRAINED params isolates batch assembly from any
    # cross-runtime optimizer drift
    out0 = eval_exec(new_state().params, gvb)
    results["eval_init_total"] = float(np.asarray(
        multihost_utils.process_allgather(out0["total"], tiled=True)).ravel()[0])
    out = eval_exec(state.params, gvb)
    gathered = {k2: np.asarray(multihost_utils.process_allgather(v, tiled=True))
                for k2, v in out.items()}
    results["eval_total"] = float(gathered["total"].ravel()[0])
    results["eval_flow_shape"] = list(gathered["flow"].shape)
    results["eval_flow_sum"] = float(np.abs(gathered["flow"]).sum())

    # Atomic publish BEFORE the distributed shutdown: the coordination
    # service's shutdown barrier can fail under scheduler contention
    # (observed r05: "Shutdown barrier has failed" -> FATAL after all
    # work completed). A complete results file is the worker's success
    # criterion; the parent treats a teardown-phase crash after both
    # files exist as a pass.
    tmp = os.path.join(outdir, f"proc{pid}.json.tmp")
    with open(tmp, "w") as f:
        json.dump(results, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(outdir, f"proc{pid}.json"))
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
