"""Pallas correlation kernel vs the XLA/numpy oracles (interpret mode on the
CPU mesh; the same kernel lowers to Mosaic on TPU). Golden-test pattern per
SURVEY.md §4.2: accelerated kernel vs reference implementation."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepof_tpu.ops.corr import correlation, correlation_oracle
from deepof_tpu.ops.pallas.corr import correlation_pallas


@pytest.fixture
def feats(rng):
    f1 = rng.randn(2, 12, 16, 8).astype(np.float32)
    f2 = rng.randn(2, 12, 16, 8).astype(np.float32)
    return f1, f2


def test_pallas_corr_matches_oracle(feats):
    f1, f2 = feats
    got = np.asarray(correlation_pallas(
        jnp.asarray(f1), jnp.asarray(f2), 2, 1, 4, True))
    want = correlation_oracle(f1, f2, max_disp=2, stride=1)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_pallas_corr_stride_and_ragged_height(feats):
    f1, f2 = feats
    f1, f2 = f1[:, :11], f2[:, :11]  # H=11 not divisible by tile_h=4
    got = np.asarray(correlation_pallas(
        jnp.asarray(f1), jnp.asarray(f2), 4, 2, 4, True))
    want = correlation_oracle(f1, f2, max_disp=4, stride=2)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_pallas_corr_grad_matches_xla(feats):
    f1, f2 = feats
    f1, f2 = jnp.asarray(f1[:1, :8, :8]), jnp.asarray(f2[:1, :8, :8])

    def loss_pallas(a, b):
        return jnp.sum(correlation_pallas(a, b, 2, 1, 4, True) ** 2)

    def loss_xla(a, b):
        return jnp.sum(correlation(a, b, max_disp=2, stride=1) ** 2)

    g1p, g2p = jax.grad(loss_pallas, argnums=(0, 1))(f1, f2)
    g1x, g2x = jax.grad(loss_xla, argnums=(0, 1))(f1, f2)
    np.testing.assert_allclose(np.asarray(g1p), np.asarray(g1x), atol=1e-4)
    np.testing.assert_allclose(np.asarray(g2p), np.asarray(g2x), atol=1e-4)


def test_pallas_corr_sharded_over_batch_mesh(feats):
    """custom_partitioning rule: under pjit with the batch sharded over the
    8-device mesh, the kernel runs per-shard (GSPMD must not all-gather or
    choke on the opaque pallas_call) and matches the oracle."""
    from deepof_tpu.parallel.mesh import batch_sharding, local_mesh

    f1, f2 = feats
    f1 = np.concatenate([f1] * 4)  # batch 8 over 8 devices
    f2 = np.concatenate([f2] * 4)
    mesh = local_mesh()
    sharding = batch_sharding(mesh)

    fn = jax.jit(lambda a, b: correlation_pallas(a, b, 2, 1, 4, True),
                 in_shardings=(sharding, sharding))
    got = fn(jax.device_put(jnp.asarray(f1), sharding),
             jax.device_put(jnp.asarray(f2), sharding))
    assert got.sharding.spec[0] == "data"
    want = correlation_oracle(f1, f2, max_disp=2, stride=1)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


def test_pallas_corr_bf16_inputs(feats):
    f1, f2 = feats
    got = correlation_pallas(
        jnp.asarray(f1, jnp.bfloat16), jnp.asarray(f2, jnp.bfloat16),
        2, 1, 4, True)
    # f32 accumulation inside, but input dtype out (same as the XLA sweep,
    # so `auto` dispatch is not backend-dependent under bf16 compute)
    assert got.dtype == jnp.bfloat16
    want = correlation_oracle(f1, f2, max_disp=2, stride=1)
    np.testing.assert_allclose(np.asarray(got, np.float32), want,
                               atol=0.05, rtol=0.05)
