"""Serving-subsystem tests (DESIGN.md "Serving").

Fast tier: the batcher contract is pinned with a deterministic fake
timed executor (no XLA) — coalescing, timeout flush, bucket routing,
poison isolation (chaos), the >=3x dynamic-batching throughput
acceptance with bit-identical responses, the HTTP frontend, offline
mode, serve_bench schema, and analyze/tail surfacing of serve_*
counters. The bucket round-trip / serial-parity pins run the REAL
engine path (jit -> AOT executable) over a tiny elementwise model, so
they stay fast while exercising the true dispatch plumbing.

Slow tier: `warmup --serve` zero-recompile acceptance with a real
flownet_s — first requests across all buckets load executables from the
persistent cache (miss counter pinned at 0).
"""

import dataclasses
import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from deepof_tpu.core.config import get_config
from deepof_tpu.serve.buckets import pick_bucket, resolve_buckets
from deepof_tpu.serve.engine import InferenceEngine, ServeError


# ----------------------------------------------------------- helpers


def _cfg(max_batch=4, timeout_ms=400.0, buckets=(), image_size=(32, 64),
         log_dir="/tmp/deepof_serve_test", **serve_kw):
    cfg = get_config("flyingchairs")
    return cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=image_size, gt_size=image_size),
        serve=dataclasses.replace(cfg.serve, max_batch=max_batch,
                                  batch_timeout_ms=timeout_ms,
                                  buckets=buckets, **serve_kw),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6), log_dir=log_dir))


class _FakeForward:
    """Deterministic timed executor: per-dispatch sleep (batch-size
    independent — a latency-bound device), flow = channel difference of
    the preprocessed pair. Counts dispatches and occupancies."""

    def __init__(self, exec_s=0.0):
        self.exec_s = exec_s
        self.dispatches = 0
        self.occupancies = []
        self.lock = threading.Lock()

    def __call__(self, bucket, x):
        with self.lock:
            self.dispatches += 1
            # padded rows are all-zero; occupancy = rows with any signal
            self.occupancies.append(int(np.sum(np.abs(x).sum(axis=(1, 2, 3))
                                               > 0)))
        if self.exec_s > 0:
            time.sleep(self.exec_s)
        return np.stack([x[..., 0] - x[..., 3], x[..., 1] - x[..., 4]],
                        axis=-1).astype(np.float32)


def _img(rng, hw=(48, 96), lo=1, hi=255):
    # lo >= 1 keeps preprocessed rows nonzero (synthetic mean is 0), so
    # the fake executor's occupancy probe can tell live rows from padding
    return rng.randint(lo, hi, (*hw, 3), dtype=np.uint8)


def _pairs(rng, n, hw=(48, 96)):
    return [(_img(rng, hw), _img(rng, hw)) for _ in range(n)]


class _TinyModel:
    """Elementwise duck-typed model for the REAL engine path (jit ->
    lower -> AOT compile): flow = k * (prev - next) on the first two
    channels. Elementwise ops make per-sample outputs bitwise
    independent of batch size — the property the serial-parity pin
    relies on without paying a conv-net compile."""

    flow_scales = (0.5,)

    def apply(self, variables, x):
        import jax.numpy as jnp

        k = variables["params"]["k"]
        return [jnp.stack([x[..., 0] - x[..., 3], x[..., 1] - x[..., 4]],
                          axis=-1) * k]


def _tiny_model_params():
    return _TinyModel(), {"k": np.float32(2.0)}


# ------------------------------------------------------------ buckets


def test_bucket_ladder_resolution_and_pick():
    cfg = _cfg(buckets=((64, 64), (32, 64), (64, 64)))
    ladder = resolve_buckets(cfg)
    assert ladder == ((32, 64), (64, 64))  # deduped, area-sorted
    assert pick_bucket((30, 60), ladder) == (32, 64)  # smallest cover
    assert pick_bucket((50, 60), ladder) == (64, 64)
    assert pick_bucket((500, 900), ladder) == (64, 64)  # nothing covers: max
    # default ladder = the eval resolution (pre-serve behavior)
    assert resolve_buckets(_cfg(buckets=())) == ((32, 64),)


# ------------------------------------------------------------ batcher


def test_batcher_coalesces_queue_into_few_dispatches(rng):
    """N queued requests execute in <= ceil(N/max_batch) dispatches."""
    fake = _FakeForward()
    with InferenceEngine(_cfg(max_batch=4, timeout_ms=500.0),
                         forward_fn=fake) as eng:
        futs = [eng.submit(p, n) for p, n in _pairs(rng, 12)]
        res = [f.result(timeout=30) for f in futs]
    assert fake.dispatches <= 3  # == ceil(12/4)
    assert fake.occupancies == [4, 4, 4]
    stats = eng.stats()
    assert stats["serve_responses"] == 12
    assert stats["serve_errors"] == 0
    assert stats["serve_occupancy_mean"] == 4.0
    for r in res:
        assert r["flow"].shape == (48, 96, 2)
        assert np.isfinite(r["flow"]).all()


def test_timeout_flushes_partial_batch(rng):
    """Fewer than max_batch pending: the oldest request's deadline
    flushes a partial batch instead of waiting forever."""
    fake = _FakeForward()
    with InferenceEngine(_cfg(max_batch=8, timeout_ms=80.0),
                         forward_fn=fake) as eng:
        futs = [eng.submit(p, n) for p, n in _pairs(rng, 3)]
        t0 = time.monotonic()
        for f in futs:
            f.result(timeout=30)
        waited = time.monotonic() - t0
    assert fake.dispatches == 1
    assert fake.occupancies == [3]
    assert waited < 10.0  # flushed by deadline, not by a full batch
    assert eng.stats()["serve_timeout_flushes"] >= 1


def test_bucket_split_routes_mixed_shapes(rng):
    """Requests mapping to different buckets never share a dispatch;
    a bucket change flushes the open batch and is counted."""
    fake = _FakeForward()
    cfg = _cfg(max_batch=8, timeout_ms=60.0, buckets=((32, 64), (64, 64)))
    with InferenceEngine(cfg, forward_fn=fake) as eng:
        futs = []
        for i in range(6):
            hw = (30, 60) if i % 2 == 0 else (60, 60)
            p, n = _img(rng, hw), _img(rng, hw)
            futs.append((hw, eng.submit(p, n)))
        for hw, f in futs:
            r = f.result(timeout=30)
            assert r["flow"].shape == (*hw, 2)
            assert r["bucket"] == ((32, 64) if hw == (30, 60) else (64, 64))
    assert eng.stats()["serve_bucket_splits"] >= 1
    # every dispatch was single-bucket: occupancies sum to request count
    assert sum(fake.occupancies) == 6


@pytest.mark.chaos
def test_poisoned_request_fails_alone(rng, tmp_path):
    """A corrupt/undecodable input yields a structured per-request error;
    batchmates succeed, the engine keeps serving, the watchdog stays
    quiet (acceptance criterion)."""
    from deepof_tpu.obs.heartbeat import Heartbeat

    corrupt = str(tmp_path / "corrupt.png")
    with open(corrupt, "wb") as f:
        f.write(b"not a png at all")
    missing = str(tmp_path / "nope.png")
    good = str(tmp_path / "good.png")
    cv2.imwrite(good, _img(rng))

    fake = _FakeForward()
    hb_path = str(tmp_path / "heartbeat.json")
    with InferenceEngine(_cfg(max_batch=4, timeout_ms=60.0),
                         forward_fn=fake) as eng:
        hb = Heartbeat(hb_path, period_s=0.05, sample=eng.heartbeat_sample)
        eng.flush_hook = hb.beat
        try:
            f_ok1 = eng.submit(good, good)
            f_bad = eng.submit(corrupt, good)
            f_missing = eng.submit(good, missing)
            f_ok2 = eng.submit(good, good)

            assert f_ok1.result(timeout=30)["flow"].shape == (48, 96, 2)
            assert f_ok2.result(timeout=30)["flow"].shape == (48, 96, 2)
            for bad in (f_bad, f_missing):
                with pytest.raises(ServeError) as ei:
                    bad.result(timeout=30)
                assert ei.value.code == "bad_input"
                assert ei.value.payload()["error"] == "bad_input"
            # the engine is not wedged: it still serves after the poison
            assert eng.submit(good, good).result(timeout=30)["request_id"] > 0
            time.sleep(0.15)  # let a heartbeat period elapse
            with open(hb_path) as f:
                beat = json.load(f)
            assert beat["wedged"] is False
            assert beat["serve_errors"] == 2
            assert beat["serve_responses"] == 3
        finally:
            hb.close()
    stats = eng.stats()
    assert stats["serve_errors"] == 2 and stats["serve_responses"] == 3


def test_dispatch_failure_fails_flush_not_engine(rng):
    """An executor crash fails that flush's requests with a structured
    dispatch_failed — and the batcher keeps serving the next ones."""
    calls = {"n": 0}

    def flaky(bucket, x):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected device fault")
        return np.zeros((*x.shape[:3], 2), np.float32)

    with InferenceEngine(_cfg(max_batch=2, timeout_ms=30.0),
                         forward_fn=flaky) as eng:
        f1 = eng.submit(*_pairs(rng, 1)[0])
        with pytest.raises(ServeError) as ei:
            f1.result(timeout=30)
        assert ei.value.code == "dispatch_failed"
        f2 = eng.submit(*_pairs(rng, 1)[0])
        assert f2.result(timeout=30)["flow"].shape == (48, 96, 2)
    assert eng.stats()["serve_dispatch_failures"] == 1


def test_submit_after_close_fails_structured(rng):
    eng = InferenceEngine(_cfg(), forward_fn=_FakeForward())
    eng.close()
    with pytest.raises(ServeError) as ei:
        eng.submit(*_pairs(rng, 1)[0]).result(timeout=5)
    assert ei.value.code == "engine_closed"


# ------------------------------------------- throughput acceptance pin


def _timed_run(cfg, pairs, gap_s, exec_s):
    fake = _FakeForward(exec_s=exec_s)
    flows = []
    t0 = time.perf_counter()
    with InferenceEngine(cfg, forward_fn=fake) as eng:
        futs = []
        for p, n in pairs:
            futs.append(eng.submit(p, n))
            time.sleep(gap_s)
        flows = [f.result(timeout=60)["flow"] for f in futs]
    return time.perf_counter() - t0, fake, flows


def test_dynamic_batcher_3x_throughput_and_bit_identical(rng):
    """The acceptance pin: with an injected per-request arrival gap and
    max_batch=8, the dynamic batcher sustains >=3x the serial per-pair
    path's throughput on identical inputs, and every response is
    bit-identical to the serial path's output (padded fixed-occupancy
    dispatch makes responses batch-independent).

    Wall-clock ratios on this 1-core host can be disturbed by scheduler
    spikes (see test_input_pipeline); bit-identity is asserted strictly
    every attempt, the ratio gets one bounded retry."""
    pairs = _pairs(rng, 16)
    exec_s, gap_s = 0.03, 0.001
    batched_cfg = _cfg(max_batch=8, timeout_ms=15.0)
    serial_cfg = _cfg(max_batch=1, timeout_ms=15.0)

    for attempt in range(2):
        wall_b, fake_b, flows_b = _timed_run(batched_cfg, pairs, gap_s, exec_s)
        wall_s, fake_s, flows_s = _timed_run(serial_cfg, pairs, gap_s, exec_s)

        # bitwise parity, strict on every attempt
        assert len(flows_b) == len(flows_s) == 16
        for fb, fs in zip(flows_b, flows_s):
            np.testing.assert_array_equal(fb, fs)
        # serial = one dispatch per pair; batched amortizes
        assert fake_s.dispatches == 16
        assert fake_b.dispatches <= 6
        ratio = wall_s / wall_b
        if ratio >= 3.0:
            break
    assert ratio >= 3.0, (
        f"dynamic batcher speedup {ratio:.2f}x < 3x "
        f"(batched {wall_b:.3f}s/{fake_b.dispatches} dispatches, "
        f"serial {wall_s:.3f}s/{fake_s.dispatches} dispatches)")


# ----------------------------- real engine path: serial parity + units


def test_engine_batched_bit_identical_to_serial_predict_pairs(rng, tmp_path):
    """predict_pairs (rewired over the engine) at serve.max_batch=1 IS
    the serial per-pair path; the batched engine's .flo outputs must be
    byte-identical at the same bucket — through the REAL jit/AOT
    dispatch plumbing (tiny elementwise model)."""
    from deepof_tpu.predict import predict_pairs

    paths = []
    for i in range(5):
        p, n = str(tmp_path / f"p{i}.png"), str(tmp_path / f"n{i}.png")
        cv2.imwrite(p, _img(rng))
        cv2.imwrite(n, _img(rng))
        paths.append((p, n))

    mp = _tiny_model_params()
    out_serial = str(tmp_path / "serial")
    out_batched = str(tmp_path / "batched")
    w_serial = predict_pairs(_cfg(max_batch=1, timeout_ms=5.0), paths,
                             out_serial, model_params=mp, write_png=False)
    w_batched = predict_pairs(_cfg(max_batch=4, timeout_ms=200.0), paths,
                              out_batched, model_params=mp, write_png=False)
    assert len(w_serial) == len(w_batched) == 5
    for a, b in zip(w_serial, w_batched):
        assert os.path.basename(a) == os.path.basename(b)
        with open(a, "rb") as fa, open(b, "rb") as fb:
            assert fa.read() == fb.read(), f"{a} differs from {b}"


def test_bucket_roundtrip_rescales_vectors_to_native_units():
    """Constant-motion pair through a bucket: the response's u/v are in
    NATIVE pixel units (bucket flow * amplifier * native/bucket)."""
    prev = np.full((48, 96, 3), 60, np.uint8)
    nxt = np.full((48, 96, 3), 20, np.uint8)
    cfg = _cfg(max_batch=2, timeout_ms=5.0)  # bucket (32, 64)
    with InferenceEngine(cfg, model_params=_tiny_model_params()) as eng:
        r = eng.submit(prev, nxt).result(timeout=60)
    assert r["bucket"] == (32, 64)
    # model: (prev-next)/255 * k * flow_scale = (40/255) * 2 * 0.5
    base = (40.0 / 255.0)
    np.testing.assert_allclose(r["flow"][..., 0], base * 96 / 64, rtol=1e-5)
    np.testing.assert_allclose(r["flow"][..., 1], base * 48 / 32, rtol=1e-5)


# ------------------------------------------------------ HTTP frontend


def _start_http(cfg, engine):
    from conftest import wait_for_listen

    from deepof_tpu.serve.server import build_server

    httpd = build_server(cfg, engine)  # binds port 0: race-free ephemeral
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="test-httpd")
    t.start()
    port = httpd.server_address[1]
    wait_for_listen("127.0.0.1", port, timeout_s=20.0)
    return httpd, port


def test_http_server_flow_and_health(rng):
    import base64
    import http.client

    fake = _FakeForward()
    cfg = _cfg(max_batch=4, timeout_ms=20.0, host="127.0.0.1", port=0)
    with InferenceEngine(cfg, forward_fn=fake) as eng:
        httpd, port = _start_http(cfg, eng)
        try:
            def b64png(img):
                ok, buf = cv2.imencode(".png", img)
                assert ok
                return base64.b64encode(buf.tobytes()).decode()

            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            body = json.dumps({"prev": b64png(_img(rng)),
                               "next": b64png(_img(rng))})
            conn.request("POST", "/v1/flow", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            payload = json.loads(resp.read())
            assert payload["shape"] == [48, 96, 2]
            flow = np.frombuffer(base64.b64decode(payload["flow_b64"]),
                                 "<f4").reshape(48, 96, 2)
            assert np.isfinite(flow).all()

            # structured client error: invalid base64 -> 400 + code
            conn.request("POST", "/v1/flow",
                         json.dumps({"prev": "!!!", "next": "!!!"}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 400
            assert json.loads(resp.read())["error"] == "bad_request"

            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            assert resp.status == 200
            health = json.loads(resp.read())
            assert health["serve_responses"] >= 1
            assert health["serve_max_batch"] == 4
            conn.close()
        finally:
            httpd.shutdown()
            httpd.server_close()


# ------------------------------------------------------- offline mode


def test_offline_directory_mode_with_corrupt_frame(rng, tmp_path, capsys):
    """Offline sweep over a frame directory via the pipeline worker
    pool: valid consecutive pairs produce .flo files, a corrupt frame
    fails only its pairs (structured), the summary reports both."""
    from deepof_tpu.serve.server import run_offline

    frames = tmp_path / "frames"
    frames.mkdir()
    for i in range(5):
        cv2.imwrite(str(frames / f"f{i:03d}.png"), _img(rng, (40, 80)))
    with open(frames / "f002.png", "wb") as f:
        f.write(b"garbage bytes")  # corrupts pairs (1,2) and (2,3)

    cfg = _cfg(max_batch=4, timeout_ms=50.0, workers=2,
               log_dir=str(tmp_path / "run"))
    out_dir = str(tmp_path / "out")
    with InferenceEngine(cfg, forward_fn=_FakeForward()) as eng:
        res = run_offline(cfg, str(frames), out_dir, write_png=False,
                          engine=eng)
    assert res["pairs"] == 4
    assert res["errors"] == 2
    flos = sorted(os.listdir(out_dir))
    assert flos == ["0000_f000_flow.flo", "0003_f003_flow.flo"]
    # structured per-request error lines were printed
    err_lines = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()
                 if "bad_input" in ln]
    assert len(err_lines) == 2
    # the shutdown summary landed in metrics.jsonl for analyze
    recs = [json.loads(ln)
            for ln in open(os.path.join(cfg.train.log_dir, "metrics.jsonl"))]
    assert any(r.get("kind") == "serve" for r in recs)


# ---------------------------------------------- serve_bench + analyze


def _load_serve_bench():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "serve_bench.py")
    spec = importlib.util.spec_from_file_location("serve_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serve_bench_schema_smoke():
    sb = _load_serve_bench()
    res = sb.serve_bench(requests=6, gap_ms=0.0, max_batch=4,
                         timeout_ms=10.0, exec_ms=1.0, serial=True)
    for key in sb.REQUIRED_KEYS:
        assert key in res, f"serve_bench result missing {key!r}"
    assert res["mode"] == "fake"
    assert res["requests"] == 6 and res["errors"] == 0
    assert res["dispatches"] >= 1
    assert res["requests_per_s"] > 0
    assert "speedup_vs_serial" in res
    json.dumps(res)  # JSON-line contract like bench.py


def test_analyze_and_tail_surface_serve_counters(tmp_path):
    from deepof_tpu.analyze import summarize, tail_summary

    log_dir = str(tmp_path)
    serve_rec = {"kind": "serve", "step": 0, "time": time.time(),
                 "serve_requests": 20, "serve_responses": 18,
                 "serve_errors": 2, "serve_batches": 5,
                 "serve_latency_p50_ms": 12.5}
    with open(os.path.join(log_dir, "metrics.jsonl"), "w") as f:
        f.write(json.dumps(serve_rec) + "\n")
    with open(os.path.join(log_dir, "heartbeat.json"), "w") as f:
        json.dump({"time": time.time(), "step": 18, "wedged": False,
                   "serve_requests": 21, "serve_queue_depth": 1,
                   "serve_requests_per_s": 3.2}, f)

    s = summarize([serve_rec])
    assert s["serve"]["requests"] == 20 and s["serve"]["errors"] == 2

    t = tail_summary(log_dir)
    # heartbeat (fresher) wins for the live block
    assert t["serve"]["requests"] == 21
    assert t["serve"]["queue_depth"] == 1
    assert t["heartbeat"]["wedged"] is False


# ------------------------------------------------- slow: warm ladder


@pytest.mark.slow
def test_warmup_serve_then_first_requests_compile_nothing(tmp_path):
    """`warmup --serve` acceptance across the FULL bucket x tier ladder
    (two buckets x three precision tiers): after the ladder is AOT-
    compiled into the persistent cache, a cold engine's FIRST request on
    every (bucket, tier) pair loads its executable (zero recompiles) —
    asserted against warmup's per-pair persisted/skipped REPORT, not raw
    cache deltas: a pair whose compile sat under jax's 1 s persistence
    floor legitimately recompiles in the next process (flownet_s
    fwd-only does this intermittently — the pre-r06 flake), while every
    pair the report calls persisted must hit."""
    import jax
    import jax.numpy as jnp

    from deepof_tpu.serve.engine import build_serve_model
    from deepof_tpu.train import warmup

    prev = jax.config.jax_compilation_cache_dir
    try:
        buckets = ((64, 64), (64, 128))
        tiers = ("f32", "bf16", "int8")
        cfg = _cfg(max_batch=2, timeout_ms=40.0, buckets=buckets,
                   image_size=(64, 64), log_dir=str(tmp_path / "run"),
                   precisions=tiers)
        # the flagship model: its forward compiles comfortably above
        # jax's 1 s persistence floor on this host (the floor must stay
        # at 1 s per the hostmesh segfault note), so the report is
        # expected to say persisted — but the assertions below derive
        # from the report either way
        cfg = cfg.replace(model="inception_v3", width_mult=1.0,
                          train=dataclasses.replace(
                              cfg.train, compile_cache=True,
                              compile_cache_dir=str(tmp_path / "xla_cache")))

        r1 = warmup.warmup_serve(cfg)
        ladder = len(buckets) * len(tiers)
        assert [(tuple(b["bucket"]), b["tier"]) for b in r1["buckets"]] \
            == [(b, t) for b in buckets for t in tiers]
        assert r1["cache"]["misses"] >= ladder
        # the report is self-consistent and filesystem-backed
        assert r1["persisted_buckets"] + r1["skipped_buckets"] == ladder
        for b in r1["buckets"]:
            assert b["status"] in ("persisted", "hit", "skipped")
            assert b["persisted"] == (b["status"] != "skipped")
        if r1["persisted_buckets"]:
            assert os.listdir(tmp_path / "xla_cache")
        persisted = {(tuple(b["bucket"]), b["tier"]) for b in r1["buckets"]
                     if b["persisted"]}
        if not persisted:
            pytest.skip("no (bucket, tier) cleared the 1 s persistence "
                        "floor on this host — nothing for the "
                        "zero-recompile pin to assert")

        jax.clear_caches()  # simulate a cold serving process
        model = build_serve_model(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 64, 64, 6)))["params"]
        rng = np.random.RandomState(0)
        with InferenceEngine(cfg, model_params=(model, params)) as eng:
            with warmup.cache_delta() as d:
                futs = [(hw, tier, eng.submit(_img(rng, hw), _img(rng, hw),
                                              precision=tier))
                        for hw in ((60, 60), (60, 120)) for tier in tiers]
                res = [(hw, tier, f.result(timeout=600))
                       for hw, tier, f in futs]
        for hw, tier, r in res:
            assert r["bucket"] == ((64, 64) if hw == (60, 60)
                                   else (64, 128))
            assert r["precision"] == tier
            assert np.isfinite(r["flow"]).all()
        delta = d.stats()
        assert delta["requests"] >= ladder  # counters are alive
        # report-driven pin: persisted pairs load, skipped pairs are
        # ALLOWED to recompile (and only they are)
        assert delta["hits"] >= len(persisted), \
            "a (bucket, tier) warmup reported persisted recompiled — " \
            "warmup_serve's lowering drifted from the engine's"
        assert delta["misses"] <= ladder - len(persisted), \
            f"more recompiles ({delta['misses']}) than skipped pairs " \
            f"({ladder - len(persisted)})"
    finally:
        warmup.enable_compile_cache(prev)
