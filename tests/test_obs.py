"""Observability layer (deepof_tpu/obs/): span tracer ring/schema/
thread-safety, heartbeat file + wedge watchdog, profiler step window,
non-finite-safe JSONL, and the slow-tier fit() acceptance pin (trace
timeline with >= 3 named threads, fresh heartbeat, telemetry fields).

Fast-tier discipline: pure host-side, no model compiles, no sleep
longer than ~100 ms (watchdog tests use sub-100 ms periods and
event-waits with generous timeouts that return early).
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from deepof_tpu.obs import trace as obs_trace
from deepof_tpu.obs.heartbeat import Heartbeat, dump_all_stacks
from deepof_tpu.obs.trace import NullTracer, Tracer
from deepof_tpu.train.metrics_log import MetricsLogger, ProfilerSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _strict_loads(text: str):
    """json.loads that REJECTS bare NaN/Infinity tokens (the strictness
    real parsers — jq, browsers, other languages — apply)."""

    def _no_const(name):
        raise ValueError(f"non-JSON constant {name!r}")

    return json.loads(text, parse_constant=_no_const)


# --------------------------------------------------------------- tracer

def test_tracer_span_schema(tmp_path):
    path = str(tmp_path / "trace.json")
    tr = Tracer(path=path, ring_size=128)
    with tr.span("dispatch", step=4):
        time.sleep(0.001)
    tr.instant("watchdog_wedge", age_s=1.5)
    assert tr.flush() == path

    payload = _strict_loads(open(path).read())
    events = payload["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in meta)
    thread_names = [e["args"]["name"] for e in meta
                    if e["name"] == "thread_name"]
    assert "MainThread" in thread_names
    spans = [e for e in events if e["ph"] == "X"]
    assert len(spans) == 1
    s = spans[0]
    assert s["name"] == "dispatch" and s["args"] == {"step": 4}
    assert isinstance(s["ts"], (int, float)) and isinstance(s["dur"],
                                                            (int, float))
    assert s["dur"] >= 1e3  # the 1 ms sleep, in microseconds
    assert any(e["ph"] == "i" and e["name"] == "watchdog_wedge"
               for e in events)


def test_tracer_ring_bound_and_thread_safety(tmp_path):
    """200 spans from 4 concurrent threads against a 64-event ring: no
    exception, <= 64 retained, every retained event well-formed, all
    writer threads named in the metadata."""
    tr = Tracer(path=str(tmp_path / "trace.json"), ring_size=64)
    n_per_thread = 50
    gate = threading.Barrier(4, timeout=10)

    def writer(k: int):
        gate.wait()  # all four alive at once => four distinct idents
        for i in range(n_per_thread):
            with tr.span(f"work-{k}", i=i):
                pass

    threads = [threading.Thread(target=writer, args=(k,),
                                name=f"writer-{k}") for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    payload = _strict_loads(open(tr.flush()).read())
    spans = [e for e in payload["traceEvents"] if e["ph"] == "X"]
    assert 0 < len(spans) <= 64  # ring bound held
    assert payload["otherData"]["dropped_spans"] == 4 * n_per_thread - len(
        spans)
    named = {e["args"]["name"] for e in payload["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {f"writer-{k}" for k in range(4)} <= named
    for s in spans:
        assert s["name"].startswith("work-") and s["dur"] >= 0


def test_module_level_tracer_install_uninstall(tmp_path):
    """span()/instant() are no-ops with nothing installed, record after
    install, and stop recording after uninstall."""
    assert isinstance(obs_trace.current(), NullTracer)
    with obs_trace.span("ignored"):
        pass  # must not raise and must not record anywhere
    tr = obs_trace.install(Tracer(path=str(tmp_path / "t.json")))
    try:
        assert obs_trace.current() is tr
        with obs_trace.span("seen"):
            pass
    finally:
        obs_trace.uninstall()
    with obs_trace.span("after"):
        pass
    names = [e["name"] for e in tr.events() if e["ph"] == "X"]
    assert names == ["seen"]
    assert obs_trace.flush_current() is None  # null tracer again


# ------------------------------------------------------------ heartbeat

def test_heartbeat_file_schema_and_atomicity(tmp_path):
    path = str(tmp_path / "heartbeat.json")
    hb = Heartbeat(path, period_s=0.05, watchdog_min_s=60.0,
                   sample=lambda: {"queue_depth": 3})
    try:
        deadline = time.monotonic() + 5.0
        seen = 0
        rec = None
        while time.monotonic() < deadline and seen < 20:
            hb.beat(seen + 1)
            if os.path.exists(path):
                # atomic rewrite: EVERY read parses — no torn files
                rec = _strict_loads(open(path).read())
                seen += 1
            time.sleep(0.01)
        assert rec is not None, "heartbeat never wrote its file"
        for key in ("time", "pid", "step", "beats", "last_step_age_s",
                    "step_time_median_s", "wedged", "wedges", "rss_bytes",
                    "dev_mem_bytes_in_use", "dev_mem_peak_bytes",
                    "queue_depth"):
            assert key in rec, key
        assert rec["wedged"] is False and rec["wedges"] == 0
        assert rec["queue_depth"] == 3  # sample callback merged in
        assert rec["rss_bytes"] is None or rec["rss_bytes"] > 0
    finally:
        hb.close()
    # close() writes a final fresh record
    final = _strict_loads(open(path).read())
    assert time.time() - final["time"] < 5.0
    assert final["step"] == rec["step"] or final["step"] >= 1


def test_watchdog_fires_on_wedge_and_dumps_stacks(tmp_path):
    """The acceptance pin: steps stop completing -> within the
    configured factor the watchdog logs every thread's stack (naming the
    wedged thread) and flushes the trace ring."""
    release = threading.Event()

    def stuck():
        release.wait(timeout=30)

    wedged_thread = threading.Thread(target=stuck, name="wedged-fetcher",
                                     daemon=True)
    wedged_thread.start()

    tracer = Tracer(path=str(tmp_path / "trace.json"), ring_size=64)
    with tracer.span("pre-wedge"):
        pass
    logs: list = []
    fired = threading.Event()
    hb = Heartbeat(str(tmp_path / "heartbeat.json"), period_s=0.05,
                   watchdog_factor=3.0, watchdog_min_s=0.05,
                   log=lambda step, msg: logs.append((step, msg)),
                   tracer=tracer, on_wedge=lambda dump: fired.set())
    try:
        for i in range(4):  # arm with ~instant steps (median ~ms)
            hb.beat(i + 1)
        # ... then no step completes: threshold = max(3 x median, 50 ms)
        assert fired.wait(timeout=10.0), "watchdog never fired"
        step, msg = logs[0]
        assert step == 4
        assert "WATCHDOG" in msg
        assert "wedged-fetcher" in msg  # the stack dump names the thread
        assert "MainThread" in msg
        assert "release.wait" in msg  # ... and where it is stuck
        # trace ring flushed on the trigger, with the wedge marker
        payload = _strict_loads(open(tracer.path).read())
        names = [e["name"] for e in payload["traceEvents"]]
        assert "watchdog_wedge" in names and "pre-wedge" in names
        # one firing per stall (no log spam while still wedged)
        time.sleep(0.12)  # >= 2 poll periods
        assert sum(1 for _, m in logs if "WATCHDOG" in m) == 1
        hb_rec = _strict_loads(
            open(str(tmp_path / "heartbeat.json")).read())
        assert hb_rec["wedged"] is True and hb_rec["wedges"] == 1
        # a resumed step re-arms
        hb.beat(5)
        assert _strict_loads(
            open(tracer.path).read()) is not None  # file still valid
    finally:
        release.set()
        hb.close()


def test_dump_all_stacks_names_threads():
    dump = dump_all_stacks()
    assert "MainThread" in dump
    assert "test_dump_all_stacks_names_threads" in dump  # caller frame


# ---------------------------------------------------- non-finite JSONL

def test_metrics_logger_serializes_nonfinite_as_null(tmp_path):
    log = MetricsLogger(str(tmp_path), echo=False)
    log.log("train", 1, loss=float("nan"), grad_norm=float("inf"),
            scales=[1.0, float("-inf"), 2.0], ok=3.5, note=None)
    log.close()
    lines = open(os.path.join(str(tmp_path), "metrics.jsonl")).readlines()
    assert len(lines) == 1
    rec = _strict_loads(lines[0])  # bare NaN/Infinity would fail here
    assert rec["loss"] is None and rec["grad_norm"] is None
    assert rec["scales"] == [1.0, None, 2.0]
    assert rec["ok"] == 3.5 and rec["note"] is None


# ------------------------------------------------- profiler step window

def test_profiler_session_step_window(tmp_path, monkeypatch):
    import jax

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop", None)))

    p = ProfilerSession(str(tmp_path), steps=(2, 4))
    assert p.enabled  # a window implies enabled
    p.maybe_start()  # loop entry: window mode must NOT start here
    assert calls == []
    p.observe(0)
    p.observe(2)  # window opens
    assert [c[0] for c in calls] == ["start"]
    p.observe(3)
    p.observe(4)  # window closes
    assert [c[0] for c in calls] == ["start", "stop"]
    p.observe(6)  # never restarts
    p.maybe_stop()  # teardown: already stopped, must not double-stop
    assert [c[0] for c in calls] == ["start", "stop"]

    # stride-proof: steps_per_call=8 jumps the observed gsteps right
    # over a narrow window — the dispatch CONTAINING it must be captured
    calls.clear()
    s = ProfilerSession(str(tmp_path), steps=(100, 104))
    s.observe(96, steps_per_call=8)  # next dispatch covers 97..104
    assert [c[0] for c in calls] == ["start"]
    s.observe(104, steps_per_call=8)
    assert [c[0] for c in calls] == ["start", "stop"]

    # whole-run mode unchanged
    calls.clear()
    q = ProfilerSession(str(tmp_path), enabled=True)
    q.maybe_start()
    q.observe(100)  # no-op without a window
    q.maybe_stop()
    assert [c[0] for c in calls] == ["start", "stop"]

    with pytest.raises(ValueError):
        ProfilerSession(str(tmp_path), steps=(4, 2))
    with pytest.raises(ValueError):
        ProfilerSession(str(tmp_path), steps=(-1, 2))


# ------------------------------------------------------ trace_summary

def test_trace_summary_tool(tmp_path):
    tr = Tracer(path=str(tmp_path / "trace.json"))
    for i in range(3):
        with tr.span("dispatch", step=i):
            pass
    with tr.span("fetch"):
        time.sleep(0.002)
    tr.flush()
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_summary.py"),
         str(tmp_path / "trace.json"), "--top", "2"],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr[-500:]
    assert "dispatch" in res.stdout and "fetch" in res.stdout
    assert "longest spans" in res.stdout


# ---------------------------------------------- fit() acceptance (slow)

@pytest.mark.slow
def test_fit_writes_trace_heartbeat_and_telemetry(tmp_path):
    """The ISSUE acceptance: a cpu fit() with tracing on produces a
    strict-JSON Chrome trace with >= 3 distinct named threads and
    overlapping spans, a fresh heartbeat.json at exit, and model_tflops
    + device-memory fields in periodic train records.

    Runs the CLI in a SUBPROCESS, deliberately: the suite process has
    the persistent compile cache enabled (conftest/force_cpu_devices),
    and warm cross-process cache READS reproducibly corrupt the heap on
    this host's cpu jaxlib (hostmesh.py's documented residual risk —
    bisected here to rc=139/134 at steady-state pjit dispatch with every
    obs feature disabled). The CLI's auto gate keeps the cache OFF on
    cpu, so the child pays a fresh ~15 s compile instead of a coin-flip
    segfault — and the test exercises the real `--trace` entry path."""
    period = 0.2
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    res = subprocess.run(
        [sys.executable, "-m", "deepof_tpu", "train", "--preset",
         "flyingchairs", "--synthetic", "--max-steps", "6",
         "--log-dir", str(tmp_path), "--trace",
         "--set", "model=flownet_s", "--set", "width_mult=0.25",
         "--set", "train.log_every=1", "--set", "train.eval_every=0",
         "--set", f"obs.heartbeat_period_s={period}"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert res.returncode == 0, (res.stdout[-1000:], res.stderr[-2000:])

    payload = _strict_loads(open(str(tmp_path / "trace.json")).read())
    events = payload["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    named = {e["tid"]: e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    span_tids = {s["tid"] for s in spans}
    used_names = {named[tid] for tid in span_tids if tid in named}
    assert "MainThread" in used_names
    assert "prefetch" in used_names
    assert "metrics-fetcher" in used_names
    assert len(used_names) >= 3
    # the overlap PRs 1-2 claim, visible as a timeline: some span on one
    # thread runs concurrently with a span on another
    def overlaps(a, b):
        return (a["tid"] != b["tid"]
                and a["ts"] < b["ts"] + b["dur"]
                and b["ts"] < a["ts"] + a["dur"])

    assert any(overlaps(a, b) for i, a in enumerate(spans)
               for b in spans[i + 1:]), "no cross-thread span overlap"
    assert {"dispatch", "input_wait", "put", "assemble", "fetch"} <= {
        s["name"] for s in spans}

    train = [r for r in map(_strict_loads,
                            open(str(tmp_path / "metrics.jsonl")))
             if r.get("kind") == "train"]
    assert train, "no periodic train records"

    hb = _strict_loads(open(str(tmp_path / "heartbeat.json")).read())
    # heartbeat.close() writes a final record AFTER the last train
    # record, so at process exit the file was younger than 2x the period
    assert hb["time"] >= train[-1]["time"] - 2 * period
    assert hb["step"] == 6 and hb["wedged"] is False
    last = train[-1]
    for key in ("dev_mem_bytes_in_use", "dev_mem_peak_bytes", "rss_bytes"):
        assert key in last, key
    assert any(isinstance(r.get("model_tflops"), (int, float))
               for r in train), "model_tflops never logged"
