"""Full-split eval coverage: every val sample counted exactly once for ANY
eval_batch_size (VERDICT r02 weak item 4; reference iterates the whole
split, `flyingChairsTrain.py:227-236`). Pure-host test: fake dataset +
fake eval_fn, no model compile."""

import numpy as np
import pytest

from deepof_tpu.core.config import (
    DataConfig,
    ExperimentConfig,
    LossConfig,
    OptimConfig,
    TrainConfig,
)
from deepof_tpu.train.evaluate import evaluate_aee


class _FakeVal:
    """Val split of 10 samples; sample_val pads by wrapping to the head
    (the real loaders' convention, `datasets.py sample_val`). Each
    sample's GT flow is the constant (id, 0), so with a zero prediction
    the per-sample EPE IS the sample id — the weighted AEE over the split
    equals mean(ids) iff each id is counted exactly once."""

    num_train, num_val = 0, 10
    mean = (0.0, 0.0, 0.0)

    def sample_val(self, batch_size, batch_id):
        start = (batch_id * batch_size) % self.num_val
        ids = [(start + k) % self.num_val for k in range(batch_size)]
        flow = np.zeros((batch_size, 4, 4, 2), np.float32)
        flow[..., 0] = np.asarray(ids, np.float32)[:, None, None]
        return {"flow": flow}


def _eval_fn(params, batch):
    return {"total": np.float32(1.0),
            "flow": np.zeros_like(batch["flow"])}


def _cfg(bs):
    return ExperimentConfig(
        name="t", model="flownet_s",
        loss=LossConfig(weights=(1,)), optim=OptimConfig(),
        data=DataConfig(dataset="synthetic", image_size=(4, 4),
                        gt_size=(4, 4), batch_size=bs),
        train=TrainConfig(eval_batch_size=bs, eval_amplifier=1.0,
                          eval_clip=(-1e4, 1e4)),
    )


@pytest.mark.parametrize("bs", [4, 8, 3, 16])
def test_every_val_sample_counted_exactly_once(bs):
    # bs=4/3: remainder batch (10 % bs != 0); bs=16 > num_val: the
    # single wrapped batch must not double-count the head; bs=8: the
    # previous code's 10 // 8 = 1 batch dropped samples 8-9.
    res = evaluate_aee(_eval_fn, None, _FakeVal(), _cfg(bs))
    assert res["aee"] == pytest.approx(np.mean(np.arange(10)), abs=1e-6)


def test_remainder_batch_weights_per_sample_not_per_batch():
    # With bs=4 the batches' mean ids are 1.5, 5.5, 8.5; an unweighted
    # mean-of-means would give 5.1667, the per-sample mean is 4.5.
    res = evaluate_aee(_eval_fn, None, _FakeVal(), _cfg(4))
    assert res["aee"] == pytest.approx(4.5, abs=1e-6)
    assert res["aee"] != pytest.approx(5.1667, abs=1e-3)


def _rowmean_eval_fn(params, batch):
    # total = batch-mean of a per-row quantity (the id), mimicking the
    # row-separable jitted loss: exact split val_loss == mean(ids) == 4.5
    return {"total": np.float32(batch["flow"][..., 0].mean()),
            "flow": np.zeros_like(batch["flow"])}


@pytest.mark.parametrize("bs", [3, 4, 7, 8, 16])
def test_val_loss_exact_for_any_batch_size(bs):
    """VERDICT r04 item 7: the remainder batch's val_loss contribution
    must weight only unseen rows. The cyclic self-tiling makes the
    split val_loss exactly mean(ids) for every batch size (previously
    the wrap-padded head rows were averaged into the final batch)."""
    res = evaluate_aee(_rowmean_eval_fn, None, _FakeVal(), _cfg(bs))
    assert res["val_loss"] == pytest.approx(4.5, abs=1e-5)
