"""Incident plane (obs/incident.py + CLI + tail rc 9) — ISSUE 18.

Unit tier (jax-free): manifest schema pin, atomic-commit torn-bundle
contract, dedup/rate-limit bounds, alert-rule grammar, offline
(rc-8 ledger drift) structural dedup, supervisor collection, the
`incidents` CLI rc contract, and the obs.incidents=false structural
no-op.

Chaos tier (subprocess replicas, fake timed executor): a 2-replica
fleet with an injected SLO exhaustion and one replica SIGKILL — each
anomaly commits exactly ONE schema-valid bundle into the run root,
repeats dedup, `incidents list` exits 1 and `tail --fleet` exits 9
until `incidents ack` clears them.
"""

import dataclasses
import json
import os
import signal
import threading
import time

import pytest

from conftest import wait_for_listen  # noqa: F401 - path side effect

from deepof_tpu.core.config import get_config
from deepof_tpu.obs import incident

# ----------------------------------------------------------- helpers


def _mk_run(tmp_path, name="run"):
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    return str(d)


#: The frozen bundle manifest schema — a consumer (triage tooling,
#: dashboards) may rely on every key below existing in every committed
#: bundle. Extending the schema = bump SCHEMA_VERSION + extend here.
MANIFEST_KEYS = {
    "schema", "id", "kind", "severity", "role", "pid", "seq", "time",
    "iso_time", "trigger", "counters", "dedup_key", "config_digest",
    "registry_digest", "files", "origin",
}


# ------------------------------------------------------ manifest pin


def test_manifest_schema_pin(tmp_path):
    d = _mk_run(tmp_path)
    rec = incident.IncidentRecorder(d, "trainer")
    path = rec.record("nan_rollback", trigger={"step": 7})
    assert path is not None and os.path.isdir(path)
    mans = incident.list_incidents(d)
    assert len(mans) == 1
    man = mans[0]
    # list_incidents annotates id + acked on top of the stored schema
    assert set(man) == MANIFEST_KEYS | {"acked"}
    assert man["schema"] == incident.SCHEMA_VERSION == 1
    assert man["kind"] == "nan_rollback"
    assert man["severity"] == "warn"
    assert man["role"] == "trainer"
    assert man["trigger"] == {"step": 7}
    assert man["dedup_key"] == "nan_rollback"
    assert man["origin"] is None and man["acked"] is False
    # the bundle always carries a stack dump, and every inventoried
    # file exists on disk at its recorded size
    assert "stacks.txt" in man["files"]
    for fname, size in man["files"].items():
        p = os.path.join(path, fname)
        assert os.path.isfile(p) and os.path.getsize(p) == size
    # counters snapshot the recorder state at capture time
    assert man["counters"]["incident_captured"] == 0


def test_bundle_carries_log_tails_and_heartbeat_ring(tmp_path):
    d = _mk_run(tmp_path)
    with open(os.path.join(d, "metrics.jsonl"), "w") as f:
        for i in range(500):
            f.write(json.dumps({"kind": "train", "step": i}) + "\n")
    rec = incident.IncidentRecorder(d, "trainer", metrics_tail=10,
                                    heartbeats=3)
    for i in range(5):  # ring keeps the newest 3
        rec.observe({"step": i})
    path = rec.record("watchdog_wedge", "critical",
                      text_files={"stacks.txt": "fake dump"})
    with open(os.path.join(path, "metrics_tail.jsonl")) as f:
        lines = f.read().splitlines()
    assert len(lines) == 10
    assert json.loads(lines[-1])["step"] == 499
    with open(os.path.join(path, "heartbeats.jsonl")) as f:
        steps = [json.loads(x)["step"] for x in f.read().splitlines()]
    assert steps == [2, 3, 4]
    with open(os.path.join(path, "stacks.txt")) as f:
        assert f.read() == "fake dump"


# -------------------------------------------------- atomic commit


def test_atomic_commit_torn_capture_leaves_no_bundle(tmp_path,
                                                     monkeypatch):
    """A process killed mid-capture must never leave a half bundle that
    triage reads: the manifest is written last inside a staging dir and
    the rename is the commit. Simulated by dying right before the
    rename."""
    d = _mk_run(tmp_path)
    rec = incident.IncidentRecorder(d, "serve")

    def boom(src, dst):
        raise OSError("killed mid-capture")

    monkeypatch.setattr(incident.os, "rename", boom)
    assert rec.record("slo_exhausted", "critical") is None
    assert rec.stats()["incident_capture_errors"] == 1
    monkeypatch.undo()
    # nothing committed: list sees no incident; the summary surfaces
    # the tear as `torn` (never as a triageable incident), and gc
    # removes the orphaned staging dir
    assert incident.list_incidents(d) == []
    summ = incident.incident_summary(d)
    assert summ["total"] == 0 and summ["unacked_critical"] == 0
    assert summ["torn"] == 1
    report = incident.gc_incidents(d)
    assert report["staging_removed"] == 1 and report["removed"] == []
    assert os.listdir(incident.incidents_dir(d)) == []


# ------------------------------------------- dedup / rate limiting


def test_dedup_window_and_distinct_keys(tmp_path):
    d = _mk_run(tmp_path)
    rec = incident.IncidentRecorder(d, "serve", dedup_window_s=300.0,
                                    burst=10)
    assert rec.record("slo_exhausted", "critical") is not None
    assert rec.record("slo_exhausted", "critical") is None  # deduped
    # a distinct kind (or explicit dedup key) is its own window
    assert rec.record("quality_drift", "critical") is not None
    assert rec.record("quality_drift", "critical",
                      dedup_key="other") is not None
    s = rec.stats()
    assert s["incident_captured"] == 3 and s["incident_deduped"] == 1
    assert s["incident_by_kind"] == {"slo_exhausted": 1,
                                     "quality_drift": 2}


def test_token_bucket_bounds_distinct_kind_storm(tmp_path):
    """A storm of DISTINCT kinds passes every dedup window — the global
    token bucket must still bound captures to the configured burst."""
    d = _mk_run(tmp_path)
    rec = incident.IncidentRecorder(d, "serve", rate_per_min=0.0001,
                                    burst=3)
    results = [rec.record(f"kind_{i}") for i in range(10)]
    committed = [r for r in results if r]
    assert len(committed) == 3
    s = rec.stats()
    assert s["incident_captured"] == 3
    assert s["incident_rate_limited"] == 7
    assert len(incident.list_incidents(d)) == 3


def test_keep_bound_prunes_oldest(tmp_path):
    d = _mk_run(tmp_path)
    rec = incident.IncidentRecorder(d, "serve", dedup_window_s=0.0,
                                    rate_per_min=1e9, burst=100, keep=4)
    for i in range(8):
        assert rec.record(f"k{i}") is not None
    mans = incident.list_incidents(d)
    assert [m["kind"] for m in mans] == ["k4", "k5", "k6", "k7"]


def test_record_never_raises(tmp_path):
    """The flight recorder must never kill its trigger site: captures
    into an unwritable root count an error and return None."""
    d = _mk_run(tmp_path)
    blocker = os.path.join(d, incident.INCIDENTS_DIRNAME)
    with open(blocker, "w") as f:  # a FILE where the dir must go
        f.write("x")
    rec = incident.IncidentRecorder(d, "serve")
    assert rec.record("slo_exhausted", "critical") is None
    assert rec.stats()["incident_capture_errors"] == 1


# ----------------------------------------------------- alert engine


def test_alert_rules_parse_fire_and_reject(tmp_path):
    d = _mk_run(tmp_path)
    rec = incident.IncidentRecorder(d, "serve", alerts=(
        "serve_errors > 0 critical",
        "quiet: rate(serve_requests) < 0 warn",  # a rate can't: inert
    ))
    rec.observe({"serve_errors": 0, "serve_requests": 0})
    rec.observe({"serve_errors": 2, "serve_requests": 1})
    s = rec.stats()
    assert s["alert_rules"] == 2
    assert s["alert_firings"] == 1 and s["alert_errors"] == 0
    mans = incident.list_incidents(d)
    assert [m["kind"] for m in mans] == ["alert_serve_errors"]
    assert mans[0]["severity"] == "critical"
    assert mans[0]["trigger"]["value"] == 2.0
    # re-firing on the next sample is absorbed by the dedup window
    rec.observe({"serve_errors": 3, "serve_requests": 2})
    assert rec.stats()["alert_firings"] == 2
    assert len(incident.list_incidents(d)) == 1

    # malformed / unregistered / duplicate rules fail LOUDLY at install
    for bad in ("unregistered_counter > 1",
                "serve_errors >> 3",
                "serve_errors > nan_text",
                "serve_errors = 3"):
        with pytest.raises(ValueError):
            incident.parse_alert_rules((bad,))
    with pytest.raises(ValueError):
        incident.parse_alert_rules(("serve_errors > 1",
                                    "serve_errors < 5"))


def test_alert_rate_rule_uses_per_second_delta(tmp_path):
    d = _mk_run(tmp_path)
    rules = incident.parse_alert_rules(("hot: rate(serve_requests) > 5",))
    (rule,) = rules
    fired, value = rule.evaluate({"serve_requests": 100}, None, 10.0)
    assert not fired and value is None  # no previous sample: no rate
    prev = (10.0, {"serve_requests": 100})
    fired, value = rule.evaluate({"serve_requests": 130}, prev, 12.0)
    assert fired and value == 15.0


# ------------------------------------------------- offline recording


def test_record_offline_structural_dedup(tmp_path):
    d = _mk_run(tmp_path)
    key = json.dumps({"fingerprint_drift": ["serve_infer_b1"]})
    assert incident.record_offline(d, "ledger_drift", "critical",
                                   trigger={"x": 1},
                                   dedup_key=key) is not None
    # same verdict again (a tail --follow re-check): suppressed by the
    # EXISTING bundle, not by in-memory state
    assert incident.record_offline(d, "ledger_drift", "critical",
                                   dedup_key=key) is None
    # a DIFFERENT condensed verdict is a new regression: new bundle
    assert incident.record_offline(d, "ledger_drift", "critical",
                                   dedup_key="other") is not None
    assert len(incident.list_incidents(d)) == 2


# -------------------------------------------- supervisor collection


def test_collect_from_children_moves_once_and_annotates(tmp_path):
    run = _mk_run(tmp_path)
    child = os.path.join(run, "replica-0")
    os.makedirs(child)
    crec = incident.IncidentRecorder(child, "replica")
    cpath = crec.record("quality_drift", "critical")
    assert cpath is not None
    # a torn staging dir in the child must NOT be collected
    os.makedirs(os.path.join(child, incident.INCIDENTS_DIRNAME,
                             f"{incident.STAGING_PREFIX}999-1"))
    assert incident.collect_from_children(run) == 1
    assert incident.collect_from_children(run) == 0  # moved, not copied
    assert incident.list_incidents(child) == []
    mans = incident.list_incidents(run)
    assert len(mans) == 1
    assert mans[0]["origin"] == "replica-0"
    assert mans[0]["id"].startswith("replica-0--")
    assert mans[0]["kind"] == "quality_drift"
    summ = incident.incident_summary(run)
    assert summ["unacked_critical"] == 1


# ----------------------------------------------- structural no-op


def test_disabled_is_structural_noop(tmp_path):
    d = _mk_run(tmp_path)
    cfg = get_config("flyingchairs")
    assert cfg.obs.incidents is False  # default OFF
    assert incident.install(cfg, d, "serve") is None
    assert incident.install(
        cfg.replace(obs=dataclasses.replace(cfg.obs, incidents=True)),
        None, "serve") is None  # no log dir: still no recorder
    on = incident.install(
        cfg.replace(obs=dataclasses.replace(cfg.obs, incidents=True)),
        d, "serve")
    assert on is not None and on.role == "serve"
    # with nothing recorded, analyze/tail summaries omit the block
    # entirely (no incidents/ dir is ever created eagerly)
    from deepof_tpu.analyze import tail_summary

    with open(os.path.join(d, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "train", "step": 1, "loss": 1.0,
                            "time": 1.0}) + "\n")
    assert "incidents" not in tail_summary(d)
    assert not os.path.isdir(incident.incidents_dir(d))


# --------------------------------------------------- CLI rc contract


def test_cli_incidents_rc_contract(tmp_path, capsys):
    """`incidents` is jax-free triage with the artifacts/verify-ckpt rc
    family: 0 = healthy, 1 = unacked CRITICAL bundles, 2 = none."""
    from deepof_tpu.cli import main as cli_main

    d = _mk_run(tmp_path)
    assert cli_main(["incidents", "list", "--log-dir", d]) == 2

    rec = incident.IncidentRecorder(d, "serve", dedup_window_s=0.0)
    rec.record("nan_rollback")  # warn only: healthy
    assert cli_main(["incidents", "list", "--log-dir", d]) == 0
    path = rec.record("slo_exhausted", "critical")
    bid = os.path.basename(path)
    capsys.readouterr()  # drop the earlier calls' output
    assert cli_main(["incidents", "list", "--log-dir", d]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["summary"]["unacked_critical"] == 1
    assert [r["id"] for r in out["incidents"]][-1] == bid

    # show: full manifest + on-disk inventory; unknown id is rc 1
    capsys.readouterr()
    assert cli_main(["incidents", "show", "--log-dir", d,
                     "--id", bid]) == 0
    detail = json.loads(capsys.readouterr().out)
    assert detail["kind"] == "slo_exhausted"
    assert "stacks.txt" in detail["files_on_disk"]
    assert cli_main(["incidents", "show", "--log-dir", d,
                     "--id", "nope"]) == 1

    # ack clears the rc-1 (and tail's rc-9) condition
    capsys.readouterr()
    assert cli_main(["incidents", "ack", "--log-dir", d,
                     "--id", bid]) == 0
    acked = json.loads(capsys.readouterr().out)["acked"]
    assert acked == [bid]
    assert cli_main(["incidents", "list", "--log-dir", d]) == 0

    # gc --acked removes the acknowledged bundle, keeps the warn one
    capsys.readouterr()
    assert cli_main(["incidents", "gc", "--log-dir", d, "--acked"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["removed"] == [bid] and report["kept"] == 1
    assert [m["kind"] for m in incident.list_incidents(d)] \
        == ["nan_rollback"]


def test_tail_rc9_outranks_other_verdicts(tmp_path, capsys):
    """rc 9 is FIRST in tail's ladder: the bundle carries the
    underlying verdict, and `incidents ack` moves triage past it where
    cumulative counters would re-fire forever."""
    from deepof_tpu.cli import main as cli_main

    d = _mk_run(tmp_path)
    with open(os.path.join(d, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "train", "step": 1, "loss": 1.0,
                            "time": 1.0}) + "\n")
    assert cli_main(["tail", "--log-dir", d]) == 0
    rec = incident.IncidentRecorder(d, "trainer", dedup_window_s=0.0)
    rec.record("nan_rollback")  # warn: tail stays healthy
    assert cli_main(["tail", "--log-dir", d]) == 0
    rec.record("nan_quarantine_exhausted", "critical")
    assert cli_main(["tail", "--log-dir", d]) == 9
    assert json.loads(
        capsys.readouterr().out.splitlines()[-1]
    )["incidents"]["unacked_critical"] == 1
    assert cli_main(["incidents", "ack", "--log-dir", d]) == 0
    assert cli_main(["tail", "--log-dir", d]) == 0


# --------------------------------------------- chaos (subprocess)


def _b64png(rng, hw=(30, 60)):
    import base64

    import cv2
    import numpy as np

    ok, buf = cv2.imencode(
        ".png", rng.randint(1, 255, (*hw, 3), dtype=np.uint8))
    assert ok
    return base64.b64encode(buf.tobytes()).decode()


def _post(port, body, path="/v1/flow", timeout=30.0):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


@pytest.mark.chaos
def test_incident_chaos_sigkill_and_slo_exhaustion(rng, tmp_path):
    """ISSUE 18 acceptance drill: a 2-replica fleet (fake timed
    executor) with obs.incidents on. An injected SLO exhaustion
    (impossible latency target) and one replica SIGKILL each commit
    exactly ONE schema-valid bundle into the run root; repeats dedup;
    replica-side bundles are collected (moved) into the run root;
    `incidents list` exits 1 and `tail --fleet` exits 9 until
    `incidents ack` clears them — after which the underlying rc-4
    eviction counters surface again."""
    from deepof_tpu.cli import main as cli_main
    from deepof_tpu.core import supervise
    from deepof_tpu.obs.heartbeat import Heartbeat
    from deepof_tpu.serve.fleet import Fleet
    from deepof_tpu.serve.router import Router, build_router_server
    from conftest import wait_for_listen as _wfl

    fleet_dir = tmp_path / "fleet"
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=(32, 64), gt_size=(32, 64)),
        serve=dataclasses.replace(
            cfg.serve, max_batch=4, batch_timeout_ms=5.0, buckets=(),
            fake_exec_ms=5.0, host="127.0.0.1", port=0,
            fleet=dataclasses.replace(
                cfg.serve.fleet, poll_s=0.1, stale_after_s=5.0,
                stall_after_s=2.0, spawn_timeout_s=90.0, term_grace_s=1.0,
                backoff_s=0.1, backoff_max_s=0.5, healthy_after_s=30.0,
                proxy_timeout_s=2.0, max_in_flight=64,
                drain_timeout_s=2.0)),
        train=dataclasses.replace(cfg.train, log_dir=str(fleet_dir)),
        obs=dataclasses.replace(
            cfg.obs, heartbeat_period_s=0.1, watchdog_min_s=3600.0,
            incidents=True,
            # injected SLO exhaustion: a 5ms fake executor can never
            # meet 0.001ms, so the first admitted request burns the
            # whole error budget
            slo_latency_ms=0.001, slo_error_budget=0.01))

    bodies = [json.dumps({"prev": _b64png(rng), "next": _b64png(rng)})
              .encode() for _ in range(2)]
    with Fleet(cfg, 2) as fleet:
        fleet.incidents = incident.install(cfg, str(fleet_dir), "fleet")
        assert fleet.incidents is not None
        fleet.start()
        fleet.wait_ready(min_ready=2, timeout_s=120)
        router = Router(cfg, fleet)
        router.incidents = fleet.incidents
        httpd = build_router_server(cfg, router)
        threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="incident-router").start()
        port = httpd.server_address[1]
        _wfl("127.0.0.1", port)
        hb = Heartbeat(str(fleet_dir / "heartbeat.json"), period_s=0.1,
                       watchdog_min_s=3600.0,
                       sample=fleet.incidents.wrap_sample(
                           lambda: {**fleet.stats(), **router.stats()}),
                       devmem=False)
        try:
            for i in range(12):
                status, _ = _post(port, bodies[i % 2])
                assert status == 200
            # the router's stats pass records the slo_exhausted
            # incident; heartbeat-cadence re-checks dedup against it
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if fleet.incidents.stats()["incident_by_kind"].get(
                        "slo_exhausted"):
                    break
                time.sleep(0.1)
            s = fleet.incidents.stats()
            assert s["incident_by_kind"].get("slo_exhausted") == 1, s

            # SIGKILL replica 0 (pid from its own live heartbeat): the
            # supervisor observes the crash and commits the bundle
            rhb = supervise.read_heartbeat(str(fleet_dir / "replica-0"))
            os.kill(int(rhb["pid"]), signal.SIGKILL)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                s = fleet.incidents.stats()
                if (s["incident_by_kind"].get("fleet_replica_crash")
                        and s["incident_deduped"] >= 1
                        and s["incident_collected"] >= 1):
                    break
                time.sleep(0.2)
            stats = fleet.stats()
            s = fleet.incidents.stats()
        finally:
            hb.close()
            router.draining = True
            httpd.shutdown()
            httpd.server_close()

    assert stats["fleet_crashes"] >= 1, stats
    # exactly ONE bundle per anomaly, dedup absorbed the re-checks
    mans = incident.list_incidents(str(fleet_dir))
    own = [m for m in mans if m["origin"] is None]
    assert [m["kind"] for m in own
            if m["kind"] == "fleet_replica_crash"] \
        == ["fleet_replica_crash"], mans
    assert [m["kind"] for m in own if m["kind"] == "slo_exhausted"] \
        == ["slo_exhausted"], mans
    assert s["incident_deduped"] >= 1, s
    for m in own:
        assert m["schema"] == incident.SCHEMA_VERSION
        assert m["severity"] == "critical"
        assert m["role"] == "fleet"
    # replica-recorded bundles (each replica's own serve_slo verdict)
    # were MOVED into the run root with their origin annotated
    collected = [m for m in mans if m["origin"]]
    assert collected and s["incident_collected"] >= 1, (mans, s)
    assert all(m["role"] == "replica" for m in collected)

    # the whole drill from the run dir: rc 9 until acked, then the
    # underlying rc-4 eviction counters surface again
    assert cli_main(["incidents", "list",
                     "--log-dir", str(fleet_dir)]) == 1
    assert cli_main(["tail", "--log-dir", str(fleet_dir),
                     "--fleet"]) == 9
    assert cli_main(["incidents", "ack",
                     "--log-dir", str(fleet_dir)]) == 0
    assert cli_main(["incidents", "list",
                     "--log-dir", str(fleet_dir)]) == 0
    assert cli_main(["tail", "--log-dir", str(fleet_dir),
                     "--fleet"]) == 4
