"""Test harness: run everything on a virtual 8-device CPU mesh.

Must set env vars before jax is imported anywhere (SURVEY.md §4: multi-device
tests via host-platform device-count simulation).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)
