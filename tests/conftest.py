"""Test harness: run the suite on a virtual 8-device CPU mesh.

Multi-device tests follow SURVEY.md §4: simulate a mesh with
`--xla_force_host_platform_device_count=8` on CPU.

The container's sitecustomize registers an `axon` TPU backend in every
interpreter *before* pytest starts, and initializing it from a second
process can hang on the device tunnel. jax is therefore already imported by
the time this conftest runs; switching platforms must go through
`jax.config` and the backend-factory registry, not env vars alone.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepof_tpu.core.hostmesh import force_cpu_devices  # noqa: E402

# The suite is XLA-compile-dominated (multi-device train steps on the CPU
# mesh); force_cpu_devices also enables the persistent compilation cache,
# which cuts repeat runs from minutes to seconds.
force_cpu_devices(8)

import socket  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Shared networking helpers for every server-shaped test (test_serve,
# test_fleet): the canonical wait-for-listen lives next to the fleet's
# own spawn logic — one definition, no port-collision or
# connect-before-bind flakes.
from deepof_tpu.serve.fleet import wait_for_listen  # noqa: E402, F401


def free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port that was free at bind time. Prefer binding the
    server to port 0 and reading its bound address (race-free); use this
    only where a port number must exist before the server does."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


@pytest.fixture
def rng():
    return np.random.RandomState(0)
