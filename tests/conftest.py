"""Test harness: run the suite on a virtual 8-device CPU mesh.

Multi-device tests follow SURVEY.md §4: simulate a mesh with
`--xla_force_host_platform_device_count=8` on CPU.

The container's sitecustomize registers an `axon` TPU backend in every
interpreter *before* pytest starts, and initializing it from a second
process can hang on the device tunnel. jax is therefore already imported by
the time this conftest runs; switching platforms must go through
`jax.config` and the backend-factory registry, not env vars alone.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge  # noqa: E402

xla_bridge._backend_factories.pop("axon", None)

# The suite is XLA-compile-dominated (multi-device train steps on the CPU
# mesh); a persistent cache cuts repeat runs from minutes to seconds.
jax.config.update("jax_compilation_cache_dir", "/tmp/deepof_tpu_jax_cache")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)
