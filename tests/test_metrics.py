import numpy as np

from deepof_tpu.utils import flow_epe, flow_aae
from deepof_tpu.utils.flowviz import flow_to_color, make_colorwheel


def test_epe_zero():
    f = np.random.RandomState(1).randn(2, 8, 8, 2)
    assert flow_epe(f, f) == 0.0


def test_epe_known():
    gt = np.zeros((1, 4, 4, 2))
    pred = np.zeros((1, 4, 4, 2))
    pred[..., 0] = 3.0
    pred[..., 1] = 4.0
    assert np.isclose(flow_epe(pred, gt), 5.0)


def test_epe_masked():
    gt = np.zeros((1, 2, 2, 2))
    pred = np.zeros((1, 2, 2, 2))
    pred[0, 0, 0] = (3.0, 4.0)
    mask = np.zeros((1, 2, 2))
    mask[0, 0, 0] = 1
    assert np.isclose(flow_epe(pred, gt, mask), 5.0)


def test_aae_matches_reference_formula(rng):
    """Cross-check against a direct transcription of utils.py:70-80."""
    f1 = rng.randn(2, 6, 7, 2)
    f2 = rng.randn(2, 6, 7, 2)
    u, v = f1[..., 0], f1[..., 1]
    ug, vg = f2[..., 0], f2[..., 1]
    num = 1 + u * ug + v * vg
    den = np.sqrt(1 + u**2 + v**2) * np.sqrt(1 + ug**2 + vg**2)
    expect = np.arccos(np.clip(num / den, -1, 1)).mean()
    assert np.isclose(flow_aae(f1, f2), expect)


def test_colorwheel_shape():
    w = make_colorwheel()
    assert w.shape == (55, 3)
    assert w.min() >= 0 and w.max() <= 1


def test_flow_to_color():
    flow = np.zeros((16, 16, 2), np.float32)
    flow[:, :8, 0] = 10.0
    flow[:, 8:, 0] = -10.0
    img = flow_to_color(flow)
    assert img.shape == (16, 16, 3) and img.dtype == np.uint8
    # opposite directions must land on different colors
    assert np.any(img[0, 0] != img[0, 15])


def test_flow_to_color_zero_flow_is_white():
    img = flow_to_color(np.zeros((4, 4, 2)))
    assert (img >= 250).all()


def test_epe_broadcast_mask():
    """(H,W) mask shared across a batch must not inflate the metric."""
    gt = np.zeros((4, 2, 2, 2))
    pred = np.zeros((4, 2, 2, 2))
    pred[..., 0] = 3.0
    pred[..., 1] = 4.0
    mask = np.ones((2, 2))
    assert np.isclose(flow_epe(pred, gt, mask), 5.0)
    assert np.isclose(flow_aae(pred, gt, mask), flow_aae(pred, gt))
