"""Spatial CP / temporal pair parallelism tests on the virtual 8-device
CPU mesh (SURVEY.md §4: multi-node behavior without a real cluster)."""

import numpy as np
import jax
import pytest
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from deepof_tpu.core.config import (
    DataConfig,
    ExperimentConfig,
    LossConfig,
    MeshConfig,
    OptimConfig,
    TrainConfig,
)
from deepof_tpu.data import SyntheticData
from deepof_tpu.models.registry import build_model
from deepof_tpu.parallel.mesh import batch_sharding, build_mesh
from deepof_tpu.parallel.spatial import halo_exchange
from deepof_tpu.train.state import create_train_state, make_optimizer
from deepof_tpu.train.step import make_train_step
pytestmark = pytest.mark.slow  # full-model/train-step compiles; see pytest.ini

H, W = 32, 64
# Spatial CP only activates at high resolution: every pyramid level must
# keep >= 2 rows per spatial shard (the per-model min_spatial_height bound,
# parallel/spatial.py). 256 = 2 * 64 (flownet_s downsample) * 2 shards.
H_CP = 256


def _cfg(mesh_cfg: MeshConfig, height: int = H, batch: int = 8,
         **data_kw) -> ExperimentConfig:
    data = dict(dataset="synthetic", image_size=(height, W),
                gt_size=(height, W), batch_size=batch)
    data.update(data_kw)
    return ExperimentConfig(
        model="flownet_s",
        width_mult=0.25,  # thin trunk: CP/halo semantics are width-free
        loss=LossConfig(weights=(16, 8, 4, 2, 1, 1)),
        optim=OptimConfig(learning_rate=1e-4),
        data=DataConfig(**data),
        mesh=mesh_cfg,
        train=TrainConfig(seed=0),
    )


def _run_one_step(mesh_cfg: MeshConfig, time_step: int = 2,
                  expect_constraint: str | None = None, height: int = H,
                  batch: int = 8):
    cfg = _cfg(mesh_cfg, height=height, batch=batch, time_step=time_step)
    mesh = build_mesh(cfg.mesh)
    ds = SyntheticData(cfg.data)
    t = cfg.data.time_step
    model = build_model("flownet_s", flow_channels=2 * (t - 1), width_mult=0.25)
    tx = make_optimizer(cfg.optim, lambda s: 1e-4)
    state = create_train_state(model, jnp.zeros((batch, height, W, 3 * t)),
                               tx, seed=0)
    step = make_train_step(model, cfg, ds.mean, mesh)
    batch = jax.device_put(ds.sample_train(batch, iteration=0),
                           batch_sharding(mesh))
    if expect_constraint is not None:
        # positive proof the parallelism is active, not a silent no-op:
        # the lowered module must carry sharding constraints on the axis
        txt = step.lower(state, batch).as_text()
        hits = [l for l in txt.splitlines()
                if "sharding" in l and f'"{expect_constraint}"' in l
                and "sdy.mesh" not in l]
        assert hits, f"no sharding constraint on axis {expect_constraint!r}"
    new_state, metrics = step(state, batch)
    return float(metrics["total"]), float(metrics["grad_norm"])


def test_spatial_cp_matches_data_parallel():
    """H sharded over 2 spatial shards == pure data parallel: same loss and
    same global gradient norm (up to fp reduction order; per-param
    comparison after Adam is meaningless — the first-step update is
    ~lr*sign(g), which amplifies fp noise on near-zero grads)."""
    loss_dp, gn_dp = _run_one_step(MeshConfig(), height=H_CP)
    loss_sp, gn_sp = _run_one_step(MeshConfig(spatial=2),
                                   expect_constraint="spatial",
                                   height=H_CP)
    assert np.isclose(loss_dp, loss_sp, rtol=1e-5)
    assert np.isclose(gn_dp, gn_sp, rtol=1e-4)


def test_spatial_grad_exact_at_derived_bound():
    """Gradient correctness AT the fence: H == min_spatial_height (every
    level keeps exactly MIN_ROWS_PER_SHARD rows per shard) gives sharded
    grads equal to replicated grads. The unsafe side one octave below is
    pinned by tools/halo_grad_repro.py (x4 upstream grads) and fenced off
    by constrain_batch (test below)."""
    from flax import linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepof_tpu.parallel.spatial import min_spatial_height

    mesh = build_mesh(MeshConfig(spatial=2))
    spatial, n_down = 2, 5  # downsample factor 32
    h = min_spatial_height(2 ** n_down, spatial)  # == 128
    assert h == 128

    class Stack(nn.Module):
        @nn.compact
        def __call__(self, x):
            for i in range(n_down):
                x = nn.elu(nn.Conv(4, (3, 3), strides=(2, 2),
                                   padding="SAME", name=f"c{i}")(x))
            return nn.Conv(2, (3, 3), padding="SAME", name="head")(x)

    model = Stack()
    x = jnp.asarray(np.random.RandomState(0).rand(4, h, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]

    def loss(p, xx, shard):
        if shard:
            xx = jax.lax.with_sharding_constraint(
                xx, NamedSharding(mesh, P(("data",), "spatial")))
        return (model.apply({"params": p}, xx) ** 2).sum()

    g_repl = jax.device_get(
        jax.jit(jax.grad(lambda p, xx: loss(p, xx, False)))(params, x))
    g_shard = jax.device_get(
        jax.jit(jax.grad(lambda p, xx: loss(p, xx, True)))(params, x))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g_repl, g_shard)


def test_spatial_fence_below_bound():
    """constrain_batch must refuse to shard below the derived bound (the
    degenerate-halo regime) and apply the constraint at or above it."""
    from jax.sharding import NamedSharding

    from deepof_tpu.parallel.spatial import constrain_batch, min_spatial_height

    mesh = build_mesh(MeshConfig(spatial=2))
    assert min_spatial_height(32, 2) == 128
    below = jnp.zeros((4, 64, 32, 3))   # divides spatial, but < bound
    at = jnp.zeros((4, 128, 32, 3))
    out = jax.jit(lambda b: constrain_batch(b, mesh=mesh, max_downsample=32))(
        {"below": below, "at": at})
    spatial_sh = NamedSharding(mesh, P(("data",), "spatial"))
    assert not out["below"].sharding.is_equivalent_to(spatial_sh, 4)
    assert out["at"].sharding.is_equivalent_to(spatial_sh, 4)
    # a deeper model (factor 64) must refuse H=128 too
    out64 = jax.jit(lambda b: constrain_batch(b, mesh=mesh,
                                              max_downsample=64))({"at": at})
    assert not out64["at"].sharding.is_equivalent_to(spatial_sh, 4)
    # above the bound with an UNEVEN deepest level (160/32 = 5 rows over
    # 2 shards) is gradient-exact (tools/halo_grad_repro.py probes) and
    # must shard — this is the flagship H=320 flownet_s case scaled down
    odd = jnp.zeros((4, 160, 32, 3))
    out_odd = jax.jit(lambda b: constrain_batch(b, mesh=mesh,
                                                max_downsample=32))({"x": odd})
    assert out_odd["x"].sharding.is_equivalent_to(spatial_sh, 4)


def test_time_axis_pair_parallel_volume():
    """Sintel-style T-frame volume step with the folded pair axis sharded
    over the "time" mesh axis matches the unsharded result."""
    loss_t1, _ = _run_one_step(MeshConfig(), time_step=3)
    loss_t2, _ = _run_one_step(MeshConfig(time=2), time_step=3,
                               expect_constraint="time")
    assert np.isfinite(loss_t2)
    assert np.isclose(loss_t1, loss_t2, rtol=1e-5)


def test_halo_exchange_ring():
    mesh = build_mesh(MeshConfig(spatial=4, data=2))
    x = np.arange(8 * 16 * 4, dtype=np.float32).reshape(8, 16, 4)

    fn = shard_map(
        lambda blk: halo_exchange(blk, halo=2, axis_name="spatial", axis=1),
        mesh=mesh,
        in_specs=P(("data",), "spatial"),
        out_specs=P(("data",), "spatial"),
    )
    out = np.asarray(fn(jnp.asarray(x)))  # (8, 16+2*4*2? no: per-shard +4) ->
    # out global H = 16 + 4 shards * 2*2 halo rows... shard_map concatenates
    # per-shard (4+4) rows -> global (8, 32, 4)
    assert out.shape == (8, 32, 4)
    # shard 1 (global out rows 8..16): halo-from-prev = x rows 2..4,
    # body = x rows 4..8, halo-from-next = x rows 8..10
    np.testing.assert_array_equal(out[:, 8:10], x[:, 2:4])
    np.testing.assert_array_equal(out[:, 10:14], x[:, 4:8])
    np.testing.assert_array_equal(out[:, 14:16], x[:, 8:10])
    # edge shards: zero halos at the outer borders
    assert (out[:, 0:2] == 0).all() and (out[:, -2:] == 0).all()


def test_local_batch_rows_single_process():
    from deepof_tpu.parallel.mesh import (
        local_batch_rows, process_data_coords, put_global, batch_sharding)

    mesh = build_mesh(MeshConfig())  # data=8 on the CPU test mesh
    assert process_data_coords(mesh) == list(range(8))
    n, rows = local_batch_rows(mesh, 16)
    assert n == 16 and rows == list(range(16))

    mesh2 = build_mesh(MeshConfig(spatial=2))  # data=4
    n, rows = local_batch_rows(mesh2, 8)
    assert n == 8 and rows == list(range(8))

    import pytest as _pytest
    with _pytest.raises(ValueError, match="not divisible"):
        local_batch_rows(mesh, 7)


def test_local_batch_rows_simulated_multihost(monkeypatch):
    """Monkeypatch jax.local_devices to emulate a host owning only
    data-coords {2, 3}: its rows must be that contiguous block."""
    from deepof_tpu.parallel import mesh as M

    mesh = build_mesh(MeshConfig())  # (8, 1, 1)
    subset = list(mesh.devices[2:4].flat)
    monkeypatch.setattr(jax, "local_devices", lambda: subset)
    assert M.process_data_coords(mesh) == [2, 3]
    n, rows = M.local_batch_rows(mesh, 16)
    assert n == 4 and rows == [4, 5, 6, 7]


def test_put_global_single_process_matches_device_put():
    from deepof_tpu.parallel.mesh import (
        batch_sharding, put_global, put_global_from_full)

    mesh = build_mesh(MeshConfig())
    sh = batch_sharding(mesh)
    batch = {"source": np.arange(8 * 4, dtype=np.float32).reshape(8, 4)}
    a = put_global(batch, sh)
    b = put_global_from_full(batch, mesh, sh)
    np.testing.assert_array_equal(np.asarray(a["source"]), batch["source"])
    np.testing.assert_array_equal(np.asarray(b["source"]), batch["source"])
    assert a["source"].sharding.is_equivalent_to(sh, 2)


def test_assemble_from_local_array_single_process():
    """D2D global assembly from an on-device local-rows array (the
    multi-process hot path for augmented batches), exercised on the
    8-device mesh where local rows == global rows."""
    from deepof_tpu.parallel.mesh import (
        _assemble_from_local_array, batch_sharding)

    mesh = build_mesh(MeshConfig())
    sh = batch_sharding(mesh)
    x = jnp.arange(16 * 3, dtype=jnp.float32).reshape(16, 3)
    out = _assemble_from_local_array(x, sh)
    assert out.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    assert out.sharding.is_equivalent_to(sh, 2)


def test_process_seed_and_span_guard(monkeypatch):
    from deepof_tpu.parallel import mesh as M

    mesh = build_mesh(MeshConfig(spatial=2))  # (4, 2, 1)
    assert M.process_seed(mesh, 7) == 7  # single process: min coord 0

    # replica emulation: a host owning exactly one spanning coord is OK
    monkeypatch.setattr(jax, "local_devices",
                        lambda: [mesh.devices[1, 0, 0]])
    assert M.process_data_coords(mesh) == [1]
    n, rows = M.local_batch_rows(mesh, 8)
    assert n == 2 and rows == [2, 3]
    assert M.process_seed(mesh, 7) == 8  # seed + coord, shared by replicas

    # partial span across multiple owned coords is ambiguous: reject
    monkeypatch.setattr(
        jax, "local_devices",
        lambda: [mesh.devices[0, 0, 0], mesh.devices[0, 1, 0],
                 mesh.devices[1, 0, 0]])
    import pytest as _pytest
    with _pytest.raises(ValueError, match="span processes"):
        M.local_batch_rows(mesh, 8)
