"""Pallas warp kernel vs the jnp/XLA oracle (interpret mode on CPU).

Mirrors the reference's golden-test pattern (`check_loss.py`: numpy
re-implementation vs the accelerated graph — SURVEY.md §4.2): the
vectorized jnp `backward_warp` is itself tested against numpy in
test_warp.py, and serves here as the oracle for the Pallas kernel.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepof_tpu.core.config import LossConfig
from deepof_tpu.losses.photometric import loss_interp, loss_interp_multi
from deepof_tpu.ops.warp import backward_warp
from deepof_tpu.ops.pallas.warp import backward_warp_pallas


@pytest.mark.parametrize(
    "shape,mag",
    [((2, 5, 7, 3), 3.0),      # level-6 size: flow >> image size (all clip)
     ((2, 10, 14, 3), 30.0),   # level-5
     ((1, 40, 56, 3), 80.0),   # level-3
     ((1, 80, 112, 3), 20.0),  # level-2: the widest auto-admitted level
     ((2, 16, 128, 2), 200.0)],  # full-lane width, huge flow
)
def test_pallas_warp_matches_xla(rng, shape, mag):
    b, h, w, c = shape
    img = jnp.asarray(rng.rand(b, h, w, c), jnp.float32)
    flow = jnp.asarray(rng.randn(b, h, w, 2) * mag, jnp.float32)
    ref = backward_warp(img, flow)
    out = backward_warp_pallas(img, flow)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pallas_warp_rejects_wide_levels(rng):
    img = jnp.zeros((1, 8, 256, 3))
    flow = jnp.zeros((1, 8, 256, 2))
    with pytest.raises(ValueError, match="W <= 128"):
        backward_warp_pallas(img, flow)


def test_pallas_warp_gradients_match(rng):
    img = jnp.asarray(rng.rand(2, 10, 14, 3), jnp.float32)
    flow = jnp.asarray(rng.randn(2, 10, 14, 2) * 2.0, jnp.float32)

    def loss_p(i, f):
        return jnp.sum(backward_warp_pallas(i, f) ** 2)

    def loss_x(i, f):
        return jnp.sum(backward_warp(i, f) ** 2)

    gip, gfp = jax.grad(loss_p, argnums=(0, 1))(img, flow)
    gix, gfx = jax.grad(loss_x, argnums=(0, 1))(img, flow)
    np.testing.assert_allclose(np.asarray(gfp), np.asarray(gfx),
                               rtol=1e-5, atol=1e-5)
    # image cotangent (bilinear scatter) must match too — impl switching
    # may not change gradient semantics
    np.testing.assert_allclose(np.asarray(gip), np.asarray(gix),
                               rtol=1e-5, atol=1e-5)
    assert float(jnp.abs(gip).max()) > 0.0


def test_loss_interp_pallas_impl_matches(rng):
    cfg_x = LossConfig()
    cfg_p = LossConfig(warp_impl="pallas")
    flow = jnp.asarray(rng.randn(2, 20, 28, 2), jnp.float32)
    prev = jnp.asarray(rng.rand(2, 20, 28, 3), jnp.float32)
    nxt = jnp.asarray(rng.rand(2, 20, 28, 3), jnp.float32)
    lx, rx = loss_interp(flow, prev, nxt, 2.5, cfg_x)
    lp, rp = loss_interp(flow, prev, nxt, 2.5, cfg_p)
    np.testing.assert_allclose(np.asarray(rp), np.asarray(rx),
                               rtol=1e-5, atol=1e-5)
    for k in lx:
        np.testing.assert_allclose(float(lp[k]), float(lx[k]),
                                   rtol=1e-5, atol=1e-6)


def test_loss_interp_multi_pallas_impl_matches(rng):
    t = 4
    cfg_x = LossConfig()
    cfg_p = LossConfig(warp_impl="pallas")
    flows = jnp.asarray(rng.randn(2, 10, 14, 2 * (t - 1)), jnp.float32)
    vol = jnp.asarray(rng.rand(2, 10, 14, 3 * t), jnp.float32)
    lx, _ = loss_interp_multi(flows, vol, 1.25, cfg_x)
    lp, _ = loss_interp_multi(flows, vol, 1.25, cfg_p)
    for k in lx:
        np.testing.assert_allclose(float(lp[k]), float(lx[k]),
                                   rtol=1e-5, atol=1e-6)


def test_auto_impl_dispatch(rng):
    # auto: small level -> pallas path must agree; wide level -> xla path runs
    img = jnp.asarray(rng.rand(1, 12, 16, 3), jnp.float32)
    flow = jnp.asarray(rng.randn(1, 12, 16, 2), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(backward_warp(img, flow, impl="auto")),
        np.asarray(backward_warp(img, flow)), rtol=1e-5, atol=1e-5)
    wide = jnp.asarray(rng.rand(1, 8, 200, 3), jnp.float32)
    wflow = jnp.zeros((1, 8, 200, 2))
    out = backward_warp(wide, wflow, impl="auto")  # falls back to xla
    np.testing.assert_allclose(np.asarray(out), np.asarray(wide), atol=1e-6)


def test_pallas_flow_grad_clipped_and_flow_only(rng):
    """The Pallas flow-cotangent kernel on heavily clipped flows (all four
    bilinear neighbors at the border), differentiated wrt flow ONLY — the
    training hot path, where the image cotangent is dead code."""
    img = jnp.asarray(rng.rand(2, 8, 10, 3), jnp.float32)
    flow = jnp.asarray(rng.randn(2, 8, 10, 2) * 50.0, jnp.float32)

    gp = jax.grad(lambda f: jnp.sum(backward_warp_pallas(img, f) ** 2))(flow)
    gx = jax.grad(lambda f: jnp.sum(backward_warp(img, f) ** 2))(flow)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                               rtol=1e-5, atol=1e-5)
