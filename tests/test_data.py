"""Data pipeline tests: dataset index/split semantics against synthetic
on-disk fixtures, batch shapes, prefetcher overlap and error propagation."""

import os

import numpy as np
import cv2
import pytest

from deepof_tpu.core.config import DataConfig
from deepof_tpu.data import (
    FlyingChairsData,
    Prefetcher,
    SintelData,
    SyntheticData,
    UCF101Data,
    build_dataset,
)
from deepof_tpu.io.flo import write_flo


def _write_ppm(path, h=32, w=48, seed=0):
    rng = np.random.RandomState(seed)
    cv2.imwrite(str(path), rng.randint(0, 255, (h, w, 3), np.uint8))


def _make_flyingchairs(root, n=10):
    for i in range(1, n + 1):
        sid = f"{i:05d}"
        _write_ppm(root / f"{sid}_img1.ppm", seed=i)
        _write_ppm(root / f"{sid}_img2.ppm", seed=i + 1000)
        write_flo(root / f"{sid}_flow.flo",
                  np.random.RandomState(i).rand(32, 48, 2).astype(np.float32))
    # split file: markers 1=train, 2=val; last 3 are val
    markers = ["1"] * (n - 3) + ["2"] * 3
    (root / "FlyingChairs_train_val.txt").write_text("\n".join(markers) + "\n")


@pytest.fixture
def chairs_root(tmp_path):
    _make_flyingchairs(tmp_path)
    return tmp_path


def test_flyingchairs_split_and_shapes(chairs_root):
    cfg = DataConfig(dataset="flyingchairs", data_path=str(chairs_root),
                     image_size=(24, 40), gt_size=(32, 48), batch_size=2)
    ds = FlyingChairsData(cfg)
    assert ds.num_train == 7 and ds.num_val == 3
    b = ds.sample_train(2, iteration=0)
    assert b["source"].shape == (2, 24, 40, 3)
    assert b["flow"].shape == (2, 32, 48, 2)  # GT stays native
    # sequential batching is deterministic
    b2 = ds.sample_train(2, iteration=0)
    np.testing.assert_array_equal(b["source"], b2["source"])
    v = ds.sample_val(2, 0)
    assert v["source"].shape[0] == 2


def test_flyingchairs_sequential_never_short_batches(tmp_path):
    """Sequential (gen-2) sampling must wrap like sample_val: a short
    batch (num_train < batch_size, or a start near the tail) breaks the
    compiled executable's fixed shapes."""
    _make_flyingchairs(tmp_path, n=5)  # markers: 2 train, 3 val
    cfg = DataConfig(dataset="flyingchairs", data_path=str(tmp_path),
                     image_size=(24, 40), gt_size=(32, 48), batch_size=4)
    ds = FlyingChairsData(cfg)
    assert ds.num_train == 2  # smaller than the batch
    for it in range(3):
        b = ds.sample_train(4, iteration=it)
        assert b["source"].shape[0] == 4
        assert b["flow"].shape[0] == 4
    # wrap is deterministic per iteration
    np.testing.assert_array_equal(ds.sample_train(4, iteration=1)["source"],
                                  ds.sample_train(4, iteration=1)["source"])


def test_flyingchairs_fallback_split(tmp_path):
    _make_flyingchairs(tmp_path, n=5)
    os.remove(tmp_path / "FlyingChairs_train_val.txt")
    cfg = DataConfig(dataset="flyingchairs", data_path=str(tmp_path),
                     image_size=(24, 40))
    ds = FlyingChairsData(cfg)
    assert ds.num_train == 4 and ds.num_val == 1  # both splits non-empty
    assert ds.sample_train(2, rng=np.random.RandomState(0))["source"].shape[0] == 2


def _make_sintel(root, clips=("alley_1", "bamboo_2"), frames=6):
    for clip in clips:
        img_dir = root / "training" / "final" / clip
        flow_dir = root / "training" / "flow" / clip
        img_dir.mkdir(parents=True)
        flow_dir.mkdir(parents=True)
        for f in range(1, frames + 1):
            _write_ppm(img_dir / f"frame_{f:04d}.png", h=32, w=64, seed=f)
            if f < frames:
                write_flo(flow_dir / f"frame_{f:04d}.flo",
                          np.ones((32, 64, 2), np.float32) * f)


def test_sintel_windows_and_volume(tmp_path):
    _make_sintel(tmp_path)
    cfg = DataConfig(dataset="sintel", data_path=str(tmp_path),
                     image_size=(32, 64), gt_size=(32, 64), time_step=3,
                     sintel_pass="final")
    ds = SintelData(cfg)
    # 2 clips x (6-3+1) windows = 8 windows
    assert len(ds.windows) == 8
    # reference membership pinned (`sintelLoader.py:47-70`): first window
    # of each clip in sorted order, plus bamboo_2's window starting at
    # frame time_step — and nothing else
    assert ds.val_idx == [0, 4, 4 + ds.t]
    assert [ds.windows[i][0] for i in ds.val_idx] == [
        str(tmp_path / "training/final/alley_1/frame_0001.png"),
        str(tmp_path / "training/final/bamboo_2/frame_0001.png"),
        str(tmp_path / "training/final/bamboo_2/frame_0004.png"),
    ]
    assert ds.num_val == 3
    b = ds.sample_train(2, rng=np.random.RandomState(0))
    assert b["volume"].shape == (2, 32, 64, 9)  # 3T channels
    assert b["flow"].shape == (2, 32, 64, 4)  # 2(T-1)
    v = ds.sample_val(2, 0)
    assert v["volume"].shape[-1] == 9


def test_sintel_ucf_sequential_iteration_is_deterministic(tmp_path):
    """Datasets without a true sequential mode must still honor the
    `iteration` contract: a seeded, exact-batch_size draw per iteration
    (not a silently unseeded one)."""
    _make_sintel(tmp_path)
    cfg = DataConfig(dataset="sintel", data_path=str(tmp_path),
                     image_size=(32, 64), gt_size=(32, 64), time_step=2,
                     sintel_pass="final")
    ds = SintelData(cfg)
    a = ds.sample_train(2, iteration=3)
    b = ds.sample_train(2, iteration=3)
    c = ds.sample_train(2, iteration=4)
    np.testing.assert_array_equal(a["volume"], b["volume"])
    assert a["volume"].shape[0] == 2
    assert not np.array_equal(a["volume"], c["volume"])

    ucf_root = tmp_path / "ucf"
    _make_ucf101(ucf_root)
    ucfg = DataConfig(dataset="ucf101", data_path=str(ucf_root),
                      image_size=(24, 32), batch_size=2)
    uds = UCF101Data(ucfg)
    ua = uds.sample_train(2, iteration=3)
    ub = uds.sample_train(2, iteration=3)
    np.testing.assert_array_equal(ua["source"], ub["source"])
    np.testing.assert_array_equal(ua["label"], ub["label"])


def test_sintel_crop(tmp_path):
    _make_sintel(tmp_path)
    cfg = DataConfig(dataset="sintel", data_path=str(tmp_path),
                     image_size=(32, 64), crop_size=(16, 32), time_step=2,
                     sintel_pass="final")
    ds = SintelData(cfg)
    b = ds.sample_train(1, rng=np.random.RandomState(0))
    assert b["volume"].shape == (1, 16, 32, 6)


def test_sintel_gen1_pair_split(tmp_path):
    """Gen-1 Sintel_train_val.txt membership (`version1/loader/
    sintelLoader.py:38-70`): line k labels the k-th consecutive pair in
    sorted clip x frame order, '1' = train, '2' = val (VERDICT r04
    item 6 — this split was unreachable by config)."""
    import pytest

    _make_sintel(tmp_path)  # 2 clips x 5 pairs = 10 pairs
    split = tmp_path / "Sintel_train_val.txt"
    labels = ["1", "2", "1", "1", "2", "1", "1", "1", "2", "1"]
    split.write_text("\n".join(labels) + "\n")
    cfg = DataConfig(dataset="sintel", data_path=str(tmp_path),
                     image_size=(32, 64), gt_size=(32, 64), time_step=2,
                     sintel_pass="final",
                     sintel_pair_split_file=str(split))
    ds = SintelData(cfg)
    assert ds.val_idx == [1, 4, 8]
    assert ds.num_train == 7 and ds.num_val == 3
    # pair 1 = alley_1 frames 2-3; pair 8 = bamboo_2 frames 4-5
    assert ds.windows[1][0].endswith("alley_1/frame_0002.png")
    assert ds.windows[8][0].endswith("bamboo_2/frame_0004.png")

    # volume-mode configs must reject the pair split by name
    with pytest.raises(ValueError, match="time_step=2"):
        SintelData(DataConfig(dataset="sintel", data_path=str(tmp_path),
                              image_size=(32, 64), time_step=3,
                              sintel_pair_split_file=str(split)))
    # wrong entry count raises (guards silent misalignment)
    split.write_text("1\n2\n")
    with pytest.raises(ValueError, match="2 entries"):
        SintelData(cfg)


def test_ucf101_eval_at_reference_scale(tmp_path):
    """The accuracy aggregation path (`evaluate_ucf101`) at the reference's
    101-class scale (`ucf101train.py:210-223`): one batch per class, every
    class visited exactly once, accuracy aggregated over all of them."""
    from deepof_tpu.core.config import (
        ExperimentConfig, LossConfig, OptimConfig, TrainConfig,
    )
    from deepof_tpu.train.evaluate import evaluate_ucf101

    n_cls = 101
    for ci in range(n_cls):
        cls = f"Class{ci:03d}"
        clip = tmp_path / "frames" / cls / f"v_{cls}_g03_c01"  # group 3 = val
        clip.mkdir(parents=True)
        for f in range(2):
            _write_ppm(clip / f"f{f}.jpg", h=8, w=8, seed=ci * 10 + f)
    cfg = DataConfig(dataset="ucf101", data_path=str(tmp_path),
                     image_size=(8, 8))
    ds = UCF101Data(cfg)
    assert len(ds.val_clips) == n_cls and ds.num_val == n_cls

    exp = ExperimentConfig(
        name="t", model="st_single", loss=LossConfig(),
        optim=OptimConfig(), data=cfg,
        train=TrainConfig(eval_batch_size=4, log_dir=str(tmp_path)))
    seen_labels = []

    def fake_eval_fn(params, batch):
        # predict the true class for even class ids, class 0 otherwise
        seen_labels.append(batch["label"].copy())
        b = batch["label"].shape[0]
        logits = np.zeros((b, n_cls), np.float32)
        for i, lbl in enumerate(batch["label"]):
            logits[i, int(lbl) if lbl % 2 == 0 else 0] = 1.0
        return {"logits": logits, "total": 0.5}

    res = evaluate_ucf101(fake_eval_fn, None, ds, exp)
    labels = np.concatenate(seen_labels)
    # 101 batches of 4, each from a single class; all 101 classes covered
    assert labels.shape[0] == n_cls * 4
    assert sorted(set(labels.tolist())) == list(range(n_cls))
    for lb in seen_labels:
        assert len(set(lb.tolist())) == 1
    # even class ids (51 of 101) predicted correctly, odd ids mapped to 0
    assert np.isclose(res["accuracy"], 51 / 101)
    assert np.isclose(res["val_loss"], 0.5)


def _make_ucf101(root, classes=("ApplyEyeMakeup", "Archery"), n_frames=4):
    for cls in classes:
        for g, c in [(8, 1), (9, 1), (3, 1)]:  # groups 8,9 train; 3 val
            clip = root / "frames" / cls / f"v_{cls}_g{g:02d}_c{c:02d}"
            clip.mkdir(parents=True)
            for f in range(n_frames):
                _write_ppm(clip / f"frame{f:03d}.jpg", h=32, w=40, seed=f)


def test_ucf101_split_and_batches(tmp_path):
    _make_ucf101(tmp_path)
    cfg = DataConfig(dataset="ucf101", data_path=str(tmp_path),
                     image_size=(24, 32), batch_size=2)
    ds = UCF101Data(cfg)
    assert ds.num_train == 4 and ds.num_val == 2  # 2 classes x (2 train, 1 val)
    b = ds.sample_train(2, rng=np.random.RandomState(0))
    assert b["source"].shape == (2, 24, 32, 3)
    assert b["label"].shape == (2,)
    assert len(set(b["label"])) == 2  # distinct classes
    v = ds.sample_val(2, 1)
    assert len(set(v["label"])) == 1  # one class per val batch


def test_synthetic_flow_consistency():
    """GT flow must be the minimizer of the backward-warp loss:
    backward_warp(target, flow) == source (away from borders)."""
    from deepof_tpu.ops.warp import backward_warp

    cfg = DataConfig(dataset="synthetic", image_size=(32, 48), batch_size=2)
    ds = SyntheticData(cfg, max_shift=3)
    b = ds.sample_train(2, iteration=0)
    recon = np.asarray(backward_warp(b["target"], b["flow"]))
    m = 4  # exclude the clip-at-border band (|flow| <= max_shift)
    np.testing.assert_allclose(recon[:, m:-m, m:-m], b["source"][:, m:-m, m:-m],
                               atol=1e-3)
    # pixel-level relation: source[y, x] == target[y + fv, x + fu]
    fu, fv = int(b["flow"][0, 0, 0, 0]), int(b["flow"][0, 0, 0, 1])
    h, w = 32, 48
    src_part = b["source"][0][max(0, -fv) : h + min(0, -fv),
                              max(0, -fu) : w + min(0, -fu)]
    tgt_part = b["target"][0][max(0, fv) : h + min(0, fv),
                              max(0, fu) : w + min(0, fu)]
    np.testing.assert_allclose(src_part, tgt_part, atol=1e-4)


def test_synthetic_blobs_style_consistency():
    """The blobs style (unambiguous structure for unsupervised fitting —
    tools/synthetic_fit.py) keeps the same shift/flow contract."""
    from deepof_tpu.ops.warp import backward_warp

    cfg = DataConfig(dataset="synthetic", image_size=(32, 48), batch_size=2)
    ds = SyntheticData(cfg, max_shift=3, style="blobs")
    b = ds.sample_train(2, iteration=0)
    assert b["source"].min() >= 0.0 and b["source"].max() <= 255.0
    recon = np.asarray(backward_warp(b["target"], b["flow"]))
    m = 4
    np.testing.assert_allclose(recon[:, m:-m, m:-m],
                               b["source"][:, m:-m, m:-m], atol=1e-3)
    # deterministic per seed
    b2 = ds.sample_train(2, iteration=0)
    np.testing.assert_array_equal(b["source"], b2["source"])


def test_synthetic_affine_style_consistency():
    """The affine style's spatially varying GT field keeps the loss
    contract: backward_warp(target, flow) reconstructs the source.
    For float32 input cv2's INTER_LINEAR uses float weights (its 5-bit
    fixed-point tables apply only to uint8), so interior pixels agree to
    float rounding; the m=4 crop excludes the border rows where
    backward_warp's clip-at-border convention and remap's border mode
    legitimately differ. Measured max |err| over 10 draws: ~5e-5."""
    from deepof_tpu.ops.warp import backward_warp

    cfg = DataConfig(dataset="synthetic", image_size=(32, 48), batch_size=2)
    ds = SyntheticData(cfg, max_shift=3, style="affine")
    b = ds.sample_train(2, iteration=0)
    flow = b["flow"]
    assert float(np.abs(flow).max()) <= 3.0 + 1e-5
    # the field must actually vary spatially (the style's whole point)
    assert float(np.std(flow[0, ..., 0])) > 1e-2
    recon = np.asarray(backward_warp(b["target"], b["flow"]))
    m = 4
    np.testing.assert_allclose(recon[:, m:-m, m:-m],
                               b["source"][:, m:-m, m:-m], atol=1e-3)
    b2 = ds.sample_train(2, iteration=0)
    np.testing.assert_array_equal(b["source"], b2["source"])


def test_synthetic_train_shift_override_keeps_canvas():
    """The curriculum's sample_train(max_shift=...) override bounds the
    DISPLACEMENT only: same seeds give byte-identical source canvases
    (blob sigma follows the constructor's max_shift, not the override),
    and sample_val ignores it entirely."""
    cfg = DataConfig(dataset="synthetic", image_size=(32, 48), batch_size=4)
    ds = SyntheticData(cfg, max_shift=4.0, style="blobs", n_blobs=20)
    full = ds.sample_train(4, iteration=0)
    curr = ds.sample_train(4, iteration=0, max_shift=1.0)
    full_max = float(np.abs(full["flow"]).max())
    assert full_max <= 4.0  # bound holds
    # and the full draw actually exceeds the curriculum bound, so the
    # override comparison below is meaningful (ADVICE r04: the old
    # `== 4.0 or <= 4.0` collapsed to the bound check alone)
    assert full_max > 1.0
    assert float(np.abs(curr["flow"]).max()) <= 1.0
    np.testing.assert_array_equal(full["source"], curr["source"])
    val_a = ds.sample_val(4, 0)
    assert float(np.abs(val_a["flow"]).max()) <= 4.0


def test_build_dataset_dispatch():
    cfg = DataConfig(dataset="synthetic", image_size=(16, 16))
    assert isinstance(build_dataset(cfg), SyntheticData)
    with pytest.raises(KeyError):
        build_dataset(DataConfig(dataset="nope"))


def test_prefetcher_produces_and_closes():
    cfg = DataConfig(dataset="synthetic", image_size=(16, 16), batch_size=2)
    ds = SyntheticData(cfg)
    calls = {"n": 0}

    def produce():
        calls["n"] += 1
        return ds.sample_train(2, iteration=calls["n"])

    pf = Prefetcher(produce, depth=2)
    b1, b2 = pf.get(), pf.get()
    assert b1["source"].shape == (2, 16, 16, 3)
    assert not np.array_equal(b1["source"], b2["source"])
    pf.close()


def test_prefetcher_propagates_errors():
    def boom():
        raise ValueError("decode failed")

    pf = Prefetcher(boom, depth=1)
    with pytest.raises(ValueError, match="decode failed"):
        pf.get()
    pf.close()
