"""Resilience-layer tests (DESIGN.md "Resilience").

The fast-tier chaos suite (`-m "chaos and not slow"`) exercises every
injection site once — decode, assemble, fetch, dispatch, ckpt_save,
ckpt_restore, and the two post-commit tamper sites — against the exact
recovery path that guards it. The slow-tier acceptance drives a full
fit() through all four operational sites in a subprocess (the suite's
warm compile cache makes in-process fits segfault on this host's cpu
jaxlib — hostmesh.py r07 addendum) and pins the determinism contract:
recoverable data faults leave the final params bit-identical to a
fault-free run.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from deepof_tpu.data.pipeline import InputPipeline, derive_batch_rng
from deepof_tpu.resilience import verify as ckpt_verify
from deepof_tpu.resilience.faults import (
    FaultConfig,
    FaultInjector,
    InjectedFault,
    build_injector,
)
from deepof_tpu.resilience.healing import HealingSampler, QuarantineError
from deepof_tpu.train.checkpoint import CheckpointManager
from deepof_tpu.train.metrics_log import AsyncFetcher, SyncFetcher
from deepof_tpu.train.state import TrainState

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ injector

def test_build_injector_disabled_is_none():
    """Zero-overhead contract: a disabled config never constructs an
    injector (sites guard on `is not None`)."""
    assert build_injector(None) is None
    assert build_injector(FaultConfig()) is None
    assert build_injector(FaultConfig(enabled=True)) is not None


def test_injector_probability_deterministic():
    """Probability scheduling is a pure function of (seed, site, index):
    identical across injector instances, different across seeds."""
    mk = lambda s: FaultInjector(FaultConfig(enabled=True, decode_p=0.3,
                                             seed=s))  # noqa: E731
    a = [mk(7).scheduled("decode", i) for i in range(200)]
    b = [mk(7).scheduled("decode", i) for i in range(200)]
    c = [mk(8).scheduled("decode", i) for i in range(200)]
    assert a == b
    assert a != c
    assert 20 <= sum(a) <= 100  # ~30% of 200, loose band


def test_injector_tolerates_scalar_at_override():
    """--set resilience.faults.dispatch_at=9 (unquoted scalar) must
    behave like (9,), not TypeError in the hot loop."""
    inj = FaultInjector(FaultConfig(enabled=True, dispatch_at=9))
    assert inj.hit("dispatch", 9)
    assert not inj.hit("dispatch", 8)


def test_injector_attempt_counting():
    """fail_attempts bounds persistence: a (site, index) faults that many
    checks, then recovers — transient (1) heals on first retry,
    retries+1 exhausts the retry budget and forces substitution."""
    inj = FaultInjector(FaultConfig(enabled=True, decode_at=(3,),
                                    fail_attempts=2))
    for _ in range(2):
        with pytest.raises(InjectedFault):
            inj.check("decode", 3)
    inj.check("decode", 3)  # third attempt recovers
    inj.check("decode", 4)  # unscheduled index never faults
    assert inj.stats()["decode"] == 2


# ----------------------------------------------------- derive_batch_rng

def test_derive_batch_rng_salt_streams():
    """salt=0 must be bit-identical to the pre-salt stream (the
    determinism contract of every existing run); salted streams are
    distinct, deterministic siblings (the substitute draws)."""
    base = np.array([11, 22], np.uint32)
    words = np.array([11, 0, 22, 0, 5, 0], np.uint32)  # pre-salt layout
    legacy = np.random.RandomState(words).randint(0, 2**31, 8)
    np.testing.assert_array_equal(
        derive_batch_rng(base, 5).randint(0, 2**31, 8), legacy)
    np.testing.assert_array_equal(
        derive_batch_rng(base, 5, salt=0).randint(0, 2**31, 8), legacy)
    s1 = derive_batch_rng(base, 5, salt=1).randint(0, 2**31, 8)
    s1b = derive_batch_rng(base, 5, salt=1).randint(0, 2**31, 8)
    np.testing.assert_array_equal(s1, s1b)
    assert not np.array_equal(s1, legacy)


# -------------------------------------------------- self-healing data path

def _sample(i, rng):
    return {"x": rng.randint(0, 1000, 4)}


def _healer(injector=None, **kw):
    kw.setdefault("retries", 2)
    kw.setdefault("backoff_s", 0.0)
    return HealingSampler(lambda i, r: derive_batch_rng(9, i, salt=r),
                          _sample, injector=injector, **kw)


@pytest.mark.chaos
def test_healing_transient_retry_is_bit_identical():
    """decode site, transient: the retry re-derives the rng, so the
    healed stream equals the fault-free stream exactly — the substrate
    of the acceptance determinism pin."""
    inj = FaultInjector(FaultConfig(enabled=True, decode_at=(2,),
                                    fail_attempts=1))
    healed, clean = _healer(inj), _healer()
    for i in range(6):
        np.testing.assert_array_equal(healed(i)["x"], clean(i)["x"])
    assert healed.stats() == {"sample_retries": 1, "quarantined": 0,
                              "substituted": 0}


@pytest.mark.chaos
def test_healing_quarantine_and_deterministic_substitute():
    """decode site, persistent: the retry budget exhausts, the draw is
    quarantined (counted + listed) and replaced by the salt=1 sibling
    draw — a pure function of (stream, index, round), so identical for
    any worker count."""
    events = []
    inj = FaultInjector(FaultConfig(enabled=True, decode_at=(1,),
                                    fail_attempts=3))  # = retries + 1
    h = _healer(inj, log=events.append)
    out = h(1)
    np.testing.assert_array_equal(
        out["x"], _sample(1, derive_batch_rng(9, 1, salt=1))["x"])
    assert h.stats() == {"sample_retries": 2, "quarantined": 1,
                         "substituted": 1}
    assert h.quarantine_log[0]["index"] == 1
    assert events and "quarantined" in events[0]
    # other indices untouched
    np.testing.assert_array_equal(h(2)["x"], _healer()(2)["x"])


def test_healing_heals_corrupt_payload_valueerror():
    """A truncated .flo surfaces as ValueError (io/flo.py) — the
    quarantine path must treat it like any persistent per-sample decode
    fault, not let it kill the run."""
    # "sample X's .flo is truncated": the fault follows the DRAWN sample
    # (round 0's draw), so the substitute redraw — different samples for
    # the same batch index — heals it
    bad = _sample(4, derive_batch_rng(9, 4, salt=0))["x"].tolist()

    def sample(i, rng):
        out = _sample(i, rng)
        if out["x"].tolist() == bad:
            raise ValueError("truncated flow data")
        return out

    h = HealingSampler(lambda i, r: derive_batch_rng(9, i, salt=r), sample,
                       retries=1, backoff_s=0.0, substitutes=2)
    out = h(4)  # substituted from the salt=1 redraw (different draw, same shape)
    assert out["x"].shape == (4,)
    assert h.stats()["quarantined"] == 1 and h.stats()["substituted"] == 1


@pytest.mark.chaos
def test_healing_gives_up_when_data_path_is_down():
    inj = FaultInjector(FaultConfig(enabled=True, decode_at=(0,),
                                    fail_attempts=10**6))
    h = _healer(inj, retries=1, substitutes=1)
    with pytest.raises(QuarantineError, match="data path is down"):
        h(0)


@pytest.mark.chaos
@pytest.mark.parametrize("workers", [0, 2])
def test_pipeline_worker_retry_transient(workers):
    """assemble site: a transient worker error is retried (make_batch is
    index-pure, so the retry is bit-identical) instead of dooming
    delivery from that index on."""
    calls = {}

    def flaky(i):
        calls[i] = calls.get(i, 0) + 1
        if i == 1 and calls[i] == 1:
            raise OSError("transient")
        return {"i": np.array([i])}

    p = InputPipeline(flaky, num_workers=workers, retries=1, backoff_s=0.0)
    try:
        assert [int(p.get()["i"][0]) for _ in range(4)] == [0, 1, 2, 3]
        assert p.stats()["retries"] == 1
    finally:
        p.close()


@pytest.mark.chaos
def test_pipeline_does_not_retry_quarantine_error():
    """QuarantineError is the healing ladder's TERMINAL verdict: the
    pipeline's own retry rung must surface it immediately, not re-run
    the whole exhausted ladder (which would double-count quarantines)."""
    calls = {"n": 0}

    def down(i):
        calls["n"] += 1
        raise QuarantineError("data path down")

    p = InputPipeline(down, num_workers=0, retries=3, backoff_s=0.0)
    try:
        with pytest.raises(QuarantineError):
            p.get()
        assert calls["n"] == 1  # no retries of the terminal error
    finally:
        p.close()


@pytest.mark.chaos
def test_pipeline_retry_exhaustion_still_surfaces():
    def always_bad(i):
        if i == 0:
            raise OSError("persistent")
        return {"i": np.array([i])}

    p = InputPipeline(always_bad, num_workers=1, retries=2, backoff_s=0.0)
    try:
        with pytest.raises(OSError, match="persistent"):
            p.get()
    finally:
        p.close()


# ------------------------------------------------------------ fetch site

@pytest.mark.chaos
@pytest.mark.parametrize("async_", [False, True])
def test_fetcher_retries_transient_fetch_faults(async_):
    inj = FaultInjector(FaultConfig(enabled=True, fetch_at=(0,),
                                    fail_attempts=1))
    got = []
    kw = dict(fetch_fn=lambda t: t, retries=2, backoff_s=0.0, injector=inj)
    f = AsyncFetcher(depth=2, **kw) if async_ else SyncFetcher(**kw)
    try:
        f.submit(("t", 0, True), {"total": 1.0}, lambda tag, m: got.append(m))
        assert f.drain(timeout=10.0)
        assert got == [{"total": 1.0}]
        assert f.stats()["fetch_retries"] == 1
    finally:
        f.close()


@pytest.mark.chaos
def test_fetcher_exhausted_retries_surface():
    inj = FaultInjector(FaultConfig(enabled=True, fetch_at=(0,),
                                    fail_attempts=10))
    f = SyncFetcher(fetch_fn=lambda t: t, retries=1, backoff_s=0.0,
                    injector=inj)
    with pytest.raises(InjectedFault):
        f.submit(("t", 0, True), {"total": 1.0}, lambda *a: None)


# ---------------------------------------------------------- dispatch site

@pytest.mark.chaos
def test_poison_batch_and_dispatch_hit():
    from deepof_tpu.train.loop import _poison_batch

    inj = FaultInjector(FaultConfig(enabled=True, dispatch_at=(6,)))
    assert not inj.hit("dispatch", 5)
    assert inj.hit("dispatch", 6)
    assert not inj.hit("dispatch", 6)  # consume-once
    # stride-proof window scan (steps_per_call > 1): a scheduled step
    # inside a K-wide dispatch window is found exactly once
    inj2 = FaultInjector(FaultConfig(enabled=True, dispatch_at=(9,)))
    assert [s for s in range(8, 12) if inj2.hit("dispatch", s)] == [9]
    assert [s for s in range(8, 12) if inj2.hit("dispatch", s)] == []
    batch = {"source": np.zeros((2, 3, 3, 3), np.float32),
             "label": np.zeros((2,), np.int32)}
    out = _poison_batch(batch)
    assert np.isnan(np.asarray(out["source"])).sum() == 1
    np.testing.assert_array_equal(out["label"], batch["label"])
    assert not np.isnan(batch["source"]).any()  # input not mutated


# ------------------------------------------------------ verified ckpts

def _mk_state(step: int, val: float) -> TrainState:
    tx = optax.sgd(0.1)
    params = {"w": jnp.full((4,), float(val))}
    return TrainState(step=jnp.asarray(step, jnp.int32), params=params,
                      opt_state=tx.init(params), rng=jax.random.PRNGKey(0),
                      tx=tx)


def _largest_file(d):
    return max(((os.path.getsize(p), p)
                for p in glob.glob(os.path.join(d, "**"), recursive=True)
                if os.path.isfile(p)))[1]


def test_manifest_written_and_verifies(tmp_path):
    msgs = []
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=5,
                            log=lambda s, m: msgs.append(m),
                            config_digest="cafe0001")
    mgr.save(_mk_state(1, 1.0))
    mgr.finalize()
    mans = glob.glob(str(tmp_path / "ckpt" / "*.manifest.json"))
    assert len(mans) == 1
    m = ckpt_verify.load_manifest(mans[0])
    assert m["step"] == 1 and m["files"] and m["config_digest"] == "cafe0001"
    assert m["structure"]["num_leaves"] >= 3  # step, w, opt leaves, rng
    rep = ckpt_verify.verify_run(str(tmp_path))
    assert rep["ok"] and rep["valid_steps"] == [1]
    # restore of an intact checkpoint: no fallback, no warnings
    assert int(mgr.restore(_mk_state(0, 0.0)).step) == 1
    assert mgr.stats()["restore_fallbacks"] == 0


@pytest.mark.chaos
def test_restore_falls_back_past_corrupt_and_truncated(tmp_path):
    """The verified-restore ladder: newest (byte-flipped) and middle
    (truncated) checkpoints are skipped with logged warnings; the newest
    VALID step restores."""
    msgs = []
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=5,
                            log=lambda s, m: msgs.append(m))
    for s in (1, 2, 3):
        mgr.save(_mk_state(s, float(s)))
    mgr.finalize()
    p3 = _largest_file(str(tmp_path / "ckpt" / "step_0000000003"))
    with open(p3, "r+b") as f:
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))
    os.remove(_largest_file(str(tmp_path / "ckpt" / "step_0000000002")))

    restored = mgr.restore(_mk_state(0, 0.0))
    assert int(restored.step) == 1
    assert float(np.asarray(restored.params["w"])[0]) == 1.0
    st = mgr.stats()
    assert st["verify_failures"] == 2 and st["restore_fallbacks"] == 1
    assert any("failed verification" in m for m in msgs)
    rep = ckpt_verify.verify_run(str(tmp_path))
    assert rep["corrupt_steps"] == [2, 3] and rep["valid_steps"] == [1]


def test_restore_rejects_structure_mismatch(tmp_path):
    """Files-intact-but-wrong-tree: the manifest's pytree digest must
    block the restore (counted as a verification failure) instead of
    handing orbax a mismatched template."""
    msgs = []
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=5,
                            log=lambda s, m: msgs.append(m))
    mgr.save(_mk_state(1, 1.0))
    mgr.finalize()
    tx = optax.sgd(0.1)
    params = {"w": jnp.zeros((4,)), "extra": jnp.zeros((2,))}
    other = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=tx.init(params), rng=jax.random.PRNGKey(0),
                       tx=tx)
    assert mgr.restore(other) is None
    assert mgr.stats()["verify_failures"] == 1
    assert any("structure mismatch" in m for m in msgs)
    # the matching template still restores
    assert int(mgr.restore(_mk_state(0, 0.0)).step) == 1


@pytest.mark.chaos
def test_ckpt_save_failure_degrades_to_warning(tmp_path):
    inj = FaultInjector(FaultConfig(enabled=True, ckpt_save_at=(2,),
                                    fail_attempts=1))
    msgs = []
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=5,
                            log=lambda s, m: msgs.append(m), injector=inj)
    assert mgr.save(_mk_state(1, 1.0)) is not None
    assert mgr.save(_mk_state(2, 2.0)) is None  # injected: degrade, no raise
    assert mgr.save(_mk_state(3, 3.0)) is not None
    mgr.finalize()
    assert mgr.stats()["save_failures"] == 1
    assert any("previous checkpoint retained" in m for m in msgs)
    # step-1 checkpoint survived the failed step-2 save
    assert mgr.all_steps() == [1, 3]


@pytest.mark.chaos
def test_ckpt_save_prewrite_failure_keeps_committed_checkpoint(tmp_path):
    """A save failure BEFORE the write starts (injected pre-write fault
    on a re-save of an existing step) must not delete the previously
    COMMITTED checkpoint at that step — 'previous checkpoint retained'
    has to be literally true."""
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=5,
                            log=lambda s, m: None)
    assert mgr.save(_mk_state(5, 5.0)) is not None
    mgr.finalize()
    # second manager (fresh process analog) re-saves step 5 with an
    # injected pre-write fault
    inj = FaultInjector(FaultConfig(enabled=True, ckpt_save_at=(5,),
                                    fail_attempts=1))
    msgs = []
    mgr2 = CheckpointManager(str(tmp_path / "ckpt"), keep=5,
                             log=lambda s, m: msgs.append(m), injector=inj)
    assert mgr2.save(_mk_state(5, 6.0)) is None
    # the run-1 checkpoint (and its manifest) survived and restores
    restored = mgr2.restore(_mk_state(0, 0.0))
    assert restored is not None and int(restored.step) == 5
    assert float(np.asarray(restored.params["w"])[0]) == 5.0
    rep = ckpt_verify.verify_run(str(tmp_path))
    assert rep["valid_steps"] == [5], rep


@pytest.mark.chaos
def test_ckpt_tamper_and_restore_injection(tmp_path):
    """ckpt_truncate / ckpt_corrupt tamper the committed dir after the
    manifest (detectable, like real corruption); an injected
    ckpt_restore error falls back like a real read failure."""
    inj = FaultInjector(FaultConfig(enabled=True, ckpt_truncate_at=(2,),
                                    ckpt_corrupt_at=(3,),
                                    ckpt_restore_at=(1,), fail_attempts=1))
    msgs = []
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=5,
                            log=lambda s, m: msgs.append(m), injector=inj)
    for s in (1, 2, 3):
        mgr.save(_mk_state(s, float(s)))
    mgr.finalize()
    assert inj.stats()["ckpt_truncate"] == 1
    assert inj.stats()["ckpt_corrupt"] == 1
    rep = ckpt_verify.verify_run(str(tmp_path))
    assert rep["corrupt_steps"] == [2, 3] and rep["valid_steps"] == [1]
    # steps 3 and 2 fail verification; step 1's restore hits the injected
    # ckpt_restore fault once -> counted, retried as a fallback candidate
    # exhausts -> None (fail_attempts=1 means the SECOND attempt would
    # succeed, but each candidate is tried once per restore call)
    assert mgr.restore(_mk_state(0, 0.0)) is None
    st = mgr.stats()
    assert st["verify_failures"] == 2 and st["restore_failures"] == 1
    # a second restore call: step 1's injected fault is spent -> succeeds
    restored = mgr.restore(_mk_state(0, 0.0))
    assert restored is not None and int(restored.step) == 1


def test_rollback_error_names_ckpt_dir(tmp_path):
    """Satellite: _rollback with no restorable checkpoint must fail with
    an actionable error naming the checkpoint directory."""
    from deepof_tpu.train.loop import Trainer

    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    fake = SimpleNamespace(ckpt=mgr, state=None, logger=None)
    with pytest.raises(FloatingPointError) as ei:
        Trainer._rollback(fake, step=7)
    assert str(tmp_path / "ckpt") in str(ei.value)
    assert "verify-ckpt" in str(ei.value)


# ------------------------------------------------------------- CLI verbs

def test_verify_ckpt_cli_jax_free(tmp_path):
    """verify-ckpt validates manifests without importing jax and exits
    nonzero on corruption (2 when there is nothing to verify)."""
    run = tmp_path / "run"
    ck = run / "ckpt" / "step_0000000001"
    os.makedirs(ck)
    (ck / "a.bin").write_bytes(b"payload" * 64)
    ckpt_verify.write_manifest(str(ck), ckpt_verify.build_manifest(str(ck), 1))
    env = dict(os.environ, PYTHONPATH=REPO)

    def run_cli(path):
        return subprocess.run(
            [sys.executable, "-c",
             "import sys; from deepof_tpu.cli import main; "
             "sys.exit(main(['verify-ckpt', sys.argv[1]]))", path],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO)

    res = run_cli(str(run))
    assert res.returncode == 0, res.stderr[-800:]
    assert json.loads(res.stdout)["ok"] is True
    (ck / "a.bin").write_bytes(b"tampered")
    res = run_cli(str(run))
    assert res.returncode == 1
    assert json.loads(res.stdout)["corrupt_steps"] == [1]
    empty = tmp_path / "empty"
    os.makedirs(empty)
    assert run_cli(str(empty)).returncode == 2


def test_tail_exits_nonzero_when_wedged(tmp_path, capsys):
    from deepof_tpu.cli import main

    (tmp_path / "metrics.jsonl").write_text(json.dumps(
        {"kind": "train", "step": 4, "time": time.time(), "loss": 1.0,
         "skipped_updates": 2, "data_quarantined": 1}) + "\n")
    (tmp_path / "heartbeat.json").write_text(json.dumps(
        {"time": time.time(), "step": 4, "wedged": False}))
    assert main(["tail", "--log-dir", str(tmp_path)]) == 0
    out = json.loads(capsys.readouterr().out)
    # satellite: resilience counters surface in tail
    assert out["resilience"] == {"skipped_updates": 2, "data_quarantined": 1}
    (tmp_path / "heartbeat.json").write_text(json.dumps(
        {"time": time.time(), "step": 4, "wedged": True}))
    assert main(["tail", "--log-dir", str(tmp_path)]) == 3


def test_deep_set_override():
    from deepof_tpu.cli import _apply_override
    from deepof_tpu.core.config import get_config

    cfg = get_config("flyingchairs")
    cfg = _apply_override(cfg, "resilience.faults.decode_p", "0.25")
    cfg = _apply_override(cfg, "resilience.faults.decode_at", "(3, 7)")
    cfg = _apply_override(cfg, "resilience.max_consecutive_skips", "2")
    assert cfg.resilience.faults.decode_p == 0.25
    assert cfg.resilience.faults.decode_at == (3, 7)
    assert cfg.resilience.max_consecutive_skips == 2
    with pytest.raises(SystemExit):
        _apply_override(cfg, "resilience.faults.nope", "1")


def test_counter_summary_surfaces_resilience():
    from deepof_tpu.analyze import _counter_summary

    rec = {"step": 100, "starved": 3, "skipped_updates": 2, "rollbacks": 1,
           "data_quarantined": 4, "ckpt_restore_fallbacks": 1,
           "fault_decode": 5, "data_batches": 10}
    out = _counter_summary(rec)
    assert out["resilience"]["skipped_updates"] == 2
    assert out["resilience"]["rollbacks"] == 1
    assert out["resilience"]["data_quarantined"] == 4
    assert out["resilience"]["ckpt_restore_fallbacks"] == 1
    assert out["resilience"]["fault_decode"] == 5


# --------------------------------------------------- acceptance (slow)

def _train_cli(log_dir, steps, extra, timeout=420):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    res = subprocess.run(
        [sys.executable, "-m", "deepof_tpu", "train", "--preset",
         "flyingchairs", "--synthetic", "--max-steps", str(steps),
         "--log-dir", str(log_dir),
         "--set", "model=flownet_s", "--set", "width_mult=0.25",
         "--set", "train.log_every=1", "--set", "train.eval_every=0",
         "--set", "train.ckpt_every_epochs=1000000",
         "--set", "resilience.data_backoff_s=0.001",
         *extra],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-3000:])
    return json.loads(res.stdout.strip().splitlines()[-1])


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_acceptance_fit_recovers_through_all_sites(tmp_path):
    """ISSUE 4 acceptance: a fit() with injected faults at all four
    sites — persistent 5%+scheduled decode IO errors (quarantine +
    substitute), one dispatch-adjacent non-finite grad (skip in place,
    escalating to rollback at max_consecutive_skips=1), and one
    truncated + one checksum-corrupted checkpoint (rollback falls back
    past both to the step-0 target) — completes to the target steps
    without aborting and reports every event in the run summary."""
    d = tmp_path / "chaos"
    out = _train_cli(
        d, 12,
        ["--set", "train.ckpt_every_steps=4",
         "--set", "train.keep_ckpts=10",
         "--set", "data.num_workers=2",
         "--set", "resilience.max_consecutive_skips=1",
         "--set", "resilience.faults.enabled=true",
         "--set", "resilience.faults.decode_p=0.05",
         "--set", "resilience.faults.decode_at=(2,5)",
         "--set", "resilience.faults.fail_attempts=3",  # data_retries+1
         "--set", "resilience.faults.dispatch_at=(9,)",
         "--set", "resilience.faults.ckpt_truncate_at=(4,)",
         "--set", "resilience.faults.ckpt_corrupt_at=(8,)"])
    # every event class reported in the run summary
    assert out["fault_dispatch"] == 1
    assert out["fault_ckpt_truncate"] == 1 and out["fault_ckpt_corrupt"] == 1
    assert out["fault_decode"] >= 2 and out["data_quarantined"] >= 2
    assert out["data_substituted"] == out["data_quarantined"]
    assert out["skipped_updates"] >= 1
    assert out["rollbacks"] >= 1
    assert out["ckpt_restore_fallbacks"] >= 1
    assert out["ckpt_verify_failures"] >= 2

    text = (d / "metrics.jsonl").read_text()
    assert "skipped in place" in text
    assert "failed verification" in text
    assert "rolled back to step 0" in text
    assert "quarantined sample draw" in text
    assert "poisoned with NaN" in text

    # completed to target steps and the surviving checkpoints verify
    train = [json.loads(ln) for ln in text.splitlines()
             if '"kind": "train"' in ln]
    assert max(r["step"] for r in train) == 12
    rep = ckpt_verify.verify_run(str(d))
    assert rep["ok"], rep
    assert 12 in rep["valid_steps"]


@pytest.mark.slow
@pytest.mark.chaos
def test_recoverable_faults_keep_params_bit_identical(tmp_path):
    """ISSUE 4 acceptance, determinism half: with recoverable data
    faults only (transient decode errors healed by retry), the final
    params are bit-identical to a fault-free run at the same seed and
    num_workers."""
    common = ["--set", "data.num_workers=2"]
    _train_cli(tmp_path / "faulty", 6, common + [
        "--set", "resilience.faults.enabled=true",
        "--set", "resilience.faults.decode_at=(1,3)",
        "--set", "resilience.faults.fail_attempts=1"])
    _train_cli(tmp_path / "clean", 6, common)

    params = {}
    for name in ("faulty", "clean"):
        mgr = CheckpointManager(str(tmp_path / name / "ckpt"), create=False,
                                async_save=False)
        assert mgr.latest_step() == 6
        params[name] = mgr.restore_raw(subtree="params")
    leaves_f = jax.tree_util.tree_leaves(params["faulty"])
    leaves_c = jax.tree_util.tree_leaves(params["clean"])
    assert len(leaves_f) == len(leaves_c) and leaves_f
    for a, b in zip(leaves_f, leaves_c):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_second_sigterm_falls_through_to_default(tmp_path):
    """Satellite: fit()'s graceful handler absorbs the FIRST SIGTERM
    (stop flag); a SECOND must fall through to the default action and
    kill even a run wedged where the stop flag is never polled — no
    operator SIGKILL needed. Subprocess, consistent with the
    warm-cache-read caveat in hostmesh.py."""
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    p = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "_sigterm_worker.py"),
         str(tmp_path / "run")],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    try:
        deadline = time.time() + 300
        wedged = False
        while time.time() < deadline:
            line = p.stdout.readline()
            if "WEDGED" in line:
                wedged = True
                break
            if line == "" and p.poll() is not None:
                break
        assert wedged, "worker never reached its wedged step"
        p.send_signal(signal.SIGTERM)  # absorbed: graceful stop flag
        # generous margin: on a loaded host, slow signal delivery must not
        # let the second SIGTERM land before the first was handled
        time.sleep(2.0)
        assert p.poll() is None, "first SIGTERM must not kill a wedged run"
        p.send_signal(signal.SIGTERM)  # escalates to the default action
        rc = p.wait(timeout=120)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == -signal.SIGTERM, rc
