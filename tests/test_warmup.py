"""Execution-layer tests: persistent compile cache + AOT warmup.

Pins the layer's core contract (ISSUE r06 acceptance): after `warmup`
populates the on-disk cache for a config, a cold process reaches
first-step execution with ZERO recompilations — the train-step
executable loads from `artifacts/xla_cache` instead of paying XLA
inside a scarce tunnel window. "Cold process" is simulated in-process
with `jax.clear_caches()` (drops jax's in-memory jit/pjit caches, so
the next call re-lowers and consults the persistent cache exactly as a
fresh interpreter would).
"""

import json
import os

import jax
import numpy as np
import pytest

from deepof_tpu.core.config import (
    DataConfig,
    ExperimentConfig,
    LossConfig,
    OptimConfig,
    TrainConfig,
)
from deepof_tpu.train import warmup

pytestmark = pytest.mark.slow  # full train-step XLA compiles; see pytest.ini


def _cfg(tmp_path, **train_kw) -> ExperimentConfig:
    """The headline PIPELINE (inception flagship pipeline shape is pinned
    on TPU by bench.py; here the suite's thin-trunk convention keeps the
    CPU mesh affordable) with the headline's steps_per_call=4 scan."""
    train = dict(num_epochs=1, log_every=1, eval_every=0,
                 ckpt_every_epochs=10**6, log_dir=str(tmp_path / "run"),
                 eval_amplifier=1.0, eval_clip=(-1e4, 1e4),
                 eval_batch_size=8, seed=0, steps_per_call=4,
                 # explicit True: the auto default disables the cache on
                 # cpu (cross-process read corruption, TrainConfig
                 # comment); these tests exercise it in-process, which
                 # has been stable on this host
                 compile_cache=True,
                 compile_cache_dir=str(tmp_path / "xla_cache"))
    train.update(train_kw)
    return ExperimentConfig(
        name="warmup_test", model="flownet_s", width_mult=0.25,
        loss=LossConfig(weights=(16, 8, 4, 2, 1, 1)),
        optim=OptimConfig(learning_rate=1e-4, epochs_per_decay=2),
        data=DataConfig(dataset="synthetic", image_size=(64, 64),
                        gt_size=(64, 64), batch_size=8),
        train=TrainConfig(**train),
    )


@pytest.fixture
def restore_cache_dir():
    """Tests point the persistent cache at a tmp dir; restore the
    suite-wide dir (conftest's force_cpu_devices) afterwards so later
    tests keep their warm cache."""
    prev = jax.config.jax_compilation_cache_dir
    yield
    warmup.enable_compile_cache(prev)


def test_warmup_cold_then_warm_cache_hit(tmp_path, restore_cache_dir):
    """Second compile of the warmed executables is all hits, no misses —
    the 'second process compiles nothing' counter pin."""
    cfg = _cfg(tmp_path)
    r1 = warmup.warmup_compile(cfg)
    assert r1["cache_dir"] == str(tmp_path / "xla_cache")
    assert r1["cache"]["misses"] >= 2  # train + eval compiled cold
    assert r1["cache"]["hits"] == 0
    assert os.listdir(tmp_path / "xla_cache")  # entries actually on disk

    jax.clear_caches()  # simulate a cold process
    r2 = warmup.warmup_compile(cfg)
    assert r2["cache"]["misses"] == 0
    assert r2["cache"]["hits"] == r1["cache"]["misses"]
    # loading is the point: far cheaper than compiling
    assert r2["train_compile_s"] < r1["train_compile_s"]


def test_warmup_then_trainer_compiles_nothing(tmp_path, restore_cache_dir):
    """The end-to-end acceptance pin: warmup a config, then a cold
    Trainer's FIRST STEP executes with zero train-step recompilations —
    pinned by the compile_cache_misses counter the loop logs. This also
    guards warmup's batch/state spec against drifting from the real
    producer (any aval mismatch = different cache key = a miss here)."""
    from deepof_tpu.train.loop import Trainer

    cfg = _cfg(tmp_path)
    warmup.warmup_compile(cfg, include_eval=False)
    jax.clear_caches()  # cold process: in-memory jit caches gone

    trainer = Trainer(cfg, profile=False)
    trainer.fit(num_epochs=1, max_steps=4)

    records = [json.loads(ln) for ln in
               open(os.path.join(cfg.train.log_dir, "metrics.jsonl"))]
    first = [r for r in records if r.get("kind") == "info"
             and "first step" in str(r.get("message", ""))]
    assert first, "first-step info record missing"
    assert first[-1]["compile_cache_misses"] == 0, \
        "warmed train step recompiled — warmup spec drifted from the loop"
    assert first[-1]["compile_cache_hits"] >= 1


def test_trainer_first_step_counters_present_cold(tmp_path,
                                                  restore_cache_dir):
    """Without warmup the same counters surface a nonzero miss count —
    the observable that distinguishes a cold window from a warm one."""
    from deepof_tpu.train.loop import Trainer

    cfg = _cfg(tmp_path, steps_per_call=1)
    trainer = Trainer(cfg, profile=False)
    trainer.fit(num_epochs=1, max_steps=2)
    records = [json.loads(ln) for ln in
               open(os.path.join(cfg.train.log_dir, "metrics.jsonl"))]
    first = [r for r in records if r.get("kind") == "info"
             and "first step" in str(r.get("message", ""))]
    assert first and first[-1]["compile_cache_misses"] >= 1


def test_enable_after_early_compile_still_initializes(tmp_path,
                                                      restore_cache_dir):
    """jax initializes its cache singleton at most once per process; a
    jit that runs before any cache dir is configured trips that latch
    and every later write silently no-ops (found end-to-end: the CLI's
    import-time jits disabled caching for the whole train process).
    enable_compile_cache must recover by resetting the singleton."""
    import jax.numpy as jnp
    from jax._src import compilation_cache as _cc

    # simulate a process whose first compile predates any cache config
    jax.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()
    jax.clear_caches()
    jax.jit(lambda x: x + 1)(jnp.ones(4))  # trips the init-once latch
    assert _cc._cache is None

    warmup.enable_compile_cache(str(tmp_path / "late_cache"))
    jax.clear_caches()
    jax.jit(lambda x: x * 2)(jnp.ones(4))
    # the singleton must now be live against the late-configured dir
    assert _cc._cache is not None
    assert str(tmp_path / "late_cache") in str(_cc._cache._path)


def test_compile_cache_false_disables_even_when_already_enabled(
        tmp_path, restore_cache_dir):
    """train.compile_cache=False must actually turn caching off — the
    documented escape hatch for the jaxlib cache-writer crash — even in
    a process where an earlier caller (bench's _import_compute, the CPU
    test mesh) already enabled it."""
    from jax._src import compilation_cache as _cc

    warmup.enable_compile_cache(str(tmp_path / "on_cache"))
    cfg = _cfg(tmp_path, compile_cache=False)
    assert warmup.enable_for_config(cfg) is None
    assert jax.config.jax_compilation_cache_dir is None
    assert _cc._cache is None  # singleton dropped: no reads or writes


def test_compile_cache_auto_disables_on_cpu(tmp_path, restore_cache_dir):
    """The auto default (compile_cache=None) must not ENABLE the cache on
    the cpu backend: cross-process cache reads on this host's grafted
    jaxlib intermittently corrupt the heap (bisected r06 — spurious NaN
    rollbacks and rc=139/134 in ~50% of warm CLI runs). Ambient state is
    left alone either way (the suite's process-wide cache must survive a
    default-config Trainer construction)."""
    ambient = str(tmp_path / "ambient_cache")
    warmup.enable_compile_cache(ambient)
    cfg = _cfg(tmp_path, compile_cache=None)
    assert jax.default_backend() == "cpu"  # suite invariant
    assert warmup.enable_for_config(cfg) is None
    # not redirected to cfg's dir, not torn down: ambient untouched
    assert jax.config.jax_compilation_cache_dir == ambient


def test_example_train_batch_matches_producer_stacking(tmp_path):
    """steps_per_call stacking: [K, B, ...] leaves with the dataset's
    dtypes — the aval contract the cache key depends on."""
    from deepof_tpu.data import build_dataset

    cfg = _cfg(tmp_path)
    ds = build_dataset(cfg.data)
    b = warmup.example_train_batch(cfg, ds)
    # the FULL producer key set, label included — extra keys are part of
    # the jitted signature and therefore of the cache key
    assert set(b) == {"source", "target", "flow", "label"}
    assert b["source"].shape[:2] == (4, 8)  # [K, B]
    assert b["source"].dtype == np.float32


def test_warmup_cli_verb_refuses_without_active_cache(tmp_path,
                                                      restore_cache_dir,
                                                      capsys):
    """On cpu the auto default disables the cache; the warmup verb must
    refuse (rc=2, no compile) instead of paying minutes of XLA and
    persisting nothing."""
    from deepof_tpu.cli import main

    rc = main(["warmup", "--preset", "flyingchairs", "--synthetic",
               "--set", "width_mult=0.25", "--set", "model=flownet_s",
               "--no-eval"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "compile_cache=true" in err  # tells the user the opt-in


def test_warmup_cli_verb(tmp_path, restore_cache_dir, capsys):
    """`deepof_tpu warmup` prints one JSON object with compile timings
    and the cache delta, rc=0."""
    from deepof_tpu.cli import main

    rc = main(["warmup", "--preset", "flyingchairs", "--synthetic",
               "--set", "train.compile_cache=true",  # cpu: auto = off
               "--set", f"train.compile_cache_dir={tmp_path / 'cli_cache'}",
               "--set", "width_mult=0.25", "--set", "model=flownet_s",
               "--no-eval"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["train_compile_s"] > 0
    assert out["cache"]["requests"] >= 1
    assert os.listdir(tmp_path / "cli_cache")
