"""End-to-end inference path: train a few steps (synthetic), checkpoint,
then `predict` on image files -> .flo + flow-color png at native resolution."""

import json
import os

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from deepof_tpu.cli import main as cli_main
from deepof_tpu.io.flo import read_flo
pytestmark = pytest.mark.slow  # full-model/train-step compiles; see pytest.ini


def test_predict_cli_roundtrip(tmp_path):
    log_dir = str(tmp_path / "run")
    rc = cli_main([
        "train", "--preset", "flyingchairs", "--model", "flownet_s",
        "--set", "width_mult=0.25",  # thin trunk, see test_train._cfg
        "--synthetic", "--steps", "2", "--log-dir", log_dir,
    ])
    assert rc == 0

    rng = np.random.RandomState(0)
    prev = str(tmp_path / "prev.png")
    nxt = str(tmp_path / "next.png")
    # native resolution different from the 64x64 net input: exercises the
    # resize-back protocol
    cv2.imwrite(prev, rng.randint(0, 255, (48, 96, 3), dtype=np.uint8))
    cv2.imwrite(nxt, rng.randint(0, 255, (48, 96, 3), dtype=np.uint8))

    out_dir = str(tmp_path / "out")
    rc = cli_main([
        "predict", "--preset", "flyingchairs", "--model", "flownet_s",
        "--set", "width_mult=0.25",
        "--synthetic", "--log-dir", log_dir, "--out", out_dir,
        "--pairs", f"{prev}:{nxt}",
    ])
    assert rc == 0

    flow = read_flo(os.path.join(out_dir, "prev_flow.flo"))
    assert flow.shape == (48, 96, 2)
    assert np.isfinite(flow).all()
    png = cv2.imread(os.path.join(out_dir, "prev_flow.png"))
    assert png.shape == (48, 96, 3)
