"""True multi-process DCN-path test (SURVEY.md §5.8).

Spawns 2 subprocess JAX CPU processes (2 virtual devices each) joined via
`jax.distributed.initialize`, runs the multi-host data plumbing
(`local_batch_rows` / `put_global` / stacked steps_per_call /
allgathered eval) inside them, and asserts loss equality with a
single-process run of the identical batches on this process's own
8-device mesh. The experiment setup is shared with the worker
(`_mp_worker.make_setup`) so both sides are guaranteed identical.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _mp_worker  # noqa: E402

from deepof_tpu.parallel.mesh import batch_sharding, build_mesh  # noqa: E402
from deepof_tpu.train.step import make_eval_fn, make_train_step  # noqa: E402

pytestmark = pytest.mark.slow  # 2 extra processes, each compiling 3 steps

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_reference():
    """The same batches/model/optimizer on this process's 8-device mesh."""
    cfg, ds, model, new_state = _mp_worker.make_setup()
    batch = _mp_worker.BATCH
    mesh = build_mesh(cfg.mesh)
    state = new_state()
    step = make_train_step(model, cfg, ds.mean, mesh)
    totals = []
    for k in range(2):
        b = jax.device_put(ds.sample_train(batch, iteration=k),
                           batch_sharding(mesh))
        state, m = step(state, b)
        totals.append(float(jax.device_get(m["total"])))
    eval_fn = make_eval_fn(model, cfg, ds.mean, mesh=mesh)
    vb = jax.device_put(ds.sample_val(batch, 0), batch_sharding(mesh))
    eval_init = float(jax.device_get(eval_fn(new_state().params, vb)["total"]))
    out = eval_fn(state.params, vb)
    return totals, float(jax.device_get(out["total"])), eval_init


def _run_two_process(tmp_path):
    """One 2-process run; returns (returncodes, outputs). A worker that
    outlives the deadline is killed and reported rc=-9/"TIMEOUT" rather
    than raising — the caller's transient-failure retry must see it
    (r04: a TimeoutExpired here errored the test with no retry)."""
    # stale results from a prior attempt must not satisfy the parent's
    # results-complete acceptance for THIS attempt
    for pid in range(2):
        try:
            os.remove(tmp_path / f"proc{pid}.json")
        except OSError:
            pass
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    env = dict(os.environ)
    # a clean interpreter: no sitecustomize (axon backend), no inherited
    # XLA flags from this pytest process (its 8-device count would
    # override the workers' own 2-device setting)
    env.pop("PYTHONPATH", None)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "_mp_worker.py"),
             addr, "2", str(pid), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in range(2)
    ]
    outs, rcs = [], []
    try:
        for p in procs:
            # generous: 3 cold compile legs per worker on a
            # potentially contended single-core host
            try:
                out, _ = p.communicate(timeout=1200)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                out = (out or "") + "\nTIMEOUT: worker exceeded deadline"
            outs.append(out)
            rcs.append(p.returncode)
    finally:
        for p in procs:
            p.kill()
    return rcs, outs


#: Failure signatures of the distributed runtime's hard-deadlined
#: rendezvous/shutdown phases — transient under scheduler contention on
#: this single-core host, deterministic failures look different (worker
#: asserts / JSON mismatches fail every attempt).
_TRANSIENT = ("Gloo context initialization failed", "DEADLINE_EXCEEDED",
              "BarrierError", "CoordinationService", "UNAVAILABLE",
              "TIMEOUT: worker exceeded deadline", "Connection refused",
              "Shutdown barrier", "coordination_service",
              "distributed service detected fatal errors")


def _results_complete(tmp_path) -> bool:
    """Both workers atomically published complete result files — every
    data-path claim is verified; only teardown remained."""
    try:
        for pid in range(2):
            with open(tmp_path / f"proc{pid}.json") as f:
                json.load(f)
        return True
    except (OSError, ValueError):
        return False


def test_two_process_dcn_path(tmp_path):
    # gloo's rendezvous has a hard 30s deadline and the coordination
    # service's shutdown barrier a similar one; a contended scheduler
    # (full suite + background jobs) can blow either transiently. Up to
    # 3 attempts, each logged — a deterministic failure fails them all.
    # (A longer rendezvous timeout would be preferable, but jaxlib's
    # make_gloo_tcp_collectives exposes only hostname/interface — the
    # 30s kv-store deadline is baked into the C++ wrapper, checked
    # jax 0.9: no Python-reachable knob.) A SHUTDOWN-phase crash after
    # both workers published complete results is a pass: the DCN
    # data-path claims are all in the files; only teardown failed
    # (r05 full-suite observation: "Shutdown barrier has failed" FATAL
    # after every metric had been written and fsync'd).
    for attempt in range(3):
        rcs, outs = _run_two_process(tmp_path)
        if not any(rcs):
            break
        transient = any(sig in o for o in outs for sig in _TRANSIENT)
        accepted = transient and _results_complete(tmp_path)
        print(f"[mp-retry] attempt {attempt + 1} rcs={rcs} "
              f"transient={transient} results_complete={accepted}",
              flush=True)
        if accepted or not transient:
            break
    ok = (not any(rcs)
          or (_results_complete(tmp_path)
              and any(sig in o for o in outs for sig in _TRANSIENT)))
    if not ok:
        for rc, out in zip(rcs, outs):
            assert rc == 0, f"worker failed:\n{out[-3000:]}"

    res = []
    for pid in range(2):
        with open(tmp_path / f"proc{pid}.json") as f:
            res.append(json.load(f))

    # each process owns a disjoint contiguous half of the global batch
    assert res[0]["n_local"] == res[1]["n_local"] == 4
    assert sorted(res[0]["rows"] + res[1]["rows"]) == list(range(8))
    assert not set(res[0]["rows"]) & set(res[1]["rows"])
    # distinct data coords -> decorrelated host sampling streams
    assert res[0]["process_seed"] != res[1]["process_seed"]

    # metrics are replicated: both processes observe identical values
    for key in ("step0_total", "step1_total", "step0_gradnorm",
                "step1_gradnorm", "step0_param_checksum",
                "step1_param_checksum", "scan_totals", "eval_total",
                "eval_flow_sum", "eval_flow_shape"):
        assert res[0][key] == res[1][key], key

    # and they equal the single-process run of the same batches.
    # step0 evaluates at IDENTICAL params (pure reassociation bound);
    # step1 already includes one step of curvature-amplified drift
    ref_totals, ref_eval, ref_eval_init = _single_process_reference()
    np.testing.assert_allclose(res[0]["step0_total"], ref_totals[0], rtol=1e-5)
    np.testing.assert_allclose(res[0]["step1_total"], ref_totals[1], rtol=1e-4)
    # the scanned K=2 path consumed the same two batches
    np.testing.assert_allclose(res[0]["scan_totals"], ref_totals, rtol=1e-4)
    # the assembled global val batch is byte-identical to the full copy
    assert res[0]["val_src_assembled_ok"]
    np.testing.assert_allclose(res[0]["eval_init_total"], ref_eval_init,
                               rtol=1e-5)
    # the 2-step-trained eval compares across DIFFERENT collective
    # topologies (hierarchical 2-process all-reduce vs single-runtime):
    # the reduction-reassociation noise is amplified by the loss curvature
    # each SGD step (measured ~100x/step at lr=1e-3), so exact equality is
    # unattainable by construction; 1e-3 bounds the chaos at lr=1e-4 with
    # an order of margin. The exact-equality claims are the init-params
    # eval and per-step losses above.
    np.testing.assert_allclose(res[0]["eval_total"], ref_eval, rtol=1e-3)
    # allgathered eval output covers the FULL global val batch on each host
    assert res[0]["eval_flow_shape"][0] == 8
