"""Deterministic executable-ledger fixture (tests/test_ledger.py).

Builds `tests/fixtures/ledger/` — a frozen baseline ledger (the shape
`warmup --serve` records: train/eval steps plus a serve lattice slice
and a quality scorer, every row carrying the full obs/ledger.py
ROW_KEYS schema) and two run dirs diffed against it:

  run_clean/  a same-config warm rerun — identical fingerprints, every
              compile a persistent-cache hit, identical footprints.
              diff_ledgers must come back failed=false with zero
              entries in every failure class.
  run_drift/  one of EACH failure class the sentinel exists for:
              train_step's HLO fingerprint drifted, eval_step's compile
              missed where the baseline hit (unexpected recompile), the
              serve cold executable's compile_s blew past
              max(floor, baseline * factor), the warm executable's
              arg+out+temp footprint grew past baseline * factor —
              plus one new and one missing name, which are REPORTED but
              never fail.

Every timestamp and counter is fixed, so the diff_ledgers verdicts
over the fixture are byte-for-byte reproducible; the goldens under
`tests/fixtures/goldens/ledger_diff_{clean,drift}.json` pin them (rc 8
semantics included — `failed` drives tail's exit code). Both run dirs
also carry a minimal metrics.jsonl so `deepof_tpu tail` runs over them
directly. Regenerate with `python tests/fixtures/make_ledger_fixture.py
--record-goldens` from the repo root if the schema ever needs to grow,
then re-verify the pinned verdicts by eye before committing.
"""

import json
import os
import sys

BASE_TIME = 1700000000.0

HERE = os.path.dirname(os.path.abspath(__file__))
LEDGER_DIR = os.path.join(HERE, "ledger")
GOLDENS = os.path.join(HERE, "goldens")

#: the full obs/ledger.py lowering-row schema, frozen values — the
#: fixture is also the ROW_KEYS pin's reference instance
def _row(name, fingerprint, compile_s, hits, misses, *, arg_b, out_b,
         temp_b, flops=2.5e9, bytes_accessed=5.0e8, t=10.0):
    return {
        "kind": "exec", "schema": 1, "name": name,
        "time": BASE_TIME + t, "backend": "cpu",
        "fingerprint": fingerprint, "hlo_chars": 4321,
        "compile_s": compile_s, "compile_kind": "aot",
        "cache_requests": hits + misses,
        "cache_hits": hits, "cache_misses": misses,
        "flops": flops, "bytes_accessed": bytes_accessed,
        "arith_intensity": round(flops / bytes_accessed, 3),
        "roofline_s": flops / (197.0 * 1e12),
        "argument_bytes": arg_b, "output_bytes": out_b,
        "temp_bytes": temp_b, "alias_bytes": 0, "code_bytes": 98765,
        "donated_args": 160, "num_args": 164,
    }


def _timing(name, count, mean_s, roofline_s, t=90.0):
    return {"kind": "exec_timing", "schema": 1, "name": name,
            "time": BASE_TIME + t, "count": count,
            "total_s": round(count * mean_s, 4), "mean_s": mean_s,
            "mfu_nominal": round(roofline_s / mean_s, 6)}


def baseline_rows():
    """The committed-baseline side: a warmed run — every compile hit."""
    return [
        _row("train_step", "aaaa1111bbbb2222", 0.9, 1, 0,
             arg_b=30_000_000, out_b=15_000_000, temp_b=8_000_000, t=10),
        _row("eval_step", "cccc3333dddd4444", 0.4, 1, 0,
             arg_b=10_000_000, out_b=5_000_000, temp_b=2_000_000, t=20),
        _row("serve:32x64:f32:cold", "eeee5555ffff6666", 0.5, 1, 0,
             arg_b=4_000_000, out_b=1_000_000, temp_b=500_000, t=30),
        _row("serve:32x64:f32:warm", "9999aaaa0000bbbb", 0.3, 1, 0,
             arg_b=4_100_000, out_b=1_000_000, temp_b=600_000, t=40),
        _row("quality:32x64", "1212343456567878", 0.2, 1, 0,
             arg_b=2_000_000, out_b=100_000, temp_b=50_000, t=50),
        _timing("serve:32x64:f32:cold", 40, 0.004, 2.5e9 / 197e12),
    ]


def clean_rows():
    """A same-config warm rerun: identical provenance, fresh times."""
    return [dict(r, time=r["time"] + 1000.0) for r in baseline_rows()]


def drift_rows():
    """One of each failure class + one new / one missing name."""
    rows = [
        # fingerprint drift: the computation is not the baseline's
        _row("train_step", "deadbeefdeadbeef", 0.9, 0, 1,
             arg_b=30_000_000, out_b=15_000_000, temp_b=8_000_000,
             t=1010),
        # unexpected recompile: baseline hit, this run missed — same HLO
        _row("eval_step", "cccc3333dddd4444", 0.5, 0, 1,
             arg_b=10_000_000, out_b=5_000_000, temp_b=2_000_000,
             t=1020),
        # compile blowup: 1.2 s > max(floor 1.0, 0.5 * factor 2.0)
        # (cache still hit — wall time regressed, provenance did not)
        _row("serve:32x64:f32:cold", "eeee5555ffff6666", 1.2, 1, 0,
             arg_b=4_000_000, out_b=1_000_000, temp_b=500_000, t=1030),
        # memory growth: footprint * 1.3 > baseline * factor 1.2
        _row("serve:32x64:f32:warm", "9999aaaa0000bbbb", 0.3, 1, 0,
             arg_b=5_330_000, out_b=1_300_000, temp_b=780_000, t=1040),
        # a new lattice entry (reported, never fails) ...
        _row("serve:64x64:f32:cold", "0101232345456767", 0.6, 0, 1,
             arg_b=8_000_000, out_b=2_000_000, temp_b=900_000, t=1050),
        # ... and quality:32x64 deliberately absent (missing)
    ]
    return rows


def write_jsonl(path, rows):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write("".join(json.dumps(r) + "\n" for r in rows))


def main(record_goldens: bool = False) -> None:
    write_jsonl(os.path.join(LEDGER_DIR, "baseline.jsonl"),
                baseline_rows())
    for name, rows in (("run_clean", clean_rows()),
                       ("run_drift", drift_rows())):
        d = os.path.join(LEDGER_DIR, name)
        write_jsonl(os.path.join(d, "ledger.jsonl"), rows)
        # a minimal metrics.jsonl so `deepof_tpu tail` runs over the
        # fixture dir unmodified
        write_jsonl(os.path.join(d, "metrics.jsonl"), [
            {"kind": "train", "step": 10, "time": BASE_TIME + 100.0,
             "total": 0.5}])
    print(f"wrote ledger fixture: {LEDGER_DIR}")
    if record_goldens:
        sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))
        from deepof_tpu.obs.ledger import diff_ledgers

        for name, rows in (("clean", clean_rows()),
                           ("drift", drift_rows())):
            verdict = diff_ledgers(baseline_rows(), rows)
            path = os.path.join(GOLDENS, f"ledger_diff_{name}.json")
            with open(path, "w") as f:
                json.dump(verdict, f)
            print(f"recorded golden: {path} (failed={verdict['failed']})")


if __name__ == "__main__":
    main(record_goldens="--record-goldens" in sys.argv[1:])
