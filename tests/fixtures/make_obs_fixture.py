"""Deterministic observability fixture run dir (tests/test_lint.py).

Builds `tests/fixtures/obs_run/` — a frozen 2-replica fleet drill's
artifacts (supervisor metrics.jsonl + heartbeat.json, replica-N
subdirs, and the two replica /healthz payloads the scrape test serves
from stubs) — with every timestamp and counter fixed, so the
analyze/tail/scrape merge output over it is byte-for-byte reproducible.
The goldens under `tests/fixtures/goldens/` pin that output; this
script regenerates the fixture if the schema ever needs to grow (run
it from the repo root, then re-record the goldens per the test
docstring).

Every serve_* block carries the FULL engine stats() key schema
(histograms, per-tier maps, the warm_start bool, derived percentiles,
an SLO block) so the merge paths are exercised over every merge kind
the registry declares.
"""

import json
import os

BASE_TIME = 1700000000.0
#: the `now` the tests pass to tail_summary/aggregate_processes
FIXED_NOW = BASE_TIME + 123.0

HERE = os.path.dirname(os.path.abspath(__file__))
RUN_DIR = os.path.join(HERE, "obs_run")


def _hist(observed_ms):
    """A LatencyHistogram snapshot built from fixed millisecond
    observations (same arithmetic as obs/export.py, inlined so the
    fixture never drifts with the implementation)."""
    from bisect import bisect_left

    buckets = [0.5 * 2 ** i for i in range(16)]
    counts = [0] * (len(buckets) + 1)
    for ms in observed_ms:
        counts[bisect_left(buckets, ms)] += 1
    return {"buckets_ms": buckets, "counts": counts,
            "sum_ms": round(float(sum(observed_ms)), 3),
            "count": len(observed_ms)}


def replica_stats(idx: int) -> dict:
    """One replica's full serve_* block (the /healthz payload shape)."""
    n = 40 + 10 * idx
    lat = [2.0 + 0.5 * i + idx for i in range(8)]
    slat = [1.0 + 0.25 * i + idx for i in range(4)]
    return {
        "serve_requests": n,
        "serve_responses": n - 2,
        "serve_errors": 2,
        "serve_server_errors": 1,
        "serve_batches": 10 + idx,
        "serve_dispatch_failures": idx,
        "serve_bucket_splits": 1 + idx,
        "serve_tier_splits": 2,
        "serve_warm_splits": idx,
        "serve_requests_by_tier": {"f32": n - 5, "bf16": 5},
        "serve_responses_by_tier": {"f32": n - 7, "bf16": 5},
        "serve_timeout_flushes": 3 + idx,
        "serve_queue_depth": idx,
        "serve_max_queue_depth": 6 + 2 * idx,
        "serve_last_occupancy": 4,
        "serve_occupancy_mean": 3.5 + idx,
        "serve_max_batch": 8,
        "serve_buckets": 2,
        "serve_tiers": 2,
        "serve_latency_p50_ms": 3.0 + idx,
        "serve_latency_p99_ms": 8.0 + idx,
        "serve_requests_per_s": 12.5 + idx,
        "serve_sessions_active": 1 + idx,
        "serve_sessions_created": 3 + idx,
        "serve_sessions_resumed": idx,
        "serve_sessions_expired": 1,
        "serve_sessions_evicted": idx,
        "serve_sessions_deleted": 1,
        "serve_sessions_rebucketed": idx,
        "serve_sessions_frames": 12 + idx,
        "serve_sessions_steps": 9 + idx,
        "serve_sessions_decode_saved": 9 + idx,
        "serve_sessions_warm_steps": 4 + idx,
        "serve_sessions_cold_fallbacks": 2,
        "serve_sessions_warm_start": True,
        "serve_session_latency_hist": _hist(slat),
        "serve_session_latency_p50_ms": 2.0,
        "serve_session_latency_p99_ms": 4.0,
        "serve_latency_hist": _hist(lat),
        "serve_slo": {"latency_ms": 8.0, "bucket_ms": 8.0,
                      "error_budget": 0.01, "requests": n,
                      "breaches": 1 + idx, "failures": 1,
                      "bad_fraction": round((2 + idx) / n, 6),
                      "burn": round((2 + idx) / n / 0.01, 4),
                      "exhausted": True},
    }


def supervisor_block() -> dict:
    """The fleet supervisor+router heartbeat's fleet_* block."""
    return {
        "fleet_replicas": 2,
        "fleet_ready": 2,
        "fleet_states": {"replica-0": "ready", "replica-1": "ready"},
        "fleet_evictions": 1,
        "fleet_crashes": 1,
        "fleet_clean_exits": 0,
        "fleet_wedge_evictions": 1,
        "fleet_stale_evictions": 0,
        "fleet_spawn_failures": 0,
        "fleet_respawns": 1,
        "fleet_broken": 0,
        "fleet_kill_escalations": 0,
        "fleet_requests": 90,
        "fleet_responses": 86,
        "fleet_errors": 4,
        "fleet_server_errors": 2,
        "fleet_failovers": 1,
        "fleet_retries": 2,
        "fleet_shed": 1,
        "fleet_unavailable": 0,
        "fleet_in_flight": 0,
        "fleet_routed": {"replica-0": 46, "replica-1": 44},
        "fleet_draining": False,
        "fleet_sessions_sticky": 2,
        "fleet_session_primes": 4,
        "fleet_session_steps": 18,
        "fleet_session_lost": 1,
        "fleet_session_evicted": 0,
        "fleet_session_expired": 1,
        "fleet_latency_hist": _hist([3.0, 4.0, 5.0, 9.0]),
    }


def heartbeat(step: int, extra: dict) -> dict:
    return {"time": BASE_TIME + 100.0, "pid": 4242 + step, "step": step,
            "beats": 12, "last_step_age_s": 0.4,
            "step_time_median_s": 0.05, "heartbeat_period_s": 5.0,
            "wedged": False, "wedges": 0, "rss_bytes": 123456789,
            "dev_mem_bytes_in_use": None, "dev_mem_peak_bytes": None,
            **extra}


def write(path: str, obj) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        if isinstance(obj, list):  # jsonl
            f.write("".join(json.dumps(r) + "\n" for r in obj))
        else:
            json.dump(obj, f)


def main() -> None:
    sup = supervisor_block()
    write(os.path.join(RUN_DIR, "heartbeat.json"), heartbeat(0, sup))
    write(os.path.join(RUN_DIR, "metrics.jsonl"), [
        {"kind": "warn", "step": 0, "time": BASE_TIME + 10.0,
         "message": "fleet: replica-0 evicted (wedged)"},
        {"kind": "serve", "step": 0, "time": BASE_TIME + 110.0, **sup},
    ])
    for idx in range(2):
        stats = replica_stats(idx)
        d = os.path.join(RUN_DIR, f"replica-{idx}")
        write(os.path.join(d, "heartbeat.json"),
              heartbeat(10 + idx, stats))
        write(os.path.join(d, "metrics.jsonl"), [
            {"kind": "serve", "step": 10 + idx,
             "time": BASE_TIME + 105.0, **stats},
        ])
        # the /healthz payload the scrape stubs serve (same block)
        write(os.path.join(RUN_DIR, f"healthz-replica-{idx}.json"), stats)
    print(f"wrote fixture run dir: {RUN_DIR}")


if __name__ == "__main__":
    main()
