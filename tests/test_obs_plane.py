"""Fleet-wide observability plane (ISSUE 9, DESIGN.md "Fleet
observability"): fixed-bucket latency histograms that merge exactly,
Prometheus /metrics rendering + parsing, the SLO error-budget layer,
emit-time thread naming (the tid-recycle fix), multi-process trace
aggregation with request-id flow arrows, `trace_summary --merge`,
`tail --fleet` / rc 6, and the 2-replica fleet drill acceptance
(router /metrics histogram == exact sum of the replicas').

Fast tier throughout; the drill test spawns two jax-free fake-executor
replica subprocesses (same cost profile as the test_fleet chaos tier).
"""

import dataclasses
import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

from conftest import wait_for_listen

from deepof_tpu.core.config import get_config
from deepof_tpu.obs import aggregate, trace as obs_trace
from deepof_tpu.obs.export import (LATENCY_BUCKETS_MS, LatencyHistogram,
                                   merge_hists, parse_prometheus,
                                   render_prometheus, slo_state,
                                   start_metrics_server)
from deepof_tpu.obs.trace import Tracer
from deepof_tpu.serve.engine import InferenceEngine, make_fake_forward

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _serve_cfg(log_dir, max_batch=4, timeout_ms=5.0, slo_ms=0.0,
               budget=0.01):
    cfg = get_config("flyingchairs")
    return cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=(32, 64), gt_size=(32, 64)),
        serve=dataclasses.replace(cfg.serve, max_batch=max_batch,
                                  batch_timeout_ms=timeout_ms,
                                  host="127.0.0.1", port=0),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6),
                                  log_dir=str(log_dir)),
        obs=dataclasses.replace(cfg.obs, slo_latency_ms=slo_ms,
                                slo_error_budget=budget))


def _pair(rng, hw=(30, 60)):
    return (rng.randint(0, 255, (*hw, 3), dtype=np.uint8),
            rng.randint(0, 255, (*hw, 3), dtype=np.uint8))


# ------------------------------------------------------------ histogram


def test_latency_histogram_fixed_buckets_merge_exactly(rng):
    """The bucket contract: snapshots from independent histograms merge
    by element-wise sum — bucket counts, total count, and sum all equal
    the arithmetic sums (no approximation anywhere)."""
    hists = [LatencyHistogram() for _ in range(3)]
    for h in hists:
        for _ in range(200):
            h.observe(float(rng.uniform(0, 3.0)))
    snaps = [h.snapshot() for h in hists]
    merged = merge_hists(snaps)
    assert merged["count"] == 600
    for i in range(len(LATENCY_BUCKETS_MS) + 1):
        assert merged["counts"][i] == sum(s["counts"][i] for s in snaps)
    assert merged["sum_ms"] == pytest.approx(
        sum(s["sum_ms"] for s in snaps), abs=0.01)
    # a foreign bucket layout must fail loudly, never merge approximately
    bad = dict(snaps[0], buckets_ms=[1.0, 2.0])
    with pytest.raises(ValueError):
        merge_hists([bad])


def test_prometheus_render_parse_round_trip():
    h = LatencyHistogram()
    for ms in (0.4, 3.0, 700.0, 99999.0):
        h.observe(ms / 1e3)
    stats = {"serve_requests": 7, "serve_errors": 0, "flag": True,
             "skipped": None, "name": "ignored-string",
             "serve_requests_by_tier": {"f32": 5, "int8": 2},
             "fleet_states": {"replica-0": "ready", "replica-1": "backoff"},
             "serve_latency_hist": h.snapshot()}
    parsed = parse_prometheus(render_prometheus(stats))
    assert parsed["deepof_serve_requests"] == 7
    assert parsed["deepof_flag"] == 1
    assert parsed['deepof_serve_requests_by_tier{key="int8"}'] == 2
    assert parsed['deepof_fleet_states{key="replica-1",value="backoff"}'] == 1
    # histogram: cumulative buckets, +Inf carries the total
    assert parsed['deepof_serve_latency_ms_bucket{le="+Inf"}'] == 4
    assert parsed['deepof_serve_latency_ms_bucket{le="0.5"}'] == 1
    assert parsed["deepof_serve_latency_ms_count"] == 4
    # the beyond-last-bound observation lives only in +Inf
    assert parsed['deepof_serve_latency_ms_bucket{le="16384"}'] == 3
    assert "deepof_skipped" not in parsed and "deepof_name" not in parsed


def test_slo_state_burn_and_exhaustion():
    h = LatencyHistogram()
    for _ in range(90):
        h.observe(0.010)  # 10 ms: inside a 16 ms target
    for _ in range(10):
        h.observe(0.500)  # 500 ms: breaches
    ok = slo_state(h.snapshot(), requests=100, failures=0,
                   latency_ms=16.0, error_budget=0.2)
    assert ok["breaches"] == 10 and ok["bucket_ms"] == 16.0
    assert ok["burn"] == pytest.approx(0.5)
    assert not ok["exhausted"]
    # failures burn the same budget; a 10% budget is now exhausted
    bad = slo_state(h.snapshot(), requests=100, failures=5,
                    latency_ms=16.0, error_budget=0.1)
    assert bad["breaches"] == 10 and bad["failures"] == 5
    assert bad["exhausted"] and bad["burn"] == pytest.approx(1.5)
    # a target between bounds rounds UP to the next bucket bound (the
    # merge-stable threshold)
    assert slo_state(h.snapshot(), 100, 0, 10.0, 0.5)["bucket_ms"] == 16.0
    # no traffic: never exhausted
    assert not slo_state(None, 0, 0, 16.0, 0.01)["exhausted"]


def test_unmeasurable_slo_target_rejected_at_construction(tmp_path):
    """A latency target past the largest fixed bucket bound could never
    count a breach — the engine (and router) must refuse it loudly at
    construction, not serve a silently-never-burning SLO."""
    cfg = _serve_cfg(tmp_path, slo_ms=LATENCY_BUCKETS_MS[-1] + 1.0)
    with pytest.raises(ValueError, match="slo_latency_ms"):
        InferenceEngine(cfg, forward_fn=make_fake_forward(1.0))
    # the largest bound itself is fine
    cfg_ok = _serve_cfg(tmp_path, slo_ms=LATENCY_BUCKETS_MS[-1])
    eng = InferenceEngine(cfg_ok, forward_fn=make_fake_forward(1.0))
    eng.close()
    # a zero/negative error budget is equally unmeasurable
    cfg_budget = _serve_cfg(tmp_path, slo_ms=16.0, budget=0.0)
    with pytest.raises(ValueError, match="slo_error_budget"):
        InferenceEngine(cfg_budget, forward_fn=make_fake_forward(1.0))


# ------------------------------------------------- emit-time thread name


def test_tracer_recycled_tid_keeps_both_thread_names(tmp_path):
    """The PR 3 hazard: a tid recycled onto a differently-named thread
    must not retroactively rename earlier spans. Names are captured at
    emit time; events() splits one tid into per-name tracks."""
    tr = Tracer(path=str(tmp_path / "t.json"))
    me = threading.current_thread()
    orig = me.name
    try:
        me.name = "first-owner"
        with tr.span("early"):
            pass
        me.name = "second-owner"  # same ident, new name = recycled tid
        with tr.span("late"):
            pass
    finally:
        me.name = orig
    events = tr.events()
    names = {e["tid"]: e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    spans = {e["name"]: e["tid"] for e in events if e["ph"] == "X"}
    assert spans["early"] != spans["late"]  # split tracks
    assert names[spans["early"]] == "first-owner"
    assert names[spans["late"]] == "second-owner"


def test_tracer_collapses_auto_named_ephemeral_threads(tmp_path):
    """ThreadingHTTPServer auto-names one thread per request
    ("Thread-N (process_request_thread)"); a recycled tid under those
    names must NOT mint one single-span track per request — the serial
    is dropped, so they share one track."""
    tr = Tracer(path=str(tmp_path / "t.json"))
    me = threading.current_thread()
    orig = me.name
    try:
        for n in (7, 8, 9):  # same ident, fresh auto-name per "request"
            me.name = f"Thread-{n} (process_request_thread)"
            with tr.span(f"req-{n}"):
                pass
    finally:
        me.name = orig
    events = tr.events()
    meta = [e for e in events if e["ph"] == "M"
            and e["name"] == "thread_name"]
    tids = {e["tid"] for e in events if e["ph"] == "X"}
    assert len(tids) == 1  # one track, not three
    assert any(e["args"]["name"] == "Thread (process_request_thread)"
               for e in meta)


# --------------------------------------------- multi-process aggregation


def _write_synthetic_fleet(run_dir):
    """Router + 2 replicas + a coordinator-style supervisor dir, with
    cross-process request ids and per-process heartbeat/metrics —
    the synthetic shape of a real `serve --replicas 2` run dir."""
    os.makedirs(run_dir, exist_ok=True)
    router = Tracer(path=os.path.join(run_dir, "trace.json"),
                    role="router")
    with router.span("route", request_id="r1-1"):
        time.sleep(0.002)
    with router.span("route", request_id="r1-2"):
        time.sleep(0.001)
    router.flush()
    with open(os.path.join(run_dir, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "warn", "step": 0, "time": time.time(),
                            "message": "fleet replica-1 evicted"}) + "\n")
    hists = []
    for i in range(2):
        rdir = os.path.join(run_dir, f"replica-{i}")
        os.makedirs(rdir, exist_ok=True)
        tr = Tracer(path=os.path.join(rdir, "trace.json"),
                    role="replica", index=i)
        rid = f"r1-{i + 1}"
        with tr.span("serve_enqueue", request_id=rid):
            pass
        with tr.span("serve_dispatch", request_ids=[rid], occupancy=1):
            time.sleep(0.001)
        with tr.span("serve_postprocess", request_ids=[rid], occupancy=1):
            pass
        tr.flush()
        h = LatencyHistogram()
        for k in range(3 + i):
            h.observe(0.004 * (k + 1))
        hists.append(h.snapshot())
        with open(os.path.join(rdir, "heartbeat.json"), "w") as f:
            json.dump({"time": time.time(), "pid": os.getpid() + i,
                       "step": 0, "wedged": False, "serve_requests": 3 + i,
                       "serve_responses": 3 + i, "serve_errors": 0,
                       "serve_latency_hist": hists[-1]}, f)
        with open(os.path.join(rdir, "metrics.jsonl"), "w") as f:
            f.write(json.dumps({"kind": "serve", "step": 0,
                                "time": time.time(),
                                "serve_requests": 3 + i}) + "\n")
    return hists


def test_aggregate_run_pins_merged_trace_schema(tmp_path):
    """The tentpole pin: a synthetic router + 2 replicas run dir merges
    into one trace with >= 3 process tracks, per-request flow arrows
    whose ids chain the SAME request across router and replica, and
    timestamps on one shared clock."""
    run = str(tmp_path / "drill")
    _write_synthetic_fleet(run)
    summary = aggregate.aggregate_run(run)
    assert summary["path"] == os.path.join(run, "trace_merged.json")
    assert summary["requests_correlated"] == 2
    payload = json.load(open(summary["path"]))
    events = payload["traceEvents"]
    tracks = {e["pid"]: e["args"]["name"] for e in events
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert len(tracks) >= 3
    names = set(tracks.values())
    assert any(n.startswith("router") for n in names)
    assert any(n.startswith("replica-0") for n in names)
    assert any(n.startswith("replica-1") for n in names)
    # flow arrows: each correlated request id chains s -> ... -> f, and
    # its events sit on >= 2 distinct process tracks
    for rid in ("r1-1", "r1-2"):
        flow = [e for e in events if e.get("id") == rid
                and e["ph"] in ("s", "t", "f")]
        assert [e["ph"] for e in flow][0] == "s"
        assert [e["ph"] for e in flow][-1] == "f"
        assert len({e["pid"] for e in flow}) >= 2
        # arrows bind inside the spans they link: every flow ts must be
        # >= its span's start on the shared clock
        assert all(isinstance(e["ts"], (int, float)) for e in flow)
    # heartbeat + metrics.jsonl landmarks ride along as instants
    assert any(e["ph"] == "i" and e["name"] == "heartbeat" for e in events)
    assert any(e["ph"] == "i" and e["name"] == "metrics_warn"
               for e in events)
    # per-process pid remap: small distinct pids, originals preserved
    assert sorted(tracks) == [1, 2, 3]


def test_trace_summary_merge_cli(tmp_path, capsys):
    import sys

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_summary

    run = str(tmp_path / "drill")
    _write_synthetic_fleet(run)
    rc = trace_summary.main(["--merge", run, "--top", "5"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "2 correlated across processes" in out
    assert "router" in out and "replica-1" in out
    assert "serve_dispatch" in out
    assert "request journey" in out
    # and the merged artifact is on disk for Perfetto
    assert os.path.exists(os.path.join(run, "trace_merged.json"))


def test_analyze_and_tail_aggregate_process_dirs(tmp_path):
    """analyze()/tail --fleet summarize a whole drill dir: per-process
    blocks plus a merged block whose histogram is the EXACT bucket sum
    of the children's."""
    from deepof_tpu.analyze import aggregate_processes, tail_summary

    run = str(tmp_path / "drill")
    hists = _write_synthetic_fleet(run)
    # discovery is depth-bounded: an artifact nested BELOW a child (an
    # old run copied inside, a checkpoint tree) is never adopted as a
    # phantom process
    deep = os.path.join(run, "replica-0", "old-copy")
    os.makedirs(deep)
    with open(os.path.join(deep, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "serve", "serve_requests": 999,
                            "time": time.time()}) + "\n")
    assert [p["rel"] for p in aggregate.discover_processes(run)] == \
        ["", "replica-0", "replica-1"]
    agg = aggregate_processes(run)
    assert set(agg["processes"]) == {"replica-0", "replica-1"}
    assert agg["processes"]["replica-0"]["serve"]["requests"] == 3
    merged = agg["merged"]
    assert merged["requests"] == 7 and merged["responses"] == 7
    expect = merge_hists(hists)
    assert merged["latency_hist"]["counts"] == expect["counts"]
    assert merged["latency_hist"]["count"] == 7
    # the tail face: --fleet folds the same blocks into the summary
    t = tail_summary(run, fleet=True)
    assert t["processes"]["replica-1"]["serve"]["responses"] == 4
    assert t["merged"]["latency_hist"]["count"] == 7
    # without the flag the summary stays single-process shaped
    assert "processes" not in tail_summary(run)
    # the flag must not be confusable with the fleet_* COUNTER block
    # (a local once shadowed the parameter): a supervisor heartbeat
    # carrying fleet_* keys must not force aggregation with the flag
    # off, and a heartbeat WITHOUT them (an elastic coordinator's) must
    # not suppress it with the flag on
    with open(os.path.join(run, "heartbeat.json"), "w") as f:
        json.dump({"time": time.time(), "pid": os.getpid(), "step": 0,
                   "fleet_requests": 7, "fleet_responses": 7}, f)
    assert "processes" not in tail_summary(run)          # flag off
    with open(os.path.join(run, "heartbeat.json"), "w") as f:
        json.dump({"time": time.time(), "pid": os.getpid(), "step": 0,
                   "elastic_generation": 1}, f)
    assert "processes" in tail_summary(run, fleet=True)  # flag on


# --------------------------------------------------------- /metrics HTTP


def test_serve_metrics_endpoint_matches_engine_counters(rng, tmp_path):
    """/metrics consistency pin over the fake executor: the Prometheus
    scrape equals the engine's live counters — requests, responses, and
    the histogram total — and the SLO block rides along."""
    from deepof_tpu.serve.server import build_server

    cfg = _serve_cfg(tmp_path, slo_ms=0.5, budget=0.001)  # everything
    #   slower than 0.5 ms breaches: the fake 2 ms executor exhausts it
    eng = InferenceEngine(cfg, forward_fn=make_fake_forward(2.0))
    httpd = build_server(cfg, eng)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    wait_for_listen("127.0.0.1", port)
    try:
        futs = [eng.submit(*_pair(rng)) for _ in range(10)]
        for f in futs:
            f.result(timeout=30)
        stats = eng.stats()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.getheader("Content-Type").startswith("text/plain")
            parsed = parse_prometheus(resp.read().decode())
        finally:
            conn.close()
        assert parsed["deepof_serve_requests"] == stats["serve_requests"]
        assert parsed["deepof_serve_responses"] == 10
        # server-side failure count rides the scrape (0 here: the fake
        # executor never fails) — distinguishable from client errors
        assert parsed["deepof_serve_server_errors"] == 0
        assert parsed['deepof_serve_latency_ms_bucket{le="+Inf"}'] == 10
        assert parsed["deepof_serve_latency_ms_count"] == 10
        # SLO layer surfaced on the same scrape (and exhausted: the
        # fake executor cannot beat a 0.5 ms target)
        assert parsed['deepof_serve_slo{key="exhausted"}'] == 1
        assert stats["serve_slo"]["exhausted"] is True
    finally:
        httpd.shutdown()
        httpd.server_close()
        eng.close()


def test_start_metrics_server_coordinator_face():
    """The standalone /metrics endpoint (the elastic coordinator's):
    Prometheus on /metrics, JSON on /healthz, 500 on a stats failure."""
    calls = {"n": 0}

    def stats():
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("boom")
        return {"elastic_generation": 2, "elastic_reforms": 1}

    srv = start_metrics_server(stats)
    port = srv.server_address[1]
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", "/metrics")
            body = conn.getresponse().read().decode()
            assert parse_prometheus(body)["deepof_elastic_generation"] == 2
            conn.request("GET", "/healthz")
            assert json.loads(conn.getresponse().read())[
                "elastic_reforms"] == 1
            conn.request("GET", "/metrics")  # the injected stats failure
            resp = conn.getresponse()
            assert resp.status == 500
            assert json.loads(resp.read())["error"] == "stats_failed"
        finally:
            conn.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_tail_exits_6_on_exhausted_slo_budget(tmp_path, capsys):
    from deepof_tpu.cli import main as cli_main

    run = tmp_path / "slo"
    run.mkdir()
    (run / "metrics.jsonl").write_text("")
    h = LatencyHistogram()
    h.observe(5.0)
    (run / "heartbeat.json").write_text(json.dumps({
        "time": time.time(), "pid": os.getpid(), "step": 0,
        "serve_requests": 100, "serve_responses": 100,
        "serve_slo": slo_state(h.snapshot(), 100, 0, 16.0, 0.001)}))
    rc = cli_main(["tail", "--log-dir", str(run)])
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["serve"]["slo"]["exhausted"] is True
    assert rc == 6


# ----------------------------------------------- fleet drill acceptance


@pytest.mark.chaos
def test_fleet_drill_metrics_exactness_and_merged_trace(rng, tmp_path):
    """ISSUE 9 acceptance: a live 2-replica fleet drill. The router's
    /metrics histogram bucket counts EXACTLY equal the sum of the
    replicas' own counts for the same window, and the run dir merges
    into one trace with >= 3 process tracks and at least one request's
    spans correlated across router and replica by X-Request-Id."""
    cv2 = pytest.importorskip("cv2")  # noqa: F841 - request bodies
    from test_fleet import _fleet_cfg, _flow_body, _get_json, _post, \
        _start_router
    from deepof_tpu.serve.fleet import Fleet

    fleet_dir = tmp_path / "fleet"
    cfg = _fleet_cfg(fleet_dir, max_batch=4, timeout_ms=5.0, exec_ms=3.0)
    cfg = cfg.replace(obs=dataclasses.replace(cfg.obs, trace=True,
                                              slo_latency_ms=4096.0))
    body = _flow_body(rng)
    tracer = obs_trace.install(obs_trace.Tracer(
        path=str(fleet_dir / "trace.json"), role="router"))
    try:
        with Fleet(cfg, 2) as fleet:
            fleet.start()
            fleet.wait_ready(min_ready=2, timeout_s=120)
            router, httpd, port = _start_router(cfg, fleet)
            try:
                statuses = [_post(port, body)[0] for _ in range(16)]
                assert statuses.count(200) == 16
                # traffic quiesced: scrape the router and each replica
                status, metrics_text = _get_json_text(port, "/metrics")
                assert status == 200
                parsed = parse_prometheus(metrics_text)
                replica_hists = []
                for r in fleet.ready_replicas():
                    hstat, health = _get_json(r.port, "/healthz")
                    assert hstat == 200
                    replica_hists.append(health["serve_latency_hist"])
                expect = merge_hists(replica_hists)
                cum = 0
                for bound, count in zip(expect["buckets_ms"],
                                        expect["counts"]):
                    cum += count
                    key = (f'deepof_serve_latency_ms_bucket'
                           f'{{le="{_fmt_bound(bound)}"}}')
                    assert parsed[key] == cum, key
                assert parsed[
                    'deepof_serve_latency_ms_bucket{le="+Inf"}'] == 16
                assert parsed["deepof_serve_latency_ms_count"] == 16
                assert parsed["deepof_serve_responses"] == 16
                # both replicas actually served (affinity map is exercised
                # by test_fleet; here we only need multi-process traces)
                assert parsed["deepof_fleet_responses"] == 16
                # SLO block on the same scrape (healthy: 4 s target)
                assert parsed['deepof_fleet_slo{key="exhausted"}'] == 0
            finally:
                router.draining = True
                httpd.shutdown()
                httpd.server_close()
        # fleet closed: replicas drained gracefully and flushed traces
    finally:
        obs_trace.uninstall()
        tracer.flush()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if all(os.path.exists(str(fleet_dir / f"replica-{i}" /
                                  "trace.json")) for i in range(2)):
            break
        time.sleep(0.2)
    summary = aggregate.aggregate_run(str(fleet_dir))
    names = [p["name"] for p in summary["processes"]]
    assert len(names) >= 3 and "router" in names
    assert {"replica-0", "replica-1"} <= set(names)
    assert summary["requests_correlated"] >= 1
    # the correlated ids are the router's X-Request-Ids (pid-stamped)
    payload = json.load(open(summary["path"]))
    rids = {e["id"] for e in payload["traceEvents"]
            if e.get("ph") in ("s", "t", "f")}
    assert any(str(r).startswith("r") for r in rids)


def _fmt_bound(bound: float) -> str:
    f = float(bound)
    return repr(int(f)) if f == int(f) else repr(f)


def _get_json_text(port, path, timeout=20.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()
