"""Loss-layer tests: masks, Charbonnier normalization, smoothness variants,
multi-frame volume loss, pyramid orchestration, LRN."""

import math

import numpy as np
import jax.numpy as jnp

from deepof_tpu.core.config import LossConfig
from deepof_tpu.losses import (
    border_mask,
    charbonnier,
    loss_interp,
    loss_interp_multi,
    pyramid_loss,
)
from deepof_tpu.losses.pyramid import lrn_normalize, preprocess
from deepof_tpu.ops import local_response_normalization


def test_border_mask():
    m = np.asarray(border_mask(20, 30, 0.1))
    bw = math.ceil(20 * 0.1)
    assert m[:bw].sum() == 0 and m[:, :bw].sum() == 0
    assert m[bw : 20 - bw, bw : 30 - bw].all()
    assert m.sum() == (20 - 2 * bw) * (30 - 2 * bw)


def test_charbonnier():
    out = np.asarray(charbonnier(jnp.asarray([3.0]), 1e-4, 0.5))
    assert np.isclose(out[0], np.sqrt(9 + 1e-8))


def test_lrn_matches_tf_formula(rng):
    """LRN vs direct per-channel windowed-sum formula (r=4, beta=0.7)."""
    x = rng.randn(2, 4, 5, 3).astype(np.float32)
    got = np.asarray(local_response_normalization(jnp.asarray(x)))
    sq = x**2
    want = x / (1.0 + sq.sum(-1, keepdims=True)) ** 0.7  # r=4 >= C: full window
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_lrn_windowed(rng):
    """r < C-1 path: windowed channel sums."""
    x = rng.randn(1, 2, 2, 8).astype(np.float32)
    got = np.asarray(local_response_normalization(jnp.asarray(x), depth_radius=2))
    for d in range(8):
        lo, hi = max(0, d - 2), min(8, d + 3)
        win = (x[..., lo:hi] ** 2).sum(-1)
        np.testing.assert_allclose(got[..., d], x[..., d] / (1 + win) ** 0.7, rtol=1e-5)


def _loss_cfg(**kw):
    base = dict(epsilon=1e-4, alpha_c=0.25, alpha_s=0.37, lambda_smooth=1.0)
    base.update(kw)
    return LossConfig(**base)


def test_perfect_reconstruction_low_photo_loss(rng):
    """Identical frames + zero flow -> photometric loss == charb(0) masked mean
    == (eps^2)^alpha_c, and zero-flow smoothness == (eps^2)^alpha_s terms."""
    img = jnp.asarray(rng.rand(2, 12, 16, 3).astype(np.float32))
    flow = jnp.zeros((2, 12, 16, 2))
    cfg = _loss_cfg()
    ld, recon = loss_interp(flow, img, img, 1.0, cfg)
    eps_term = (1e-4**2) ** 0.25
    assert np.isclose(float(ld["Charbonnier_reconstruct"]), eps_term, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(recon), np.asarray(img), rtol=1e-6)


def test_photo_loss_increases_with_mismatch(rng):
    img1 = jnp.asarray(rng.rand(1, 12, 16, 3).astype(np.float32))
    img2 = jnp.asarray(rng.rand(1, 12, 16, 3).astype(np.float32))
    flow = jnp.zeros((1, 12, 16, 2))
    cfg = _loss_cfg()
    ld_same, _ = loss_interp(flow, img1, img1, 1.0, cfg)
    ld_diff, _ = loss_interp(flow, img1, img2, 1.0, cfg)
    assert float(ld_diff["Charbonnier_reconstruct"]) > float(ld_same["Charbonnier_reconstruct"])


def test_census_photometric(rng):
    """Census loss: zero for identical frames, robust to per-image
    illumination (gain/bias) changes, discriminative for real mismatch."""
    from deepof_tpu.ops.census import census_distance, census_transform

    img = jnp.asarray(rng.rand(1, 16, 20, 3).astype(np.float32))
    other = jnp.asarray(rng.rand(1, 16, 20, 3).astype(np.float32))
    flow = jnp.zeros((1, 16, 20, 2))
    cfg = _loss_cfg(photometric="census")

    ld_same, _ = loss_interp(flow, img, img, 1.0, cfg)
    assert float(ld_same["Charbonnier_reconstruct"]) < 1e-6

    # gain+bias: census distance stays small; raw-RGB charbonnier explodes
    lit = img * 1.3 + 0.1
    d_lit = float(jnp.mean(census_distance(census_transform(img),
                                           census_transform(lit))))
    d_other = float(jnp.mean(census_distance(census_transform(img),
                                             census_transform(other))))
    assert d_lit < 0.15 * d_other

    ld_diff, _ = loss_interp(flow, img, other, 1.0, cfg)
    assert float(ld_diff["Charbonnier_reconstruct"]) > float(
        ld_same["Charbonnier_reconstruct"]) + 1.0

    # differentiable end-to-end (no NaN through warp + census)
    import jax

    g = jax.grad(lambda f: loss_interp(f, img, other, 1.0, cfg)[0]["total"])(
        jnp.ones((1, 16, 20, 2)) * 0.3)
    assert np.isfinite(np.asarray(g)).all()


def test_second_order_smoothness(rng):
    """Affine flow fields pay no 2nd-order penalty (beyond the eps floor)
    but a nonzero 1st-order one; curvature is penalized by both."""
    h, w = 12, 16
    img = jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32))
    xs = jnp.arange(w, dtype=jnp.float32)[None, None, :, None]
    affine = jnp.broadcast_to(0.5 * xs, (1, h, w, 2))  # slope, no curvature
    eps_floor = (1e-4**2) ** 0.37

    cfg1 = _loss_cfg()
    cfg2 = _loss_cfg(smoothness_order=2)
    zero = jnp.zeros((1, h, w, 2))
    base2 = float(loss_interp(zero, img, img, 1.0, cfg2)[0]["U_loss"])

    ld1, _ = loss_interp(affine, img, img, 1.0, cfg1)
    ld2, _ = loss_interp(affine, img, img, 1.0, cfg2)
    # slope costs under 1st order...
    assert float(ld1["U_loss"]) > 2 * eps_floor
    # ...but an affine field is indistinguishable from zero flow at 2nd order
    assert np.isclose(float(ld2["U_loss"]), base2, rtol=1e-3)

    rough = jnp.asarray(rng.rand(1, h, w, 2).astype(np.float32)) * 4
    ldr, _ = loss_interp(rough, img, img, 1.0, cfg2)
    assert float(ldr["U_loss"]) > 2 * base2


def test_occlusion_mask_and_loss(rng):
    """Consistent fw/bw flows stay visible; inconsistent regions drop out
    of the photometric term (and its normalizer)."""
    from deepof_tpu.losses.photometric import occlusion_mask

    cfg = _loss_cfg()
    h, w = 16, 20
    # constant translation u=+2: backward flow -2 exactly cancels
    fw = jnp.zeros((1, h, w, 2)).at[..., 0].set(2.0)
    bw = jnp.zeros((1, h, w, 2)).at[..., 0].set(-2.0)
    occ = occlusion_mask(fw, bw, cfg)
    # interior fully visible (warp clip only disturbs the last columns)
    assert float(jnp.mean(occ[:, :, : w - 3, :])) == 1.0

    # contradictory backward flow -> occluded everywhere
    occ_bad = occlusion_mask(fw, fw * 3.0, cfg)
    assert float(jnp.mean(occ_bad)) < 0.2

    # masked photometric: occluded pixels leave sum AND normalizer
    img1 = jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32))
    img2 = jnp.asarray(rng.rand(1, h, w, 3).astype(np.float32))
    flow = jnp.zeros((1, h, w, 2))
    ld_all, _ = loss_interp(flow, img1, img2, 1.0, cfg,
                            occ_mask=jnp.ones((1, h, w, 1)))
    ld_none, _ = loss_interp(flow, img1, img2, 1.0, cfg,
                             occ_mask=jnp.zeros((1, h, w, 1)))
    ld_plain, _ = loss_interp(flow, img1, img2, 1.0, cfg)
    assert np.isclose(float(ld_all["Charbonnier_reconstruct"]),
                      float(ld_plain["Charbonnier_reconstruct"]), rtol=1e-6)
    # fully-occluded = no reconstruction term, only the per-pixel penalty
    # (occluded interior fraction = 1.0) — occlusion is never free
    assert np.isclose(float(ld_none["Charbonnier_reconstruct"]),
                      cfg.occ_penalty, rtol=1e-6)


def test_pyramid_loss_occlusion_end_to_end(rng):
    """pyramid_loss with a backward pyramid runs and masking changes the
    photometric total (inconsistent bw flow masks pixels out)."""
    from deepof_tpu.losses.pyramid import pyramid_loss

    img1 = jnp.asarray(rng.rand(2, 16, 24, 3).astype(np.float32))
    img2 = jnp.asarray(rng.rand(2, 16, 24, 3).astype(np.float32))
    cfg = _loss_cfg()
    flows = [jnp.asarray(rng.rand(2, 16 // s, 24 // s, 2).astype(np.float32))
             for s in (1, 2)]
    pyr = list(zip(flows, (1.0, 2.0)))
    t_plain, _, _ = pyramid_loss(pyr, img1, img2, cfg)
    bw = [f * 5.0 for f in flows]  # contradicts fw -> heavy masking
    t_masked, losses, _ = pyramid_loss(pyr, img1, img2, cfg,
                                       flow_pyramid_bw=bw)
    assert np.isfinite(float(t_masked))
    assert float(t_masked) != float(t_plain)
    assert all(np.isfinite(float(d["total"])) for d in losses)


def test_smoothness_penalizes_rough_flow(rng):
    img = jnp.asarray(rng.rand(1, 12, 16, 3).astype(np.float32))
    smooth_flow = jnp.ones((1, 12, 16, 2))
    rough = jnp.asarray(rng.randn(1, 12, 16, 2).astype(np.float32) * 5)
    cfg = _loss_cfg()
    ld_s, _ = loss_interp(smooth_flow, img, img, 1.0, cfg)
    ld_r, _ = loss_interp(rough, img, img, 1.0, cfg)
    assert float(ld_r["U_loss"] + ld_r["V_loss"]) > float(ld_s["U_loss"] + ld_s["V_loss"])
    # constant flow has zero gradient inside masks: every one of the H*W
    # cells contributes the (eps^2)^alpha_s floor, normalized by the
    # *image* valid count B*C*interior (reference normalization).
    eps_floor = (1e-4**2) ** 0.37
    interior = (12 - 2 * 2) * (16 - 2 * 2)
    want = eps_floor * 12 * 16 / (3 * interior)
    assert np.isclose(float(ld_s["U_loss"]), want, rtol=1e-3)


def test_depthwise_variant_runs(rng):
    img = jnp.asarray(rng.rand(2, 12, 16, 3).astype(np.float32))
    flow = jnp.asarray(rng.randn(2, 12, 16, 2).astype(np.float32))
    cfg = _loss_cfg(smoothness="depthwise")
    ld, _ = loss_interp(flow, img, img, 2.0, cfg)
    for k in ("total", "Charbonnier_reconstruct", "U_loss", "V_loss"):
        assert np.isfinite(float(ld[k]))


def test_edge_aware_reduces_smoothness(rng):
    """Edge-aware weighting can only shrink the smoothness integrand."""
    img = jnp.asarray(rng.rand(1, 12, 16, 3).astype(np.float32))
    flow = jnp.asarray(rng.randn(1, 12, 16, 2).astype(np.float32) * 3)
    plain, _ = loss_interp(flow, img, img, 1.0, _loss_cfg(smoothness="depthwise"))
    edge, _ = loss_interp(flow, img, img, 1.0, _loss_cfg(smoothness="depthwise", edge_aware=True))
    assert float(edge["U_loss"]) <= float(plain["U_loss"]) + 1e-9
    assert float(edge["V_loss"]) <= float(plain["V_loss"]) + 1e-9


def test_edge_aware_photo_matches_oracle(rng):
    """needImageGradients photometric weighting vs a direct numpy port of
    the reference (`flyingChairsWrapFlow_vgg.py:226-276`): elementwise
    Charbonnier * border mask * per-sample min-max-normalized Sobel
    gradient magnitude of the target image, summed / numValidPixels."""
    img1 = rng.rand(2, 20, 24, 3).astype(np.float32)
    img2 = rng.rand(2, 20, 24, 3).astype(np.float32)
    flow = np.zeros((2, 20, 24, 2), np.float32)
    cfg = _loss_cfg(edge_aware_photo=True)
    ld, _ = loss_interp(jnp.asarray(flow), jnp.asarray(img1),
                        jnp.asarray(img2), 1.0, cfg)

    b, h, w, c = img1.shape
    bw = math.ceil(h * 0.1)
    bmask = np.zeros((h, w), np.float32)
    bmask[bw : h - bw, bw : w - bw] = 1.0

    # gradient mask of the *inputs* (prev frame)
    mn = img1.min(axis=(1, 2, 3), keepdims=True)
    mx = img1.max(axis=(1, 2, 3), keepdims=True)
    scaled = np.clip(np.floor(255.0 * (img1 - mn) / (mx - mn)), 0, 255)
    gray = scaled @ np.array([0.2989, 0.587, 0.114], np.float32)  # (b,h,w)
    kx = np.array([[-1, 0, 1], [-2, 0, 2], [-1, 0, 1]], np.float32)
    pad = np.pad(gray, ((0, 0), (1, 1), (1, 1)))
    gx = np.zeros_like(gray)
    gy = np.zeros_like(gray)
    for dy in range(3):
        for dx in range(3):
            win = pad[:, dy : dy + h, dx : dx + w]
            gx += kx[dy, dx] * win
            gy += kx.T[dy, dx] * win
    mag = np.sqrt(gx**2 + gy**2)
    mmn = mag.min(axis=(1, 2), keepdims=True)
    mmx = mag.max(axis=(1, 2), keepdims=True)
    gmask = np.clip((mag - mmn) / (mmx - mmn), 0.0, 1.0)

    diff = 255.0 * (img2 - img1)  # zero flow -> recon == img2
    ele = (diff**2 + 1e-8) ** 0.25 * bmask[None, :, :, None]
    ele = ele * gmask[..., None]
    want = ele.sum() / (b * c * bmask.sum())
    np.testing.assert_allclose(float(ld["Charbonnier_reconstruct"]), want,
                               rtol=1e-4)
    # weighting must change (reduce) the unweighted loss
    ld0, _ = loss_interp(jnp.asarray(flow), jnp.asarray(img1),
                         jnp.asarray(img2), 1.0, _loss_cfg())
    assert float(ld["Charbonnier_reconstruct"]) < float(
        ld0["Charbonnier_reconstruct"])

    # smoothness side (`flyingChairsWrapFlow_vgg.py:293-301`): both terms
    # weighted by 1-|grad| — closed form with zero flow in the depthwise
    # variant: ele == (eps^2)^alpha_s everywhere, x/y channels identical
    ldd, _ = loss_interp(jnp.asarray(flow), jnp.asarray(img1),
                         jnp.asarray(img2), 1.0,
                         _loss_cfg(edge_aware_photo=True,
                                   smoothness="depthwise"))
    eps_s = (1e-8) ** 0.37
    want_u = (eps_s * 2.0 * ((1.0 - gmask) * bmask[None]).sum()
              / (b * c * bmask.sum() / 3.0 * 2.0))
    np.testing.assert_allclose(float(ldd["U_loss"]), want_u, rtol=1e-4)
    np.testing.assert_allclose(float(ldd["V_loss"]), want_u, rtol=1e-4)

    # multi-frame volume loss must reject the flag, not silently skip it
    import pytest as _pytest

    from deepof_tpu.losses import loss_interp_multi

    with _pytest.raises(ValueError, match="edge_aware_photo"):
        loss_interp_multi(jnp.zeros((1, 20, 24, 4)),
                          jnp.zeros((1, 20, 24, 9)), 1.0,
                          _loss_cfg(edge_aware_photo=True))


def test_default_loss_monotone_toward_gt_on_blobs():
    """Learnability of the DEFAULT FlyingChairs loss on the synthetic
    blobs data (the tools/synthetic_fit.py proxy): walking the flow from
    zero toward the ground truth must strictly decrease the pyramid loss,
    and overshooting in the wrong direction must increase it — i.e. the
    unsupervised objective's minimizer is the true flow and the descent
    path from the zero-flow collapse point is open (DESIGN.md "Learning
    evidence")."""
    import jax

    from deepof_tpu.core.config import DataConfig
    from deepof_tpu.data.datasets import SyntheticData
    from deepof_tpu.models.flownet_s import FLOW_SCALES

    h = w = 64
    ds = SyntheticData(DataConfig(dataset="synthetic", image_size=(h, w),
                                  gt_size=(h, w), batch_size=4),
                       style="blobs")
    b = ds.sample_train(4, iteration=0)
    src = lrn_normalize(preprocess(jnp.asarray(b["source"]), ds.mean))
    tgt = lrn_normalize(preprocess(jnp.asarray(b["target"]), ds.mean))
    gt = jnp.asarray(b["flow"])
    cfg = LossConfig(weights=(16, 8, 4, 2, 1, 1))
    scales = FLOW_SCALES  # finest-first, matches the trained model

    def loss_at(mult):
        pyr = []
        for k, s in enumerate(scales):
            hk, wk = h >> (k + 1), w >> (k + 1)
            fk = (jax.image.resize(gt * mult, (4, hk, wk, 2), "bilinear")
                  * (hk / h) / s)
            pyr.append((fk, s))
        total, _, _ = pyramid_loss(pyr, src, tgt, cfg)
        return float(total)

    path = [loss_at(m) for m in (0.0, 0.25, 0.5, 0.75, 1.0)]
    assert all(a > b for a, b in zip(path, path[1:])), path
    assert loss_at(-1.0) > path[0]  # wrong direction is penalized


def test_multi_frame_matches_stacked_two_frame(rng):
    """For T=2 the volume loss photometric term must equal the 2-frame one."""
    b, h, w = 1, 12, 16
    img1 = rng.rand(b, h, w, 3).astype(np.float32)
    img2 = rng.rand(b, h, w, 3).astype(np.float32)
    flow = (rng.rand(b, h, w, 2).astype(np.float32) - 0.5) * 4
    cfg = _loss_cfg()
    vol = jnp.asarray(np.concatenate([img1, img2], axis=-1))
    ld_multi, rec_m = loss_interp_multi(jnp.asarray(flow), vol, 1.5, cfg)
    ld_two, rec_t = loss_interp(jnp.asarray(flow), jnp.asarray(img1), jnp.asarray(img2), 1.5, cfg)
    assert np.isclose(float(ld_multi["Charbonnier_reconstruct"]),
                      float(ld_two["Charbonnier_reconstruct"]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(rec_m), np.asarray(rec_t), rtol=1e-5)


def test_multi_frame_t10_shapes(rng):
    b, h, w, t = 1, 12, 16, 10
    vol = jnp.asarray(rng.rand(b, h, w, 3 * t).astype(np.float32))
    flows = jnp.asarray(rng.randn(b, h, w, 2 * (t - 1)).astype(np.float32))
    ld, recon = loss_interp_multi(flows, vol, 1.0, _loss_cfg())
    assert recon.shape == (b, h, w, 3 * (t - 1))
    assert np.isfinite(float(ld["total"]))


def test_multi_frame_census_matches_per_pair_two_frame(rng):
    """Volume census photometric (VERDICT r04 weak #4: previously a silent
    Charbonnier fallback) = mean of the per-pair 2-frame census photo
    terms (the volume normalizer sums the folded pairs' masks, so with
    identical per-pair masks the sums average)."""
    b, h, w, t = 2, 24, 28, 3
    frames = [rng.rand(b, h, w, 3).astype(np.float32) for _ in range(t)]
    flows = (rng.rand(b, h, w, 2 * (t - 1)).astype(np.float32) - 0.5) * 4
    cfg = _loss_cfg(photometric="census")
    vol = jnp.asarray(np.concatenate(frames, axis=-1))
    ld_multi, _ = loss_interp_multi(jnp.asarray(flows), vol, 1.5, cfg)
    pair_photos = []
    for k in range(t - 1):
        ld_two, _ = loss_interp(
            jnp.asarray(flows[..., 2 * k : 2 * k + 2]),
            jnp.asarray(frames[k]), jnp.asarray(frames[k + 1]), 1.5, cfg)
        pair_photos.append(float(ld_two["Charbonnier_reconstruct"]))
    assert np.isclose(float(ld_multi["Charbonnier_reconstruct"]),
                      np.mean(pair_photos), rtol=1e-5)
    # and it actually dispatched: differs from the Charbonnier result
    ld_charb, _ = loss_interp_multi(jnp.asarray(flows), vol, 1.5, _loss_cfg())
    assert not np.isclose(float(ld_multi["Charbonnier_reconstruct"]),
                          float(ld_charb["Charbonnier_reconstruct"]),
                          rtol=1e-3)


def test_multi_frame_rejects_unsupported_knobs_by_name(rng):
    """Every knob the volume path cannot honor raises a NAMED error
    instead of silently computing the default (VERDICT r04 weak #4)."""
    import pytest

    flows = jnp.zeros((1, 20, 24, 4))
    vol = jnp.zeros((1, 20, 24, 9))
    for kw, match in (
        (dict(edge_aware=True), "edge_aware"),
        (dict(occlusion=True), "occlusion"),
        (dict(smoothness="depthwise"), "smoothness"),
        (dict(photometric="nope"), "photometric"),
    ):
        with pytest.raises(ValueError, match=match):
            loss_interp_multi(flows, vol, 1.0, _loss_cfg(**kw))


def test_two_frame_canonical_rejects_edge_aware(rng):
    """edge_aware belongs to the depthwise (gen-1) smoothness variant;
    pairing it with canonical previously dropped it silently."""
    import pytest

    img = jnp.asarray(rng.rand(1, 12, 16, 3).astype(np.float32))
    with pytest.raises(ValueError, match="edge_aware"):
        loss_interp(jnp.zeros((1, 12, 16, 2)), img, img, 1.0,
                    _loss_cfg(edge_aware=True))


def test_pyramid_loss_weighting(rng):
    """Weighted total = sum w_k * total_k, finest first."""
    b = 1
    inp = jnp.asarray(rng.rand(b, 16, 24, 3).astype(np.float32))
    out = jnp.asarray(rng.rand(b, 16, 24, 3).astype(np.float32))
    pyr = [
        (jnp.asarray(rng.randn(b, 16, 24, 2).astype(np.float32)), 10.0),
        (jnp.asarray(rng.randn(b, 8, 12, 2).astype(np.float32)), 5.0),
        (jnp.asarray(rng.randn(b, 4, 6, 2).astype(np.float32)), 2.5),
    ]
    cfg = _loss_cfg(weights=(16, 8, 4))
    total, losses, recon = pyramid_loss(pyr, inp, out, cfg)
    want = 16 * losses[0]["total"] + 8 * losses[1]["total"] + 4 * losses[2]["total"]
    assert np.isclose(float(total), float(want), rtol=1e-6)
    assert recon.shape == (b, 16, 24, 3)


def test_preprocess_and_lrn(rng):
    img = jnp.asarray(rng.rand(1, 8, 8, 3).astype(np.float32) * 255)
    mean = [97.533, 99.238, 97.056]
    scaled = preprocess(img, mean)
    assert float(jnp.max(jnp.abs(scaled))) <= 1.0
    norm = lrn_normalize(scaled)
    assert norm.shape == scaled.shape
    # LRN shrinks magnitudes (denominator >= 1)
    assert float(jnp.max(jnp.abs(norm))) <= float(jnp.max(jnp.abs(scaled))) + 1e-6


def test_tiny_level_no_nan(rng):
    """Coarse pyramid levels (h<=2) have an empty border-mask interior; the
    loss must stay finite (regression: NaN via 0-division)."""
    img = jnp.asarray(rng.rand(2, 2, 4, 3).astype(np.float32))
    flow = jnp.asarray(rng.randn(2, 2, 4, 2).astype(np.float32))
    ld, _ = loss_interp(flow, img, img, 0.3125, _loss_cfg())
    for k in ("total", "Charbonnier_reconstruct", "U_loss", "V_loss"):
        assert np.isfinite(float(ld[k])), k
    # a degenerate level contributes exactly zero (not an unnormalized sum)
    assert float(ld["U_loss"]) == 0.0 and float(ld["V_loss"]) == 0.0
    assert float(ld["Charbonnier_reconstruct"]) == 0.0
    vol = jnp.asarray(rng.rand(1, 1, 2, 9).astype(np.float32))
    flows = jnp.asarray(rng.randn(1, 1, 2, 4).astype(np.float32))
    ld2, _ = loss_interp_multi(flows, vol, 1.0, _loss_cfg())
    assert np.isfinite(float(ld2["total"]))


def test_gather_dtype_bf16_close_to_f32():
    """loss.gather_dtype='bfloat16' (opt-in throughput lever) quantizes
    only the warped operand: the loss must stay within bf16's ~0.4%
    relative error of the exact f32 path, and the default must remain
    bit-identical f32."""
    rng = np.random.RandomState(0)
    flow = jnp.asarray(rng.randn(2, 16, 24, 2).astype(np.float32))
    li = jnp.asarray(rng.rand(2, 16, 24, 3).astype(np.float32))
    lo = jnp.asarray(rng.rand(2, 16, 24, 3).astype(np.float32))
    ld32, _ = loss_interp(flow, li, lo, 2.0, _loss_cfg())
    ld32b, _ = loss_interp(flow, li, lo, 2.0,
                           _loss_cfg(gather_dtype="float32"))
    assert float(ld32["total"]) == float(ld32b["total"])
    ld16, _ = loss_interp(flow, li, lo, 2.0,
                          _loss_cfg(gather_dtype="bfloat16"))
    f32, f16 = float(ld32["total"]), float(ld16["total"])
    assert f32 != 0.0
    assert abs(f16 - f32) / abs(f32) < 0.02
