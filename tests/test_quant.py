"""Mixed-precision serving-tier tests (DESIGN.md "Precision tiers").

Fast tier: the pure params->params transforms (int8 round-trip error
bounded by scale/2 PER OUTPUT CHANNEL, bf16 cast, tier-vocabulary
validation), the engine's (bucket, tier) batching + per-tier counters
over the fake executor, the REAL flownet_s end-to-end pins — int8/bf16
EPE vs f32 under a pinned threshold on seeded inputs, bf16 bit-stable
across repeated dispatches — the HTTP `precision` field, router tier
affinity over the flattened (bucket x tier) ladder, the serve_bench
--precision schema, and analyze/tail surfacing of the per-tier counts.

The slow-tier `warmup --serve` zero-recompile acceptance across the
full bucket x tier ladder lives in tests/test_serve.py.
"""

import dataclasses
import importlib.util
import json
import os
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from deepof_tpu.core.config import get_config
from deepof_tpu.serve.engine import InferenceEngine, ServeError
from deepof_tpu.serve.quant import (PRECISIONS, dequantize_params,
                                    int8_roundtrip_max_error, params_nbytes,
                                    quantize_params, resolve_precisions)


def _cfg(max_batch=4, timeout_ms=300.0, image_size=(32, 64),
         precisions=("f32", "bf16", "int8"), **serve_kw):
    cfg = get_config("flyingchairs")
    return cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=image_size, gt_size=image_size),
        serve=dataclasses.replace(cfg.serve, max_batch=max_batch,
                                  batch_timeout_ms=timeout_ms,
                                  precisions=precisions, **serve_kw),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6),
                                  log_dir="/tmp/deepof_quant_test"))


def _img(rng, hw=(30, 60)):
    return rng.randint(1, 255, (*hw, 3), dtype=np.uint8)


def _params_tree(rng):
    """A flax-shaped tree: conv + deconv kernels with wildly different
    per-channel dynamic ranges, biases, norm params, a scalar."""
    k1 = rng.randn(3, 3, 6, 16).astype(np.float32)
    k1 *= np.logspace(-3, 1, 16, dtype=np.float32)  # 4 decades across cout
    return {
        "conv1": {"kernel": k1, "bias": rng.randn(16).astype(np.float32)},
        "decoder": {
            "upconv1": {"kernel": rng.randn(4, 4, 16, 8).astype(np.float32)},
            "pr1": {"kernel": rng.randn(3, 3, 8, 2).astype(np.float32),
                    "bias": np.zeros(2, np.float32)}},
        "norm": {"scale": np.ones(16, np.float32),
                 "bias": np.zeros(16, np.float32)},
        "k": np.float32(2.0),
    }


def _epe(a, b) -> float:
    return float(np.mean(np.sqrt(np.sum((a - b) ** 2, axis=-1))))


# --------------------------------------------------- pure transforms


def test_resolve_precisions_validates_vocabulary():
    assert resolve_precisions(_cfg(precisions=("f32",))) == ("f32",)
    # order preserved: the first entry is the default tier
    assert resolve_precisions(_cfg(precisions=("int8", "f32"))) \
        == ("int8", "f32")
    with pytest.raises(ValueError, match="fp4"):
        resolve_precisions(_cfg(precisions=("f32", "fp4")))
    with pytest.raises(ValueError, match="twice"):
        resolve_precisions(_cfg(precisions=("f32", "f32")))
    assert set(PRECISIONS) == {"f32", "bf16", "int8"}


def test_int8_roundtrip_error_bounded_per_channel(rng):
    """The quantization contract: for every conv kernel,
    |w - dequant(quant(w))| <= scale/2 PER OUTPUT CHANNEL — the
    per-channel scales keep small-dynamic-range channels exact to their
    own half-step, which one per-tensor scale could not."""
    params = _params_tree(rng)
    assert int8_roundtrip_max_error(params) <= 0.5 + 1e-4

    q = quantize_params(params, "int8")
    # kernels became {"q": int8, "scale": f32[cout]}; everything else f32
    assert q["conv1"]["kernel"]["q"].dtype == np.int8
    assert q["conv1"]["kernel"]["scale"].shape == (16,)
    assert q["decoder"]["upconv1"]["kernel"]["q"].dtype == np.int8
    assert q["conv1"]["bias"].dtype == np.float32
    assert q["norm"]["scale"].dtype == np.float32

    # absolute per-channel bound, channel by channel
    w = params["conv1"]["kernel"]
    dq = np.asarray(q["conv1"]["kernel"]["q"], np.float32) \
        * np.asarray(q["conv1"]["kernel"]["scale"])
    scale = np.asarray(q["conv1"]["kernel"]["scale"])
    for c in range(w.shape[-1]):
        assert np.max(np.abs(w[..., c] - dq[..., c])) <= scale[c] / 2 + 1e-7

    # dequantize restores plain f32 kernels (the tree model.apply takes)
    restored = dequantize_params(q)
    assert restored["conv1"]["kernel"].dtype == np.float32
    assert restored["conv1"]["kernel"].shape == w.shape
    # weight bytes: int8 tree is a fraction of the f32 tree
    assert params_nbytes(q) < 0.4 * params_nbytes(params)


def test_bf16_cast_and_f32_identity(rng):
    params = _params_tree(rng)
    b = quantize_params(params, "bf16")
    assert b["conv1"]["kernel"].dtype == "bfloat16"
    assert b["conv1"]["bias"].dtype == "bfloat16"
    assert params_nbytes(b) == params_nbytes(params) // 2
    # f32 is the identity — same objects, zero copies
    assert quantize_params(params, "f32") is params
    # dequantize is a structural no-op on unquantized trees
    d = dequantize_params(params)
    assert np.array_equal(d["conv1"]["kernel"], params["conv1"]["kernel"])
    with pytest.raises(ValueError, match="unknown precision"):
        quantize_params(params, "fp4")


# ------------------------------------------- engine (bucket, tier) axis


class _FakeForward:
    """Counts dispatches and the keys they ran under."""

    def __init__(self):
        self.keys = []
        self.lock = threading.Lock()

    def __call__(self, bucket, x):
        with self.lock:
            self.keys.append(bucket)
        return np.stack([x[..., 0] - x[..., 3], x[..., 1] - x[..., 4]],
                        axis=-1).astype(np.float32)


def test_engine_batches_per_tier_and_counts(rng):
    """Requests on different tiers never share a dispatch; per-tier
    request/response counts and the tier-split counter are live; an
    unknown tier fails structured without touching the batcher."""
    fake = _FakeForward()
    with InferenceEngine(_cfg(max_batch=8, timeout_ms=60.0),
                         forward_fn=fake) as eng:
        futs = [(tier, eng.submit(*(_img(rng), _img(rng)), precision=tier))
                for tier in ("f32", "int8", "int8", "bf16", None)]
        for tier, f in futs:
            r = f.result(timeout=30)
            assert r["precision"] == (tier or "f32")
        with pytest.raises(ServeError) as ei:
            eng.submit(_img(rng), _img(rng), precision="fp4").result(
                timeout=10)
        assert ei.value.code == "bad_request"
        assert "fp4" in str(ei.value)
    stats = eng.stats()
    assert stats["serve_requests_by_tier"] == {"f32": 2, "bf16": 1,
                                               "int8": 2}
    assert stats["serve_responses_by_tier"] == {"f32": 2, "bf16": 1,
                                                "int8": 2}
    assert stats["serve_tiers"] == 3
    assert stats["serve_tier_splits"] >= 1
    assert stats["serve_errors"] == 1
    # the custom executor saw (bucket, tier)-pure dispatches: its first
    # arg is always the plain bucket tuple (compat contract)
    assert all(k == (32, 64) for k in fake.keys)


def test_engine_real_model_tier_pins(rng):
    """The acceptance pins on the REAL jit/AOT path (flownet_s 0.25,
    seeded): (1) int8 and bf16 flows stay within a pinned EPE of the
    f32 tier on identical inputs; (2) the bf16 tier is bit-stable
    across repeated dispatches (same input -> same bits, whatever batch
    it rode in); (3) warm() covers the full bucket x tier ladder."""
    import jax
    import jax.numpy as jnp

    from deepof_tpu.serve.engine import build_serve_model

    cfg = _cfg(max_batch=2, timeout_ms=10.0)
    model = build_serve_model(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 32, 64, 6)))["params"]
    a, b = _img(rng), _img(rng)
    with InferenceEngine(cfg, model_params=(model, params)) as eng:
        warm = eng.warm()
        assert [(tuple(e["bucket"]), e["tier"]) for e in warm["buckets"]] \
            == [((32, 64), t) for t in ("f32", "bf16", "int8")]
        flows = {t: eng.submit(a, b, precision=t).result(timeout=300)["flow"]
                 for t in ("f32", "bf16", "int8")}
        bf16_again = eng.submit(a, b, precision="bf16").result(
            timeout=300)["flow"]
        int8_again = eng.submit(a, b, precision="int8").result(
            timeout=300)["flow"]
    # quantized tiers track f32 on seeded inputs (measured ~0.02-0.03 px
    # at |flow| ~ 3 px on this seed; 0.2 px is the pinned ceiling)
    assert _epe(flows["bf16"], flows["f32"]) < 0.2
    assert _epe(flows["int8"], flows["f32"]) < 0.2
    # and the quantized paths really are different operating points,
    # not aliases of the f32 executable
    assert not np.array_equal(flows["int8"], flows["f32"])
    # deterministic across dispatches (padded fixed-occupancy batches)
    np.testing.assert_array_equal(flows["bf16"], bf16_again)
    np.testing.assert_array_equal(flows["int8"], int8_again)


# ------------------------------------------------------ HTTP precision


def test_http_precision_field(rng):
    import base64
    import http.client

    from conftest import wait_for_listen

    from deepof_tpu.serve.server import build_server

    cfg = _cfg(max_batch=4, timeout_ms=20.0, host="127.0.0.1", port=0)
    with InferenceEngine(cfg, forward_fn=_FakeForward()) as eng:
        httpd = build_server(cfg, eng)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        port = httpd.server_address[1]
        wait_for_listen("127.0.0.1", port, timeout_s=20.0)
        try:
            def b64png(img):
                ok, buf = cv2.imencode(".png", img)
                assert ok
                return base64.b64encode(buf.tobytes()).decode()

            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            body = {"prev": b64png(_img(rng)), "next": b64png(_img(rng))}
            conn.request("POST", "/v1/flow",
                         json.dumps({**body, "precision": "int8"}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert json.loads(resp.read())["precision"] == "int8"

            # no field -> the config's default (first) tier
            conn.request("POST", "/v1/flow", json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert json.loads(resp.read())["precision"] == "f32"

            # unknown tier -> structured 400, batchmates unaffected
            conn.request("POST", "/v1/flow",
                         json.dumps({**body, "precision": "fp4"}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 400
            err = json.loads(resp.read())
            assert err["error"] == "bad_request"
            assert "fp4" in err["message"]

            conn.request("GET", "/healthz")
            health = json.loads(conn.getresponse().read())
            assert health["serve_requests_by_tier"]["int8"] == 1
            conn.close()
        finally:
            httpd.shutdown()
            httpd.server_close()


# ------------------------------------------------- router tier affinity


def test_router_affinity_spreads_bucket_tier_ladder(rng):
    """The affinity map is the FLATTENED (bucket x tier) ladder mod N:
    with 2 buckets x 3 tiers over 6 replicas every pair gets its own
    replica; with one tier the map reduces to the pre-tier bucket map."""
    import base64

    from deepof_tpu.serve.router import Router

    cfg = _cfg(buckets=((32, 64), (64, 64)))
    router = Router(cfg, SimpleNamespace(size=6))

    def body(hw, precision=None):
        ok, buf = cv2.imencode(".png", _img(rng, hw))
        assert ok
        req = {"prev": base64.b64encode(buf.tobytes()).decode()}
        if precision is not None:
            req["precision"] = precision
        return json.dumps(req).encode()

    seen = {}
    for hw, bucket in (((30, 60), (32, 64)), ((60, 60), (64, 64))):
        for tier in ("f32", "bf16", "int8"):
            key = router.route_key(body(hw, tier))
            assert key == (bucket, tier)
            seen[(bucket, tier)] = router._preferred(key)
    assert sorted(seen.values()) == [0, 1, 2, 3, 4, 5]

    # unknown tier routes as the default, the replica owns the 400
    assert router.route_key(body((30, 60), "fp4")) == ((32, 64), "f32")
    # no precision field -> default tier
    assert router.route_key(body((30, 60))) == ((32, 64), "f32")

    # single tier: identical to the pre-tier bucket-index map
    r1 = Router(_cfg(precisions=("f32",), buckets=((32, 64), (64, 64))),
                SimpleNamespace(size=2))
    assert r1._preferred(((32, 64), "f32")) == 0
    assert r1._preferred(((64, 64), "f32")) == 1


# -------------------------------------- serve_bench --precision schema


def _load_serve_bench():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "serve_bench.py")
    spec = importlib.util.spec_from_file_location("serve_bench_q", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serve_bench_precision_schema_smoke():
    sb = _load_serve_bench()
    res = sb.precision_bench(requests=4, gap_ms=0.0, max_batch=2,
                             timeout_ms=5.0, bucket=(32, 64),
                             native_hw=(30, 60),
                             tiers=("f32", "bf16", "int8"))
    for key in sb.PRECISION_REQUIRED_KEYS:
        assert key in res, f"precision_bench result missing {key!r}"
    assert res["mode"] == "precision"
    assert list(res["tiers"]) == ["f32", "bf16", "int8"]
    for tier, block in res["tiers"].items():
        for key in sb.TIER_REQUIRED_KEYS:
            assert key in block, f"tier {tier} missing {key!r}"
        assert block["errors"] == 0
        assert block["requests_per_s"] > 0
    assert res["tiers"]["f32"]["epe_vs_f32"] == 0.0
    assert 0 < res["tiers"]["int8"]["epe_vs_f32"] < 0.2
    assert res["tiers"]["bf16"]["weight_bytes"] \
        < res["tiers"]["f32"]["weight_bytes"]
    assert res["tiers"]["int8"]["weight_bytes"] \
        < res["tiers"]["bf16"]["weight_bytes"]
    json.dumps(res)  # JSON-line contract like bench.py


# ------------------------------------------- analyze/tail per-tier


def test_analyze_and_tail_surface_per_tier_counts(tmp_path):
    from deepof_tpu.analyze import summarize, tail_summary

    by_tier = {"f32": 9, "bf16": 0, "int8": 5}
    serve_rec = {"kind": "serve", "step": 0, "time": time.time(),
                 "serve_requests": 14, "serve_responses": 14,
                 "serve_requests_by_tier": by_tier,
                 "serve_tier_splits": 3, "serve_tiers": 3}
    log_dir = str(tmp_path)
    with open(os.path.join(log_dir, "metrics.jsonl"), "w") as f:
        f.write(json.dumps(serve_rec) + "\n")
    with open(os.path.join(log_dir, "heartbeat.json"), "w") as f:
        json.dump({"time": time.time(), "step": 14, "wedged": False,
                   "serve_requests": 15,
                   "serve_requests_by_tier": {**by_tier, "f32": 10}}, f)

    s = summarize([serve_rec])
    assert s["serve"]["requests_by_tier"] == by_tier
    assert s["serve"]["tier_splits"] == 3

    t = tail_summary(log_dir)
    # the heartbeat (fresher) wins for the live block
    assert t["serve"]["requests_by_tier"]["f32"] == 10
    assert t["serve"]["requests_by_tier"]["int8"] == 5
