"""Shared supervisor core tests (DESIGN.md "Supervision plane").

Unit tier only — everything here is pure or touches nothing but a tmp
dir: the pid-gated heartbeat verdict both supervisors judge children
with (core/supervise.py), the crash-loop/backoff/breaker arithmetic,
the child-dir round-trip, and the autoscaler's decision core
(serve/autoscale.py `evaluate`) driven with fabricated clocks and
signals — no threads, subprocesses or sleeps. The behavior-preserving
half of the extraction contract is pinned by the UNCHANGED fleet +
elastic chaos suites (tests/test_fleet.py, tests/test_elastic.py).
"""

import dataclasses
import json
import os
from types import SimpleNamespace

import pytest

from deepof_tpu.core import supervise
from deepof_tpu.core.config import config_from_dict, get_config
from deepof_tpu.serve.autoscale import Autoscaler
from deepof_tpu.serve.router import Router

# ----------------------------------------------------- heartbeat verdict

NOW = 1_000_000.0


def _hb(pid=42, age=None, wedged=False, t=NOW, **extra):
    hb = {"pid": pid, "time": t, **extra}
    if age is not None:
        hb["last_step_age_s"] = age
    if wedged:
        hb["wedged"] = True
    return hb


def _verdict(hb, pid=42, stale=5.0, stall=2.0, gate=None):
    return supervise.heartbeat_verdict(hb, pid, NOW, stale, stall,
                                       stall_gate=gate)


def test_verdict_healthy():
    assert _verdict(_hb(age=0.1)) == "ok"


def test_verdict_no_heartbeat():
    assert _verdict(None) == "no_heartbeat"


def test_verdict_foreign_pid():
    # a dead incarnation's file can neither vouch for nor condemn the
    # current process — even when it says wedged
    assert _verdict(_hb(pid=41, wedged=True)) == "foreign_pid"


def test_verdict_missing_pid_field_accepted():
    # pre-pid-field heartbeat (or a writer that omits it): not gated
    assert _verdict(_hb(pid=None)) == "ok"


def test_verdict_wedged():
    assert _verdict(_hb(wedged=True)) == "wedged"


def test_verdict_stale():
    assert _verdict(_hb(t=NOW - 6.0)) == "stale"


def test_verdict_stalled_requires_gate_approval():
    hb = _hb(age=10.0)
    assert _verdict(hb, gate=lambda h: True) == "stalled"
    # the gate is the subsystem's "is the stall clock meaningful"
    # predicate: gate says no -> a huge age is not a stall
    assert _verdict(hb, gate=lambda h: False) == "ok"
    # no gate given: age alone judges
    assert _verdict(hb) == "stalled"


def test_verdict_stall_disabled():
    assert _verdict(_hb(age=10.0), stall=0.0) == "ok"
    assert _verdict(_hb(age=10.0), stall=-1.0) == "ok"


def test_verdict_precedence():
    # wedged (the child's own watchdog) outranks stale outranks stalled
    assert _verdict(_hb(wedged=True, t=NOW - 60, age=60)) == "wedged"
    assert _verdict(_hb(t=NOW - 60, age=60)) == "stale"


# ------------------------------------------------- backoff + breaker


def test_crash_loop_counting():
    # only a FAST non-clean death counts toward the breaker
    n = supervise.crash_loop_update(0, fast=True)
    assert n == 1
    n = supervise.crash_loop_update(n, fast=True)
    assert n == 2
    # a slow death resets (the breaker is for crash loops, not a child
    # that ran healthily and then died once)
    assert supervise.crash_loop_update(n, fast=False) == 0
    # a clean rc=0 exit never counts either way (rolling restarts —
    # however quick — must not open the breaker)
    assert supervise.crash_loop_update(2, fast=True, clean=True) == 2
    assert supervise.crash_loop_update(2, fast=False, clean=True) == 2


def test_backoff_delay_exponential_capped():
    assert supervise.backoff_delay(0.1, 5.0, 1) == pytest.approx(0.1)
    assert supervise.backoff_delay(0.1, 5.0, 3) == pytest.approx(0.4)
    assert supervise.backoff_delay(0.1, 5.0, 50) == 5.0
    # historical fleet arithmetic pinned exactly: half-base at a
    # reset (0) count
    assert supervise.backoff_delay(0.1, 5.0, 0) == pytest.approx(0.05)


def test_breaker_open_threshold():
    assert not supervise.breaker_open(2, 3)
    assert supervise.breaker_open(3, 3)
    assert supervise.breaker_open(4, 3)


# ------------------------------------------------------ child plumbing


def test_read_heartbeat_absent_and_torn(tmp_path):
    d = str(tmp_path)
    assert supervise.read_heartbeat(d) is None
    (tmp_path / "heartbeat.json").write_text('{"pid": 42, "tim')  # torn
    assert supervise.read_heartbeat(d) is None
    (tmp_path / "heartbeat.json").write_text('{"pid": 42}')
    assert supervise.read_heartbeat(d) == {"pid": 42}


def test_prepare_child_dir_roundtrip(tmp_path):
    child = str(tmp_path / "replica-0")
    cfg = get_config("flyingchairs").replace(model="flownet_s")
    # a dead incarnation's heartbeat must not speak for the next
    os.makedirs(child)
    with open(os.path.join(child, "heartbeat.json"), "w") as f:
        f.write('{"pid": 1, "wedged": true}')
    cfg_path = supervise.prepare_child_dir(child, cfg)
    assert supervise.read_heartbeat(child) is None
    with open(cfg_path) as f:
        assert config_from_dict(json.load(f)) == cfg


def test_child_env(monkeypatch):
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    env = supervise.child_env(extra={"X_REPLICA": "3"}, force_cpu=True)
    assert env["PYTHONPATH"].split(os.pathsep)[0] == supervise.REPO_ROOT
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["X_REPLICA"] == "3"
    # a caller-exported JAX_PLATFORMS wins over the force_cpu backstop
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    assert supervise.child_env(force_cpu=True)["JAX_PLATFORMS"] == "tpu"
    monkeypatch.delenv("JAX_PLATFORMS")
    assert "JAX_PLATFORMS" not in supervise.child_env()


# ------------------------------------------- autoscaler decision core


def _scaler(**fleet_kw):
    """An Autoscaler with no live fleet/router: `evaluate` is a pure
    function of (clock, signals, accumulated streak state) — exactly
    what these tests drive."""
    defaults = dict(autoscale=True, min_replicas=1, max_replicas=4,
                    autoscale_period_s=0.25, autoscale_up_after_s=2.0,
                    autoscale_down_after_s=20.0,
                    autoscale_up_occupancy=0.75,
                    autoscale_down_occupancy=0.15,
                    autoscale_up_slo_burn=0.5,
                    autoscale_up_cooldown_s=5.0,
                    autoscale_down_cooldown_s=30.0)
    defaults.update(fleet_kw)
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(serve=dataclasses.replace(
        cfg.serve, fleet=dataclasses.replace(cfg.serve.fleet, **defaults)))
    return Autoscaler(cfg, fleet=None, router=None)


def _sig(**kw):
    base = dict(size=2, ready=2, bad_total=0, occupancy=0.4,
                slo_breaches=0, slo_burn=0.0)
    base.update(kw)
    return base


def test_autoscale_unsatisfiable_bounds_rejected():
    # min > max must fail at construction — both at the Autoscaler and
    # at Fleet.__init__ — not be quietly clamped to one side
    with pytest.raises(ValueError, match="min_replicas"):
        _scaler(min_replicas=4, max_replicas=2)
    from deepof_tpu.serve.fleet import Fleet
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(serve=dataclasses.replace(
        cfg.serve, fleet=dataclasses.replace(
            cfg.serve.fleet, autoscale=True,
            min_replicas=4, max_replicas=2)))
    with pytest.raises(ValueError, match="min_replicas"):
        Fleet(cfg)


def test_autoscale_shed_pressure_sustained():
    a = _scaler()
    # new refused work each tick: pressure from t=0, sustained past the
    # 2 s window -> ONE scale-up, reason shed
    assert a.evaluate(0.0, _sig(bad_total=5)) == (None, "holding")
    assert a.evaluate(1.0, _sig(bad_total=9))[0] is None
    assert a.evaluate(2.5, _sig(bad_total=14)) == ("up", "shed")


def test_autoscale_occupancy_pressure_and_hysteresis_band():
    a = _scaler()
    a.evaluate(0.0, _sig(occupancy=0.9))
    # one mid-band tick (between down 0.15 and up 0.75 thresholds)
    # resets the streak: the next decision re-earns its full window
    a.evaluate(1.5, _sig(occupancy=0.4))
    assert a.evaluate(3.0, _sig(occupancy=0.9))[0] is None
    assert a.evaluate(5.5, _sig(occupancy=0.9)) == ("up", "occupancy")


def test_autoscale_slo_burn_needs_breaches_and_burn():
    a = _scaler()
    # burn without NEW breaches is history, not pressure
    a.evaluate(0.0, _sig(slo_burn=0.9))
    assert a.evaluate(2.5, _sig(slo_burn=0.9))[0] is None
    # new breaches while burn is past the threshold: pressure
    a = _scaler()
    a.evaluate(0.0, _sig(slo_breaches=1, slo_burn=0.6))
    assert a.evaluate(2.5, _sig(slo_breaches=2, slo_burn=0.6)) \
        == ("up", "slo_burn")
    # new breaches with budget headroom: not yet
    a = _scaler()
    a.evaluate(0.0, _sig(slo_breaches=1, slo_burn=0.1))
    assert a.evaluate(2.5, _sig(slo_breaches=2, slo_burn=0.1))[0] is None


def test_autoscale_up_bounds_and_cooldown():
    a = _scaler()
    a.evaluate(0.0, _sig(size=4, occupancy=1.0))
    action, reason = a.evaluate(2.5, _sig(size=4, occupancy=1.0))
    assert action is None and "max_replicas" in reason
    assert a.stats()["fleet_autoscale_blocked_max"] == 1
    # a burst must not spawn the whole ladder before the first new
    # replica has compiled: cooldown from the previous scale-up
    a = _scaler()
    a._last_up_m = 2.0
    a.evaluate(3.0, _sig(occupancy=1.0))
    assert a.evaluate(5.5, _sig(occupancy=1.0)) == (None, "up cooldown")
    assert a.evaluate(8.5, _sig(occupancy=1.0))[0] == "up"


def test_autoscale_idle_scale_down_and_floor():
    a = _scaler()
    assert a.evaluate(0.0, _sig(occupancy=0.05)) == (None, "holding")
    assert a.evaluate(10.0, _sig(occupancy=0.05))[0] is None
    assert a.evaluate(20.5, _sig(occupancy=0.05)) \
        == ("down", "sustained idle")
    # at the floor: idle never goes below min_replicas
    a = _scaler()
    a.evaluate(0.0, _sig(size=1, occupancy=0.0))
    action, reason = a.evaluate(20.5, _sig(size=1, occupancy=0.0))
    assert action is None and "min_replicas" in reason
    # the floor also counts SERVING capacity: a broken slot pads size
    # past min while ready sits at it — retiring the only ready replica
    # would leave the pool serving nothing
    a = _scaler()
    a.evaluate(0.0, _sig(size=2, ready=1, occupancy=0.0))
    action, reason = a.evaluate(20.5, _sig(size=2, ready=1, occupancy=0.0))
    assert action is None and "min_replicas" in reason


def test_autoscale_idle_requires_zero_shed():
    # idle is occupancy AND nothing refused: sheds break the idle streak
    a = _scaler()
    a.evaluate(0.0, _sig(occupancy=0.05))
    # a shed delta at t=10 is PRESSURE: the idle streak restarts from
    # the next shed-free tick and re-earns the full 20 s window
    a.evaluate(10.0, _sig(occupancy=0.05, bad_total=3))
    assert a.evaluate(20.5, _sig(occupancy=0.05, bad_total=3))[0] is None
    assert a.evaluate(41.0, _sig(occupancy=0.05, bad_total=3))[0] == "down"


def test_autoscale_down_cooldown_from_any_event():
    # a fresh replica's warm-up idle must not immediately retire its
    # sibling: down cooldown measured from ANY scale event
    a = _scaler()
    a._last_event_m = 15.0
    a.evaluate(16.0, _sig(occupancy=0.05))
    assert a.evaluate(36.5, _sig(occupancy=0.05)) == (None, "down cooldown")
    assert a.evaluate(46.0, _sig(occupancy=0.05))[0] == "down"


def test_autoscale_signals_exclude_broken_from_size():
    # broken slots are terminal (breaker open, no process): counting
    # them toward size would block scale-up at max FOREVER while the
    # surviving replica sheds — signals() must report live slots only
    a = _scaler(max_replicas=4)
    a.fleet = SimpleNamespace(stats=lambda: {
        "fleet_replicas": 4, "fleet_ready": 1,
        "fleet_states": {"replica-0": "ready", "replica-1": "broken",
                         "replica-2": "broken", "replica-3": "broken"}})
    a.router = SimpleNamespace(stats=lambda: {
        "fleet_shed": 10, "fleet_unavailable": 0, "fleet_in_flight": 1})
    sig = a.signals()
    assert sig["size"] == 1 and sig["ready"] == 1
    # sustained shed pressure on those signals scales UP, not blocked
    a.evaluate(0.0, sig)
    sig2 = dict(sig, bad_total=sig["bad_total"] + 5)
    assert a.evaluate(2.5, sig2) == ("up", "shed")


# --------------------------------- router aging under a shrinking pool


class _ShrinkFleet:
    """Duck-typed Fleet whose pool can shrink mid-test: idx -> port,
    None = not ready (tests/test_fleet.py _StubFleet lineage, plus
    retirement — the slot leaves both the ready set and the size)."""

    def __init__(self, ports, host="127.0.0.1"):
        self.host = host
        self.ports = dict(enumerate(ports))
        self.failures = []

    @property
    def size(self):
        return len(self.ports)

    def retire(self, idx):
        del self.ports[idx]

    def ready_replicas(self):
        return [SimpleNamespace(idx=i, port=p)
                for i, p in sorted(self.ports.items()) if p is not None]

    def note_failure(self, idx):
        self.failures.append(idx)

    def stats(self):
        return {"fleet_replicas": self.size,
                "fleet_ready": len(self.ready_replicas())}

    def describe(self):
        return []


def _stub_replica():
    """Minimal replica-shaped HTTP server: POST -> 200 with its port."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            body = json.dumps(
                {"served_by": self.server.server_address[1]}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def test_router_retire_slot_ages_maps_and_demotes_sessions(tmp_path):
    """ISSUE 14 satellite: on scale-down the router's per-index maps
    age out the retired slot (routed folds into the monotonic
    fleet_routed_retired total) and a sticky session pinned there
    demotes to the structured 410 session_lost on its next frame —
    PR 10's contract re-pinned under a shrinking pool."""
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(serve=dataclasses.replace(
        cfg.serve, host="127.0.0.1", port=0))
    stub = _stub_replica()
    try:
        port = stub.server_address[1]
        fleet = _ShrinkFleet([port, port])  # two slots, one stub behind
        router = Router(cfg, fleet)
        # prime a session: the first stream frame pins sid -> a replica
        frame = json.dumps({"session": "s1", "frame": ""}).encode()
        status, _, _ = router.handle_flow("/v1/flow/stream", frame,
                                          "application/json")
        assert status == 200
        stats = router.stats()
        assert stats["fleet_sessions_sticky"] == 1
        pinned = next(int(k.split("-")[1]) for k, n
                      in stats["fleet_routed"].items() if n)
        routed_pinned = stats["fleet_routed"][f"replica-{pinned}"]

        # retire the pinned slot: fleet shrinks, router ages the maps
        fleet.retire(pinned)
        router.retire_slot(pinned)
        stats = router.stats()
        assert f"replica-{pinned}" not in stats["fleet_routed"]
        assert stats["fleet_routed_retired"] == routed_pinned
        # a late release for the aged slot must not resurrect the entry
        router._release(pinned)
        assert router.stats()["fleet_in_flight"] == 0

        # the sticky entry survives until the next frame DEMOTES it —
        # silently dropping the pin would re-prime mid-stream with no
        # signal to the client
        assert router.stats()["fleet_sessions_sticky"] == 1
        status, payload, _ = router.handle_flow("/v1/flow/stream", frame,
                                                "application/json")
        assert status == 410
        assert json.loads(payload)["error"] == "session_lost"
        stats = router.stats()
        assert stats["fleet_session_lost"] == 1
        assert stats["fleet_sessions_sticky"] == 0

        # the demoted session re-primes on the surviving replica
        status, _, _ = router.handle_flow("/v1/flow/stream", frame,
                                          "application/json")
        assert status == 200
        assert router.stats()["fleet_sessions_sticky"] == 1
    finally:
        stub.shutdown()
        stub.server_close()


def test_autoscale_stats_block_registry_shape():
    from deepof_tpu.obs.registry import lookup

    a = _scaler()
    stats = a.stats()
    assert stats["fleet_autoscale_min"] == 1
    assert stats["fleet_autoscale_max"] == 4
    assert stats["fleet_autoscale_up"] == 0
    # every exported key is registry-declared (the PR 12 lint gate
    # checks the source side; this pins the live block)
    for key in stats:
        assert lookup(key) is not None, f"undeclared counter {key}"


# ------------------------------------------- ramp bench (live pool)


@pytest.mark.chaos
def test_serve_bench_ramp_schema_and_load_follower_shape(tmp_path):
    """`serve_bench --ramp` end to end with compressed windows: the
    pinned RAMP_REQUIRED_KEYS schema, plus the load-follower shape the
    ISSUE 14 acceptance names — the floor pool sheds under burst, the
    autoscaler scales up, the scaled pool absorbs the same burst
    (sheds_after_scale << sheds_burst), and NOTHING is silently
    dropped. Scale-down timing is host-sensitive, so the strict
    back-to-the-floor walk is the drill tool's job
    (tools/autoscale_drill.py), not this schema pin's."""
    import importlib.util

    pytest.importorskip("cv2")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "serve_bench.py")
    spec = importlib.util.spec_from_file_location("serve_bench_ramp_t",
                                                  path)
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)

    res = sb.ramp_bench(max_replicas=2, burst_clients=8, warm_s=0.5,
                        burst_s=3.0, idle_s=8.0,
                        log_dir=str(tmp_path / "ramp"))
    for key in sb.RAMP_REQUIRED_KEYS:
        assert key in res, f"ramp_bench result missing {key!r}"
    json.dumps(res)  # JSON-line contract
    assert res["mode"] == "ramp"
    assert res["drops"] == 0
    assert res["evictions"] == 0
    assert res["sheds_burst"] > 0          # the floor pool shed
    assert res["scale_ups"] >= 1           # ...and the pool followed
    assert res["peak_replicas"] == 2
    assert res["sheds_after_scale"] < res["sheds_burst"]
    # the scale events are in the run dir as kind="fleet" records and
    # surface through the analyze/tail scale_events block
    from deepof_tpu.analyze import load_records, summarize

    summary = summarize(load_records(str(tmp_path / "ramp")))
    assert summary["scale_events"]["ups"] == res["scale_ups"]
