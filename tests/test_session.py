"""Streaming video-session tests (DESIGN.md "Streaming sessions").

Unit tier: SessionStore bounds (LRU + TTL, tombstone protocol, resumed
accounting), engine submit_next semantics (prime -> step -> expire),
the bit-identical parity pin (a streamed session's flows == the same
pairs submitted pairwise — the prepare_frame concat contract), the
prime/step/delete HTTP roundtrip, config round-trip + unknown-key
rejection for the SessionConfig block, router sticky affinity against
stub replicas (pin, session_lost demotion, re-prime, DELETE routing),
observability surfacing (stats / /metrics / tail), and the
serve_bench --stream schema + >= 1.5x decode-bound acceptance.

Chaos tier (subprocess replicas): the ISSUE 10 acceptance — SIGKILL a
session's replica mid-walk; the client re-primes from the structured
`session_lost` reply and finishes the walk with 100% of frames
acknowledged, zero silent drops.
"""

import base64
import dataclasses
import http.client
import importlib.util
import json
import os
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from conftest import wait_for_listen

from deepof_tpu.core.config import config_from_dict, get_config
from deepof_tpu.serve.engine import (InferenceEngine, ServeError,
                                     make_fake_forward)
from deepof_tpu.serve.session import SessionExpired, SessionStore

# ----------------------------------------------------------- helpers


def _cfg(max_batch=4, timeout_ms=5.0, buckets=(), image_size=(32, 64),
         log_dir="/tmp/deepof_session_test", session_kw=None, **serve_kw):
    cfg = get_config("flyingchairs")
    session = cfg.serve.session
    if session_kw:
        session = dataclasses.replace(session, **session_kw)
    return cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=image_size, gt_size=image_size),
        serve=dataclasses.replace(cfg.serve, max_batch=max_batch,
                                  batch_timeout_ms=timeout_ms,
                                  buckets=buckets, session=session,
                                  **serve_kw),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6), log_dir=log_dir))


def _img(rng, hw=(30, 60)):
    return rng.randint(1, 255, (*hw, 3), dtype=np.uint8)


def _b64png(img):
    ok, buf = cv2.imencode(".png", img)
    assert ok
    return base64.b64encode(buf.tobytes()).decode()


def _row(rng, hw=(4, 4)):
    return rng.rand(*hw, 3).astype(np.float32)


# ------------------------------------------------------ SessionStore


def test_store_lru_bound_and_tombstone_protocol(rng):
    """The store never holds more than max_sessions; the LRU victim's
    next use is ONE structured SessionExpired (the notification), and
    the retry re-primes counted as `resumed` — never a silent drop."""
    store = SessionStore(max_sessions=2, ttl_s=0, sweep_s=0)
    for sid in ("a", "b", "c"):  # c evicts a (oldest)
        kind, _ = store.advance(sid, _row(rng), (4, 4), (4, 4), "f32")
        assert kind == "primed"
    s = store.stats()
    assert s["serve_sessions_active"] == 2
    assert s["serve_sessions_evicted"] == 1
    # touching b keeps it warm; a new session now evicts c, not b
    assert store.advance("b", _row(rng), (4, 4), (4, 4), "f32")[0] == "step"
    store.advance("d", _row(rng), (4, 4), (4, 4), "f32")
    assert store.contains("b") and not store.contains("c")

    # dead id: exactly one structured notification, then a resume
    with pytest.raises(SessionExpired) as exc:
        store.advance("a", _row(rng), (4, 4), (4, 4), "f32")
    assert exc.value.reason == "evicted"
    kind, _ = store.advance("a", _row(rng), (4, 4), (4, 4), "f32")
    assert kind == "primed"
    s = store.stats()
    assert s["serve_sessions_resumed"] == 1
    assert s["serve_sessions_active"] == 2  # bound still holds
    store.close()


def test_store_ttl_expiry_lazy_and_swept(rng):
    """TTL is exact on access (no sweeper needed) AND the sweeper evicts
    idle sessions in the background; both paths tombstone."""
    store = SessionStore(max_sessions=8, ttl_s=0.15, sweep_s=0)
    store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    time.sleep(0.25)
    with pytest.raises(SessionExpired) as exc:  # lazy: caught on access
        store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    assert exc.value.reason == "expired"
    assert store.stats()["serve_sessions_expired"] == 1

    swept = SessionStore(max_sessions=8, ttl_s=0.1, sweep_s=0.02)
    swept.advance("w", _row(rng), (4, 4), (4, 4), "f32")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if swept.stats()["serve_sessions_expired"] >= 1:
            break
        time.sleep(0.02)
    assert swept.stats()["serve_sessions_expired"] == 1  # swept, no access
    assert swept.stats()["serve_sessions_active"] == 0
    swept.close()
    store.close()


def test_store_delete_ends_clean(rng):
    """DELETE removes without a tombstone: the id's next frame is a
    fresh prime (created, not resumed); deleting the unknown is False."""
    store = SessionStore(max_sessions=4, ttl_s=0, sweep_s=0)
    store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    assert store.delete("v") is True
    assert store.delete("v") is False
    kind, _ = store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    assert kind == "primed"
    s = store.stats()
    assert s["serve_sessions_deleted"] == 1
    assert s["serve_sessions_created"] == 2 and s["serve_sessions_resumed"] == 0
    store.close()


# ----------------------------------------------------------- engine


def test_engine_stream_bit_identical_to_pairwise_walk(rng):
    """THE parity pin: a streamed session's flows are bitwise the flows
    of the same consecutive pairs submitted pairwise — the session cache
    changes host work, never numerics (prepare_pair == concat of two
    prepare_frame halves). Also pins the decode-savings ledger."""
    frames = [_img(rng) for _ in range(6)]
    with InferenceEngine(_cfg(), forward_fn=make_fake_forward(1.0)) as eng:
        pairwise = [eng.submit(a, b).result(30)["flow"]
                    for a, b in zip(frames, frames[1:])]
        primed = eng.submit_next("vid", frames[0]).result(30)
        assert primed["primed"] is True and primed["frames"] == 1
        streamed = [eng.submit_next("vid", f).result(30)
                    for f in frames[1:]]
        for i, (pw, st) in enumerate(zip(pairwise, streamed)):
            assert np.array_equal(pw, st["flow"]), f"pair {i} diverged"
        assert [st["frame_index"] for st in streamed] == [1, 2, 3, 4, 5]
        assert all(st["session"] == "vid" for st in streamed)
        stats = eng.stats()
        assert stats["serve_sessions_frames"] == 6
        assert stats["serve_sessions_steps"] == 5
        assert stats["serve_sessions_decode_saved"] == 5
        # the per-session-frame histogram observed every step
        assert stats["serve_session_latency_hist"]["count"] == 5
        assert stats["serve_session_latency_p50_ms"] is not None


def test_engine_session_expired_is_structured_and_resumable(rng):
    """A TTL-expired session's next frame fails with a structured
    session_expired ServeError that does NOT burn the server-error
    budget; resending the frame re-primes (resumed)."""
    cfg = _cfg(session_kw=dict(ttl_s=0.15, sweep_s=0.02))
    frames = [_img(rng) for _ in range(3)]
    with InferenceEngine(cfg, forward_fn=make_fake_forward(1.0)) as eng:
        eng.submit_next("v", frames[0]).result(30)
        eng.submit_next("v", frames[1]).result(30)
        time.sleep(0.3)
        with pytest.raises(ServeError) as exc:
            eng.submit_next("v", frames[2]).result(30)
        assert exc.value.code == "session_expired"
        stats = eng.stats()
        assert stats["serve_server_errors"] == 0  # protocol, not failure
        assert stats["serve_errors"] == 1
        res = eng.submit_next("v", frames[2]).result(30)
        assert res["primed"] is True
        assert eng.stats()["serve_sessions_resumed"] == 1


def test_engine_rebucket_reprimes_and_bad_frame_keeps_session(rng):
    """A mid-session resolution change re-primes in place (counted);
    a corrupt frame fails alone WITHOUT advancing the session."""
    cfg = _cfg(buckets=((32, 64), (64, 64)))
    a, b = _img(rng, (30, 60)), _img(rng, (30, 60))
    big = _img(rng, (60, 60))  # maps to the (64, 64) bucket
    with InferenceEngine(cfg, forward_fn=make_fake_forward(1.0)) as eng:
        eng.submit_next("v", a).result(30)
        res = eng.submit_next("v", big).result(30)
        assert res["primed"] is True  # rebucketed, not resized silently
        assert eng.stats()["serve_sessions_rebucketed"] == 1

        with pytest.raises(ServeError) as exc:  # undecodable "path"
            eng.submit_next("v", "/nonexistent/frame.png").result(30)
        assert exc.value.code == "bad_input"
        # the session still holds `big`: the next good frame is a step
        res = eng.submit_next("v", _img(rng, (60, 60))).result(30)
        assert "flow" in res and res["frame_index"] == 2


# ------------------------------------------------------------- HTTP


def _post(port, path, body, timeout=30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(body).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _delete(port, path, timeout=30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("DELETE", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _get(port, path, timeout=30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def test_http_stream_prime_step_delete_roundtrip(rng):
    """The whole session lifecycle over HTTP: 202 prime -> 200 steps
    (flow_b64 identical to the pairwise endpoint's) -> DELETE -> 404 on
    re-DELETE -> fresh 202; malformed stream bodies are structured
    400s; /metrics exposes the serve_sessions_* block + histogram."""
    from deepof_tpu.serve.server import build_server

    cfg = _cfg(port=0)
    frames = [_img(rng) for _ in range(3)]
    eng = InferenceEngine(cfg, forward_fn=make_fake_forward(1.0))
    httpd = build_server(cfg, eng)
    threading.Thread(target=httpd.serve_forever, daemon=True,
                     name="session-http").start()
    port = httpd.server_address[1]
    wait_for_listen("127.0.0.1", port)
    try:
        status, p = _post(port, "/v1/flow/stream",
                          {"session": "vid", "frame": _b64png(frames[0])})
        assert status == 202 and p["primed"] and p["frames"] == 1, p
        status, p = _post(port, "/v1/flow/stream",
                          {"session": "vid", "frame": _b64png(frames[1])})
        assert status == 200 and p["session"] == "vid", p
        assert p["frame_index"] == 1
        status, pw = _post(port, "/v1/flow", {"prev": _b64png(frames[0]),
                                              "next": _b64png(frames[1])})
        assert status == 200
        assert pw["flow_b64"] == p["flow_b64"]  # parity through HTTP

        # malformed stream bodies are structured client errors
        status, p = _post(port, "/v1/flow/stream",
                          {"frame": _b64png(frames[2])})
        assert status == 400 and p["error"] == "bad_request", p
        status, p = _post(port, "/v1/flow/stream",
                          {"session": "vid", "frame": "!!notb64!!"})
        assert status == 400, p
        # a slash-bearing id would be unaddressable in the DELETE URL
        # (and router/replica would parse it differently): rejected
        status, p = _post(port, "/v1/flow/stream",
                          {"session": "a/b", "frame": _b64png(frames[2])})
        assert status == 400 and p["error"] == "bad_request", p

        status, p = _delete(port, "/v1/flow/stream/vid")
        assert status == 200 and p["deleted"] is True, p
        status, p = _delete(port, "/v1/flow/stream/vid")
        assert status == 404 and p["error"] == "session_unknown", p
        status, p = _post(port, "/v1/flow/stream",
                          {"session": "vid", "frame": _b64png(frames[2])})
        assert status == 202, p  # deleted id starts clean

        status, text = _get(port, "/metrics")
        text = text.decode()
        assert status == 200
        assert "deepof_serve_sessions_created" in text
        assert "deepof_serve_sessions_decode_saved" in text
        assert "deepof_serve_session_latency_ms_bucket" in text
    finally:
        httpd.shutdown()
        httpd.server_close()
        eng.close()


def test_http_stream_session_expired_is_410(rng):
    """TTL expiry over HTTP is the documented 410 + session_expired
    payload, and resending the same frame re-primes with 202."""
    from deepof_tpu.serve.server import build_server

    cfg = _cfg(port=0, session_kw=dict(ttl_s=0.15, sweep_s=0.02))
    frames = [_img(rng) for _ in range(2)]
    eng = InferenceEngine(cfg, forward_fn=make_fake_forward(1.0))
    httpd = build_server(cfg, eng)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    wait_for_listen("127.0.0.1", port)
    try:
        assert _post(port, "/v1/flow/stream",
                     {"session": "v", "frame": _b64png(frames[0])})[0] == 202
        time.sleep(0.3)
        status, p = _post(port, "/v1/flow/stream",
                          {"session": "v", "frame": _b64png(frames[1])})
        assert status == 410 and p["error"] == "session_expired", (status, p)
        status, p = _post(port, "/v1/flow/stream",
                          {"session": "v", "frame": _b64png(frames[1])})
        assert status == 202, (status, p)
    finally:
        httpd.shutdown()
        httpd.server_close()
        eng.close()


# ------------------------------------------------------------ config


def test_session_config_round_trip_and_unknown_key_rejection():
    """The parent->replica handoff covers the SessionConfig block, and
    unknown keys inside it are rejected loudly (the FleetConfig pin,
    extended to the new block)."""
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(serve=dataclasses.replace(
        cfg.serve, session=dataclasses.replace(
            cfg.serve.session, max_sessions=7, ttl_s=3.5, sweep_s=0.5)))
    restored = config_from_dict(json.loads(json.dumps(
        dataclasses.asdict(cfg))))
    assert restored == cfg
    assert restored.serve.session.max_sessions == 7
    # typo rejection ("ttl_sec") moved to the registry-driven whole-tree
    # walk in test_lint.py, which keeps this assertion as a parity pin


# ---------------------------------------------- router (stub fleet)


class _StubFleet:
    def __init__(self, ports, host="127.0.0.1"):
        self.host = host
        self.ports = list(ports)
        self.size = len(self.ports)
        self.failures = []

    def ready_replicas(self):
        return [SimpleNamespace(idx=i, port=p)
                for i, p in enumerate(self.ports) if p is not None]

    def note_failure(self, idx):
        self.failures.append(idx)


def _stub_replica():
    """Session-aware replica stub: primes unknown sids (202), steps
    known ones (200), deletes, and tags every reply with its port."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def _send(self, status, payload):
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802
            req = json.loads(self.rfile.read(
                int(self.headers.get("Content-Length", 0))) or b"{}")
            port = self.server.server_address[1]
            sid = req.get("session")
            if sid is None:
                self._send(200, {"served_by": port})
                return
            sessions = self.server.sessions
            if sid in sessions:
                sessions[sid] += 1
                self._send(200, {"served_by": port, "session": sid,
                                 "frame_index": sessions[sid]})
            else:
                sessions[sid] = 0
                self._send(202, {"primed": True, "served_by": port,
                                 "session": sid})

        def do_DELETE(self):  # noqa: N802
            sid = self.path.rsplit("/", 1)[-1]
            gone = self.server.sessions.pop(sid, None) is not None
            self._send(200 if gone else 404,
                       {"session": sid, "deleted": gone})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    httpd.sessions = {}
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def _frame_body(rng, sid, hw=(30, 60)):
    return json.dumps({"session": sid,
                       "frame": _b64png(_img(rng, hw))}).encode()


def test_router_session_sticky_lost_and_reprime(rng, tmp_path):
    """Sticky affinity end to end at the router: every frame of a
    session lands on the replica that primed it; killing that replica
    demotes the next frame to a structured 410 session_lost (no
    failover — a sibling has no cached frame); the re-prime pins to the
    survivor; DELETE routes to the pin and drops it."""
    from deepof_tpu.serve.router import Router

    cfg = _cfg(log_dir=str(tmp_path), fake_exec_ms=5.0, port=0)
    s0, s1 = _stub_replica(), _stub_replica()
    try:
        fleet = _StubFleet([s0.server_address[1], s1.server_address[1]])
        router = Router(cfg, fleet)
        status, p, _ = router.handle_flow(
            "/v1/flow/stream", _frame_body(rng, "vid"), "application/json")
        p = json.loads(p)
        assert status == 202 and p["primed"], (status, p)
        home = p["served_by"]
        for i in range(1, 4):
            status, p, _ = router.handle_flow(
                "/v1/flow/stream", _frame_body(rng, "vid"),
                "application/json")
            p = json.loads(p)
            assert status == 200 and p["served_by"] == home, (status, p)
            assert p["frame_index"] == i
        stats = router.stats()
        assert stats["fleet_sessions_sticky"] == 1
        assert stats["fleet_session_primes"] == 1
        assert stats["fleet_session_steps"] == 3

        # SIGKILL stand-in: the pinned replica stops answering
        dead, dead_slot = ((s0, 0) if s0.server_address[1] == home
                           else (s1, 1))
        dead.shutdown()
        dead.server_close()
        status, p, _ = router.handle_flow(
            "/v1/flow/stream", _frame_body(rng, "vid"), "application/json")
        p = json.loads(p)
        assert status == 410 and p["error"] == "session_lost", (status, p)
        assert p["session"] == "vid"
        assert dead_slot in fleet.failures  # the supervisor got poked

        # supervisor takes the dead replica out; the client re-primes
        fleet.ports[dead_slot] = None
        status, p, _ = router.handle_flow(
            "/v1/flow/stream", _frame_body(rng, "vid"), "application/json")
        p = json.loads(p)
        assert status == 202 and p["served_by"] != home, (status, p)
        status, p, _ = router.handle_flow(
            "/v1/flow/stream", _frame_body(rng, "vid"), "application/json")
        assert status == 200
        stats = router.stats()
        assert stats["fleet_session_lost"] == 1
        assert stats["fleet_sessions_sticky"] == 1

        status, p, _ = router.handle_session_delete("/v1/flow/stream/vid")
        p = json.loads(p)
        assert status == 200 and p["deleted"] is True, (status, p)
        status, p, _ = router.handle_session_delete("/v1/flow/stream/vid")
        assert status == 404 and json.loads(p)["error"] == "session_unknown"
    finally:
        for s in (s0, s1):
            try:
                s.shutdown()
                s.server_close()
            except OSError:
                pass


def test_router_sticky_map_is_bounded_and_ttl_aged(rng, tmp_path):
    """The sticky map cannot outgrow max_sessions x fleet size (LRU)
    and TTL-ages entries on access, mirroring the replica stores."""
    from deepof_tpu.serve.router import Router

    cfg = _cfg(log_dir=str(tmp_path), fake_exec_ms=5.0, port=0,
               session_kw=dict(max_sessions=2, ttl_s=0.15))
    s0 = _stub_replica()
    try:
        fleet = _StubFleet([s0.server_address[1]])
        router = Router(cfg, fleet)
        for sid in ("a", "b", "c"):  # cap = 2 x 1 fleet = 2
            router.handle_flow("/v1/flow/stream", _frame_body(rng, sid),
                               "application/json")
        stats = router.stats()
        assert stats["fleet_sessions_sticky"] == 2, stats
        assert stats["fleet_session_evicted"] >= 1, stats
        time.sleep(0.3)
        assert router._sticky_get("c") is None  # TTL-aged on access
        assert router.stats()["fleet_session_expired"] >= 1
    finally:
        s0.shutdown()
        s0.server_close()


# ----------------------------------------------------- observability


def test_tail_and_analyze_surface_session_counters(tmp_path):
    """The serve_sessions_* block rides the existing serve surfaces:
    tail's serve block (from the heartbeat) and analyze's merged
    child aggregation, including the per-key histogram merge."""
    from deepof_tpu.analyze import aggregate_processes, tail_summary
    from deepof_tpu.obs.export import LatencyHistogram

    hist = LatencyHistogram()
    hist.observe(0.01)
    snap = hist.snapshot()
    (tmp_path / "metrics.jsonl").write_text(json.dumps(
        {"kind": "serve", "step": 0, "time": time.time(),
         "serve_requests": 5, "serve_responses": 5}) + "\n")
    (tmp_path / "heartbeat.json").write_text(json.dumps(
        {"time": time.time(), "step": 5, "wedged": False,
         "serve_requests": 5, "serve_sessions_active": 2,
         "serve_sessions_created": 3, "serve_sessions_decode_saved": 9,
         "serve_session_latency_hist": snap}))
    out = tail_summary(str(tmp_path))
    assert out["serve"]["sessions_active"] == 2
    assert out["serve"]["sessions_decode_saved"] == 9

    # two fake replica children: merged sums + per-key histogram merge
    for i in range(2):
        d = tmp_path / f"replica-{i}"
        d.mkdir()
        (d / "metrics.jsonl").write_text(json.dumps(
            {"kind": "serve", "step": 0, "time": time.time(),
             "serve_requests": 4, "serve_responses": 4,
             "serve_sessions_created": 2, "serve_sessions_steps": 3,
             "serve_sessions_decode_saved": 3,
             "serve_latency_hist": snap,
             "serve_session_latency_hist": snap}) + "\n")
    agg = aggregate_processes(str(tmp_path))
    merged = agg["merged"]
    assert merged["sessions_created"] == 4
    assert merged["sessions_decode_saved"] == 6
    assert merged["latency_hist"]["count"] == 2
    assert merged["session_latency_hist"]["count"] == 2


# ------------------------------------------------------- serve_bench


def _load_serve_bench():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "serve_bench.py")
    spec = importlib.util.spec_from_file_location("serve_bench_stream", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serve_bench_stream_speedup_and_schema():
    """ISSUE 10 + 11 acceptance: on a decode-bound walk (20 ms injected
    decode, 2 ms executor) the streamed session sustains >= 1.5x the
    pairwise walk's frames/s with bit-identical flows, AND the
    real-model temporal warm-start block reports warm_speedup >= 1.3
    (refinement-only executable vs the full cold network) inside the
    epe_vs_cold <= 0.5 px quality gate; the JSON schema is pinned. One
    bounded retry on the timing ratios (scheduler spikes on this small
    host); the schema, parity, ledger, and quality gates assert
    strictly every time."""
    sb = _load_serve_bench()
    for attempt in range(2):
        res = sb.stream_bench(frames=32, decode_ms=20.0, exec_ms=2.0,
                              max_batch=4, timeout_ms=2.0,
                              warm_frames=12)
        for key in sb.STREAM_REQUIRED_KEYS:
            assert key in res, f"stream result missing {key!r}"
        json.dumps(res)  # JSON-line contract
        assert res["mode"] == "stream" and res["errors"] == 0
        assert res["flow_bitwise_equal"] is True
        # the decode ledger is deterministic: N vs 2(N-1)
        assert res["stream_decodes"] == 32
        assert res["pairwise_decodes"] == 62
        assert res["decode_saved"] == 31
        # warm-start structure + quality gate: strict every attempt
        assert res["warm_errors"] == 0
        assert res["warm_steps"] == 10  # 12 frames: prime, fallback, 10
        assert res["warm_cold_fallbacks"] == 1
        assert res["epe_vs_cold"] <= 0.5, res
        if res["stream_speedup"] >= 1.5 and res["warm_speedup"] >= 1.3:
            break
    assert res["stream_speedup"] >= 1.5, res
    assert res["warm_speedup"] >= 1.3, res


# ------------------------------------------------ chaos (subprocess)


@pytest.mark.chaos
def test_session_chaos_replica_sigkill_reprime_no_silent_drops(rng,
                                                               tmp_path):
    """ISSUE 10 chaos acceptance: a live 2-replica fleet serves a video
    session; the session's replica is SIGKILLed mid-walk
    (`replica_crash` injection). The client re-primes from the
    structured `session_lost` reply and finishes the walk: 100% of
    frames acknowledged (every frame gets a 200 flow or a 202 prime
    within bounded retries), zero silent drops, and the session
    counters are visible on the router's /metrics."""
    from deepof_tpu.serve.fleet import Fleet
    from deepof_tpu.serve.router import Router, build_router_server

    fleet_dir = tmp_path / "fleet"
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=(32, 64), gt_size=(32, 64)),
        serve=dataclasses.replace(
            cfg.serve, max_batch=4, batch_timeout_ms=5.0,
            fake_exec_ms=5.0, host="127.0.0.1", port=0,
            fleet=dataclasses.replace(
                cfg.serve.fleet, poll_s=0.1, stale_after_s=5.0,
                stall_after_s=2.0, spawn_timeout_s=90.0, term_grace_s=1.0,
                backoff_s=0.1, backoff_max_s=0.5, healthy_after_s=30.0,
                proxy_timeout_s=2.0, max_in_flight=64,
                drain_timeout_s=2.0)),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6),
                                  log_dir=str(fleet_dir)),
        obs=dataclasses.replace(cfg.obs, heartbeat_period_s=0.1,
                                watchdog_min_s=0.5),
        resilience=dataclasses.replace(
            cfg.resilience,
            faults=dataclasses.replace(
                cfg.resilience.faults, enabled=True,
                # the single (32, 64) bucket's affinity replica is 0, so
                # the session pins there — and replica 0 dies after 6
                # engine responses, mid-walk
                replica_crash_at=(0,), replica_fault_after=6)))
    frames = [_img(rng) for _ in range(24)]
    with Fleet(cfg, 2) as fleet:
        fleet.start()
        fleet.wait_ready(min_ready=2, timeout_s=120)
        router = Router(cfg, fleet)
        httpd = build_router_server(cfg, router)
        threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="chaos-router").start()
        port = httpd.server_address[1]
        wait_for_listen("127.0.0.1", port)
        outcomes = []  # (frame idx, final status) — the drop ledger
        flows = reprimes = 0
        try:
            for idx, frame in enumerate(frames):
                body = {"session": "vid", "frame": _b64png(frame)}
                for attempt in range(20):
                    status, p = _post(port, "/v1/flow/stream", body,
                                      timeout=30.0)
                    if status == 200:
                        flows += 1
                        break
                    if status == 202:
                        if idx > 0:
                            reprimes += 1
                        break
                    # structured demotions the client recovers from:
                    # 410 session_lost/expired -> resend (re-prime),
                    # 503 (router saw the crash before the supervisor)
                    assert status in (410, 503), (idx, status, p)
                    assert p.get("error") in ("session_lost",
                                              "session_expired",
                                              "overloaded",
                                              "unavailable"), p
                    time.sleep(0.3)
                else:
                    pytest.fail(f"frame {idx} never acknowledged")
                outcomes.append((idx, status))
            stats = {**fleet.stats(), **router.stats()}
            status, text = _get(port, "/metrics", timeout=30.0)
            metrics_text = text.decode()
        finally:
            router.draining = True
            httpd.shutdown()
            httpd.server_close()

    # 100% client success: every frame acknowledged, in order
    assert [i for i, _ in outcomes] == list(range(len(frames)))
    assert flows + reprimes + 1 == len(frames)  # +1: the initial prime
    # the chaos actually happened and was survived via re-prime
    assert stats["fleet_crashes"] >= 1, stats
    assert stats["fleet_session_lost"] >= 1, stats
    assert reprimes >= 1
    # most frames still produced flow (one lost pair per re-prime)
    assert flows >= len(frames) - 1 - 2 * (reprimes + 1), (flows, reprimes)
    # the axis is observable end to end on the fleet's /metrics
    assert "deepof_fleet_session_lost" in metrics_text
    assert "deepof_fleet_session_steps" in metrics_text
    assert "deepof_serve_sessions_created" in metrics_text
